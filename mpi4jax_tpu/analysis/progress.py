"""Progress checking: simulate the matched schedules to a fixed point.

Runs the matched whole-program (analysis/matcher.py) under this
library's execution semantics — buffered sends (deferred pairing: a send
never blocks), receives blocking until the matching send is *issued*,
collectives synchronizing every member, ``*_wait`` blocking until every
member issued its paired ``*_start`` — and advances every rank's program
counter until nothing moves.  A non-empty residue is a deadlock: the
wait-for graph over the blocked ranks is built and its cycles are
reported, classified by what the cycle's ranks are blocked in:

- all point-to-point  -> **MPX121** (send/recv deadlock cycle, rendered
  rank-by-rank: who is blocked where, waiting on whom);
- all collectives     -> **MPX120** (cross-rank collective order
  mismatch: e.g. two comms' collectives interleaved in opposite orders);
- mixed               -> **MPX122** (collective/p2p interleave deadlock).

Because sends are modeled buffered, every cycle found here deadlocks
under ANY buffering — no false alarms from send-buffer pressure (the
rendezvous-only hazard class is deliberately out of scope; this
library's in-region sends genuinely never block).  Blocked ranks whose
peer simply never issues the matching op are the matcher's domain
(MPX101/102/123) and are not re-reported here.  Dependency-free (no
jax); hand-built schedules drive it in tests/test_crossrank_pure.py.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Set, Tuple

from .matcher import MatchedProgram, inst_key
from .report import Finding
from .schedule import SchedOp

CROSSRANK_CODES = ("MPX121", "MPX122")


def check_progress(matched: MatchedProgram) -> List[Finding]:
    """Simulate ``matched`` to a fixed point; report deadlock cycles
    (and replay the MPX110 FIFO-ambiguity advisory, which is only
    observable at simulated match time)."""
    sim = _Simulation(matched)
    sim.run()
    return sim.deadlock_findings() + sim.ambiguity_findings()


class _Simulation:
    def __init__(self, matched: MatchedProgram):
        self.m = matched
        self.ranks = matched.ranks
        self.ptr: Dict[int, int] = {r: 0 for r in self.ranks}
        # per-channel issue/consume counters (FIFO positions)
        self.sent: Dict[Tuple, int] = {}
        self.recvd: Dict[Tuple, int] = {}
        # wildcard pool: issued-unconsumed send count per (ck, dst, tag)
        self.pool: Dict[Tuple, int] = {}
        # ranks whose *start* for an instance has been issued
        self.started: Dict[Tuple, Set[int]] = {}
        # MPX110 replay: (rank, recv op, pending-send depth) at match
        self.ambiguous: List[Tuple[int, SchedOp, int]] = []
        # per-rank FIFO ordinal of each p2p op (precomputed)
        self.ordinal: Dict[Tuple[int, int], int] = {}  # (rank, pos) -> k
        for r in self.ranks:
            counts: Dict[Tuple, int] = {}
            for op in matched.schedules[r]:
                if op.kind == "send":
                    key = ("s", op.comm_key, op.src, op.dst, op.tag)
                elif op.kind == "recv" and op.src is not None:
                    key = ("r", op.comm_key, op.src, op.dst, op.tag)
                else:
                    continue
                self.ordinal[(r, op.pos)] = counts.get(key, 0)
                counts[key] = counts.get(key, 0) + 1

    def head(self, r: int) -> Optional[SchedOp]:
        sched = self.m.schedules[r]
        return sched[self.ptr[r]] if self.ptr[r] < len(sched) else None

    def _issue_send(self, r: int, op: SchedOp) -> None:
        ch = (op.comm_key, op.src, op.dst, op.tag)
        self.sent[ch] = self.sent.get(ch, 0) + 1
        self.pool[(op.comm_key, op.dst, op.tag)] = self.pool.get(
            (op.comm_key, op.dst, op.tag), 0) + 1

    def _recv_ready(self, r: int, op: SchedOp) -> bool:
        if op.src is None:  # wildcard: any issued-unconsumed send to me
            return self.pool.get((op.comm_key, op.dst, op.tag), 0) > 0
        ch = (op.comm_key, op.src, op.dst, op.tag)
        return self.sent.get(ch, 0) > self.ordinal[(r, op.pos)]

    def _consume_recv(self, r: int, op: SchedOp) -> None:
        key = (op.comm_key, op.dst, op.tag)
        if self.pool.get(key, 0) > 0:
            self.pool[key] -= 1

    def _coll_ready(self, key: Tuple) -> bool:
        """Every expected member's head is this instance."""
        for q in self.m.expected.get(key, ()):
            h = self.head(q)
            if h is None or h.kind != "coll" or inst_key(h) != key:
                return False
        return True

    def _wait_ready(self, key: Tuple) -> bool:
        """Every expected member has issued its paired start."""
        exp = self.m.expected.get(key, ())
        return all(q in self.started.get(key, set()) for q in exp)

    def run(self) -> None:
        moved = True
        while moved:
            moved = False
            for r in self.ranks:
                while True:
                    op = self.head(r)
                    if op is None:
                        break
                    if op.kind == "send":
                        self._issue_send(r, op)
                        self.ptr[r] += 1
                        self._retire_send(r, op)
                    elif op.kind == "recv":
                        if not self._recv_ready(r, op):
                            break
                        self._note_ambiguity(r, op)
                        self._consume_recv(r, op)
                        self.ptr[r] += 1
                        self._retire_recv(r, op)
                    elif op.kind == "start":
                        # nonblocking issue: record it for the paired
                        # wait's readiness check and move on
                        self.started.setdefault(
                            inst_key(op), set()).add(r)
                        self.ptr[r] += 1
                        self._retire_start(r, op)
                    elif op.kind == "coll":
                        key = inst_key(op)
                        if not self._coll_ready(key):
                            break
                        members = self.m.expected.get(key, (r,))
                        for q in members:
                            self.ptr[q] += 1
                        self._retire_coll(key, members)
                    elif op.kind == "wait":
                        if not self._wait_ready(inst_key(op)):
                            break
                        self.ptr[r] += 1
                        self._retire_wait(r, op)
                    else:  # unknown kinds never block
                        self.ptr[r] += 1
                    moved = True

    # -- retirement hooks --------------------------------------------------
    # The timed (critical-path) simulation in analysis/cost.py subclasses
    # this simulation and overrides these: each is invoked exactly once,
    # at the moment the op retires under the SAME buffered-send execution
    # semantics the deadlock verdicts use — so predicted timings and
    # progress verdicts can never disagree about what runs when.  A coll
    # retires all member ranks together (one call, ``members`` in rank
    # order); everything else retires per rank.

    def _retire_send(self, r: int, op: SchedOp) -> None:
        pass

    def _retire_recv(self, r: int, op: SchedOp) -> None:
        pass

    def _retire_start(self, r: int, op: SchedOp) -> None:
        pass

    def _retire_coll(self, key: Tuple, members) -> None:
        pass

    def _retire_wait(self, r: int, op: SchedOp) -> None:
        pass

    def _note_ambiguity(self, r: int, op: SchedOp) -> None:
        """MPX110 replay (the single-trace FIFO-ambiguity advisory, which
        the per-rank pass skips): this recv is about to match while >= 2
        sends sit unconsumed on its channel — FIFO picks the oldest."""
        if op.src is None:
            depth = self.pool.get((op.comm_key, op.dst, op.tag), 0)
        else:
            ch = (op.comm_key, op.src, op.dst, op.tag)
            depth = self.sent.get(ch, 0) - self.ordinal[(r, op.pos)]
        if depth >= 2:
            self.ambiguous.append((r, op, depth))

    def ambiguity_findings(self) -> List[Finding]:
        return [
            Finding(
                code="MPX110", op=op.op, index=op.event_index, rank=r,
                message=(f"rank {r}'s recv(tag={op.tag}) matched while "
                         f"{depth} sends were pending on its channel; "
                         "FIFO picked the oldest"),
                suggestion=("use distinct tags (or a Clone()d comm) if "
                            "the pending sends are not interchangeable"),
            )
            for r, op, depth in self.ambiguous
        ]

    # -- deadlock analysis -------------------------------------------------

    def _block_targets(self, r: int, op: SchedOp) -> List[int]:
        """Ranks ``r`` is waiting on (edges of the wait-for graph).
        Empty when the block is a never-issued-op case the matcher
        already reported (MPX101/102/123)."""
        if op.kind == "recv":
            if op.src is None:
                # any rank still holding an unissued send to (dst, tag)
                out = []
                for q in self.ranks:
                    for s in self.m.schedules[q][self.ptr[q]:]:
                        if (s.kind == "send" and s.comm_key == op.comm_key
                                and s.dst == op.dst and s.tag == op.tag):
                            out.append(q)
                            break
                return out
            # the specific sender, if its matching send is still ahead
            q = op.src
            if q not in self.ptr:
                return []
            need = self.ordinal[(r, op.pos)]
            seen = 0
            for s in self.m.schedules[q][:self.ptr[q]]:
                if (s.kind == "send" and s.comm_key == op.comm_key
                        and s.dst == op.dst and s.tag == op.tag):
                    seen += 1
            remaining = sum(
                1 for s in self.m.schedules[q][self.ptr[q]:]
                if (s.kind == "send" and s.comm_key == op.comm_key
                    and s.dst == op.dst and s.tag == op.tag)
            )
            return [q] if seen + remaining > need else []
        if op.kind in ("coll", "wait"):
            key = inst_key(op)
            out = []
            for q in self.m.expected.get(key, ()):
                if q == r:
                    continue
                if op.kind == "wait" and q in self.started.get(key, set()):
                    continue
                h = self.head(q)
                if h is not None and (h.kind != "coll"
                                      or inst_key(h) != key):
                    out.append(q)
            return out
        return []

    def deadlock_findings(self) -> List[Finding]:
        blocked = {r: self.head(r) for r in self.ranks
                   if self.head(r) is not None}
        if not blocked:
            return []
        edges = {r: self._block_targets(r, op)
                 for r, op in blocked.items()}
        findings: List[Finding] = []
        seen_cycles: Set[Tuple[int, ...]] = set()
        for start in sorted(blocked):
            cycle = _find_cycle(edges, start)
            if cycle is None:
                continue
            canon = tuple(sorted(cycle))
            if canon in seen_cycles:
                continue
            seen_cycles.add(canon)
            kinds = {blocked[r].kind for r in cycle}
            if kinds <= {"recv", "send"}:
                code = "MPX121"
                label = "send/recv deadlock cycle"
            elif kinds <= {"coll", "start", "wait"}:
                code = "MPX120"
                label = ("cross-rank collective order mismatch "
                         "(collectives interleaved in conflicting orders)")
            else:
                code = "MPX122"
                label = "collective/p2p interleave deadlock"
            chain = "; ".join(
                f"rank {r}: blocked at {blocked[r].describe()} "
                f"(schedule position {blocked[r].pos}) -> waits for "
                f"rank {cycle[(i + 1) % len(cycle)]}"
                for i, r in enumerate(cycle)
            )
            first = cycle[0]
            findings.append(Finding(
                code=code, op=blocked[first].op,
                index=blocked[first].event_index, rank=first,
                seq=blocked[first].seq,
                message=f"{label} over ranks {sorted(cycle)}: {chain}",
                suggestion=("break the cycle: reorder one rank's ops so "
                            "some rank's blocking op is matched first "
                            "(e.g. pair the exchange with sendrecv, or "
                            "hoist the collective out of the divergent "
                            "branch)"),
            ))
        return findings


def _find_cycle(edges: Dict[int, List[int]], start: int) -> Optional[List[int]]:
    """A cycle reachable from ``start`` in the wait-for graph, as the
    ordered rank list of the cycle itself (path prefix trimmed)."""
    path: List[int] = []
    on_path: Set[int] = set()
    seen: Set[int] = set()

    def dfs(r: int) -> Optional[List[int]]:
        if r in on_path:
            return path[path.index(r):]
        if r in seen:
            return None
        seen.add(r)
        path.append(r)
        on_path.add(r)
        for q in edges.get(r, ()):
            got = dfs(q)
            if got is not None:
                return got
        path.pop()
        on_path.remove(r)
        return None

    return dfs(start)
