"""Analyze whole scripts: ``python -m mpi4jax_tpu.analysis script.py ...``.

Runs each script with ``MPI4JAX_TPU_ANALYZE=error`` (unless the caller
already set a mode), so every spmd region and eager op the script traces
is verified and ANY finding fails the run — the CI ``analyze`` lane runs
this over everything in ``examples/`` (.github/workflows/test.yml).
"""

import os
import runpy
import sys


def main(argv) -> int:
    if not argv:
        print("usage: python -m mpi4jax_tpu.analysis script.py [...]",
              file=sys.stderr)
        return 2
    os.environ.setdefault("MPI4JAX_TPU_ANALYZE", "error")
    mode = os.environ["MPI4JAX_TPU_ANALYZE"]
    saved_argv = sys.argv
    for path in argv:
        print(f"[mpx.analyze] running {path} with MPI4JAX_TPU_ANALYZE={mode}")
        sys.argv = [path]
        try:
            runpy.run_path(path, run_name="__main__")
        finally:
            sys.argv = saved_argv
    print(f"[mpx.analyze] {len(argv)} script(s) analyzed clean")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
