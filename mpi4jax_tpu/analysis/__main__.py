"""Analyze whole scripts: ``python -m mpi4jax_tpu.analysis script.py ...``.

Runs each script with the ambient verifier armed (``MPI4JAX_TPU_ANALYZE``
defaulting to ``warn`` — the CLI aggregates findings itself instead of
aborting at the first one) and applies the CI exit-code contract:

- **0** — every script analyzed, no error-severity finding (advisories
  are listed but do not fail the run);
- **1** — at least one error-severity finding (including MPX-tagged
  trace-time raises, converted to findings);
- **2** — usage error, or a script failed outside the verifier (an
  untagged exception: import error, bad path, ...).

Options:

- ``--ranks N`` — sets ``MPI4JAX_TPU_ANALYZE_RANKS=N``: the cross-rank
  schedule pass (per-rank re-trace + deadlock/progress matching,
  MPX120–MPX125) runs for every spmd region on a comm of at most N
  ranks;
- ``--cost`` — sets ``MPI4JAX_TPU_ANALYZE_COST=on``: every cross-rank
  pass extends into the critical-path timing simulation
  (analysis/cost.py) — reports gain a ``cost`` breakdown (predicted
  step time, per-op / per-link-class latency+bytes, the critical path)
  and the quantified MPX131–MPX135 performance advisories;
- ``--cost-model PATH`` — sets ``MPI4JAX_TPU_COST_MODEL=PATH``: load
  measured alpha/beta parameters from a tuning file (the
  ``benchmarks/micro.py --cost-calibrate`` schema) instead of the
  analytic defaults;
- ``--json`` — print the aggregated machine-readable payload (one
  ``Report.to_json()`` object per dirty — or, under ``--cost``,
  costed — region, plus per-script status) to stdout; the scripts' own
  stdout is redirected to stderr so the payload stays parseable;
- ``--strict-advisories`` — exit 1 on advisory-severity findings too
  (MPX1xx ADVISORY rows, e.g. the MPX142 approximate-lineage taint):
  for lanes that gate on a fully silent analysis rather than the
  default errors-only contract.

The CI ``lint/analyze`` lane runs this over everything in ``examples/``
with ``--ranks 8 --cost --json``, uploads the payloads as artifacts,
and asserts ``examples/pipeline_parallel.py`` reports MPX135 while
exiting 0 — advisory severity never fails the lane
(.github/workflows/test.yml).
"""

import contextlib
import json
import os
import runpy
import sys
import traceback

USAGE = ("usage: python -m mpi4jax_tpu.analysis [--ranks N] [--cost] "
         "[--cost-model PATH] [--json] [--strict-advisories] "
         "script.py [...]")


def _parse_args(argv):
    ranks = None
    as_json = False
    cost = False
    cost_model = None
    strict_advisories = False
    scripts = []
    i = 0
    while i < len(argv):
        a = argv[i]
        if a == "--ranks":
            i += 1
            if i >= len(argv):
                return None
            ranks = argv[i]
        elif a.startswith("--ranks="):
            ranks = a.split("=", 1)[1]
        elif a == "--cost":
            cost = True
        elif a == "--cost-model":
            i += 1
            if i >= len(argv):
                return None
            cost_model = argv[i]
        elif a.startswith("--cost-model="):
            cost_model = a.split("=", 1)[1]
        elif a == "--json":
            as_json = True
        elif a == "--strict-advisories":
            strict_advisories = True
        elif a.startswith("-"):
            return None
        else:
            scripts.append(a)
        i += 1
    if not scripts:
        return None
    return ranks, as_json, cost, cost_model, strict_advisories, scripts


def main(argv) -> int:
    parsed = _parse_args(argv)
    if parsed is None:
        print(USAGE, file=sys.stderr)
        return 2
    ranks, as_json, cost, cost_model, strict_advisories, scripts = parsed
    if ranks is not None:
        os.environ["MPI4JAX_TPU_ANALYZE_RANKS"] = ranks
    if cost:
        os.environ["MPI4JAX_TPU_ANALYZE_COST"] = "on"
    if cost_model is not None:
        os.environ["MPI4JAX_TPU_COST_MODEL"] = cost_model
    os.environ.setdefault("MPI4JAX_TPU_ANALYZE", "warn")
    mode = os.environ["MPI4JAX_TPU_ANALYZE"]

    from .hook import set_report_sink
    from .report import AnalysisError, Report, finding_from_exception

    sink = []
    set_report_sink(sink)
    script_status = {}
    trace_failure = False
    saved_argv = sys.argv
    try:
        for path in scripts:
            print(f"[mpx.analyze] running {path} with "
                  f"MPI4JAX_TPU_ANALYZE={mode}", file=sys.stderr)
            sys.argv = [path]
            before = len(sink)
            try:
                if as_json:
                    # scripts print freely; the JSON payload owns stdout
                    with contextlib.redirect_stdout(sys.stderr):
                        runpy.run_path(path, run_name="__main__")
                else:
                    runpy.run_path(path, run_name="__main__")
                script_status[path] = "ok"
            except AnalysisError as e:
                # ambient error-mode raises sink their report BEFORE
                # raising; an explicit `report.raise_if_findings()` in
                # the script does not — recover its findings here so the
                # exit-code contract sees them either way
                if len(sink) == before:
                    sink.append((path, Report(findings=e.findings)))
                script_status[path] = "findings"
            except SystemExit as e:
                # scripts commonly end with sys.exit(...): a zero exit is
                # a normal completion (any sunk findings still count); a
                # nonzero one is the script failing on its own terms —
                # either way the CLI's exit-code contract, not the
                # script's, decides the process exit
                code = e.code if isinstance(e.code, int) else (
                    0 if e.code is None else 1)
                if code == 0:
                    script_status[path] = "ok"
                else:
                    print(f"[mpx.analyze] {path} exited with status "
                          f"{e.code}", file=sys.stderr)
                    script_status[path] = "trace-failure"
                    trace_failure = True
            except Exception as e:
                f = finding_from_exception(e)
                if f is not None:
                    # an MPX-tagged trace-time raise IS a finding
                    sink.append((path, Report(findings=(f,))))
                    script_status[path] = "findings"
                else:
                    traceback.print_exc()
                    script_status[path] = "trace-failure"
                    trace_failure = True
            finally:
                sys.argv = saved_argv
            if script_status[path] == "ok" and any(
                    rep.findings for _, rep in sink[before:]):
                # a clean --cost breakdown report is not a "finding"
                script_status[path] = "findings"
    finally:
        sys.argv = saved_argv
        set_report_sink(None)

    findings = [f for _, rep in sink for f in rep.findings]
    n_errors = sum(1 for f in findings if f.severity == "error")
    if as_json:
        payload = {
            "scripts": script_status,
            "errors": n_errors,
            "advisories": len(findings) - n_errors,
            "reports": [
                {"where": where, **rep.to_json()} for where, rep in sink
            ],
        }
        print(json.dumps(payload, indent=2))
    for where, rep in sink:
        label = "findings in" if rep.findings else "cost report for"
        print(f"[mpx.analyze] {label} {where}:\n{rep.render()}",
              file=sys.stderr)
    if trace_failure:
        return 2
    if n_errors:
        print(f"[mpx.analyze] {n_errors} error-severity finding(s) over "
              f"{len(scripts)} script(s)", file=sys.stderr)
        return 1
    n_advisories = len(findings) - n_errors
    if strict_advisories and n_advisories:
        print(f"[mpx.analyze] --strict-advisories: {n_advisories} "
              f"advisory finding(s) over {len(scripts)} script(s)",
              file=sys.stderr)
        return 1
    print(f"[mpx.analyze] {len(scripts)} script(s) analyzed, no errors "
          f"({n_advisories} advisory finding(s))", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
