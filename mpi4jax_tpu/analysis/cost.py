"""Critical-path timing + the performance critic (MPX131-135, MPX144).

``mpx.analyze(fn, *args, ranks=..., cost=True)`` extends the cross-rank
progress simulation (analysis/progress.py) into a **timed** one: the
same buffered-send execution semantics, but every retirement advances a
per-rank clock by the alpha-beta-gamma model's predicted cost
(analysis/costmodel.py) plus a roofline compute term estimated from each
rank's jaxpr memory traffic.  Because the timed simulation subclasses
the progress simulation's retirement hooks, the timing and the deadlock
verdicts can never disagree about what runs when; a program with a
progress residue (a real deadlock) yields no cost report at all — there
is no step time to predict.

Out the other end:

- :class:`CostReport` (``Report.cost``): predicted step time, per-op and
  per-link-class latency+byte breakdown, the critical path rendered
  rank by rank, and the predicted megastep/fusion amortization;
- six **quantified advisories** (each stated in predicted microseconds
  and bytes, never vibes): MPX131 overlap opportunity, MPX132 fusion
  opportunity (the quantified upgrade of MPX111), MPX133 algorithm
  mispick, MPX134 structural load imbalance, MPX135 serialized
  point-to-point chain on the critical path (the GPipe-shaped check —
  ``examples/pipeline_parallel.py`` is the seeded positive, and the
  advisory now cites the modeled bubble fraction of the ladder plus the
  1F1B price ``mpx.pipeline`` would get), MPX144 pipeline schedule
  mispick (a program stamped by the schedule compiler ran a schedule
  the model prices measurably worse than an expressible alternative).

Dependency-free at import (no jax): scripted schedules drive the timed
simulation in tests/test_cost_pure.py under any JAX version; the jaxpr
compute estimate is duck-typed the same way analysis/walker.py is.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ..parallel.topology import link_class, span_hosts
from . import costmodel
from .checkers import ALGO_OPS, ENUM_REDUCTIONS, FUSABLE_OPS
from .costmodel import CostModel, OpCost, collective_cost, p2p_cost
from .matcher import MatchedProgram, inst_key
from .progress import _Simulation
from .report import Finding
from .schedule import SchedOp

# codes this module owns in the checker-coverage sense
COST_CODES = ("MPX131", "MPX132", "MPX133", "MPX134", "MPX135", "MPX144")

# MPX131: fraction of a blocking collective's predicted time the
# adjacent compute must be able to hide before the advisory fires
OVERLAP_HIDE_FRACTION = 0.3
# ops with an async *_start/*_wait split (ops/_async.py)
ASYNC_CAPABLE_OPS = ("allreduce", "reduce_scatter", "alltoall")
# MPX133: predicted delta below this fraction of the best time is noise
MISPICK_MIN_FRACTION = 0.10
# MPX135: minimum transfer hops + distinct ranks of a serialized chain,
# and the minimum share of the critical path it must occupy
CHAIN_MIN_HOPS = 3
CHAIN_MIN_RANKS = 3
CHAIN_MIN_FRACTION = 0.2

resolve_model = costmodel.load_model


# ---------------------------------------------------------------------------
# roofline compute estimate from the per-rank jaxprs
# ---------------------------------------------------------------------------


def _aval_bytes(aval) -> int:
    shape = getattr(aval, "shape", None)
    dtype = getattr(aval, "dtype", None)
    if shape is None or dtype is None:
        return 0
    n = 1
    for d in shape:
        try:
            n *= int(d)
        except (TypeError, ValueError):  # symbolic dims: skip
            return 0
    itemsize = getattr(dtype, "itemsize", None)
    if itemsize is None:
        try:
            import numpy as np

            itemsize = np.dtype(dtype).itemsize
        except Exception:
            return 0
    return n * int(itemsize)


def jaxpr_traffic_bytes(closed) -> int:
    """Roofline memory-traffic estimate of one rank's program: the sum
    of every equation's output bytes (writes; reads are of the same
    order), recursing into sub-jaxprs; a cond counts its widest branch.
    Equations that carry a sub-jaxpr contribute only the sub-jaxpr
    (never double-counted).

    A loop body (scan/while — what a ``fori_loop`` or megastep
    ``unroll=N`` lowers to) is deliberately counted ONCE, never
    multiplied by its trip count: the event stream records a loop
    body's collectives exactly once too (the body traces once), so
    compute and communication must cover the same window — the
    prediction is per loop-body execution, consistent with the matched
    schedules the timing runs over.  Duck-typed like
    analysis/walker.py, so fakes drive it in the pure tests."""
    jaxpr = getattr(closed, "jaxpr", closed)
    if jaxpr is None:
        return 0
    total = 0
    for eqn in getattr(jaxpr, "eqns", ()):
        params = getattr(eqn, "params", None) or {}
        subs = []
        for key in ("jaxpr", "call_jaxpr", "body_jaxpr", "cond_jaxpr"):
            if key in params and params[key] is not None:
                subs.append(params[key])
        branches = params.get("branches")
        if branches:
            total += max(
                (jaxpr_traffic_bytes(b) for b in branches), default=0)
        if subs:
            for sub in subs:
                total += jaxpr_traffic_bytes(sub)
            continue
        if branches:
            continue
        for v in getattr(eqn, "outvars", ()):
            total += _aval_bytes(getattr(v, "aval", None))
    return total


def host_map_for(comm):
    """``host_of_rank`` of the analyzed comm's world, or ``None`` (all
    ICI) when no topology is derivable — the flat-fallback convention of
    parallel/topology.py."""
    from ..parallel.topology import derive_world_topology

    topo = derive_world_topology(comm)
    return None if topo is None else topo.host_of_rank


# ---------------------------------------------------------------------------
# per-SchedOp cost
# ---------------------------------------------------------------------------


def _base_op(op: SchedOp) -> str:
    if op.kind in ("start", "wait"):
        return op.op.rsplit("_", 1)[0]
    return op.op


def _op_payload(op: SchedOp) -> int:
    if op.fused is not None and op.fused[1]:
        return int(op.fused[1])  # flat-buffer bytes of a fused op
    return int(op.payload_bytes or 0)


def sched_op_cost(op: SchedOp, world: int,
                  host_of_rank=None,
                  payload: Optional[int] = None) -> OpCost:
    """Model one schedule op: group size from the participants claim,
    host span from the dispatch annotation (or the topology map), the
    algorithm the selector recorded (``native`` HLO where none was)."""
    base = _base_op(op)
    nbytes = _op_payload(op) if payload is None else payload
    if op.kind in ("send", "recv"):
        if op.src is None or op.dst is None or host_of_rank is None:
            return p2p_cost(nbytes, same_host=True)
        return p2p_cost(
            nbytes,
            same_host=link_class(host_of_rank, op.src, op.dst) == "ici")
    members = op.participants
    k = len(members) if members else world
    hosts = op.hosts
    if hosts is None and host_of_rank is not None:
        span = members if members else range(world)
        try:
            hosts = span_hosts(host_of_rank, list(span))
        except IndexError:  # sub-world rank ids beyond the map: flat
            hosts = None
    preserve = (op.reduction is not None
                and op.reduction not in ENUM_REDUCTIONS)
    return collective_cost(base, op.algo, nbytes, k, hosts=hosts,
                           hier=op.hier, preserve=preserve,
                           codec=getattr(op, "codec", None))


# ---------------------------------------------------------------------------
# the timed simulation
# ---------------------------------------------------------------------------


class _Node:
    """One retirement on the timeline; ``pred`` is the node that gated
    it (the critical-path back-pointer)."""

    __slots__ = ("rank", "pos", "op", "t0", "t1", "pred")

    def __init__(self, rank, pos, op, t0, t1, pred):
        self.rank = rank
        self.pos = pos
        self.op = op
        self.t0 = t0
        self.t1 = t1
        self.pred = pred

    def to_json(self) -> dict:
        return {
            "rank": self.rank,
            "pos": self.pos,
            "op": self.op.op,
            "kind": self.op.kind,
            "t0_us": round(self.t0, 3),
            "t1_us": round(self.t1, 3),
        }


class _TimedSimulation(_Simulation):
    """The progress simulation with clocks: identical readiness rules,
    plus per-rank time advanced by the cost model at every retirement.
    Between consecutive ops a rank pays its **compute gap** — the
    roofline compute estimate spread uniformly over the schedule's gaps
    (ops + 1), the simplest placement consistent with not knowing where
    the program's FLOPs sit relative to its collectives."""

    def __init__(self, matched: MatchedProgram, model: CostModel,
                 host_of_rank=None, gaps: Optional[Dict[int, float]] = None):
        super().__init__(matched)
        self.model = model
        self.host_of_rank = host_of_rank
        self.world = len(self.ranks)
        self.gap = {r: (gaps or {}).get(r, 0.0) for r in self.ranks}
        self.clock: Dict[int, float] = {r: 0.0 for r in self.ranks}
        self.last: Dict[int, Optional[_Node]] = {r: None for r in self.ranks}
        self.send_nodes: Dict[Tuple, List[_Node]] = {}
        self.pool_nodes: Dict[Tuple, List[_Node]] = {}
        self.start_nodes: Dict[Tuple, Dict[int, _Node]] = {}
        self.inst_time: Dict[Tuple, float] = {}  # per matched instance
        self.link_totals = {
            lc: {"rounds": 0, "bytes": 0, "time_us": 0.0}
            for lc in costmodel.LINK_CLASSES
        }
        self.per_op: Dict[str, Dict] = {}

    # -- bookkeeping -------------------------------------------------------

    def _arrive(self, r: int) -> float:
        """Rank ``r``'s arrival time at its next op: clock + one compute
        gap."""
        return self.clock[r] + self.gap[r]

    def _account(self, op_label: str, cost: OpCost, time_us: float) -> None:
        for lc in costmodel.LINK_CLASSES:
            term = cost.link(lc)
            tot = self.link_totals[lc]
            tot["rounds"] += term.rounds
            tot["bytes"] += term.nbytes
            tot["time_us"] += self.model.link_time_us(lc, term.rounds,
                                                      term.nbytes)
        agg = self.per_op.setdefault(
            op_label, {"count": 0, "time_us": 0.0, "bytes": 0})
        agg["count"] += 1
        agg["time_us"] += time_us
        agg["bytes"] += cost.ici.nbytes + cost.dcn.nbytes

    def _node(self, r: int, op: SchedOp, t0: float, t1: float,
              pred) -> _Node:
        node = _Node(r, op.pos, op, t0, t1, pred)
        self.last[r] = node
        self.clock[r] = t1
        return node

    def _inst_cost(self, key: Tuple, members) -> Tuple[OpCost, float]:
        """Cost of one matched collective instance: the widest member's
        payload prices it (the straggler defines completion — exactly
        MPX134's claim)."""
        present = self.m.instances.get(key, {})
        ops = [present[q] for q in present] or None
        if ops is None:
            return costmodel.ZERO_COST, 0.0
        widest = max(ops, key=_op_payload)
        cost = sched_op_cost(widest, self.world, self.host_of_rank)
        t = self.model.time_us(cost)
        return cost, t

    # -- retirement hooks (the timing semantics) ---------------------------

    def _retire_send(self, r: int, op: SchedOp) -> None:
        # buffered: the sender does not block; the transfer is priced at
        # the matching receive
        t = self._arrive(r)
        node = self._node(r, op, t, t, self.last[r])
        ch = (op.comm_key, op.src, op.dst, op.tag)
        self.send_nodes.setdefault(ch, []).append(node)
        self.pool_nodes.setdefault(
            (op.comm_key, op.dst, op.tag), []).append(node)

    def _retire_recv(self, r: int, op: SchedOp) -> None:
        t = self._arrive(r)
        snode = None
        pool = self.pool_nodes.get((op.comm_key, op.dst, op.tag))
        if op.src is None:
            if pool:
                snode = pool.pop(0)
        else:
            ch = (op.comm_key, op.src, op.dst, op.tag)
            idx = self.ordinal.get((r, op.pos), 0)
            sends = self.send_nodes.get(ch, ())
            if idx < len(sends):
                snode = sends[idx]
                if pool is not None and snode in pool:
                    # mirror the base simulation's _consume_recv, which
                    # drains the wildcard pool for EVERY recv: a later
                    # wildcard must never adopt an already-consumed send
                    pool.remove(snode)
        ready = t if snode is None else max(t, snode.t1)
        same = (snode is None or self.host_of_rank is None
                or link_class(self.host_of_rank, snode.rank, r) == "ici")
        cost = p2p_cost(_op_payload(op), same_host=same)
        dt = self.model.time_us(cost)
        pred = snode if (snode is not None and snode.t1 > t) else self.last[r]
        self._node(r, op, ready, ready + dt, pred)
        self._account(op.op, cost, dt)

    def _retire_start(self, r: int, op: SchedOp) -> None:
        # nonblocking issue: free at issue; the phases are priced at the
        # paired wait, which is what makes overlap visible to the model
        t = self._arrive(r)
        node = self._node(r, op, t, t, self.last[r])
        self.start_nodes.setdefault(inst_key(op), {})[r] = node

    def _retire_coll(self, key: Tuple, members) -> None:
        entries = {q: self._arrive(q) for q in members}
        anchor = max(entries, key=lambda q: (entries[q], q))
        t0 = entries[anchor]
        cost, dt = self._inst_cost(key, members)
        t1 = t0 + dt
        self.inst_time[key] = dt
        anchor_node = self._node(anchor, self.m.instances[key].get(
            anchor, self.m.instances[key][min(self.m.instances[key])]),
            t0, t1, self.last[anchor])
        for q in members:
            if q == anchor:
                continue
            op_q = self.m.instances.get(key, {}).get(q)
            if op_q is None:
                self.clock[q] = t1
                continue
            self._node(q, op_q, t0, t1, anchor_node)
        self._account(_base_op(anchor_node.op), cost, dt)

    def _retire_wait(self, r: int, op: SchedOp) -> None:
        key = inst_key(op)
        starts = self.start_nodes.get(key, {})
        issue = max((n.t1 for n in starts.values()), default=0.0)
        cost, dt = self._inst_cost(key, self.m.expected.get(key, (r,)))
        done = issue + dt
        t = self._arrive(r)
        if key not in self.inst_time:
            self.inst_time[key] = dt
            # account under the base op name, like _retire_coll: one
            # logical collective type = one per-op breakdown row,
            # whether it dispatched blocking or as a start/wait span
            self._account(_base_op(op), cost, dt)
        if done > t:
            anchor = max(starts, key=lambda q: starts[q].t1) if starts \
                else None
            pred = starts.get(anchor) if anchor is not None else self.last[r]
            self._node(r, op, t, done, pred)
        else:  # fully hidden behind the compute since the start
            self._node(r, op, t, t, self.last[r])

    # -- results -----------------------------------------------------------

    def finished(self) -> bool:
        return all(self.head(r) is None for r in self.ranks)

    def finish_times(self) -> Dict[int, float]:
        """Per-rank predicted finish: the clock plus the trailing
        compute gap (a schedule of N ops has N+1 gaps)."""
        return {r: self.clock[r] + self.gap[r] for r in self.ranks}

    def critical_path(self) -> List[_Node]:
        finish = self.finish_times()
        tail_rank = max(finish, key=lambda r: (finish[r], r))
        node = self.last[tail_rank]
        path: List[_Node] = []
        seen = set()
        while node is not None and id(node) not in seen:
            seen.add(id(node))
            path.append(node)
            node = node.pred
        path.reverse()
        return path


# ---------------------------------------------------------------------------
# the report
# ---------------------------------------------------------------------------


@dataclass
class CostReport:
    """``Report.cost``: the prediction and its breakdown.  All times in
    microseconds; ``total_us`` is the headline predicted step time
    (critical path + fixed host dispatch)."""

    total_us: float = 0.0
    path_us: float = 0.0
    dispatch_us: float = 0.0
    compute_us: Dict[int, float] = field(default_factory=dict)
    per_link: Dict[str, Dict] = field(default_factory=dict)
    per_op: Dict[str, Dict] = field(default_factory=dict)
    critical_path: List[Dict] = field(default_factory=list)
    amortization: Dict = field(default_factory=dict)
    params: Dict = field(default_factory=dict)
    source: Optional[str] = None
    ranks: Tuple[int, ...] = ()

    def to_json(self) -> Dict:
        return {
            "total_us": round(self.total_us, 3),
            "path_us": round(self.path_us, 3),
            "dispatch_us": round(self.dispatch_us, 3),
            "compute_us": {str(r): round(v, 3)
                           for r, v in sorted(self.compute_us.items())},
            "per_link": {
                lc: {"rounds": v["rounds"], "bytes": v["bytes"],
                     "time_us": round(v["time_us"], 3)}
                for lc, v in self.per_link.items()
            },
            "per_op": {
                op: {"count": v["count"], "bytes": v["bytes"],
                     "time_us": round(v["time_us"], 3)}
                for op, v in sorted(self.per_op.items())
            },
            "critical_path": self.critical_path,
            "amortization": self.amortization,
            "params": self.params,
            "source": self.source,
            "ranks": list(self.ranks),
        }

    def render(self, max_path: int = 20) -> str:
        src = self.source or "analytic defaults"
        lines = [
            f"predicted step time: {self.total_us:.1f} us "
            f"(critical path {self.path_us:.1f} us + dispatch "
            f"{self.dispatch_us:.1f} us; cost model: {src})"
        ]
        for lc in sorted(self.per_link):
            v = self.per_link[lc]
            lines.append(
                f"  {lc}: {v['bytes']} B over {v['rounds']} round(s), "
                f"{v['time_us']:.1f} us"
            )
        for op, v in sorted(self.per_op.items()):
            lines.append(
                f"  {op} x{v['count']}: {v['bytes']} B, "
                f"{v['time_us']:.1f} us"
            )
        if self.compute_us:
            hi = max(self.compute_us.values())
            lines.append(f"  compute (roofline): up to {hi:.1f} us/rank")
        if self.critical_path:
            lines.append("  critical path:")
            shown = self.critical_path[:max_path]
            for n in shown:
                lines.append(
                    f"    rank {n['rank']}: {n['op']} (pos {n['pos']}) "
                    f"{n['t0_us']:.1f} -> {n['t1_us']:.1f} us"
                )
            if len(self.critical_path) > len(shown):
                lines.append(
                    f"    ... {len(self.critical_path) - len(shown)} "
                    "more node(s)")
        return "\n".join(lines)


# ---------------------------------------------------------------------------
# the pass
# ---------------------------------------------------------------------------


def run_cost_pass(matched: MatchedProgram, *, model: Optional[CostModel]
                  = None, host_of_rank=None, closed=None,
                  meta: Optional[dict] = None
                  ) -> Tuple[Optional[CostReport], List[Finding]]:
    """Timed simulation + the MPX131-135 critic over a matched program.

    ``closed`` maps rank -> (duck-typed) closed jaxpr for the roofline
    compute estimate; missing ranks reuse the first available estimate
    (SPMD programs are near-uniform).  Returns ``(None, [])`` when the
    schedules do not run to completion — a deadlocked program has no
    step time, and the progress checker already owns the diagnosis."""
    if model is None:
        model = CostModel()
    meta = dict(meta or {})
    traffic: Dict[int, int] = {}
    default_traffic = 0
    for r in matched.ranks:
        t = jaxpr_traffic_bytes((closed or {}).get(r))
        if t:
            default_traffic = default_traffic or t
        traffic[r] = t
    compute_us = {
        r: model.compute_us(traffic[r] or default_traffic)
        for r in matched.ranks
    }
    gaps = {
        r: compute_us[r] / (len(matched.schedules[r]) + 1)
        for r in matched.ranks
    }
    sim = _TimedSimulation(matched, model, host_of_rank, gaps)
    sim.run()
    if not sim.finished():
        return None, []
    finish = sim.finish_times()
    path_us = max(finish.values()) if finish else 0.0
    path = sim.critical_path()

    findings: List[Finding] = []
    findings.extend(_check_overlap(sim, matched))
    fusion_savings, fusion_findings = _check_fusion(sim, matched, meta)
    findings.extend(fusion_findings)
    findings.extend(_check_mispick(sim, matched))
    findings.extend(_check_imbalance(sim, matched))
    findings.extend(_check_p2p_chain(sim, path, path_us))
    findings.extend(_check_pipeline_mispick(sim, matched))
    findings.sort(key=lambda f: (f.index if f.index is not None else -1,
                                 f.code))

    dispatch = model.dispatch_us
    report = CostReport(
        total_us=path_us + dispatch,
        path_us=path_us,
        dispatch_us=dispatch,
        compute_us=compute_us,
        per_link=sim.link_totals,
        per_op=sim.per_op,
        critical_path=[n.to_json() for n in path],
        amortization={
            "dispatch_us": dispatch,
            # mpx.compile(fn, ..., unroll=N) keeps N steps device-
            # resident per host dispatch (docs/aot.md): host cost ~1/N
            "megastep_per_step_host_us": {
                str(n): round(dispatch / n, 3) for n in (1, 8, 64)
            },
            "fusion_savings_us": round(fusion_savings, 3),
        },
        params=model.to_json(),
        source=model.source,
        ranks=tuple(matched.ranks),
    )
    return report, findings


# ---------------------------------------------------------------------------
# the critic
# ---------------------------------------------------------------------------


def _model_provenance(model) -> str:
    """Advisory-text provenance of a tuning-layer-sourced model
    (``tuned@<stamp>`` — docs/autotune.md): the MPX131-133 texts then
    cite MEASURED parameters, not the analytic defaults.  Empty for
    defaults and plain cost-model files (whose path already rides
    ``Report.cost``)."""
    stamp = getattr(model, "tuned_stamp", None)
    return f" [model tuned@{stamp}]" if stamp else ""


def _check_overlap(sim: _TimedSimulation,
                   matched: MatchedProgram) -> List[Finding]:
    """MPX131: blocking collectives whose predicted wire time the
    adjacent compute could substantially hide via the async split."""
    agg: Dict[Tuple, Dict] = {}
    for key, present in matched.instances.items():
        anchor = min(present)
        op = present[anchor]
        if op.kind != "coll" or _base_op(op) not in ASYNC_CAPABLE_OPS:
            continue
        t = sim.inst_time.get(key, 0.0)
        if t <= 0:
            continue
        gap = max(sim.gap.get(q, 0.0) for q in present)
        hideable = min(gap, t)
        if hideable < OVERLAP_HIDE_FRACTION * t:
            continue
        slot = agg.setdefault((op.op, op.comm_uid), {
            "count": 0, "hideable": 0.0, "total": 0.0, "op": op})
        slot["count"] += 1
        slot["hideable"] += hideable
        slot["total"] += t
    findings = []
    for (name, comm_uid), v in sorted(agg.items(), key=lambda kv: str(kv[0])):
        op = v["op"]
        pct = 100.0 * v["hideable"] / v["total"]
        findings.append(Finding(
            code="MPX131", op=name, index=op.event_index, rank=op.rank,
            seq=op.seq,
            message=(f"{v['count']} blocking {name} collective(s) on comm "
                     f"{comm_uid} predict {v['total']:.1f} us of wire "
                     f"time while the adjacent compute could hide "
                     f"{v['hideable']:.1f} us (~{pct:.0f}%) of it"
                     + _model_provenance(sim.model)),
            suggestion=(f"split them with {name}_start/{name}_wait and "
                        "issue the independent compute between the two "
                        "(mpx.overlap() pairs automatically) — "
                        "docs/overlap.md"),
        ))
    return findings


def _check_fusion(sim: _TimedSimulation, matched: MatchedProgram,
                  meta: dict) -> Tuple[float, List[Finding]]:
    """MPX132: adjacent fusable collectives, priced — N alpha rounds
    collapse into one flat-buffer collective (upgrades MPX111 with
    predicted savings).  Mirrors MPX111's adjacency rule over the
    anchor rank's schedule."""
    if meta.get("fusion") != "off" or not matched.ranks:
        return 0.0, []
    cap = (meta.get("measured_fusion_bucket_bytes")
           or meta.get("fusion_bucket_bytes") or 0)
    sched = matched.schedules[matched.ranks[0]]
    findings: List[Finding] = []
    total_savings = 0.0
    run: List[SchedOp] = []

    def _key(op: SchedOp):
        return (op.op, op.comm_key, op.reduction, op.root)

    def _fusable(op: SchedOp) -> bool:
        # mirror MPX111's rule exactly, eager exclusion included: an
        # eager op never enters the fusion queue, so advising
        # MPI4JAX_TPU_FUSION=auto for it would be wrong
        return (op.kind == "coll" and op.op in FUSABLE_OPS
                and not op.eager and op.fused is None
                and (op.reduction is None
                     or op.reduction in ENUM_REDUCTIONS)
                and (not cap or _op_payload(op) <= cap))

    def _close(run: List[SchedOp]):
        nonlocal total_savings
        if len(run) < 2:
            return
        first = run[0]
        separate = sum(
            sim.model.time_us(sched_op_cost(op, sim.world,
                                            sim.host_of_rank))
            for op in run
        )
        total = sum(_op_payload(op) for op in run)
        fused = sim.model.time_us(sched_op_cost(first, sim.world,
                                                sim.host_of_rank,
                                                payload=total))
        savings = separate - fused
        if savings <= 0:
            return
        total_savings += savings
        findings.append(Finding(
            code="MPX132", op=first.op, index=first.event_index,
            rank=first.rank, seq=first.seq,
            message=(f"{len(run)} adjacent {first.op} collectives on "
                     f"comm {first.comm_uid} ({total} B total) would "
                     f"coalesce into one flat-buffer collective: the "
                     f"cost model predicts {separate:.1f} us separate "
                     f"vs {fused:.1f} us fused — {savings:.1f} us "
                     "saved per step" + _model_provenance(sim.model)),
            suggestion=("set MPI4JAX_TPU_FUSION=auto (or "
                        "mpx.set_fusion_mode('auto')) and consume "
                        "results after issuing the whole batch — "
                        "docs/overlap.md"),
        ))

    for op in sched:
        if _fusable(op) and run and _key(run[-1]) == _key(op):
            run.append(op)
            continue
        _close(run)
        run = [op] if _fusable(op) else []
    _close(run)
    return total_savings, findings


def _check_mispick(sim: _TimedSimulation,
                   matched: MatchedProgram) -> List[Finding]:
    """MPX133: the model disagrees with resolve_algo's pick by more
    than the mispick threshold."""
    findings: List[Finding] = []
    seen = set()
    for key in sorted(matched.instances, key=str):
        present = matched.instances[key]
        op = present[min(present)]
        base = _base_op(op)
        if op.kind != "coll" or (base not in ALGO_OPS
                                 and base != "alltoall"):
            continue
        if base == "alltoall":
            # the permutation family: flat ("native"/"pairwise" price
            # identically — a fixed permutation) vs the two-level split
            if op.algo not in ("native", "pairwise", "hier"):
                continue
            chosen = "native" if op.algo == "pairwise" else op.algo
        else:
            if op.algo not in ("butterfly", "ring", "hier"):
                continue
            chosen = op.algo
        members = op.participants
        k = len(members) if members else sim.world
        if k < 2:
            continue
        nbytes = _op_payload(op)
        hier = op.hier
        if hier is None and op.hosts and op.hosts > 1 and k % op.hosts == 0:
            hier = (op.hosts, k // op.hosts)
        preserve = (op.reduction is not None
                    and op.reduction not in ENUM_REDUCTIONS)
        best, times = costmodel.best_algo(
            base, nbytes, k, sim.model, hosts=op.hosts, hier=hier,
            preserve=preserve)
        if chosen not in times or best == chosen:
            continue
        delta = times[chosen] - times[best]
        if delta < MISPICK_MIN_FRACTION * max(times[best], 1e-9):
            continue
        dedupe = (base, op.comm_uid, nbytes, chosen, best)
        if dedupe in seen:
            continue
        seen.add(dedupe)
        findings.append(Finding(
            code="MPX133", op=op.op, index=op.event_index, rank=op.rank,
            seq=op.seq,
            message=(f"{base} on comm {op.comm_uid} ({nbytes} B over "
                     f"{k} rank(s)) lowered as '{chosen}' "
                     f"({times[chosen]:.1f} us predicted) but the cost "
                     f"model predicts '{best}' at {times[best]:.1f} us "
                     f"— {delta:.1f} us/step faster"
                     + _model_provenance(sim.model)),
            suggestion=(f"force MPI4JAX_TPU_COLLECTIVE_ALGO={best} for "
                        "an A/B run, or recalibrate the crossover flags "
                        "with benchmarks/micro.py --cost-calibrate"),
        ))
    return findings


def _check_imbalance(sim: _TimedSimulation,
                     matched: MatchedProgram) -> List[Finding]:
    """MPX134: rank-divergent payload bytes on one matched collective —
    the widest rank is a straggler by construction."""
    findings: List[Finding] = []
    for key in sorted(matched.instances, key=str):
        present = matched.instances[key]
        if len(present) < 2:
            continue
        op0 = present[min(present)]
        if op0.kind != "coll":
            continue
        payloads = {q: _op_payload(present[q]) for q in present}
        lo_r = min(payloads, key=lambda q: (payloads[q], q))
        hi_r = max(payloads, key=lambda q: (payloads[q], q))
        if payloads[lo_r] == payloads[hi_r]:
            continue
        t_hi = sim.model.time_us(sched_op_cost(
            present[hi_r], sim.world, sim.host_of_rank))
        t_lo = sim.model.time_us(sched_op_cost(
            present[lo_r], sim.world, sim.host_of_rank))
        delta = max(0.0, t_hi - t_lo)
        findings.append(Finding(
            code="MPX134", op=op0.op, index=op0.event_index, rank=hi_r,
            seq=op0.seq,
            message=(f"collective #{op0.seq} on comm {op0.comm_uid} "
                     f"ships {payloads[lo_r]}..{payloads[hi_r]} B across "
                     f"its member ranks: rank {hi_r} is a straggler by "
                     f"construction — every member waits out a "
                     f"predicted +{delta:.1f} us each step"),
            suggestion=("pad or re-shard the payload so matched members "
                        "carry equal bytes (rank-divergent shapes also "
                        "defeat fusion bucketing, docs/overlap.md)"),
        ))
    return findings


def _check_p2p_chain(sim: _TimedSimulation, path: List[_Node],
                     path_us: float) -> List[Finding]:
    """MPX135: a serialized send/recv ladder occupying the critical
    path — the GPipe shape.  Fires on maximal runs of consecutive p2p
    nodes crossing enough distinct ranks (a lockstep halo exchange stays
    on one or two ranks and never trips this)."""
    findings: List[Finding] = []
    if not path or path_us <= 0:
        return findings
    run: List[_Node] = []

    def _close(run: List[_Node]):
        if not run:
            return
        hops = sum(1 for n in run if n.op.kind == "recv")
        ranks = {n.rank for n in run}
        span = run[-1].t1 - run[0].t0
        if (hops < CHAIN_MIN_HOPS or len(ranks) < CHAIN_MIN_RANKS
                or span < CHAIN_MIN_FRACTION * path_us):
            return
        first = run[0]
        chain = " -> ".join(
            f"rank {n.rank}" for i, n in enumerate(run)
            if n.op.kind == "recv" and (i == 0 or run[i - 1].rank != n.rank)
        ) or f"rank {first.rank}"
        pct = 100.0 * span / path_us
        # the chain is pipeline-shaped: price it as a naive ladder over
        # len(ranks) stages and cite the modeled bubble fraction plus
        # the 1F1B twin the schedule compiler would emit instead
        # (satellite of the mpx.pipeline PR — the MPX111->MPX132 move)
        payload = max(
            (_op_payload(n.op) for n in run if n.op.kind == "recv"),
            default=0)
        s = len(ranks)
        m = max(1, hops // max(1, s - 1))
        c = sim.model.compute_us(2 * payload)
        try:
            ladder_us = costmodel.pipeline_wall_us(
                "ladder", s, m, payload, c, sim.model)
            f1b_us = costmodel.pipeline_wall_us(
                "1f1b", s, m, payload, c, sim.model)
            bubble = costmodel.pipeline_bubble_fraction(
                "ladder", s, m, payload, c, sim.model)
        except ValueError:
            ladder_us = f1b_us = bubble = 0.0
        findings.append(Finding(
            code="MPX135", op=first.op.op, index=first.op.event_index,
            rank=first.rank, seq=first.op.seq,
            message=(f"a serialized point-to-point chain of {hops} "
                     f"transfer(s) across ranks "
                     f"{sorted(ranks)} occupies {span:.1f} us "
                     f"(~{pct:.0f}%) of the predicted critical path "
                     f"({chain}): each hop waits for the previous "
                     "stage's full compute + transfer — modeled as a "
                     f"{s}-stage ladder its bubble fraction is "
                     f"{100.0 * bubble:.0f}%"
                     + _model_provenance(sim.model)),
            suggestion=(f"microbatch the ladder with mpx.pipeline "
                        f"(schedule='auto'): at this shape a 1F1B "
                        f"schedule prices at {f1b_us:.1f} us/round vs "
                        f"{ladder_us:.1f} us serialized, so stage i+1's "
                        "transfer overlaps stage i's compute — see "
                        "examples/pipeline_parallel.py and "
                        "docs/pipeline.md"),
        ))

    for n in path:
        if n.op.kind in ("send", "recv"):
            run.append(n)
        else:
            _close(run)
            run = []
    _close(run)
    return findings


def _check_pipeline_mispick(sim: _TimedSimulation,
                            matched: MatchedProgram) -> List[Finding]:
    """MPX144: a pipeline program (mpx.pipeline) stamped its boundary
    transfers with a ``(schedule, stages, microbatches, virtual,
    payload_bytes)`` tuple (SchedOp.meta["pipeline"], via
    hook.mark_last_event); when the cost model prices an expressible
    alternative schedule measurably better at that point, say so.  The
    candidate set matches the compiler's own ``schedule='auto'`` search
    (``costmodel.best_schedule``): gpipe vs 1f1b for a flat program
    (v == 1); a program already chunked into v >= 2 stage-chunks can
    only express interleaved, so it has no alternative and the advisory
    never fires on it — an alternative that needs restructuring is not
    'expressible'."""
    findings: List[Finding] = []
    seen = set()
    for r in matched.ranks:
        for op in matched.schedules[r]:
            stamp = (op.meta or {}).get("pipeline")
            if not stamp:
                continue
            try:
                schedule = str(stamp[0])
                stages, microbatches, virtual, payload = (
                    int(stamp[1]), int(stamp[2]), int(stamp[3]),
                    int(stamp[4]))
            except (TypeError, ValueError, IndexError, KeyError):
                continue
            key = (schedule, stages, microbatches, virtual, payload)
            if key in seen:
                continue
            seen.add(key)
            # same per-microbatch compute estimate the compiler's auto
            # pick uses: the roofline floor of streaming the boundary
            # activation in and out of each stage
            c = sim.model.compute_us(2 * payload)
            try:
                chosen_us = costmodel.pipeline_wall_us(
                    schedule, stages, microbatches, payload, c,
                    sim.model, virtual=virtual)
                best, times = costmodel.best_schedule(
                    stages, microbatches, payload, c, sim.model,
                    virtual=virtual)
            except ValueError:
                continue
            best_us = times[best]
            if best == schedule or chosen_us <= 0:
                continue
            delta = chosen_us - best_us
            if delta < MISPICK_MIN_FRACTION * best_us:
                continue
            try:
                bub_chosen = costmodel.pipeline_bubble_fraction(
                    schedule, stages, microbatches, payload, c,
                    sim.model, virtual=virtual)
                bub_best = costmodel.pipeline_bubble_fraction(
                    best, stages, microbatches, payload, c, sim.model,
                    virtual=virtual)
            except ValueError:
                bub_chosen = bub_best = 0.0
            findings.append(Finding(
                code="MPX144", op=op.op, index=op.event_index, rank=r,
                seq=op.seq,
                message=(f"pipeline program runs schedule '{schedule}' "
                         f"over {stages} stage(s) x {microbatches} "
                         f"microbatch(es) ({payload} B boundary "
                         f"payload): predicted {chosen_us:.1f} us/round "
                         f"vs {best_us:.1f} us for '{best}' — bubble "
                         f"fraction {100.0 * bub_chosen:.0f}% vs "
                         f"{100.0 * bub_best:.0f}%"
                         + _model_provenance(sim.model)),
                suggestion=(f"pass schedule='auto' (or "
                            f"schedule='{best}') to mpx.pipeline so "
                            "the cost model picks the cheaper phase "
                            "program (docs/pipeline.md)"),
            ))
    return findings
