"""Global schedule matching: pair every op across the per-rank schedules.

ISP/MUST-style whole-program matching over the per-rank
:class:`~.schedule.SchedOp` schedules:

- **collectives** match by ``(comm_key, seq)`` across all member ranks:
  the k-th collective a rank issues on a comm must be the SAME operation
  (kind, root, reduction, member group) every other member issues as its
  k-th — a signature disagreement is MPX120, a member that never arrives
  is MPX123, divergent fusion packing is MPX124, and a divergent
  two-level hierarchy plan is MPX125;
- **point-to-point** matches by ``(comm_key, src, dst, tag)`` channel
  with FIFO (non-overtaking) semantics: the k-th send on a channel pairs
  with the k-th receive.  Count/type mismatches reuse the established
  codes cross-rank: a send no rank ever receives is MPX101, a receive no
  rank ever sends to is MPX102, a paired send/recv whose dtype or
  element count disagree is MPX106;
- **async** ``*_start``/``*_wait`` pairs arrive already span-linked by
  the schedule builder (the start carries the instance's seq; the wait
  references it), so they match like collectives.

The matcher is purely structural; ordering-dependent hangs (cycles) are
the progress checker's job (analysis/progress.py) over the
:class:`MatchedProgram` built here.  Dependency-free (no jax).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from .report import Finding
from .schedule import SchedOp

# codes this module owns in the checker-coverage sense (MPX101/102/106
# are reused from the single-trace catalog with cross-rank messages)
CROSSRANK_CODES = ("MPX120", "MPX123", "MPX124", "MPX125")


def inst_key(op: SchedOp) -> Tuple:
    """Matching identity of a collective instance: on a color-split comm
    one traced op is a SEPARATE exchange per member group, so the
    participants claim is part of the key (two groups of one comm never
    match each other — and never deadlock each other)."""
    return (op.comm_key, op.seq, op.participants)


@dataclass
class MatchedProgram:
    """The matched whole-program view the progress checker consumes."""

    schedules: Dict[int, List[SchedOp]]
    # inst_key -> {rank: its coll/start op}
    instances: Dict[Tuple, Dict[int, SchedOp]]
    # inst_key -> {rank: its wait op}
    waits: Dict[Tuple, Dict[int, SchedOp]]
    # inst_key -> sorted expected member ranks (∩ analyzed)
    expected: Dict[Tuple, Tuple[int, ...]]
    # (comm_key, src, dst, tag) -> ([send ops], [explicit recv ops])
    channels: Dict[Tuple[int, int, int, Optional[int]],
                   Tuple[List[SchedOp], List[SchedOp]]]
    # (comm_key, dst, tag) -> [wildcard recv ops]
    wildcards: Dict[Tuple[int, int, Optional[int]], List[SchedOp]]
    findings: List[Finding] = field(default_factory=list)

    @property
    def ranks(self) -> Tuple[int, ...]:
        return tuple(sorted(self.schedules))


def match_schedules(schedules: Dict[int, List[SchedOp]]) -> MatchedProgram:
    """Match ``schedules`` (rank -> ordered SchedOps) and report every
    structural mismatch; the analyzed rank set is ``schedules``' keys
    (membership checks are restricted to it, so analyzing a subset of a
    comm never false-positives the absent ranks)."""
    analyzed = set(schedules)
    instances: Dict[Tuple, Dict[int, SchedOp]] = {}
    waits: Dict[Tuple, Dict[int, SchedOp]] = {}
    channels: Dict = {}
    wildcards: Dict = {}
    coll_counts: Dict[Tuple[int, int], int] = {}  # (rank, comm_key)
    at_rank: Dict[Tuple[int, int, int], SchedOp] = {}  # (rank, ck, seq)

    for r in sorted(schedules):
        for op in schedules[r]:
            if op.kind in ("coll", "start"):
                instances.setdefault(inst_key(op), {})[r] = op
                k = (r, op.comm_key)
                coll_counts[k] = coll_counts.get(k, 0) + 1
                at_rank[(r, op.comm_key, op.seq)] = op
            elif op.kind == "wait":
                waits.setdefault(inst_key(op), {})[r] = op
            elif op.kind == "send":
                ch = channels.setdefault(
                    (op.comm_key, op.src, op.dst, op.tag), ([], []))
                ch[0].append(op)
            elif op.kind == "recv":
                if op.src is None:
                    wildcards.setdefault(
                        (op.comm_key, op.dst, op.tag), []).append(op)
                else:
                    ch = channels.setdefault(
                        (op.comm_key, op.src, op.dst, op.tag), ([], []))
                    ch[1].append(op)

    findings: List[Finding] = []
    expected: Dict[Tuple, Tuple[int, ...]] = {}
    orphaned: set = set()       # (comm_key, rank) reported once
    group_mismatch: set = set()  # (comm_key, seq) reported once

    for key in sorted(instances, key=str):
        ck, seq, parts = key
        present = instances[key]
        members: set = (set(parts) if parts is not None else set(present))
        exp = tuple(sorted(members & analyzed))
        expected[key] = exp

        # member-group agreement: a rank this cluster claims that issued
        # its (ck, seq)-th collective with a DIFFERENT group claim
        for q in exp:
            other = at_rank.get((q, ck, seq))
            if (other is None or other.participants == parts
                    or other.participants is None
                    or (ck, seq) in group_mismatch):
                continue
            group_mismatch.add((ck, seq))
            first = present[min(present)]
            findings.append(Finding(
                code="MPX120", op=first.op, index=first.event_index,
                rank=min(present), seq=seq,
                message=(f"collective #{seq} on comm {first.comm_uid} "
                         "diverges across ranks: rank(s) "
                         f"{sorted(present)} pair group {parts} while "
                         f"rank {q} pairs group {other.participants} — "
                         "the groups never match each other"),
                suggestion=("derive the member groups from shared static "
                            "structure (the same Split tables on every "
                            "rank)"),
            ))

        # signature agreement across the matched members (MPX120)
        sigs: Dict[Tuple, List[int]] = {}
        for r in sorted(present):
            op = present[r]
            sig = (op.op, op.root, op.reduction)
            sigs.setdefault(sig, []).append(r)
        if len(sigs) > 1:
            first = present[min(present)]
            detail = "; ".join(
                f"rank(s) {rs} issue {s[0]}"
                + (f" root={s[1]}" if s[1] is not None else "")
                + (f" reduction={s[2]}" if s[2] is not None else "")
                for s, rs in sorted(sigs.items(), key=lambda kv: kv[1])
            )
            findings.append(Finding(
                code="MPX120", op=first.op, index=first.event_index,
                rank=min(present), seq=seq,
                message=(f"collective #{seq} on comm {first.comm_uid} "
                         f"diverges across ranks: {detail} — each side "
                         "waits in a collective its peers never enter"),
                suggestion=("make every member rank issue the same "
                            "collective sequence on this comm (hoist the "
                            "divergent branch, or split the comm)"),
            ))

        # fusion packing agreement (MPX124)
        fsigs = {op.fused for op in present.values() if op.fused is not None}
        if len(fsigs) > 1:
            first = present[min(present)]
            per_rank = ", ".join(
                f"rank {r}: {present[r].fused[0]} member(s) / "
                f"{present[r].fused[1]} B"
                for r in sorted(present) if present[r].fused is not None
            )
            findings.append(Finding(
                code="MPX124", op=first.op, index=first.event_index,
                rank=min(present), seq=seq,
                message=(f"fused collective #{seq} on comm "
                         f"{first.comm_uid} packs different flat buffers "
                         f"across ranks ({per_rank}): the flat-buffer "
                         "exchange would ship mismatched payloads"),
                suggestion=("issue the same fusable op sequence on every "
                            "rank (rank-divergent branches must not add "
                            "or drop members inside a fusion region)"),
            ))

        # two-level hierarchy plan agreement (MPX125)
        hsigs = {op.hier for op in present.values()}
        if len(hsigs) > 1 and any(h is not None for h in hsigs):
            first = present[min(present)]
            per_rank = ", ".join(
                f"rank {r}: "
                + (f"{present[r].hier[0]}x{present[r].hier[1]}"
                   if present[r].hier is not None else "flat")
                for r in sorted(present)
            )
            findings.append(Finding(
                code="MPX125", op=first.op, index=first.event_index,
                rank=min(present), seq=seq,
                message=(f"collective #{seq} on comm {first.comm_uid} "
                         "derives different two-level ICI/DCN "
                         f"decompositions across ranks ({per_rank}): "
                         "intra-host and inter-host phases would pair "
                         "different groups"),
                suggestion=("declare one topology for every rank "
                            "(MPI4JAX_TPU_TOPOLOGY) and derive the plan "
                            "from the shared mesh — see docs/topology.md"),
            ))

        # orphaned members (MPX123): an expected rank whose schedule on
        # this comm ends before this instance
        for q in exp:
            if q in present or (ck, q) in orphaned:
                continue
            if coll_counts.get((q, ck), 0) <= seq:
                orphaned.add((ck, q))
                first = present[min(present)]
                findings.append(Finding(
                    code="MPX123", op=first.op, index=first.event_index,
                    rank=q, seq=seq,
                    message=(f"rank {q} is a member of comm "
                             f"{first.comm_uid} but never issues "
                             f"collective #{seq} ({first.op}) that "
                             f"rank(s) {sorted(present)} are matched in: "
                             "the peers block forever"),
                    suggestion=("ensure every member rank reaches this "
                                "collective (a rank-divergent branch that "
                                "skips it orphans the group)"),
                ))

    findings.extend(_check_p2p_counts(channels, wildcards))
    findings.sort(key=lambda f: (f.seq if f.seq is not None else -1, f.code))
    return MatchedProgram(schedules=schedules, instances=instances,
                          waits=waits, expected=expected, channels=channels,
                          wildcards=wildcards, findings=findings)


def _check_p2p_counts(channels, wildcards) -> List[Finding]:
    """Channel-count matching: FIFO pairing + MPX106 on paired type
    signatures; surplus sends may be drained by wildcard receives at the
    same (comm, dst, tag) before MPX101 fires."""
    findings: List[Finding] = []
    # surplus sends per (comm_key, dst, tag), candidates for wildcards
    surplus: Dict[Tuple[int, int, Optional[int]], List[SchedOp]] = {}

    for key in sorted(channels):
        ck, src, dst, tag = key
        sends, recvs = channels[key]
        for s, v in zip(sends, recvs):
            if (s.dtype and v.dtype and s.dtype != v.dtype) or (
                    s.nelems is not None and v.nelems is not None
                    and s.nelems != v.nelems):
                findings.append(Finding(
                    code="MPX106", op="recv", index=v.event_index,
                    rank=v.rank,
                    message=(f"rank {dst}'s recv(src={src}, tag={tag}) "
                             f"template ({v.nelems} x {v.dtype}) does not "
                             f"match rank {src}'s send "
                             f"({s.nelems} x {s.dtype}): MPI "
                             "type-signature rule"),
                    suggestion="make both sides agree in dtype and "
                               "element count",
                ))
        for v in recvs[len(sends):]:
            findings.append(Finding(
                code="MPX102", op="recv", index=v.event_index, rank=v.rank,
                message=(f"rank {dst} receives from rank {src} "
                         f"(tag={tag}) more often than rank {src} sends: "
                         f"this recv (schedule position {v.pos}) has no "
                         "matching send on any rank — it blocks forever"),
                suggestion=(f"issue the matching send on rank {src}, or "
                            "drop the recv"),
            ))
        surplus.setdefault((ck, dst, tag), []).extend(sends[len(recvs):])

    for key in sorted(surplus, key=str):
        ck, dst, tag = key
        extra = surplus[key]
        wild = wildcards.get(key, [])
        for s in extra[len(wild):]:
            findings.append(Finding(
                code="MPX101", op="send", index=s.event_index, rank=s.rank,
                message=(f"rank {s.src}'s send to rank {dst} (tag={tag}, "
                         f"schedule position {s.pos}) is never received "
                         "by any rank: the message is lost (the "
                         "reference would deadlock at MPI_Finalize)"),
                suggestion=(f"issue the matching recv on rank {dst}, or "
                            "drop the send"),
            ))
    for key in sorted(wildcards, key=str):
        ck, dst, tag = key
        wild = wildcards[key]
        avail = len(surplus.get(key, []))
        for v in wild[avail:]:
            findings.append(Finding(
                code="MPX102", op="recv", index=v.event_index, rank=v.rank,
                message=(f"rank {dst}'s wildcard recv (tag={tag}, "
                         f"schedule position {v.pos}) has no remaining "
                         "unmatched send from any rank"),
                suggestion="issue a matching send on some rank, or drop "
                           "the recv",
            ))
    return findings
