"""Per-rank schedules: the cross-rank verifier's program model.

Classic MPI verifiers (ISP / MUST-style schedule matching) show that
cross-rank hangs — mismatched collective orders, send/recv cycles,
orphaned group members — are decidable statically from each rank's
*ordered op schedule*.  This module provides the two halves every
front-end shares:

- the **rank-concretization scope**: while ``mpx.analyze(ranks=...)``
  (or the ambient cross-rank pass) re-traces a program for one rank,
  ``Comm.Get_rank`` returns that rank's concrete coordinates instead of
  a traced ``axis_index``, so rank-dependent Python branches and
  ``lax.cond`` predicates take their real per-rank paths (the per-rank
  re-trace is what makes rank-divergent programs — untraceable in the
  single-program SPMD model — expressible to the verifier at all);
- the **schedule builder**: one rank's recorded event stream
  (:class:`~.graph.CollectiveEvent`) projected onto that rank's ordered
  :class:`SchedOp` list — collectives keep a per-comm sequence number,
  point-to-point ops keep their (src, dst, tag) role, async
  ``*_start``/``*_wait`` pairs keep their span link.

The execution model downstream (analysis/matcher.py + progress.py)
mirrors THIS library's semantics, not textbook rendezvous MPI: sends are
**buffered** (in-region sends record-and-defer; the recv performs the
transfer), receives block until the matching send is *issued*,
collectives synchronize all group members, and a ``*_wait`` blocks until
every member has issued its ``*_start``.  A deadlock found under
buffered sends deadlocks under any buffering, so every cycle reported is
a genuine hang (no false alarms from send-buffer pressure).

Dependency-free (no jax): hand-built schedules drive the matcher and
progress checkers in tests/test_crossrank_pure.py under any JAX version.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

# op names with point-to-point roles (everything else on the dispatch
# stream is treated as a collective over its comm's member group)
P2P_OPS = ("send", "recv", "sendrecv")


# ---------------------------------------------------------------------------
# rank concretization
# ---------------------------------------------------------------------------


class RankConcrete(int):
    """The concretized rank: an ``int`` for data uses (masks,
    coordinates, Python branching — the whole point of the per-rank
    re-trace), but still *rejected* as a structural argument (roots,
    tags, routing specs) exactly like the traced rank it stands in for:
    structure must be rank-uniform statics, and a per-rank trace must
    not silently accept a program the real trace refuses (MPX104).
    Any arithmetic (``r % 2``, ``r ^ 1``, ``int(r)``) returns a plain
    int, so rank-DERIVED values are ordinary statics."""

    __slots__ = ()


def is_rank_concrete(x) -> bool:
    return isinstance(x, RankConcrete)


class ConcreteScope:
    """Active while one rank's schedule trace runs.

    Holds the region comm's axis names/sizes and the concrete linear
    rank (row-major over those axes, the same order ``Get_rank``
    defines).  ``Comm.Get_rank`` / ``GroupComm.Get_rank`` consult the
    innermost scope and return Python ints, so the traced function's
    rank-dependent branches concretize.
    """

    def __init__(self, axis_names: Sequence[str], axis_sizes: Sequence[int],
                 index: int):
        self.names: Tuple[str, ...] = tuple(axis_names)
        self.sizes: Tuple[int, ...] = tuple(int(s) for s in axis_sizes)
        if len(self.names) != len(self.sizes):
            raise ValueError("axis_names and axis_sizes must align")
        world = 1
        for s in self.sizes:
            world *= s
        self.world = world
        if not 0 <= int(index) < world:
            raise ValueError(f"rank index {index} out of range for "
                             f"world {world}")
        self.index = int(index)
        self.coords: Dict[str, int] = dict(
            zip(self.names, _unravel(self.index, self.sizes))
        )


def _unravel(index: int, sizes: Tuple[int, ...]) -> Tuple[int, ...]:
    """Row-major coordinates of ``index`` over ``sizes``."""
    coords = []
    for s in reversed(sizes):
        coords.append(index % s)
        index //= s
    return tuple(reversed(coords))


# thread-local: a per-rank re-trace on one thread must never leak its
# concretization into another thread's REAL trace (where a spurious
# ``concretizing()`` would silently relax send/recv matching).  The
# ``lax.cond`` patch in analysis/crossrank.py is still process-global —
# concurrent tracing while an analysis pass runs is unsupported
# (docs/analysis.md model notes).
import threading

_tls = threading.local()


def _scope_stack() -> List[ConcreteScope]:
    stack = getattr(_tls, "stack", None)
    if stack is None:
        stack = _tls.stack = []
    return stack


def concretizing() -> bool:
    """True while a per-rank schedule trace is running (on this thread):
    in-region send/recv matching relaxes to one-sided recording (the
    cross-rank matcher pairs them instead), and ``Get_rank``
    concretizes."""
    return bool(_scope_stack())


def current_scope() -> Optional[ConcreteScope]:
    stack = _scope_stack()
    return stack[-1] if stack else None


class scope:
    """Context manager installing a :class:`ConcreteScope`."""

    def __init__(self, axis_names, axis_sizes, index):
        self._scope = ConcreteScope(axis_names, axis_sizes, index)

    def __enter__(self):
        _scope_stack().append(self._scope)
        return self._scope

    def __exit__(self, *exc):
        _scope_stack().pop()
        return False


def concrete_comm_rank(axes: Sequence[str]) -> Optional[RankConcrete]:
    """The active scope's linear rank over ``axes`` (row-major), or
    ``None`` when no scope is active or ``axes`` are not all covered
    (the caller falls back to the traced ``axis_index`` path)."""
    sc = current_scope()
    if sc is None:
        return None
    sizes = dict(zip(sc.names, sc.sizes))
    rank = 0
    for a in axes:
        if a not in sc.coords:
            return None
        rank = rank * sizes[a] + sc.coords[a]
    return RankConcrete(rank)


# the partition is a pure function of (scope axes, sizes, comm axes) and
# is consulted once per RECORDED EVENT (hook.begin_event) across world
# re-traces — memoized so a region records O(events) dict hits, not
# O(world^2 * events) partition rebuilds
_groups_memo: Dict[Tuple, Tuple[Tuple[int, ...], ...]] = {}
_GROUPS_MEMO_MAX = 64


def groups_for_axes(axes: Sequence[str]) -> Optional[Tuple[Tuple[int, ...], ...]]:
    """Member groups (world-linear rank ids, group order) a collective
    over ``axes`` forms inside the active scope's world — the implicit
    partition a sub-axes comm induces (e.g. ``comm.sub("x")`` on a
    ``("y", "x")`` mesh groups ranks by row).  ``None`` when no scope is
    active or ``axes`` are not covered."""
    sc = current_scope()
    if sc is None or not set(axes) <= set(sc.names):
        return None
    memo_key = (sc.names, sc.sizes, tuple(axes))
    cached = _groups_memo.get(memo_key)
    if cached is not None:
        return cached
    sizes = dict(zip(sc.names, sc.sizes))
    buckets: Dict[Tuple[int, ...], List[Tuple[int, int]]] = {}
    for wid in range(sc.world):
        cmap = dict(zip(sc.names, _unravel(wid, sc.sizes)))
        key = tuple(cmap[n] for n in sc.names if n not in axes)
        sub = 0
        for a in axes:
            sub = sub * sizes[a] + cmap[a]
        buckets.setdefault(key, []).append((sub, wid))
    out = tuple(
        tuple(w for _, w in sorted(members))
        for _, members in sorted(buckets.items())
    )
    if len(_groups_memo) >= _GROUPS_MEMO_MAX:
        _groups_memo.clear()
    _groups_memo[memo_key] = out
    return out


def static_groups_for(comm) -> Optional[Tuple[Tuple[int, ...], ...]]:
    """Static member groups of ``comm`` for the event recorder: explicit
    on a color split, scope-derived for (sub-)axes comms — recorded only
    during a per-rank trace (the schedule builder is the one consumer,
    so single-trace recording skips the O(world) table copy entirely).
    Duck-typed; never raises."""
    if not concretizing():
        return None
    groups = getattr(comm, "groups", None)
    if groups is not None:
        return tuple(tuple(g) for g in groups)
    axes = getattr(comm, "axes", None)
    if axes is None:
        return None
    return groups_for_axes(axes)


# ---------------------------------------------------------------------------
# the schedule model
# ---------------------------------------------------------------------------


@dataclass
class SchedOp:
    """One rank's op at one schedule position.

    ``kind`` is the progress semantics: ``coll`` (synchronizing
    collective), ``send`` (buffered — never blocks), ``recv`` (blocks
    until the matching send is issued; ``src=None`` is a wildcard),
    ``start`` (nonblocking issue), ``wait`` (blocks until every member
    issued the paired start).  ``comm_key`` is the opaque cross-rank
    communicator identity used for matching (``build_schedule`` derives
    it from the uid, normalizing comms created inside the traced
    function by creation order — see its docstring); ``comm_uid`` is
    kept for display.
    """

    rank: int
    pos: int
    kind: str
    op: str
    comm_uid: int = 0
    comm_key: object = 0
    seq: Optional[int] = None
    participants: Optional[Tuple[int, ...]] = None
    src: Optional[int] = None
    dst: Optional[int] = None
    tag: Optional[int] = None
    root: Optional[int] = None
    reduction: Optional[str] = None
    dtype: str = ""
    nelems: Optional[int] = None
    span: Optional[int] = None
    event_index: int = -1
    fused: Optional[Tuple] = None
    hier: Optional[Tuple] = None
    # cost-model inputs (analysis/cost.py): the dispatch-point payload
    # bytes, the algorithm the selector picked, the host span the
    # hierarchical layer annotated (None where no plan was derivable),
    # and whether the op dispatched eagerly (outside any region — the
    # MPX132 fusion critic mirrors MPX111's eager exclusion from it)
    payload_bytes: int = 0
    algo: Optional[str] = None
    hosts: Optional[int] = None
    # DCN wire codec the hierarchy applied (docs/compression.md) — the
    # cost pass prices the inter-host leg at wire bytes through it
    codec: Optional[str] = None
    eager: bool = False
    meta: Dict = field(default_factory=dict)

    def describe(self) -> str:
        if self.kind == "send":
            return f"send(dst={self.dst}, tag={self.tag})"
        if self.kind == "recv":
            src = "ANY" if self.src is None else self.src
            return f"recv(src={src}, tag={self.tag})"
        tail = f" #{self.seq}" if self.seq is not None else ""
        return f"{self.op}{tail} on comm {self.comm_uid}"


def _nelems(shape) -> Optional[int]:
    if not shape:
        return None
    n = 1
    for d in shape:
        n *= d
    return n


def build_schedule(events, rank: int, world: Optional[int] = None,
                   uid_watermark: Optional[int] = None) -> List[SchedOp]:
    """Project one rank's ordered :class:`SchedOp` schedule out of a
    recorded event stream.

    Works on both front-end shapes: a per-rank re-trace's stream (every
    event belongs to ``rank``'s program; p2p roles filter by the
    resolved routing pairs) and a single SPMD trace's stream (the same
    projection, applied once per member rank).

    ``uid_watermark`` is the comm-uid counter value captured before the
    per-rank re-traces began (analysis/crossrank.py): comms created
    BEFORE it are shared Python objects whose uid is identical in every
    rank's trace, so the uid itself is the cross-rank identity; comms
    created DURING a trace get fresh uids per re-trace and are aligned
    by creation order instead (uids are monotonic, so the j-th
    watermark-exceeding uid in one trace corresponds to the j-th in
    another).  Without a watermark every uid is treated as stable.
    """
    sched: List[SchedOp] = []
    pre: set = set()
    traced: List[int] = []
    for uid in sorted({e.comm_uid for e in events}):
        if uid_watermark is not None and uid >= uid_watermark:
            traced.append(uid)
        else:
            pre.add(uid)
    comm_keys: Dict[int, Tuple] = {uid: ("u", uid) for uid in pre}
    comm_keys.update({uid: ("t", j) for j, uid in enumerate(traced)})
    seq_counters: Dict[Tuple, int] = {}
    span_seq: Dict[int, Tuple[Tuple, int]] = {}
    # wildcard-source adoption: a recv recorded with pairs=None (the
    # reference-compatible ``recv(source=None)`` that adopts the queued
    # send's routing) pairs FIFO with the preceding send on its
    # (comm, tag) channel in the SAME stream — mirroring the region
    # queue the per-rank re-trace bypassed.  Only a recv with no
    # preceding send stays a true wildcard.
    chan_sends: Dict[Tuple, List] = {}

    def key_of(uid: int) -> Tuple:
        return comm_keys[uid]

    def participants_of(e) -> Optional[Tuple[int, ...]]:
        if e.groups is not None:
            for g in e.groups:
                if rank in g:
                    return tuple(g)
            return ()  # member of no group: not a participant
        if world is not None and e.comm_size == world:
            return tuple(range(world))
        return None  # unknown membership (sub-comm without groups info)

    # async p2p (ops/_async.py send_start/recv_start/p2p_wait): the send
    # half is buffered at issue, exactly like the blocking send; the recv
    # half ADOPTS its routing at issue position (FIFO, same channel rule
    # as above) but BLOCKS — and is therefore matched/priced — at its
    # p2p_wait position.  span -> (comm_key, tag, pairs) carries the
    # adoption from the start to the wait.
    p2p_spans: Dict[int, Optional[Tuple]] = {}

    for e in events:
        ck = key_of(e.comm_uid)
        base = dict(rank=rank, pos=len(sched), op=e.op, comm_uid=e.comm_uid,
                    comm_key=ck, dtype=e.dtype, nelems=_nelems(e.shape),
                    payload_bytes=e.payload_bytes, eager=e.eager,
                    event_index=e.index, meta=dict(e.extra))
        if e.op in ("send_start", "recv_start", "p2p_wait"):
            if e.op == "send_start":
                pairs = e.pairs
                if not e.eager:
                    chan_sends.setdefault((ck, e.tag), []).append(pairs)
                if e.span is not None:
                    p2p_spans[e.span] = None  # send side: wait is local
                if pairs:
                    for s, d in pairs:
                        if s == rank:
                            sched.append(SchedOp(kind="send", src=rank,
                                                 dst=d, tag=e.tag,
                                                 span=e.span, **base))
                            base = dict(base, pos=len(sched))
            elif e.op == "recv_start":
                pairs = e.pairs
                if not e.eager:
                    queued = chan_sends.get((ck, e.tag))
                    adopted = queued.pop(0) if queued else None
                    if pairs is None:
                        pairs = adopted
                if e.span is not None:
                    p2p_spans[e.span] = (ck, e.tag, pairs)
                # nothing blocks here: the transfer retires at the wait
            else:  # p2p_wait
                if e.span is None or e.span not in p2p_spans:
                    continue  # unpaired wait: MPX112's domain
                linked = p2p_spans.pop(e.span)
                if linked is None:
                    continue  # send-side wait never blocks on a peer
                ck2, tag, pairs = linked
                base["comm_key"] = ck2
                if pairs is None:
                    sched.append(SchedOp(kind="recv", src=None, dst=rank,
                                         tag=tag, span=e.span, **base))
                    continue
                for s, d in pairs:
                    if d == rank:
                        sched.append(SchedOp(kind="recv", src=s, dst=rank,
                                             tag=tag, span=e.span, **base))
                        base = dict(base, pos=len(sched))
            continue
        if e.op in P2P_OPS:
            pairs = e.pairs
            if e.op == "send" and not e.eager:
                chan_sends.setdefault((ck, e.tag), []).append(pairs)
            if e.op in ("send", "sendrecv") and pairs:
                for s, d in pairs:
                    if s == rank:
                        sched.append(SchedOp(kind="send", src=rank, dst=d,
                                             tag=e.tag, **base))
                        base = dict(base, pos=len(sched))
            if e.op == "recv" and not e.eager:
                queued = chan_sends.get((ck, e.tag))
                adopted = queued.pop(0) if queued else None
                if pairs is None:
                    pairs = adopted
            if e.op == "recv" and pairs is None:
                # true wildcard: source unresolved AND no preceding send
                # on the channel — matches any issued send to this
                # rank/tag at match time
                sched.append(SchedOp(kind="recv", src=None, dst=rank,
                                     tag=e.tag, **base))
                continue
            if e.op in ("recv", "sendrecv") and pairs:
                for s, d in pairs:
                    if d == rank:
                        sched.append(SchedOp(kind="recv", src=s, dst=rank,
                                             tag=e.tag, **base))
                        base = dict(base, pos=len(sched))
            continue

        parts = participants_of(e)
        if parts == ():
            continue
        fused = None
        if e.fused_members is not None:
            fused = (e.fused_members, e.fused_bytes, e.fused_layout)
        if e.op.endswith("_start"):
            seq = seq_counters.get(ck, 0)
            seq_counters[ck] = seq + 1
            if e.span is not None:
                span_seq[e.span] = (ck, seq)
            kind = "start"
        elif e.op.endswith("_wait"):
            linked = span_seq.get(e.span) if e.span is not None else None
            if linked is None:
                continue  # unpaired wait: MPX112's domain, not matchable
            ck, seq = linked
            base["comm_key"] = ck
            kind = "wait"
        else:
            seq = seq_counters.get(ck, 0)
            seq_counters[ck] = seq + 1
            kind = "coll"
        sched.append(SchedOp(kind=kind, seq=seq, participants=parts,
                             root=e.root, reduction=e.reduction,
                             span=e.span, fused=fused, hier=e.hier,
                             algo=e.algo, hosts=e.hosts,
                             codec=getattr(e, "codec", None), **base))
    return sched
