"""Flow-sensitive dataflow taint pass: rank-local and approximate lineage.

The walker (analysis/walker.py) proves *structural* properties of the
closed jaxpr; the checkers (analysis/checkers.py + hazards.py) prove
*schedule* properties of the recorded event stream.  This pass follows
the *values*: a forward def-use taint propagation over the closed jaxpr,
tracking two lineages that the schedule passes cannot see —

``rank``-local lineage (MPX141, ERROR)
    Values that differ across ranks: outputs of ``axis_index`` (the
    ``Get_rank`` lowering), and any value whose aval carries a nonempty
    collective-varying type (the duck-typed ``vma`` set that shard_map's
    type system threads through the jaxpr — error-feedback residuals,
    per-shard gradients, anything not yet replicated).  Replicating
    collectives (``psum``/``pmin``/``pmax``/``all_gather``) launder the
    taint — their result is rank-invariant by construction; permuting
    and scattering collectives (``ppermute``, ``all_to_all``,
    ``psum_scatter``, ``reduce_scatter``) do not.  The sink is a
    ``lax.cond``/``switch`` predicate whose branches issue *different*
    collective schedules: if the predicate ever differs across ranks the
    schedule itself diverges — the hang class the cross-rank re-trace
    (analysis/crossrank.py) only catches after producing the divergent
    schedules, caught here statically from one trace.

``approx``imate lineage (MPX142, ADVISORY)
    Values that passed through a lossy wire-codec roundtrip — a
    float-to-smaller-float ``convert_element_type`` (the bf16/fp8
    quantize half of ops/_compress.py's ``roundtrip``).  Seeding is
    armed only when the recorded dispatch graph shows codec or
    error-feedback activity (:func:`graph_arms_approx`), so plain mixed
    precision never taints.  Approximate taint survives every op —
    including reductions — and the sinks are positions that assume
    exact arithmetic: indices of ``gather``/``dynamic_slice``/
    ``dynamic_update_slice``/``scatter*`` (routing tables, MoE capacity
    bookkeeping, shard-store commit offsets) and branch predicates that
    gate communication.  Quantization error can flip those decisions
    differently per rank.

Every finding carries the taint frontier — the op-by-op path from the
lineage seed to the sink — in ``Finding.frontier``, rendered as
``taint:`` lines by the report.

Duck typing keeps this module importable (and unit-testable with fake
jaxpr objects, tests/test_hazards_pure.py) under any JAX version, like
the walker it extends.
"""

from __future__ import annotations

import re
from typing import Dict, List, Optional, Tuple

import numpy as np

from .report import Finding
from .walker import _iter_jaxprs, _sub_jaxprs, is_collective

# taint kinds
RANK = "rank"      # differs across ranks until a replicating collective
APPROX = "approx"  # passed through a lossy wire-codec downcast

# collectives whose RESULT is replicated across the reducing axis — they
# launder rank-local taint.  psum_scatter starts with "psum" but leaves a
# different shard on every rank, so it must NOT launder (checked
# explicitly before the prefix match).
REPLICATING_PREFIXES = ("psum", "pmin", "pmax", "all_gather")
_NON_REPLICATING = ("psum_scatter",)

# primitives whose index operands are exactness-required sinks (MPX142):
# name -> slice of eqn.invars holding the indices
_INDEX_SINKS = {
    "gather": slice(1, 2),
    "dynamic_slice": slice(1, None),
    "dynamic_update_slice": slice(2, None),
}

# frontier trails are capped: long programs keep the seed end and the
# live end, with one elision marker in the middle
_TRAIL_CAP = 24
_ELLIPSIS = "... (taint path elided) ..."

Taint = Dict[str, Tuple[str, ...]]  # kind -> frontier trail


def replicates(primitive_name: str) -> bool:
    """True for collectives whose output is rank-invariant (they clear
    rank-local taint)."""
    if primitive_name.startswith(_NON_REPLICATING):
        return False
    return primitive_name.startswith(REPLICATING_PREFIXES)


def collective_signature(jaxpr) -> Tuple[Tuple[str, int], ...]:
    """The multiset of collective primitive names in ``jaxpr`` (all
    nesting levels), as a sorted (name, count) tuple — two branches with
    equal signatures issue the same schedule shape even when a
    rank-varying predicate picks between them."""
    counts: Dict[str, int] = {}

    def _walk(j):
        for eqn in j.eqns:
            name = eqn.primitive.name
            if is_collective(name):
                counts[name] = counts.get(name, 0) + 1
            for sub in _sub_jaxprs(eqn):
                _walk(sub)

    _walk(jaxpr)
    return tuple(sorted(counts.items()))


def _fmt_sig(sig) -> str:
    if not sig:
        return "{no collectives}"
    return "{" + ", ".join(f"{n}x{c}" for n, c in sig) + "}"


def graph_arms_approx(graph) -> bool:
    """True when the recorded dispatch graph shows lossy-codec activity —
    a DCN wire codec on any event (ops/_hierarchy.annotate_selection),
    an error-feedback step (ops/_compress.ef_allreduce stamps the ``ef``
    extra), or a non-``off`` wire-codec knob in the config snapshot —
    which arms the approximate-lineage seeds.  Without it, a float
    downcast is ordinary mixed precision and must not taint."""
    if graph is None:
        return False
    meta = getattr(graph, "meta", None) or {}
    if meta.get("compress") not in (None, "off"):
        return True
    for e in getattr(graph, "events", ()):
        if getattr(e, "codec", None):
            return True
        extra = getattr(e, "extra", None)
        if extra and extra.get("ef"):
            return True
    return False


def _is_lit(atom) -> bool:
    return hasattr(atom, "val")


_FLOAT_NAME = re.compile(r"(?:bfloat|float)(\d+)")


def _float_bytes(d) -> Optional[int]:
    """Itemsize when ``d`` is a float dtype, else None.  The narrow
    float families (bfloat16, float8_*) are matched by NAME: ml_dtypes
    registers them with numpy under kind 'V', and without ml_dtypes the
    name may not parse as a dtype at all."""
    if d is None:
        return None  # np.dtype(None) would silently mean float64
    try:
        dt = np.dtype(d)
    except TypeError:
        m = _FLOAT_NAME.match(str(d))
        return int(m.group(1)) // 8 if m else None
    if dt.kind == "f" or _FLOAT_NAME.match(dt.name):
        return dt.itemsize
    return None


def _is_lossy_downcast(eqn) -> bool:
    """float -> smaller-float convert_element_type: the quantize half of
    a codec roundtrip (ops/_compress.roundtrip)."""
    if not eqn.invars:
        return False
    old = _float_bytes(
        getattr(getattr(eqn.invars[0], "aval", None), "dtype", None))
    new = _float_bytes(eqn.params.get("new_dtype"))
    return old is not None and new is not None and new < old


def _merge(taints) -> Taint:
    """Union taint dicts; on collision the shorter (closer-to-seed)
    frontier wins."""
    out: Taint = {}
    for t in taints:
        for kind, trail in t.items():
            if kind not in out or len(trail) < len(out[kind]):
                out[kind] = trail
    return out


def _extend(trail: Tuple[str, ...], step: str) -> Tuple[str, ...]:
    if len(trail) >= _TRAIL_CAP:
        keep = _TRAIL_CAP // 3
        if _ELLIPSIS not in trail:
            trail = trail[:keep] + (_ELLIPSIS,) + trail[-keep:]
        else:
            trail = trail[:keep + 1] + trail[-(keep - 1):]
    return trail + (step,)


class _Pass:
    """One forward propagation over one (closed) jaxpr tree."""

    def __init__(self, approx_armed: bool, rank: Optional[int] = None):
        self.approx_armed = approx_armed
        self.rank = rank
        self.findings: List[Finding] = []
        self._seen = set()

    # -- taint environment ------------------------------------------------

    def _taint_of(self, atom, env) -> Taint:
        if _is_lit(atom):
            return {}
        t = dict(env.get(atom, ()))
        if RANK not in t:
            # shard_map's collective-varying type system already proved
            # this value differs across ranks — adopt its verdict as an
            # implicit seed (duck-typed: absent on older JAX and fakes)
            vma = getattr(getattr(atom, "aval", None), "vma", None)
            if vma:
                axes = ",".join(sorted(str(a) for a in vma))
                t[RANK] = (f"rank-varying typed value (vma={{{axes}}})",)
        return t

    # -- findings ---------------------------------------------------------

    def _emit(self, code, op, message, suggestion, frontier):
        key = (code, op, message)
        if key in self._seen:
            return
        self._seen.add(key)
        self.findings.append(Finding(
            code=code, op=op, message=message, suggestion=suggestion,
            rank=self.rank, frontier=tuple(frontier),
        ))

    def _check_sinks(self, eqn, name, env):
        if name == "cond":
            pred = self._taint_of(eqn.invars[0], env)
            branch_jaxprs = [next(_iter_jaxprs(b), None)
                             for b in eqn.params.get("branches", ())]
            sigs = [collective_signature(bj) if bj is not None else ()
                    for bj in branch_jaxprs]
            if RANK in pred and len(set(sigs)) > 1:
                rendered = ", ".join(
                    f"branch {i}: {_fmt_sig(s)}" for i, s in enumerate(sigs))
                self._emit(
                    "MPX141", "cond",
                    "rank-local lineage reaches a branch predicate whose "
                    f"branches issue different collective schedules "
                    f"({rendered}) — if the predicate differs across "
                    "ranks the schedule itself diverges and the "
                    "communicating side hangs",
                    "replicate the gating value first (allreduce it), or "
                    "make every branch issue the same collectives "
                    "(docs/sharp_bits.md)",
                    _extend(pred[RANK], "cond predicate (schedule gate)"),
                )
            if APPROX in pred and any(sigs):
                self._emit(
                    "MPX142", "cond",
                    "approximate (wire-codec) lineage reaches a branch "
                    "predicate that gates communication — quantization "
                    "error can flip the decision differently per rank",
                    "derive the gating value from exact (pre-codec) "
                    "data, or carry the error through error feedback "
                    "(docs/compression.md)",
                    _extend(pred[APPROX], "cond predicate (schedule gate)"),
                )
            return
        sink = _INDEX_SINKS.get(name)
        if sink is None and name.startswith("scatter"):
            sink = slice(1, 2)
        if sink is not None:
            for atom in eqn.invars[sink]:
                t = self._taint_of(atom, env)
                if APPROX in t:
                    self._emit(
                        "MPX142", name,
                        "approximate (wire-codec) lineage reaches an "
                        f"index operand of `{name}` — a routing/offset "
                        "decision that assumes exact arithmetic; "
                        "quantization error can route or commit "
                        "differently per rank",
                        "compute routing indices and commit offsets from "
                        "exact values (docs/compression.md)",
                        _extend(t[APPROX], f"{name} index operand"),
                    )
                    break

    # -- propagation ------------------------------------------------------

    def run(self, jaxpr, env) -> dict:
        """Propagate taint through ``jaxpr`` starting from ``env``
        (var -> Taint); returns the final environment so callers can read
        outvar taint."""
        for eqn in jaxpr.eqns:
            name = eqn.primitive.name
            self._check_sinks(eqn, name, env)
            in_taints = [self._taint_of(a, env) for a in eqn.invars]
            out = _merge(in_taints)
            if out:
                out = {k: _extend(tr, name) for k, tr in out.items()}
            # seeds
            if name.startswith("axis_index"):
                out[RANK] = ("axis_index (rank-local seed)",)
            if (self.approx_armed and name == "convert_element_type"
                    and _is_lossy_downcast(eqn)):
                out.setdefault(
                    APPROX,
                    (f"convert_element_type -> "
                     f"{eqn.params.get('new_dtype')} (lossy codec "
                     "downcast, approx seed)",))
            # replicating collectives launder rank-locality (their result
            # is the same on every rank); approximate error survives the
            # reduction, so APPROX stays
            if replicates(name):
                out.pop(RANK, None)
            # descend into sub-jaxprs, mapping taint through binders
            if name == "cond":
                out = _merge([out, self._run_cond(eqn, in_taints)])
            else:
                subs = list(_sub_jaxprs(eqn))
                if subs:
                    out = _merge(
                        [out, self._run_subs(eqn, subs, in_taints)])
            if out:
                for ov in eqn.outvars:
                    if not _is_lit(ov):
                        env[ov] = out
        return env

    def _run_cond(self, eqn, in_taints) -> Taint:
        """Branch operands are eqn.invars[1:], positional against each
        branch's invars; outvar taint merges across branches."""
        ops = in_taints[1:]
        union = _merge(ops)
        out: Taint = {}
        for b in eqn.params.get("branches", ()):
            for bj in _iter_jaxprs(b):
                child = {}
                if len(bj.invars) == len(ops):
                    child = {v: t for v, t in zip(bj.invars, ops) if t}
                elif union:
                    child = {v: dict(union) for v in bj.invars}
                sub_env = self.run(bj, child)
                outs = [({} if _is_lit(ov) else sub_env.get(ov, {}))
                        for ov in bj.outvars]
                out = _merge([out] + outs)
        return out

    def _run_subs(self, eqn, subs, in_taints) -> Taint:
        """Generic descent (pjit, shard_map, scan, while, custom_*):
        positional binder mapping when arities line up, conservative
        union-taint otherwise.  A loop-carried jaxpr (scan: num_carry /
        num_consts params) runs a second round with carry-output taint
        fed back into the carry binders, so lineage that only becomes
        tainted on iteration N+1 is still seen."""
        union = _merge(in_taints)
        n_carry = eqn.params.get("num_carry")
        n_consts = eqn.params.get("num_consts")
        loop_carried = (isinstance(n_carry, int) and n_carry > 0
                        and isinstance(n_consts, int))
        out: Taint = {}
        fed_back: Dict[int, Taint] = {}  # invar position -> carry taint
        for _ in range(2 if loop_carried else 1):
            out = {}
            new_feedback: Dict[int, Taint] = {}
            for sj in subs:
                child = {}
                if len(sj.invars) == len(in_taints):
                    child = {v: t
                             for v, t in zip(sj.invars, in_taints) if t}
                elif union:
                    child = {v: dict(union) for v in sj.invars}
                for pos, t in fed_back.items():
                    if pos < len(sj.invars) and t:
                        v = sj.invars[pos]
                        child[v] = _merge([child.get(v, {}), t])
                sub_env = self.run(sj, child)
                sub_outs = [({} if _is_lit(ov) else sub_env.get(ov, {}))
                            for ov in sj.outvars]
                out = _merge([out] + sub_outs)
                if loop_carried and len(sj.outvars) >= n_carry:
                    # scan body outvars = carry + ys; carry i re-enters
                    # at invar position num_consts + i next iteration
                    for i in range(n_carry):
                        if sub_outs[i]:
                            pos = n_consts + i
                            new_feedback[pos] = _merge(
                                [new_feedback.get(pos, {}), sub_outs[i]])
            if not new_feedback:
                break
            fed_back = new_feedback
        return out


def hazard_jaxpr_findings(closed_jaxpr, *, approx_armed: bool = False,
                          rank: Optional[int] = None) -> List[Finding]:
    """MPX141/MPX142 findings for a traced program's closed jaxpr.

    ``approx_armed`` gates the lossy-downcast seeds — pass
    ``graph_arms_approx(graph)`` for the recording that accompanied the
    trace.  ``rank`` stamps findings produced from a per-rank re-trace.
    """
    p = _Pass(approx_armed, rank=rank)
    j = next(_iter_jaxprs(closed_jaxpr), closed_jaxpr)
    p.run(j, {})
    return p.findings
