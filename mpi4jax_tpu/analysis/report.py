"""Findings, reports, and the MPX error-code catalog.

Every rule docs/sharp_bits.md states in prose carries a stable ``MPX1xx``
code here, so a diagnostic can be grepped, suppressed in a code review, or
cross-referenced from the docs the way compiler warnings are.  Codes are
append-only: a released code never changes meaning.

This module is dependency-free (no jax, no package siblings) so the raise
sites that tag their exceptions (ops, rankspec, validation) can import it
from anywhere without cycles, and the pure-Python test half
(tests/test_analysis_pure.py) can load it under any JAX version.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Optional, Tuple


ERROR = "error"
ADVISORY = "advisory"


@dataclass(frozen=True)
class CodeInfo:
    """Catalog entry for one diagnostic code."""

    code: str
    title: str
    severity: str
    doc: str


# The checker catalog (docs/analysis.md mirrors this table; the docs-sync
# lint in tests/test_lint.py asserts every code below appears there).
CODES = {
    c.code: c
    for c in (
        CodeInfo(
            "MPX101", "unmatched send", ERROR,
            "A send was never matched by a recv on the same (comm, tag) "
            "before its parallel region (or flush/exit, for eager sends) "
            "ended.  Matching is FIFO per (comm, tag); the reference "
            "implementation would deadlock at run time.",
        ),
        CodeInfo(
            "MPX102", "recv without matching send", ERROR,
            "A recv found no queued send on its (comm, tag).  Under SPMD "
            "the matching send must appear earlier in the same region "
            "(FIFO per channel); the reference would block forever.",
        ),
        CodeInfo(
            "MPX103", "bare-int routing", ERROR,
            "A point-to-point routing spec was a bare int rank.  One SPMD "
            "program describes all ranks at once, so 'dest=1' would mean "
            "every rank sends to rank 1 — not a permutation.",
        ),
        CodeInfo(
            "MPX104", "traced structural argument", ERROR,
            "A root, tag, or routing spec was a JAX tracer.  Structure "
            "must be static Python values: one traced program serves all "
            "ranks, so structural choices cannot depend on traced data.",
        ),
        CodeInfo(
            "MPX105", "root out of range", ERROR,
            "A static root index does not exist on the communicator (on a "
            "color split it must be a valid group position in EVERY "
            "group).",
        ),
        CodeInfo(
            "MPX106", "send/recv type-signature mismatch", ERROR,
            "The two sides of a sendrecv (or a matched send/recv pair) "
            "disagree in dtype or element count.  MPI's type-signature "
            "rule; under SPMD a count mismatch cannot be routed at all.",
        ),
        CodeInfo(
            "MPX107", "dropped or forked token", ERROR,
            "A collective's output token is never consumed while a later "
            "collective on the same comm threads an older token.  The "
            "ordering the dropped token was meant to pin is silently "
            "lost (and differs between token and notoken modes).",
        ),
        CodeInfo(
            "MPX108", "collective under one branch of cond", ERROR,
            "A lax.cond has collectives in some branches but not others. "
            "If the predicate ever varies across ranks (notoken mode has "
            "no token ordering to save you), participating ranks hang in "
            "the collective while the others skip it.",
        ),
        CodeInfo(
            "MPX109", "payload near algorithm crossover", ADVISORY,
            "Under MPI4JAX_TPU_COLLECTIVE_ALGO=auto this payload lands "
            "within 2x of MPI4JAX_TPU_RING_CROSSOVER_BYTES, so shape-"
            "polymorphic retraces may flip between the butterfly and ring "
            "lowerings nondeterministically (different perf, same math).",
        ),
        CodeInfo(
            "MPX110", "ambiguous FIFO match", ADVISORY,
            "A recv matched while two or more sends were pending on its "
            "(comm, tag).  FIFO picks the oldest; if the sends are not "
            "interchangeable, use distinct tags or a Clone()d comm.",
        ),
        CodeInfo(
            "MPX111", "adjacent fusable collectives not fused", ADVISORY,
            "With MPI4JAX_TPU_FUSION=off, two or more adjacent "
            "collectives share (op, comm, reduction, root) and each fits "
            "the fusion bucket cap: enabling MPI4JAX_TPU_FUSION=auto "
            "would coalesce them into one flat-buffer collective and cut "
            "per-call dispatch + per-collective latency "
            "(docs/overlap.md).",
        ),
        CodeInfo(
            "MPX112", "unpaired async start/wait", ERROR,
            "An async collective's *_start has no matching *_wait on the "
            "token chain (its phases would be dead-code-eliminated "
            "silently — with the watchdog armed, fatally), or a *_wait "
            "ran without a live start (double wait).  Each start pairs "
            "with exactly one wait on the same handle.",
        ),
        CodeInfo(
            "MPX113", "flat algorithm on a multi-host comm", ADVISORY,
            "A comm spanning multiple hosts ran a flat (single-level) "
            "ring or butterfly at a payload above the ring crossover: "
            "every round is then gated on the slowest DCN hop.  The "
            "two-level hierarchical lowering (intra-host over ICI, "
            "inter-host over DCN) was expressible here — let auto pick "
            "it, or force MPI4JAX_TPU_COLLECTIVE_ALGO=hier "
            "(docs/topology.md).",
        ),
        # --- cross-rank schedule codes (analysis/matcher.py + progress.py):
        # whole-program properties over the per-rank schedules the
        # ranks= re-trace (or a hand-built schedule set) provides.
        CodeInfo(
            "MPX120", "cross-rank collective order mismatch", ERROR,
            "Member ranks of one communicator issue different "
            "collectives at the same schedule position, or are mutually "
            "blocked in collectives on different communicators (an "
            "interleave cycle).  Each side waits in a collective its "
            "peers never enter — a hang at run time (ISP/MUST-style "
            "schedule matching makes this decidable statically).",
        ),
        CodeInfo(
            "MPX121", "send/recv deadlock cycle", ERROR,
            "A cycle of ranks each blocked in a point-to-point receive "
            "whose matching send is issued only after the next rank in "
            "the cycle unblocks.  The cycle is rendered rank-by-rank; "
            "it deadlocks under ANY buffering (sends are modeled "
            "buffered, matching this library's deferred pairing), so "
            "the reference runtime hangs too.",
        ),
        CodeInfo(
            "MPX122", "collective/p2p interleave deadlock", ERROR,
            "A dependency cycle mixing collectives and point-to-point: "
            "some ranks wait in a collective while its other members "
            "are blocked in receives (or vice versa).  No schedule "
            "order exists in which every rank progresses.",
        ),
        CodeInfo(
            "MPX123", "orphaned rank", ERROR,
            "A rank is a member of a communicator group but never "
            "issues the collective its peers are matched in: the peers "
            "block in the collective forever.  Classic cause: a "
            "rank-divergent branch that skips a collective on some "
            "ranks only.",
        ),
        CodeInfo(
            "MPX124", "rank-divergent fusion bucketing", ERROR,
            "Member ranks of one fused collective would pack different "
            "flat buffers (member count, packed bytes, or dtype layout "
            "differ): the flat-buffer exchange would ship mismatched "
            "payloads.  Fusion deferral must see the same op sequence "
            "on every rank.",
        ),
        CodeInfo(
            "MPX125", "hierarchical decomposition mismatch", ERROR,
            "A rank's two-level ICI/DCN plan (ops/_hierarchy.py) "
            "disagrees with its peers' for the same collective under "
            "the declared Topology: intra-host and inter-host phases "
            "would pair different groups.  All members must derive the "
            "same (hosts, ranks-per-host) decomposition.",
        ),
        CodeInfo(
            "MPX127", "collective on a drained communicator", ERROR,
            "A collective was issued on a communicator whose world "
            "executed a planned drain past its leave boundary "
            "(resilience/elastic.py graceful drain): the departed ranks "
            "committed their state and exited on purpose, but this "
            "comm's group tables still include them, so the collective "
            "would block on peers that will never arrive.  Collectives "
            "are legal on a draining comm THROUGH the boundary; after "
            "it, use the rebuilt comm mpx.elastic.run provides (or "
            "comm.shrink the drained ranks out by hand).",
        ),
        CodeInfo(
            "MPX126", "collective on a revoked communication epoch", ERROR,
            "A collective was issued on a communicator stamped with an "
            "epoch older than the current one: the world shrank "
            "(resilience/elastic.py revoked the epoch) but this comm "
            "was never rebuilt, so its group tables, mesh binding, and "
            "rank numbering describe the OLD world — dead ranks "
            "included.  Re-enter through mpx.elastic.run (which rebuilds "
            "the comm on recovery) or call comm.shrink(failed, "
            "mesh=...) and re-issue on the result.",
        ),
        # --- AOT pinning codes (aot/pinning.py + aot/invalidation.py):
        CodeInfo(
            "MPX128", "hot loop not pinned", ADVISORY,
            "One trace dispatches the same (op, comm, statics) "
            "collective signature many times — a Python-level hot loop "
            "unrolled into the program, each dispatch paying the full "
            "Python fast path at trace time and the program growing "
            "linearly with the trip count.  mpx.compile would pin the "
            "program to one executable whose call path does no per-call "
            "key work (docs/aot.md).",
        ),
        CodeInfo(
            "MPX129", "stale pinned program", ERROR,
            "A pinned program (mpx.compile) was called after the world "
            "it was compiled for was revoked: a configuration flag or "
            "set_* override changed the config stamp, or the elastic "
            "communication epoch advanced (shrink, grow, drain).  A "
            "pinned executable does no per-call key work and cannot "
            "retrace itself — re-pin (program.repin(), or a fresh "
            "mpx.compile; mpx.elastic.run re-pins step functions "
            "automatically).",
        ),
        # --- static cost-model advisories (analysis/cost.py, the
        # performance critic over the critical-path timing simulation):
        # each is QUANTIFIED by the alpha-beta-gamma model
        # (analysis/costmodel.py) — predicted microseconds and bytes, not
        # heuristics — and only fires under mpx.analyze(..., cost=True) /
        # MPI4JAX_TPU_ANALYZE_COST=on.
        CodeInfo(
            "MPX131", "overlap opportunity", ADVISORY,
            "A blocking collective's result is consumed late enough "
            "that the surrounding independent compute could hide a "
            "substantial fraction of its predicted wire time: the "
            "async split (*_start/*_wait, docs/overlap.md) would "
            "overlap the phases.  The finding quantifies the hideable "
            "microseconds from the cost model's critical-path "
            "simulation.",
        ),
        CodeInfo(
            "MPX132", "fusion opportunity (quantified)", ADVISORY,
            "Adjacent fusable collectives whose coalescing the cost "
            "model prices: one flat-buffer collective replaces N "
            "per-collective alpha rounds, with the predicted savings "
            "stated in bytes and microseconds — the quantified upgrade "
            "of the MPX111 heuristic (set MPI4JAX_TPU_FUSION=auto, "
            "docs/overlap.md).",
        ),
        CodeInfo(
            "MPX133", "algorithm mispick", ADVISORY,
            "The cost model predicts a different ring/butterfly/hier "
            "lowering than resolve_algo chose for this payload, group "
            "size, and host topology, by more than the mispick "
            "threshold; the finding states the predicted delta.  "
            "Usually a crossover flag "
            "(MPI4JAX_TPU_RING_CROSSOVER_BYTES / _DCN_CROSSOVER_BYTES) "
            "sitting far from the measured value — recalibrate with "
            "benchmarks/micro.py --cost-calibrate.",
        ),
        CodeInfo(
            "MPX134", "structural load imbalance", ADVISORY,
            "Member ranks of one matched collective carry different "
            "payload bytes, so the widest rank is a straggler BY "
            "CONSTRUCTION — every other member waits out the predicted "
            "delta each step.  Pad or re-shard the payload so matched "
            "members ship equal bytes.",
        ),
        CodeInfo(
            "MPX135", "serialized point-to-point chain", ADVISORY,
            "An unpipelined send/recv ladder occupies the predicted "
            "critical path: each hop waits for the previous stage's "
            "full compute + transfer, so the chain's stages run "
            "serially.  Split the batch into microbatches (GPipe-style) "
            "so stage i+1's transfer overlaps stage i's compute — see "
            "examples/pipeline_parallel.py.",
        ),
        CodeInfo(
            "MPX136", "batch dimension outside the serving bucket set",
            ADVISORY,
            "A serving bucket table is declared "
            "(mpx.serving.declare_buckets — the serving engine scopes "
            "one around its serving loop) but a traced collective's "
            "leading (batch) "
            "dimension is not one of the declared buckets: every "
            "distinct request batch shape traces, compiles, and pins a "
            "SEPARATE program, so serving pays an unpinned retrace per "
            "request count instead of one program per (bucket, phase).  "
            "Pad the live batch up to its covering bucket "
            "(BucketTable.bucket_for / pad) before dispatch "
            "(docs/serving.md).",
        ),
        CodeInfo(
            "MPX137", "flat alltoall on a multi-host comm", ADVISORY,
            "A comm spanning multiple hosts ran a flat (single-level) "
            "alltoall at a payload above "
            "MPI4JAX_TPU_ALLTOALL_CROSSOVER_BYTES while the two-level "
            "hierarchical lowering was expressible: every rank "
            "addresses every remote rank directly, paying r times the "
            "DCN message count of the hierarchical split (intra-host "
            "transpose over ICI, inter-host exchange of host-aggregated "
            "contiguous blocks over DCN — ops/_hierarchy.py).  The "
            "MPX113 analog for the permutation family; let auto pick "
            "the hierarchy, or force MPI4JAX_TPU_COLLECTIVE_ALGO=hier "
            "(docs/moe.md).",
        ),
        CodeInfo(
            "MPX138", "uncompressed DCN leg above the crossover", ADVISORY,
            "A hierarchical collective on a multi-host comm ships a "
            "float32 inter-host (DCN) leg above "
            "MPI4JAX_TPU_DCN_CROSSOVER_BYTES uncompressed while the "
            "wire codec layer is off: MPI4JAX_TPU_COMPRESS=bf16 halves "
            "the DCN wire bytes (fp8 quarters them, with per-chunk "
            "scales) at the cost of bit-identity — the error-feedback "
            "accumulator (mpx.compress.ef_allreduce) carries the "
            "rounding residual across steps, and the convergence "
            "harness (BENCH_compress.json) is the parity contract.  "
            "Opt-in and off by default; let mpx.autotune() sweep the "
            "codecs against the error budget (docs/compression.md).",
        ),
        CodeInfo(
            "MPX130", "async span straddles a megastep loop boundary", ERROR,
            "An async *_start/*_wait span crosses a megastep loop "
            "boundary (mpx.compile/mpx.spmd unroll=N, "
            "parallel/megastep.py): the loop body traces once, so a "
            "start whose wait is not in the same iteration leaves every "
            "iteration's collective phases un-awaited at run time — "
            "instrumentation armed with nothing to disarm it, phases "
            "dead-code-eliminated out of the carry.  Keep each span "
            "inside one iteration (overlap is per-iteration in a "
            "megastep), or drop unroll= for this program.",
        ),
        # --- dataflow hazard codes (analysis/dataflow.py + hazards.py):
        # value-level safety over the closed jaxpr joined with the
        # recorded dispatch graph — races, donation, and lineage taint,
        # not schedule structure.
        CodeInfo(
            "MPX139", "buffer mutated while an async span holds it", ERROR,
            "A buffer was donated (or rebound in place) while an open "
            "async *_start/*_wait span still holds it: the span's "
            "exchange phases read the buffer after the start, so a "
            "donation or in-place update between start and wait is a "
            "write-after-start race — the wire may ship the OVERWRITTEN "
            "bytes.  This includes spans crossing mpx.overlap() region "
            "boundaries and fusion LazyResults aliasing bucket members.  "
            "Wait on the handle (or leave the overlap region) before "
            "donating or rebinding the buffer.",
        ),
        CodeInfo(
            "MPX140", "value consumed after donation", ERROR,
            "A value was consumed by a later collective after the pinned "
            "call (mpx.compile donate_argnums) that donated its buffer, "
            "within one trace: the donated buffer's storage is handed to "
            "the executable, so the later read sees freed or aliased "
            "memory.  Drop the stale reference and use the pinned "
            "program's OUTPUT, or remove the argument from "
            "donate_argnums (docs/aot.md).",
        ),
        CodeInfo(
            "MPX141", "rank-local lineage shapes the collective schedule",
            ERROR,
            "A rank-local (non-replicated) value — a Get_rank-derived "
            "scalar, an error-feedback residual, any lineage that "
            "differs per rank — flows into a predicate that gates "
            "collectives (lax.cond/switch with communicating branches "
            "that differ): the schedule itself then diverges across "
            "ranks, the hang class the cross-rank pass only catches "
            "after re-tracing every rank.  Replicate the value first "
            "(allreduce it) or make the branch structure rank-invariant "
            "(docs/sharp_bits.md).",
        ),
        CodeInfo(
            "MPX142", "approximate lineage reaches an exactness-required "
            "sink", ADVISORY,
            "A value carrying approximate (wire-codec) lineage — it "
            "passed through a quantize/dequantize roundtrip (bf16/fp8, "
            "ops/_compress.py) — reaches a sink that assumes exact "
            "arithmetic: a collective root or routing index, an MoE "
            "capacity count, a branch predicate, or shard-store commit "
            "bytes.  Quantization error can flip the decision "
            "differently per rank or corrupt committed state; the "
            "finding renders the taint frontier op by op.  Derive the "
            "decision from exact values, or carry the error through "
            "error feedback (docs/compression.md).",
        ),
        # --- health-plane codes (telemetry/health.py):
        CodeInfo(
            "MPX143", "flight ring smaller than one iteration's "
            "collectives", ADVISORY,
            "The health plane's flight recorder (MPI4JAX_TPU_HEALTH=on) "
            "keeps the most recent MPI4JAX_TPU_FLIGHT_RING records, but "
            "one iteration of this program's loop dispatches more "
            "collectives than the ring holds: by the time a hang is "
            "detected, the ring has already overwritten the iteration's "
            "own history, so a postmortem bundle cannot show where the "
            "ranks diverged.  Raise MPI4JAX_TPU_FLIGHT_RING above the "
            "per-iteration collective count (with headroom for begin + "
            "end records per op) or the bundles will only answer 'what "
            "ran last', not 'who was stuck where' "
            "(docs/observability.md).",
        ),
        # --- pipeline-schedule codes (analysis/cost.py pipeline pass):
        CodeInfo(
            "MPX144", "pipeline runs a schedule the cost model prices "
            "worse", ADVISORY,
            "A pipeline program (mpx.pipeline) ran with a schedule the "
            "cost model prices measurably worse than an expressible "
            "alternative at this (stages, microbatches, payload) point: "
            "the predicted wall time of the chosen schedule exceeds the "
            "best candidate's by more than the mispick threshold.  Pass "
            "schedule='auto' to let the model pick, or switch to the "
            "named schedule in the finding (docs/pipeline.md).",
        ),
    )
}

# the dataflow-hazard code families, referenced by Report.hazards and the
# ownership accounting in tests/test_analysis_pure.py: the graph half
# (checker-registered in analysis/hazards.py) and the jaxpr half (emitted
# by the analysis/dataflow.py walker, like MPX108).
HAZARD_GRAPH_CODES = ("MPX139", "MPX140")
HAZARD_JAXPR_CODES = ("MPX141", "MPX142")
HAZARD_CODES = HAZARD_GRAPH_CODES + HAZARD_JAXPR_CODES


def mpx_error(exc_type, code: str, message: str):
    """Build an exception tagged with a stable MPX code.

    The code rides along as ``exc.mpx_code`` (so ``mpx.analyze`` can
    convert the raise into a :class:`Finding`) and is appended to the
    message (so plain tracebacks are greppable).  Raise sites use this
    instead of bare ``raise TypeError(...)`` for every rule the checker
    catalog covers.
    """
    assert code in CODES, f"unknown MPX code {code}"
    exc = exc_type(f"{message} [{code}]")
    exc.mpx_code = code
    return exc


@dataclass(frozen=True)
class Finding:
    """One diagnostic: a stable code, a one-line message, a suggested fix.

    ``rank`` and ``seq`` are the cross-rank provenance fields (which
    rank's schedule anchors the finding, and at which per-comm collective
    sequence number) — ``None`` for single-trace findings.

    ``frontier`` is the taint frontier of a dataflow-hazard finding
    (MPX141/MPX142): the op-by-op path from the lineage seed to the
    sink, one human-readable step per entry.  Empty for every other
    finding, and emitted in ``to_json`` only when non-empty, so
    pre-hazard payloads are byte-identical."""

    code: str
    message: str
    suggestion: str = ""
    op: Optional[str] = None
    index: Optional[int] = None
    rank: Optional[int] = None
    seq: Optional[int] = None
    frontier: Tuple[str, ...] = ()

    @property
    def severity(self) -> str:
        return CODES[self.code].severity

    def render(self) -> str:
        where = f" at {self.op}#{self.index}" if self.op is not None else ""
        if self.rank is not None:
            where += f" (rank {self.rank})"
        line = f"{self.code} [{self.severity}]{where}: {self.message}"
        for step in self.frontier:
            line += f"\n    taint: {step}"
        if self.suggestion:
            line += f"\n    fix: {self.suggestion}"
        return line

    def to_json(self) -> Dict:
        """Machine-readable form (one object per finding, with rank/op/
        seq provenance) — the unit of ``Report.to_json``."""
        out = {
            "code": self.code,
            "severity": self.severity,
            "title": CODES[self.code].title,
            "message": self.message,
            "suggestion": self.suggestion,
            "op": self.op,
            "index": self.index,
            "rank": self.rank,
            "seq": self.seq,
        }
        if self.frontier:
            # present only on taint findings: every other payload keeps
            # its pre-hazard key set byte-for-byte
            out["frontier"] = list(self.frontier)
        return out


def finding_from_exception(exc) -> Optional[Finding]:
    """Convert an ``mpx_error``-tagged exception into a Finding (or None
    for untagged exceptions, which should propagate)."""
    code = getattr(exc, "mpx_code", None)
    if code is None:
        return None
    return Finding(code=code, message=str(exc),
                   suggestion=CODES[code].doc.split(".")[0] + ".")


@dataclass(frozen=True)
class Report:
    """Result of one analysis pass: the findings, the event stream they
    were derived from (``events`` entries are
    :class:`~mpi4jax_tpu.analysis.graph.CollectiveEvent`), and the config
    snapshot the checkers saw (``meta``: collective_algo, crossover).

    ``cost`` is the critical-path timing prediction
    (:class:`~mpi4jax_tpu.analysis.cost.CostReport`) when the pass ran
    with ``cost=True`` / ``MPI4JAX_TPU_ANALYZE_COST=on`` — ``None``
    otherwise, keeping the report (and its JSON shape) byte-identical
    to a build without the cost model."""

    findings: Tuple[Finding, ...] = ()
    events: Tuple = ()
    meta: Dict = field(default_factory=dict)
    cost: Optional[object] = None

    @property
    def ok(self) -> bool:
        return not self.findings

    @property
    def errors(self) -> Tuple[Finding, ...]:
        return tuple(f for f in self.findings if f.severity == ERROR)

    @property
    def advisories(self) -> Tuple[Finding, ...]:
        return tuple(f for f in self.findings if f.severity == ADVISORY)

    @property
    def hazards(self) -> Tuple[Finding, ...]:
        """The dataflow-hazard findings (MPX139-MPX142): races, donation
        violations, and lineage taint — the value-level subset of
        ``findings``."""
        return tuple(f for f in self.findings if f.code in HAZARD_CODES)

    def render(self) -> str:
        if not self.findings:
            head = (f"mpx.analyze: clean ({len(self.events)} collective(s) "
                    "analyzed)")
            if self.cost is not None:
                head += "\n" + self.cost.render()
            return head
        head = (f"mpx.analyze: {len(self.errors)} error(s), "
                f"{len(self.advisories)} advisory(ies) over "
                f"{len(self.events)} collective(s)")
        lines = [head] + [f.render() for f in self.findings]
        if self.cost is not None:
            lines.append(self.cost.render())
        return "\n".join(lines)

    def __str__(self) -> str:
        return self.render()

    def to_json(self) -> Dict:
        """CI-consumable payload: counts, the config snapshot, and one
        object per finding with rank/op/seq provenance (printed by
        ``python -m mpi4jax_tpu.analysis --json``)."""
        codes: Dict[str, int] = {}
        for f in self.findings:
            codes[f.code] = codes.get(f.code, 0) + 1
        meta = {}
        for k, v in self.meta.items():
            if isinstance(v, (str, int, float, bool)) or v is None:
                meta[k] = v
            elif isinstance(v, (list, tuple)):
                meta[k] = [x if isinstance(x, (str, int, float, bool))
                           else repr(x) for x in v]
            else:
                meta[k] = repr(v)
        payload = {
            "ok": self.ok,
            "errors": len(self.errors),
            "advisories": len(self.advisories),
            "events": len(self.events),
            "codes": codes,
            "meta": meta,
            "findings": [f.to_json() for f in self.findings],
        }
        if self.cost is not None:
            # only present when the cost pass ran: cost=off payloads stay
            # byte-identical to a build without the cost model
            payload["cost"] = self.cost.to_json()
        return payload

    def raise_if_findings(self) -> None:
        if self.findings:
            raise AnalysisError(self.findings, self.render())


class AnalysisError(RuntimeError):
    """Raised by ``MPI4JAX_TPU_ANALYZE=error`` (and
    ``Report.raise_if_findings``) when any finding fired.  The structured
    findings are available as ``.findings``."""

    def __init__(self, findings, message):
        super().__init__(message)
        self.findings = tuple(findings)
