"""Graph-side dataflow hazard checkers: donation races, use-after-donate.

The second half of the dataflow hazard verifier (the jaxpr half lives in
analysis/dataflow.py).  These checkers join the recorded event stream
with the *donation records* the AOT pinner leaves in
``graph.meta["donations"]`` (aot/pinning.py: one ``(pos, buffer_ids,
where)`` entry per recorded pinned call with ``donate_argnums``, where
``pos`` is the event-stream position the donation happened at) and the
per-event ``buffers`` identity tuples the dispatch hook records
(analysis/hook.py) —

MPX139 (ERROR)
    A donation lands while an async ``*_start``/``*_wait`` span still
    holds one of the donated buffers: the span's exchange phases read
    the buffer *after* the start, so handing its storage to an
    executable between start and wait is a write-after-start race — the
    wire may ship the overwritten bytes.  Spans are tracked by stream
    position, so spans crossing ``mpx.overlap()`` region boundaries and
    fusion flushes (whose events carry the *member* buffer ids, so a
    ``LazyResult`` aliasing a bucket member is covered) are all seen.

MPX140 (ERROR)
    A collective consumes a buffer whose storage an earlier pinned call
    in the same trace already donated: the read sees freed or aliased
    memory.

Buffer identities are ``id()``s of the traced carriers, pinned alive by
the recorder for the recording's lifetime (the token-edge discipline,
graph.py) — checkers use them purely as equality handles and never print
them, so per-rank re-traces dedupe cleanly.

Dependency-free (no jax): hand-built graphs drive both checkers in
tests/test_hazards_pure.py under any JAX version.
"""

from __future__ import annotations

from typing import List, Optional

from .checkers import checker
from .dataflow import graph_arms_approx, hazard_jaxpr_findings
from .graph import CollectiveGraph
from .report import Finding


def hazard_findings(closed_jaxpr, graph=None,
                    rank: Optional[int] = None) -> List[Finding]:
    """The jaxpr half in one call: MPX141/MPX142 over ``closed_jaxpr``,
    with the approximate-lineage seeds armed by the recorded ``graph``'s
    codec/error-feedback activity."""
    return hazard_jaxpr_findings(
        closed_jaxpr, approx_armed=graph_arms_approx(graph), rank=rank)


def _span_intervals(graph: CollectiveGraph) -> dict:
    """span id -> [start_pos, wait_pos or None, held buffer ids, start
    event], by stream position (event stream positions and donation
    ``pos`` values share one clock: ``len(events)`` at record time)."""
    spans: dict = {}
    for pos, e in enumerate(graph.events):
        if e.span is None:
            continue
        if e.op.endswith("_start"):
            held = set(getattr(e, "buffers", ()) or ())
            spans[e.span] = [pos, None, held, e]
        elif e.op.endswith("_wait"):
            rec = spans.get(e.span)
            if rec is not None and rec[1] is None:
                rec[1] = pos
    return spans


@checker("MPX139")
def check_span_donation_race(graph: CollectiveGraph) -> List[Finding]:
    """Donation while an open async span holds the buffer."""
    donations = graph.meta.get("donations", ())
    if not donations:
        return []
    spans = _span_intervals(graph)
    findings: List[Finding] = []
    for dpos, ids, where in donations:
        for span_id, (spos, wpos, held, start) in sorted(spans.items()):
            if spos >= dpos:
                continue  # span opened after the donation landed
            if wpos is not None and wpos < dpos:
                continue  # span already waited — buffer released
            if held & set(ids):
                findings.append(Finding(
                    code="MPX139", op=start.op, index=start.index,
                    message=(f"{where} donates a buffer the open async "
                             f"span {span_id} ({start.where()}) still "
                             "holds: the span's exchange phases read it "
                             "after the start, so the wire may ship the "
                             "overwritten bytes (write-after-start "
                             "race)"),
                    suggestion=("wait on the handle "
                                f"({start.op.replace('_start', '_wait')})"
                                " — or leave the mpx.overlap() region — "
                                "before the donating call"),
                ))
    return findings


@checker("MPX140")
def check_use_after_donate(graph: CollectiveGraph) -> List[Finding]:
    """Collective consuming a buffer a pinned call already donated."""
    donations = graph.meta.get("donations", ())
    if not donations:
        return []
    findings: List[Finding] = []
    for pos, e in enumerate(graph.events):
        bufs = set(getattr(e, "buffers", ()) or ())
        if not bufs:
            continue
        for dpos, ids, where in donations:
            if dpos <= pos and bufs & set(ids):
                findings.append(Finding(
                    code="MPX140", op=e.op, index=e.index,
                    message=(f"{e.where()} consumes a buffer whose "
                             f"storage {where} already donated to its "
                             "executable: the read sees freed or "
                             "aliased memory"),
                    suggestion=("use the pinned program's OUTPUT instead "
                                "of the stale donated reference, or drop "
                                "the argument from donate_argnums "
                                "(docs/aot.md)"),
                ))
                break
    return findings
