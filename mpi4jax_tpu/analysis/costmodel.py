"""The static alpha-beta-gamma communication cost model.

The analyzer (analysis/) reconstructs per-rank schedules, matches every
collective, and simulates progress — but a verdict of *correct* says
nothing about *slow*.  This module supplies the missing half: an
analytical cost model in the classic Hockney/LogP family, per **link
class** —

- ``ici``: intra-host inter-chip links (fast, low latency);
- ``dcn``: the data-center network between hosts (roughly an order of
  magnitude more per-hop latency, several times less bandwidth);

with three parameter groups per prediction:

- **alpha** (``alpha_us``): fixed per-round latency of one neighbor hop
  on the class (ppermute round, DCN RTT share);
- **beta** (``gb_per_s``): sustained per-rank bandwidth of the class;
- **gamma** (``gamma_gb_per_s``): local reduction fold throughput (the
  combine the reduction family pays per byte on top of the wire).

``collective_cost`` maps every one of the 13 ops x its selectable
algorithms (butterfly, ring, van de Geijn, two-level hier) to
``(rounds, bytes)`` per link class, REUSING the pinned byte models the
hierarchical layer ships (``ops/_hierarchy.hier_link_bytes`` /
``flat_link_bytes`` — the same functions the lockstep simulator pins in
tests/test_hierarchy.py), so the cost model can never drift from what
the lowerings actually move.  The round counts mirror the lowerings'
loop structure and are pinned by tests/test_cost_pure.py.

Parameters default to documented analytic values and load measured
numbers from a tuning file (``MPI4JAX_TPU_COST_MODEL=path.json``, schema
``mpx-cost-model/1`` — exactly what ``benchmarks/micro.py
--cost-calibrate`` emits), the bridge to ROADMAP's ``mpx.autotune()``:
the autotuner's output is this file.

Horovod's tensor-fusion heuristics and NCCL's tree/ring selection both
ship analytical models of this shape to drive their choices; here the
model additionally powers a performance critic (MPX131-MPX135,
analysis/cost.py) and the critical-path step-time prediction.

Only stdlib + the config registry at import time (the byte-model reuse
imports ``ops._hierarchy`` lazily), so the isolated-loader test half
(tests/test_cost_pure.py) runs under any JAX version.
"""

from __future__ import annotations

import json
import os
import warnings
from dataclasses import dataclass
from typing import Dict, Optional, Sequence, Tuple

from ..utils import config

ICI = "ici"
DCN = "dcn"
LINK_CLASSES = (ICI, DCN)

SCHEMA = "mpx-cost-model/1"

# the tuning superset (autotune/schema.py): an ``mpx-tuning/1`` file
# carries the same links/gamma/compute/dispatch/measured sections plus
# the config-layer knobs, so EITHER schema feeds this model —
# ``benchmarks/micro.py --cost-calibrate`` now emits the superset and
# ``MPI4JAX_TPU_COST_MODEL`` keeps accepting both (docs/autotune.md)
TUNING_SCHEMA = "mpx-tuning/1"
ACCEPTED_SCHEMAS = (SCHEMA, TUNING_SCHEMA)

# ops whose lowering folds operands locally (the gamma term)
REDUCTION_OPS = ("allreduce", "reduce", "reduce_scatter", "scan")

# the 13 public collectives the formula matrix covers (ops/__init__.py)
MODELED_OPS = (
    "allgather", "allreduce", "alltoall", "barrier", "bcast", "gather",
    "recv", "reduce", "reduce_scatter", "scan", "scatter", "send",
    "sendrecv",
)

# Documented analytic defaults (overridden by the tuning file):
#
# - ici: ~100 GB/s sustained per-rank ICI bandwidth and ~1 us per
#   ppermute round — the order of magnitude of a TPU ICI link
#   (docs/topology.md);
# - dcn: ~12.5 GB/s (a 100 Gb/s NIC) and ~25 us per inter-host round —
#   the "order of magnitude more per-hop latency" the hierarchical
#   layer's crossover rationale already documents (utils/config.py
#   DEFAULT_DCN_CROSSOVER_BYTES);
# - gamma: ~400 GB/s local fold throughput (reduction combine is
#   HBM-streaming-bound, faster than the wire);
# - compute_gb_per_s: the HBM-roofline throughput the per-rank compute
#   estimate divides jaxpr memory traffic by — ~300 GB/s matches the
#   measured shallow-water state traffic (BENCH_r05
#   state_traffic_gb_per_s = 298);
# - dispatch_us: fixed host dispatch per step — BENCH_r05's
#   dispatch_overhead_s over its step count is ~140 us/step (the cost
#   ``mpx.compile`` unroll= amortizes ~1/N, docs/aot.md).
DEFAULT_PARAMS = {
    "links": {
        ICI: {"alpha_us": 1.0, "gb_per_s": 100.0},
        DCN: {"alpha_us": 25.0, "gb_per_s": 12.5},
    },
    "gamma_gb_per_s": 400.0,
    "compute_gb_per_s": 300.0,
    "dispatch_us": 140.0,
}


@dataclass(frozen=True)
class LinkTerm:
    """One link class's share of an op: latency rounds + wire bytes."""

    rounds: int = 0
    nbytes: int = 0

    def __bool__(self) -> bool:
        return bool(self.rounds or self.nbytes)


@dataclass(frozen=True)
class OpCost:
    """Modeled per-rank cost of one collective instance."""

    ici: LinkTerm = LinkTerm()
    dcn: LinkTerm = LinkTerm()
    gamma_bytes: int = 0

    def link(self, name: str) -> LinkTerm:
        return self.ici if name == ICI else self.dcn


ZERO_COST = OpCost()


class CostModel:
    """Parameter set + time arithmetic.  ``source`` records where the
    parameters came from (a tuning-file path, or ``None`` for the
    analytic defaults); ``measured`` carries the calibrated crossovers
    the checker texts cite (MPX111/MPX113)."""

    __slots__ = ("params", "source", "measured", "tuned_stamp")

    def __init__(self, params: Optional[dict] = None,
                 source: Optional[str] = None,
                 measured: Optional[dict] = None,
                 tuned_stamp: Optional[str] = None):
        # provenance of a tuning-layer-sourced model: the mpx-tuning/1
        # content stamp the MPX131-133 advisory texts cite as
        # ``tuned@<stamp>`` (None for files loaded via the cost-model
        # flag or the analytic defaults)
        self.tuned_stamp = tuned_stamp
        base = {
            "links": {
                lc: dict(DEFAULT_PARAMS["links"][lc]) for lc in LINK_CLASSES
            },
        }
        for k in ("gamma_gb_per_s", "compute_gb_per_s", "dispatch_us"):
            base[k] = DEFAULT_PARAMS[k]
        if params:
            for lc, vals in (params.get("links") or {}).items():
                base["links"][lc].update(vals)
            for k in ("gamma_gb_per_s", "compute_gb_per_s", "dispatch_us"):
                if k in params:
                    base[k] = float(params[k])
        self.params = base
        self.source = source
        self.measured = dict(measured or {})

    # -- time arithmetic ---------------------------------------------------
    # 1 GB/s == 1000 bytes/us, so bytes / (gb_per_s * 1000) is microseconds.

    def link_time_us(self, link: str, rounds: int, nbytes: int) -> float:
        p = self.params["links"][link]
        return rounds * p["alpha_us"] + nbytes / (p["gb_per_s"] * 1e3)

    def time_us(self, cost: OpCost) -> float:
        t = self.link_time_us(ICI, cost.ici.rounds, cost.ici.nbytes)
        t += self.link_time_us(DCN, cost.dcn.rounds, cost.dcn.nbytes)
        t += cost.gamma_bytes / (self.params["gamma_gb_per_s"] * 1e3)
        return t

    def compute_us(self, traffic_bytes: int) -> float:
        """Roofline compute time of ``traffic_bytes`` of jaxpr memory
        traffic (analysis/cost.py ``jaxpr_traffic_bytes``)."""
        return traffic_bytes / (self.params["compute_gb_per_s"] * 1e3)

    @property
    def dispatch_us(self) -> float:
        return self.params["dispatch_us"]

    def stamp(self) -> tuple:
        """Hashable identity for memo keys (only folded in when the cost
        pass is ON, so cost=off cache keys stay byte-identical)."""
        links = tuple(
            (lc, self.params["links"][lc]["alpha_us"],
             self.params["links"][lc]["gb_per_s"])
            for lc in LINK_CLASSES
        )
        return (links, self.params["gamma_gb_per_s"],
                self.params["compute_gb_per_s"], self.params["dispatch_us"],
                self.source, self.tuned_stamp)

    def to_json(self) -> dict:
        out = {"schema": SCHEMA, "links": {
            lc: dict(self.params["links"][lc]) for lc in LINK_CLASSES
        }}
        for k in ("gamma_gb_per_s", "compute_gb_per_s", "dispatch_us"):
            out[k] = self.params[k]
        if self.source:
            out["source"] = self.source
        if self.measured:
            out["measured"] = dict(self.measured)
        return out

    def __repr__(self):
        src = self.source or "analytic defaults"
        return f"CostModel({src})"


# ---------------------------------------------------------------------------
# tuning-file loading (the mpx-cost-model/1 schema)
# ---------------------------------------------------------------------------


def validate_model_dict(payload) -> Tuple[dict, dict]:
    """Validate a parsed tuning payload; returns ``(params, measured)``
    or raises ``ValueError`` with a clear message.  The schema is
    exactly what ``benchmarks/micro.py --cost-calibrate`` emits, so a
    calibration capture loads verbatim."""
    if not isinstance(payload, dict):
        raise ValueError(
            "cost-model tuning file must be a JSON object "
            f"(got {type(payload).__name__})"
        )
    if "links" not in payload and isinstance(payload.get("cost_model"),
                                             dict):
        # a full ``benchmarks/micro.py --save`` capture embeds the
        # tuning payload under "cost_model" — accept it whole, so the
        # sweep artifact IS a valid MPI4JAX_TPU_COST_MODEL file
        payload = payload["cost_model"]
    schema = payload.get("schema", SCHEMA)
    if schema not in ACCEPTED_SCHEMAS:
        raise ValueError(
            f"cost-model tuning file declares schema {schema!r}; this "
            f"build reads {ACCEPTED_SCHEMAS}"
        )
    params: dict = {}
    links = payload.get("links")
    if links is not None:
        if not isinstance(links, dict):
            raise ValueError("cost-model 'links' must be an object")
        for lc, vals in links.items():
            if lc not in LINK_CLASSES:
                raise ValueError(
                    f"cost-model link class {lc!r} unknown (expected one "
                    f"of {LINK_CLASSES})"
                )
            if not isinstance(vals, dict):
                raise ValueError(f"cost-model links[{lc!r}] must be an "
                                 "object")
            for key, val in vals.items():
                if key not in ("alpha_us", "gb_per_s"):
                    raise ValueError(
                        f"cost-model links[{lc!r}] key {key!r} unknown "
                        "(expected alpha_us / gb_per_s)"
                    )
                if not isinstance(val, (int, float)) or isinstance(
                        val, bool):
                    raise ValueError(
                        f"cost-model links[{lc!r}].{key} must be a "
                        f"number (got {val!r})"
                    )
                if key == "gb_per_s" and val <= 0:
                    raise ValueError(
                        f"cost-model links[{lc!r}].gb_per_s must be > 0 "
                        f"(got {val!r})"
                    )
                if key == "alpha_us" and val < 0:
                    raise ValueError(
                        f"cost-model links[{lc!r}].alpha_us must be >= 0 "
                        f"(got {val!r})"
                    )
        params["links"] = links
    for k in ("gamma_gb_per_s", "compute_gb_per_s", "dispatch_us"):
        if k in payload:
            val = payload[k]
            if not isinstance(val, (int, float)) or isinstance(val, bool) \
                    or (val <= 0 and k != "dispatch_us") or val < 0:
                raise ValueError(
                    f"cost-model {k} must be a positive number "
                    f"(got {val!r})"
                )
            params[k] = val
    measured = payload.get("measured", {})
    if not isinstance(measured, dict):
        raise ValueError("cost-model 'measured' must be an object")
    for k, v in measured.items():
        if not isinstance(v, (int, float)) or isinstance(v, bool) or v < 0:
            raise ValueError(
                f"cost-model measured[{k!r}] must be a non-negative "
                f"number (got {v!r})"
            )
    return params, measured


def model_from_dict(payload, source: Optional[str] = None) -> CostModel:
    params, measured = validate_model_dict(payload)
    if source is None:
        source = payload.get("source")
    return CostModel(params, source=source, measured=measured)


def model_from_tuning(tf) -> CostModel:
    """A model sourced from the active tuning layer (an
    ``autotune.schema.TuningFile``): same parameter extraction as a
    direct file load, plus the ``tuned@<stamp>`` provenance the
    MPX131-133 texts cite."""
    params, measured = validate_model_dict(tf.payload)
    return CostModel(params, source=tf.path or "<tuning layer>",
                     measured=measured, tuned_stamp=tf.stamp)


def model_from_file(path: str) -> CostModel:
    try:
        with open(path) as f:
            payload = json.load(f)
    except OSError as e:
        raise ValueError(
            f"cost-model tuning file {path!r} could not be read: {e}"
        ) from e
    except json.JSONDecodeError as e:
        raise ValueError(
            f"cost-model tuning file {path!r} is not valid JSON: {e}"
        ) from e
    params, measured = validate_model_dict(payload)
    return CostModel(params, source=path, measured=measured)


# (path, mtime) -> CostModel | ValueError — config_snapshot consults the
# measured crossovers on every recorded trace, which must not re-read
# the file per event stream
_load_memo: Dict[Tuple[str, float], object] = {}


def load_model(spec=None) -> CostModel:
    """Resolve a model: ``None`` reads ``MPI4JAX_TPU_COST_MODEL`` (or
    the analytic defaults when unset), a path string loads the file, a
    dict validates in place, a :class:`CostModel` passes through."""
    if isinstance(spec, CostModel):
        return spec
    if isinstance(spec, dict):
        return model_from_dict(spec)
    path = spec if isinstance(spec, str) and spec else \
        config.cost_model_path()
    if not path:
        # the unification bridge (docs/autotune.md): with no cost-model
        # flag, an active tuning layer that carries the links section
        # feeds the model — one file serves selector and cost model
        tf = config.active_tuning()
        if tf is not None and tf.has_links():
            return model_from_tuning(tf)
        return CostModel()
    try:
        mtime = os.path.getmtime(path)
    except OSError:
        mtime = -1.0
    key = (path, mtime)
    cached = _load_memo.get(key)
    if cached is None:
        if len(_load_memo) > 16:
            _load_memo.clear()
        try:
            cached = model_from_file(path)
        except ValueError as e:
            cached = e
        _load_memo[key] = cached
    if isinstance(cached, ValueError):
        raise cached
    return cached


def measured_meta() -> dict:
    """The config-snapshot fragment the checker texts consume
    (analysis/hook.config_snapshot): the tuning file's measured
    crossovers, keyed ``measured_*``, plus the file path — empty when no
    file is configured.  Never raises (a malformed file warns once and
    falls back to no measured data; ``mpx.analyze(cost=True)`` raises
    the same error loudly)."""
    path = config.cost_model_path()
    if not path:
        # the tuning layer's measured section feeds the same advisory
        # texts, tagged with its content stamp (``tuned@<stamp>``)
        try:
            tf = config.active_tuning()
        except ValueError as e:
            warnings.warn(f"MPI4JAX_TPU_TUNING ignored for advisory "
                          f"texts: {e}", stacklevel=2)
            return {}
        if tf is None:
            return {}
        out = {"cost_model": tf.path or "<tuning layer>",
               "tuned_stamp": tf.stamp}
        for k, v in tf.measured().items():
            out[f"measured_{k}"] = v
        return out
    try:
        model = load_model(path)
    except ValueError as e:
        warnings.warn(f"MPI4JAX_TPU_COST_MODEL ignored for advisory "
                      f"texts: {e}", stacklevel=2)
        return {}
    out = {"cost_model": path}
    for k, v in model.measured.items():
        out[f"measured_{k}"] = v
    return out


# ---------------------------------------------------------------------------
# the formula matrix: (rounds, bytes) per link class for all 13 ops
# ---------------------------------------------------------------------------


def _log2ceil(k: int) -> int:
    return (k - 1).bit_length() if k > 1 else 0


def _byte_models():
    """The pinned byte models from the hierarchical layer (PR 6) — the
    single source of truth for what the reduction-family lowerings move
    per link class.  Imported lazily: ``ops/_hierarchy`` imports jax,
    which the analysis package proper never does."""
    from ..ops import _hierarchy

    return _hierarchy.flat_link_bytes, _hierarchy.hier_link_bytes


def _dcn_algo(shard_bytes: int, h: int, ring_ok: bool = True) -> str:
    """The hierarchical inter-host phase's ring/butterfly pick — the
    SAME rule ``ops/_algos.resolve_dcn_algo`` applies (pinned equal by
    tests/test_cost_pure.py), restated here over the config registry so
    the round counts below never disagree with the byte model."""
    if (ring_ok and h >= 4  # _algos.RING_MIN_GROUP, mirrored literally
            and shard_bytes >= config.dcn_crossover_bytes()):
        return "ring"
    return "butterfly"


def _hier_rounds(kind: str, nbytes: int, h: int, r: int,
                 preserve: bool) -> Tuple[int, int]:
    """(intra, inter) round counts of the two-level lowerings, mirroring
    ops/_hierarchy.py phase for phase."""
    chunk = -(-nbytes // r) if r else nbytes
    lh = _log2ceil(h)
    if kind == "allreduce":
        intra = 2 * (r - 1)  # ring reduce-scatter + ring allgather
        inter = (2 * (h - 1)
                 if _dcn_algo(chunk, h, ring_ok=not preserve) == "ring"
                 else 2 * lh)
        return intra, inter
    if kind == "reduce_scatter":
        intra = r - 1
        inter = (h - 1) if _dcn_algo(chunk, h) == "ring" else 2 * lh
        return intra, inter
    if kind == "bcast":
        intra = _log2ceil(r) + (r - 1)  # halving scatter + ring allgather
        inter = (lh + (h - 1)) if _dcn_algo(chunk, h) == "ring" else lh
        return intra, inter
    raise ValueError(f"unknown hierarchical collective kind {kind!r}")


def _flat_rounds(kind: str, algo: str, k: int) -> int:
    """Round counts of the flat lowerings, mirroring ops/_algos.py and
    ops/_base.py loop structure."""
    rounds = _log2ceil(k)
    if algo == "butterfly":
        if kind == "bcast":
            return rounds  # doubling broadcast
        return 2 * rounds  # fold + doubling broadcast
    if algo == "ring":
        if kind == "bcast":  # van de Geijn: halving scatter + allgather
            return rounds + (k - 1)
        if kind == "reduce_scatter":
            return k - 1
        return 2 * (k - 1)  # allreduce: reduce-scatter + allgather
    return 1  # native HLO: XLA schedules it; one logical round


def _dcn_wire_bytes(nbytes: int, codec: Optional[str]) -> int:
    """Post-codec bytes of a compressed DCN leg (docs/compression.md):
    the codec layer's byte math, reused so the priced wire bytes can
    never drift from what the lowering ships.  Identity for exact legs."""
    if not codec or codec == "off":
        return nbytes
    from ..ops import _codec

    return _codec.wire_bytes(nbytes, codec)


def collective_cost(op: str, algo: Optional[str], nbytes: int, k: int,
                    hosts: Optional[int] = None,
                    hier: Optional[Tuple[int, int]] = None,
                    preserve: bool = False,
                    codec: Optional[str] = None) -> OpCost:
    """Modeled per-rank cost of one collective of ``nbytes`` payload
    over a ``k``-rank group spanning ``hosts`` hosts.

    The reduction family (allreduce / reduce / reduce_scatter / bcast)
    delegates its wire bytes to the pinned PR-6 byte models and mirrors
    their round structure; the remaining ops use the canonical formulas
    documented in docs/analysis.md 'Cost model' (and pinned by
    tests/test_cost_pure.py).  Flat algorithms on a multi-host comm land
    entirely on the DCN class — every round gated on the slowest hop,
    exactly MPX113's serialization — matching ``flat_link_bytes``'s
    attribution.

    ``codec`` prices a wire-compressed DCN leg (docs/compression.md):
    only the hierarchical lowerings compress, only their inter-host
    bytes shrink — round counts, ICI bytes, and the gamma fold are the
    logical payload's.
    """
    if k <= 1 or op in ("send", "recv", "sendrecv"):
        if op in ("send", "recv", "sendrecv"):
            raise ValueError(
                f"{op} is point-to-point: use p2p_cost (the link class "
                "depends on the endpoints, not the group)"
            )
        return ZERO_COST
    multi = hosts is not None and hosts > 1
    rounds = _log2ceil(k)
    gamma = nbytes if op in REDUCTION_OPS else 0
    kind = "allreduce" if op == "reduce" else op

    if kind in ("allreduce", "reduce_scatter", "bcast"):
        flat_link_bytes, hier_link_bytes = _byte_models()
        if algo == "hier" and hier is not None:
            h, r = hier
            intra_b, inter_b = hier_link_bytes(kind, nbytes, h, r, preserve)
            intra_r, inter_r = _hier_rounds(kind, nbytes, h, r, preserve)
            return OpCost(ici=LinkTerm(intra_r, intra_b),
                          dcn=LinkTerm(inter_r,
                                       _dcn_wire_bytes(inter_b, codec)),
                          gamma_bytes=gamma)
        eff = algo if algo in ("butterfly", "ring") else "native"
        intra_b, inter_b = flat_link_bytes(kind, eff, nbytes, k, hosts,
                                           preserve)
        n_rounds = _flat_rounds(kind, eff, k)
        if inter_b:
            return OpCost(dcn=LinkTerm(n_rounds, inter_b),
                          gamma_bytes=gamma)
        return OpCost(ici=LinkTerm(n_rounds, intra_b), gamma_bytes=gamma)

    chunk = -(-nbytes // k)
    if op == "allgather":
        term = LinkTerm(k - 1, (k - 1) * nbytes)  # nbytes = one block
    elif op == "alltoall":
        if algo == "hier" and hier is not None:
            # the two-level split (ops/_hierarchy.apply_hier_alltoall):
            # byte model reused from the pinned PR-6 family so the cost
            # model can never drift from what the lowering moves —
            # intra transpose (r-1 rounds over ICI), inter exchange of
            # host-aggregated blocks (h-1 rounds over DCN, 1/r the flat
            # message count)
            _, hier_link_bytes = _byte_models()
            h, r = hier
            intra_b, inter_b = hier_link_bytes("alltoall", nbytes, h, r)
            return OpCost(ici=LinkTerm(r - 1 if r > 1 else 0, intra_b),
                          dcn=LinkTerm(h - 1,
                                       _dcn_wire_bytes(inter_b, codec)))
        term = LinkTerm(k - 1, (k - 1) * chunk)  # nbytes = full buffer
    elif op == "gather":
        term = LinkTerm(rounds, (k - 1) * nbytes)  # binomial, per-block
    elif op == "scatter":
        term = LinkTerm(rounds, (k - 1) * chunk)  # nbytes = full buffer
    elif op == "scan":
        term = LinkTerm(rounds, rounds * nbytes)  # log-depth prefix
    elif op == "barrier":
        term = LinkTerm(rounds, 0)  # latency only
    else:
        raise ValueError(f"collective_cost: unmodeled op {op!r} "
                         f"(modeled: {MODELED_OPS})")
    if multi:
        return OpCost(dcn=term, gamma_bytes=gamma)
    return OpCost(ici=term, gamma_bytes=gamma)


def p2p_cost(nbytes: int, same_host: bool = True) -> OpCost:
    """One point-to-point transfer: a single round carrying the payload
    on the endpoints' link class."""
    term = LinkTerm(1, nbytes)
    return OpCost(ici=term) if same_host else OpCost(dcn=term)


def chunked_async_cost(cost: OpCost, chunks: int) -> OpCost:
    """Modeled cost of the ``C``-chunk async split of one collective
    (ops/_async.py ``*_start``/``*_wait``): the chunks partition the
    payload, so total wire bytes are unchanged; each active link pays
    ``C - 1`` extra chunk-rounds of pipeline fill (double buffering) on
    top of the base round count.  The alpha overhead is the price of
    the split — the win, which the critical-path simulation (not this
    per-op formula) credits, is that everything past the fill is
    hideable behind independent compute issued in the start→wait gap
    (MPX131 quantifies exactly that)."""
    if chunks <= 1:
        return cost

    def _ext(term: LinkTerm) -> LinkTerm:
        if not term:
            return term
        return LinkTerm(term.rounds + chunks - 1, term.nbytes)

    return OpCost(ici=_ext(cost.ici), dcn=_ext(cost.dcn),
                  gamma_bytes=cost.gamma_bytes)


def best_algo(op: str, nbytes: int, k: int, model: CostModel,
              hosts: Optional[int] = None,
              hier: Optional[Tuple[int, int]] = None,
              candidates: Optional[Sequence[str]] = None,
              preserve: bool = False) -> Tuple[str, Dict[str, float]]:
    """Model-predicted algorithm pick for one reduction-family
    collective: evaluates every expressible candidate and returns
    ``(best, {algo: time_us})`` — the MPX133 discriminator and the
    flat-vs-hier comparator the acceptance sweep checks sign against."""
    if candidates is None:
        if op == "alltoall":
            # the permutation family has exactly two shapes: the flat
            # single-level exchange ("native" — the pairwise rounds
            # price identically) and the two-level hierarchical split
            candidates = ["native"]
            if hier is not None:
                candidates.append("hier")
        else:
            candidates = ["butterfly"]
            if k >= 4 and not preserve:  # RING_MIN_GROUP, mirrored
                candidates.append("ring")
            if hier is not None:
                candidates.append("hier")
    times = {
        a: model.time_us(collective_cost(op, a, nbytes, k, hosts=hosts,
                                         hier=hier, preserve=preserve))
        for a in candidates
    }
    return min(times, key=lambda a: (times[a], a)), times


# ---------------------------------------------------------------------------
# pipeline-parallel schedule formulas (parallel/pipeline.py, docs/pipeline.md)
# ---------------------------------------------------------------------------
#
# Wall-clock models of one pipeline round over S stages x M microbatches,
# with c = per-microbatch per-stage compute (us) and x = one boundary
# transfer of the microbatch activation (us, ``p2p_cost`` through the
# model).  Per-rank useful work is always M*c; everything else is bubble.
#
# - ladder ("naive"): every stage computes the WHOLE batch then forwards
#   it — the un-microbatched send/recv chain MPX135 flags.  Fully serial:
#       wall = S*(M*c) + (S-1)*(M*x)
# - gpipe (Huang et al.): M microbatches through S stages in lockstep
#   ticks; the blocking sendrecv boundary puts the transfer on the
#   critical path of every tick:
#       wall = (M+S-1) * (c + x)
# - 1f1b (PipeDream-flush, Narayanan et al.): same (M+S-1)-tick skeleton,
#   but the boundary goes through send_start/recv_start so the steady-
#   state transfer overlaps the next microbatch's compute.  Only the
#   (S-1) warmup-edge transfers and any per-tick excess of x over c stay
#   exposed:
#       wall = (M+S-1)*c + (S-1)*x + max(0, x-c)*(M-1)
# - interleaved (Megatron virtual stages): v stage-chunks per rank, so
#   P = S*v virtual stages of compute c/v each; the fill shrinks by v
#   while each chunk boundary moves 1/v of the activation bytes (alpha
#   paid v times as often — the classic bubble-vs-latency trade):
#       wall = M*c + (S-1)*(c/v) + (S-1)*x_v + max(0, x_v - c/v)*(M*v-1)
#   with x_v = one transfer of payload_bytes/v.
#
# The orderings the BENCH_pipeline.json acceptance grid pins (x > 0,
# M >= 2, S >= 2): ladder > gpipe (microbatching wins (S-1)*(M-1)*c of
# fill) and gpipe > 1f1b (async overlap hides M*x - max(0,x-c)*(M-1) > 0
# of wire time).  interleaved-vs-1f1b depends on alpha vs c/v — exactly
# why ``schedule='auto'`` asks this model instead of hard-coding.

PIPELINE_SCHEDULES = ("ladder", "gpipe", "1f1b", "interleaved")


def pipeline_wall_us(schedule: str, stages: int, microbatches: int,
                     payload_bytes: int, stage_compute_us: float,
                     model: CostModel, same_host: bool = True,
                     virtual: int = 2) -> float:
    """Modeled wall-clock (us) of one forward round of ``schedule`` over
    ``stages`` x ``microbatches`` with per-boundary activation payloads
    of ``payload_bytes``."""
    if stages < 1 or microbatches < 1:
        raise ValueError("pipeline_wall_us: stages and microbatches "
                         "must be >= 1")
    s, m = stages, microbatches
    c = stage_compute_us
    x = model.time_us(p2p_cost(payload_bytes, same_host=same_host))
    if schedule == "ladder":
        return s * m * c + (s - 1) * m * x
    if schedule == "gpipe":
        return (m + s - 1) * (c + x)
    if schedule == "1f1b":
        return (m + s - 1) * c + (s - 1) * x + max(0.0, x - c) * (m - 1)
    if schedule == "interleaved":
        v = max(1, virtual)
        cv = c / v
        xv = model.time_us(p2p_cost(-(-payload_bytes // v),
                                    same_host=same_host))
        return (m * c + (s - 1) * cv + (s - 1) * xv
                + max(0.0, xv - cv) * (m * v - 1))
    raise ValueError(f"pipeline_wall_us: unknown schedule {schedule!r} "
                     f"(expressible: {PIPELINE_SCHEDULES})")


def pipeline_bubble_fraction(schedule: str, stages: int, microbatches: int,
                             payload_bytes: int, stage_compute_us: float,
                             model: CostModel, same_host: bool = True,
                             virtual: int = 2) -> float:
    """Predicted bubble fraction: the share of the round's wall clock a
    rank spends NOT computing, ``1 - M*c / wall`` (0 = perfectly full)."""
    wall = pipeline_wall_us(schedule, stages, microbatches, payload_bytes,
                            stage_compute_us, model, same_host=same_host,
                            virtual=virtual)
    if wall <= 0.0:
        return 0.0
    busy = microbatches * stage_compute_us
    return max(0.0, 1.0 - busy / wall)


def best_schedule(stages: int, microbatches: int, payload_bytes: int,
                  stage_compute_us: float, model: CostModel,
                  same_host: bool = True, virtual: int = 2,
                  candidates: Optional[Sequence[str]] = None,
                  ) -> Tuple[str, Dict[str, float]]:
    """Model-predicted schedule pick, mirroring :func:`best_algo`:
    evaluates every expressible candidate and returns ``(best, {schedule:
    wall_us})`` — ``mpx.pipeline(schedule='auto')``'s argmin and the
    MPX144 mispick discriminator.  The default candidate set is what the
    PROGRAM at ``virtual`` can express — an alternative that needs
    restructuring is not a candidate: the ladder never (it is the shape
    :func:`pipeline` exists to replace); a flat program (``virtual ==
    1``) prices gpipe vs 1f1b; a program already chunked into ``virtual
    >= 2`` stage-chunks per rank can only run interleaved, because
    gpipe/1f1b apply one stage fn per rank and would need the chunks
    composed back into a single fn.  Pass ``candidates`` explicitly to
    price across program shapes (benchmarks/pipeline_replay.py's
    cross-shape argmin does)."""
    if candidates is None:
        if virtual >= 2:
            candidates = ["interleaved"]
        else:
            candidates = ["gpipe", "1f1b"]
    times = {
        sched: pipeline_wall_us(sched, stages, microbatches, payload_bytes,
                                stage_compute_us, model,
                                same_host=same_host, virtual=virtual)
        for sched in candidates
    }
    return min(times, key=lambda sched: (times[sched], sched)), times
