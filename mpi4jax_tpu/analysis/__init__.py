"""Trace-time SPMD collective verifier (``mpx.analyze``).

The hazards docs/sharp_bits.md used to state only in prose — unmatched
point-to-point, rank-dependent structure, token misuse, algorithm-
crossover surprises — are enforced mechanically here, each with a stable
``MPX1xx`` code, a one-line finding, and a suggested rewrite.  Two ways
in:

- **explicit**: ``mpx.analyze(fn, *args, comm=...) -> Report`` re-traces
  ``fn`` abstractly (no compile, no execution, no devices touched),
  records every collective at the shared dispatch point, walks the closed
  jaxpr, and runs the checker registry;
- **ambient**: ``MPI4JAX_TPU_ANALYZE={off,warn,error}`` verifies every
  spmd region / eager op as it traces — ``error`` turns any finding into
  a trace-time :class:`AnalysisError`, which is how CI keeps
  ``examples/`` clean (``python -m mpi4jax_tpu.analysis script.py``).

The verifier is the mandatory registration layer for future ops: anything
flowing through ``ops/_base.dispatch`` is recorded (op kind, comm, root,
routing, payload, token edges, selected algorithm) and checked — the same
way resilience (PR 1) and the algorithm selector (PR 2) ride the single
dispatch point.
"""

from __future__ import annotations

from typing import Optional

from .checkers import CHECKERS, registered_codes, run_checkers  # noqa: F401
from .graph import CollectiveEvent, CollectiveGraph  # noqa: F401
from .hook import (  # noqa: F401
    Recorder,
    analysis_cache_token,
    clear_analysis_caches,
    effective_mode,
    pop_recorder,
    push_recorder,
    set_analyze_mode,
)
from .report import (  # noqa: F401
    CODES,
    AnalysisError,
    Finding,
    Report,
    finding_from_exception,
)
from .dataflow import graph_arms_approx, hazard_jaxpr_findings  # noqa: F401
from .walker import check_cond_divergence  # noqa: F401


def analyze(fn, *args, comm=None, wrap: Optional[bool] = None,
            static_argnums=None, ranks=None, cost: bool = False,
            cost_model=None) -> Report:
    """Statically verify the collective structure of ``fn(*args)``.

    ``fn`` is re-traced abstractly (nothing executes, nothing compiles):
    ``args`` may be arrays or ``jax.ShapeDtypeStruct`` templates.  Three
    calling conventions:

    - ``fn`` decorated with :func:`mpi4jax_tpu.spmd`: analyzed as-is
      (``args`` are the global arrays you would call it with); the
      analysis re-traces the underlying per-rank function, so compiled-
      program caches cannot hide ops from the verifier;
    - a plain per-rank function: wrapped in ``spmd`` over ``comm`` (or
      the default comm) first, like ``mpx.run`` would;
    - ``wrap=False``: traced exactly as given (for eager-style functions
      that take global arrays and call ops outside any region).

    ``ranks`` enables **cross-rank schedule verification** (the
    whole-program deadlock/progress pass, docs/analysis.md): ``'all'``
    re-traces the function once per rank of the comm — concretizing
    ``comm.Get_rank`` so rank-dependent Python/``lax.cond`` branches
    take their real paths — extracts each rank's ordered op schedule,
    matches collectives by (comm, seq) / point-to-point by (src, dst,
    tag) FIFO / start-wait by span across ranks, and checks the matched
    program for progress (MPX120–MPX125).  An int ``n`` analyzes ranks
    ``0..n-1``; an iterable names them explicitly.  Requires a
    region-style function (``wrap=False`` has no per-rank program to
    concretize).

    ``cost=True`` additionally extends the progress simulation into the
    **critical-path timing simulation** (analysis/cost.py): the report
    gains ``Report.cost`` — predicted step time, per-op / per-link-class
    latency+byte breakdown, the critical path rank by rank, predicted
    megastep/fusion amortization — and the quantified performance
    advisories MPX131-MPX135 join ``Report.findings``.  Parameters come
    from the alpha-beta-gamma model's documented analytic defaults, a
    ``MPI4JAX_TPU_COST_MODEL`` tuning file, or an explicit ``cost_model``
    (a path, a parsed dict, or a
    :class:`~mpi4jax_tpu.analysis.costmodel.CostModel`).  ``cost``
    implies ``ranks='all'`` when ``ranks`` is not given (the timing runs
    over the matched cross-rank schedules); with ``cost=False`` the
    report, the memo keys, and the lowered HLO stay byte-identical to a
    build without the cost model (docs/analysis.md 'Cost model').

    Returns a :class:`Report`; ``report.raise_if_findings()`` converts it
    into the same :class:`AnalysisError` the
    ``MPI4JAX_TPU_ANALYZE=error`` dispatch mode raises.  Results are
    memoized per (fn, arg shapes, ranks, algo config);
    ``mpx.clear_caches()`` drops the memo.
    """
    import jax

    from ..ops._algos import algo_cache_token
    from ..parallel.region import resolve_comm, spmd

    ranks_implied = cost and ranks is None
    if ranks_implied:
        ranks = "all"
    if wrap is None:
        wrap = not getattr(fn, "_mpx_spmd", False)
    if ranks is not None and not wrap and not getattr(fn, "_mpx_spmd", False):
        what = ("analyze(cost=True) implies ranks='all' (the timing "
                "runs over the matched cross-rank schedules) and"
                if ranks_implied else "analyze(ranks=...)")
        raise ValueError(
            f"{what} needs a region-style function (plain "
            "per-rank or spmd-decorated): an eager-style wrap=False "
            "function has no per-rank program to re-trace"
        )

    region_comm = comm
    if not wrap and getattr(fn, "_mpx_spmd", False):
        # rebuild the un-jitted twin of the spmd wrapper: jit's trace cache
        # would otherwise serve a cached jaxpr and record nothing
        kw = fn._mpx_spmd_kwargs
        region_comm = comm if comm is not None else kw["comm"]
        target = spmd(
            fn._mpx_fn,
            comm=region_comm,
            in_specs=kw["in_specs"],
            out_specs=kw["out_specs"],
            static_argnums=kw["static_argnums"],
            # the megastep loop is part of the verified structure: the
            # twin must trace it so MPX130 can see span straddles
            unroll=kw.get("unroll"),
            jit=False,
        )
        if static_argnums is None:
            static_argnums = kw["static_argnums"]
    elif wrap:
        target = spmd(fn, comm=comm, jit=False)
    else:
        target = fn

    statics = _normalize_statics(static_argnums, len(args))
    from .hook import _analyze_cache

    rank_list = None
    if ranks is not None:
        from . import crossrank

        c = resolve_comm(region_comm)
        if c.mesh is None:
            raise RuntimeError(
                "analyze(ranks=...) needs a comm bound to a mesh (the "
                "rank set and axis sizes come from it)"
            )
        axis_sizes = [c.mesh.shape[a] for a in c.axes]
        world = 1
        for s in axis_sizes:
            world *= s
        rank_list = crossrank.resolve_rank_list(ranks, world)

    model = None
    if cost:
        from . import cost as _cost

        model = _cost.resolve_model(cost_model)

    key = _cache_key(jax, fn, comm, args, statics, wrap, algo_cache_token(),
                     rank_list)
    if key is not None and cost:
        # appended ONLY when the cost pass runs: cost=False keys stay
        # byte-identical to a build without the cost model
        key = key + ("cost", model.stamp())
    if key is not None and key in _analyze_cache:
        return _analyze_cache[key]

    if rank_list is not None:
        report = _analyze_cross_rank(jax, target, args, statics, c,
                                     axis_sizes, world, rank_list,
                                     cost_model=model)
        if key is not None:
            _analyze_cache[key] = report
        return report

    rec = Recorder("collect")
    push_recorder(rec)
    fatal = None
    closed = None
    try:
        closed = jax.make_jaxpr(target, static_argnums=statics)(*args)
    except Exception as e:  # only MPX-tagged raises become findings
        fatal = finding_from_exception(e)
        if fatal is None:
            raise
    finally:
        pop_recorder()

    graph = rec.graph()
    findings = run_checkers(graph)
    if fatal is not None:
        # the aborted trace is ONE defect: the graph checkers may have
        # replayed the same hazard from the events recorded before the
        # raise — keep only the fatal finding for its code
        findings = [f for f in findings if f.code != fatal.code]
        findings.insert(0, fatal)
    if closed is not None:
        findings.extend(check_cond_divergence(closed))
        # the dataflow taint pass (MPX141/MPX142): value-level lineage
        # over the same closed jaxpr, approx seeds armed by the recorded
        # graph's codec/EF activity
        findings.extend(hazard_jaxpr_findings(
            closed, approx_armed=graph_arms_approx(graph)))
    report = Report(findings=tuple(findings), events=tuple(rec.events),
                    meta=dict(graph.meta))
    if key is not None:
        _analyze_cache[key] = report
    return report


def _analyze_cross_rank(jax, target, args, statics, c, axis_sizes, world,
                        rank_list, cost_model=None) -> Report:
    """The ranks= path: per-rank re-traces -> per-rank graph checkers ->
    global matcher -> progress checker -> (optionally) the critical-path
    cost pass."""
    from . import crossrank
    from .hook import config_snapshot

    watermark = crossrank.uid_watermark()
    per_rank, fatal, closed = crossrank.trace_rank_schedules(
        target, args, {}, statics, c.axes, axis_sizes, rank_list)
    findings = list(fatal)
    # an aborted rank trace is ONE defect per code: the graph checkers
    # may replay the same hazard from the events recorded before the
    # raise (the single-trace path applies the same filter)
    fatal_codes = {f.code for f in fatal}
    findings.extend(f for f in crossrank.per_rank_graph_findings(per_rank)
                    if f.code not in fatal_codes)
    seen_cond = set()
    for r in sorted(closed):
        for f in check_cond_divergence(closed[r]):
            if f.message in seen_cond:
                continue
            seen_cond.add(f.message)
            findings.append(f)
    # the dataflow taint pass over each rank's re-trace, deduplicated by
    # message; MPX141 findings cite the would-diverge rank pair
    findings.extend(crossrank.per_rank_hazard_findings(closed, per_rank))
    cost_report = None
    if not fatal:
        matched = crossrank.match_rank_schedules(per_rank, world, watermark)
        findings.extend(
            crossrank.cross_rank_findings(per_rank, world, matched=matched))
        if cost_model is not None:
            from . import cost as _cost

            cost_report, cost_findings = _cost.run_cost_pass(
                matched, model=cost_model,
                host_of_rank=_cost.host_map_for(c), closed=closed,
                meta=config_snapshot())
            findings.extend(cost_findings)
    events = per_rank.get(rank_list[0], ())
    return Report(findings=tuple(findings), events=tuple(events),
                  meta=dict(config_snapshot(), ranks=list(rank_list)),
                  cost=cost_report)


def _normalize_statics(static_argnums, nargs) -> tuple:
    if static_argnums is None:
        return ()
    if isinstance(static_argnums, int):
        static_argnums = (static_argnums,)
    return tuple(sorted(i if i >= 0 else i + nargs for i in static_argnums))


def _cache_key(jax, fn, comm, args, statics, wrap, algo_token, rank_list=None):
    dyn = tuple(a for i, a in enumerate(args) if i not in statics)
    stat_vals = tuple(args[i] for i in statics)
    leaves, treedef = jax.tree.flatten(dyn)
    avals = tuple(
        (tuple(leaf.shape), str(leaf.dtype))
        if hasattr(leaf, "shape") and hasattr(leaf, "dtype")
        else repr(leaf)
        for leaf in leaves
    )
    key = (fn, comm, stat_vals, treedef, avals, wrap, algo_token, rank_list)
    try:
        hash(key)
    except TypeError:
        return None  # unhashable statics/fn: analyze uncached
    return key
