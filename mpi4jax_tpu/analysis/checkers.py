"""The checker registry: pure functions over a :class:`CollectiveGraph`.

Each checker declares the ``MPX1xx`` codes it can emit (every code in
``report.CODES`` must be owned by exactly one checker or one tagged raise
site — tests/test_analysis_pure.py asserts the registry covers the
catalog).  Checkers are pure: graph in, findings out, no jax — so every
future op or algorithm that records richer events gets verified without
touching this module, and the whole registry runs under any JAX version.

Two kinds of rules live in the verifier:

- **trace-aborting rules** (MPX101-106): already hard errors at their
  raise sites (ops, rankspec, validation), now tagged with their code via
  ``report.mpx_error`` so ``mpx.analyze`` converts the raise into a
  Finding.  The graph checkers below re-implement them structurally so
  hand-built graphs (and future front-ends that build graphs without
  tracing) get the same verdicts;
- **stream rules** (MPX107, MPX109, MPX110): only expressible over the
  whole op stream — they never raise at dispatch and are the reason the
  env mode (``MPI4JAX_TPU_ANALYZE=warn|error``) exists.
"""

from __future__ import annotations

from typing import Callable, List

from .graph import CollectiveGraph
from .report import CODES, Finding

# ops whose lowering consults the payload-aware selector (ops/_algos.py);
# scan is deliberately absent — its prefix lowering has no ring form
ALGO_OPS = ("allreduce", "reduce", "bcast", "reduce_scatter")

# selector constants mirrored from ops/_algos.py (kept literal here so the
# checkers stay importable without jax; test_analysis_pure pins equality)
RING_MIN_GROUP = 4

# ops the fusion deferral layer accepts, mirrored from ops/_fusion.py
# FUSABLE_OPS (same no-jax-import rationale; equality pinned by
# tests/test_analysis_pure.py)
FUSABLE_OPS = ("allreduce", "bcast")

# enum reduction names (ops/_base.Op values, mirrored literally): only
# enum reductions defer — a callable records its __name__ here and can
# never fuse, so advising MPI4JAX_TPU_FUSION=auto for it would be wrong
ENUM_REDUCTIONS = ("sum", "prod", "min", "max", "land", "lor", "lxor",
                   "band", "bor", "bxor")

CHECKERS: List[tuple] = []  # (codes, fn)


def checker(*codes: str) -> Callable:
    for c in codes:
        assert c in CODES, f"unknown MPX code {c}"

    def register(fn):
        CHECKERS.append((codes, fn))
        return fn

    return register


def run_checkers(graph: CollectiveGraph, skip=()) -> List[Finding]:
    """Run the registry over ``graph``.  ``skip`` names codes whose
    checkers must not run (the cross-rank front-end skips the per-trace
    p2p FIFO replay: a single rank's schedule legitimately holds one
    side of an exchange — see analysis/crossrank.py)."""
    findings: List[Finding] = []
    for codes, fn in CHECKERS:
        if skip and any(c in skip for c in codes):
            continue
        findings.extend(fn(graph))
    findings.sort(key=lambda f: (f.index if f.index is not None else -1,
                                 f.code))
    return findings


def registered_codes() -> set:
    return {c for codes, _ in CHECKERS for c in codes}


# ---------------------------------------------------------------------------
# point-to-point matching (MPX101 / MPX102 / MPX106 / MPX110)
# ---------------------------------------------------------------------------


@checker("MPX101", "MPX102", "MPX106", "MPX110")
def check_p2p_matching(graph: CollectiveGraph) -> List[Finding]:
    """Replay FIFO matching per (comm, tag) channel over the event stream."""
    findings: List[Finding] = []
    for (comm_uid, tag), events in sorted(graph.by_channel().items(),
                                          key=lambda kv: str(kv[0])):
        # eager p2p uses deferred pairing (the send never enters dispatch,
        # so the stream sees only the recv) — its matching is validated by
        # the eager queues themselves, not replayed here
        events = [e for e in events if not e.eager]
        pending: List = []  # unmatched send events, FIFO
        for e in events:
            if e.op == "send":
                pending.append(e)
                continue
            # recv
            if not pending:
                findings.append(Finding(
                    code="MPX102", op=e.op, index=e.index,
                    message=(f"recv(tag={tag}) on comm {comm_uid} has no "
                             "matching send queued (matching is FIFO per "
                             "(comm, tag) within one region)"),
                    suggestion=("issue the matching send earlier in the "
                                "same parallel region, or check the comm/"
                                "tag pair"),
                ))
                continue
            if len(pending) >= 2 and "queue_depth" not in e.extra:
                e.extra["queue_depth"] = len(pending)
            s = pending.pop(0)
            if (s.dtype and e.dtype and s.dtype != e.dtype) or (
                    s.shape and e.shape and
                    _nelems(s.shape) != _nelems(e.shape)):
                findings.append(Finding(
                    code="MPX106", op=e.op, index=e.index,
                    message=(f"recv template {e.shape}/{e.dtype} does not "
                             f"match the send at {s.where()} "
                             f"({s.shape}/{s.dtype}): MPI type-signature "
                             "rule (shapes may differ only at equal "
                             "element count)"),
                    suggestion="make both sides agree in dtype and element "
                               "count",
                ))
        for s in pending:
            findings.append(Finding(
                code="MPX101", op=s.op, index=s.index,
                message=(f"send(tag={tag}) on comm {comm_uid} is never "
                         "matched by a recv before the region ends "
                         "(matching is FIFO per (comm, tag); the reference "
                         "would deadlock at MPI_Finalize)"),
                suggestion=("add the matching recv on the same comm and "
                            "tag, or drop the send"),
            ))
    # ambiguity advisories (depth annotated by the live recv, or replayed
    # above for hand-built graphs)
    for e in graph.events:
        depth = e.extra.get("queue_depth", 0)
        if e.op == "recv" and depth >= 2:
            findings.append(Finding(
                code="MPX110", op=e.op, index=e.index,
                message=(f"recv(tag={e.tag}) matched while {depth} sends "
                         "were pending on this (comm, tag); FIFO picked "
                         "the oldest"),
                suggestion=("use distinct tags (or a Clone()d comm) if the "
                            "pending sends are not interchangeable"),
            ))
    return findings


def _nelems(shape) -> int:
    n = 1
    for d in shape:
        n *= d
    return n


# ---------------------------------------------------------------------------
# structural statics (MPX103 / MPX104 / MPX105)
# ---------------------------------------------------------------------------


@checker("MPX103", "MPX104")
def check_static_structure(graph: CollectiveGraph) -> List[Finding]:
    """Events flagged non-static at dispatch (the live raise sites tag the
    same hazards; this covers graphs built without tracing)."""
    findings: List[Finding] = []
    for e in graph.events:
        if e.extra.get("bare_int_routing"):
            findings.append(Finding(
                code="MPX103", op=e.op, index=e.index,
                message=(f"{e.op} routing was a bare int rank; under SPMD "
                         "routing describes all ranks at once"),
                suggestion="use pairs=[(src, dst)], shift(k), or a "
                           "{src: dst} dict",
            ))
        if e.extra.get("traced_structure"):
            findings.append(Finding(
                code="MPX104", op=e.op, index=e.index,
                message=(f"{e.op} structural argument "
                         f"({e.extra['traced_structure']}) was a JAX "
                         "tracer; roots/tags/routing must be static"),
                suggestion="pass a Python int (mark it static through jit "
                           "with static_argnums)",
            ))
    return findings


@checker("MPX105")
def check_root_range(graph: CollectiveGraph) -> List[Finding]:
    findings: List[Finding] = []
    for e in graph.events:
        if e.root is None or e.min_size is None:
            continue
        if not 0 <= e.root < e.min_size:
            kind = "smallest group" if e.split else "comm"
            findings.append(Finding(
                code="MPX105", op=e.op, index=e.index,
                message=(f"{e.op} root {e.root} out of range for the "
                         f"{kind} (size {e.min_size})"),
                suggestion=f"use a root in [0, {e.min_size})",
            ))
    return findings


# ---------------------------------------------------------------------------
# token discipline (MPX107)
# ---------------------------------------------------------------------------


@checker("MPX107")
def check_token_chains(graph: CollectiveGraph) -> List[Finding]:
    """Dropped/forked tokens: op ``e`` produced a token that nothing ever
    consumes, while a LATER op on the same comm threads a token that was
    already in circulation before ``e`` — the classic fork::

        t = create_token()
        a, t1 = allreduce(x, token=t)
        b, t2 = allreduce(y, token=t)   # forked from t; t1 dropped

    The final token of a chain is legitimately unconsumed; it only becomes
    a finding when an older token is used after it.
    """
    findings: List[Finding] = []
    for comm_uid, events in sorted(graph.by_comm().items()):
        chain = [e for e in events
                 if e.token_in is not None or e.token_out is not None]
        consumed = {e.token_in for e in chain if e.token_in is not None}
        first_seen: dict = {}
        for pos, e in enumerate(chain):
            for t in (e.token_in, e.token_out):
                if t is not None and t not in first_seen:
                    first_seen[t] = pos
        for pos, e in enumerate(chain):
            if e.token_out is None or e.token_out in consumed:
                continue
            if e.token_out == e.token_in:  # notoken passthrough
                continue
            stale = next(
                (f for f in chain[pos + 1:]
                 if f.token_in is not None
                 and first_seen.get(f.token_in, len(chain)) <= pos),
                None,
            )
            if stale is not None:
                findings.append(Finding(
                    code="MPX107", op=e.op, index=e.index,
                    message=(f"the token produced by {e.where()} is never "
                             f"consumed, but {stale.where()} on the same "
                             "comm threads an older token — the chain was "
                             "forked and this op's ordering dropped"),
                    suggestion=(f"thread {e.where()}'s output token into "
                               f"{stale.where()} (each op consumes the "
                               "previous op's token)"),
                ))
    return findings


# ---------------------------------------------------------------------------
# fusion opportunity (MPX111) + async pairing (MPX112)
# ---------------------------------------------------------------------------


@checker("MPX111")
def check_unfused_adjacent(graph: CollectiveGraph) -> List[Finding]:
    """Adjacent fusable collectives that would bucket, with fusion off:
    a run of >= 2 consecutive events sharing (op, comm, reduction, root),
    each within the bucket byte cap — exactly what
    ``MPI4JAX_TPU_FUSION=auto`` coalesces into one flat-buffer collective
    (the packing is dtype-segregated, so mixed dtypes still bucket).

    Gated on the config snapshot EXPLICITLY recording ``fusion: off``
    (every real trace does, via ``hook.config_snapshot``): hand-built
    graphs without fusion meta are testing other rules.

    When a cost-model tuning file is loaded
    (``MPI4JAX_TPU_COST_MODEL``), the MEASURED fusion bucket takes the
    place of the static env default — both as the bucketing cap and in
    the advisory text (which then cites the calibration source instead
    of the flag)."""
    if graph.meta.get("fusion") != "off":
        return []
    measured = graph.meta.get("measured_fusion_bucket_bytes")
    cap = measured or graph.meta.get("fusion_bucket_bytes", 0)
    cap_cite = (
        f"the measured {measured} B bucket ({_calibration_cite(graph.meta)})"
        if measured else "the fusion bucket cap"
    )
    findings: List[Finding] = []
    run: List = []

    def _key(e):
        return (e.op, e.comm_uid, e.reduction, e.root)

    def _close(run):
        if len(run) >= 2:
            first = run[0]
            total = sum(e.payload_bytes for e in run)
            findings.append(Finding(
                code="MPX111", op=first.op, index=first.index,
                message=(f"{len(run)} adjacent {first.op} collectives on "
                         f"comm {first.comm_uid} "
                         f"(events {first.index}..{run[-1].index}, "
                         f"{total} B total) each fit {cap_cite} and "
                         "would coalesce into one flat-buffer "
                         "collective, but MPI4JAX_TPU_FUSION is off"),
                suggestion=("set MPI4JAX_TPU_FUSION=auto (or call "
                            "mpx.set_fusion_mode('auto')) and consume "
                            "results after issuing the whole batch — see "
                            "docs/overlap.md"),
            ))

    for e in graph.events:
        fusable = (e.op in FUSABLE_OPS and not e.eager
                   and (e.reduction is None or e.reduction in ENUM_REDUCTIONS)
                   and (not cap or e.payload_bytes <= cap))
        if fusable and run and _key(run[-1]) == _key(e):
            run.append(e)
            continue
        _close(run)
        run = [e] if fusable else []
    _close(run)
    return findings


@checker("MPX112")
def check_start_wait(graph: CollectiveGraph) -> List[Finding]:
    """Async pairing: every ``*_start`` needs exactly one later ``*_wait``
    on the same span handle, and every wait needs a live start.  An
    unwaited start's phases are silently dead-code-eliminated (and leave
    the collective watchdog armed); a wait without a live start is a
    double wait."""
    findings: List[Finding] = []
    open_starts: dict = {}  # span id -> start event
    for e in graph.events:
        if e.span is None:
            continue
        if e.op.endswith("_start"):
            open_starts[e.span] = e
        elif e.op.endswith("_wait"):
            if open_starts.pop(e.span, None) is None:
                findings.append(Finding(
                    code="MPX112", op=e.op, index=e.index,
                    message=(f"{e.where()} has no live matching "
                             f"{e.op.replace('_wait', '_start')} on this "
                             "token chain (wait before start, or a "
                             "second wait on the same handle)"),
                    suggestion=("pair each *_start handle with exactly "
                                "one *_wait, in program order"),
                ))
    for span, e in sorted(open_starts.items()):
        findings.append(Finding(
            code="MPX112", op=e.op, index=e.index,
            message=(f"{e.where()} is never waited: its communication "
                     "phases have no consumer and will be dead-code-"
                     "eliminated (with the watchdog armed at start, the "
                     "missing disarm is fatal at run time)"),
            suggestion=(f"call {e.op.replace('_start', '_wait')} on the "
                        "returned handle (mpx.overlap() pairs "
                        "automatically at region exit)"),
        ))
    return findings


# ---------------------------------------------------------------------------
# revoked-epoch collectives (MPX126)
# ---------------------------------------------------------------------------


@checker("MPX126")
def check_epoch_boundary(graph: CollectiveGraph) -> List[Finding]:
    """A collective issued on a comm whose communication epoch is behind
    the current one (``graph.meta["epoch"]``): the world shrank after the
    comm was built — its mesh binding and group tables still describe the
    pre-failure world, dead ranks included.  Recovery through
    ``mpx.elastic.run`` (or an explicit ``comm.shrink``) produces
    current-epoch comms and never fires this; holding a pre-shrink comm
    across the boundary does."""
    current = graph.meta.get("epoch")
    if not current:  # epoch 0 (or no elastic layer): nothing is revoked
        return []
    findings: List[Finding] = []
    for e in graph.events:
        if e.epoch is None or e.epoch >= current:
            continue
        findings.append(Finding(
            code="MPX126", op=e.op, index=e.index,
            message=(f"{e.op} on comm {e.comm_uid} was issued in epoch "
                     f"{current} but the comm was built in epoch "
                     f"{e.epoch}: the world shrank in between and this "
                     "comm still addresses the revoked (pre-failure) "
                     "rank space"),
            suggestion=("re-enter the training loop through "
                        "mpx.elastic.run (it rebuilds comms on "
                        "recovery), or rebuild by hand with "
                        "comm.shrink(failed, mesh=...) — "
                        "docs/resilience.md 'Elastic recovery'"),
        ))
    return findings


# ---------------------------------------------------------------------------
# drained-comm collectives (MPX127)
# ---------------------------------------------------------------------------


@checker("MPX127")
def check_drained_comm(graph: CollectiveGraph) -> List[Finding]:
    """A collective issued on a comm whose world executed a planned
    drain past its leave boundary (``resilience/elastic.py`` graceful
    drain): the drained ranks left ON PURPOSE — the comm's group tables
    still include them, so the collective would wait on peers that
    committed, said goodbye, and exited.  A comm merely *scheduled* to
    drain (boundary not reached) is clean: collectives remain legal
    through the boundary — that is what makes the drain graceful."""
    findings: List[Finding] = []
    for e in graph.events:
        if not e.drained:
            continue
        findings.append(Finding(
            code="MPX127", op=e.op, index=e.index,
            message=(f"{e.op} on comm {e.comm_uid} was issued after the "
                     "comm's leave boundary: its world executed a "
                     "planned drain and the departed ranks will never "
                     "enter this collective"),
            suggestion=("use the comm mpx.elastic.run hands the step "
                        "function after the drain boundary (it is "
                        "rebuilt without the drained ranks), or rebuild "
                        "by hand with comm.shrink(drained, mesh=...) — "
                        "docs/resilience.md 'Grow and graceful drain'"),
        ))
    return findings


# ---------------------------------------------------------------------------
# AOT pinning advisory (MPX128)
# ---------------------------------------------------------------------------

# repeats of one collective signature inside a single trace before the
# advisory fires: below this, dispatch cost is noise; at or above it the
# trace is almost certainly a Python-level hot loop (fori_loop bodies
# trace ONCE, so they never trip this)
AOT_ADVISORY_MIN_REPEATS = 8


@checker("MPX128")
def check_unpinned_hot_loop(graph: CollectiveGraph) -> List[Finding]:
    """A single trace re-dispatching the same (op, comm, statics) prefix
    ``AOT_ADVISORY_MIN_REPEATS``-or-more times: a Python loop unrolled
    into the program — every iteration pays the full dispatch fast path
    at trace time and grows the program linearly — where ``mpx.compile``
    would pin the whole thing once (docs/aot.md).

    Gated on the config snapshot EXPLICITLY recording ``pinned: False``
    (every real trace does, via ``hook.config_snapshot``; a trace that
    is being pinned right now records True): hand-built graphs without
    pinning meta are testing other rules.  Eager events never count —
    each eager op is its own one-op program, not an unrolled loop.
    Events traced inside a megastep loop body (``e.loop`` set,
    parallel/megastep.py) never count either: the body traces ONCE — the
    advisory's advice (keep the loop on device) is already taken, the
    exact mirror of the ``tracing_pinned()`` exemption.
    """
    if graph.meta.get("pinned") is not False:
        return []
    counts: dict = {}
    for e in graph.events:
        if e.eager or e.loop is not None:
            continue
        # point-to-point loops (one send/recv per neighbor) and async
        # spans are STRUCTURE — same-signature repeats there route to
        # different peers, not a hot loop; only whole-group collectives
        # count
        if e.op in ("send", "recv", "sendrecv") or e.span is not None:
            continue
        sig = (e.op, e.comm_uid, e.reduction, e.root, e.tag, e.dtype,
               e.shape)
        counts.setdefault(sig, []).append(e)
    findings: List[Finding] = []
    for sig, events in counts.items():
        if len(events) < AOT_ADVISORY_MIN_REPEATS:
            continue
        first = events[0]
        findings.append(Finding(
            code="MPX128", op=first.op, index=first.index,
            message=(f"{len(events)} dispatches of the same {first.op} "
                     f"signature on comm {first.comm_uid} in one trace "
                     f"(events {first.index}..{events[-1].index}): a "
                     "Python-level hot loop unrolled into the program"),
            suggestion=("pin the program once with mpx.compile(fn, "
                        "*abstract_args, comm=...) and call the pinned "
                        "executable in the loop — or collapse the loop "
                        "onto the device with unroll=: mpx.compile(fn, "
                        "*abstract_args, comm=..., unroll=N) / "
                        "mpx.spmd(..., unroll=N) keeps N iterations "
                        "device-resident per host dispatch (megastep "
                        "execution, docs/aot.md)"),
        ))
    return findings


# ---------------------------------------------------------------------------
# megastep span-straddle error (MPX130)
# ---------------------------------------------------------------------------


@checker("MPX130")
def check_megastep_span_straddle(graph: CollectiveGraph) -> List[Finding]:
    """An async ``*_start``/``*_wait`` span straddling a megastep loop
    boundary (parallel/megastep.py): the loop body traces ONCE, so a
    start whose wait is not inside the same loop body would — at run
    time — leave iteration N's collective phases un-awaited when
    iteration N+1 begins (its instrumentation span armed with nothing to
    disarm it, its phases dead-code-eliminated out of the carry).  Spans
    must open AND close within one iteration; a span fully inside the
    loop body (start and wait under the same loop id) is legal and
    overlaps per-iteration.
    """
    spans: dict = {}
    for e in graph.events:
        if e.span is not None:
            spans.setdefault(e.span, []).append(e)
    findings: List[Finding] = []
    for span_id, events in sorted(spans.items()):
        loops = {e.loop for e in events}
        if loops == {None}:
            continue  # span entirely outside any megastep: MPX112 domain
        first = events[0]
        starts = [e for e in events if e.op.endswith("_start")]
        waits = [e for e in events if e.op.endswith("_wait")]
        if len(loops) > 1:
            where = ("the start is inside the loop body and the wait "
                     "outside (or in a different loop)"
                     if starts and starts[0].loop is not None
                     else "the wait is inside the loop body but its "
                     "start is not")
            findings.append(Finding(
                code="MPX130", op=first.op, index=first.index,
                message=(f"async span {span_id} ({first.op} on comm "
                         f"{first.comm_uid}) straddles a megastep loop "
                         f"boundary: {where}"),
                suggestion=("keep each *_start/*_wait pair inside one "
                            "loop iteration (overlap is per-iteration "
                            "in a megastep), or drop unroll= for this "
                            "program — docs/aot.md 'Megastep "
                            "execution'"),
            ))
        elif not (starts and waits):
            missing = "*_wait" if starts else "*_start"
            findings.append(Finding(
                code="MPX130", op=first.op, index=first.index,
                message=(f"async span {span_id} ({first.op} on comm "
                         f"{first.comm_uid}) opens inside a megastep "
                         f"loop body with no matching {missing} in the "
                         "same iteration: the span straddles the loop "
                         "boundary by construction"),
                suggestion=("issue the matching start/wait inside the "
                            "same loop iteration, or drop unroll= for "
                            "this program — docs/aot.md 'Megastep "
                            "execution'"),
            ))
    return findings


# ---------------------------------------------------------------------------
# serving bucket advisory (MPX136)
# ---------------------------------------------------------------------------


@checker("MPX136")
def check_unbucketed_batch(graph: CollectiveGraph) -> List[Finding]:
    """A traced collective whose leading (batch) dimension is not in the
    DECLARED serving bucket set (``graph.meta["serving_buckets"]``,
    recorded by ``hook.config_snapshot`` from
    ``mpx.serving.declare_buckets``; the engine scopes a declaration
    around its serving loop): under the serving runtime's
    one-program-per-(bucket, phase) rule, such a shape forces an
    unpinned retrace per request count.  Fires once per distinct
    offending batch size.  Inert — and the snapshot key absent —
    whenever no serving runtime has declared a table, so non-serving
    programs are never flagged (their leading dimensions are not batch
    sizes)."""
    buckets = graph.meta.get("serving_buckets")
    if not buckets:
        return []
    declared = set(buckets)
    findings: List[Finding] = []
    flagged: set = set()
    for e in graph.events:
        if e.eager or not e.shape:
            continue
        batch = e.shape[0]
        if batch in declared or batch in flagged:
            continue
        flagged.add(batch)
        findings.append(Finding(
            code="MPX136", op=e.op, index=e.index,
            message=(f"{e.op} payload has leading (batch) dimension "
                     f"{batch}, which is not in the declared serving "
                     f"bucket set {tuple(sorted(declared))}: each "
                     "distinct request batch shape traces and pins a "
                     "separate program — an unpinned retrace per "
                     "request count"),
            suggestion=("pad the live batch to its covering bucket "
                        "before dispatch (BucketTable.bucket_for / "
                        ".pad — the serving engine does this "
                        "automatically), or declare the shape in "
                        "MPI4JAX_TPU_SERVING_BUCKETS — docs/serving.md"),
        ))
    return findings


# ---------------------------------------------------------------------------
# flight-ring capacity advisory (MPX143)
# ---------------------------------------------------------------------------


@checker("MPX143")
def check_flight_ring_capacity(graph: CollectiveGraph) -> List[Finding]:
    """A megastep loop body dispatching more collectives per iteration
    than the health plane's flight-recorder ring can hold
    (telemetry/health.py): in the events tier every execution spills a
    begin AND an end record, so a ring of ``MPI4JAX_TPU_FLIGHT_RING``
    records holds at most ``capacity // 2`` collectives — fewer than one
    iteration means a postmortem bundle's ring has already overwritten
    the iteration's own history by the time a hang is detected.  Gated
    on ``graph.meta["flight_ring"]`` (recorded by
    ``hook.config_snapshot`` only when ``MPI4JAX_TPU_HEALTH=on``), so
    health-off traces — and hand-built graphs testing other rules — are
    never flagged.  Fires at most once per loop id."""
    capacity = graph.meta.get("flight_ring")
    if not capacity:
        return []
    implied = int(capacity) // 2  # begin + end records per collective
    loops: dict = {}
    for e in graph.events:
        if e.loop is not None:
            loops.setdefault(e.loop, []).append(e)
    findings: List[Finding] = []
    for loop_id, events in sorted(loops.items()):
        if len(events) <= implied:
            continue
        first = events[0]
        findings.append(Finding(
            code="MPX143", op=first.op, index=first.index,
            message=(f"megastep loop {loop_id} dispatches {len(events)} "
                     f"collectives per iteration, but "
                     f"MPI4JAX_TPU_FLIGHT_RING={int(capacity)} holds "
                     f"only ~{implied} (a begin + an end record each): "
                     "the flight recorder overwrites the current "
                     "iteration's own history, so a postmortem cannot "
                     "show where the ranks diverged"),
            suggestion=(f"raise MPI4JAX_TPU_FLIGHT_RING to at least "
                        f"{2 * len(events)} (2 records per collective "
                        "per iteration, plus headroom for incidents) — "
                        "docs/observability.md 'Runtime health'"),
        ))
    return findings


# ---------------------------------------------------------------------------
# topology advisory (MPX113)
# ---------------------------------------------------------------------------


@checker("MPX113")
def check_flat_over_dcn(graph: CollectiveGraph) -> List[Finding]:
    """Flat ring/butterfly on a multi-host comm above the ring crossover:
    the payload is large enough that ``auto`` would have chosen the
    two-level ICI/DCN lowering, but a forced flat algorithm (or an
    explicit crossover move) kept the single-level one — every round of
    which is gated on the slowest DCN hop.

    Events carry ``hosts`` only when a hierarchical plan was derivable
    for their comm (``ops/_hierarchy.annotate_selection``), so comms
    whose host partition is non-uniform — where flat is the only option —
    never fire this.  Requires ``comm_size > hosts`` (with one rank per
    host there is no intra level and hier degenerates to flat).

    When a cost-model tuning file is loaded
    (``MPI4JAX_TPU_COST_MODEL``), the MEASURED ring crossover replaces
    the static env default — as the firing threshold and in the
    advisory text, which then cites the calibration source.
    """
    measured = graph.meta.get("measured_ring_crossover_bytes")
    crossover = measured or graph.meta.get("ring_crossover_bytes")
    if not crossover:
        return []
    cite = (
        f"measured crossover, {_calibration_cite(graph.meta)}"
        if measured else "ring crossover"
    )
    findings: List[Finding] = []
    for e in graph.events:
        if e.op not in ALGO_OPS or e.algo not in ("ring", "butterfly"):
            continue
        if not e.hosts or e.hosts <= 1:
            continue
        if e.comm_size is None or e.comm_size <= e.hosts:
            continue
        if e.payload_bytes < crossover:
            continue
        findings.append(Finding(
            code="MPX113", op=e.op, index=e.index,
            message=(f"{e.op} on comm {e.comm_uid} spans {e.hosts} hosts "
                     f"({e.comm_size} ranks) but ran the flat '{e.algo}' "
                     f"algorithm at {e.payload_bytes} B (>= the "
                     f"{crossover} B {cite}): every round is "
                     "gated on the slowest DCN hop"),
            suggestion=("let algo=auto pick the two-level lowering, or "
                        "force MPI4JAX_TPU_COLLECTIVE_ALGO=hier for an "
                        "A/B run — see docs/topology.md"),
        ))
    return findings


@checker("MPX137")
def check_flat_alltoall_over_dcn(graph: CollectiveGraph) -> List[Finding]:
    """Flat alltoall on a multi-host comm above the alltoall crossover:
    the MPX113 analog for the permutation family.  The payload is large
    enough that ``auto`` would have chosen the two-level ICI/DCN
    lowering (intra-host transpose, inter-host exchange of
    host-aggregated contiguous blocks — 1/r the DCN message count), but
    a forced flat algorithm (or an explicit crossover move) kept the
    single-level exchange, whose every per-rank message crosses DCN
    individually.

    Events carry ``hosts`` only when a hierarchical plan was derivable
    for their comm (``ops/_hierarchy.annotate_selection``), so comms
    whose host partition is non-uniform — where flat is the only
    option — never fire this.  Async ``alltoall_start`` spans count
    like the blocking op (the start phase runs the exchange).

    Like its MPX113 template, a calibrated MEASURED crossover (from a
    loaded tuning/cost-model file) replaces the static value — as the
    firing threshold and in the advisory text, which then cites the
    calibration source."""
    measured = graph.meta.get("measured_alltoall_crossover_bytes")
    crossover = measured or graph.meta.get("alltoall_crossover_bytes")
    if not crossover:
        return []
    cite = (
        f"measured alltoall crossover, {_calibration_cite(graph.meta)}"
        if measured else "alltoall crossover"
    )
    findings: List[Finding] = []
    for e in graph.events:
        if e.op not in ("alltoall", "alltoall_start"):
            continue
        if e.algo not in ("native", "pairwise"):
            continue
        if not e.hosts or e.hosts <= 1:
            continue
        if e.comm_size is None or e.comm_size <= e.hosts:
            continue
        if e.payload_bytes < crossover:
            continue
        r = e.comm_size // e.hosts
        findings.append(Finding(
            code="MPX137", op=e.op, index=e.index,
            message=(f"{e.op} on comm {e.comm_uid} spans {e.hosts} hosts "
                     f"({e.comm_size} ranks) but ran the flat "
                     f"'{e.algo}' exchange at {e.payload_bytes} B (>= "
                     f"the {crossover} B {cite}): every "
                     f"rank addresses every remote rank directly — "
                     f"{r}x the DCN message count of the two-level "
                     "lowering"),
            suggestion=("let algo=auto pick the hierarchical alltoall, "
                        "or force MPI4JAX_TPU_COLLECTIVE_ALGO=hier for "
                        "an A/B run — see docs/moe.md and "
                        "docs/topology.md"),
        ))
    return findings


@checker("MPX138")
def check_uncompressed_dcn(graph: CollectiveGraph) -> List[Finding]:
    """Uncompressed above-crossover DCN traffic: a hierarchical
    collective on a multi-host comm ships a float32 inter-host leg at
    or above the DCN crossover while the wire codec layer
    (``MPI4JAX_TPU_COMPRESS``, docs/compression.md) is off.

    Fires only when the snapshot's compress mode is ``off`` — a trace
    that already opted in but left THIS event exact (non-float32,
    callable reduction, payload bucketed to ``off``) made a deliberate
    choice the advisory must not second-guess.  Gates mirror MPX113:
    ``hosts`` present (a plan was derivable), ``comm_size > hosts``
    (a real intra level), and the modeled DCN-leg bytes — payload/r for
    the reduction family, the full payload for alltoall — at or above
    the (measured, when calibrated) DCN crossover.
    """
    if graph.meta.get("compress", "off") != "off":
        return []
    measured = graph.meta.get("measured_dcn_crossover_bytes")
    crossover = measured or graph.meta.get("dcn_crossover_bytes")
    if not crossover:
        return []
    cite = (
        f"measured DCN crossover, {_calibration_cite(graph.meta)}"
        if measured else "DCN crossover"
    )
    compressible = ("allreduce", "reduce_scatter", "alltoall",
                    "allreduce_start", "reduce_scatter_start",
                    "alltoall_start")
    findings: List[Finding] = []
    for e in graph.events:
        if e.op not in compressible or e.algo != "hier":
            continue
        if getattr(e, "codec", None) is not None:
            continue
        if not e.hosts or e.hosts <= 1:
            continue
        if e.comm_size is None or e.comm_size <= e.hosts:
            continue
        if e.dtype not in ("", "float32"):
            continue  # the codec layer only compresses float32
        r = e.comm_size // e.hosts
        leg = (e.payload_bytes if e.op.startswith("alltoall")
               else -(-e.payload_bytes // max(r, 1)))
        if leg < crossover:
            continue
        findings.append(Finding(
            code="MPX138", op=e.op, index=e.index,
            message=(f"{e.op} on comm {e.comm_uid} spans {e.hosts} hosts "
                     f"({e.comm_size} ranks) and ships a {leg} B "
                     f"float32 DCN leg uncompressed (>= the {crossover} "
                     f"B {cite}): MPI4JAX_TPU_COMPRESS=bf16 would halve "
                     "the wire bytes on that leg (fp8 would quarter "
                     "them), ICI staying exact"),
            suggestion=("opt in with MPI4JAX_TPU_COMPRESS=bf16 (not "
                        "bit-identical — pair gradients with "
                        "mpx.compress.ef_allreduce), or let "
                        "mpx.autotune() sweep the codecs against the "
                        "error budget — see docs/compression.md"),
        ))
    return findings


# ---------------------------------------------------------------------------
# perf advisory (MPX109)
# ---------------------------------------------------------------------------


def _calibration_cite(meta: dict) -> str:
    """Provenance of a measured threshold in an advisory text: the
    tuning layer's content stamp (``tuned@<stamp>`` — docs/autotune.md)
    when one is active, else the cost-model file path
    (``MPI4JAX_TPU_COST_MODEL``)."""
    stamp = meta.get("tuned_stamp")
    if stamp:
        return f"tuned@{stamp}"
    return f"cost model {meta.get('cost_model')}"


@checker("MPX109")
def check_crossover_proximity(graph: CollectiveGraph) -> List[Finding]:
    """Payload within 2x of the ring/butterfly crossover under algo=auto:
    shape-polymorphic retraces straddling the threshold silently flip the
    lowering (same math, different perf) between traces.  With an active
    tuning layer the crossover in the snapshot IS the measured value
    (the config layer serves it), and the text carries the
    ``tuned@<stamp>`` provenance."""
    if graph.meta.get("collective_algo", "auto") != "auto":
        return []
    crossover = graph.meta.get("ring_crossover_bytes")
    if not crossover:
        return []
    # cite measured provenance only when the effective crossover IS the
    # layer's measured value — a file that tunes other knobs, or an env
    # override shadowing the file, must not be presented as "measured"
    measured = graph.meta.get("measured_ring_crossover_bytes")
    cite = (f"measured ring crossover, {_calibration_cite(graph.meta)}"
            if graph.meta.get("tuned_stamp") and measured == crossover
            else "ring crossover")
    findings: List[Finding] = []
    for e in graph.events:
        if e.op not in ALGO_OPS or e.algo in (None, "native"):
            continue
        k = e.comm_size
        if k is None or k < RING_MIN_GROUP:
            continue
        if crossover / 2 <= e.payload_bytes < crossover * 2:
            findings.append(Finding(
                code="MPX109", op=e.op, index=e.index,
                message=(f"{e.op} payload ({e.payload_bytes} B) is within "
                         f"2x of the {cite} ({crossover} B) under "
                         "algo=auto: retraces at nearby shapes may pick "
                         f"different lowerings (this trace chose "
                         f"'{e.algo}')"),
                suggestion=("pin MPI4JAX_TPU_COLLECTIVE_ALGO=butterfly or "
                            "=ring for this workload, or move "
                            "MPI4JAX_TPU_RING_CROSSOVER_BYTES away from "
                            "the working payload size"),
            ))
    return findings


# the dataflow hazard checkers (MPX139/MPX140, analysis/hazards.py)
# register themselves on import; imported at the BOTTOM so hazards can
# import ``checker`` from this module without a cycle
from . import hazards  # noqa: E402,F401
