"""Closed-jaxpr walker: structural checks no event stream can see.

The ops lower to plain ``jax.lax`` collectives, so a traced program's
jaxpr contains ``psum``/``ppermute``/``all_gather``/... equations wherever
communication happens — including inside control-flow sub-jaxprs that the
dispatch-point recorder observes only as a flat stream.  This walker
descends the whole jaxpr tree (duck-typed: anything with ``.eqns`` is a
jaxpr, anything with ``.jaxpr`` is a closed jaxpr, params may hold jaxprs
or lists of them) and flags ``lax.cond`` equations whose branches disagree
about communicating (MPX108): if the predicate ever varies across ranks,
the communicating branch hangs waiting for ranks that took the other one.

Duck typing keeps this module importable (and unit-testable with fake
jaxpr objects) under any JAX version.
"""

from __future__ import annotations

from typing import List

from .report import Finding

# primitive-name prefixes that perform cross-rank communication (matched
# by prefix so renames like psum -> psum2/psum_invariant across JAX
# versions stay covered).  Deliberately NOT listed: pbroadcast/pcast —
# in the VMA collective type system those are typing promotions that
# lower to nothing, and flagging them would false-positive every branch
# that merely re-types a value.
COLLECTIVE_PRIMITIVE_PREFIXES = (
    "psum",
    "pmin",
    "pmax",
    "ppermute",
    "all_gather",
    "all_to_all",
    "psum_scatter",
    "reduce_scatter",
)


def _iter_jaxprs(v):
    """Yield every jaxpr reachable from a params value (jaxpr, closed
    jaxpr, or (nested) sequence thereof)."""
    if hasattr(v, "eqns"):
        yield v
    elif hasattr(v, "jaxpr"):
        yield from _iter_jaxprs(v.jaxpr)
    elif isinstance(v, (list, tuple)):
        for x in v:
            yield from _iter_jaxprs(x)


def _sub_jaxprs(eqn):
    for v in eqn.params.values():
        yield from _iter_jaxprs(v)


def is_collective(primitive_name: str) -> bool:
    return primitive_name.startswith(COLLECTIVE_PRIMITIVE_PREFIXES)


def count_collectives(jaxpr) -> int:
    """Number of collective equations in ``jaxpr``, including all nested
    sub-jaxprs (control flow, pjit, shard_map, custom_* wrappers)."""
    n = 0
    for eqn in jaxpr.eqns:
        if is_collective(eqn.primitive.name):
            n += 1
        for sub in _sub_jaxprs(eqn):
            n += count_collectives(sub)
    return n


def find_cond_divergences(jaxpr) -> List[dict]:
    """All ``cond`` equations (at any depth) whose branches disagree on
    whether they communicate.  Returns records with per-branch collective
    counts."""
    out: List[dict] = []
    for eqn in jaxpr.eqns:
        if eqn.primitive.name == "cond":
            counts = [
                sum(count_collectives(j) for j in _iter_jaxprs(b))
                for b in eqn.params.get("branches", ())
            ]
            if any(counts) and not all(counts):
                out.append({"counts": counts})
        # descend regardless: nested conds inside branches/bodies
        for sub in _sub_jaxprs(eqn):
            out.extend(find_cond_divergences(sub))
    return out


def check_cond_divergence(closed_jaxpr) -> List[Finding]:
    """MPX108 findings for a traced program's closed jaxpr."""
    findings: List[Finding] = []
    for rec in find_cond_divergences(
            next(_iter_jaxprs(closed_jaxpr), closed_jaxpr)):
        counts = rec["counts"]
        with_c = [i for i, c in enumerate(counts) if c]
        without = [i for i, c in enumerate(counts) if not c]
        findings.append(Finding(
            code="MPX108", op="cond",
            message=(f"lax.cond branches disagree about communicating: "
                     f"branch(es) {with_c} contain "
                     f"{sum(counts)} collective(s), branch(es) {without} "
                     "contain none — a rank-varying predicate hangs the "
                     "communicating side"),
            suggestion=("hoist the collective out of the cond, or make "
                        "every branch issue the same collectives (e.g. "
                        "reduce a masked value)"),
        ))
    return findings
