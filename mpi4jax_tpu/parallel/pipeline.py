"""Pipeline-parallel schedule compiler: GPipe, 1F1B, interleaved.

The last classic parallelism axis (ROADMAP item 1): one stage of a
layered model per rank, microbatches wavefronting through the stage
chain.  ``examples/pipeline_parallel.py``'s hand-rolled ladder showed
the shape; this module makes it a *compiled schedule* the library owns:

- :func:`compile_phases` — the pure half (no JAX; tests/
  test_pipeline_pure.py runs it under the isolated loader): per-rank
  forward/backward micro-op programs for every schedule, the
  warmup/steady/cooldown tick split the traced driver executes, and the
  activation-stash bound each schedule needs.  GPipe stashes every
  microbatch (depth ``M``); 1F1B's early backwards cap the stash at
  ``min(S, M)`` — the PipeDream-flush memory win (docs/pipeline.md
  "Activation stash");
- :class:`PipelineProgram` / :func:`pipeline` — the runnable program.
  Boundary transfers go through the async point-to-point primitives
  (``send_start``/``recv_start``/``p2p_wait``, ops/_async.py) under the
  ``1f1b`` and ``interleaved`` schedules: wire-independent work issues
  inside the recv span and the send-side wait closes only after the
  stage compute, so neither the wire nor the downstream rank's progress
  gates a tick's compute; ``gpipe`` keeps the blocking ``sendrecv``
  boundary (the baseline the BENCH grid prices).  The
  steady-state ticks — the 1F1B core — compose with the megastep
  compiler (parallel/megastep.py): one device-resident ``fori_loop``
  dispatch executes the whole steady window, and the MPX130 span rule
  holds because every start/wait pair lives inside one iteration;
- ``schedule='auto'`` — the cost model picks: ``costmodel.
  best_schedule`` prices every expressible schedule with the active
  alpha-beta model (tuned parameters when ``mpx-tuning/1`` is loaded)
  and the argmin runs.  Programs annotate their (schedule, stages,
  microbatches, virtual, payload) onto the event stream, and the MPX144
  advisory (analysis/cost.py) fires when a run's schedule is priced
  measurably worse than an expressible alternative.

Interleaved virtual stages (Megatron-style): ``virtual=v`` gives every
rank ``v`` stage-chunks — rank ``r`` owns virtual stages ``c*S + r`` —
shrinking the pipeline fill by ``v`` at the price of ``v``x as many
(1/v-sized) boundary messages.  The driver moves the whole chunk stack
in one ring transfer per tick.

Run :class:`PipelineProgram` eagerly (``prog(mbs, params)``) and the
warmup/steady/cooldown phases dispatch separately under host telemetry
brackets — ``pipeline.stage`` / ``pipeline.bubble_wait`` rows in the
per-op table plus the bubble-time meters ``telemetry.report()`` turns
into a MEASURED bubble fraction — or call ``prog.trace(...)`` inside an
existing region to inline the whole round into a larger program.

Only stdlib at import time (JAX and the ops load inside the drivers),
so the pure half stays loadable under any JAX.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Callable, Dict, Optional, Sequence, Tuple, Union

__all__ = [
    "PipelineProgram",
    "SCHEDULES",
    "PhasePlan",
    "compile_phases",
    "pipeline",
    "rank_program",
    "split_microbatches",
    "stash_depth",
]

# the expressible schedules; "auto" resolves to one of these via
# analysis.costmodel.best_schedule (the ladder is the anti-pattern this
# module replaces — MPX135 points at it, it is never a candidate)
SCHEDULES = ("gpipe", "1f1b", "interleaved")


# ---------------------------------------------------------------------------
# the pure half: per-rank micro-op programs + phase split (no JAX)
# ---------------------------------------------------------------------------


def _validate(schedule: str, stages: int, microbatches: int,
              virtual: int) -> None:
    if schedule not in SCHEDULES:
        raise ValueError(
            f"pipeline: unknown schedule {schedule!r} "
            f"(expressible: {SCHEDULES}, plus 'auto')"
        )
    if stages < 1:
        raise ValueError(f"pipeline: stages must be >= 1, got {stages}")
    if microbatches < 1:
        raise ValueError(
            f"pipeline: n_microbatches must be >= 1, got {microbatches}")
    if virtual < 1:
        raise ValueError(f"pipeline: virtual must be >= 1, got {virtual}")
    if schedule == "interleaved" and virtual < 2:
        raise ValueError(
            "pipeline: the interleaved schedule needs virtual >= 2 "
            "stage-chunks per rank (virtual=1 is plain 1f1b)"
        )
    if schedule != "interleaved" and virtual != 1:
        raise ValueError(
            f"pipeline: virtual={virtual} only applies to the "
            "interleaved schedule"
        )


def rank_program(schedule: str, stages: int, microbatches: int, rank: int,
                 virtual: int = 1) -> Tuple[Tuple[str, int, int], ...]:
    """Rank ``rank``'s ordered micro-op program: ``("F"|"B", microbatch,
    chunk)`` triples.  The F/B interleaving is what bounds the
    activation stash (:func:`stash_depth`); the traced driver executes
    the forward wavefront, and the program is the schedule's training-
    shaped accounting (docs/pipeline.md "Schedule programs")."""
    _validate(schedule, stages, microbatches, virtual)
    if not 0 <= rank < stages:
        raise ValueError(f"pipeline: rank {rank} out of range for "
                         f"{stages} stage(s)")
    s, m, v = stages, microbatches, virtual
    if schedule == "gpipe":
        # synchronous flush: every forward, then every backward
        return tuple([("F", i, 0) for i in range(m)]
                     + [("B", i, 0) for i in reversed(range(m))])
    # 1f1b / interleaved: forward items in wavefront completion order
    # (chunk c of this rank is virtual stage c*S + rank); warmup fills
    # the pipe below this rank's deepest chunk, then strict one-forward-
    # one-backward alternation, then the backward drain
    items = sorted((i + c * s + rank, c, i)
                   for i in range(m) for c in range(v))
    fwd = [(i, c) for _t, c, i in items]
    warmup = min(m * v, (s - 1 - rank) + (v - 1) * s)
    prog = []
    done = 0
    for j, (i, c) in enumerate(fwd):
        prog.append(("F", i, c))
        if j >= warmup:
            prog.append(("B",) + fwd[done])
            done += 1
    while done < len(fwd):
        prog.append(("B",) + fwd[done])
        done += 1
    return tuple(prog)


def stash_depth(program: Sequence[Tuple[str, int, int]]) -> int:
    """Peak number of live activation stashes a micro-op program holds
    (each F pushes its input activation for the matching B)."""
    depth = peak = 0
    for op, _i, _c in program:
        if op == "F":
            depth += 1
            peak = max(peak, depth)
        elif op == "B":
            depth -= 1
            if depth < 0:
                raise ValueError("pipeline: program pops an activation "
                                 "it never stashed")
    return peak


@dataclass(frozen=True)
class PhasePlan:
    """One compiled schedule: the tick split the traced driver executes
    plus the per-rank stash accounting.

    A forward round is ``ticks = M + P - 1`` wavefront ticks over
    ``P = S * v`` virtual stages: ``warmup`` ticks fill the pipe
    (Python-unrolled — early ticks have no valid output row), ``steady``
    ticks are the full-pipe window ``[P-1, M-1]`` (megastep-eligible:
    every input and output index is in range, no masks), ``cooldown``
    ticks drain.  ``max_stash`` is the worst rank's activation-stash
    bound: ``M`` for gpipe, ``min(S, M)`` for 1f1b — the 1F1B memory
    claim tests/test_pipeline_pure.py pins.
    """

    schedule: str
    stages: int
    microbatches: int
    virtual: int
    warmup: int
    steady: int
    cooldown: int
    ticks: int
    max_stash: int
    stash_by_rank: Tuple[int, ...]


def compile_phases(schedule: str, stages: int, microbatches: int,
                   virtual: int = 1) -> PhasePlan:
    """Compile ``schedule`` over ``stages`` x ``microbatches`` (and
    ``virtual`` chunks per rank) into its :class:`PhasePlan`."""
    _validate(schedule, stages, microbatches, virtual)
    p = stages * virtual
    ticks = microbatches + p - 1
    steady = max(0, microbatches - (p - 1))
    warmup = p - 1
    cooldown = ticks - warmup - steady
    stash = tuple(
        stash_depth(rank_program(schedule, stages, microbatches, r,
                                 virtual))
        for r in range(stages)
    )
    return PhasePlan(schedule=schedule, stages=stages,
                     microbatches=microbatches, virtual=virtual,
                     warmup=warmup, steady=steady, cooldown=cooldown,
                     ticks=ticks, max_stash=max(stash),
                     stash_by_rank=stash)


def split_microbatches(x, n: Optional[int] = None):
    """Split a batch-leading array ``(B, ...)`` into ``(M, B/M, ...)``
    microbatches: ``n`` explicit, else the tuned
    ``pipeline_microbatches`` knob (mpx-tuning/1, payload-bucketed by
    the batch's byte size), else 1.  ``B`` must divide evenly."""
    from ..utils import config

    if n is None:
        n = config.pipeline_microbatches(payload_bytes=_nbytes_of(x)) or 1
    n = int(n)
    b = int(x.shape[0])
    if n < 1 or b % n:
        raise ValueError(
            f"pipeline: cannot split batch of {b} into {n} equal "
            "microbatch(es)"
        )
    return x.reshape((n, b // n) + tuple(x.shape[1:]))


def _nbytes_of(x) -> int:
    n = 1
    for d in x.shape:
        n *= int(d)
    return n * getattr(getattr(x, "dtype", None), "itemsize", 4)


# ---------------------------------------------------------------------------
# the runnable program
# ---------------------------------------------------------------------------


StageFns = Union[Callable, Sequence[Callable]]


class PipelineProgram:
    """A compiled pipeline round: call eagerly (phase-bracketed
    dispatches) or ``trace`` inside an existing parallel region.

    Eager inputs are global arrays (leading axis = ranks): ``mbs`` is
    ``(S, M, mb, ...)`` with stage 0's row carrying the real
    microbatches (:func:`split_microbatches` builds the per-rank view),
    and the result is ``(S, M, mb, ...)`` whose LAST stage row holds
    the model output.
    """

    def __init__(self, stage_fns: StageFns, n_microbatches: Optional[int],
                 schedule: str, virtual: Optional[int], comm,
                 megastep: bool):
        if callable(stage_fns):
            self._fns: Optional[Tuple[Callable, ...]] = None
            self._fn: Optional[Callable] = stage_fns
        else:
            fns = tuple(stage_fns)
            if not fns or not all(callable(f) for f in fns):
                raise TypeError(
                    "pipeline: stage_fns must be a callable or a "
                    "non-empty sequence of callables (one per virtual "
                    "stage-chunk)"
                )
            self._fns, self._fn = fns, None
            if virtual is not None and virtual != len(fns):
                raise ValueError(
                    f"pipeline: virtual={virtual} disagrees with "
                    f"{len(fns)} stage_fns"
                )
            virtual = len(fns)
        if schedule != "auto" and schedule not in SCHEDULES:
            raise ValueError(
                f"pipeline: unknown schedule {schedule!r} "
                f"(expressible: {SCHEDULES}, plus 'auto')"
            )
        if schedule in ("gpipe", "1f1b") and virtual is not None and \
                int(virtual) >= 2:
            raise ValueError(
                f"pipeline: schedule={schedule!r} cannot run a program "
                f"carrying {virtual} stage-chunks per rank — only the "
                "interleaved schedule applies per-chunk stage fns; "
                "compose the chunks into one stage fn per rank, or use "
                "schedule='interleaved' (or 'auto')"
            )
        self._requested = schedule
        self._n_microbatches = n_microbatches
        self._virtual = virtual
        self._comm = comm
        self._megastep = bool(megastep)
        self._progs: Dict[tuple, tuple] = {}

    # -- planning ----------------------------------------------------------

    def _resolve_virtual(self, schedule: str) -> int:
        from ..utils import config

        v = self._virtual
        if v is None:
            v = config.pipeline_virtual_stages() or 0
        if schedule == "interleaved":
            return max(2, int(v))
        if schedule == "auto":
            return max(1, int(v))
        return 1

    def _carries_chunks(self) -> bool:
        """Whether this program is built from ``v >= 2`` stage-chunks
        per rank (a multi-fn stage list, or a single fn whose params
        carry the chunk axis an explicit ``virtual`` promises).  Such a
        program can only express the interleaved schedule: gpipe/1f1b
        apply one stage fn per rank, so running them would silently
        drop chunks ``1..v-1``."""
        if self._fns is not None and len(self._fns) >= 2:
            return True
        return self._virtual is not None and int(self._virtual) >= 2

    def plan(self, stages: int, microbatches: int, payload_bytes: int
             ) -> PhasePlan:
        """Resolve ``schedule='auto'`` through the cost model and compile
        the phase plan (also the introspection entry the tests and docs
        use — pure, callable without a device in sight)."""
        schedule = self._requested
        virtual = self._resolve_virtual(schedule)
        if schedule == "auto":
            from ..analysis import costmodel

            model = costmodel.load_model()
            # roofline floor for the per-microbatch stage compute: a
            # stage at minimum streams its boundary activation in and
            # out (docs/pipeline.md "Choosing a schedule").  The
            # candidate set is virtual-aware: best_schedule prices
            # gpipe vs 1f1b for a flat program and interleaved alone
            # for a chunked one (virtual >= 2) — a schedule the
            # program cannot express without restructuring is never
            # the argmin.
            compute_us = model.compute_us(2 * payload_bytes)
            schedule, _times = costmodel.best_schedule(
                stages, microbatches, payload_bytes, compute_us, model,
                virtual=virtual)
        if schedule != "interleaved":
            if self._carries_chunks():
                raise ValueError(
                    f"pipeline: this program carries "
                    f"{self._virtual} stage-chunks per rank but "
                    f"resolved schedule {schedule!r}; only "
                    "'interleaved' can run chunked stage fns — "
                    "gpipe/1f1b would silently drop every chunk but "
                    "the first"
                )
            virtual = 1
        return compile_phases(schedule, stages, microbatches, virtual)

    def _stamp(self, plan: PhasePlan, payload_bytes: int) -> tuple:
        return (plan.schedule, plan.stages, plan.microbatches,
                plan.virtual, payload_bytes)

    # -- the traced driver -------------------------------------------------

    def trace(self, mbs, params, *, token=None):
        """Run one pipeline round inside the CURRENT parallel region.

        ``mbs`` is the per-rank microbatch view ``(M, mb, ...)`` (stage
        0's lanes real); returns ``(out, token)`` with ``out`` of shape
        ``(M, mb, ...)`` — the last rank's lanes are the model output.
        """
        import jax.numpy as jnp

        from .region import current_context

        comm = self._comm if self._comm is not None else \
            current_context().comm
        stages = comm.Get_size()
        m = int(mbs.shape[0])
        if self._n_microbatches is not None and \
                int(self._n_microbatches) != m:
            raise ValueError(
                f"pipeline: n_microbatches={self._n_microbatches} but "
                f"the input carries {m} microbatch(es); split the batch "
                "with mpx.parallel.pipeline.split_microbatches"
            )
        plan = self.plan(stages, m, _nbytes_of(mbs[0]))
        ticks = _TickDriver(self, plan, comm, mbs, params)
        h = jnp.stack([jnp.zeros_like(mbs[0])] * plan.virtual)
        out = jnp.zeros(mbs.shape, mbs.dtype)
        h, out, token = ticks.run(0, plan.warmup, h, out, token,
                                  use_megastep=False)
        h, out, token = ticks.run(plan.warmup, plan.warmup + plan.steady,
                                  h, out, token,
                                  use_megastep=self._megastep)
        h, out, token = ticks.run(plan.warmup + plan.steady, plan.ticks,
                                  h, out, token, use_megastep=False)
        return out, token

    # -- the eager phase driver --------------------------------------------

    def __call__(self, mbs, params):
        """One eagerly-dispatched pipeline round: warmup, steady, and
        cooldown run as separate dispatches under ``pipeline.{phase}``
        host telemetry brackets, so the MEASURED bubble share (warmup +
        cooldown wall over total) lands in ``telemetry.report()``."""
        import jax.numpy as jnp

        from .region import resolve_comm

        comm = resolve_comm(self._comm)
        stages = comm.Get_size()
        if int(mbs.shape[0]) != stages:
            raise ValueError(
                f"pipeline: global input leading axis {mbs.shape[0]} != "
                f"comm size {stages} (one stage per rank)"
            )
        m = int(mbs.shape[1])
        if self._n_microbatches is not None and \
                int(self._n_microbatches) != m:
            raise ValueError(
                f"pipeline: n_microbatches={self._n_microbatches} but "
                f"the input carries {m} microbatch(es)"
            )
        nbytes = _nbytes_of(mbs[0, 0])
        plan = self.plan(stages, m, nbytes)
        warm, steady, cool = self._phase_progs(comm, plan)
        h = jnp.zeros((stages, plan.virtual) + tuple(mbs.shape[2:]),
                      mbs.dtype)
        out = jnp.zeros(mbs.shape, mbs.dtype)
        with _phase_bracket(comm, plan, "bubble_wait", nbytes):
            h, out = warm(mbs, h, out, params)
            h, out = _block_for_timing(h, out)
        if steady is not None:
            with _phase_bracket(comm, plan, "stage", nbytes):
                h, out = steady(mbs, h, out, params)
                h, out = _block_for_timing(h, out)
        with _phase_bracket(comm, plan, "bubble_wait", nbytes):
            h, out = cool(mbs, h, out, params)
            h, out = _block_for_timing(h, out)
        return out

    def _phase_progs(self, comm, plan: PhasePlan):
        from .region import spmd

        key = (comm.uid, plan)
        cached = self._progs.get(key)
        if cached is not None:
            return cached

        def phase_fn(lo, hi, use_megastep):
            def run(mbs, h, out, params):
                ticks = _TickDriver(self, plan, comm, mbs, params)
                h2, out2, _ = ticks.run(lo, hi, h, out, None,
                                        use_megastep=use_megastep)
                return h2, out2

            return spmd(run, comm=comm)

        warm = phase_fn(0, plan.warmup, False)
        steady = None
        if plan.steady:
            steady = phase_fn(plan.warmup, plan.warmup + plan.steady,
                              self._megastep)
        cool = phase_fn(plan.warmup + plan.steady, plan.ticks, False)
        progs = (warm, steady, cool)
        self._progs[key] = progs
        return progs


class _TickDriver:
    """The shared tick machinery of one pipeline round (per-rank view):
    built fresh per trace, drives any ``[lo, hi)`` window of the plan's
    ticks, Python-unrolled or as one megastep ``fori_loop``."""

    def __init__(self, prog: PipelineProgram, plan: PhasePlan, comm,
                 mbs, params):
        self.prog, self.plan, self.comm = prog, plan, comm
        self.mbs, self.params = mbs, params
        self.stamped = False

    def _chunk_fn(self, c: int):
        prog, v = self.prog, self.plan.virtual
        if prog._fns is not None:
            return lambda x: prog._fns[c](x, self.params)
        if v == 1:
            return lambda x: prog._fn(x, self.params)
        import jax

        pc = jax.tree.map(lambda leaf: leaf[c], self.params)
        return lambda x: prog._fn(x, pc)

    def _mark(self):
        if self.stamped:
            return
        self.stamped = True
        from ..analysis.hook import mark_last_event
        from .region import current_context

        stamp = self.prog._stamp(self.plan, _nbytes_of(self.mbs[0]))
        mark_last_event("pipeline", stamp, current_context())

    def _boundary_starts(self, h, tok):
        """Issue the tick's boundary transfer.  gpipe: the blocking
        ``sendrecv`` — the transfer sits on the tick edge by design
        (the baseline the BENCH grid prices), so it completes here and
        the returned "handle" is already the received stack.  Async
        schedules (1f1b/interleaved): open both spans and return
        WITHOUT blocking — the recv wait lands in :meth:`_boundary_recv`
        (after the wire-independent work the tick issues into the
        span) and the send wait in :meth:`_boundary_send_finish`
        (after the stage compute), so neither the wire nor the
        downstream rank's progress gates this rank's compute.  That
        one-tick send decoupling is what lets the ranks skew instead
        of running the lockstep the gpipe boundary enforces; the
        residual per-tick exposure is the ``max(0, x - c)`` term
        ``costmodel.pipeline_wall_us`` prices (docs/pipeline.md
        "Phases, async p2p")."""
        from ..ops._async import recv_start, send_start
        from ..ops.sendrecv import sendrecv
        from .rankspec import shift

        # interleaved boundaries form a ring (the last rank's chunk-c
        # output is rank 0's chunk-(c+1) input); a flat pipe stops at
        # the edge
        dest = shift(1, wrap=self.plan.virtual > 1)
        if self.plan.schedule == "gpipe":
            got, tok = sendrecv(h, h, dest=dest, token=tok)
            self._mark()
            return None, got, tok
        sh, tok = send_start(h, dest, token=tok)
        rh, tok = recv_start(h, token=tok)
        return sh, rh, tok

    def _boundary_recv(self, rh, tok):
        if self.plan.schedule == "gpipe":
            return rh, tok  # the blocking boundary already delivered
        from ..ops._async import p2p_wait

        got, tok = p2p_wait(rh, token=tok)
        self._mark()
        return got, tok

    def _boundary_send_finish(self, sh, tok):
        if sh is None:
            return tok
        from ..ops._async import p2p_wait

        _, tok = p2p_wait(sh, token=tok)
        return tok

    def _advance(self, h, got, feed):
        import jax.numpy as jnp

        rank = self.comm.Get_rank()
        v = self.plan.virtual
        # chunk c's input: the upstream stage's output — got[c] from
        # rank r-1, except rank 0 where the ring delivers the last
        # rank's chunk c-1 (and chunk 0 eats the fresh microbatch)
        shifted = jnp.concatenate([feed[None], got[:-1]], axis=0) \
            if v > 1 else feed[None]
        inp = jnp.where(rank == 0, shifted, got)
        return jnp.stack([self._chunk_fn(c)(inp[c]) for c in range(v)])

    def _tick_py(self, t: int, h, out, tok):
        import jax.numpy as jnp

        plan = self.plan
        p = plan.stages * plan.virtual
        sh, rh, tok = self._boundary_starts(h, tok)
        # inside the recv span: the fresh-microbatch gather never
        # touches the wire, so it overlaps the boundary transfer
        feed = self.mbs[t] if t < plan.microbatches \
            else jnp.zeros_like(self.mbs[0])
        got, tok = self._boundary_recv(rh, tok)
        h = self._advance(h, got, feed)
        tok = self._boundary_send_finish(sh, tok)
        if t >= p - 1:
            out = out.at[t - (p - 1)].set(h[plan.virtual - 1])
        return h, out, tok

    def _tick_traced(self, t, h, out, tok):
        from jax import lax

        plan = self.plan
        p = plan.stages * plan.virtual
        sh, rh, tok = self._boundary_starts(h, tok)
        feed = lax.dynamic_index_in_dim(self.mbs, t, 0, keepdims=False)
        got, tok = self._boundary_recv(rh, tok)
        h = self._advance(h, got, feed)
        tok = self._boundary_send_finish(sh, tok)
        out = lax.dynamic_update_index_in_dim(out, h[plan.virtual - 1],
                                              t - (p - 1), 0)
        return h, out, tok

    def run(self, lo: int, hi: int, h, out, tok, *, use_megastep: bool):
        if hi <= lo:
            return h, out, tok
        if use_megastep and hi - lo > 1:
            from .megastep import megastep_loop

            def one(i, carry):
                hh, oo = carry
                hh, oo, _ = self._tick_traced(i + lo, hh, oo, None)
                return hh, oo

            h, out = megastep_loop(
                one, (h, out), hi - lo, self.comm,
                label=f"pipeline[{self.plan.schedule}]")
            return h, out, tok
        for t in range(lo, hi):
            h, out, tok = self._tick_py(t, h, out, tok)
        return h, out, tok


def _block_for_timing(*outs):
    """Sync the phase outputs before the bracket's end timestamp: JAX
    dispatch is async, so without a device sync the bracket would time
    the dispatch, not the execution, and the measured bubble fraction
    in ``telemetry.report()`` would be fiction.  Blocks only while
    telemetry is collecting — 'off' keeps the phases fully async."""
    from ..telemetry import core as tcore

    if tcore.effective_mode() == "off":
        return outs
    import jax

    return jax.block_until_ready(outs)


def _phase_bracket(comm, plan: PhasePlan, phase: str, nbytes: int):
    """Serving-style host bracket around one phase dispatch: a
    ``pipeline.{phase}`` row in the per-op table, a latency sample, and
    the integer-microsecond bubble/stage meters ``telemetry.report()``
    folds into the measured bubble fraction."""
    import contextlib

    from ..telemetry import core as tcore

    @contextlib.contextmanager
    def bracket():
        if tcore.effective_mode() == "off":
            yield
            return
        key = tcore.op_key(f"pipeline.{phase}", comm.uid,
                           plan.schedule, "")
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            tcore.count_host_op(key, nbytes)
            tcore.record_latency(key, dt)
            tcore.meter(f"pipeline.{phase}_us", max(0, int(dt * 1e6)))
            if phase == "stage":
                tcore.meter("pipeline.rounds")

    return bracket()


def pipeline(stage_fns: StageFns, n_microbatches: Optional[int] = None,
             schedule: str = "auto", *, virtual: Optional[int] = None,
             comm=None, megastep: bool = True) -> PipelineProgram:
    """Compile a pipeline-parallel round over the comm's ranks (one
    stage per rank; ``virtual`` stage-chunks per rank under the
    interleaved schedule).  See docs/pipeline.md.

    ``stage_fns`` is one ``f(h, params)`` callable (with ``virtual=v >
    1`` every params leaf carries a leading chunk axis) or a sequence of
    per-chunk callables.  ``schedule`` is ``'auto'`` (the cost model
    picks — tuned parameters when a tuning file is active), ``'gpipe'``,
    ``'1f1b'``, or ``'interleaved'``.  A program carrying ``v >= 2``
    stage-chunks per rank can only express the interleaved schedule:
    gpipe/1f1b apply one stage fn per rank, so requesting them raises
    (and ``'auto'`` only prices interleaved) rather than silently
    dropping chunks.  ``megastep=False`` keeps the steady state
    Python-unrolled (debugging; the compiled program is the point).
    """
    return PipelineProgram(stage_fns, n_microbatches, schedule, virtual,
                           comm, megastep)
