"""Static rank-routing specifications for point-to-point ops.

In the reference, each MPI process passes its own ``source``/``dest`` integers
to ``send``/``recv``/``sendrecv`` (ref: mpi4jax/_src/collective_ops/send.py:41,
recv.py:43, sendrecv.py:46).  Under SPMD one traced program describes *all*
ranks at once, so routing must be given as a static description of the whole
pattern, which lowers to a single ``CollectivePermute``.  A ``RankSpec`` is
any of:

- ``shift(k)`` / ``shift(k, wrap=False)`` — ring / edge-stopping shift, the
  halo-exchange workhorse;
- a dict ``{src_rank: dst_rank}``;
- a list of ``(src, dst)`` pairs (ppermute-style);
- a callable ``rank -> Optional[dst]``;
- ``None`` — derived from the matching send/recv side.

Wildcards (``ANY_SOURCE``/``ANY_TAG``, ref recv.py:44-48) do not exist on a
statically-scheduled interconnect; ``recv(source=None)`` instead adopts the
routing of the queued matching ``send`` (see ops/send.py / ops/recv.py), which
covers the reference's default-argument use.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence, Tuple, Union


def _is_tracer(x) -> bool:
    """Duck-typed tracer probe (jax stays an optional import here so the
    routing layer remains loadable in pure-Python contexts)."""
    try:
        import jax.core
    except ImportError:  # pragma: no cover - jax is always present in prod
        return False
    return isinstance(x, jax.core.Tracer)


class shift:
    """Ring (or edge-stopping) shift pattern: rank ``r`` sends to ``r + k``.

    ``wrap=True`` (default) wraps modulo the comm size, giving a ring.
    ``wrap=False`` drops out-of-range endpoints: the halo-exchange pattern at
    domain boundaries (ref examples/shallow_water.py:228-263 sends only where
    a neighbor exists).
    """

    def __init__(self, k: int, *, wrap: bool = True):
        self.k = int(k)
        self.wrap = bool(wrap)

    def __call__(self, r: int, size: int) -> Optional[int]:
        d = r + self.k
        if self.wrap:
            return d % size
        return d if 0 <= d < size else None

    def inverse(self) -> "shift":
        return shift(-self.k, wrap=self.wrap)

    def __repr__(self):
        return f"shift({self.k}{'' if self.wrap else ', wrap=False'})"


RankSpecLike = Union[
    shift,
    Dict[int, int],
    Sequence[Tuple[int, int]],
    Callable[[int], Optional[int]],
    None,
]


def normalize_dest(spec: RankSpecLike, size: int, *,
                   what: str) -> Tuple[Tuple[int, int], ...]:
    """Normalize a routing spec into a sorted tuple of (src, dst) pairs.

    Validates that the pairs form a partial permutation (no duplicate sources
    or destinations) — the contract ``CollectivePermute`` requires.
    """
    from ..analysis.report import mpx_error

    if spec is None:
        raise ValueError(
            f"{what}: routing spec is required here (got None). Under SPMD, "
            "point-to-point routing describes all ranks at once; use "
            "shift(k), a {src: dst} dict, or [(src, dst), ...] pairs."
        )
    if _is_tracer(spec):
        raise mpx_error(
            TypeError, "MPX104",
            f"{what}: routing spec was a JAX tracer. Routing is structure — "
            "it must be static Python values known at trace time (one SPMD "
            "program serves all ranks); if you are passing it through jit, "
            "mark it static (static_argnums).",
        )
    from ..analysis.schedule import is_rank_concrete

    if is_rank_concrete(spec):
        # the cross-rank verifier's concretized rank: structure must stay
        # rank-uniform even in a per-rank re-trace (the traced-rank form
        # of this mistake raises the same code just above)
        raise mpx_error(
            TypeError, "MPX104",
            f"{what}: routing spec is the comm rank (concretized for "
            "per-rank analysis). Routing is structure — it must be "
            "rank-uniform static values describing the whole pattern "
            "(pairs/shift/dict), not a per-rank destination.",
        )
    if isinstance(spec, int):
        raise mpx_error(
            TypeError, "MPX103",
            f"{what}: a bare int rank is ambiguous under SPMD (every rank "
            "executes the same program, so 'dest=1' would mean all ranks send "
            "to rank 1 — not a valid permutation). Describe the full pattern: "
            "pairs=[(0, 1)] for a single message, shift(k) for rings, or a "
            "{src: dst} dict.",
        )
    pairs: List[Tuple[int, int]]
    if isinstance(spec, shift):
        pairs = []
        for r in range(size):
            d = spec(r, size)
            if d is not None:
                pairs.append((r, d))
    elif isinstance(spec, dict):
        pairs = [(int(s), int(d)) for s, d in spec.items()]
    elif callable(spec):
        pairs = []
        for r in range(size):
            d = spec(r)
            if d is not None:
                pairs.append((r, int(d)))
    else:
        pairs = [(int(s), int(d)) for (s, d) in spec]

    srcs = [s for s, _ in pairs]
    dsts = [d for _, d in pairs]
    for v, role in ((srcs, "source"), (dsts, "destination")):
        if len(set(v)) != len(v):
            raise ValueError(
                f"{what}: duplicate {role} ranks in routing {pairs}; "
                "point-to-point routing must be a (partial) permutation"
            )
    for v in srcs + dsts:
        if not (0 <= v < size):
            raise ValueError(f"{what}: rank {v} out of range for comm size {size}")
    return tuple(sorted(pairs))


def normalize_source(spec: RankSpecLike, size: int, *,
                     what: str) -> Tuple[Tuple[int, int], ...]:
    """Like ``normalize_dest`` but the spec is receiver-centric:
    ``spec(r) = source of rank r``.  Returns (src, dst) pairs."""
    if isinstance(spec, shift):
        # receiving from r+k  <=>  r+k sends to r
        inv = spec.inverse()
        return normalize_dest(inv, size, what=what)
    if isinstance(spec, dict):
        return normalize_dest(
            {int(s): int(r) for r, s in spec.items()}, size, what=what)
    if spec is None or isinstance(spec, int):
        return normalize_dest(spec, size, what=what)  # raises with guidance
    if callable(spec):
        pairs = {}
        for r in range(size):
            s = spec(r)
            if s is not None:
                pairs[int(s)] = r
        return normalize_dest(pairs, size, what=what)
    # sequence of (dst, src)? — for sequences we require (src, dst) pairs
    # directly, same as dest specs, to avoid silent transposition bugs.
    return normalize_dest(spec, size, what=what)


def invert_pairs(pairs: Sequence[Tuple[int, int]]) -> Tuple[Tuple[int, int], ...]:
    return tuple(sorted((d, s) for s, d in pairs))


def resolve_routing(comm, source, dest, *, what: str
                    ) -> Tuple[Tuple[int, int], ...]:
    """Normalize ``source``/``dest`` specs to GLOBAL (src, dst) pairs over
    ``comm``'s mesh axes — the single resolution point for every
    point-to-point op.

    Give either spec (the other is inferred) or both (validated for
    consistency).  On a color-split comm each group normalizes the spec at
    ITS OWN size and maps through the static member tables, so
    ``shift``/callable specs route correctly on UNEQUAL group sizes too
    (each group gets its own ring/edge pattern); a dict/pairs spec naming
    a rank a group doesn't have raises that group's out-of-range error.
    """

    def norm(size):
        pairs_d = (normalize_dest(dest, size, what=what)
                   if dest is not None else None)
        pairs_s = (normalize_source(source, size, what=what)
                   if source is not None else None)
        if pairs_d is not None and pairs_s is not None and pairs_d != pairs_s:
            raise ValueError(
                f"{what}: inconsistent routing — dest spec gives pairs "
                f"{pairs_d} but source spec gives pairs {pairs_s}"
            )
        if pairs_d is None and pairs_s is None:
            raise ValueError(
                f"{what}: provide a routing spec via dest= and/or source= "
                "(e.g. dest=shift(1) for a ring)"
            )
        return pairs_d if pairs_d is not None else pairs_s

    groups = comm.groups
    if groups is None:
        return tuple(comm.expand_pairs(norm(comm.Get_size())))
    out = []
    for members in groups:
        out.extend((members[s], members[d]) for s, d in norm(len(members)))
    return tuple(sorted(out))
