"""Parallel regions: the SPMD execution surface.

The reference runs one OS process per rank (``mpirun``), and every op executes
against the process-global MPI state.  The TPU-native model traces ONE program
for all ranks with ``jax.shard_map`` over a device mesh; a *parallel region*
is that traced body.  This module provides:

- ``spmd(...)`` — decorator turning a per-rank function into a jitted
  ``shard_map`` over a comm's mesh (global arrays carry a leading rank axis);
- the trace-time region context that (a) supplies the default communicator to
  ops called with ``comm=None`` and (b) holds the send/recv matching queues
  (see ops/send.py);
- ``run(fn, *args)`` — one-shot form of ``spmd``.

Because the region is a single program, every rank observes the same schedule
of collectives — the deadlock class the reference's token machinery exists to
prevent (ref docs/sharp-bits.rst, tests/collective_ops/test_send_and_recv.py:91-110
"this deadlocks without proper token management") cannot occur by construction.
Tokens are still honored: they pin the *relative order* of collectives through
``optimization_barrier`` data dependencies (see ops/token.py).
"""

from __future__ import annotations

import functools
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

import jax
from jax.sharding import PartitionSpec as P

from .comm import Comm
from .mesh import DEFAULT_AXIS, get_default_mesh


class RegionContext:
    """Trace-time state for one parallel region."""

    def __init__(self, comm: Comm):
        self.comm = comm
        # (comm_uid, tag) -> deque of pending _PendingSend (see ops/send.py)
        self.send_queues: Dict[Tuple[int, int], deque] = {}
        # implicit ordering handle for the tokenless API (the ordered-effects
        # analog, ref notoken abstract evals declare {ordered_effect}): a
        # tokenless barrier deposits its token here; the next op (or the
        # region's outputs) consumes it, so the synchronizing collective is
        # never dead-code-eliminated and subsequent ops are ordered after it.
        self.pending_sync = None
        # env-mode collective verifier sink, armed by analysis.hook when
        # MPI4JAX_TPU_ANALYZE != off (None otherwise — zero overhead)
        self.analysis_recorder = None
        # pending adjacent-collective fusion queue (ops/_fusion.py), only
        # ever non-None while MPI4JAX_TPU_FUSION is auto/force; drained by
        # any non-joining dispatch and at region exit
        self.fusion_queue = None

    def queue(self, comm_uid: int, tag: int) -> deque:
        return self.send_queues.setdefault((comm_uid, tag), deque())

    def check_drained(self) -> None:
        leftover = {k: len(q) for k, q in self.send_queues.items() if q}
        if leftover:
            from ..analysis.report import mpx_error

            raise mpx_error(
                RuntimeError, "MPX101",
                f"parallel region ended with unmatched send(s): "
                f"{{(comm_uid, tag): count}} = {leftover}. Every send must be "
                "matched by a recv on the same comm and tag within the same "
                "region (matching is FIFO per (comm, tag); the SPMD analog "
                "of the reference's matched-pair requirement).",
            )


_region_stack: List[RegionContext] = []

# Fallback context for ops used inside a *user's own* shard_map (no spmd
# wrapper). Queues here are keyed the same way; staleness across traces is
# caught by JAX's leaked-tracer errors.
_global_ctx = RegionContext(comm=None)  # type: ignore[arg-type]

_default_comm: Optional[Comm] = None


def current_context() -> RegionContext:
    return _region_stack[-1] if _region_stack else _global_ctx


def get_default_comm() -> Comm:
    """The world communicator (analog of ref ``get_default_comm``,
    mpi4jax/_src/comm.py:4-11): inside a region, the region's comm; outside,
    a cached comm over the default world mesh."""
    ctx = current_context()
    if ctx.comm is not None:
        return ctx.comm
    global _default_comm
    if _default_comm is None:
        _default_comm = Comm(DEFAULT_AXIS, mesh=get_default_mesh())
    return _default_comm


def resolve_comm(comm: Optional[Comm]) -> Comm:
    return comm if comm is not None else get_default_comm()


def region_axes_spec(c: Comm):
    """The default PartitionSpec of a comm's region: global arrays carry
    a leading axis sharded over the comm's mesh axes."""
    return P(c.axes if len(c.axes) > 1 else c.axes[0])


def make_region_body(f, c: Comm, statics, static_vals, kw_names, n_dyn,
                     squeeze_in: bool, squeeze_out: bool, unroll: int = 1):
    """Build the per-rank region body ``spmd`` traces: argument
    re-interleaving, the region context push/pop, fusion drain, pending
    tokenless-barrier tie-in, and the trace-time verifier hooks.

    Shared by the ``spmd`` program cache (below) and the AOT pinning
    layer (``mpi4jax_tpu/aot/pinning.py``), so a pinned program traces
    the IDENTICAL body a cached ``spmd`` program would — same HLO, same
    jaxpr fingerprint, same persistent-cache artifact.

    ``unroll > 1`` rewrites the body into a device-resident megastep
    loop (parallel/megastep.py): the dynamic positional arguments become
    the ``lax.fori_loop`` carry and ``f`` runs once per iteration — one
    host dispatch executes ``unroll`` steps.  ``f`` must map its dynamic
    arguments to a like-structured pytree (the carry contract;
    docs/aot.md "Megastep execution").  ``unroll == 1`` keeps the exact
    single-step body — trace and HLO byte-identical to before the
    megastep layer existed.
    """

    def body(*a):
        from ..analysis import hook as _analysis

        ctx = RegionContext(c)
        _analysis.arm_context(ctx)
        _region_stack.append(ctx)
        try:
            if squeeze_in:
                a = jax.tree.map(lambda v: v[0], a)
            pos, kwvals = a[:n_dyn], a[n_dyn:]
            kw = dict(zip(kw_names, kwvals))
            # re-interleave the closed-over static args
            full = list(pos)
            for i, v in zip(statics, static_vals):
                full.insert(i, v)
            if unroll > 1:
                from .megastep import megastep_loop

                label = getattr(f, "__name__", "fn")

                def one(_i, carry):
                    it_full = list(carry)
                    for si, v in zip(statics, static_vals):
                        it_full.insert(si, v)
                    r = f(*it_full)
                    if n_dyn == 1:
                        return (r,)
                    if (not isinstance(r, (tuple, list))
                            or len(r) != n_dyn):
                        raise ValueError(
                            f"megastep carry contract violated in "
                            f"{label!r}: with unroll={unroll} and "
                            f"{n_dyn} dynamic arguments the step must "
                            f"return a matching {n_dyn}-tuple of new "
                            "states, got "
                            f"{type(r).__name__} (docs/aot.md "
                            "'Megastep execution')"
                        )
                    return tuple(r)

                final = megastep_loop(one, tuple(pos), unroll, c,
                                      label=label)
                out = final[0] if n_dyn == 1 else final
            else:
                out = f(*full, **kw)
            # drain the fusion queue and force any deferred
            # results: region outputs must be real arrays
            # before they cross the shard_map boundary
            from ..ops import _fusion

            _fusion.flush_pending(ctx)
            out = _fusion.materialize_tree(out)
            if ctx.pending_sync is not None:
                # a trailing tokenless barrier: tie it into the
                # region outputs so it is not dead-code-eliminated
                from ..ops.token import tie

                sync = ctx.pending_sync
                ctx.pending_sync = None
                out = jax.tree.map(lambda v: tie(sync, v), out)
            if squeeze_out:
                out = jax.tree.map(lambda v: v[None], out)
            ctx.check_drained()
            _analysis.finish_context(
                ctx, f"spmd region {getattr(f, '__name__', f)!s}"
            )
            return out
        finally:
            _region_stack.pop()

    return body


def spmd(
    fn=None,
    *,
    comm: Optional[Comm] = None,
    in_specs: Any = None,
    out_specs: Any = None,
    jit: bool = True,
    static_argnums=(),
    unroll: Optional[int] = None,
):
    """Turn a per-rank function into an SPMD program over ``comm``'s mesh.

    The wrapped function sees rank-local arrays; global inputs/outputs carry a
    leading rank axis by default (``in_specs=P(axis)``), matching the
    convention that rank ``r``'s local value is ``global[r]``.  Custom
    ``in_specs``/``out_specs`` follow ``jax.shard_map``.

    Inside the body, ops called with ``comm=None`` use this region's comm, and
    ``send``/``recv`` matching is scoped to the region.

    ``unroll=N`` (N > 1) compiles a **megastep**: the body becomes a
    device-resident ``lax.fori_loop`` over N iterations with the dynamic
    positional arguments as the carry, so one host call runs N steps
    (docs/aot.md "Megastep execution").  The step must map its dynamic
    arguments to a like-structured pytree, and keyword arguments are not
    accepted in megastep mode.  ``None`` (default) resolves
    ``MPI4JAX_TPU_UNROLL_DEFAULT`` (1 = off — body and HLO unchanged).
    """

    def wrap(f):
        # One compiled program per (mesh, comm) — built lazily on first call
        # and reused, so host loops over an spmd function hit the jit cache
        # instead of re-tracing every iteration.
        program_cache = {}

        # normalize like jax.jit: accept a bare int, sort ascending (the
        # re-interleaving insert below requires ascending order); negative
        # indices are resolved against the actual call arity per call
        if static_argnums is None:
            statics_raw = ()
        elif isinstance(static_argnums, int):
            statics_raw = (static_argnums,)
        else:
            statics_raw = tuple(static_argnums)

        @functools.wraps(f)
        def wrapped(*args, **kwargs):
            c = resolve_comm(comm)
            if c.mesh is None:
                raise RuntimeError(
                    "spmd requires a comm bound to a mesh (comm.bind(mesh)) "
                    "or an available default mesh"
                )
            # static args are closed over (they never enter shard_map, whose
            # in_specs only describe arrays); the cache is keyed on their
            # values, mirroring jit's static_argnums semantics
            statics = tuple(sorted({
                i if i >= 0 else i + len(args) for i in statics_raw
            }))
            for i in statics:
                if not 0 <= i < len(args):
                    # like jax.jit: a static argument supplied by keyword is
                    # a dedicated error, not a confusing out-of-range one
                    import inspect

                    try:
                        names = list(inspect.signature(f).parameters)
                    except (TypeError, ValueError):
                        names = []
                    if 0 <= i < len(names) and names[i] in kwargs:
                        raise TypeError(
                            f"spmd static argument {names[i]!r} "
                            f"(static_argnums position {i}) was passed as a "
                            "keyword; pass it positionally"
                        )
                    raise ValueError(
                        f"static_argnums entry {i} out of range for "
                        f"{len(args)} positional arguments"
                    )
            static_vals = tuple(args[i] for i in statics)
            try:
                hash(static_vals)
            except TypeError as e:
                raise TypeError(
                    f"spmd static argument values must be hashable (like "
                    f"jax.jit static_argnums); got {static_vals!r}"
                ) from e
            dyn_args = tuple(a for i, a in enumerate(args) if i not in statics)
            # shard_map is positional-only: keyword arrays are appended as
            # trailing positionals (sorted by name) and rebound in the body
            kw_names = tuple(sorted(kwargs))
            if kw_names and in_specs is not None:
                raise TypeError(
                    "spmd with custom in_specs takes positional arguments "
                    f"only (got keyword argument(s) {kw_names}); in_specs "
                    "entries cannot be matched to keywords"
                )
            n_dyn = len(dyn_args)
            from .megastep import validate_unroll

            if unroll is not None:
                n_unroll = validate_unroll(unroll)
            else:
                from ..utils.config import unroll_default

                n_unroll = unroll_default()
            if n_unroll > 1 and (kw_names or n_dyn == 0):
                # only an EXPLICIT unroll= is a contract error here: a
                # fleet-wide MPI4JAX_TPU_UNROLL_DEFAULT must not break
                # unrelated programs that cannot carry a megastep loop —
                # those degrade to the single-step body
                if unroll is None:
                    n_unroll = 1
                elif kw_names:
                    raise TypeError(
                        "spmd(unroll=N) takes positional arguments only "
                        f"(got keyword argument(s) {kw_names}): the "
                        "megastep carry is the dynamic positional tuple"
                    )
                else:
                    raise ValueError(
                        "spmd(unroll=N) needs at least one dynamic "
                        "argument to carry through the device-resident "
                        "loop"
                    )
            # every dynamically-read flag that shapes the trace must be in
            # the key (mirrors _eager_cache in ops/_base.py), or toggling
            # tracing/logging/prefer_notoken after the first call would
            # silently keep serving the stale compiled program.  The flag
            # half comes pre-parsed and hash-cached from the dispatch fast
            # path (ops/_base.dynamic_cache_token): a warm call re-parses
            # no environment flags.
            from ..ops._base import _dynamic_state
            from ..telemetry import core as _telemetry

            dyn_token, analysis_off, _ = _dynamic_state()
            key = (c.mesh, c.uid, statics, static_vals, kw_names, n_dyn,
                   n_unroll, dyn_token)
            sm = program_cache.get(key)
            if not analysis_off:
                # ambient cross-rank pass (analysis/crossrank.py): runs
                # per CALL, not per program-cache miss — jit retraces
                # internally on new argument shapes without missing this
                # cache, and a shape-dependent rank-divergent path must
                # still be verified before it compiles.  Memoized by
                # avals + config inside, so warm calls cost one memo
                # lookup; with the verifier off (the default) this
                # branch is a single memoized-flag test.
                from ..analysis import crossrank as _crossrank

                _crossrank.verify_region_crossrank(
                    f, comm=comm, in_specs=in_specs, out_specs=out_specs,
                    static_argnums=statics_raw, c=c, args=args,
                    kwargs=kwargs)
            if sm is not None:
                _telemetry.meter("spmd_cache.hits")
            else:
                # per-function recompile meter: a retrace storm (e.g. a
                # flag flapping per step, or unhashed static args) shows
                # up as a climbing recompiles.spmd.<name> count
                _telemetry.meter("spmd_cache.misses")
                _telemetry.meter(
                    f"recompiles.spmd.{getattr(f, '__name__', 'fn')}"
                )
            if sm is None:
                axes_spec = region_axes_spec(c)
                ispecs = in_specs if in_specs is not None else axes_spec
                ospecs = out_specs if out_specs is not None else axes_spec
                # Default-spec convention: a global array is
                # (size, *local_shape), global[r] being rank r's value — so
                # the body sees true local shapes, we squeeze the sharded
                # leading axis on the way in and restore it on the way out.
                # Custom specs disable this.
                body = make_region_body(
                    f, c, statics, static_vals, kw_names, n_dyn,
                    squeeze_in=in_specs is None,
                    squeeze_out=out_specs is None,
                    unroll=n_unroll,
                )
                sm = jax.shard_map(
                    body, mesh=c.mesh, in_specs=ispecs, out_specs=ospecs
                )
                if jit:
                    sm = jax.jit(sm)
                    # the persistent tier (docs/aot.md): with
                    # MPI4JAX_TPU_COMPILE_CACHE_DIR set, a program-cache
                    # MISS consults the on-disk compiled-program cache
                    # before XLA re-lowers — a multi-host cold start
                    # deserializes identical SPMD programs instead of
                    # compiling them on every rank.  Unset (default),
                    # the jitted program is used as-is: keys and HLO
                    # byte-identical to a build without the AOT layer.
                    from ..utils.config import compile_cache_dir

                    if compile_cache_dir():
                        from ..aot import pinning as _pinning

                        sm = _pinning.through_disk_cache(
                            sm, c, label=getattr(f, "__name__", "fn"))
                program_cache[key] = sm
            return sm(*dyn_args, *(kwargs[k] for k in kw_names))

        # breadcrumbs for mpx.analyze: it rebuilds an UN-jitted twin from
        # the underlying per-rank function, because jit's trace cache
        # would otherwise serve a cached jaxpr and record no events
        wrapped._mpx_spmd = True
        wrapped._mpx_fn = f
        wrapped._mpx_spmd_kwargs = dict(
            comm=comm, in_specs=in_specs, out_specs=out_specs,
            static_argnums=statics_raw, unroll=unroll,
        )
        return wrapped

    if fn is not None:
        return wrap(fn)
    return wrap


def run(f, *args, comm: Optional[Comm] = None, **spmd_kwargs):
    """One-shot ``spmd``: ``run(f, x)`` == ``spmd(f, ...)(x)``."""
    return spmd(comm=comm, **spmd_kwargs)(f)(*args)


def in_parallel_region(comm: Comm) -> bool:
    """True if the comm's axes are bound in the current trace (i.e. we are
    inside a shard_map body over those axes)."""
    from ..utils.jax_compat import axis_bound

    return all(axis_bound(a) for a in comm.axes)
