"""The host-topology model: where ranks live.

The algorithm layer added in ``ops/_algos.py`` selects ring vs butterfly
from payload bytes alone — it is blind to *where* ranks live.  On a
multi-host pod that matters enormously: devices on one host talk over ICI
(fast, low-latency links), devices on different hosts talk over DCN (the
data-center network, roughly an order of magnitude more per-hop latency
and less bandwidth).  A flat ring over a 2-host pod serializes every DCN
hop behind the slowest ICI step.

This module derives a :class:`Topology` — the static host partition of a
communicator's flat rank space — from either:

- the **JAX process layout** of the comm's bound mesh: device ``d``'s
  ``process_index`` says which host owns it (``init_distributed`` /
  ``make_world_mesh`` already arrange the global device order so that
  processes own contiguous blocks where possible); or
- the **``MPI4JAX_TPU_TOPOLOGY`` override** (declared in the
  ``utils/config.py`` flag registry): ``<hosts>x<ranks_per_host>`` (e.g.
  ``2x4``) or comma-separated per-host counts (``3,5``) — the test and
  heterogeneous-cluster knob, and how the CI topology lane fakes a
  2-host pod on the 8-device virtual CPU mesh.

Host ids are *canonical* (renumbered by first appearance in flat rank
order), so two meshes with the same co-location pattern but different
process ids compare equal — the hierarchical lowerings only care about
the partition, never the physical ids.  The topology's fingerprint is
hashable and folds into ``ops/_algos.algo_cache_token()`` (via the raw
spec) and both compiled-program cache keys, so changing topology
retraces like every other knob (docs/topology.md).

Derivation is best-effort by design: whenever the host partition cannot
be established (unbound comm outside a trace, a spec whose rank count
does not match this comm's world, a mesh whose axis slabs disagree on
the co-location pattern), ``derive_world_topology`` returns ``None`` and
the caller keeps the flat single-level algorithms — topology support
never turns a working program into an error.

Besides the hierarchical lowerings, the elastic layer consumes this
partition for *placement*: ``resilience/elastic.stripe_placement``
stripes every shard replica onto a different host than its owner, so a
whole-host loss stays recoverable (docs/resilience.md "Replica
placement").  The same best-effort convention applies — no derivable
topology means the stripe degrades to the neighbor ring, never an
error.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

from ..utils import config


def canonical_labels(raw: Sequence) -> Tuple[int, ...]:
    """Renumber arbitrary host labels by first appearance: ``(7, 7, 3)``
    -> ``(0, 0, 1)``.  The hierarchical lowerings depend only on the
    partition pattern, so canonical labels make topologies comparable
    (and cache keys stable) across physical process ids."""
    seen: dict = {}
    out = []
    for x in raw:
        if x not in seen:
            seen[x] = len(seen)
        out.append(seen[x])
    return tuple(out)


class Topology:
    """The static host partition of a flat rank space.

    ``host_of_rank[r]`` is the canonical host index of flat rank ``r``
    (the row-major rank order of the comm's mesh axes — the same order
    ``Comm.Get_rank`` defines).
    """

    __slots__ = ("host_of_rank",)

    def __init__(self, host_of_rank: Sequence[int]):
        self.host_of_rank = canonical_labels(host_of_rank)

    @property
    def num_hosts(self) -> int:
        return len(set(self.host_of_rank)) if self.host_of_rank else 0

    @property
    def ranks_per_host(self) -> Tuple[int, ...]:
        """Rank count per host, in host order (the shape the elastic
        stripe placement and the hierarchical plans both consume)."""
        counts: dict = {}
        for h in self.host_of_rank:
            counts[h] = counts.get(h, 0) + 1
        return tuple(counts[h] for h in sorted(counts))

    def fingerprint(self) -> tuple:
        """Hashable identity for cache keys and plan memos."""
        return self.host_of_rank

    def __eq__(self, other):
        return (isinstance(other, Topology)
                and self.host_of_rank == other.host_of_rank)

    def __hash__(self):
        return hash(self.host_of_rank)

    def __repr__(self):
        return (f"Topology(num_hosts={self.num_hosts}, "
                f"ranks_per_host={self.ranks_per_host})")


def span_hosts(host_of_rank: Sequence[int],
               members: Sequence[int]) -> int:
    """How many hosts ``members`` (world-linear ranks) span under
    ``host_of_rank`` — the link-class discriminator the static cost
    model (analysis/costmodel.py) shares with the hierarchical plan
    geometry: a group spanning one host prices on the ICI class, a
    multi-host group on DCN."""
    return len({host_of_rank[m] for m in members}) if members else 0


def link_class(host_of_rank: Optional[Sequence[int]], a: int,
               b: int) -> str:
    """Link class of the (a, b) rank pair: ``"dcn"`` when the two live
    on different hosts, ``"ici"`` otherwise (including when no topology
    is derivable — the flat-fallback convention everywhere else)."""
    if host_of_rank is None:
        return "ici"
    return "ici" if host_of_rank[a] == host_of_rank[b] else "dcn"


def from_counts(counts: Sequence[int]) -> Topology:
    """Topology from per-host rank counts: ``(3, 5)`` -> ranks 0-2 on
    host 0, ranks 3-7 on host 1."""
    host_of_rank = []
    for h, c in enumerate(counts):
        host_of_rank.extend([h] * c)
    return Topology(host_of_rank)


# memoized: derivation walks the device list / parses the spec, and it
# runs once per traced collective (LRU-bounded — mesh keys pin meshes)
from collections import OrderedDict

_topo_memo: "OrderedDict" = OrderedDict()
_TOPO_MEMO_MAX = 64
_NO_TOPO = object()


def derive_world_topology(comm) -> Optional[Topology]:
    """The host partition of ``comm``'s flat (full-axes) rank space, or
    ``None`` when it cannot be established (the caller falls back to the
    flat algorithms — never an error).

    Priority: the ``MPI4JAX_TPU_TOPOLOGY`` spec when its total rank count
    matches this comm's world (a mismatched spec — e.g. a world spec seen
    by a smaller sub-comm — yields ``None`` for that comm); otherwise the
    bound mesh's JAX process layout.
    """
    spec = config.topology_spec()
    if spec:
        try:
            world = comm.world_size()
        except RuntimeError:  # unbound comm outside any trace
            return None
        key = ("spec", spec, world)
    else:
        mesh = comm.mesh
        if mesh is None:
            return None
        key = ("mesh", mesh, comm.axes)
    cached = _topo_memo.get(key)
    if cached is not None:
        _topo_memo.move_to_end(key)
        return None if cached is _NO_TOPO else cached
    if spec:
        counts = config.parse_topology_spec(spec)
        topo = from_counts(counts) if sum(counts) == world else None
    else:
        topo = mesh_topology(mesh, comm.axes)
    _topo_memo[key] = _NO_TOPO if topo is None else topo
    if len(_topo_memo) > _TOPO_MEMO_MAX:
        _topo_memo.popitem(last=False)
    return topo


def mesh_topology(mesh, axes: Tuple[str, ...]) -> Optional[Topology]:
    """Host partition of the flat rank space over ``axes`` of ``mesh``,
    from each device's ``process_index``.

    The flat rank order is row-major over ``axes`` (matching
    ``Comm.Get_rank``).  For a comm over a *subset* of the mesh axes, one
    comm rank maps to many devices (one per remaining-axes coordinate);
    the SPMD program is shared, so a topology exists only when every
    remaining-axes slab exhibits the SAME canonical co-location pattern —
    otherwise ``None`` (flat fallback).
    """
    import numpy as np

    names = tuple(mesh.axis_names)
    if any(a not in names for a in axes):
        return None
    devs = np.asarray(mesh.devices)
    order = [names.index(a) for a in axes] + [
        i for i, n in enumerate(names) if n not in axes
    ]
    arr = np.transpose(devs, order)
    k = 1
    for a in axes:
        k *= mesh.shape[a]
    arr = arr.reshape(k, -1)
    patterns = {
        canonical_labels(
            [getattr(d, "process_index", 0) for d in arr[:, j]]
        )
        for j in range(arr.shape[1])
    }
    if len(patterns) != 1:
        return None
    return Topology(patterns.pop())
