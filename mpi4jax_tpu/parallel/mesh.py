"""Runtime bootstrap: device meshes and multi-host initialization.

TPU-native replacement for the reference's process bootstrap
(ref: ``mpirun -n N python`` + implicit ``MPI_Init`` on ``import mpi4py``,
mpi4jax/_src/__init__.py:1-3).  Here the launch model is plain ``python``:

- single host: all local devices form the mesh;
- multi-host (TPU pod slices): ``init_distributed()`` wraps
  ``jax.distributed.initialize`` — process coordination over DCN, collectives
  over ICI — then the *global* device list forms the mesh.

Device order matters for ring patterns: ``jax.make_mesh`` orders devices so
that neighboring mesh coordinates are ICI-neighbors where possible, which is
what keeps ``shift``-pattern ``CollectivePermute`` on ICI links (the ≥80%
link-bandwidth target in BASELINE.md).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np

DEFAULT_AXIS = "mpi4jax"

_default_mesh: Optional[jax.sharding.Mesh] = None
_distributed_initialized = False


def init_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    *,
    connect_deadline: Optional[float] = None,
    connect_max_attempts: Optional[int] = None,
    connect_base_delay: float = 1.0,
    connect_max_delay: float = 30.0,
    **kwargs,
) -> None:
    """Initialize multi-host JAX (the ``mpirun`` replacement).

    On TPU pods the arguments are auto-detected from the TPU metadata
    environment, so a bare ``init_distributed()`` suffices; on CPU/GPU
    clusters pass coordinator/process info explicitly.  Idempotent.

    The coordinator connection is retried with full-jitter exponential
    backoff (resilience/retry.py): at job start workers race the coordinator
    process, and on preempted pods transient refusals are the norm —
    a worker that gives up on the first ``ConnectionError`` turns routine
    scheduler jitter into a failed job.  ``connect_deadline`` bounds the
    total wait (seconds) and ``connect_max_attempts`` the attempt count;
    both default to the declared flags ``MPI4JAX_TPU_BOOTSTRAP_DEADLINE``
    / ``MPI4JAX_TPU_BOOTSTRAP_MAX_ATTEMPTS`` (utils/config.py) — the same
    policy the elastic re-bootstrap reuses after a shrink
    (resilience/elastic.py).  On expiry a ``RuntimeError`` names the
    attempt count, elapsed time, and last underlying error.
    ``connect_base_delay`` and ``connect_max_delay`` shape the backoff
    (docs/resilience.md).
    """
    global _distributed_initialized
    if _distributed_initialized:
        return

    from ..resilience.retry import retry_with_backoff
    from ..utils import config

    if connect_deadline is None:
        connect_deadline = config.bootstrap_deadline()
    if connect_max_attempts is None:
        connect_max_attempts = config.bootstrap_max_attempts()

    def _connect():
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
            **kwargs,
        )

    retry_with_backoff(
        _connect,
        what="jax.distributed coordinator connection "
             f"({coordinator_address or 'auto-detected'})",
        deadline=connect_deadline,
        max_attempts=connect_max_attempts or None,
        base_delay=connect_base_delay,
        max_delay=connect_max_delay,
        # a second initialize on an already-initialized backend is a
        # programming error, not a transient refusal: retrying it would
        # spin until the deadline on every attempt.  JAX's message is
        # "distributed.initialize should only be called once." (stable
        # wording across releases); match loosely in case it drifts.
        giveup=lambda e: ("already initialized" in str(e)
                          or "only be called once" in str(e)),
    )
    _distributed_initialized = True


def make_world_mesh(
    shape: Optional[Sequence[int]] = None,
    axes: Optional[Sequence[str]] = None,
    *,
    devices=None,
) -> jax.sharding.Mesh:
    """Build a mesh over all (global) devices.

    Default: 1-D mesh named ``"mpi4jax"`` over every device — the analog of
    ``MPI_COMM_WORLD``.  Pass ``shape``/``axes`` for Cartesian grids, e.g.
    ``make_world_mesh((4, 2), ("y", "x"))`` for the shallow-water process
    grid (ref examples/shallow_water.py:57-67).
    """
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    if shape is None:
        shape = (n,)
    if axes is None:
        axes = ((DEFAULT_AXIS,) if len(shape) == 1
                else tuple(f"ax{i}" for i in range(len(shape))))
    if int(np.prod(shape)) != n:
        raise ValueError(f"mesh shape {tuple(shape)} does not cover {n} devices")
    # Auto axis types: global ops outside parallel regions behave classically;
    # collective typing (VMA) still applies inside shard_map bodies.
    return jax.make_mesh(
        tuple(shape),
        tuple(axes),
        axis_types=tuple(jax.sharding.AxisType.Auto for _ in shape),
        devices=devices,
    )


def shrink_world_mesh(
    mesh: jax.sharding.Mesh, failed, fail_unit: str = "rank"
) -> jax.sharding.Mesh:
    """Rebuild ``mesh`` without the devices of the ``failed`` global ranks
    (row-major rank order, the same rank space ``Comm.Get_rank`` defines)
    — the mesh half of an elastic shrink (resilience/elastic.py).

    ``fail_unit`` picks the shrink granularity
    (``MPI4JAX_TPU_ELASTIC_FAIL_UNIT``):

    - ``"rank"`` (default): remove exactly the failed ranks.  1-D meshes
      only — removing arbitrary ranks from a Cartesian grid leaves a
      ragged grid no mesh can express.
    - ``"row"`` / ``"col"``: remove every WHOLE grid row (first axis) or
      column (second axis) containing a failed rank, so 2-D
      (tensor x data) meshes shrink structurally.  On a 1-D mesh a row
      IS a rank, so both degrade to ``"rank"``.

    The caller passes the *expanded* failed set (``elastic
    .expand_fail_unit`` — the same set ``compact_rank_map`` renumbers
    with); this function validates the expansion covers whole rows or
    columns.
    """
    from ..resilience.elastic import expand_fail_unit

    shape = tuple(mesh.shape.values())
    failed = expand_fail_unit(failed, shape, fail_unit)
    devices = list(mesh.devices.flat)
    world = len(devices)
    if len(shape) > 1 and fail_unit == "rank":
        raise ValueError(
            f"shrink_world_mesh: only 1-D meshes can shrink by rank (got "
            f"shape {dict(mesh.shape)}); arbitrary rank removal leaves a "
            "ragged grid — shrink whole grid rows/columns instead "
            "(fail_unit='row'|'col', MPI4JAX_TPU_ELASTIC_FAIL_UNIT; "
            "docs/resilience.md)"
        )
    survivors = [d for r, d in enumerate(devices) if r not in failed]
    if not survivors:
        raise ValueError("shrink_world_mesh: no surviving devices")
    from ..resilience.elastic import shrunken_shape

    new_shape = shrunken_shape(shape, failed, fail_unit)
    assert int(np.prod(new_shape)) == len(survivors), (new_shape, world)
    return make_world_mesh(new_shape, tuple(mesh.axis_names),
                           devices=survivors)


def grow_world_mesh(mesh: jax.sharding.Mesh, added: int) -> jax.sharding.Mesh:
    """Rebuild ``mesh`` with ``added`` more devices appended — the
    single-controller mesh half of an elastic *grow* (a simulated join:
    the devices still exist on the controller, only the mesh shrank).
    1-D meshes only; replacement devices are taken from ``jax.devices()``
    in order, skipping those already in the mesh."""
    shape = tuple(mesh.shape.values())
    if len(shape) != 1:
        raise ValueError(
            f"grow_world_mesh: only 1-D meshes can grow (got shape "
            f"{dict(mesh.shape)}) — docs/resilience.md"
        )
    if added < 1:
        raise ValueError(f"grow_world_mesh: added must be >= 1, got {added}")
    current = list(mesh.devices.flat)
    have = {d.id for d in current}
    spare = [d for d in jax.devices() if d.id not in have]
    if len(spare) < added:
        raise ValueError(
            f"grow_world_mesh: {added} replacement device(s) requested "
            f"but only {len(spare)} available outside the mesh"
        )
    devices = current + spare[:added]
    (axis,) = mesh.axis_names
    return make_world_mesh((len(devices),), (axis,), devices=devices)


def get_default_mesh() -> jax.sharding.Mesh:
    """The lazily-created world mesh (analog of the cached default comm,
    ref mpi4jax/_src/comm.py:4-11)."""
    global _default_mesh
    if _default_mesh is None:
        _default_mesh = make_world_mesh()
    return _default_mesh


def set_default_mesh(mesh: Optional[jax.sharding.Mesh]) -> None:
    global _default_mesh
    _default_mesh = mesh
