"""Communicators as named mesh axes.

TPU-native replacement for the reference's mpi4py communicator handling
(ref: mpi4jax/_src/comm.py:4-11 default ``COMM_WORLD.Clone()``;
mpi4jax/_src/utils.py:80-96 handle marshalling).  An MPI communicator is a
(process group, message-matching namespace); the TPU-native equivalent is a
(set of mesh axes, point-to-point matching namespace):

- the *process group* is the set of devices along the comm's mesh axes;
- collectives over the group are XLA HLO collectives over those axes,
  scheduled on ICI/DCN by the compiler — no channel/tag bookkeeping needed;
- the *matching namespace* only matters for ``send``/``recv`` pairing, which
  this framework matches at trace time per (comm, tag) — so ``Clone()``
  returns a comm with a fresh matching namespace, preserving the reference's
  isolation guarantee (user traffic on a cloned comm can never collide,
  ref docs/sharp-bits.rst:82-143).

A ``Comm`` may be *bound* to a concrete ``jax.sharding.Mesh`` (so it knows its
size statically and can run ops eagerly by auto-wrapping them in
``jax.shard_map``), or *unbound* (axes only — usable inside any user
``shard_map`` that defines those axes).
"""

from __future__ import annotations

import itertools
from typing import Optional, Tuple

import jax
import numpy as np
from jax import lax

_uid_counter = itertools.count()


class Comm:
    """A communicator over one or more mesh axes.

    Parameters
    ----------
    axes:
        Mesh axis name, or sequence of names.  Multiple axes form one flat
        group in row-major order (first axis is slowest-varying), like an MPI
        communicator over a Cartesian grid.
    mesh:
        Optional concrete ``jax.sharding.Mesh`` binding.  Required for eager
        (outside-``shard_map``) execution and for static ``Get_size`` outside
        a trace.
    """

    def __init__(self, axes, *, mesh: Optional[jax.sharding.Mesh] = None):
        if isinstance(axes, str):
            axes = (axes,)
        self._axes: Tuple[str, ...] = tuple(axes)
        if not self._axes:
            raise ValueError("Comm needs at least one mesh axis name")
        self._mesh = mesh
        if mesh is not None:
            missing = [a for a in self._axes if a not in mesh.shape]
            if missing:
                raise ValueError(
                    f"axes {missing} not present in mesh axes {tuple(mesh.shape)}"
                )
        # Unique id = the p2p matching namespace (Clone isolation).
        self._uid = next(_uid_counter)

    # -- structure ---------------------------------------------------------

    @property
    def axes(self) -> Tuple[str, ...]:
        return self._axes

    @property
    def axis(self) -> str:
        """The single axis name; raises for multi-axis comms."""
        if len(self._axes) != 1:
            raise ValueError(
                f"operation requires a single-axis communicator, got axes "
                f"{self._axes}; use comm.sub(axis) to select one axis"
            )
        return self._axes[0]

    @property
    def mesh(self) -> Optional[jax.sharding.Mesh]:
        return self._mesh

    @property
    def uid(self) -> int:
        return self._uid

    def bind(self, mesh: jax.sharding.Mesh) -> "Comm":
        """Return a copy of this comm bound to ``mesh`` (same namespace)."""
        new = Comm(self._axes, mesh=mesh)
        new._uid = self._uid
        return new

    # -- MPI-style surface -------------------------------------------------

    def Get_size(self) -> int:
        """Number of ranks (static Python int).

        Works either from the bound mesh or, inside a ``shard_map`` trace,
        from the axis environment (``lax.axis_size``).
        """
        if self._mesh is not None:
            return int(np.prod([self._mesh.shape[a] for a in self._axes]))
        from ..utils.jax_compat import axis_bound

        if all(axis_bound(a) for a in self._axes):
            return int(np.prod([lax.axis_size(a) for a in self._axes]))
        raise RuntimeError(
            f"Comm({self._axes}) is not bound to a mesh and axis sizes "
            "are not available outside a shard_map trace. Bind the comm "
            "(comm.bind(mesh)) or call inside a parallel region."
        )

    def Get_rank(self):
        """Linear rank of the calling device (traced value, row-major).

        Unlike the reference (where rank is a Python int per process,
        ref _src/utils.py:86-90), the TPU SPMD model traces ONE program for
        all ranks, so the rank is a traced scalar.  Use it for data
        (coordinates, masks); structural choices (roots, routing) take static
        Python values.
        """
        rank = lax.axis_index(self._axes[0])
        for a in self._axes[1:]:
            rank = rank * lax.axis_size(a) + lax.axis_index(a)
        return rank

    # MPI spells it Get_rank/Get_size; offer pythonic aliases too.
    rank = Get_rank
    size = Get_size

    def Clone(self) -> "Comm":
        """Fresh matching namespace over the same group.

        Ref parity: ``comm.Clone()`` isolates this library's traffic from the
        user's (ref _src/comm.py:4-11).  Here collectives cannot collide at
        all (each HLO op is independent), so cloning only isolates
        send/recv trace-time matching queues.
        """
        return Comm(self._axes, mesh=self._mesh)

    Dup = Clone

    def sub(self, *axes: str) -> "Comm":
        """Communicator over a subset of this comm's axes.

        The TPU-native form of ``MPI_Comm_split`` for Cartesian grids: on a
        mesh ``("y", "x")``, ``comm.sub("x")`` is the row communicator (one
        group per y-coordinate), ``comm.sub("y")`` the column communicator.
        Arbitrary (non-grid) color splits are not supported — XLA's
        ``axis_index_groups`` is unavailable under shard_map; reshape your
        mesh instead.
        """
        for a in axes:
            if a not in self._axes:
                raise ValueError(f"axis {a!r} not in comm axes {self._axes}")
        return Comm(axes, mesh=self._mesh)

    def Split(self, color_axis: str) -> "Comm":
        """Alias for ``sub`` with MPI naming; split along remaining axes."""
        remaining = tuple(a for a in self._axes if a != color_axis)
        if not remaining:
            raise ValueError("Split would leave an empty communicator")
        return Comm(remaining, mesh=self._mesh)

    def __repr__(self):
        bound = f", mesh={tuple(self._mesh.shape.items())}" if self._mesh else ""
        return f"Comm(axes={self._axes}{bound}, uid={self._uid})"
