"""Communicators as named mesh axes.

TPU-native replacement for the reference's mpi4py communicator handling
(ref: mpi4jax/_src/comm.py:4-11 default ``COMM_WORLD.Clone()``;
mpi4jax/_src/utils.py:80-96 handle marshalling).  An MPI communicator is a
(process group, message-matching namespace); the TPU-native equivalent is a
(set of mesh axes, point-to-point matching namespace):

- the *process group* is the set of devices along the comm's mesh axes;
- collectives over the group are XLA HLO collectives over those axes,
  scheduled on ICI/DCN by the compiler — no channel/tag bookkeeping needed;
- the *matching namespace* only matters for ``send``/``recv`` pairing, which
  this framework matches at trace time per (comm, tag) — so ``Clone()``
  returns a comm with a fresh matching namespace, preserving the reference's
  isolation guarantee (user traffic on a cloned comm can never collide,
  ref docs/sharp-bits.rst:82-143).

A ``Comm`` may be *bound* to a concrete ``jax.sharding.Mesh`` (so it knows its
size statically and can run ops eagerly by auto-wrapping them in
``jax.shard_map``), or *unbound* (axes only — usable inside any user
``shard_map`` that defines those axes).
"""

from __future__ import annotations

import itertools
from typing import Optional, Tuple

import jax
import numpy as np
from jax import lax

_uid_counter = itertools.count()


def _color_order_key(colors):
    """Group-ordering key for Split color values: numeric when every color
    is a number (so 10 sorts after 2, like MPI's integer colors), string
    otherwise (mixed/naming colors get a stable lexicographic order)."""
    import numbers

    if all(isinstance(c, numbers.Real) and not isinstance(c, bool)
           for c in colors):
        return lambda kv: float(kv[0])
    return lambda kv: str(kv[0])


class Comm:
    """A communicator over one or more mesh axes.

    Parameters
    ----------
    axes:
        Mesh axis name, or sequence of names.  Multiple axes form one flat
        group in row-major order (first axis is slowest-varying), like an MPI
        communicator over a Cartesian grid.
    mesh:
        Optional concrete ``jax.sharding.Mesh`` binding.  Required for eager
        (outside-``shard_map``) execution and for static ``Get_size`` outside
        a trace.
    """

    def __init__(self, axes, *, mesh: Optional[jax.sharding.Mesh] = None):
        if isinstance(axes, str):
            axes = (axes,)
        self._axes: Tuple[str, ...] = tuple(axes)
        if not self._axes:
            raise ValueError("Comm needs at least one mesh axis name")
        self._mesh = mesh
        if mesh is not None:
            missing = [a for a in self._axes if a not in mesh.shape]
            if missing:
                raise ValueError(
                    f"axes {missing} not present in mesh axes {tuple(mesh.shape)}"
                )
        # Unique id = the p2p matching namespace (Clone isolation).
        self._uid = next(_uid_counter)
        # Communication epoch this comm belongs to (resilience/elastic.py):
        # advancing the epoch revokes every comm stamped with an older one —
        # derived comms (Clone/bind/sub/Split) inherit their parent's stamp,
        # shrink() re-stamps with the post-revocation epoch.  A collective
        # dispatched on a stale comm is flagged MPX126 by the verifier.
        from ..resilience.elastic import current_epoch

        self._epoch = current_epoch()

    # -- structure ---------------------------------------------------------

    @property
    def axes(self) -> Tuple[str, ...]:
        return self._axes

    @property
    def axis(self) -> str:
        """The single axis name; raises for multi-axis comms."""
        if len(self._axes) != 1:
            raise ValueError(
                f"operation requires a single-axis communicator, got axes "
                f"{self._axes}; use comm.sub(axis) to select one axis"
            )
        return self._axes[0]

    @property
    def mesh(self) -> Optional[jax.sharding.Mesh]:
        return self._mesh

    @property
    def uid(self) -> int:
        return self._uid

    @property
    def epoch(self) -> int:
        """Communication epoch this comm was built in (elastic recovery:
        resilience/elastic.py).  0 for the whole life of a job that never
        shrank."""
        return self._epoch

    @property
    def drained(self) -> bool:
        """True once this comm's world executed a planned drain past its
        leave boundary (resilience/elastic.py graceful drain): its rank
        space includes ranks that left on purpose, so issuing a
        collective on it is flagged MPX127 by the verifier.  A comm
        merely *scheduled* to drain stays False through the boundary."""
        from ..resilience.elastic import comm_drained

        return comm_drained(self._uid)

    def bind(self, mesh: jax.sharding.Mesh) -> "Comm":
        """Return a copy of this comm bound to ``mesh`` (same namespace)."""
        new = Comm(self._axes, mesh=mesh)
        new._uid = self._uid
        new._epoch = self._epoch
        return new

    # -- MPI-style surface -------------------------------------------------

    def Get_size(self) -> int:
        """Number of ranks (static Python int).

        Works either from the bound mesh or, inside a ``shard_map`` trace,
        from the axis environment (``lax.axis_size``).
        """
        if self._mesh is not None:
            return int(np.prod([self._mesh.shape[a] for a in self._axes]))
        from ..utils.jax_compat import axis_bound

        if all(axis_bound(a) for a in self._axes):
            return int(np.prod([lax.axis_size(a) for a in self._axes]))
        raise RuntimeError(
            f"Comm({self._axes}) is not bound to a mesh and axis sizes "
            "are not available outside a shard_map trace. Bind the comm "
            "(comm.bind(mesh)) or call inside a parallel region."
        )

    def Get_rank(self):
        """Linear rank of the calling device (traced value, row-major).

        Unlike the reference (where rank is a Python int per process,
        ref _src/utils.py:86-90), the TPU SPMD model traces ONE program for
        all ranks, so the rank is a traced scalar.  Use it for data
        (coordinates, masks); structural choices (roots, routing) take static
        Python values.

        Exception: while the cross-rank verifier re-traces the program
        for ONE rank (``mpx.analyze(ranks=...)`` or the ambient
        cross-rank pass — analysis/schedule.py), the rank is that rank's
        concrete Python int, so rank-dependent branches take their real
        per-rank paths.
        """
        from ..analysis.schedule import concrete_comm_rank

        concrete = concrete_comm_rank(self._axes)
        if concrete is not None:
            return concrete
        rank = lax.axis_index(self._axes[0])
        for a in self._axes[1:]:
            rank = rank * lax.axis_size(a) + lax.axis_index(a)
        return rank

    # MPI spells it Get_rank/Get_size; offer pythonic aliases too.
    rank = Get_rank
    size = Get_size

    # -- group-split hooks (overridden by GroupComm; identity here) --------

    @property
    def groups(self):
        """Rank groups of a color-split comm (``None`` for a whole-axes
        comm — see ``GroupComm``)."""
        return None

    def world_size(self) -> int:
        """Flat device count along this comm's mesh axes (= ``Get_size``
        except on a color-split comm, where ``Get_size`` is the group
        size)."""
        return Comm.Get_size(self)

    def global_rank(self):
        """Linear rank over the full mesh axes (traced; = ``Get_rank``
        except on a color-split comm, where ``Get_rank`` is group-local)."""
        return Comm.Get_rank(self)

    def local_rank_of(self, r: int) -> int:
        """Static translation of a global rank to this comm's rank space."""
        return r

    def expand_pairs(self, pairs):
        """Static translation of comm-local routing pairs to global pairs
        along the mesh axes (identity except on a color-split comm)."""
        return pairs

    def min_size(self) -> int:
        """Smallest group size (= ``Get_size`` for a whole-axes comm) —
        the bound a static root index must satisfy on every group."""
        return self.Get_size()

    def uniform_size(self) -> Optional[int]:
        """The single static group size shared by every group, or
        ``None`` when group sizes differ (only possible on a color-split
        comm — see ``GroupComm.uniform_size``).

        The explicit accessor the algorithm selector uses
        (``ops/_algos.static_group_size``): asking "can this comm ring?"
        is an ordinary question with an ordinary ``None`` answer, not an
        exception (``Get_size`` keeps its loud error for the gather
        family, whose output SHAPES genuinely require a uniform size).
        """
        return self.Get_size()

    def Clone(self) -> "Comm":
        """Fresh matching namespace over the same group.

        Ref parity: ``comm.Clone()`` isolates this library's traffic from the
        user's (ref _src/comm.py:4-11).  Here collectives cannot collide at
        all (each HLO op is independent), so cloning only isolates
        send/recv trace-time matching queues.
        """
        new = Comm(self._axes, mesh=self._mesh)
        new._epoch = self._epoch
        return new

    Dup = Clone

    def shrink(self, failed, *, mesh: jax.sharding.Mesh) -> "Comm":
        """Rebuild this communicator as "all minus ``failed``" over the
        post-shrink ``mesh`` — the comm half of elastic recovery
        (resilience/elastic.py; the analog of ULFM's ``MPI_Comm_shrink``).

        ``failed`` are OLD-world global ranks; survivors are renumbered
        compactly in ascending old-rank order (``compact_rank_map``).
        The result is a NEW communicator (fresh matching namespace)
        stamped with the CURRENT epoch, so programs traced against it
        cache under post-revocation keys.
        """
        from ..resilience.elastic import compact_rank_map

        failed = frozenset(int(r) for r in failed)
        world = self.world_size()
        rmap = compact_rank_map(world, failed)  # validates range/survivors
        expect = len(rmap)
        got = int(np.prod([mesh.shape[a] for a in self._axes
                           if a in mesh.shape]))
        if got != expect:
            raise ValueError(
                f"shrink: mesh spans {got} ranks along axes {self._axes} "
                f"but {expect} of {world} ranks survive — pass the mesh "
                "shrink_world_mesh built for this failure"
            )
        return Comm(self._axes, mesh=mesh)

    def sub(self, *axes: str) -> "Comm":
        """Communicator over a subset of this comm's axes.

        The TPU-native form of ``MPI_Comm_split`` for Cartesian grids: on a
        mesh ``("y", "x")``, ``comm.sub("x")`` is the row communicator (one
        group per y-coordinate), ``comm.sub("y")`` the column communicator.
        Arbitrary (non-grid) color splits are not supported — XLA's
        ``axis_index_groups`` is unavailable under shard_map; reshape your
        mesh instead.
        """
        for a in axes:
            if a not in self._axes:
                raise ValueError(f"axis {a!r} not in comm axes {self._axes}")
        new = Comm(axes, mesh=self._mesh)
        new._epoch = self._epoch
        return new

    def Split(self, color, key=None) -> "Comm":
        """Split this communicator — the analog of ``MPI_Comm_split``.

        Two forms:

        - ``Split("axis_name")`` — Cartesian split along the remaining mesh
          axes (the grid form, zero-cost: collectives stay native HLO).
        - ``Split(colors, key=None)`` — **arbitrary color split**: ``colors``
          is a length-``Get_size()`` sequence giving every rank's color
          (the SPMD form of MPI's per-process ``color`` argument — one
          traced program must know the whole table).  Ranks sharing a color
          form a group, ordered by ``(key[r], r)`` when ``key`` (same
          length) is given, else by rank — exactly MPI's ordering rule.
          Returns a :class:`GroupComm`, whose collectives run over the
          full axes with masked routing (XLA's ``axis_index_groups`` is
          unavailable under shard_map, verified on jax 0.9): correct for
          any partition.  allreduce/reduce/bcast/scan lower to log-depth
          doubling rounds over CollectivePermute (O(log k) depth and
          per-rank bandwidth); the gather family moves O(world) via a
          full-axes AllGather.  Regular splits prefer the grid form
          (single native HLO collectives).
        """
        if isinstance(color, str):
            remaining = tuple(a for a in self._axes if a != color)
            if not remaining:
                raise ValueError("Split would leave an empty communicator")
            new = Comm(remaining, mesh=self._mesh)
            new._epoch = self._epoch
            return new

        size = self.Get_size()
        colors = list(color)
        if len(colors) != size:
            raise ValueError(
                f"Split: colors must list every rank's color "
                f"(got {len(colors)} entries for {size} ranks). Under SPMD "
                "one traced program serves all ranks, so the whole color "
                "table is required (the per-process form of MPI_Comm_split "
                "has no single-program analog)."
            )
        keys = list(key) if key is not None else [0] * size
        if len(keys) != size:
            raise ValueError(
                f"Split: key must have one entry per rank "
                f"(got {len(keys)} for {size})"
            )
        by_color = {}
        for r in range(size):
            by_color.setdefault(colors[r], []).append(r)
        groups = tuple(
            tuple(sorted(members, key=lambda r: (keys[r], r)))
            for _, members in sorted(by_color.items(),
                                     key=_color_order_key(colors))
        )
        return GroupComm(self, groups)

    def __repr__(self):
        bound = f", mesh={tuple(self._mesh.shape.items())}" if self._mesh else ""
        return f"Comm(axes={self._axes}{bound}, uid={self._uid})"


class GroupComm(Comm):
    """A color-split communicator: a partition of a parent comm's ranks.

    Produced by ``Comm.Split(colors, key)``.  The group structure is static
    (``groups``: tuple of tuples of *global* ranks); collectives run over
    the parent's full mesh axes with masked routing, so any partition
    works — including non-Cartesian and unequal-sized groups — at
    O(log k) per-rank bandwidth for the reduction family and O(world)
    for the gather family.  ``Get_rank``/``Get_size`` follow MPI:
    group-local rank and
    group size.  All 12 ops work on UNIFORM group sizes;
    allreduce/reduce/bcast/barrier additionally work on unequal-sized
    partitions.  Ops whose routing or output shape needs a static group
    size (the gather family: allgather/alltoall/gather/scatter) raise
    ``Get_size``'s clear error on unequal groups — one SPMD program
    cannot express a per-group shape (the rank-dependent-shape
    restriction, docs/sharp_bits.md).  ``scan`` and point-to-point
    (``shift``/callable routing) work on unequal groups too: their
    routing comes from the static group tables, not a uniform size.
    """

    def __init__(self, parent: Comm, groups):
        super().__init__(parent.axes, mesh=parent.mesh)
        self._epoch = parent.epoch
        seen = [r for g in groups for r in g]
        try:
            world = Comm.Get_size(self)
        except RuntimeError:  # unbound comm outside any trace
            world = None
        if sorted(seen) != sorted(set(seen)):
            raise ValueError(f"Split groups overlap: {groups}")
        if world is not None and sorted(seen) != list(range(world)):
            raise ValueError(
                f"Split groups {groups} must partition all {world} ranks "
                "(MPI_UNDEFINED colors are not supported: every rank "
                "executes the SPMD program, so every rank needs a group)"
            )
        self._groups = tuple(tuple(int(r) for r in g) for g in groups)
        # global rank -> (group id, local rank, group size), as static
        # tables built ONCE here (collective lowerings look them up on
        # every trace — rebuilding the dense size table per collective was
        # an O(world) python loop per trace of a split comm)
        n = len(seen)
        self._gid = [0] * n
        self._lrank = [0] * n
        self._ksize = [0] * n
        for g, members in enumerate(self._groups):
            for i, r in enumerate(members):
                self._gid[r] = g
                self._lrank[r] = i
                self._ksize[r] = len(members)
        self._ksize = tuple(self._ksize)

    @property
    def groups(self):
        return self._groups

    def Get_size(self) -> int:
        size = self.uniform_size()
        if size is None:
            raise RuntimeError(
                f"Get_size on a color-split comm with unequal group sizes "
                f"{sorted(len(g) for g in self._groups)} has no single "
                "static value. Only the gather family (allgather/"
                "alltoall/gather/scatter) and reduce_scatter need uniform "
                "groups — their shapes/blocking depend on the group size; "
                "every other op works on unequal groups."
            )
        return size

    def Get_rank(self):
        """Group-local rank (traced), per MPI_Comm_split semantics.
        Concrete (a Python int, via the static group tables) while the
        cross-rank verifier re-traces for one rank — see ``Comm.Get_rank``."""
        g = self.global_rank()
        if isinstance(g, int):
            from ..analysis.schedule import RankConcrete

            return RankConcrete(self._lrank[g])
        import jax.numpy as jnp

        return jnp.asarray(self._lrank)[g]

    rank = Get_rank
    size = Get_size

    def min_size(self) -> int:
        return min(len(g) for g in self._groups)

    def uniform_size(self) -> Optional[int]:
        """The uniform group size, or ``None`` for unequal splits —
        without raising (``Get_size`` raises, which forced the algorithm
        selector into ``RuntimeError``-as-control-flow)."""
        sizes = {len(g) for g in self._groups}
        if len(sizes) != 1:
            return None
        return sizes.pop()

    def group_size_table(self):
        """Static per-GLOBAL-rank group-size tuple (``table[r]`` = size of
        the group containing rank ``r``), cached at construction — the
        table the butterfly lowerings index with the traced global rank
        (``ops/_base._comm_pos_size``)."""
        return self._ksize

    def local_rank_of(self, r: int) -> int:
        return self._lrank[r]

    def my_group_members(self):
        """Traced ``(group_size,)`` vector of this rank's group's global
        ranks, in group order — the index table the gather-family group
        lowerings select with (uniform group sizes only)."""
        import jax.numpy as jnp

        self.Get_size()  # uniform-size check with the clear error
        mat = jnp.asarray(self._groups)
        return mat[jnp.asarray(self._gid)[self.global_rank()]]

    def expand_pairs(self, pairs):
        """Group-local (send, recv) pairs -> global pairs, applied to every
        group (requires uniform group sizes — Get_size enforces that before
        any routing spec is normalized)."""
        out = []
        for members in self._groups:
            for s, d in pairs:
                out.append((members[s], members[d]))
        return tuple(out)

    def Clone(self) -> "Comm":
        clone = GroupComm.__new__(GroupComm)
        Comm.__init__(clone, self._axes, mesh=self._mesh)
        clone._epoch = self._epoch
        clone._groups = self._groups
        clone._gid = self._gid
        clone._lrank = self._lrank
        clone._ksize = self._ksize
        return clone

    Dup = Clone

    def shrink(self, failed, *, mesh: jax.sharding.Mesh) -> "Comm":
        """Shrink a color-split comm: drop the failed ranks from every
        group, renumber survivors compactly (``shrink_groups`` preserves
        each group's member order), drop groups that lost every member,
        and rebuild over the post-shrink ``mesh``.  A fresh current-epoch
        :class:`GroupComm` results — the group-table half of elastic
        recovery."""
        from ..resilience.elastic import shrink_groups

        failed = frozenset(int(r) for r in failed)
        world = self.world_size()
        new_groups = shrink_groups(self._groups, failed, world)
        parent = Comm(self._axes, mesh=mesh)
        expect = world - len(failed)
        got = parent.world_size()
        if got != expect:
            raise ValueError(
                f"shrink: mesh spans {got} ranks along axes {self._axes} "
                f"but {expect} of {world} ranks survive — pass the mesh "
                "shrink_world_mesh built for this failure"
            )
        return GroupComm(parent, new_groups)

    def bind(self, mesh: jax.sharding.Mesh) -> "Comm":
        """Bind to a mesh, PRESERVING the group structure (the inherited
        bind would silently return a whole-axes comm and run collectives
        over the full world)."""
        new = self.Clone()
        new._mesh = mesh
        missing = [a for a in self._axes if a not in mesh.shape]
        if missing:
            raise ValueError(
                f"axes {missing} not present in mesh axes {tuple(mesh.shape)}"
            )
        new._uid = self._uid
        return new

    def sub(self, *axes: str) -> "Comm":
        raise ValueError(
            "sub() on a color-split comm is not supported — take sub-comms "
            "from the parent comm before splitting"
        )

    def Split(self, color, key=None) -> "Comm":
        """Nested ``MPI_Comm_split``: refine this partition.

        ``colors``/``key`` are world-length tables indexed by GLOBAL rank
        (the same SPMD convention as the parent's ``Split`` — every rank
        of the mesh belongs to some group, so every rank needs an entry).
        New groups form WITHIN each existing group — two ranks share a new
        group only if they share both the old group and the new color —
        ordered by ``(key, old group-local rank)``, MPI's rule with "rank
        in the old comm" being the group-local rank."""
        if isinstance(color, str):
            raise ValueError(
                "grid splits of a color-split comm are not supported — "
                "take sub-comms from the parent comm before splitting"
            )
        n = len(self._lrank)
        colors = list(color)
        if len(colors) != n:
            raise ValueError(
                f"Split: colors must list every rank's color "
                f"(got {len(colors)} entries for {n} mesh ranks; on a "
                "color-split comm the table is indexed by GLOBAL rank)"
            )
        keys = list(key) if key is not None else [0] * n
        if len(keys) != n:
            raise ValueError(
                f"Split: key must have one entry per rank "
                f"(got {len(keys)} for {n})"
            )
        new_groups = []
        keyfn = _color_order_key(colors)  # once: the scan is O(world)
        for members in self._groups:
            by_color = {}
            for i, r in enumerate(members):
                by_color.setdefault(colors[r], []).append((keys[r], i, r))
            for _, lst in sorted(by_color.items(), key=keyfn):
                new_groups.append(tuple(r for _, _, r in sorted(lst)))
        return GroupComm(self, tuple(new_groups))

    def __repr__(self):
        return (f"GroupComm(axes={self._axes}, groups={self._groups}, "
                f"uid={self._uid})")
