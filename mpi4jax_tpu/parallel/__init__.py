"""Parallel runtime: communicators, meshes, SPMD regions, routing specs.

TPU-native replacement for the reference's MPI runtime layer
(ref: mpi4jax/_src/comm.py, the mpirun launch model, and the
communicator-handle plumbing in _src/utils.py:80-96).
"""

from .comm import Comm  # noqa: F401
from . import moe  # noqa: F401  (expert-parallel MoE helper, docs/moe.md)
from .mesh import (  # noqa: F401
    DEFAULT_AXIS,
    get_default_mesh,
    init_distributed,
    make_world_mesh,
    set_default_mesh,
    shrink_world_mesh,
)
from .pipeline import (  # noqa: F401
    PipelineProgram,
    pipeline,
)
from .rankspec import (  # noqa: F401
    invert_pairs,
    normalize_dest,
    normalize_source,
    shift,
)
from .region import (  # noqa: F401
    current_context,
    get_default_comm,
    in_parallel_region,
    resolve_comm,
    run,
    spmd,
)
