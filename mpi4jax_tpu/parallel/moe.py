"""Expert-parallel mixture-of-experts: capacity-bucketed alltoall
dispatch/combine around a per-expert MLP (docs/moe.md).

The workload class ROADMAP item 5a names: ``k`` ranks each own ONE
expert, tokens are routed by a top-1 gate, and the two hottest
collectives in the program are **alltoalls** —

- **dispatch**: every rank buckets its local tokens by destination
  expert into a ``(experts, capacity, d)`` buffer (tokens beyond the
  capacity are dropped, the standard top-1 discipline) and one alltoall
  ships bucket ``e`` to rank ``e``;
- **expert compute**: each rank runs ITS expert's MLP over the
  ``k · capacity`` tokens it received;
- **combine**: the mirror alltoall ships every processed bucket back to
  its source rank, where the gate probability weighs it into the output
  (dropped tokens contribute zero).

The combine is where Tutel/FasterMoE-style overlap pays: the per-expert
compute and the combine-exchange split into
``MPI4JAX_TPU_MOE_CAPACITY_CHUNKS`` capacity chunks, chunk ``i``'s
combine issued via :func:`~mpi4jax_tpu.alltoall_start` while chunk
``i+1``'s MLP runs — the exchange rides the async alltoall fast path
(ops/_async.py), hierarchical over ICI/DCN where the topology layer
selects it (ops/_hierarchy.py).

**Determinism contract**: the gate and capacity math is pure and seeded
(``init_moe_params``), every bucket operation is a one-hot einsum (no
data-dependent gather ordering), and dispatch/combine are fixed
permutations — so the 8-device layer output is BIT-IDENTICAL to the
single-device :func:`reference_moe` fold (pinned by tests/test_moe.py),
and the overlapped pipeline is bit-identical to the synchronous one.

The gate math helpers are numpy-polymorphic (they take the array module
as an argument), so tests/test_moe_pure.py drives the SAME functions
through plain numpy under any installed JAX.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import numpy as np

__all__ = [
    "MoEParams",
    "capacity_for",
    "init_moe_params",
    "gate_tokens",
    "dispatch_tensor",
    "expert_mlp",
    "moe_layer",
    "reference_moe",
]


def capacity_for(tokens: int, experts: int, factor: float = 1.25) -> int:
    """Per-expert token capacity of one rank's dispatch bucket:
    ``ceil(tokens / experts · factor)``, at least 1 — the padded bucket
    shape every rank shares, so the dispatch alltoall is shape-uniform
    across ranks (rank-divergent capacities are exactly the MPX120
    fixture under examples/broken/)."""
    if tokens < 1 or experts < 1:
        raise ValueError(
            f"capacity_for needs tokens >= 1 and experts >= 1, got "
            f"tokens={tokens}, experts={experts}"
        )
    if factor <= 0:
        raise ValueError(f"capacity factor must be > 0, got {factor}")
    return max(1, -(-int(tokens * factor) // experts))


class MoEParams(NamedTuple):
    """One rank's MoE parameters: the (replicated) router plus THIS
    rank's expert MLP (expert-parallel: rank ``e`` owns expert ``e``)."""

    w_gate: object   # (d, experts) — replicated router
    w_in: object     # (d, d_ff)    — this rank's expert, layer 1
    w_out: object    # (d_ff, d)    — this rank's expert, layer 2


def init_moe_params(d: int, d_ff: int, experts: int, rank: int = 0,
                    seed: int = 0):
    """Seeded numpy parameter init (float32).  Pure and deterministic:
    the router is identical on every rank (same seed), the expert MLP is
    seeded per ``rank`` — so a single-device reference can rebuild every
    expert's weights exactly (``reference_moe``)."""
    gate_rng = np.random.default_rng(seed)
    w_gate = gate_rng.standard_normal((d, experts)).astype(np.float32) * 0.3
    ex_rng = np.random.default_rng(seed * 7919 + 31 + rank)
    w_in = ex_rng.standard_normal((d, d_ff)).astype(np.float32) * 0.2
    w_out = ex_rng.standard_normal((d_ff, d)).astype(np.float32) * 0.2
    return MoEParams(w_gate=w_gate, w_in=w_in, w_out=w_out)


def gate_tokens(xp, x, w_gate):
    """Top-1 gating: returns ``(assignment, gate_prob)`` for local
    tokens ``x`` (shape ``(tokens, d)``) — the expert index each token
    routes to and its softmax probability.  ``xp`` is the array module
    (``jax.numpy`` traced, ``numpy`` in the pure tests): the math is
    identical, which is what makes the 8-device pins possible."""
    logits = x @ w_gate
    a = xp.argmax(logits, axis=-1)
    z = xp.exp(logits - xp.max(logits, axis=-1, keepdims=True))
    probs = z / xp.sum(z, axis=-1, keepdims=True)
    gate = xp.take_along_axis(probs, a[:, None], axis=-1)[:, 0]
    return a, gate


def dispatch_tensor(xp, assignment, experts: int, capacity: int):
    """The one-hot dispatch tensor ``D[t, e, c]`` = 1 iff local token
    ``t`` is the ``c``-th token (in position order) routed to expert
    ``e`` and ``c < capacity``.  Everything downstream is an einsum
    against ``D`` — bucketing, un-bucketing, and the gate-weighted
    combine — so there is no data-dependent gather order to diverge
    across ranks or between the traced and reference paths."""
    onehot = (assignment[:, None] ==
              xp.arange(experts)[None, :]).astype(xp.float32)
    pos = xp.cumsum(onehot, axis=0) * onehot - onehot  # 0-based in-bucket
    slot = (pos[:, :, None] ==
            xp.arange(capacity)[None, None, :]).astype(xp.float32)
    return slot * onehot[:, :, None]


def expert_mlp(xp, z, w_in, w_out):
    """One expert's feed-forward over a token block: ``tanh`` MLP —
    smooth, bounded, and bit-reproducible across the traced and numpy
    reference paths (no erf/gelu implementation divergence)."""
    return xp.tanh(z @ w_in) @ w_out


def moe_layer(x, params: MoEParams, *, comm=None, token=None,
              capacity_factor: float = 1.25,
              chunks: Optional[int] = None):
    """The expert-parallel MoE layer (rank-local view, inside a managed
    parallel region): gate → capacity-bucketed dispatch alltoall →
    per-expert MLP → combine alltoall → gate-weighted output.

    ``chunks`` (default ``MPI4JAX_TPU_MOE_CAPACITY_CHUNKS``) pipelines
    the combine: the received buckets split into capacity chunks, chunk
    ``i``'s combine-exchange is issued with ``alltoall_start`` and
    chunk ``i+1``'s expert MLP runs in the gap; the waits land after
    the last chunk's compute.  ``chunks=1`` is the synchronous layer —
    bit-identical output either way (pinned by tests/test_moe.py).

    Returns ``(y, token)`` with ``y`` shaped like ``x``; dropped tokens
    (beyond an expert's capacity) produce zero rows, the standard top-1
    capacity discipline.
    """
    import jax.numpy as jnp

    from ..ops import _async
    from ..ops.alltoall import alltoall
    from ..parallel.region import resolve_comm
    from ..utils import config

    comm = resolve_comm(comm)
    k = comm.Get_size()
    tokens, d = x.shape
    capacity = capacity_for(tokens, k, capacity_factor)
    if chunks is None:
        chunks = config.moe_capacity_chunks()
    chunks = max(1, min(int(chunks), capacity))

    a, gate = gate_tokens(jnp, x, jnp.asarray(params.w_gate))
    D = dispatch_tensor(jnp, a, k, capacity)           # (tokens, k, cap)
    dispatch = jnp.einsum("tec,td->ecd", D, x)         # (k, cap, d)
    received, tok = alltoall(dispatch, comm=comm, token=token)
    # received[g, c] = rank g's c-th token for MY expert

    w_in = jnp.asarray(params.w_in)
    w_out = jnp.asarray(params.w_out)
    sizes = _async.overlap_chunk_split(capacity, chunks)
    if len(sizes) == 1:
        # synchronous: one MLP, one combine exchange
        processed = expert_mlp(jnp, received, w_in, w_out)
        combined, tok = alltoall(processed, comm=comm, token=tok)
    else:
        # the overlap pipeline: chunk i's combine-alltoall is in flight
        # while chunk i+1's expert MLP runs (docs/moe.md)
        handles = []
        off = 0
        for csz in sizes:
            block = received[:, off:off + csz]
            off += csz
            out = expert_mlp(jnp, block, w_in, w_out)
            h, tok = _async.alltoall_start(out, comm=comm, token=tok)
            handles.append(h)
        parts = []
        for h in handles:
            part, tok = _async.alltoall_wait(h, token=tok)
            parts.append(part)
        combined = jnp.concatenate(parts, axis=1)
    # combined[e, c] = my c-th token as processed by expert e
    y = jnp.einsum("tec,ecd->td", D, combined) * gate[:, None]
    return y, tok


def reference_moe(x_global, d_ff: int, experts: int, *, seed: int = 0,
                  capacity_factor: float = 1.25):
    """Single-device numpy reference of the whole expert-parallel layer:
    ``x_global`` is ``(ranks, tokens, d)`` (rank-major, the eager global
    convention) and the return is the matching global output — the
    8-device dryrun pin (tests/test_moe.py compares bit-for-bit).

    Rebuilds every expert's weights from the same seeded init the ranks
    use, replays the same capacity discipline, and never simulates the
    wire: dispatch/combine are fixed permutations, so equality with the
    distributed layer is exact.
    """
    k, tokens, d = x_global.shape
    assert k == experts, (k, experts)
    capacity = capacity_for(tokens, experts, capacity_factor)
    params = [init_moe_params(d, d_ff, experts, rank=r, seed=seed)
              for r in range(k)]
    # per-rank gating + dispatch buckets
    disp = np.zeros((k, experts, capacity, d), np.float32)
    Ds = []
    gates = []
    for r in range(k):
        a, gate = gate_tokens(np, x_global[r], params[r].w_gate)
        D = dispatch_tensor(np, a, experts, capacity)
        Ds.append(D)
        gates.append(gate)
        disp[r] = np.einsum("tec,td->ecd", D, x_global[r])
    # alltoall: expert e receives bucket e of every rank
    received = np.stack([disp[:, e] for e in range(experts)])  # (e, k, c, d)
    processed = np.stack([
        expert_mlp(np, received[e], params[e].w_in, params[e].w_out)
        for e in range(experts)
    ])
    # combine alltoall back: rank r's view of expert e's output bucket
    out = np.zeros_like(x_global)
    for r in range(k):
        combined = processed[:, r]  # (e, c, d): my tokens at each expert
        out[r] = np.einsum("tec,ecd->td", Ds[r],
                           combined) * gates[r][:, None]
    return out
