"""Megastep execution: device-resident multi-step loops.

BENCH_r05 put the residual host tax at ~14% of the shallow-water wall
even on the pinned path (956 delivered vs 1106 on-chip steps/s/chip,
``dispatch_overhead_s`` 0.063): every step still crosses Python once.
The megastep compiler ends that the way CUDA Graphs' capture-and-replay
amortizes launch overhead — ``mpx.compile(fn, unroll=N)`` (and
``mpx.spmd(..., unroll=N)``) rewrite the step body into a device-resident
``lax.fori_loop`` over N iterations, so ONE host dispatch executes N
steps and the per-step host cost falls as 1/N.

:func:`megastep_loop` is the shared loop builder (the ``spmd``/pin region
body in parallel/region.py and the elastic step adapter in
aot/pinning.py both call it):

- **carry contract**: the iteration body must map its carry pytree to an
  output of identical structure, shapes, and dtypes (state -> state; the
  ``lax.fori_loop`` requirement).  A mismatch raises a ``ValueError``
  naming the offending leaf at trace time.  Carries are re-typed
  rank-varying over the comm's axes each iteration, so collective
  results (replicated-typed in JAX's collective type system) are legal
  carries without a manual ``mpx.varying``;
- **per-iteration fusion**: the deferral queue (ops/_fusion.py) is
  flushed and every deferred result materialized at the END of the loop
  body, so fusion buckets formed inside the body stay per-iteration — no
  cross-iteration packing (the lockstep simulator pins bucketing per
  dispatch sequence, and a bucket straddling iterations would not exist
  at run time anyway: the body traces once);
- **span rule**: an async ``*_start``/``*_wait`` span may not straddle
  the loop boundary — a start without its wait inside the same iteration
  would arm instrumentation the next iteration cannot close.  Events
  recorded inside the body carry the loop scope, and the MPX130 checker
  (analysis/checkers.py) errors on straddling spans (``mpx.analyze`` or
  ``MPI4JAX_TPU_ANALYZE=error``);
- **watchdog**: when the collective watchdog is armed, one extra bracket
  wraps the WHOLE megastep with the deadline scaled by N (per-op arms
  inside the loop keep their per-collective deadline — a single hung
  collective still trips at the per-op timeout; the outer bracket covers
  the loop machinery itself);
- **telemetry**: in the ``events`` tier the megastep contributes ONE
  begin/end journal bracket (op ``megastep``, tagged with ``unroll``)
  per execution plus a synthesized per-step latency estimate
  (``latency / N`` fed into the ``megastep_step`` histogram by the
  journal — bucket math on the host, no extra io_callbacks on the hot
  path).

``unroll=1`` never reaches this module: callers keep their original body
construction, so the traced program and HLO are byte-identical to a
build without the megastep layer (pinned by tests/test_megastep.py).
"""

from __future__ import annotations

import itertools

__all__ = ["megastep_loop", "register_boundary_hook",
           "run_boundary_hooks", "tracing_megastep", "validate_unroll"]

_loop_ids = itertools.count(1)

# ---------------------------------------------------------------------------
# megastep boundary hooks (host-side)
# ---------------------------------------------------------------------------
#
# A megastep's BOUNDARY — the host-side gap between two device-resident
# dispatches — is the only point where anything outside the program can
# act: the serving runtime admits/evicts requests there
# (mpi4jax_tpu/serving/engine.py), the elastic layer executes planned
# drains there, tests observe cadence there.  The registry keeps those
# consumers decoupled from the loops that own the boundary: a driver
# calls ``run_boundary_hooks(step, **info)`` once per boundary and every
# registered hook fires in registration order.  Pure host Python — never
# traced, never in the program.

_boundary_hooks: list = []   # (name, fn)


def register_boundary_hook(name: str, fn):
    """Register ``fn(step, **info)`` to run at every megastep boundary a
    driver publishes.  Returns a zero-argument unregister callable.
    Hook exceptions propagate to the driver — a boundary consumer that
    fails must stop the loop, not be silently dropped."""
    if not callable(fn):
        raise TypeError(f"boundary hook {name!r} must be callable")
    entry = (str(name), fn)
    _boundary_hooks.append(entry)

    def unregister():
        try:
            _boundary_hooks.remove(entry)
        except ValueError:
            pass

    return unregister


def run_boundary_hooks(step: int, **info) -> list:
    """Fire every registered hook for boundary ``step``; returns
    ``[(name, result), ...]`` in registration order."""
    return [(name, fn(step, **info)) for name, fn in list(_boundary_hooks)]

# nesting depth of megastep loop-body traces (the config-snapshot twin
# of aot.pinning's _pinning_depth; the checker-facing discriminator is
# the per-event ``loop`` stamp, see tracing_megastep)
_megastep_depth = 0


def tracing_megastep() -> bool:
    """True while a megastep loop body is being traced.

    Informational: ``analysis.hook.config_snapshot`` records it as the
    ``megastep`` meta key (a graph snapshotted mid-body says so), but
    the MPX128/MPX130 checkers key on the PER-EVENT ``loop`` stamp —
    events recorded inside the body carry their loop id — because by
    the time a region's checkers run the body trace has already
    exited."""
    return _megastep_depth > 0


def validate_unroll(unroll) -> int:
    """Normalize an ``unroll=`` argument: a positive int (1 = no loop)."""
    try:
        n = int(unroll)
    except (TypeError, ValueError):
        raise TypeError(
            f"unroll must be a positive integer, got {unroll!r}"
        ) from None
    if n < 1:
        raise ValueError(f"unroll must be >= 1, got {unroll!r}")
    return n


class _loop_trace_scope:
    """Marks one loop body's trace: bumps the module depth and stamps the
    region context so ``analysis.hook.begin_event`` tags every event
    recorded inside with ``(loop_id, unroll)``."""

    __slots__ = ("ctx", "scope", "saved")

    def __init__(self, ctx, loop_id: int, unroll: int):
        self.ctx = ctx
        self.scope = (loop_id, unroll)
        self.saved = None

    def __enter__(self):
        global _megastep_depth
        _megastep_depth += 1
        self.saved = getattr(self.ctx, "megastep", None)
        self.ctx.megastep = self.scope
        return self

    def __exit__(self, *exc):
        global _megastep_depth
        _megastep_depth -= 1
        self.ctx.megastep = self.saved
        return False


def _carry_signature(jax, jnp, tree):
    leaves, treedef = jax.tree.flatten(tree)
    return treedef, tuple(
        (tuple(jnp.shape(leaf)), str(jnp.result_type(leaf)))
        for leaf in leaves
    )


def _check_carry(jax, jnp, treedef0, sig0, out, label: str):
    treedef1, sig1 = _carry_signature(jax, jnp, out)
    if treedef1 != treedef0:
        raise ValueError(
            f"megastep carry contract violated in {label!r}: the loop "
            f"body returned pytree structure {treedef1} but its carry "
            f"(the dynamic arguments) has structure {treedef0}.  With "
            "unroll > 1 the step must map its state to a like-structured "
            "state (docs/aot.md 'Megastep execution')."
        )
    for i, (got, want) in enumerate(zip(sig1, sig0)):
        if got != want:
            raise ValueError(
                f"megastep carry contract violated in {label!r}: carry "
                f"leaf {i} went in as shape/dtype {want} and came out as "
                f"{got} — a lax.fori_loop carry must keep its "
                "shapes/dtypes (docs/aot.md 'Megastep execution')."
            )


def megastep_loop(body_fn, carry, unroll: int, comm, label: str = "fn"):
    """Run ``carry = body_fn(i, carry)`` for ``unroll`` device-resident
    iterations inside the CURRENT parallel region's trace.

    ``body_fn(i, carry)`` is the per-rank iteration (``i`` is the traced
    loop index); ``carry`` is any pytree obeying the carry contract
    above.  Returns the final carry.  ``unroll == 1`` degenerates to a
    single direct call — no loop, no brackets, byte-identical trace.
    """
    n = validate_unroll(unroll)
    if n == 1:
        return body_fn(0, carry)

    import jax
    import jax.numpy as jnp
    from jax import lax

    from ..ops import _fusion
    from ..ops._base import _next_call_id, as_varying
    from .region import current_context

    ctx = current_context()
    loop_id = next(_loop_ids)

    # stabilize the carry typing up front: region inputs are rank-varying
    # already (no-op), but replicated trace constants fed as initial state
    # must match the varying-typed body output
    carry = jax.tree.map(lambda v: as_varying(jnp.asarray(v), comm.axes),
                         carry)
    treedef0, sig0 = _carry_signature(jax, jnp, carry)

    def one(i, c):
        with _loop_trace_scope(ctx, loop_id, n):
            out = body_fn(i, c)
            # per-iteration drain: buckets formed inside the body stay
            # per-iteration, and deferred LazyResults never leak into the
            # fori_loop carry
            _fusion.flush_pending(ctx)
            out = _fusion.materialize_tree(out)
            if ctx.pending_sync is not None:
                # a trailing tokenless barrier inside the iteration: tie
                # it into the carry so each iteration's barrier survives
                from ..ops.token import tie

                sync = ctx.pending_sync
                ctx.pending_sync = None
                out = jax.tree.map(lambda v: tie(sync, v), out)
        _check_carry(jax, jnp, treedef0, sig0, out, label)
        return jax.tree.map(lambda v: as_varying(v, comm.axes), out)

    leaves = jax.tree.leaves(carry)

    # whole-megastep watchdog bracket, deadline scaled by the trip count
    # (resilience/runtime.py per-op arms inside the loop are untouched)
    from ..resilience import runtime as _resilience

    timeout = _resilience.effective_watchdog_timeout()
    wd_call_id = rank = None
    if timeout is not None and leaves:
        from .. import native
        from ..resilience import watchdog as wd

        wd_call_id = _next_call_id()
        rank = comm.global_rank()
        armed = wd.arm_in_graph(f"MPI_Megastep[{label}]", wd_call_id, comm,
                                rank, timeout * n)
        carry = jax.tree.map(lambda v: native._tie(v, armed), carry)

    # one events-tier journal bracket per megastep execution
    from ..telemetry import core as _tcore

    ev_call_id = None
    if _tcore.events_on() and leaves:
        ev_call_id = _next_call_id()
        carry = _bracket_begin(ev_call_id, comm, carry, n, label)

    final = lax.fori_loop(0, n, one, carry)

    # both closers were installed only when the carry has leaves, so the
    # anchor exists exactly when it is needed
    if ev_call_id is not None or wd_call_id is not None:
        dep = jax.tree.leaves(final)[0]
    if ev_call_id is not None:
        _bracket_end(ev_call_id, comm, dep)
    if wd_call_id is not None:
        from ..resilience import watchdog as wd

        wd.disarm_in_graph(f"MPI_Megastep[{label}]", wd_call_id, comm, rank,
                           dep)
    return final


# ---------------------------------------------------------------------------
# the events-tier megastep bracket (mirrors telemetry/bracket.py, with
# megastep meta: one begin/end pair per megastep EXECUTION; the journal
# synthesizes the per-step estimate from latency / unroll)
# ---------------------------------------------------------------------------


def _io_callback(fn, operand):
    import jax
    import jax.numpy as jnp
    from jax.experimental import io_callback

    return io_callback(
        fn, jax.ShapeDtypeStruct((), jnp.uint32), operand, ordered=False
    )


def _bracket_begin(call_id: str, comm, carry, unroll: int, label: str):
    import jax
    import jax.numpy as jnp
    import numpy as np

    from .. import native
    from ..telemetry import journal

    meta = {
        "op": "megastep",
        "label": label,
        "unroll": unroll,
        "comm_uid": str(comm.uid),
        "axes": list(comm.axes),
        "bytes": 0,
        "dtype": "",
    }

    def _begin(r):
        journal.begin(call_id, int(r), meta)
        return np.uint32(r)

    rank = jnp.asarray(comm.global_rank(), jnp.uint32)
    rank = native._tie(rank, jax.tree.leaves(carry)[0])
    dep = _io_callback(_begin, rank)
    return jax.tree.map(lambda v: native._tie(v, dep), carry)


def _bracket_end(call_id: str, comm, dep):
    import jax.numpy as jnp
    import numpy as np

    from .. import native
    from ..telemetry import journal

    def _end(r):
        journal.end(call_id, int(r), {"algo": "loop"})
        return np.uint32(r)

    rank = jnp.asarray(comm.global_rank(), jnp.uint32)
    _io_callback(_end, native._tie(rank, dep))
