"""TPU Pallas kernels for hot compute paths.

The reference has no device kernels at all (its Cython "GPU" module is
host-side staging code, SURVEY.md §2.2) — compute around the collectives is
where this framework can exceed it.  Kernels here are optional accelerators:
every caller has an identical pure-``jax.numpy`` path, and the kernels are
validated against it (tests/test_kernels.py runs them in interpret mode on
CPU; the TPU build runs them natively).
"""

from .flash_attention import flash_block_partials  # noqa: F401
