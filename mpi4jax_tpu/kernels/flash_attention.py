"""Flash-attention block partials — the ring-attention hot op, in Pallas.

One ring-attention step computes attention of the local queries against one
rotating K/V block (mpi4jax_tpu/attention.py).  The Pallas kernel
fuses score computation, masking, and the streaming-softmax partials for one
(batch, head) pair entirely in VMEM — the (Tq, Tk) score matrix never
touches HBM (XLA materializes it between the einsum and the softmax in the
fallback path).

Outputs are *partials* in the standard flash/log-sum-exp form, merged across
ring steps by the caller:

    m      = rowmax(scores)                      (B, H, Tq)
    l      = rowsum(exp(scores - m))             (B, H, Tq)
    o_part = exp(scores - m) @ V                 (B, Tq, H, D)

``flash_block_partials`` dispatches to the kernel on TPU and to an
identical-math jnp path elsewhere (or under ``force_jnp=True``); interpret
mode covers CPU testing (tests/test_kernels.py; the jnp/kernel equality,
fully- and partially-masked rows, and the blockwise-merge invariant).

Both kernels stream (bq, bk) KEY TILES with online-softmax carries, so
the live score tile is fixed-size for ANY Tk — the VMEM ceiling is the
K/V residency, ~2·Tk·D·itemsize (≈ Tk 90k for f32 D=128 under the
100 MB limit; roughly half that with a user mask, whose (bq, Tk_pad)
block is also VMEM-resident), not the Tk² of a materialized score
matrix.  Round 4's non-causal kernel computed one (bq, Tk) score tile
per grid step, capping non-causal blocks at Tk ≈ 4k before VMEM
overflow (long Ulysses sequences fell back to the einsum); the
streaming rework removed that cap — verified fwd+bwd at Tk = 32768 on
chip.

Measured on one v5e chip (B=4, T=4096, H=8, D=128, f32, amortized over
a 25-iteration fori_loop with host-fetch sync; the attach tunnel makes
ABSOLUTE figures drift ~±30% minute-to-minute — docs/microbenchmarks.md
— so same-run interleaved RATIOS are the stable claims): non-causal
streaming kernel **1.8-2.4x** the XLA einsum+softmax path (5.4-7.1
ms/block = 39-51 TFLOP/s vs 12.8-13.1 ms for the einsum with all three
outputs live; earlier one-shot-kernel sessions measured the same ratio
at 2.6x).  ``causal=True`` → ``_kernel_causal`` SKIPS fully-masked key
tiles instead of masking computed scores: 1.24x the masked streaming
kernel at this config (6.2 vs 7.7 ms; ~2x less MXU work, bounded by
the shared epilogue), outputs within f32 matmul-precision noise of the
masked path (normalized attention ~6e-4 abs on this chip, where f32
dots use the MXU's bf16-multiply default in both kernels).  Historical
sessions measured these kernels as fast as 2.1-2.2 ms/block (~125
TFLOP/s); treat every absolute number as a session band.  ``bfloat16``
inputs measure within the f32 band (interleaved same-session
comparison): the MXU already multiplies in bf16 for f32 dots by
default, and operand traffic is not the bottleneck, so bf16 here saves
memory, not time.

End-to-end, the causal ring (mpi4jax_tpu/attention.py) skips
fully-masked ring steps per rank (lax.cond) and drops masking on fully-
visible blocks, so total causal FLOPs are n(n+1)/2 blocks instead of n^2.
Measured 2.10x end-to-end speedup on the 8-rank test mesh (CPU — a ring
needs multiple devices, which the single-chip TPU attach cannot host;
per-block kernel throughput above is the on-chip number and is unchanged
by the skip), with outputs within 1 ulp of the always-masked path.
"""

import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pallas TPU backend is absent on some CPU-only installs
    from jax.experimental.pallas import tpu as pltpu

    _HAS_PLTPU = True
except ImportError:  # pragma: no cover
    pltpu = None
    _HAS_PLTPU = False

_Q_TILE = 512  # query rows per grid step (keeps the score tile VMEM-sized)
# keys per streaming tile in the non-causal kernel.  Swept interleaved on a
# v5e chip at (B=4, T=4096, H=8, D=128) over {512, 1024, 2048, 4096}: 512
# was fastest (5.35 ms/block best-of-6 vs 6.2-6.6 for the larger tiles) —
# the (512, 512) score tile fits the fused VPU epilogue best, and larger
# tiles buy nothing since the per-tile rescale is already <15% of the MXU
# work.  Keeps the live score tile at 1 MB f32 for ANY Tk.
_K_TILE = 512


def _merge_tile(carry, s, vv):
    """Fold one (bq, bk) score tile into the online-softmax carry
    ``(m, l, acc)`` — the shared rescale step of both streaming kernels.
    Masked entries must already carry ``-inf`` in ``s``."""
    m0, l0, acc0 = carry
    mt = jnp.maximum(m0, s.max(axis=-1))
    # fully-masked-so-far rows: exp against a 0 stand-in, p stays 0
    mt_safe = jnp.where(jnp.isinf(mt), 0.0, mt)
    p = jnp.exp(s - mt_safe[:, None])
    p = jnp.where(jnp.isinf(s), 0.0, p)  # masked entries carry -inf
    c = jnp.where(jnp.isinf(m0), 0.0, jnp.exp(m0 - mt_safe))
    l1 = l0 * c + p.sum(axis=-1)
    acc1 = acc0 * c[:, None] + jnp.dot(
        p.astype(vv.dtype), vv, preferred_element_type=jnp.float32
    )
    return mt, l1, acc1


def _carry_init(bq, d):
    return (
        jnp.full((bq,), -jnp.inf, jnp.float32),
        jnp.zeros((bq,), jnp.float32),
        jnp.zeros((bq, d), jnp.float32),
    )


def _pad_to(x, axis, target):
    if x.shape[axis] == target:
        return x
    pad = [(0, 0)] * x.ndim
    pad[axis] = (0, target - x.shape[axis])
    return jnp.pad(x, pad)


def _kernel(*refs, bq, bk, tk, n_kt, has_mask):
    # Non-causal streaming kernel (one (batch*head, q-tile) grid step):
    # q (1, Bq, D), k/v whole (1, Tk_pad, D), [mask (Bq, Tk_pad) — absent
    # when unmasked], o (1, Bq, D), m/l (1, 1, Bq).
    # A fori_loop walks (Bq, bk) KEY TILES with online-softmax carries, so
    # the live score tile is a fixed (Bq, bk) regardless of Tk — long
    # non-causal blocks no longer materialize a (Bq, Tk) score tile (the
    # pre-round-5 kernel did, capping Tk at ~4k before VMEM overflow).
    # Mosaic tiling requires the last two block dims be (8, 128)-divisible
    # or span the whole array — hence the flattened (B*H, T, D) layout
    # (a (1, Tq, 1, D) block over (B, Tq, H, D) is not lowerable).
    if has_mask:
        q_ref, k_ref, v_ref, mask_ref, o_ref, m_ref, l_ref = refs
    else:
        q_ref, k_ref, v_ref, o_ref, m_ref, l_ref = refs
        mask_ref = None
    q = q_ref[0]
    d = q.shape[-1]

    ragged = tk != n_kt * bk

    def body(kt, carry):
        kk = k_ref[0, pl.dslice(kt * bk, bk), :]
        vv = v_ref[0, pl.dslice(kt * bk, bk), :]
        s = jnp.dot(q, kk.T, preferred_element_type=jnp.float32)
        valid = None
        if ragged:  # padded tail keys never attend
            kpos = kt * bk + jax.lax.broadcasted_iota(
                jnp.int32, (bq, bk), 1
            )
            valid = kpos < tk
        if mask_ref is not None:
            mt_tile = mask_ref[:, pl.dslice(kt * bk, bk)]
            valid = mt_tile if valid is None else valid & mt_tile
        if valid is not None:
            s = jnp.where(valid, s, -jnp.inf)
        return _merge_tile(carry, s, vv)

    m, l, acc = jax.lax.fori_loop(0, n_kt, body, _carry_init(bq, d))
    o_ref[0] = acc.astype(o_ref.dtype)
    m_ref[0, 0] = m
    l_ref[0, 0] = l


def _kernel_causal(q_ref, k_ref, v_ref, o_ref, m_ref, l_ref, *, bq, bk, tk):
    """Causal diagonal-block kernel with KEY-TILE SKIPPING: query tile
    ``qi`` only touches key tiles ``0..qi`` — a ``fori_loop`` over the
    fully-visible tiles (no masking at all) plus one triangular-masked
    boundary tile — so the MXU does ~half the work of the
    compute-everything-then-mask kernel on a causal block.  Streaming
    (online-softmax) accumulators carry across key tiles; outputs are the
    same partials contract as ``_kernel``."""
    qi = pl.program_id(1)
    q = q_ref[0]
    d = q.shape[-1]

    def load_tile(ref, kt):
        return ref[0, pl.dslice(kt * bk, bk), :]

    def body(kt, carry):
        s = jnp.dot(q, load_tile(k_ref, kt).T,
                    preferred_element_type=jnp.float32)
        return _merge_tile(carry, s, load_tile(v_ref, kt))

    m, l, acc = jax.lax.fori_loop(0, qi, body, _carry_init(bq, d))

    # boundary tile: triangular causal mask on global positions, plus the
    # ragged-tail guard (the final tile's rows beyond tk read clamped data)
    s = jnp.dot(q, load_tile(k_ref, qi).T, preferred_element_type=jnp.float32)
    qpos = qi * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
    kpos = qi * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
    s = jnp.where((qpos >= kpos) & (kpos < tk), s, -jnp.inf)
    m, l, acc = _merge_tile((m, l, acc), s, load_tile(v_ref, qi))

    o_ref[0] = acc.astype(o_ref.dtype)
    m_ref[0, 0] = m
    l_ref[0, 0] = l


def _partials_impl(q, k, v, mask, scale, causal, interpret, force_jnp):
    """Forward partials — see ``flash_block_partials`` for the contract."""
    b, tq, h, d = q.shape
    tk = k.shape[1]

    use_kernel = _HAS_PLTPU and not force_jnp and (
        interpret or jax.default_backend() == "tpu"
    )
    if not use_kernel:
        if causal:
            mask = jnp.tril(jnp.ones((tq, tk), bool))
        # scores/partials in f32, matching the kernel's accumulators, so
        # the two paths agree for sub-f32 inputs too
        s = jnp.einsum(
            "bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32
        ) * jnp.float32(scale)
        if mask is not None:
            s = jnp.where(mask[None, None], s, -jnp.inf)
        m = s.max(axis=-1)
        m_safe = jnp.where(jnp.isinf(m), 0.0, m) if mask is not None else m
        p = jnp.exp(s - m_safe[..., None])
        if mask is not None:
            p = jnp.where(mask[None, None], p, 0.0)
        l = p.sum(axis=-1)
        o = jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v)
        return o.astype(q.dtype), m, l

    qs = q * jnp.asarray(scale, q.dtype)

    # flatten to the (B*H, T, D) flash layout (see _kernel); both kernels
    # walk (bq, bk) key tiles, so the live score tile is fixed-size and the
    # VMEM ceiling is set by the K/V residency (~2·Tk·D·itemsize), not Tk²
    def to_bht(x, t):
        return jnp.moveaxis(x, 2, 1).reshape(b * h, t, d)

    bq = _Q_TILE if tq > _Q_TILE else tq  # partial final tiles are fine
    bk = bq if causal else (_K_TILE if tk > _K_TILE else tk)
    n_kt = (tk + bk - 1) // bk
    tk_pad = n_kt * bk
    grid = (b * h, (tq + bq - 1) // bq)
    # under shard_map with VMA checking (ring attention on a mesh) the
    # outputs must be typed varying over the same axes as the inputs
    vma = frozenset(getattr(jax.typeof(q), "vma", frozenset()))
    out_shapes = (
        jax.ShapeDtypeStruct((b * h, tq, d), q.dtype, vma=vma),
        jax.ShapeDtypeStruct((b * h, 1, tq), jnp.float32, vma=vma),
        jax.ShapeDtypeStruct((b * h, 1, tq), jnp.float32, vma=vma),
    )
    q_spec = pl.BlockSpec((1, bq, d), lambda i, j: (i, j, 0),
                          memory_space=pltpu.VMEM)
    kv_spec = pl.BlockSpec((1, tk_pad, d), lambda i, j: (i, 0, 0),
                           memory_space=pltpu.VMEM)
    ml_spec = pl.BlockSpec((1, 1, bq), lambda i, j: (i, 0, j),
                           memory_space=pltpu.VMEM)
    in_specs = [q_spec, kv_spec, kv_spec]
    # pad K/V to a whole number of key tiles: pl.dslice would CLAMP the
    # last tile's start otherwise, silently misaligning the positional
    # masks; padded keys sit at kpos >= tk, which both kernels' ragged
    # guards discard
    kf = _pad_to(to_bht(k, tk), 1, tk_pad)
    vf = _pad_to(to_bht(v, tk), 1, tk_pad)
    operands = [to_bht(qs, tq), kf, vf]
    if causal:
        kernel = functools.partial(_kernel_causal, bq=bq, bk=bq, tk=tk)
    else:
        kernel = functools.partial(
            _kernel, bq=bq, bk=bk, tk=tk, n_kt=n_kt,
            has_mask=mask is not None,
        )
        if mask is not None:
            in_specs.append(
                pl.BlockSpec((bq, tk_pad), lambda i, j: (j, 0),
                             memory_space=pltpu.VMEM)
            )
            operands.append(_pad_to(mask, 1, tk_pad))
    o_bht, m_f, l_f = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=(q_spec, ml_spec, ml_spec),
        out_shape=out_shapes,
        interpret=interpret,
        compiler_params=(
            None if interpret else pltpu.CompilerParams(
                vmem_limit_bytes=100 * 1024 * 1024
            )
        ),
    )(*operands)
    o = jnp.moveaxis(o_bht.reshape(b, h, tq, d), 1, 2)
    m = m_f.reshape(b, h, tq)
    l = l_f.reshape(b, h, tq)
    return o, m, l


# ---------------------------------------------------------------------------
# backward (custom VJP)
# ---------------------------------------------------------------------------
#
# The partials map f(q, k, v) = (o_part, m, l) gets a blockwise custom VJP so
# `jax.grad` composes with the Pallas forward ON TPU (the kernel has no
# transpose rule of its own; before round 5 grads only worked on the CPU/jnp
# fallback).  The backward recomputes p = exp(s - m) tile-by-tile from the
# (q, k, v, m) residuals — the (Tq, Tk) score matrix never materializes in
# HBM, mirroring the forward — and applies
#
#     dp = g_o @ v^T + g_l          ds = p * dp * scale
#     dq = ds @ k                   dk = ds^T @ q_scaled
#     dv = p^T @ g_o
#
# Measured on the v5e chip at (B=4, T=4096, H=8, D=128, f32), interleaved
# 15-iteration fori_loop amortization: full grad (fwd + both backward
# kernels) costs ~2.4x the forward alone non-causal (16.5 vs 7.0 ms/iter
# in one session) — consistent with the backward's ~2.5x matmul FLOPs (5
# tile dots vs the forward's 2) — and ~1.6x causal (12.8 vs 8.1 ms),
# where both backward kernels inherit the key-tile skipping via their
# loop bounds.  Session-band caveats as in the module docstring.
#
# Stabilizer semantics: `m` is treated as `stop_gradient` — its incoming
# cotangent is DROPPED.  This is exact for every numerically sane consumer:
# the downstream combination (merge_partials chains + the final `acc / l`
# normalization) is invariant to the stabilizer (shifting m while rescaling
# o_part and l by exp(m - m') leaves the result unchanged), so the composed
# gradient equals JAX's argmax-routed gradient of the jnp path in exact
# arithmetic.  Differentiating a function of `m` *alone* (e.g. `sum(m)`) is
# outside the contract and returns zero.


def _bwd_dq_kernel(*refs, scale, causal, bq, bk, tk, n_kt, has_mask):
    # grid step (i, qj): q/g_o tiles (1, bq, d), m/g_l (1, 1, bq),
    # k/v whole (1, tk_pad, d), [mask (bq, tk_pad)], out dq (1, bq, d).
    if has_mask:
        q_ref, k_ref, v_ref, m_ref, gl_ref, go_ref, mask_ref, dq_ref = refs
    else:
        q_ref, k_ref, v_ref, m_ref, gl_ref, go_ref, dq_ref = refs
        mask_ref = None
    qj = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32)
    go = go_ref[0].astype(jnp.float32)
    m = m_ref[0, 0]
    gl = gl_ref[0, 0]
    m_safe = jnp.where(jnp.isinf(m), 0.0, m)
    d = q.shape[-1]
    qpos = qj * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)

    def body(kt, acc):
        kk = k_ref[0, pl.dslice(kt * bk, bk), :].astype(jnp.float32)
        vv = v_ref[0, pl.dslice(kt * bk, bk), :].astype(jnp.float32)
        s = jnp.dot(q, kk.T, preferred_element_type=jnp.float32) * scale
        kpos = kt * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
        valid = kpos < tk
        if causal:
            valid &= qpos >= kpos
        if mask_ref is not None:
            valid &= mask_ref[:, pl.dslice(kt * bk, bk)]
        p = jnp.where(valid, jnp.exp(s - m_safe[:, None]), 0.0)
        dp = jnp.dot(go, vv.T, preferred_element_type=jnp.float32)
        dp = dp + gl[:, None]
        ds = p * dp * scale
        return acc + jnp.dot(ds, kk, preferred_element_type=jnp.float32)

    hi = qj + 1 if causal else n_kt  # causal: key tiles past qj fully masked
    acc = jax.lax.fori_loop(0, hi, body, jnp.zeros((bq, d), jnp.float32))
    dq_ref[0] = acc.astype(dq_ref.dtype)


def _bwd_dkv_kernel(*refs, scale, causal, bq, bk, tq, tk, n_qt, has_mask):
    # grid step (i, kj): k/v tiles (1, bk, d), q/g_o whole (1, tq_pad, d),
    # m/g_l whole (1, tq_pad, 1), [mask (tq_pad, bk)], out dk/dv (1, bk, d).
    # m/g_l arrive TRANSPOSED (query positions on the SUBLANE dim): the
    # fori_loop below slices them at qj*bq, and Mosaic requires lane-dim
    # dynamic offsets to be provable multiples of 128 — only true when bq
    # is itself a multiple of 128, and bq = min(Tq, 512) — while sublane
    # offsets only need multiples of 8 (every bq here is).
    if has_mask:
        (q_ref, k_ref, v_ref, m_ref, gl_ref, go_ref, mask_ref,
         dk_ref, dv_ref) = refs
    else:
        q_ref, k_ref, v_ref, m_ref, gl_ref, go_ref, dk_ref, dv_ref = refs
        mask_ref = None
    kj = pl.program_id(1)
    kk = k_ref[0].astype(jnp.float32)
    vv = v_ref[0].astype(jnp.float32)
    d = kk.shape[-1]
    kpos = kj * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)

    def body(qj, carry):
        dk_acc, dv_acc = carry
        qt = q_ref[0, pl.dslice(qj * bq, bq), :].astype(jnp.float32)
        got = go_ref[0, pl.dslice(qj * bq, bq), :].astype(jnp.float32)
        mt = m_ref[0, pl.dslice(qj * bq, bq), 0]
        glt = gl_ref[0, pl.dslice(qj * bq, bq), 0]
        m_safe = jnp.where(jnp.isinf(mt), 0.0, mt)
        s = jnp.dot(qt, kk.T, preferred_element_type=jnp.float32) * scale
        qpos = qj * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
        valid = (kpos < tk) & (qpos < tq)
        if causal:
            valid &= qpos >= kpos
        if mask_ref is not None:
            valid &= mask_ref[pl.dslice(qj * bq, bq), :]
        p = jnp.where(valid, jnp.exp(s - m_safe[:, None]), 0.0)
        dp = jnp.dot(got, vv.T, preferred_element_type=jnp.float32)
        dp = dp + glt[:, None]
        ds = p * dp * scale
        dk_acc = dk_acc + jnp.dot(
            ds.T, qt, preferred_element_type=jnp.float32
        )
        dv_acc = dv_acc + jnp.dot(
            p.T, got, preferred_element_type=jnp.float32
        )
        return dk_acc, dv_acc

    lo = kj if causal else 0  # causal: query tiles before kj see no key here
    zeros = jnp.zeros((bk, d), jnp.float32)
    dk, dv = jax.lax.fori_loop(lo, n_qt, body, (zeros, zeros))
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _partials_bwd_impl(q, k, v, mask, m, g_o, g_l, scale, causal, interpret):
    b, tq, h, d = q.shape
    tk = k.shape[1]
    bq = _Q_TILE if tq > _Q_TILE else tq
    bk = bq if causal else (_Q_TILE if tk > _Q_TILE else tk)
    n_qt = (tq + bq - 1) // bq
    n_kt = (tk + bk - 1) // bk
    tq_pad, tk_pad = n_qt * bq, n_kt * bk

    def to_bht(x, t, tp):
        return _pad_to(jnp.moveaxis(x, 2, 1).reshape(b * h, t, d), 1, tp)

    qf = to_bht(q, tq, tq_pad)
    gof = to_bht(g_o, tq, tq_pad)
    kf = to_bht(k, tk, tk_pad)
    vf = to_bht(v, tk, tk_pad)
    # padded m rows are 0 (finite): their p is finite garbage, but padded
    # g_o/g_l rows are 0 so every contribution they touch is 0, and the
    # qpos/kpos guards zero them in dk/dv anyway
    mf = _pad_to(m.reshape(b * h, 1, tq), 2, tq_pad)
    glf = _pad_to(g_l.reshape(b * h, 1, tq), 2, tq_pad)
    maskf = None
    if mask is not None:
        maskf = _pad_to(_pad_to(mask, 0, tq_pad), 1, tk_pad)

    vma = frozenset(getattr(jax.typeof(q), "vma", frozenset()))
    tile_spec = pl.BlockSpec((1, bq, d), lambda i, j: (i, j, 0),
                             memory_space=pltpu.VMEM)
    ktile_spec = pl.BlockSpec((1, bk, d), lambda i, j: (i, j, 0),
                              memory_space=pltpu.VMEM)
    qwhole_spec = pl.BlockSpec((1, tq_pad, d), lambda i, j: (i, 0, 0),
                               memory_space=pltpu.VMEM)
    kwhole_spec = pl.BlockSpec((1, tk_pad, d), lambda i, j: (i, 0, 0),
                               memory_space=pltpu.VMEM)
    mtile_spec = pl.BlockSpec((1, 1, bq), lambda i, j: (i, 0, j),
                              memory_space=pltpu.VMEM)
    params = (
        None if interpret else pltpu.CompilerParams(
            vmem_limit_bytes=100 * 1024 * 1024
        )
    )

    # dq: one grid step per (batch*head, query tile), loop over key tiles
    dq_in_specs = [tile_spec, kwhole_spec, kwhole_spec, mtile_spec,
                   mtile_spec, tile_spec]
    dq_operands = [qf, kf, vf, mf, glf, gof]
    if maskf is not None:
        dq_in_specs.append(
            pl.BlockSpec((bq, tk_pad), lambda i, j: (j, 0),
                         memory_space=pltpu.VMEM)
        )
        dq_operands.append(maskf)
    dq_f = pl.pallas_call(
        functools.partial(
            _bwd_dq_kernel, scale=scale, causal=causal, bq=bq, bk=bk,
            tk=tk, n_kt=n_kt, has_mask=maskf is not None,
        ),
        grid=(b * h, n_qt),
        in_specs=dq_in_specs,
        out_specs=tile_spec,
        out_shape=jax.ShapeDtypeStruct((b * h, tq_pad, d), q.dtype, vma=vma),
        interpret=interpret,
        compiler_params=params,
    )(*dq_operands)

    # dk/dv: one grid step per (batch*head, key tile), loop over query
    # tiles.  m/g_l go in TRANSPOSED — (bh, tq_pad, 1), query positions on
    # the sublane dim — because the kernel's fori_loop slices them at
    # qj*bq and lane-dim dynamic offsets must be provable multiples of
    # 128, which only holds when bq is one (sublane offsets need 8s).
    mT_spec = pl.BlockSpec((1, tq_pad, 1), lambda i, j: (i, 0, 0),
                           memory_space=pltpu.VMEM)
    mf_t = jnp.swapaxes(mf, 1, 2)
    glf_t = jnp.swapaxes(glf, 1, 2)
    dkv_in_specs = [qwhole_spec, ktile_spec, ktile_spec, mT_spec,
                    mT_spec, qwhole_spec]
    dkv_operands = [qf, kf, vf, mf_t, glf_t, gof]
    if maskf is not None:
        dkv_in_specs.append(
            pl.BlockSpec((tq_pad, bk), lambda i, j: (0, j),
                         memory_space=pltpu.VMEM)
        )
        dkv_operands.append(maskf)
    dk_f, dv_f = pl.pallas_call(
        functools.partial(
            _bwd_dkv_kernel, scale=scale, causal=causal, bq=bq, bk=bk,
            tq=tq, tk=tk, n_qt=n_qt, has_mask=maskf is not None,
        ),
        grid=(b * h, n_kt),
        in_specs=dkv_in_specs,
        out_specs=(ktile_spec, ktile_spec),
        out_shape=(
            jax.ShapeDtypeStruct((b * h, tk_pad, d), k.dtype, vma=vma),
            jax.ShapeDtypeStruct((b * h, tk_pad, d), v.dtype, vma=vma),
        ),
        interpret=interpret,
        compiler_params=params,
    )(*dkv_operands)

    def from_bht(x, t):
        return jnp.moveaxis(x[:, :t].reshape(b, h, t, d), 1, 2)

    return from_bht(dq_f, tq), from_bht(dk_f, tk), from_bht(dv_f, tk)


@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1, 2))
def _partials(scale, causal, interpret, q, k, v, mask):
    return _partials_impl(q, k, v, mask, scale, causal, interpret, False)


def _partials_fwd(scale, causal, interpret, q, k, v, mask):
    o, m, l = _partials_impl(q, k, v, mask, scale, causal, interpret, False)
    return (o, m, l), (q, k, v, mask, m)


def _partials_bwd(scale, causal, interpret, res, cts):
    q, k, v, mask, m = res
    g_o, _g_m, g_l = cts  # g_m dropped: stop-gradient stabilizer (see above)
    dq, dk, dv = _partials_bwd_impl(
        q, k, v, mask, m, g_o, g_l, scale, causal, interpret
    )
    dmask = None if mask is None else np.zeros(mask.shape, jax.dtypes.float0)
    return dq, dk, dv, dmask


_partials.defvjp(_partials_fwd, _partials_bwd)


@functools.partial(
    jax.jit, static_argnames=("scale", "causal", "interpret", "force_jnp")
)
def flash_block_partials(
    q,
    k,
    v,
    mask,
    *,
    scale: float,
    causal: bool = False,
    interpret: bool = False,
    force_jnp: bool = False,
):
    """Streaming-softmax partials of ``softmax(q k^T * scale) v`` for one
    K/V block.

    ``q``: (B, Tq, H, D); ``k``/``v``: (B, Tk, H, D); ``mask``: (Tq, Tk)
    bool, True = attend (shared across batch and heads — the ring-step
    causal mask depends only on block offsets), or ``None`` for no masking
    (skips the mask load and selects entirely).

    ``causal=True`` (requires ``mask=None`` and ``Tq == Tk``) declares the
    triangular diagonal-block pattern *structurally*, which lets the TPU
    path use the key-tile-skipping kernel (``_kernel_causal``): ~2x less
    MXU work than masking a fully-computed score block.  Semantically
    identical to ``mask=jnp.tril(...)``.

    Returns ``(o_part, m, l)`` with shapes (B, Tq, H, D), (B, H, Tq),
    (B, H, Tq); ``m``/``l`` are float32, ``o_part`` keeps ``q``'s dtype
    (both paths).  Rows with no attendable key get ``m = -inf``, ``l = 0``,
    ``o_part = 0``.

    **Differentiable on every backend.**  The kernel path carries a
    blockwise custom VJP (Pallas backward kernels — the score matrix never
    reaches HBM in either direction); the jnp fallback is left unwrapped,
    so it keeps JAX's full native autodiff including *forward mode*.
    Forward-mode through the kernel path is unsupported (``jax.jvp``
    raises ``TypeError`` on a ``custom_vjp`` function — same reach as the
    reference's CPU/GPU builds, where p2p forward-mode also raises).  The
    custom VJP treats the stabilizer output ``m`` as ``stop_gradient``:
    any stabilizer-invariant consumer (``merge_partials`` chains, the
    ``acc / l`` normalization — i.e. any correct use) gets exact gradients;
    differentiating ``m`` in isolation returns zero by design.
    """
    if causal:
        if mask is not None:
            raise ValueError("causal=True replaces mask; pass mask=None")
        if q.shape[1] != k.shape[1]:
            raise ValueError(
                f"causal=True is the diagonal-block pattern and needs "
                f"Tq == Tk, got {q.shape[1]} vs {k.shape[1]}"
            )
    use_kernel = _HAS_PLTPU and not force_jnp and (
        interpret or jax.default_backend() == "tpu"
    )
    if use_kernel:
        return _partials(scale, causal, interpret, q, k, v, mask)
    return _partials_impl(q, k, v, mask, scale, causal, interpret, force_jnp)


def merge_partials(acc, m, l, o_new, m_new, l_new):
    """Log-sum-exp merge of two partial-attention states (the flash
    combine rule); all rows stay in the (B,H,Tq)/(B,Tq,H,D) layout."""
    m_out = jnp.maximum(m, m_new)
    m_safe = jnp.where(jnp.isinf(m_out), 0.0, m_out)
    c_old = jnp.where(jnp.isinf(m), 0.0, jnp.exp(m - m_safe))
    c_new = jnp.where(jnp.isinf(m_new), 0.0, jnp.exp(m_new - m_safe))
    l_out = l * c_old + l_new * c_new
    to_qhd = lambda c: jnp.moveaxis(c, 1, 2)[..., None]  # noqa: E731
    acc_out = acc * to_qhd(c_old) + o_new * to_qhd(c_new)
    return acc_out, m_out, l_out
