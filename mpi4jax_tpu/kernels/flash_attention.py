"""Flash-attention block partials — the ring-attention hot op, in Pallas.

One ring-attention step computes attention of the local queries against one
rotating K/V block (examples/long_context_attention.py).  The Pallas kernel
fuses score computation, masking, and the streaming-softmax partials for one
(batch, head) pair entirely in VMEM — the (Tq, Tk) score matrix never
touches HBM (XLA materializes it between the einsum and the softmax in the
fallback path).

Outputs are *partials* in the standard flash/log-sum-exp form, merged across
ring steps by the caller:

    m      = rowmax(scores)                      (B, H, Tq)
    l      = rowsum(exp(scores - m))             (B, H, Tq)
    o_part = exp(scores - m) @ V                 (B, Tq, H, D)

``flash_block_partials`` dispatches to the kernel on TPU and to an
identical-math jnp path elsewhere (or under ``force_jnp=True``); interpret
mode covers CPU testing (tests/test_kernels.py).
"""

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pallas TPU backend is absent on some CPU-only installs
    from jax.experimental.pallas import tpu as pltpu

    _HAS_PLTPU = True
except ImportError:  # pragma: no cover
    pltpu = None
    _HAS_PLTPU = False


def _kernel(q_ref, k_ref, v_ref, mask_ref, o_ref, m_ref, l_ref):
    # refs: q (1, Tq, 1, D), k/v (1, Tk, 1, D), mask (Tq, Tk),
    #       o (1, Tq, 1, D), m/l (1, 1, Tq)
    q = q_ref[0, :, 0, :]
    k = k_ref[0, :, 0, :]
    v = v_ref[0, :, 0, :]
    s = jnp.dot(q, k.T, preferred_element_type=jnp.float32)
    s = jnp.where(mask_ref[:, :], s, -jnp.inf)
    m = jnp.max(s, axis=-1)
    # fully-masked rows: exp(-inf - -inf) would be nan; zero them instead
    m_safe = jnp.where(jnp.isinf(m), 0.0, m)
    p = jnp.exp(s - m_safe[:, None])
    p = jnp.where(mask_ref[:, :], p, 0.0)
    l = jnp.sum(p, axis=-1)
    o = jnp.dot(p.astype(v.dtype), v, preferred_element_type=jnp.float32)
    o_ref[0, :, 0, :] = o.astype(o_ref.dtype)
    m_ref[0, 0, :] = m
    l_ref[0, 0, :] = l


@functools.partial(jax.jit, static_argnames=("scale", "interpret", "force_jnp"))
def flash_block_partials(
    q,
    k,
    v,
    mask,
    *,
    scale: float,
    interpret: bool = False,
    force_jnp: bool = False,
):
    """Streaming-softmax partials of ``softmax(q k^T * scale) v`` for one
    K/V block.

    ``q``: (B, Tq, H, D); ``k``/``v``: (B, Tk, H, D); ``mask``: (Tq, Tk)
    bool, True = attend (shared across batch and heads — the ring-step
    causal mask depends only on block offsets).

    Returns ``(o_part, m, l)`` with shapes (B, Tq, H, D), (B, H, Tq),
    (B, H, Tq); rows with no attendable key get ``m = -inf``, ``l = 0``,
    ``o_part = 0``.
    """
    b, tq, h, d = q.shape
    tk = k.shape[1]

    use_kernel = _HAS_PLTPU and not force_jnp and (
        interpret or jax.default_backend() == "tpu"
    )
    if not use_kernel:
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
        s = jnp.where(mask[None, None], s, -jnp.inf)
        m = s.max(axis=-1)
        m_safe = jnp.where(jnp.isinf(m), 0.0, m)
        p = jnp.exp(s - m_safe[..., None])
        p = jnp.where(mask[None, None], p, 0.0)
        l = p.sum(axis=-1)
        o = jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v)
        return o, m, l

    qs = q * jnp.asarray(scale, q.dtype)
    grid = (b, h)
    out_shapes = (
        jax.ShapeDtypeStruct((b, tq, h, d), q.dtype),
        jax.ShapeDtypeStruct((b, h, tq), jnp.float32),
        jax.ShapeDtypeStruct((b, h, tq), jnp.float32),
    )
    qkv_spec = lambda t: pl.BlockSpec(  # noqa: E731
        (1, t, 1, d), lambda i, j: (i, 0, j, 0), memory_space=pltpu.VMEM
    )
    ml_spec = pl.BlockSpec(
        (1, 1, tq), lambda i, j: (i, j, 0), memory_space=pltpu.VMEM
    )
    return pl.pallas_call(
        _kernel,
        grid=grid,
        in_specs=[
            qkv_spec(tq),
            qkv_spec(tk),
            qkv_spec(tk),
            pl.BlockSpec((tq, tk), lambda i, j: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=(qkv_spec(tq), ml_spec, ml_spec),
        out_shape=out_shapes,
        interpret=interpret,
    )(qs, k, v, mask)


def merge_partials(acc, m, l, o_new, m_new, l_new):
    """Log-sum-exp merge of two partial-attention states (the flash
    combine rule); all rows stay in the (B,H,Tq)/(B,Tq,H,D) layout."""
    m_out = jnp.maximum(m, m_new)
    m_safe = jnp.where(jnp.isinf(m_out), 0.0, m_out)
    c_old = jnp.where(jnp.isinf(m), 0.0, jnp.exp(m - m_safe))
    c_new = jnp.where(jnp.isinf(m_new), 0.0, jnp.exp(m_new - m_safe))
    l_out = l * c_old + l_new * c_new
    to_qhd = lambda c: jnp.moveaxis(c, 1, 2)[..., None]  # noqa: E731
    acc_out = acc * to_qhd(c_old) + o_new * to_qhd(c_new)
    return acc_out, m_out, l_out
