"""Experimental namespace (ref parity: mpi4jax/experimental/).

The reference's only populated experimental module is ``notoken`` — the full
primitive set re-implemented on JAX ordered effects so no user-visible
tokens are needed (ref mpi4jax/experimental/notoken/__init__.py:2-13).
"""

from . import notoken  # noqa: F401
