"""Tokenless API: every op returns only its data.

Ref parity: ``mpi4jax.experimental.notoken`` re-implements all 12 ops on
JAX's *ordered effects* so XLA threads an implicit token and users never
touch one (ref experimental/notoken/collective_ops/*.py; SURVEY.md §2.3).
All 13 ops here (the reference's 12 plus ``reduce_scatter``, which it
lacks) get the tokenless variant.

In this framework the tokenless style is the *primary* design: the SPMD
model compiles ONE program for all ranks, so cross-rank schedule divergence
(the deadlock class tokens exist to prevent, ref docs/sharp-bits.rst) is
impossible by construction, and XLA's own data/collective ordering provides
the per-rank execution order.  These wrappers are therefore thin: call the
main op with ``token=None`` and drop the returned token.

The reverse delegation also holds: with ``MPI4JAX_TPU_PREFER_NOTOKEN=1``
the token API skips threading ``optimization_barrier`` chains (ref
``MPI4JAX_PREFER_NOTOKEN``, _src/utils.py:175-177).

Signatures match the reference's notoken variants: data in, data out —
``send`` and ``barrier`` return ``None`` (ref notoken/collective_ops/
send.py:211-212 and barrier.py:146-147 return no value).
"""

from typing import Optional

from .. import ops as _ops
from ..ops import SUM, OpLike, Status
from ..parallel.comm import Comm


def allreduce(x, op: OpLike = SUM, *, comm: Optional[Comm] = None):
    res, _ = _ops.allreduce(x, op, comm=comm)
    return res


def allgather(x, *, comm: Optional[Comm] = None):
    res, _ = _ops.allgather(x, comm=comm)
    return res


def alltoall(x, *, comm: Optional[Comm] = None):
    res, _ = _ops.alltoall(x, comm=comm)
    return res


def barrier(*, comm: Optional[Comm] = None) -> None:
    """Synchronize all ranks.

    The barrier's token is deposited in the region context
    (``RegionContext.pending_sync``): the next op — or the region's outputs —
    consumes it, so the synchronizing collective survives DCE and subsequent
    work is ordered after it (the ordered-effects analog; ref
    notoken/collective_ops/barrier.py:146-147 declares {ordered_effect})."""
    from ..ops.token import deposit_sync
    from ..parallel.region import in_parallel_region, resolve_comm

    tok = _ops.barrier(comm=comm)
    if not in_parallel_region(resolve_comm(comm)):
        return  # eager: the one-op program already executed
    deposit_sync(tok)


def bcast(x, root: int, *, comm: Optional[Comm] = None):
    res, _ = _ops.bcast(x, root, comm=comm)
    return res


def gather(x, root: int, *, comm: Optional[Comm] = None):
    res, _ = _ops.gather(x, root, comm=comm)
    return res


def recv(x, source=None, tag: int = 0, *, comm: Optional[Comm] = None,
         status: Optional[Status] = None):
    res, _ = _ops.recv(x, source, tag, comm=comm, status=status)
    return res


def reduce(x, op: OpLike, root: int, *, comm: Optional[Comm] = None):
    res, _ = _ops.reduce(x, op, root, comm=comm)
    return res


def reduce_scatter(x, op: OpLike = SUM, *, comm: Optional[Comm] = None):
    res, _ = _ops.reduce_scatter(x, op, comm=comm)
    return res


def scan(x, op: OpLike = SUM, *, comm: Optional[Comm] = None):
    res, _ = _ops.scan(x, op, comm=comm)
    return res


def scatter(x, root: int, *, comm: Optional[Comm] = None):
    res, _ = _ops.scatter(x, root, comm=comm)
    return res


def send(x, dest, tag: int = 0, *, comm: Optional[Comm] = None) -> None:
    _ops.send(x, dest, tag, comm=comm)


def sendrecv(sendbuf, recvbuf, source=None, dest=None, *, sendtag: int = 0,
             recvtag: int = 0, comm: Optional[Comm] = None,
             status: Optional[Status] = None):
    res, _ = _ops.sendrecv(
        sendbuf, recvbuf, source, dest, sendtag=sendtag, recvtag=recvtag,
        comm=comm, status=status,
    )
    return res
