"""Sequence/context-parallel attention on the communication primitives.

The reference contains no sequence parallelism (SURVEY.md §5) — but its
primitive set is exactly what the standard long-context schemes are built
from.  This module implements both standard schemes TPU-natively on
mpi4jax_tpu's primitives — first-class package API, demoed by
examples/long_context_attention.py and documented in docs/long_context.md:

- **ring attention** (blockwise attention over a ``sendrecv`` ring;
  Liu et al. 2023): each rank holds a sequence shard of K/V and rotates it
  around the ring with ``shift(1)`` — one CollectivePermute per step over
  ICI — accumulating attention with a streaming (flash-style) softmax.
  Memory per chip stays O(T/n) — in the BACKWARD too: a custom VJP saves
  only rank-local residuals and re-rotates K/V during the backward, with
  dK/dV accumulators traveling the ring (see ``ring_attention``) —
  enabling sequences n× longer than one chip could hold; compute overlaps
  the permutes (XLA pipelines the unrolled steps).
  Causal runs compute only the visible blocks (fully-masked ring
  steps are skipped per rank via ``lax.cond``; fully-visible blocks skip
  masking) — n(n+1)/2 blocks of MXU work instead of n², measured 2.10×
  end-to-end on the 8-rank test mesh — and the diagonal block uses the
  key-tile-skipping causal kernel (1.66× that block on TPU, see
  kernels/flash_attention.py).
- **Ulysses-style attention** (``alltoall`` head exchange; Jacobs et al.
  2023): two all-to-alls re-shard from sequence-parallel to head-parallel
  and back, with full-sequence local attention in between.

Both are exact (not approximations) and match single-device attention to
f32 precision — see tests/test_long_context.py.
"""

import math

import jax
import jax.numpy as jnp
from functools import partial

import mpi4jax_tpu as mpx
from .experimental import notoken
from .kernels.flash_attention import flash_block_partials, merge_partials

__all__ = [
    "flash_attention",
    "reference_attention",
    "ring_attention",
    "ulysses_attention",
]


def reference_attention(q, k, v, *, causal=False):
    """Plain full attention (B, T, H, D) — the single-device ground truth."""
    scale = 1.0 / math.sqrt(q.shape[-1])
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if causal:
        tq, tk = s.shape[-2], s.shape[-1]
        mask = jnp.tril(jnp.ones((tq, tk), bool))
        s = jnp.where(mask, s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)


def ring_attention(q, k, v, *, comm=None, causal=False,
                   memory_efficient_grad=True):
    """Exact blockwise attention over a K/V ring.

    ``q``/``k``/``v``: rank-local sequence shards ``(B, T_local, H, D)``;
    the global sequence is the rank-order concatenation.  Returns the local
    shard of the attention output.  Call inside a parallel region.

    The per-block attention partials come from
    ``mpi4jax_tpu.kernels.flash_attention``: the fused Pallas kernel on TPU
    (the (Tq, Tk) score matrix never leaves VMEM), the identical-math jnp
    path elsewhere; ``merge_partials`` is the flash combine rule across
    ring steps.

    ``memory_efficient_grad=True`` (default) gives the ring its own custom
    VJP: the forward saves only rank-LOCAL tensors plus the final softmax
    stats — O(T/n) per chip — and the backward RE-ROTATES K/V around the
    ring, accumulating dK/dV gradients that travel with their blocks (one
    extra full ring of communication; blockwise kernels throughout, so no
    score matrix materializes).  Plain reverse-mode AD through the forward
    would instead pin every rotated K/V block (plus each step's merge
    accumulator) as residuals — O(T_global) per chip, silently forfeiting
    ring attention's defining memory property exactly when sequences are
    long.  Set ``False`` to use plain AD (keeps ``jax.jvp`` forward-mode
    support, which a ``custom_vjp`` function cannot offer).
    """
    comm = comm if comm is not None else mpx.get_default_comm()
    if memory_efficient_grad:
        return _ring_attention_me(causal, comm, q, k, v)
    out, _m, _l = _ring_forward(q, k, v, comm, causal)
    return out


def _ring_forward(q, k, v, comm, causal):
    """The ring forward; returns the normalized output AND the final
    streaming-softmax stats (m, l) so the memory-efficient backward can
    reconstruct per-block probabilities without storing blocks."""
    size = comm.Get_size()
    rank = comm.Get_rank()
    b, t_loc, h, d = q.shape
    scale = 1.0 / math.sqrt(d)

    # streaming-softmax accumulators (flash-attention style)
    m = jnp.full((b, h, t_loc), -jnp.inf, jnp.float32)
    l = jnp.zeros((b, h, t_loc), jnp.float32)
    acc = jnp.zeros_like(q)
    # promote fresh (replicated-typed) constants so they can join the
    # varying carry (docs/sharp_bits.md)
    # pass comm explicitly: custom_vjp traces this function lazily (at
    # grad/partial-eval time), after the enclosing region context popped,
    # so the default-comm resolution would pick the wrong axes
    m, l, acc = mpx.varying((m, l, acc), comm=comm)

    k_blk, v_blk = k, v
    # static unroll: `size` steps, each one CollectivePermute + one block of
    # MXU work — XLA pipelines compute with the permutes
    for step in range(size):
        # k_blk currently holds the shard originally owned by src = rank -
        # step (mod size).  Causal block taxonomy (block granularity, exact):
        #   step == 0  (src == rank):  the diagonal block — triangular mask;
        #   step <= rank (src < rank): every key precedes every query —
        #       fully visible, compute UNMASKED (no mask load/selects);
        #   step >  rank (src > rank): every key follows every query —
        #       fully masked, skip the block's compute entirely.
        # `rank` is a traced per-device value (SPMD traces one program), so
        # the skip is a lax.cond: ranks take the identity branch at run
        # time instead of computing a block that masking would zero out.
        # This halves total causal ring FLOPs (sum over ranks: n(n+1)/2
        # useful blocks vs n^2 computed blocks before).
        if causal and step == 0:
            # diagonal block: global offsets cancel — declare the triangle
            # structurally so the TPU kernel can SKIP the fully-masked key
            # tiles (~1.7x on this block) instead of masking computed scores
            o_new, m_new, l_new = flash_block_partials(
                q, k_blk, v_blk, None, scale=scale, causal=True
            )
            acc, m, l = merge_partials(acc, m, l, o_new, m_new, l_new)
        elif causal:

            def _attend(carry, kb=k_blk, vb=v_blk):
                acc, m, l = carry
                o_new, m_new, l_new = flash_block_partials(
                    q, kb, vb, None, scale=scale
                )
                return merge_partials(acc, m, l, o_new, m_new, l_new)

            acc, m, l = jax.lax.cond(
                step <= rank, _attend, lambda carry: carry, (acc, m, l)
            )
        else:
            o_new, m_new, l_new = flash_block_partials(
                q, k_blk, v_blk, None, scale=scale
            )
            acc, m, l = merge_partials(acc, m, l, o_new, m_new, l_new)

        if step + 1 < size:
            # rotate K/V one hop around the ring (tokenless: the data
            # dependency on k_blk/v_blk already orders the permute)
            k_blk = notoken.sendrecv(k_blk, k_blk, dest=mpx.shift(1), comm=comm)
            v_blk = notoken.sendrecv(v_blk, v_blk, dest=mpx.shift(1), comm=comm)

    l_safe = jnp.where(l == 0.0, 1.0, l)
    # merge accumulates in f32; return in the input dtype
    out = (acc / jnp.moveaxis(l_safe, 1, 2)[..., None]).astype(q.dtype)
    return out, m, l


@partial(jax.custom_vjp, nondiff_argnums=(0, 1))
def _ring_attention_me(causal, comm, q, k, v):
    out, _m, _l = _ring_forward(q, k, v, comm, causal)
    return out


def _ring_me_fwd(causal, comm, q, k, v):
    out, m, l = _ring_forward(q, k, v, comm, causal)
    # residuals are rank-LOCAL only: O(T/n) per chip
    return out, (q, k, v, out, m, l)


def _ring_me_bwd(causal, comm, res, g):
    """Ring-attention backward with re-communication instead of residuals.

    Reconstruction: with the FINAL stabilizer ``m`` and normalizer ``l``,
    the output decomposes over blocks as

        out = (sum_b o_b * e^{m_b - m}) / l,     l = sum_b l_b * e^{m_b - m}

    where ``(o_b, m_b, l_b)`` are block partials.  The cotangents of each
    block's partials are therefore ``g_o_b = (g / l) * e^{m_b - m}`` and
    ``g_l_b = -(sum_d g*out / l) * e^{m_b - m}`` (the softmax "delta"
    term), with the stabilizer weights' own derivative dropped — exact,
    because the decomposition is invariant to every stabilizer (the same
    argument as ``flash_block_partials``'s custom VJP).  Each ring step
    recomputes one block's ``m_b`` (a forward kernel call), feeds these
    cotangents through the blockwise backward kernels (``jax.vjp`` of
    ``flash_block_partials``), and accumulates (dK_b, dV_b) into buffers
    that ROTATE WITH the block — after the full cycle of ``size`` hops
    every dK/dV lands back on its owner with all ranks' contributions.
    """
    q, k, v, out, m, l = res
    size = comm.Get_size()
    rank = comm.Get_rank()
    d = q.shape[-1]
    scale = 1.0 / math.sqrt(d)
    l_safe = jnp.where(l == 0.0, 1.0, l)
    m_safe = jnp.where(jnp.isinf(m), 0.0, m)

    g = g.astype(jnp.float32)
    out32 = out.astype(jnp.float32)
    # cotangents of the (acc, l) pair that produced out = acc / l
    g_acc = g / jnp.moveaxis(l_safe, 1, 2)[..., None]          # (B,T,H,D)
    delta = jnp.moveaxis((g * out32).sum(-1), 2, 1)            # (B,H,T)
    g_l = -delta / l_safe

    dq = jnp.zeros(q.shape, jnp.float32)
    dk = jnp.zeros(k.shape, jnp.float32)
    dv = jnp.zeros(v.shape, jnp.float32)
    dq, dk, dv = mpx.varying((dq, dk, dv), comm=comm)
    k_blk, v_blk = k, v

    for step in range(size):
        blk_causal = bool(causal and step == 0)

        def _block(kb, vb, dk_c, dv_c, blk_causal=blk_causal):
            (o_b, m_b, l_b), vjp = jax.vjp(
                lambda q_, kb_, vb_: flash_block_partials(
                    q_, kb_, vb_, None, scale=scale, causal=blk_causal
                ),
                q, kb, vb,
            )
            w = jnp.exp(m_b - m_safe)  # stabilizer reweight
            g_ob = (g_acc * jnp.moveaxis(w, 1, 2)[..., None]).astype(o_b.dtype)
            g_lb = g_l * w
            # the TRUE m_b cotangent (L depends on m_b through w): with it
            # the triple is the full chain rule, so the jnp fallback's
            # native AD is exact; the kernel path's custom VJP drops it,
            # which is equally exact by stabilizer invariance
            g_mb = w * (
                jnp.moveaxis((g_acc * o_b.astype(jnp.float32)).sum(-1), 2, 1)
                + g_l * l_b
            )
            dq_b, dk_b, dv_b = vjp((g_ob, g_mb, g_lb))
            return (dq_b.astype(jnp.float32),
                    dk_c + dk_b.astype(jnp.float32),
                    dv_c + dv_b.astype(jnp.float32))

        if causal and step > 0:
            dq_b, dk, dv = jax.lax.cond(
                step <= rank,
                _block,
                lambda kb, vb, dk_c, dv_c: (jnp.zeros_like(dq), dk_c, dv_c),
                k_blk, v_blk, dk, dv,
            )
        else:
            dq_b, dk, dv = _block(k_blk, v_blk, dk, dv)
        dq = dq + dq_b

        # rotate: dK/dV accumulators travel with their block and need the
        # FULL cycle of `size` hops to land back on the owner; K/V are
        # never read after the last step, so their final hop is elided
        # (same guard as the forward)
        if step + 1 < size:
            k_blk = notoken.sendrecv(k_blk, k_blk, dest=mpx.shift(1),
                                     comm=comm)
            v_blk = notoken.sendrecv(v_blk, v_blk, dest=mpx.shift(1),
                                     comm=comm)
        dk = notoken.sendrecv(dk, dk, dest=mpx.shift(1), comm=comm)
        dv = notoken.sendrecv(dv, dv, dest=mpx.shift(1), comm=comm)

    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


_ring_attention_me.defvjp(_ring_me_fwd, _ring_me_bwd)


def flash_attention(q, k, v, causal=False):
    """Single-device attention via the fused flash kernel: block partials +
    normalization, so the (T, T) score matrix never reaches HBM (the
    ``reference_attention`` einsum materializes it).  Causal uses the
    key-tile-skipping kernel on TPU; non-causal streams (512, 512) key
    tiles with online-softmax carries, so the live score tile is fixed-
    size regardless of sequence length — the VMEM ceiling is the K/V
    residency (~2·T·D·itemsize, about 90k f32 tokens at D=128), not T².

    Differentiable on every backend: ``flash_block_partials`` carries a
    blockwise custom VJP (Pallas backward kernels on TPU), so gradients
    match ``reference_attention``'s without ever materializing the score
    matrix — forward or backward.
    """
    scale = 1.0 / math.sqrt(q.shape[-1])
    o, _, l = flash_block_partials(q, k, v, None, scale=scale, causal=causal)
    l_safe = jnp.where(l == 0.0, 1.0, l)
    return (o / jnp.moveaxis(l_safe, 1, 2)[..., None]).astype(q.dtype)


def ulysses_attention(q, k, v, *, comm=None, causal=False):
    """Exact attention via all-to-all head exchange (Ulysses).

    Input shards ``(B, T_local, H, D)`` with ``H % size == 0``: re-shard to
    ``(B, T_global, H/size, D)`` with one ``alltoall``, run full-sequence
    local flash attention on the head group (fused kernel — the global
    score matrix never hits HBM), and re-shard back.
    """
    comm = comm if comm is not None else mpx.get_default_comm()
    size = comm.Get_size()
    b, t_loc, h, d = q.shape
    if h % size != 0:
        raise ValueError(f"ulysses needs heads ({h}) divisible by ranks ({size})")
    h_loc = h // size

    def seq_to_heads(x):
        # (B, T_l, H, D) -> alltoall rows = head groups -> (B, T_g, H/size, D)
        x = x.reshape(b, t_loc, size, h_loc, d).transpose(2, 0, 1, 3, 4)
        x = notoken.alltoall(x, comm=comm)  # row i: rank i's T_l for my heads
        return x.transpose(1, 0, 2, 3, 4).reshape(b, size * t_loc, h_loc, d)

    def heads_to_seq(x):
        # (B, T_g, H/size, D) -> (B, T_l, H, D)
        x = x.reshape(b, size, t_loc, h_loc, d).transpose(1, 0, 2, 3, 4)
        x = notoken.alltoall(x, comm=comm)
        return x.transpose(1, 2, 0, 3, 4).reshape(b, t_loc, h, d)

    qh, kh, vh = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
    out = flash_attention(qh, kh, vh, causal)
    return heads_to_seq(out)
