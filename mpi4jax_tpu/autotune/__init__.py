"""Feedback-directed autotuning of the performance knobs.

``mpx.autotune(comm=..., budget_s=..., save=...)`` measures every
load-bearing magic number on the ACTUAL mesh — ring crossover, DCN
crossover, fusion bucket bytes, overlap chunk counts, cost-model
alpha/beta per link class, commit pack throughput — by running the
microbench sweeps as a library, fits the per-(payload-bucket,
topology, link-class) optima, and emits an ``mpx-tuning/1`` file the
config layer serves between defaults and environment
(``MPI4JAX_TPU_TUNING`` / ``mpx.load_tuning``; docs/autotune.md).

Offline (fleet pre-tuning)::

    python -m mpi4jax_tpu.autotune --budget-s 60 --save tuning.json

This ``__init__`` imports only the stdlib halves (schema + fitters) so
the isolated-loader tests — and ``utils/config.py``'s lazy tuning-layer
imports — work under any installed JAX; the measuring runner (which
needs jax and a mesh) loads on first call.
"""

from .fit import (  # noqa: F401
    analytic_crossover,
    auto_commit_interval,
    chunk_buckets,
    measured_crossover,
    pick_min,
)
from .schema import (  # noqa: F401
    COST_SCHEMA,
    KNOB_FLAGS,
    SCHEMA,
    TuningFile,
    load_tuning_file,
    stamp_of,
    validate_tuning_dict,
)

__all__ = [
    "SCHEMA",
    "COST_SCHEMA",
    "KNOB_FLAGS",
    "TuningFile",
    "load_tuning_file",
    "stamp_of",
    "validate_tuning_dict",
    "measured_crossover",
    "analytic_crossover",
    "pick_min",
    "chunk_buckets",
    "auto_commit_interval",
    "autotune",
    "AutotuneResult",
]


def autotune(*args, **kwargs):
    """See :func:`mpi4jax_tpu.autotune.runner.autotune` (lazy: the
    runner needs jax + the microbench library)."""
    from .runner import autotune as _autotune

    return _autotune(*args, **kwargs)


def __getattr__(name):
    if name == "AutotuneResult":
        from .runner import AutotuneResult

        return AutotuneResult
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
