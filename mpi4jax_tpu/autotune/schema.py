"""The ``mpx-tuning/1`` file format: parse, validate, stamp, look up.

One JSON file carries every feedback-directed performance parameter the
stack can measure (docs/autotune.md):

- a **tuned** section — the per-knob optima the config layer serves
  between defaults and environment overrides (``utils/config.py``):
  ring/DCN crossovers, fusion bucket bytes, overlap chunk counts
  (optionally bucketed by payload), and the commit-interval parameters
  ``mpx.elastic.run(commit_every='auto')`` consumes;
- a **topologies** map — per-topology knob overrides keyed by the
  canonical ``MPI4JAX_TPU_TOPOLOGY`` spec string (``"2x4"``), because a
  crossover measured on one host partition is wrong on another;
- the full **cost-model** section (``links`` alpha/beta per link class,
  gamma, compute, dispatch — the ``mpx-cost-model/1`` subset), so ONE
  file feeds both the algorithm selector and the static cost model
  (analysis/costmodel.py accepts either schema);
- a **measured** section — the raw interpolated crossovers the advisory
  texts (MPX109/111/113, MPX131-133) cite with ``tuned@<stamp>``
  provenance;
- a **provenance** block — jax/jaxlib versions, platform, topology,
  config stamp, budget — so a fleet of saved tunings is self-describing.

Only stdlib at import time (json/os/hashlib), by the same contract as
``utils/config.py``: the isolated-loader test half
(tests/test_autotune_pure.py) must run under any installed JAX, and
``utils/config.py`` imports this module lazily from its tuning-layer
getters.
"""

from __future__ import annotations

import hashlib
import json
from typing import Dict, Optional

SCHEMA = "mpx-tuning/1"

# the cost-model subset this schema supersets (analysis/costmodel.py
# accepts both; benchmarks/micro.py --cost-calibrate now emits the
# superset so one capture feeds selector and cost model alike)
COST_SCHEMA = "mpx-cost-model/1"

# the tunable knobs the config layer serves, with the flag each one
# shadows (docs/autotune.md flag table): a knob value applies only when
# its environment flag is NOT explicitly set — default < tuning < env
KNOB_FLAGS = {
    "ring_crossover_bytes": "MPI4JAX_TPU_RING_CROSSOVER_BYTES",
    "dcn_crossover_bytes": "MPI4JAX_TPU_DCN_CROSSOVER_BYTES",
    # schema-bump-free addition (PR 15): an unknown-key-rejecting
    # validator plus a content stamp means a NEW tuned knob needs no
    # version bump — old files simply do not tune it, new files retrace
    # via the stamp (docs/autotune.md)
    "alltoall_crossover_bytes": "MPI4JAX_TPU_ALLTOALL_CROSSOVER_BYTES",
    "fusion_bucket_bytes": "MPI4JAX_TPU_FUSION_BUCKET_BYTES",
    "overlap_chunks": "MPI4JAX_TPU_OVERLAP_CHUNKS",
    # PR 17: the DCN-leg wire codec — the one string-valued knob
    # ("off"/"bf16"/"fp8", optionally payload-bucketed like
    # overlap_chunks); same schema-bump-free addition contract
    "compress": "MPI4JAX_TPU_COMPRESS",
    # PR 20: pipeline-parallel schedule knobs (parallel/pipeline.py,
    # docs/pipeline.md) — microbatch count for split_microbatches and
    # the interleaved virtual-stage chunk count; same schema-bump-free
    # addition contract (tuned values are >= 1; "unset" exists only as
    # the static default 0 in the config layer)
    "pipeline_microbatches": "MPI4JAX_TPU_PIPELINE_MICROBATCHES",
    "pipeline_virtual_stages": "MPI4JAX_TPU_PIPELINE_VIRTUAL_STAGES",
}

# legal tuned codec values for the "compress" knob ("auto" is an env
# resolution directive, never a tuned value)
COMPRESS_CODECS = ("off", "bf16", "fp8")

# commit-interval parameters (tuned.commit — mpx.elastic.run's
# commit_every='auto' math, autotune/fit.py auto_commit_interval)
COMMIT_KEYS = ("pack_gb_per_s", "target_overhead")


def _is_num(v) -> bool:
    return isinstance(v, (int, float)) and not isinstance(v, bool)


def _require_pos_int(section: str, key: str, val) -> int:
    if not _is_num(val) or val != int(val) or val < 1:
        raise ValueError(
            f"tuning file {section}.{key} must be a positive integer "
            f"(got {val!r})"
        )
    return int(val)


def _validate_chunk_buckets(section: str, buckets) -> list:
    """``overlap_chunks`` bucket form: ascending ``max_bytes`` spans,
    the last one open-ended (``max_bytes: null``)."""
    if not isinstance(buckets, list) or not buckets:
        raise ValueError(
            f"tuning file {section}.overlap_chunks must be a positive "
            f"integer or a non-empty bucket list (got {buckets!r})"
        )
    prev = 0
    for i, b in enumerate(buckets):
        if not isinstance(b, dict) or set(b) != {"max_bytes", "chunks"}:
            raise ValueError(
                f"tuning file {section}.overlap_chunks[{i}] must be an "
                "object with exactly 'max_bytes' and 'chunks' keys"
            )
        _require_pos_int(section, f"overlap_chunks[{i}].chunks",
                         b["chunks"])
        mb = b["max_bytes"]
        last = i == len(buckets) - 1
        if mb is None:
            if not last:
                raise ValueError(
                    f"tuning file {section}.overlap_chunks[{i}]: only "
                    "the last bucket may be open-ended (max_bytes null)"
                )
            continue
        _require_pos_int(section, f"overlap_chunks[{i}].max_bytes", mb)
        if mb <= prev:
            raise ValueError(
                f"tuning file {section}.overlap_chunks bucket bounds "
                f"must be strictly ascending (bucket {i}: {mb} <= {prev})"
            )
        prev = int(mb)
    return buckets


def _require_codec(section: str, key: str, val) -> str:
    if not isinstance(val, str) or val.lower() not in COMPRESS_CODECS:
        raise ValueError(
            f"tuning file {section}.{key} must be one of "
            f"{COMPRESS_CODECS} (got {val!r})"
        )
    return val.lower()


def _validate_codec_buckets(section: str, buckets) -> list:
    """``compress`` bucket form: the overlap_chunks bucket grammar with
    a ``codec`` value per span instead of a chunk count."""
    if not isinstance(buckets, list) or not buckets:
        raise ValueError(
            f"tuning file {section}.compress must be a codec string or "
            f"a non-empty bucket list (got {buckets!r})"
        )
    prev = 0
    for i, b in enumerate(buckets):
        if not isinstance(b, dict) or set(b) != {"max_bytes", "codec"}:
            raise ValueError(
                f"tuning file {section}.compress[{i}] must be an "
                "object with exactly 'max_bytes' and 'codec' keys"
            )
        _require_codec(section, f"compress[{i}].codec", b["codec"])
        mb = b["max_bytes"]
        last = i == len(buckets) - 1
        if mb is None:
            if not last:
                raise ValueError(
                    f"tuning file {section}.compress[{i}]: only the "
                    "last bucket may be open-ended (max_bytes null)"
                )
            continue
        _require_pos_int(section, f"compress[{i}].max_bytes", mb)
        if mb <= prev:
            raise ValueError(
                f"tuning file {section}.compress bucket bounds must "
                f"be strictly ascending (bucket {i}: {mb} <= {prev})"
            )
        prev = int(mb)
    return buckets


def _validate_knobs(section: str, knobs: dict,
                    allow_commit: bool = False) -> None:
    if not isinstance(knobs, dict):
        raise ValueError(f"tuning file {section!r} must be an object")
    for key, val in knobs.items():
        if key == "commit":
            if not allow_commit:
                # only the flat tuned section is read by commit_param —
                # accepting it here would be silently ignored
                raise ValueError(
                    f"tuning file {section}: 'commit' is only valid in "
                    "the top-level 'tuned' section (per-topology commit "
                    "parameters are not supported)"
                )
            if not isinstance(val, dict):
                raise ValueError("tuning file tuned.commit must be an "
                                 "object")
            for ck, cv in val.items():
                if ck not in COMMIT_KEYS:
                    raise ValueError(
                        f"tuning file tuned.commit key {ck!r} unknown "
                        f"(expected one of {COMMIT_KEYS})"
                    )
                if not _is_num(cv) or cv <= 0:
                    raise ValueError(
                        f"tuning file tuned.commit.{ck} must be a "
                        f"positive number (got {cv!r})"
                    )
            continue
        if key not in KNOB_FLAGS:
            raise ValueError(
                f"tuning file {section} knob {key!r} unknown (expected "
                f"one of {tuple(KNOB_FLAGS)} or 'commit')"
            )
        if key == "overlap_chunks" and isinstance(val, list):
            _validate_chunk_buckets(section, val)
        elif key == "compress":
            if isinstance(val, list):
                _validate_codec_buckets(section, val)
            else:
                _require_codec(section, key, val)
        else:
            _require_pos_int(section, key, val)


def validate_tuning_dict(payload) -> dict:
    """Validate a parsed ``mpx-tuning/1`` payload in place; returns it,
    or raises ``ValueError`` with a clear message.  The cost-model
    section (``links``/gamma/compute/dispatch/``measured``) is validated
    by the cost model's own rules — single source of truth — via a lazy
    import (analysis/costmodel.py is stdlib + the config registry)."""
    if not isinstance(payload, dict):
        raise ValueError(
            f"tuning file must be a JSON object (got "
            f"{type(payload).__name__})"
        )
    schema = payload.get("schema")
    if schema != SCHEMA:
        raise ValueError(
            f"tuning file declares schema {schema!r}; this build reads "
            f"{SCHEMA!r} (plain {COST_SCHEMA!r} files feed the cost "
            "model via MPI4JAX_TPU_COST_MODEL, not the tuning layer)"
        )
    if "tuned" in payload:
        _validate_knobs("tuned", payload["tuned"], allow_commit=True)
    topos = payload.get("topologies", {})
    if not isinstance(topos, dict):
        raise ValueError("tuning file 'topologies' must be an object")
    for spec, knobs in topos.items():
        if not isinstance(spec, str) or not spec.strip():
            raise ValueError(
                f"tuning file topology key {spec!r} must be a non-empty "
                "MPI4JAX_TPU_TOPOLOGY spec string"
            )
        _validate_knobs(f"topologies[{spec!r}]", knobs)
    prov = payload.get("provenance", {})
    if not isinstance(prov, dict):
        raise ValueError("tuning file 'provenance' must be an object")
    if any(k in payload for k in ("links", "gamma_gb_per_s",
                                  "compute_gb_per_s", "dispatch_us",
                                  "measured")):
        from ..analysis.costmodel import validate_model_dict

        probe = dict(payload)
        probe["schema"] = COST_SCHEMA  # re-use the subset validator
        validate_model_dict(probe)
    return payload


def stamp_of(payload: dict) -> str:
    """Content stamp of one tuning payload: 12 hex chars of the
    canonical-JSON sha256 — the ``tuned@<stamp>`` provenance tag the
    advisories cite and the token ``algo_cache_token()`` folds into
    every compiled-program cache key (loading or changing a file
    retraces; docs/autotune.md)."""
    blob = json.dumps(payload, sort_keys=True,
                      separators=(",", ":")).encode()
    return hashlib.sha256(blob).hexdigest()[:12]


class TuningFile:
    """One validated tuning payload + its lookup rules."""

    __slots__ = ("payload", "path", "stamp")

    def __init__(self, payload: dict, path: Optional[str] = None):
        self.payload = validate_tuning_dict(payload)
        self.path = path
        self.stamp = stamp_of(payload)

    # -- knob lookup -------------------------------------------------------

    def knob(self, name: str, topology: Optional[str] = None,
             payload_bytes: Optional[int] = None):
        """The tuned value of ``name`` for the active ``topology`` (a
        per-topology override wins over the flat ``tuned`` scalar) and
        payload bucket (``overlap_chunks`` only), or ``None`` when the
        file does not tune it — the caller then falls back to the
        static default.  The ENV precedence check is the caller's
        (utils/config.py): this object never reads the environment."""
        if name not in KNOB_FLAGS:
            raise KeyError(f"unknown tuning knob {name!r} "
                           f"(expected one of {tuple(KNOB_FLAGS)})")
        val = None
        if topology:
            val = (self.payload.get("topologies", {})
                   .get(topology, {}).get(name))
        if val is None:
            val = self.payload.get("tuned", {}).get(name)
        if val is None:
            return None
        if name == "overlap_chunks" and isinstance(val, list):
            if payload_bytes is None:
                return int(val[-1]["chunks"])  # the open-ended bucket
            for b in val:
                if b["max_bytes"] is None or payload_bytes <= b["max_bytes"]:
                    return int(b["chunks"])
            return int(val[-1]["chunks"])
        if name == "compress":
            if isinstance(val, list):
                if payload_bytes is None:
                    return str(val[-1]["codec"]).lower()
                for b in val:
                    if b["max_bytes"] is None or \
                            payload_bytes <= b["max_bytes"]:
                        return str(b["codec"]).lower()
                return str(val[-1]["codec"]).lower()
            return str(val).lower()
        return int(val)

    def commit_param(self, name: str) -> Optional[float]:
        """A ``tuned.commit`` parameter (``pack_gb_per_s`` /
        ``target_overhead``), or ``None`` when untuned."""
        if name not in COMMIT_KEYS:
            raise KeyError(f"unknown commit parameter {name!r}")
        val = self.payload.get("tuned", {}).get("commit", {}).get(name)
        return float(val) if val is not None else None

    def knobs(self) -> Dict[str, object]:
        """Every flat tuned knob value (topology overrides excluded) —
        the telemetry report's tuned-vs-default table."""
        return {k: v for k, v in self.payload.get("tuned", {}).items()
                if k in KNOB_FLAGS}

    def measured(self) -> dict:
        return dict(self.payload.get("measured", {}))

    def has_links(self) -> bool:
        """Whether the file carries the cost-model section — the
        unification bridge: when it does (and MPI4JAX_TPU_COST_MODEL is
        unset) the cost model reads its parameters from here."""
        return isinstance(self.payload.get("links"), dict)

    def __repr__(self):
        src = self.path or "<in-memory>"
        return f"TuningFile({src}, tuned@{self.stamp})"


def load_tuning_file(path: str) -> TuningFile:
    """Read + validate one tuning file; raises ``ValueError`` on a
    missing/malformed file (a typo'd MPI4JAX_TPU_TUNING must not
    silently run untuned)."""
    try:
        with open(path) as f:
            payload = json.load(f)
    except OSError as e:
        raise ValueError(
            f"tuning file {path!r} could not be read: {e}"
        ) from e
    except json.JSONDecodeError as e:
        raise ValueError(
            f"tuning file {path!r} is not valid JSON: {e}"
        ) from e
    return TuningFile(payload, path=path)


# path -> TuningFile | ValueError: the config-layer getters consult the
# active env file on every stamp move and every config_snapshot, which
# must not re-read the file per trace.  Keyed by PATH ALONE, not
# (path, mtime): the memoized-token fast path (ops/_base._dynamic_state)
# cannot see an in-place file edit, so re-reading edited content on new
# traces while already-compiled programs keep the old values would mix
# old and new lowerings in one process.  Instead the env route pins the
# file's content at first read; ``mpx.load_tuning(path)`` is the
# explicit, epoch-bumping refresh (``refresh_tuning_file``) that
# re-reads AND retraces everything consistently (docs/autotune.md).
_load_memo: Dict[str, object] = {}


def load_tuning_file_memo(path: str) -> TuningFile:
    cached = _load_memo.get(path)
    if cached is None:
        if len(_load_memo) > 16:
            _load_memo.clear()
        try:
            cached = load_tuning_file(path)
        except ValueError as e:
            cached = e
        _load_memo[path] = cached
    if isinstance(cached, ValueError):
        raise cached
    return cached


def refresh_tuning_file(path: str) -> TuningFile:
    """Force a fresh read of ``path`` and replace the memo entry — the
    ``mpx.load_tuning(path)`` route, whose config-epoch bump retraces
    every consumer against the new content."""
    tf = load_tuning_file(path)
    _load_memo[path] = tf
    return tf


def as_tuning(spec, fresh: bool = False) -> TuningFile:
    """Coerce a path / dict / TuningFile into a validated TuningFile.
    ``fresh=True`` re-reads a path even when memoized (the explicit
    ``load_tuning`` refresh)."""
    if isinstance(spec, TuningFile):
        return spec
    if isinstance(spec, dict):
        return TuningFile(spec)
    if isinstance(spec, str) and spec.strip():
        path = spec.strip()
        return refresh_tuning_file(path) if fresh else \
            load_tuning_file_memo(path)
    raise TypeError(
        "expected a tuning-file path, a parsed payload dict, or a "
        f"TuningFile (got {type(spec).__name__})"
    )
