"""Pure fitting math: sweep rows in, tuned knob values out.

Everything here is plain Python over numbers — no jax, no file I/O, no
environment reads — so tests/test_autotune_pure.py drives every fitter
from scripted sweep rows under any installed JAX, the same
isolated-loader contract as the lockstep simulators.  The runner
(autotune/runner.py) feeds these from live ``benchmarks/micro.py``
sweeps; the offline CLI and ``mpx.autotune()`` share them.

The fitters mirror the measurement shapes the microbench already emits:

- :func:`measured_crossover` — where algorithm B first beats algorithm
  A in a payload sweep, linearly interpolated between the straddling
  points (the generalization of ``micro.measured_ring_crossover``);
- :func:`analytic_crossover` — the alpha-beta closed form for the
  ring/butterfly allreduce crossover on a k-rank group, used for the
  DCN class where the virtual test mesh has no real inter-host link to
  sweep (the measured alpha/beta still come from the fit);
- :func:`pick_min` — argmin over candidate settings (fusion bucket
  bytes, overlap chunk counts);
- :func:`chunk_buckets` — fold per-payload chunk winners into the
  ``overlap_chunks`` bucket list of the ``mpx-tuning/1`` schema;
- :func:`auto_commit_interval` — the commit-interval math of
  ``mpx.elastic.run(commit_every='auto')`` (ROADMAP item 4c): the
  smallest interval that keeps measured commit cost under a target
  fraction of measured step time.
"""

from __future__ import annotations

import math
from typing import List, Optional, Sequence, Tuple

# auto-commit defaults: checkpoint overhead capped at 5% of step time
# (the classic rule of thumb; Young/Daly optimal intervals need a
# failure-rate estimate the store does not have), interval capped so a
# very fast packer can never push the replay window unbounded
DEFAULT_COMMIT_OVERHEAD = 0.05
MAX_COMMIT_INTERVAL = 1024


def measured_crossover(rows: Sequence[dict], size_key: str, a_key: str,
                       b_key: str, to_bytes: float = 1e6) -> Optional[int]:
    """Payload (bytes) where measurement ``b_key`` first beats
    ``a_key`` over an ascending size sweep, linearly interpolated
    between the straddling rows.  ``None`` when B never wins, a row
    lacks either timing, or the sweep is empty — the caller then leaves
    the knob untuned rather than guessing."""
    prev: Optional[Tuple[float, float]] = None
    for row in rows:
        a, b = row.get(a_key), row.get(b_key)
        if a is None or b is None:
            return None
        nbytes = row[size_key] * to_bytes
        delta = a - b  # > 0: B wins
        if delta >= 0:
            if prev is None:
                return int(nbytes)
            p_bytes, p_delta = prev
            span = delta - p_delta
            frac = (-p_delta / span) if span > 0 else 0.0
            return int(p_bytes + frac * (nbytes - p_bytes))
        prev = (nbytes, delta)
    return None


def analytic_crossover(alpha_us: float, gb_per_s: float,
                       k: int) -> Optional[int]:
    """Alpha-beta closed form of the ring/butterfly allreduce crossover
    on a ``k``-rank group of one link class.

    Butterfly: ``2·ceil(log2 k)`` rounds shipping the full payload each
    (``t = 2L·alpha + 2L·s/bw``); ring: ``2·(k-1)`` chunk rounds
    (``t = 2(k-1)·alpha + 2·(k-1)/k·s/bw``).  Equating and solving for
    ``s`` gives the payload where the ring's byte advantage pays for
    its extra latency rounds::

        s* = (2(k-1) - 2L) · alpha · bw / (2L - 2(k-1)/k)

    with ``bw`` in bytes/us (``gb_per_s * 1e3``).  ``None`` below the
    ring's minimum group size (k < 4: the ring never wins —
    ops/_algos.RING_MIN_GROUP) or on degenerate parameters."""
    if k < 4 or alpha_us < 0 or gb_per_s <= 0:
        return None
    L = (k - 1).bit_length()  # ceil(log2 k)
    lat_gap = 2 * (k - 1) - 2 * L        # extra ring latency rounds
    byte_gap = 2 * L - 2 * (k - 1) / k   # butterfly's extra bytes factor
    if byte_gap <= 0:
        return None
    s = lat_gap * alpha_us * (gb_per_s * 1e3) / byte_gap
    return max(1, int(math.ceil(s)))


def pick_min(rows: Sequence[dict], candidate_key: str,
             metric_key: str) -> Optional[Tuple[object, float]]:
    """The candidate with the smallest metric: ``(candidate, metric)``,
    ties broken toward the earlier row (sweeps list the default-ish
    candidates first).  ``None`` on an empty sweep or missing
    metrics."""
    best = None
    for row in rows:
        cand, metric = row.get(candidate_key), row.get(metric_key)
        if cand is None or metric is None:
            return None
        if best is None or metric < best[1]:
            best = (cand, float(metric))
    return best


def chunk_buckets(winners: Sequence[Tuple[int, int]]) -> object:
    """Fold per-payload overlap-chunk winners ``[(payload_bytes,
    chunks), ...]`` into the schema's ``overlap_chunks`` value: a plain
    int when one chunk count wins everywhere, else the ascending bucket
    list with the largest payload's winner as the open-ended tail.
    Adjacent buckets with the same winner merge."""
    if not winners:
        return None
    ordered = sorted(winners)
    counts = {c for _, c in ordered}
    if len(counts) == 1:
        return int(ordered[0][1])
    buckets: List[dict] = []
    for nbytes, chunks in ordered:
        if buckets and buckets[-1]["chunks"] == chunks:
            buckets[-1]["max_bytes"] = int(nbytes)
            continue
        buckets.append({"max_bytes": int(nbytes), "chunks": int(chunks)})
    buckets[-1]["max_bytes"] = None  # largest measured payload: open tail
    return buckets


def auto_commit_interval(step_time_s: float, commit_cost_s: float,
                         target_overhead: Optional[float] = None,
                         max_interval: int = MAX_COMMIT_INTERVAL) -> int:
    """Steps between commits so that checkpoint overhead stays at or
    under ``target_overhead`` of compute: the smallest ``n`` with
    ``commit_cost <= target · n · step_time``, clamped to
    ``[1, max_interval]``.  A non-positive or unmeasurable step time
    yields the conservative 1 (commit every step — the pre-autotune
    behavior)."""
    if target_overhead is None:
        target_overhead = DEFAULT_COMMIT_OVERHEAD
    if step_time_s <= 0 or commit_cost_s < 0 or target_overhead <= 0:
        return 1
    n = math.ceil(commit_cost_s / (target_overhead * step_time_s))
    return max(1, min(int(n), max_interval))
