"""Offline autotuning CLI (fleet pre-tuning — docs/autotune.md).

Usage::

    python -m mpi4jax_tpu.autotune --budget-s 60 --save tuning.json
        [--topologies 2x4 4x2] [--json]

Runs the full measurement loop on the current mesh (the same sweeps
``mpx.autotune()`` runs in-process) and writes the ``mpx-tuning/1``
file a fleet scheduler ships to every job via ``MPI4JAX_TPU_TUNING``.

Exit codes (the analysis CLI's contract):

- ``0`` — every knob fitted; the saved file validates;
- ``1`` — partial: the file was still written, but some knobs are
  untuned (e.g. a 1-device mesh has no crossover to measure) — usable,
  listed on stderr;
- ``2`` — usage error, or the mesh/sweeps failed outright (no file).
"""

import argparse
import json
import sys


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m mpi4jax_tpu.autotune",
        description="measure the perf knobs on the actual mesh and emit "
                    "an mpx-tuning/1 file (docs/autotune.md)")
    p.add_argument("--budget-s", type=float, default=60.0,
                   help="wall-clock measurement budget in seconds "
                        "(default 60; each sweep climbs its payload "
                        "ladder while time remains)")
    p.add_argument("--save", default="tuning.json",
                   help="output path for the tuning file "
                        "(default tuning.json)")
    p.add_argument("--topologies", nargs="*", default=[],
                   help="MPI4JAX_TPU_TOPOLOGY specs to sweep per-topology "
                        "crossover overrides for (e.g. 2x4 4x2); specs "
                        "not covering the mesh are skipped with a note")
    p.add_argument("--json", action="store_true",
                   help="print the emitted payload to stdout as JSON")
    args = p.parse_args(argv)
    if args.budget_s <= 0:
        print("autotune: --budget-s must be > 0", file=sys.stderr)
        return 2

    try:
        from .runner import autotune

        result = autotune(budget_s=args.budget_s, save=args.save,
                          load=False, topologies=tuple(args.topologies),
                          verbose=True)
    except Exception as e:
        # ANY failed run is exit 2 — a crash must never be confused
        # with exit 1 ("partial fit, file still written"), which fleet
        # scripts treat as a usable tune
        print(f"autotune: failed: {type(e).__name__}: {e}",
              file=sys.stderr)
        return 2

    if args.json:
        print(json.dumps(result.payload))
    print(f"autotune: tuned@{result.stamp} -> {result.path} "
          f"({len(result.fitted)} knob(s) fitted, "
          f"{result.elapsed_s:.1f}s)", file=sys.stderr)
    if result.unfitted:
        print("autotune: untuned knob(s): "
              + ", ".join(result.unfitted), file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
