"""``mpx.autotune()``: measure the perf knobs on the ACTUAL mesh.

The repo already owns every measurement this needs — the microbench
sweeps of ``benchmarks/micro.py`` (``--fusion-sweep``,
``--overlap-sweep``, the forced butterfly-vs-ring algo sweep,
``--hierarchy-sweep``, and the ``--cost-calibrate`` alpha/beta fit).
This module runs them **as a library** (not a subprocess) under a wall
clock budget, feeds the rows through the pure fitters
(autotune/fit.py), and emits one ``mpx-tuning/1`` file
(autotune/schema.py) that the config layer loads between defaults and
environment (``MPI4JAX_TPU_TUNING`` / ``mpx.load_tuning``):

- **ici alpha/beta** by least squares over the sendrecv ring latency
  sweep (the ``--cost-calibrate`` fit), dcn scaled by the documented
  analytic ratios where there is no real inter-host link to measure;
- **ring crossover** interpolated from the forced butterfly-vs-ring
  sweep (falling back to the alpha-beta closed form when the sweep is
  inconclusive — a tiny budget must still emit a usable file);
- **DCN crossover** from the closed form over the fitted dcn class;
- **per-topology crossover overrides** from the flat-vs-hier sweep;
- **fusion bucket bytes** by sweeping candidate caps through the
  fusion bench and keeping the fastest;
- **overlap chunk counts** per payload bucket by sweeping candidate
  counts through the overlap bench;
- **commit pack throughput** by timing ``resilience.elastic
  .pack_leaves`` on a synthetic state — the measured half of
  ``mpx.elastic.run(commit_every='auto')``.

This is the Horovod-autotuning / NCCL-measured-tables move (PAPERS.md):
selection driven by measured per-link latency/bandwidth instead of byte
heuristics — the difference between "fast on this grid" and "fast on
any pod a fleet scheduler hands you" (ROADMAP item 3).

Offline form: ``python -m mpi4jax_tpu.autotune --budget-s N --save
tuning.json`` (autotune/__main__.py; exit 0 full fit / 1 partial / 2
usage-or-mesh failure, the analysis CLI's contract).
"""

from __future__ import annotations

import hashlib
import importlib.util
import json
import os
import sys
import time
from typing import List, Optional, Tuple

from . import fit
from .schema import SCHEMA, TuningFile

# candidate ladders, tiny-first: each phase climbs its ladder while the
# budget lasts, so a 10-second budget still fits every knob (coarsely)
# and a 10-minute budget refines with larger payloads
P2P_SIZES_KB = (0.004, 4.0, 64.0, 1024.0)
ALGO_SIZES_MB = (0.01, 0.1, 0.5, 1.0, 4.0, 16.0)
# default-first (4 MiB is the shipped default): pick_min breaks ties
# toward the earlier row, and a budget-truncated sweep must compare
# against the default before anything exotic can win
FUSION_BUCKET_CANDIDATES = (4 << 20, 1 << 20, 16 << 20, 1 << 18)
OVERLAP_CHUNK_CANDIDATES = (2, 1, 4)
OVERLAP_SIZES_MB = (0.25, 4.0)

# synthetic state for the pack-throughput probe: big enough that the
# per-call overhead amortizes, small enough for any host
PACK_PROBE_BYTES = 8 << 20


def _load_micro():
    """``benchmarks/micro.py`` as a library.  The benchmarks directory
    is a repo-checkout sibling of the package (not an installed
    module), so resolve it relative to this file and load it by path;
    a pip-installed tree without the checkout gets a clear error."""
    for name in ("micro", "benchmarks.micro"):
        mod = sys.modules.get(name)
        if mod is not None and hasattr(mod, "bench_allreduce_algos"):
            return mod
    path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))),
        "benchmarks", "micro.py",
    )
    if not os.path.exists(path):
        raise RuntimeError(
            "mpx.autotune needs the microbench library "
            "(benchmarks/micro.py), which ships in the repository "
            f"checkout but was not found at {path!r} — run from a "
            "checkout, or pass pre-captured sweep rows to "
            "build_tuning()"
        )
    spec = importlib.util.spec_from_file_location("_mpx_autotune_micro",
                                                  path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules["_mpx_autotune_micro"] = mod
    spec.loader.exec_module(mod)
    return mod


def _meter(name: str, n: int = 1) -> None:
    try:
        from ..telemetry.core import meter
    except ImportError:
        return
    meter(name, n)


class _Budget:
    """Wall-clock budget: phases poll ``ok()`` before each (incremental)
    measurement and stop climbing their ladder when time is up.  At
    least one rung of every phase always runs — a too-small budget
    yields a coarse file, never an empty one."""

    def __init__(self, budget_s: float):
        self.budget_s = float(budget_s)
        self.t0 = time.monotonic()

    def elapsed(self) -> float:
        return time.monotonic() - self.t0

    def ok(self) -> bool:
        return self.elapsed() < self.budget_s


class _EnvPatch:
    """Set environment knobs for one candidate measurement, restoring
    the caller's values (not just dropping them) on exit — the same
    discipline the micro sweeps use internally."""

    def __init__(self, **env):
        self.env = {k: str(v) for k, v in env.items()}
        self.saved = {}

    def __enter__(self):
        for k, v in self.env.items():
            self.saved[k] = os.environ.get(k)
            os.environ[k] = v
        return self

    def __exit__(self, *exc):
        for k, old in self.saved.items():
            if old is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = old
        return False


def _world_comm():
    import jax

    import mpi4jax_tpu as mpx

    mesh = mpx.make_world_mesh(devices=jax.devices())
    return mpx.Comm(mesh.axis_names[0], mesh=mesh)


def provenance_block(platform: str, n_devices: int) -> dict:
    """The measurement self-description every emitted artifact carries
    (jax/jaxlib versions, topology string, a content stamp of the whole
    declared-flag surface) — the CANONICAL implementation, shared with
    ``benchmarks/micro.py --save`` captures (micro delegates here so
    the two provenance shapes can never drift)."""
    import jax

    from ..utils import config

    try:
        import jaxlib

        jaxlib_version = getattr(jaxlib, "__version__", "unknown")
    except ImportError:
        jaxlib_version = "unknown"
    stamp = hashlib.sha256(
        repr(config.env_fingerprint()).encode()).hexdigest()[:12]
    topo = config.topology_spec()
    if not topo:
        try:
            procs = jax.process_count()
        except Exception:
            procs = 1
        topo = f"{procs}x{n_devices // max(procs, 1)}"
    return {
        "jax": jax.__version__,
        "jaxlib": jaxlib_version,
        "platform": platform,
        "n_devices": n_devices,
        "topology": topo,
        "config_stamp": stamp,
    }


def _provenance(n_devices: int, platform: str, budget: _Budget) -> dict:
    prov = provenance_block(platform, n_devices)
    prov.update({
        "budget_s": budget.budget_s,
        "elapsed_s": round(budget.elapsed(), 2),
        "created": time.strftime("%Y-%m-%dT%H:%M:%S"),
    })
    return prov


def _pack_throughput_gb_s() -> Optional[float]:
    """Measured ``ShardStore`` pack throughput (GB/s) over a synthetic
    state — the commit-cost half of the ``commit_every='auto'`` math
    (the step-time half is measured live by the run loop)."""
    import numpy as np

    from ..resilience.elastic import pack_leaves

    leaves = [np.ones(PACK_PROBE_BYTES // 8 // 4, np.float32)
              for _ in range(8)]
    pack_leaves(leaves)  # warm (allocator, first-touch)
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        buf, _meta = pack_leaves(leaves)
        best = min(best, time.perf_counter() - t0)
    if best <= 0 or not buf.nbytes:
        return None
    return buf.nbytes / best / 1e9


def autotune(comm=None, budget_s: float = 60.0, save: Optional[str] = None,
             load: bool = True, topologies: Tuple[str, ...] = (),
             verbose: bool = False):
    """Feedback-directed tuning of every perf knob on the actual mesh.

    Runs the microbench sweeps (as a library) under a ``budget_s`` wall
    clock, fits per-(payload-bucket, topology, link-class) crossovers
    and optima, and returns an :class:`AutotuneResult` whose
    ``.payload`` is a validated ``mpx-tuning/1`` dict.  ``save=`` also
    writes it to a path; ``load=True`` (default) installs it as the
    active tuning layer (``mpx.load_tuning``) so the very next trace
    uses the measured values — the stamp folds into the program-cache
    keys, so everything retraces exactly once.

    ``topologies``: ``MPI4JAX_TPU_TOPOLOGY`` specs to sweep flat-vs-hier
    crossovers for (per-topology overrides); default none — on a real
    multi-host pod the derived topology is already active and the flat
    sweeps measure it.
    """
    budget = _Budget(budget_s)
    if budget_s <= 0:
        raise ValueError(f"budget_s must be > 0, got {budget_s}")
    micro = _load_micro()
    if comm is None:
        comm = _world_comm()
    n = comm.Get_size()
    platform = "unknown"
    try:
        import jax

        platform = jax.devices()[0].platform
    except Exception:
        pass
    _meter("autotune.runs")

    def note(msg):
        if verbose:
            print(f"autotune: {msg}", file=sys.stderr)

    tuned: dict = {}
    # ``measured`` carries ONLY sweep-derived values (the advisories
    # cite it as "measured"); closed-form/analytic fallbacks go into
    # ``tuned`` alone, with every knob's origin in ``fit_sources``
    measured: dict = {}
    fit_sources: dict = {}
    topo_overrides: dict = {}
    fitted: List[str] = []
    unfitted: List[str] = []

    # -- phase 1: p2p alpha/beta (the --cost-calibrate fit) ---------------
    pp_rows = []
    for kb in P2P_SIZES_KB:
        pp_rows += micro.bench_sendrecv_ring(comm, sizes_kb=[kb], iters=10)
        if not budget.ok():
            break
    alpha_us, gb_per_s = micro.fit_alpha_beta(
        [(r["size_kb"] * 1e3, r["hop_us"]) for r in pp_rows])
    note(f"ici fit: alpha {alpha_us:.3f} us, {gb_per_s:.2f} GB/s "
         f"({len(pp_rows)} point(s))")

    from ..analysis import costmodel

    defaults = costmodel.DEFAULT_PARAMS
    dcn_alpha = alpha_us * (defaults["links"]["dcn"]["alpha_us"]
                            / defaults["links"]["ici"]["alpha_us"])
    dcn_bw = max(gb_per_s * (defaults["links"]["dcn"]["gb_per_s"]
                             / defaults["links"]["ici"]["gb_per_s"]),
                 0.001)
    links = {
        "ici": {"alpha_us": round(alpha_us, 4),
                "gb_per_s": round(gb_per_s, 4)},
        "dcn": {"alpha_us": round(dcn_alpha, 4),
                "gb_per_s": round(dcn_bw, 4)},
    }
    fitted.append("links")
    _meter("autotune.fits")

    # -- phase 2: ring crossover (measured, closed-form fallback) ---------
    algo_rows = []
    for mb in ALGO_SIZES_MB:
        algo_rows += micro.bench_allreduce_algos(comm, sizes_mb=[mb],
                                                 iters=5)
        # stop early once the ring has clearly crossed over: two
        # consecutive ring wins bound the interpolation from above
        if (len(algo_rows) >= 2
                and all(r["ring_speedup"] and r["ring_speedup"] > 1.0
                        for r in algo_rows[-2:])):
            break
        if not budget.ok():
            break
    ring_x = micro.measured_ring_crossover(algo_rows)
    ring_x_source = "sweep"
    if ring_x is None:
        ring_x = fit.analytic_crossover(alpha_us, gb_per_s, n)
        ring_x_source = "alpha-beta fit"
    if ring_x is not None:
        tuned["ring_crossover_bytes"] = int(ring_x)
        if ring_x_source == "sweep":
            measured["ring_crossover_bytes"] = int(ring_x)
        fit_sources["ring_crossover_bytes"] = ring_x_source
        fitted.append("ring_crossover_bytes")
        _meter("autotune.fits")
        note(f"ring crossover: {ring_x} B ({ring_x_source})")
    else:
        unfitted.append("ring_crossover_bytes")
        note("ring crossover: unfitted (group too small for the ring)")

    # -- phase 3: DCN crossover (closed form over the fitted dcn class) --
    from ..parallel.topology import derive_world_topology

    topo = derive_world_topology(comm)
    hosts = topo.num_hosts if topo is not None else 1
    dcn_x = fit.analytic_crossover(dcn_alpha, dcn_bw, max(hosts, 4))
    if dcn_x is not None:
        # closed form over the SCALED dcn class — never a sweep, so it
        # is tuned but deliberately NOT "measured"
        tuned["dcn_crossover_bytes"] = int(dcn_x)
        fit_sources["dcn_crossover_bytes"] = "alpha-beta fit (scaled dcn)"
        fitted.append("dcn_crossover_bytes")
        _meter("autotune.fits")
        note(f"dcn crossover: {dcn_x} B (alpha-beta fit, h>={max(hosts, 4)})")
    else:
        unfitted.append("dcn_crossover_bytes")

    # -- phase 4: per-topology flat-vs-hier crossovers --------------------
    for spec in topologies:
        if not budget.ok():
            note(f"budget exhausted before topology {spec}")
            break
        hier_rows = micro.bench_hierarchy(
            comm, sizes_mb=tuple(ALGO_SIZES_MB[:4]), topologies=(spec,),
            iters=5)
        x = fit.measured_crossover(hier_rows, "size_mb", "flat_us",
                                   "hier_us")
        if x is not None:
            topo_overrides[spec] = {"ring_crossover_bytes": int(x)}
            fitted.append(f"topologies[{spec}]")
            _meter("autotune.fits")
            note(f"hier crossover @ {spec}: {x} B")

    # -- phase 4b: alltoall crossover (flat vs hier, per topology) --------
    # the permutation-family twin of phase 4: the payload where the
    # two-level alltoall first beats the flat exchange, interpolated
    # from the --alltoall-sweep grid (docs/moe.md).  Swept per requested
    # topology (or the ambient derived one on a real multi-host pod);
    # single-host meshes leave the knob untuned — there is no DCN to
    # aggregate messages over.
    a2a_specs = list(topologies) or ([None] if hosts > 1 else [])
    for spec in a2a_specs:
        if not budget.ok():
            note("budget exhausted before the alltoall crossover sweep")
            break
        a2a_rows = micro.bench_alltoall(
            comm, sizes_mb=tuple(ALGO_SIZES_MB[:4]),
            topologies=(spec,), iters=5)
        x = fit.measured_crossover(a2a_rows, "size_mb", "flat_us",
                                   "hier_us")
        if x is None:
            continue
        if spec is None:
            tuned["alltoall_crossover_bytes"] = int(x)
            measured["alltoall_crossover_bytes"] = int(x)
            fit_sources["alltoall_crossover_bytes"] = "sweep"
            fitted.append("alltoall_crossover_bytes")
        else:
            topo_overrides.setdefault(spec, {})[
                "alltoall_crossover_bytes"] = int(x)
            if "alltoall_crossover_bytes" not in tuned:
                # the first fitted topology also seeds the flat knob so
                # an untopologized consumer still gets a measured value
                tuned["alltoall_crossover_bytes"] = int(x)
                measured["alltoall_crossover_bytes"] = int(x)
                fit_sources["alltoall_crossover_bytes"] = (
                    f"sweep @ {spec}")
                fitted.append("alltoall_crossover_bytes")
            fitted.append(f"alltoall[{spec}]")
        _meter("autotune.fits")
        note(f"alltoall crossover @ {spec or 'ambient'}: {x} B")
    if "alltoall_crossover_bytes" not in tuned:
        unfitted.append("alltoall_crossover_bytes")

    # -- phase 5: fusion bucket bytes -------------------------------------
    bucket_rows = []
    for cand in FUSION_BUCKET_CANDIDATES:
        if bucket_rows and not budget.ok():
            break
        with _EnvPatch(MPI4JAX_TPU_FUSION_BUCKET_BYTES=cand):
            rows = micro.bench_fusion(comm, counts=(16,), size_kb=64,
                                      iters=1)
        bucket_rows.append({"bucket_bytes": cand,
                            "fused_us_per_op": rows[0]["fused_us_per_op"]})
    # a single uncompared candidate is not a fit: leave the knob
    # untuned rather than "tuning" it to whatever rung the budget
    # happened to reach first
    best_bucket = (fit.pick_min(bucket_rows, "bucket_bytes",
                                "fused_us_per_op")
                   if len(bucket_rows) >= 2 else None)
    if best_bucket is not None:
        tuned["fusion_bucket_bytes"] = int(best_bucket[0])
        measured["fusion_bucket_bytes"] = int(best_bucket[0])
        fit_sources["fusion_bucket_bytes"] = "sweep"
        fitted.append("fusion_bucket_bytes")
        _meter("autotune.fits")
        note(f"fusion bucket: {best_bucket[0]} B "
             f"({best_bucket[1]:.2f} us/op)")
    else:
        unfitted.append("fusion_bucket_bytes")

    # -- phase 6: overlap chunks per payload bucket -----------------------
    winners = []
    for mb in OVERLAP_SIZES_MB:
        if winners and not budget.ok():
            break
        per_payload = []
        for cand in OVERLAP_CHUNK_CANDIDATES:
            with _EnvPatch(MPI4JAX_TPU_OVERLAP_CHUNKS=cand):
                rows = micro.bench_overlap(comm, sizes_mb=(mb,), iters=5,
                                           compute_dim=64)
            per_payload.append({"chunks": cand,
                                "overlap_us": rows[0]["overlap_us"]})
        best = fit.pick_min(per_payload, "chunks", "overlap_us")
        if best is not None:
            winners.append((int(mb * 1e6), int(best[0])))
    chunks = fit.chunk_buckets(winners)
    if chunks is not None:
        tuned["overlap_chunks"] = chunks
        fit_sources["overlap_chunks"] = "sweep"
        fitted.append("overlap_chunks")
        _meter("autotune.fits")
        note(f"overlap chunks: {chunks}")
    else:
        unfitted.append("overlap_chunks")

    # -- phase 6b: DCN wire codec vs the error budget ---------------------
    # pick the fastest modeled DCN leg whose MEASURED round-trip error
    # fits MPI4JAX_TPU_COMPRESS_ERROR_BUDGET (docs/compression.md);
    # "off" always fits, so the knob is always recorded — a budget no
    # codec meets tunes compression off explicitly.  Payload-bucketed:
    # legs below the DCN crossover are latency-bound, where shrinking
    # bytes buys nothing, so they stay exact.
    bench_comp = getattr(micro, "bench_compression", None)
    if bench_comp is not None and budget.ok():
        from ..utils import config as _config

        err_budget = _config.compress_error_budget()
        comp_rows = bench_comp(comm, sizes_mb=(1.0,), iters=3)
        best_codec, best_us = "off", None
        for row in comp_rows:
            if row["rel_err"] > err_budget:
                continue
            if best_us is None or row["modeled_dcn_us"] < best_us:
                best_codec, best_us = row["codec"], row["modeled_dcn_us"]
            measured[f"compress_rel_err_{row['codec']}"] = row["rel_err"]
        if best_codec == "off":
            tuned["compress"] = "off"
        else:
            bound = int(tuned.get("dcn_crossover_bytes",
                                  _config.dcn_crossover_bytes()))
            tuned["compress"] = [
                {"max_bytes": bound, "codec": "off"},
                {"max_bytes": None, "codec": best_codec},
            ]
        fit_sources["compress"] = "sweep vs error budget"
        fitted.append("compress")
        _meter("autotune.fits")
        note(f"compress codec: {best_codec} "
             f"(error budget {err_budget:g})")
    else:
        unfitted.append("compress")

    # -- phase 7: commit pack throughput ----------------------------------
    pack = _pack_throughput_gb_s()
    if pack is not None:
        tuned["commit"] = {
            "pack_gb_per_s": round(pack, 4),
            "target_overhead": fit.DEFAULT_COMMIT_OVERHEAD,
        }
        fitted.append("commit")
        _meter("autotune.fits")
        note(f"commit pack throughput: {pack:.2f} GB/s")
    else:
        unfitted.append("commit")

    payload = {
        "schema": SCHEMA,
        "source": (f"mpx.autotune ({platform}, {n} devices, "
                   f"budget {budget.budget_s:g}s)"),
        "links": links,
        "gamma_gb_per_s": defaults["gamma_gb_per_s"],
        "compute_gb_per_s": defaults["compute_gb_per_s"],
        "dispatch_us": defaults["dispatch_us"],
        "tuned": tuned,
        "measured": measured,
        "provenance": dict(_provenance(n, platform, budget),
                           fit_sources=fit_sources),
    }
    if topo_overrides:
        payload["topologies"] = topo_overrides
    tf = TuningFile(payload)  # validates — an unloadable emit is a bug here

    path = None
    if save:
        path = save
        with open(path, "w") as f:
            json.dump(payload, f, indent=2)
            f.write("\n")
        note(f"saved {path} (tuned@{tf.stamp})")
    if load:
        from ..utils import config

        tf = config.load_tuning(path if path else payload)
    return AutotuneResult(payload=payload, tuning=tf, path=path,
                          fitted=tuple(fitted), unfitted=tuple(unfitted),
                          elapsed_s=budget.elapsed())


class AutotuneResult:
    """What one autotune run produced: the validated payload, the
    (possibly installed) :class:`~.schema.TuningFile`, where it was
    saved, and which knobs were fitted vs left untuned — the CLI's
    exit-code discriminator (0 full / 1 partial)."""

    __slots__ = ("payload", "tuning", "path", "fitted", "unfitted",
                 "elapsed_s")

    def __init__(self, payload, tuning, path, fitted, unfitted, elapsed_s):
        self.payload = payload
        self.tuning = tuning
        self.path = path
        self.fitted = fitted
        self.unfitted = unfitted
        self.elapsed_s = elapsed_s

    @property
    def stamp(self) -> str:
        return self.tuning.stamp

    def __repr__(self):
        return (f"AutotuneResult(tuned@{self.stamp}, "
                f"{len(self.fitted)} fitted, "
                f"{len(self.unfitted)} unfitted, "
                f"{self.elapsed_s:.1f}s)")
