// Native host-side runtime hooks (XLA FFI custom calls, CPU backend).
//
// TPU-native equivalent of the runtime responsibilities of the reference's
// Cython bridge (ref mpi4jax/_src/xla_bridge/mpi_xla_bridge.pyx): on TPU the
// collectives themselves are compiler-emitted HLO with no host hook needed,
// but the *runtime* services the bridge provided still need a native home
// (SURVEY.md §7 step 7):
//
//   - per-op begin/end logging in the reference's format
//     ("r{rank} | {id} | MPI_X ..." / "... done ({elapsed}s)",
//     ref mpi_xla_bridge.pyx:47-60, 100-112), with wall-clock op latency
//     measured across the collective on the host;
//   - fail-fast abort: a data-dependent guard that kills the process when a
//     runtime predicate fires (the MPI_Abort-on-error semantics of
//     ref mpi_xla_bridge.pyx:67-91);
//   - collective watchdog (mpi4jax_tpu/resilience/watchdog.py): an arm/disarm
//     registry of in-flight collectives plus a C++ monitor thread that dumps
//     per-rank diagnostics and aborts when one exceeds its timeout.  The
//     registry lives here (not Python) so the timeout fires even when every
//     Python thread is wedged behind the GIL.
//
// Build: see csrc/CMakeLists.txt or `python -m mpi4jax_tpu.native build`.
// Loaded and registered from mpi4jax_tpu/native.py via ctypes + jax.ffi.

#include <chrono>
#include <cinttypes>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <deque>
#include <mutex>
#include <string>
#include <string_view>
#include <thread>
#include <unordered_map>

#include "xla/ffi/api/ffi.h"

namespace ffi = xla::ffi;

namespace {

double Now() {
  using clock = std::chrono::steady_clock;
  return std::chrono::duration<double>(clock::now().time_since_epoch()).count();
}

// (call_id, rank) -> FIFO of begin timestamps.  Call ids are unique per
// *trace site*, so a site inside lax.fori_loop fires once per iteration with
// the same id: the data dependencies order iteration N+1's begin after
// iteration N's collective, but not after N's end hook, so a plain map entry
// could be overwritten.  FIFO pairing matches each end with its own begin.
// Multiple devices run concurrently on the CPU backend, hence the mutex.
std::mutex mu;
std::unordered_map<std::string, std::deque<double>> begin_times;

ffi::Error OpBeginImpl(ffi::BufferR0<ffi::U32> rank,
                       ffi::Result<ffi::BufferR0<ffi::U32>> out,
                       std::string_view opname, std::string_view call_id,
                       std::string_view detail) {
  uint32_t r = rank.typed_data()[0];
  std::string key = std::string(call_id) + ":" + std::to_string(r);
  {
    std::lock_guard<std::mutex> lock(mu);
    begin_times[key].push_back(Now());
  }
  if (detail.empty()) {
    std::fprintf(stderr, "r%" PRIu32 " | %.*s | %.*s\n", r,
                 (int)call_id.size(), call_id.data(), (int)opname.size(),
                 opname.data());
  } else {
    std::fprintf(stderr, "r%" PRIu32 " | %.*s | %.*s: %.*s\n", r,
                 (int)call_id.size(), call_id.data(), (int)opname.size(),
                 opname.data(), (int)detail.size(), detail.data());
  }
  out->typed_data()[0] = r;
  return ffi::Error::Success();
}

ffi::Error OpEndImpl(ffi::BufferR0<ffi::U32> rank,
                     ffi::Result<ffi::BufferR0<ffi::U32>> out,
                     std::string_view opname, std::string_view call_id) {
  uint32_t r = rank.typed_data()[0];
  std::string key = std::string(call_id) + ":" + std::to_string(r);
  double elapsed = 0.0;
  {
    std::lock_guard<std::mutex> lock(mu);
    auto it = begin_times.find(key);
    if (it != begin_times.end() && !it->second.empty()) {
      elapsed = Now() - it->second.front();
      it->second.pop_front();
      if (it->second.empty()) begin_times.erase(it);
    }
  }
  // matches the reference's completion line (mpi_xla_bridge.pyx:108-112);
  // "code 0" kept for format parity — XLA collectives cannot return nonzero
  std::fprintf(stderr, "r%" PRIu32 " | %.*s | %.*s done with code 0 (%.2es)\n",
               r, (int)call_id.size(), call_id.data(), (int)opname.size(),
               opname.data(), elapsed);
  out->typed_data()[0] = r;
  return ffi::Error::Success();
}

ffi::Error AbortIfImpl(ffi::BufferR0<ffi::U32> pred,
                       ffi::BufferR0<ffi::U32> rank,
                       ffi::Result<ffi::BufferR0<ffi::U32>> out,
                       std::string_view message) {
  uint32_t p = pred.typed_data()[0];
  uint32_t r = rank.typed_data()[0];
  if (p != 0) {
    // fail-fast across the job, like MPI_Abort after an MPI error
    // (ref mpi_xla_bridge.pyx:67-91): print and kill the process group
    std::fprintf(stderr, "r%" PRIu32 " | FATAL: %.*s\n", r,
                 (int)message.size(), message.data());
    std::fflush(stderr);
    std::abort();
  }
  out->typed_data()[0] = p;
  return ffi::Error::Success();
}

// ---------------------------------------------------------------------------
// collective watchdog (resilience/watchdog.py's native backend)
// ---------------------------------------------------------------------------

struct WatchdogEntry {
  uint32_t rank;
  std::string opname;
  std::string call_id;
  std::string axes;
  double start;
  double timeout;
};

// Same FIFO-per-(call_id, rank) aliasing story as begin_times above: a trace
// site inside lax.fori_loop re-arms with the same call id before the prior
// iteration's disarm is ordered, so a plain map entry could be clobbered.
std::mutex wd_mu;
std::unordered_map<std::string, std::deque<WatchdogEntry>> wd_inflight;
bool wd_thread_running = false;

void WatchdogDump(const WatchdogEntry& expired, double now) {
  // called with wd_mu held; never returns
  for (const auto& kv : wd_inflight) {
    for (const auto& e : kv.second) {
      std::fprintf(stderr,
                   "r%" PRIu32 " | WATCHDOG | in-flight: %s (call %s, "
                   "axes=%s, elapsed %.2fs)\n",
                   e.rank, e.opname.c_str(), e.call_id.c_str(),
                   e.axes.c_str(), now - e.start);
    }
  }
  std::fprintf(stderr,
               "r%" PRIu32 " | FATAL: collective watchdog: %s exceeded "
               "%gs (call %s, axes=%s)\n",
               expired.rank, expired.opname.c_str(), expired.timeout,
               expired.call_id.c_str(), expired.axes.c_str());
  std::fflush(stderr);
  std::abort();
}

void WatchdogLoop() {
  for (;;) {
    std::this_thread::sleep_for(std::chrono::milliseconds(100));
    double now = Now();
    std::lock_guard<std::mutex> lock(wd_mu);
    for (const auto& kv : wd_inflight) {
      for (const auto& e : kv.second) {
        if (now - e.start > e.timeout) WatchdogDump(e, now);
      }
    }
  }
}

ffi::Error WatchdogArmImpl(ffi::BufferR0<ffi::U32> rank,
                           ffi::Result<ffi::BufferR0<ffi::U32>> out,
                           std::string_view opname, std::string_view call_id,
                           std::string_view axes, double timeout) {
  uint32_t r = rank.typed_data()[0];
  std::string key = std::string(call_id) + ":" + std::to_string(r);
  {
    std::lock_guard<std::mutex> lock(wd_mu);
    wd_inflight[key].push_back(WatchdogEntry{
        r, std::string(opname), std::string(call_id), std::string(axes),
        Now(), timeout});
    if (!wd_thread_running) {
      std::thread(WatchdogLoop).detach();
      wd_thread_running = true;
    }
  }
  out->typed_data()[0] = r;
  return ffi::Error::Success();
}

ffi::Error WatchdogDisarmImpl(ffi::BufferR0<ffi::U32> rank,
                              ffi::Result<ffi::BufferR0<ffi::U32>> out,
                              std::string_view call_id) {
  uint32_t r = rank.typed_data()[0];
  std::string key = std::string(call_id) + ":" + std::to_string(r);
  {
    std::lock_guard<std::mutex> lock(wd_mu);
    auto it = wd_inflight.find(key);
    if (it != wd_inflight.end() && !it->second.empty()) {
      it->second.pop_front();
      if (it->second.empty()) wd_inflight.erase(it);
    }
  }
  out->typed_data()[0] = r;
  return ffi::Error::Success();
}

ffi::Error WallclockImpl(ffi::BufferR0<ffi::U32> token,
                         ffi::Result<ffi::BufferR0<ffi::F64>> out) {
  (void)token;
  // Seconds since this library's first wallclock read, not since boot:
  // callers may downcast to f32 (x64-disabled JAX), where a since-boot
  // value has millisecond ULP. Differences are what is meaningful.
  static const double base = Now();
  out->typed_data()[0] = Now() - base;
  return ffi::Error::Success();
}

}  // namespace

XLA_FFI_DEFINE_HANDLER_SYMBOL(MpxOpBegin, OpBeginImpl,
                              ffi::Ffi::Bind()
                                  .Arg<ffi::BufferR0<ffi::U32>>()
                                  .Ret<ffi::BufferR0<ffi::U32>>()
                                  .Attr<std::string_view>("opname")
                                  .Attr<std::string_view>("call_id")
                                  .Attr<std::string_view>("detail"));

XLA_FFI_DEFINE_HANDLER_SYMBOL(MpxOpEnd, OpEndImpl,
                              ffi::Ffi::Bind()
                                  .Arg<ffi::BufferR0<ffi::U32>>()
                                  .Ret<ffi::BufferR0<ffi::U32>>()
                                  .Attr<std::string_view>("opname")
                                  .Attr<std::string_view>("call_id"));

XLA_FFI_DEFINE_HANDLER_SYMBOL(MpxAbortIf, AbortIfImpl,
                              ffi::Ffi::Bind()
                                  .Arg<ffi::BufferR0<ffi::U32>>()
                                  .Arg<ffi::BufferR0<ffi::U32>>()
                                  .Ret<ffi::BufferR0<ffi::U32>>()
                                  .Attr<std::string_view>("message"));

XLA_FFI_DEFINE_HANDLER_SYMBOL(MpxWallclock, WallclockImpl,
                              ffi::Ffi::Bind()
                                  .Arg<ffi::BufferR0<ffi::U32>>()
                                  .Ret<ffi::BufferR0<ffi::F64>>());

XLA_FFI_DEFINE_HANDLER_SYMBOL(MpxWatchdogArm, WatchdogArmImpl,
                              ffi::Ffi::Bind()
                                  .Arg<ffi::BufferR0<ffi::U32>>()
                                  .Ret<ffi::BufferR0<ffi::U32>>()
                                  .Attr<std::string_view>("opname")
                                  .Attr<std::string_view>("call_id")
                                  .Attr<std::string_view>("axes")
                                  .Attr<double>("timeout"));

XLA_FFI_DEFINE_HANDLER_SYMBOL(MpxWatchdogDisarm, WatchdogDisarmImpl,
                              ffi::Ffi::Bind()
                                  .Arg<ffi::BufferR0<ffi::U32>>()
                                  .Ret<ffi::BufferR0<ffi::U32>>()
                                  .Attr<std::string_view>("call_id"));
