"""Point-to-point: sendrecv/send/recv, halo patterns, ordering semantics.

Ports ref tests/collective_ops/test_sendrecv.py, test_send_and_recv.py, and
the ordering guarantees of tests/experimental/test_notoken.py:80-131 ("hot
potato").  The reference's deadlock tests assert that token threading makes
rank-asymmetric send/recv safe; here the same programs are safe by
construction (one SPMD program), and the suite instead asserts the matching
machinery: fused pairing, PROC_NULL edges, FIFO per (comm, tag), tag
isolation, transpose/grad through the permutation.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import mpi4jax_tpu as mpx
from helpers import per_rank, ranks_arange, world


def test_sendrecv_ring():
    _, size = world()

    @mpx.spmd
    def f(x):
        y, _ = mpx.sendrecv(x, x, dest=mpx.shift(1))
        return y

    out = np.asarray(f(ranks_arange((1,))))[:, 0]
    assert np.allclose(out, np.roll(np.arange(size), 1))


def test_sendrecv_source_only():
    _, size = world()

    @mpx.spmd
    def f(x):
        # receiver-centric: I receive from my left neighbor
        y, _ = mpx.sendrecv(x, x, source=mpx.shift(-1))
        return y

    out = np.asarray(f(ranks_arange((1,))))[:, 0]
    assert np.allclose(out, np.roll(np.arange(size), 1))


def test_sendrecv_both_specs_consistent():
    _, size = world()

    @mpx.spmd
    def f(x):
        y, _ = mpx.sendrecv(x, x, source=mpx.shift(-1), dest=mpx.shift(1))
        return y

    out = np.asarray(f(ranks_arange((1,))))[:, 0]
    assert np.allclose(out, np.roll(np.arange(size), 1))


def test_sendrecv_inconsistent_specs():
    with pytest.raises(ValueError, match="inconsistent routing"):
        @mpx.spmd
        def f(x):
            y, _ = mpx.sendrecv(x, x, source=mpx.shift(1), dest=mpx.shift(1))
            return y

        f(ranks_arange((1,)))


def test_sendrecv_edge_halo():
    # wrap=False at domain boundaries: MPI_PROC_NULL semantics — ranks with
    # no source keep their recv template (ref shallow_water halo edges)
    _, size = world()

    @mpx.spmd
    def f(x):
        template = jnp.full_like(x, -1.0)
        y, _ = mpx.sendrecv(x, template, dest=mpx.shift(1, wrap=False))
        return y

    out = np.asarray(f(ranks_arange((1,))))[:, 0]
    assert out[0] == -1.0
    assert np.allclose(out[1:], np.arange(size - 1))


def test_sendrecv_pairs_dict():
    _, size = world()

    @mpx.spmd
    def f(x):
        y, _ = mpx.sendrecv(x, jnp.zeros_like(x), dest={0: 3, 3: 0})
        return y

    out = np.asarray(f(ranks_arange((1,))))[:, 0]
    expected = np.zeros(size)
    expected[3] = 0.0  # from rank 0
    expected[0] = 3.0  # from rank 3
    assert np.allclose(out, expected)


def test_sendrecv_grad():
    # reverse-mode through the permutation: cotangent flows backwards
    _, size = world()

    def loss(x):
        @mpx.spmd
        def f(xl):
            y, _ = mpx.sendrecv(xl, xl, dest=mpx.shift(1))
            return jnp.sum(y ** 2)

        return jnp.sum(f(x))

    x = ranks_arange((1,))
    g = np.asarray(jax.grad(loss)(x))[:, 0]
    # d/dx_r of sum over receivers (x_{r})^2 (each rank's value is received
    # exactly once downstream) = 2 x_r
    assert np.allclose(g, 2 * np.arange(size))


def test_sendrecv_jvp_forward_mode():
    # The reference RAISES for forward-mode sendrecv (ref sendrecv.py:150-155)
    # because per-process tracing would put the tangent on the wrong rank.
    # SPMD traces all ranks at once, so forward-mode is simply correct —
    # documented improvement.
    _, size = world()

    @mpx.spmd
    def f(x):
        g = lambda a: mpx.sendrecv(a, a, dest=mpx.shift(1))[0]
        y, dy = jax.jvp(g, (x,), (x * 0 + jnp.arange(1.0, 2.0),))
        return dy

    out = np.asarray(f(ranks_arange((1,))))
    assert np.allclose(out, 1.0)  # tangent of ones, permuted


def test_sendrecv_transpose_swaps_direction():
    # ref sendrecv.py:461-480 — transpose swaps source and dest
    _, size = world()

    @mpx.spmd
    def f(x):
        g = lambda a: mpx.sendrecv(a, a, dest=mpx.shift(1))[0]
        t = jax.linear_transpose(g, x)
        return t(x)[0]

    out = np.asarray(f(ranks_arange((1,))))[:, 0]
    # forward shifts +1; transpose must shift -1
    assert np.allclose(out, np.roll(np.arange(size), -1))


def test_send_recv_pair():
    _, size = world()

    @mpx.spmd
    def f(x):
        token = mpx.send(x, dest=mpx.shift(1))
        y, _ = mpx.recv(x, source=mpx.shift(-1), token=token)
        return y

    out = np.asarray(f(ranks_arange((1,))))[:, 0]
    assert np.allclose(out, np.roll(np.arange(size), 1))


def test_send_recv_source_inferred():
    # recv(source=None): adopt the queued send's routing (the SPMD analog of
    # the reference's ANY_SOURCE default, ref recv.py:44-48)
    _, size = world()

    @mpx.spmd
    def f(x):
        token = mpx.send(x, dest=mpx.shift(2))
        y, _ = mpx.recv(x, token=token)
        return y

    out = np.asarray(f(ranks_arange((1,))))[:, 0]
    assert np.allclose(out, np.roll(np.arange(size), 2))


def test_send_recv_fifo_per_tag():
    # two in-flight sends on one tag: FIFO matching (MPI non-overtaking)
    _, size = world()

    @mpx.spmd
    def f(x):
        t = mpx.send(x, dest=mpx.shift(1))
        t = mpx.send(x * 10, dest=mpx.shift(2), token=t)
        a, t = mpx.recv(x, token=t)   # matches first send (+1)
        b, t = mpx.recv(x, token=t)   # matches second send (+2)
        return a, b

    a, b = f(ranks_arange((1,)))
    assert np.allclose(np.asarray(a)[:, 0], np.roll(np.arange(8), 1))
    assert np.allclose(np.asarray(b)[:, 0], 10 * np.roll(np.arange(8), 2))


def test_send_recv_tag_isolation():
    # distinct tags are independent channels: recv(tag=7) must match the
    # tag-7 send even though a tag-0 send is queued first
    _, size = world()

    @mpx.spmd
    def f(x):
        t = mpx.send(x, dest=mpx.shift(1), tag=0)
        t = mpx.send(x * 100, dest=mpx.shift(1), tag=7, token=t)
        b, t = mpx.recv(x, tag=7, token=t)
        a, t = mpx.recv(x, tag=0, token=t)
        return a, b

    a, b = f(ranks_arange((1,)))
    assert np.allclose(np.asarray(a)[:, 0], np.roll(np.arange(8), 1))
    assert np.allclose(np.asarray(b)[:, 0], 100 * np.roll(np.arange(8), 1))


def test_send_recv_comm_isolation():
    # Clone() isolates matching — a send on the clone cannot satisfy a recv
    # on the world comm (ref sharp-bits: cloned-comm message isolation)
    comm, size = world()

    @mpx.spmd
    def f(x):
        clone = mpx.get_default_comm().Clone()
        t = mpx.send(x, dest=mpx.shift(1), comm=clone)
        with pytest.raises(RuntimeError, match="no matching send"):
            mpx.recv(x, token=t)  # world comm: queue is empty
        y, t2 = mpx.recv(x, comm=clone, token=t)
        return y

    out = np.asarray(f(ranks_arange((1,))))[:, 0]
    assert np.allclose(out, np.roll(np.arange(size), 1))


def test_unmatched_send_raises():
    # the reference program would deadlock; we convert to a trace-time error
    with pytest.raises(RuntimeError, match="unmatched send"):
        @mpx.spmd
        def f(x):
            mpx.send(x, dest=mpx.shift(1))
            return x

        f(ranks_arange((1,)))


def test_recv_without_send_raises():
    with pytest.raises(RuntimeError, match="no matching send"):
        @mpx.spmd
        def f(x):
            y, _ = mpx.recv(x, source=mpx.shift(-1))
            return y

        f(ranks_arange((1,)))


def test_recv_source_mismatch_raises():
    with pytest.raises(ValueError, match="matching send declared"):
        @mpx.spmd
        def f(x):
            t = mpx.send(x, dest=mpx.shift(1))
            y, _ = mpx.recv(x, source=mpx.shift(-2), token=t)
            return y

        f(ranks_arange((1,)))


def test_hot_potato():
    # ref tests/experimental/test_notoken.py:80-131 — a value passed around
    # the ring size times accumulates every rank's contribution in order;
    # delivery must follow program order.
    _, size = world()

    @mpx.spmd
    def f(x):
        potato = x
        token = mpx.create_token()
        for step in range(size):
            potato = potato + 1.0  # each hop stamps the potato
            potato, token = mpx.sendrecv(
                potato, potato, dest=mpx.shift(1), token=token
            )
        return potato

    out = np.asarray(f(ranks_arange((1,))))[:, 0]
    # after `size` hops the potato returns home having gained size stamps
    assert np.allclose(out, np.arange(size) + size)


def test_status():
    _, size = world()

    @mpx.spmd
    def f(x):
        status = mpx.Status()
        y, _ = mpx.sendrecv(x, x, dest=mpx.shift(1), status=status)
        return y, status.Get_source()

    y, src = f(ranks_arange((1,)))
    assert np.allclose(np.asarray(src), np.roll(np.arange(size), 1))


def test_status_tag_count_dtype():
    # the full Status is filled (ref recv.py:43-48, :99-107): tag is the tag
    # the matched message was sent with, count/dtype describe the payload
    statuses = {}

    @mpx.spmd
    def f(x):
        s_sr = mpx.Status()
        y, t = mpx.sendrecv(x, x, dest=mpx.shift(1), sendtag=5, recvtag=5,
                            status=s_sr)
        s_rv = mpx.Status()
        t = mpx.send(y, dest=mpx.shift(1), tag=3, token=t)
        z, _ = mpx.recv(y, tag=3, status=s_rv, token=t)
        statuses["sr"] = s_sr
        statuses["rv"] = s_rv
        return z

    f(per_rank(lambda r: jnp.full((4,), float(r))))
    assert statuses["sr"].Get_tag() == 5
    assert statuses["sr"].Get_count() == 4
    assert statuses["sr"].dtype == jnp.float32
    assert statuses["rv"].Get_tag() == 3
    assert statuses["rv"].Get_count() == 4
    # Get_error is always SUCCESS under fail-fast semantics (any transport
    # error aborts the job before a Status could report it); Get_elements
    # counts in units of the queried basic dtype
    assert statuses["sr"].Get_error() == 0
    assert statuses["rv"].Get_error() == 0
    assert statuses["sr"].Get_elements() == 4
    assert statuses["sr"].Get_elements(jnp.uint8) == 16
    assert statuses["sr"].Get_elements(jnp.float64) == 2


def test_status_get_elements_indivisible():
    s = mpx.Status()
    s.count = 3
    s.dtype = jnp.float32  # 12 bytes
    assert s.Get_elements(jnp.uint8) == 12
    with pytest.raises(ValueError, match="whole number"):
        s.Get_elements(jnp.float64)  # 12 B / 8 B


def test_sendrecv_tags_inert_for_matching():
    # sendrecv matching is internal to the call, so differing tags (the
    # swapped-tag bidirectional-exchange idiom from ported MPI code) still
    # route correctly; Status.tag reports the tag the message was SENT with
    _, size = world()
    statuses = {}

    @mpx.spmd
    def f(x):
        s = mpx.Status()
        right, t = mpx.sendrecv(x, x, dest=mpx.shift(1),
                                sendtag=1, recvtag=2, status=s)
        left, _ = mpx.sendrecv(x, x, dest=mpx.shift(-1),
                               sendtag=2, recvtag=1, token=t)
        statuses["s"] = s
        return right, left

    right, left = f(ranks_arange((1,)))
    assert np.allclose(np.asarray(right)[:, 0], np.roll(np.arange(size), 1))
    assert np.allclose(np.asarray(left)[:, 0], np.roll(np.arange(size), -1))
    assert statuses["s"].Get_tag() == 1  # sendtag: what the message carried


def test_sendrecv_mismatched_shapes_row_for_column():
    # exchange-row-for-column: send a (1, n) row, receive into an (n, 1)
    # column — the output is typed by recvbuf (ref sendrecv.py:369-377)
    _, size = world()
    n = 3

    @mpx.spmd
    def f(x):
        row = x.reshape(1, n)
        col_template = jnp.zeros((n, 1), x.dtype)
        y, _ = mpx.sendrecv(row, col_template, dest=mpx.shift(1))
        return y

    x = per_rank(lambda r: jnp.arange(float(r), float(r) + n))
    out = np.asarray(f(x))
    assert out.shape == (size, n, 1)
    for r in range(size):
        src = (r - 1) % size
        assert np.allclose(out[r, :, 0], np.arange(src, src + n))


def test_sendrecv_mismatched_shapes_proc_null_edge():
    # ranks outside the routing keep the recv template, in the recv shape
    _, size = world()

    @mpx.spmd
    def f(x):
        template = jnp.full((2, 2), -1.0)
        y, _ = mpx.sendrecv(x, template, dest=mpx.shift(1, wrap=False))
        return y

    out = np.asarray(f(per_rank(lambda r: jnp.full((4,), float(r)))))
    assert out.shape == (size, 2, 2)
    assert np.all(out[0] == -1.0)
    for r in range(1, size):
        assert np.all(out[r] == r - 1)


def test_sendrecv_mismatched_shapes_eager():
    # the eager (outside-spmd) path stacks per-field output shapes that
    # differ from the inputs' — pin that the auto-wrapped shard_map
    # round-trips them
    _, size = world()
    send = per_rank(lambda r: np.arange(3.0).reshape(1, 3) + 10 * r)
    recv = jnp.zeros((size, 3, 1))
    y, _ = mpx.sendrecv(send, recv, dest=mpx.shift(1))
    y = np.asarray(y)
    assert y.shape == (size, 3, 1)
    for r in range(size):
        src = (r - 1) % size
        assert np.allclose(y[r][:, 0], np.arange(3.0) + 10 * src)


def test_sendrecv_mismatched_count_raises():
    with pytest.raises(ValueError, match="element counts match"):
        @mpx.spmd
        def f(x):
            y, _ = mpx.sendrecv(x, jnp.zeros((5,)), dest=mpx.shift(1))
            return y

        f(per_rank(lambda r: jnp.full((4,), float(r))))


def test_sendrecv_mismatched_dtype_raises():
    with pytest.raises(ValueError, match="dtypes"):
        @mpx.spmd
        def f(x):
            y, _ = mpx.sendrecv(x, jnp.zeros((4,), jnp.int32),
                                dest=mpx.shift(1))
            return y

        f(per_rank(lambda r: jnp.full((4,), float(r))))


def test_recv_mismatched_shape_same_count():
    # recv types its output by the template (ref recv.py:246): a sent (1, n)
    # row lands in an (n,) template
    _, size = world()
    n = 4

    @mpx.spmd
    def f(x):
        t = mpx.send(x.reshape(1, n), dest=mpx.shift(1))
        y, _ = mpx.recv(jnp.zeros((n,), x.dtype), token=t)
        return y

    out = np.asarray(f(per_rank(lambda r: jnp.full((n,), float(r)))))
    assert out.shape == (size, n)
    assert np.allclose(out[:, 0], np.roll(np.arange(size), 1))


def test_bare_int_dest_guidance():
    with pytest.raises(TypeError, match="ambiguous"):
        @mpx.spmd
        def f(x):
            y, _ = mpx.sendrecv(x, x, dest=1)
            return y

        f(ranks_arange((1,)))


# --- standalone eager send/recv (deferred pairing) -------------------------
# Ports the eager portions of ref tests/collective_ops/test_send_and_recv.py
# (each process sends/recvs outside jit) to the SPMD eager convention:
# global arrays with a leading rank axis, transfer emitted at the recv.


def test_eager_send_recv_ring():
    _, size = world()
    x = ranks_arange((3,))
    tok = mpx.send(x, dest=mpx.shift(1), tag=11)
    res, _ = mpx.recv(x, tag=11, token=tok)
    out = np.asarray(res)
    assert out.shape == x.shape
    assert np.allclose(out[:, 0], np.roll(np.arange(size), 1))


def test_eager_send_recv_fifo_and_tag_isolation():
    _, size = world()
    a = per_rank(lambda r: jnp.full((2,), float(r)))
    b = per_rank(lambda r: jnp.full((2,), 100.0 + r))
    c = per_rank(lambda r: jnp.full((2,), 200.0 + r))
    # two sends on tag 1 (FIFO) interleaved with one on tag 2
    mpx.send(a, dest=mpx.shift(1), tag=1)
    mpx.send(c, dest=mpx.shift(-1), tag=2)
    mpx.send(b, dest=mpx.shift(1), tag=1)
    ra, _ = mpx.recv(a, tag=1)
    rc, _ = mpx.recv(c, tag=2)
    rb, _ = mpx.recv(b, tag=1)
    assert np.allclose(np.asarray(ra)[:, 0], np.roll(np.arange(size), 1))
    assert np.allclose(np.asarray(rb)[:, 0], 100 + np.roll(np.arange(size), 1))
    assert np.allclose(np.asarray(rc)[:, 0], 200 + np.roll(np.arange(size), -1))


def test_eager_recv_adopts_routing_and_fills_status():
    _, size = world()
    x = ranks_arange((4,))
    mpx.send(x, dest=mpx.shift(1), tag=3)
    s = mpx.Status()
    # source=None adopts the queued send's routing; explicit source is
    # validated against it
    res, _ = mpx.recv(x, source=mpx.shift(-1), tag=3, status=s)
    assert np.allclose(np.asarray(res)[:, 0], np.roll(np.arange(size), 1))
    assert s.Get_tag() == 3
    assert s.Get_count() == 4
    assert s.Get_error() == 0


def test_eager_recv_source_mismatch_raises():
    _, size = world()
    x = ranks_arange((1,))
    mpx.send(x, dest=mpx.shift(1), tag=4)
    with pytest.raises(ValueError, match="source spec"):
        mpx.recv(x, source=mpx.shift(1), tag=4)
    # a failed recv must NOT consume the message (MPI semantics): the
    # corrected retry still matches the queued send
    res, _ = mpx.recv(x, source=mpx.shift(-1), tag=4)
    assert np.allclose(np.asarray(res)[:, 0], np.roll(np.arange(size), 1))
    mpx.flush()


def test_eager_send_traced_then_recv_outside_raises_clearly():
    # a send traced inside jit whose trace has ended queues a dead tracer;
    # a later recv — eager OR in a different trace — must raise the clear
    # staleness error (and drop the unreceivable entry), not an opaque
    # leaked-tracer failure
    world()
    x = ranks_arange((1,))

    jax.jit(lambda a: (mpx.send(a, dest=mpx.shift(1), tag=77), a)[1])(x)
    with pytest.raises(RuntimeError, match="trace has ended"):
        mpx.recv(x, tag=77)
    mpx.flush()  # the dead entry was dropped; nothing lingers

    jax.jit(lambda a: (mpx.send(a, dest=mpx.shift(1), tag=78), a)[1])(x)
    with pytest.raises(RuntimeError, match="trace has ended"):
        jax.jit(lambda a: mpx.recv(a, tag=78)[0])(x)
    mpx.flush()


def test_eager_recv_bad_template_does_not_consume():
    # a recv failing ANY argument check (here: dispatch's global-shape
    # validation — element count matches but the leading rank axis is
    # folded away) must leave the send matchable by a corrected retry
    _, size = world()
    x = ranks_arange((3,))
    mpx.send(x, dest=mpx.shift(1), tag=21)
    with pytest.raises(ValueError, match="leading rank axis"):
        mpx.recv(jnp.zeros((size * 3,)), tag=21)
    res, _ = mpx.recv(x, tag=21)
    assert np.allclose(np.asarray(res)[:, 0], np.roll(np.arange(size), 1))
    mpx.flush()


def test_eager_recv_without_send_raises():
    world()
    x = ranks_arange((1,))
    with pytest.raises(RuntimeError, match="no matching eager send"):
        mpx.recv(x, tag=55)


def test_eager_unmatched_send_raises_at_flush():
    world()
    x = ranks_arange((1,))
    mpx.send(x, dest=mpx.shift(1), tag=66)
    with pytest.raises(RuntimeError, match="unmatched eager send"):
        mpx.flush()
    # drain so the suite's own exit-time flush stays clean
    mpx.recv(x, tag=66)
    mpx.flush()


def test_eager_send_recv_grad():
    # the deferred pair is differentiable end-to-end like eager sendrecv:
    # transpose of the emitted permute swaps source/dest
    _, size = world()

    def loss(x):
        mpx.send(x, dest=mpx.shift(1), tag=9)
        y, _ = mpx.recv(x, tag=9)
        return (y**2).sum()

    x = ranks_arange((2,))
    g = jax.grad(loss)(x)
    np.testing.assert_allclose(np.asarray(g), 2 * np.asarray(x))


def test_identity_routing_elides_collective_permute():
    """A routing that resolves to the identity permutation — e.g. a
    wrapping shift along a size-1 mesh axis, the single-rank case of every
    periodic halo exchange — must still deliver the payload (self-send)
    but emit NO collective_permute: the collective is a per-rank no-op,
    and on real interconnects it is far from free."""
    _, size = world()
    mesh = mpx.make_world_mesh((size, 1), ("a", "b"))
    comm2 = mpx.Comm(("a", "b"), mesh=mesh)

    def f(x):
        # the size-1 "b" axis is the single-rank case of a periodic
        # dimension: a wrapping ring along it is a self-exchange
        y, _ = mpx.sendrecv(x, x, dest=mpx.shift(1, wrap=True),
                            comm=comm2.sub("b"))
        return y

    x = jnp.arange(float(size)).reshape(size, 1, 1)
    out = np.asarray(mpx.spmd(f, comm=comm2)(x))
    np.testing.assert_array_equal(out, np.asarray(x))  # self-delivery

    def lower_text(fn):
        return jax.jit(
            jax.shard_map(
                fn,
                mesh=mesh,
                in_specs=jax.sharding.PartitionSpec("a", "b"),
                out_specs=jax.sharding.PartitionSpec("a", "b"),
            )
        ).lower(jnp.ones((size, 1))).as_text()

    assert "collective_permute" not in lower_text(f)
    # non-wrapping shift on the size-1 axis: empty routing, same elision
    assert "collective_permute" not in lower_text(
        lambda x: mpx.sendrecv(x, x, dest=mpx.shift(1, wrap=False),
                               comm=comm2.sub("b"))[0]
    )
    if size > 1:
        # positive control: a genuinely non-identity routing must emit the
        # collective, anchoring the string the negative checks rely on
        assert "collective_permute" in lower_text(
            lambda x: mpx.sendrecv(x, x, dest=mpx.shift(1, wrap=True),
                                   comm=comm2.sub("a"))[0]
        )


def test_identity_routing_grad():
    """Transpose through the elided identity permute stays correct (the
    inverse of the identity is the identity)."""
    _, size = world()
    mesh = mpx.make_world_mesh((size, 1), ("a", "b"))
    comm2 = mpx.Comm(("a", "b"), mesh=mesh)

    @mpx.spmd(comm=comm2)
    def loss_parts(x):
        y, _ = mpx.sendrecv(x, x, dest=mpx.shift(1, wrap=True),
                            comm=comm2.sub("b"))
        return (y**2).sum(axis=tuple(range(1, x.ndim)))  # per-rank partials

    def loss(x):
        return loss_parts(x).sum()

    x = jnp.arange(float(size)).reshape(size, 1, 1) + 1.0
    g = jax.grad(loss)(x)
    np.testing.assert_allclose(np.asarray(g), 2 * np.asarray(x))
