"""Collective fusion (ops/_fusion.py): packing plan + lockstep simulator
+ traced integration.

The bucketing plan (dtype segregation, deterministic order, byte cap) and
the exact-unflattening offsets are pure functions; this file drives them
through a numpy lockstep simulator that pins fused == unfused for
allreduce and bcast buckets — any packing-order or offset bug changes the
result.  The pure half loads the module under a private package name
(``_load_isolated``, mirroring tests/test_algos.py) so it runs even where
the installed JAX is below the package's hard floor; the traced half
(deferral, flush-on-use, HLO pins, cache-key retraces) is gated on a real
``mpi4jax_tpu`` import (jax>=0.6).
"""

import importlib
import os
import pathlib
import sys
import types

import numpy as np
import pytest

REPO = pathlib.Path(__file__).resolve().parent.parent
PKG = REPO / "mpi4jax_tpu"

_ISO_NAME = "_mpx_fusion_iso"


def _load_isolated():
    """Load ops/_fusion.py + utils/config.py under a private package name,
    bypassing ``mpi4jax_tpu/__init__.py`` (whose JAX-floor check refuses
    to import on old JAX) while preserving package context for the
    relative imports."""
    if _ISO_NAME in sys.modules:
        return sys.modules[_ISO_NAME]
    root = types.ModuleType(_ISO_NAME)
    root.__path__ = [str(PKG)]
    sys.modules[_ISO_NAME] = root
    for sub in ("utils", "ops"):
        m = types.ModuleType(f"{_ISO_NAME}.{sub}")
        m.__path__ = [str(PKG / sub)]
        sys.modules[f"{_ISO_NAME}.{sub}"] = m
        setattr(root, sub, m)
    for mod in ("utils.config", "ops._fusion"):
        importlib.import_module(f"{_ISO_NAME}.{mod}")
    return root


ISO = _load_isolated()
fu = sys.modules[f"{_ISO_NAME}.ops._fusion"]
config = sys.modules[f"{_ISO_NAME}.utils.config"]

try:
    import mpi4jax_tpu  # noqa: F401

    HAS_MPX = True
except Exception:
    HAS_MPX = False

needs_mpx = pytest.mark.skipif(
    not HAS_MPX, reason="mpi4jax_tpu import refused (JAX below hard floor)"
)


@pytest.fixture(autouse=True)
def _clean_fusion_env():
    saved = {
        k: os.environ.pop(k, None)
        for k in ("MPI4JAX_TPU_FUSION", "MPI4JAX_TPU_FUSION_BUCKET_BYTES")
    }
    fu.set_fusion_mode(None)
    yield
    fu.set_fusion_mode(None)
    if HAS_MPX:
        import mpi4jax_tpu as mpx

        mpx.set_fusion_mode(None)
        mpx.clear_caches()
    for k, v in saved.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v


# ---------------------------------------------------------------------------
# the bucketing plan (pure)
# ---------------------------------------------------------------------------


def test_bucket_plan_dtype_segregation_and_order():
    plan = fu.bucket_plan(
        [("f32", 100), ("f32", 100), ("i32", 50), ("f32", 100), ("i32", 50)],
        bucket_bytes=1000,
    )
    # dtype-segregated, order-preserving within dtype, deterministic
    # bucket order (by first member index)
    assert plan == [[0, 1, 3], [2, 4]]


def test_bucket_plan_byte_cap_closes_buckets():
    # greedy: a bucket closes when the NEXT member would exceed the cap
    assert fu.bucket_plan([("f", 600), ("f", 600), ("f", 600)], 1300) == \
        [[0, 1], [2]]
    assert fu.bucket_plan([("f", 600), ("f", 600), ("f", 600)], 1000) == \
        [[0], [1], [2]]
    # a single oversized member still gets its own bucket
    assert fu.bucket_plan([("f", 9000)], 1000) == [[0]]


def test_bucket_plan_force_ignores_cap():
    assert fu.bucket_plan([("f", 600), ("f", 600), ("f", 600)], 1000,
                          force=True) == [[0, 1, 2]]


def test_bucket_plan_empty():
    assert fu.bucket_plan([], 1000) == []


def test_pack_offsets_are_exact():
    assert fu.pack_offsets([3, 4, 5]) == [(0, 3), (3, 7), (7, 12)]
    assert fu.pack_offsets([]) == []


# ---------------------------------------------------------------------------
# lockstep simulator: fused == unfused, member for member
# ---------------------------------------------------------------------------


def _sim_fused(per_rank_arrays, reduce_fn, bucket_bytes, force=False):
    """Simulate the flush: pack each rank's members with the REAL plan and
    offsets, reduce the flat buffers across ranks, unflatten — returns
    the per-member results in member order."""
    k = len(per_rank_arrays)
    members = per_rank_arrays[0]
    entries = [(str(a.dtype), a.size * a.dtype.itemsize) for a in members]
    plan = fu.bucket_plan(entries, bucket_bytes, force=force)
    out = [None] * len(members)
    for bucket in plan:
        sizes = [members[i].size for i in bucket]
        flats = [
            np.concatenate([per_rank_arrays[r][i].ravel() for i in bucket])
            for r in range(k)
        ]
        fused = reduce_fn(flats)
        for i, (start, end) in zip(bucket, fu.pack_offsets(sizes)):
            out[i] = fused[start:end].reshape(members[i].shape)
    assert all(o is not None for o in out), "plan dropped a member"
    return out


@pytest.mark.parametrize("force", [False, True])
def test_lockstep_fused_allreduce_matches_unfused(force):
    rng = np.random.RandomState(0)
    k = 4
    shapes = [(3,), (2, 2), (5,), (1,), (4,)]
    per_rank = [
        [rng.randint(1, 10, s).astype(np.int64) for s in shapes]
        for _ in range(k)
    ]
    unfused = [
        sum(per_rank[r][i] for r in range(k)) for i in range(len(shapes))
    ]
    fused = _sim_fused(per_rank, lambda flats: sum(flats),
                       bucket_bytes=1 << 20, force=force)
    for a, b in zip(unfused, fused):
        np.testing.assert_array_equal(a, b)


def test_lockstep_fused_mixed_dtypes_and_tiny_buckets():
    k = 3
    shapes = [(4,), (2,), (3,)]
    per_rank = [
        [np.full(shapes[0], r + 1, np.float64),
         np.full(shapes[1], 10 * (r + 1), np.int32),
         np.full(shapes[2], r + 0.5, np.float64)]
        for r in range(k)
    ]
    unfused = [sum(per_rank[r][i] for r in range(k)) for i in range(3)]
    # bucket cap of one f64 element forces every member into its own
    # bucket — the degenerate plan must still reassemble exactly
    fused = _sim_fused(per_rank, lambda flats: sum(flats), bucket_bytes=8)
    for a, b in zip(unfused, fused):
        np.testing.assert_array_equal(a, b)


def test_lockstep_fused_bcast_matches_unfused():
    k, root = 4, 2
    shapes = [(3,), (2, 3)]
    rng = np.random.RandomState(1)
    per_rank = [
        [rng.randn(*s).astype(np.float32) for s in shapes] for _ in range(k)
    ]
    unfused = [per_rank[root][i] for i in range(len(shapes))]
    # bcast's "reduction" across ranks is selecting the root's flat buffer
    fused = _sim_fused(per_rank, lambda flats: flats[root],
                       bucket_bytes=1 << 20)
    for a, b in zip(unfused, fused):
        np.testing.assert_array_equal(a, b)


# ---------------------------------------------------------------------------
# mode plumbing + flag registry (pure)
# ---------------------------------------------------------------------------


def test_fusion_mode_default_and_env():
    assert fu.effective_mode() == "off"
    os.environ["MPI4JAX_TPU_FUSION"] = "auto"
    assert fu.effective_mode() == "auto"
    os.environ["MPI4JAX_TPU_FUSION"] = "bogus"
    with pytest.raises(ValueError):
        fu.effective_mode()


def test_set_fusion_mode_override_and_validation():
    fu.set_fusion_mode("force")
    os.environ["MPI4JAX_TPU_FUSION"] = "off"
    assert fu.effective_mode() == "force"  # override shadows env
    fu.set_fusion_mode(None)
    assert fu.effective_mode() == "off"
    with pytest.raises(ValueError):
        fu.set_fusion_mode("loud")


def test_fusion_cache_token_tracks_mode_and_cap():
    t0 = fu.fusion_cache_token()
    assert t0 == ("off", config.DEFAULT_FUSION_BUCKET_BYTES)
    fu.set_fusion_mode("auto")
    os.environ["MPI4JAX_TPU_FUSION_BUCKET_BYTES"] = "1024"
    assert fu.fusion_cache_token() == ("auto", 1024)


def test_flags_are_declared():
    for name in ("MPI4JAX_TPU_FUSION", "MPI4JAX_TPU_FUSION_BUCKET_BYTES",
                 "MPI4JAX_TPU_OVERLAP_CHUNKS"):
        assert name in config.FLAGS
    assert config.FLAGS["MPI4JAX_TPU_FUSION"].choices == config.FUSION_MODES


def test_config_stamp_tracks_env_and_epoch():
    s0 = config.config_stamp()
    os.environ["MPI4JAX_TPU_FUSION"] = "auto"
    s1 = config.config_stamp()
    assert s1 != s0
    config.bump_config_epoch()
    assert config.config_stamp() != s1
    # set_fusion_mode is a programmatic override: epoch must move
    s2 = config.config_stamp()
    fu.set_fusion_mode("force")
    assert config.config_stamp() != s2


def test_lazy_result_metadata_without_forcing():
    cell = fu.LazyResult((2, 3), np.dtype(np.float32), ctx=None)
    assert cell.shape == (2, 3)
    assert cell.ndim == 2 and cell.size == 6
    assert "pending" in repr(cell)


def test_lazy_result_forwards_uses():
    """Drop-in contract: array methods, indexing, operators, equality,
    and np.asarray on a deferred result behave like the array itself
    (each forces)."""
    cell = fu.LazyResult((2, 3), np.dtype(np.float32), ctx=None)
    cell._value = np.arange(6, dtype=np.float32).reshape(2, 3)
    np.testing.assert_array_equal(cell.reshape(6), np.arange(6))
    assert cell.sum() == 15.0
    np.testing.assert_array_equal(cell[1], [3.0, 4.0, 5.0])
    np.testing.assert_array_equal(cell + 1, cell._value + 1)
    eq = cell == cell._value
    assert eq.all()  # elementwise, not Python identity
    np.testing.assert_array_equal(np.asarray(cell), cell._value)
    with pytest.raises(TypeError):
        hash(cell)  # unhashable, like a traced array


# ---------------------------------------------------------------------------
# traced integration (jax>=0.6)
# ---------------------------------------------------------------------------


def _world():
    import mpi4jax_tpu as mpx

    comm = mpx.get_default_comm()
    return mpx, comm, comm.Get_size()


@needs_mpx
@pytest.mark.parametrize("op_name", ["SUM", "PROD", "MAX"])
def test_fused_allreduce_matches_unfused_traced(op_name):
    import jax.numpy as jnp
    import numpy as np

    mpx, comm, size = _world()
    op = getattr(mpx, op_name)
    xs = [np.arange(1, size * n + 1, dtype=np.float32).reshape(size, n)
          for n in (3, 5, 2)]

    def prog(a, b, c):
        red = [mpx.allreduce(x, op=op)[0] for x in (a, b, c)]
        return tuple(mpx.varying(r * 1.0) for r in red)

    mpx.set_fusion_mode(None)
    want = mpx.run(prog, *map(jnp.asarray, xs))
    mpx.set_fusion_mode("auto")
    got = mpx.run(prog, *map(jnp.asarray, xs))
    for w, g in zip(want, got):
        np.testing.assert_allclose(np.asarray(w), np.asarray(g), rtol=1e-6)


@needs_mpx
def test_fused_bcast_matches_unfused_traced():
    import jax.numpy as jnp
    import numpy as np

    mpx, comm, size = _world()
    xs = [np.arange(size * n, dtype=np.float32).reshape(size, n)
          for n in (4, 2)]

    def prog(a, b):
        r1, _ = mpx.bcast(a, 1)
        r2, _ = mpx.bcast(b, 1)
        return mpx.varying(r1 + 0), mpx.varying(r2 + 0)

    mpx.set_fusion_mode(None)
    want = mpx.run(prog, *map(jnp.asarray, xs))
    mpx.set_fusion_mode("auto")
    got = mpx.run(prog, *map(jnp.asarray, xs))
    for w, g in zip(want, got):
        np.testing.assert_array_equal(np.asarray(w), np.asarray(g))


@needs_mpx
def test_fused_mixed_dtypes_segregate():
    import jax.numpy as jnp
    import numpy as np

    mpx, comm, size = _world()
    a = np.ones((size, 3), np.float32)
    b = np.ones((size, 2), np.int32)

    def prog(a, b):
        ra = mpx.allreduce(a, op=mpx.SUM)[0]
        rb = mpx.allreduce(b, op=mpx.SUM)[0]
        return mpx.varying(ra * 1.0), mpx.varying(rb + 0)

    mpx.set_fusion_mode("auto")
    ga, gb = mpx.run(prog, jnp.asarray(a), jnp.asarray(b))
    np.testing.assert_array_equal(np.asarray(ga), np.full((size, 3), size,
                                                          np.float32))
    np.testing.assert_array_equal(np.asarray(gb), np.full((size, 2), size,
                                                          np.int32))


@needs_mpx
def test_fusion_grad_parity():
    """JVP/transpose parity: grad through fused == grad through unfused."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    mpx, comm, size = _world()

    def loss(a, b):
        ra = mpx.allreduce(a, op=mpx.SUM)[0]
        rb = mpx.allreduce(b, op=mpx.SUM)[0]
        return jnp.sum(ra * ra) + jnp.sum(rb * 3.0)

    def run_grad():
        @mpx.spmd
        def g(a, b):
            ga, gb = jax.grad(loss, argnums=(0, 1))(a, b)
            return mpx.varying(ga), mpx.varying(gb)

        a = jnp.ones((size, 3), jnp.float32)
        b = jnp.ones((size, 2), jnp.float32)
        return g(a, b)

    mpx.set_fusion_mode(None)
    w0, w1 = run_grad()
    mpx.set_fusion_mode("auto")
    g0, g1 = run_grad()
    np.testing.assert_allclose(np.asarray(w0), np.asarray(g0), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(w1), np.asarray(g1), rtol=1e-6)


@needs_mpx
def test_adjacency_breaks_on_intervening_op():
    """A non-joining op flushes the queue first: program order holds and
    results stay exact."""
    import jax.numpy as jnp
    import numpy as np

    mpx, comm, size = _world()

    def prog(a, b):
        ra = mpx.allreduce(a, op=mpx.SUM)[0]
        m, _ = mpx.allreduce(b, op=mpx.MAX)  # different reduction: flush
        rb = mpx.allreduce(b, op=mpx.SUM)[0]
        return (mpx.varying(ra * 1.0), mpx.varying(m * 1.0),
                mpx.varying(rb * 1.0))

    a = jnp.asarray(np.arange(size * 2, dtype=np.float32).reshape(size, 2))
    b = jnp.asarray(np.arange(size * 3, dtype=np.float32).reshape(size, 3))
    mpx.set_fusion_mode(None)
    want = mpx.run(prog, a, b)
    mpx.set_fusion_mode("auto")
    got = mpx.run(prog, a, b)
    for w, g in zip(want, got):
        np.testing.assert_allclose(np.asarray(w), np.asarray(g))


@needs_mpx
def test_hlo_byte_identical_when_off():
    """Acceptance pin: the default (fusion off, overlap unused) HLO is
    byte-identical to a build where the deferral layer does not exist,
    and ``auto`` is NOT (fewer collectives — so the pin cannot pass
    vacuously)."""
    import jax
    import jax.numpy as jnp

    import mpi4jax_tpu as mpx
    from mpi4jax_tpu.ops import _fusion as real_fusion

    @mpx.spmd
    def f(a, b):
        ra = mpx.allreduce(a, op=mpx.SUM)[0]
        rb = mpx.allreduce(b, op=mpx.SUM)[0]
        return mpx.varying(ra * 1.0), mpx.varying(rb * 1.0)

    a = jnp.ones((8, 4))
    b = jnp.ones((8, 3))
    default_off = jax.jit(f).lower(a, b).as_text()

    import unittest.mock as mock

    with mock.patch.object(real_fusion, "maybe_defer",
                           lambda *args, **kw: None):
        uninstrumented = jax.jit(f).lower(a, b).as_text()
    assert default_off == uninstrumented

    mpx.set_fusion_mode("off")
    explicit_off = jax.jit(f).lower(a, b).as_text()
    assert explicit_off == default_off

    mpx.set_fusion_mode("auto")
    fused = jax.jit(f).lower(a, b).as_text()
    mpx.set_fusion_mode(None)
    assert fused != default_off
    # the fused program carries ONE all-reduce where the unfused has two
    assert fused.count("all-reduce") < default_off.count("all-reduce")


@needs_mpx
def test_fusion_flip_retraces_eager_program():
    """The fusion mode is folded into the eager cache key: flipping it
    must retrace (mirrors the telemetry-mode retrace pin)."""
    import jax.numpy as jnp

    import mpi4jax_tpu as mpx

    mpx.clear_caches()
    x = jnp.ones((8, 4))
    mpx.allreduce(x, op=mpx.SUM)
    mpx.set_fusion_mode("auto")
    mpx.allreduce(x, op=mpx.SUM)  # eager never defers, but must retrace
    mpx.set_fusion_mode(None)
    mpx.allreduce(x, op=mpx.SUM)  # back to the first program
    s = mpx.cache_stats()
    assert s["misses"] == 2 and s["hits"] == 1


@needs_mpx
def test_fusion_telemetry_meters():
    import jax.numpy as jnp

    import mpi4jax_tpu as mpx

    mpx.telemetry.reset()
    mpx.set_telemetry_mode("counters")
    mpx.set_fusion_mode("auto")
    try:
        def prog(a, b):
            ra = mpx.allreduce(a, op=mpx.SUM)[0]
            rb = mpx.allreduce(b, op=mpx.SUM)[0]
            return mpx.varying(ra * 1.0), mpx.varying(rb * 1.0)

        mpx.run(prog, jnp.ones((8, 3)), jnp.ones((8, 2)))
        meters = mpx.telemetry.snapshot()["meters"]
        bucket_meters = {k: v for k, v in meters.items()
                         if ".buckets" in k and k.startswith("fusion.")}
        member_meters = {k: v for k, v in meters.items()
                         if ".members" in k and k.startswith("fusion.")}
        assert sum(bucket_meters.values()) == 1
        assert sum(member_meters.values()) == 2
    finally:
        mpx.set_fusion_mode(None)
        mpx.set_telemetry_mode(None)
        mpx.telemetry.reset()
