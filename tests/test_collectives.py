"""Per-op correctness for the collective family.

Ports the per-op suites in ref tests/collective_ops/ (allgather, alltoall,
bcast, gather, scatter, reduce, scan, barrier) — eager + jit variants, shape
contracts, and the rank-dependent-result contracts where preserved.
"""


import jax
import jax.numpy as jnp
import numpy as np
import pytest

import mpi4jax_tpu as mpx
from helpers import per_rank, ranks_arange, world


def test_allgather():
    _, size = world()

    @mpx.spmd
    def f(x):
        res, _ = mpx.allgather(x)
        return res

    x = per_rank(lambda r: np.full((3,), r))
    out = np.asarray(f(x))  # (size, size, 3)
    for r in range(size):
        assert np.allclose(out[r], np.arange(size)[:, None] * np.ones(3))


def test_allgather_eager():
    _, size = world()
    x = per_rank(lambda r: np.full((3,), r))
    res, token = mpx.allgather(x)
    assert res.shape == (size, size, 3)
    assert np.allclose(np.asarray(res)[0], np.asarray(res)[size - 1])


def test_alltoall():
    _, size = world()

    @mpx.spmd
    def f(x):
        res, _ = mpx.alltoall(x)
        return res

    # rank r sends value r*size+i to rank i
    x = per_rank(
        lambda r: np.arange(r * size, (r + 1) * size, dtype=np.float32)[:, None])
    out = np.asarray(f(x))  # (size, size, 1)
    for r in range(size):
        # rank r receives from rank i: i*size + r
        assert np.allclose(out[r, :, 0], np.arange(size) * size + r)


def test_alltoall_shape_check():
    _, size = world()
    with pytest.raises(ValueError, match="leading axis"):
        @mpx.spmd
        def f(x):
            res, _ = mpx.alltoall(x)
            return res

        f(per_rank(lambda r: np.zeros((size + 1, 2))))


@pytest.mark.parametrize("root", [0, 3])
def test_bcast(root):
    _, size = world()

    @mpx.spmd
    def f(x):
        res, _ = mpx.bcast(x, root)
        return res

    x = ranks_arange((2, 2))
    out = np.asarray(f(x))
    assert np.allclose(out, root)


def test_bcast_bool():
    _, size = world()

    @mpx.spmd
    def f(x):
        res, _ = mpx.bcast(x, 1)
        return res

    x = per_rank(lambda r: np.array([r == 1, False]), dtype=jnp.bool_)
    out = np.asarray(f(x))
    assert out.dtype == bool
    assert out[:, 0].all() and not out[:, 1].any()


@pytest.mark.parametrize("algo", ["butterfly", "ring"])
def test_bcast_forced_algos_whole_comm(monkeypatch, algo):
    """Forced algorithms take the ppermute lowerings even on a whole-axes
    comm (the escape hatch the benchmarks use): doubling broadcast
    (butterfly) vs van de Geijn scatter + ring allgather (ring).  A
    non-zero root, a payload not divisible by the comm size, and bool
    dtype must all round-trip."""
    monkeypatch.setenv("MPI4JAX_TPU_COLLECTIVE_ALGO", algo)
    _, size = world()

    @mpx.spmd
    def f(x):
        res, _ = mpx.bcast(x, 3)
        return res

    x = per_rank(lambda r: 10.0 * r + np.arange(5, dtype=np.float32))
    out = np.asarray(f(x))
    np.testing.assert_allclose(out, np.asarray(x)[3])

    @mpx.spmd
    def g(x):
        res, _ = mpx.bcast(x, 1)
        return res

    xb = per_rank(lambda r: np.array([r == 1, r == 2]), dtype=jnp.bool_)
    outb = np.asarray(g(xb))
    assert outb.dtype == bool
    assert outb[:, 0].all() and not outb[:, 1].any()


def test_bcast_grad():
    # differentiable broadcast: cotangents route back to root
    _, size = world()

    def loss(x):
        @mpx.spmd
        def per_rank_f(xl):
            y, _ = mpx.bcast(xl, 0)
            return jnp.sum(y ** 2)

        return jnp.sum(per_rank_f(x))

    x = ranks_arange((2,))
    g = np.asarray(jax.grad(loss)(x))
    # every rank's output is root's value (0.0 here broadcast from rank 0);
    # d/dx_root sum_r (x_root^2) = 2 * size * x_root; non-root grads are 0
    assert np.allclose(g[0], 2 * size * 0.0)
    assert np.allclose(g[1:], 0.0)


@pytest.mark.parametrize("root", [0, 2])
def test_gather(root):
    _, size = world()

    @mpx.spmd
    def f(x):
        res, _ = mpx.gather(x, root)
        return res

    x = per_rank(lambda r: np.full((2,), r))
    out = np.asarray(f(x))  # uniform (size, size, 2) — documented divergence
    assert np.allclose(out[root], np.arange(size)[:, None] * np.ones(2))


@pytest.mark.parametrize("root", [0, 5])
def test_scatter(root):
    _, size = world()

    @mpx.spmd
    def f(x):
        res, _ = mpx.scatter(x, root)
        return res

    # only root's buffer should matter: poison other ranks' buffers
    def buf(r):
        if r == root:
            return np.arange(size, dtype=np.float32)[:, None]
        return np.full((size, 1), -99.0, dtype=np.float32)

    out = np.asarray(f(per_rank(buf)))
    assert np.allclose(out[:, 0], np.arange(size))


def test_scatter_shape_check():
    _, size = world()
    with pytest.raises(ValueError, match="leading axis"):
        @mpx.spmd
        def f(x):
            res, _ = mpx.scatter(x, 0)
            return res

        f(per_rank(lambda r: np.zeros((3,))))


@pytest.mark.parametrize("root", [0, 4])
def test_reduce(root):
    _, size = world()

    @mpx.spmd
    def f(x):
        res, _ = mpx.reduce(x, mpx.SUM, root)
        return res

    x = ranks_arange((2,))
    out = np.asarray(f(x))
    total = size * (size - 1) / 2
    # ref contract (reduce.py:77-80): root gets reduction, others their input
    assert np.allclose(out[root], total)
    for r in range(size):
        if r != root:
            assert np.allclose(out[r], r)


@pytest.mark.parametrize(
    "op,npfn",
    [(mpx.SUM, np.cumsum), (mpx.MAX, np.maximum.accumulate),
     (mpx.PROD, np.cumprod), (mpx.MIN, np.minimum.accumulate)],
)
def test_scan(op, npfn):
    _, size = world()

    @mpx.spmd
    def f(x):
        res, _ = mpx.scan(x, op=op)
        return res

    vals = np.linspace(1.5, 0.5, size).astype(np.float32).reshape(size, 1)
    out = np.asarray(f(jnp.asarray(vals)))
    assert np.allclose(out, npfn(vals, axis=0), rtol=1e-5), (out, npfn(vals, axis=0))


def test_scan_grad():
    """Reverse- and forward-mode through the prefix scan (beyond the
    reference, which has autodiff only for allreduce/sendrecv): the
    Hillis-Steele permute rounds transpose like any ppermute chain.
    d(sum_s prefix_s^2)/dx_r = 2 * sum_{s >= r} prefix_s per group order."""
    _, size = world()

    @mpx.spmd
    def parts(x):
        res, _ = mpx.scan(x, op=mpx.SUM)
        return (res ** 2).sum(axis=-1, keepdims=True)

    def loss(x):
        return parts(x).sum()

    x = jnp.linspace(1.0, 2.0, size)[:, None]
    g = np.asarray(jax.grad(loss)(x))[:, 0]
    pref = np.cumsum(np.asarray(x)[:, 0])
    exp = np.array([2 * pref[r:].sum() for r in range(size)])
    np.testing.assert_allclose(g, exp, rtol=1e-5)

    # forward mode: tangent of the prefix is the prefix of the tangent
    tan = jnp.ones_like(x)
    _, jv = jax.jvp(loss, (x,), (tan,))
    # dL = sum_s 2 * prefix_s * (s+1-ish prefix of ones) in group order
    exp_jv = (2 * pref * np.arange(1, size + 1)).sum()
    np.testing.assert_allclose(float(jv), exp_jv, rtol=1e-5)


def test_scan_int():
    _, size = world()

    @mpx.spmd
    def f(x):
        res, _ = mpx.scan(x, op=mpx.SUM)
        return res

    x = per_rank(lambda r: np.full((1,), r), dtype=jnp.int32)
    out = np.asarray(f(x))
    assert np.array_equal(out[:, 0], np.cumsum(np.arange(size)))


def test_barrier():
    @mpx.spmd
    def f(x):
        token = mpx.barrier()
        y, _ = mpx.allreduce(x, token=token)
        return y

    _, size = world()
    out = np.asarray(f(ranks_arange(())))
    assert np.allclose(out, size * (size - 1) / 2)


def test_barrier_eager():
    token = mpx.barrier()
    assert isinstance(token, mpx.Token)


def test_chained_mixed_ops():
    # a chain across op families through one token
    _, size = world()

    @mpx.spmd
    def f(x):
        token = mpx.create_token()
        a, token = mpx.bcast(x, 0, token=token)
        b, token = mpx.allreduce(x, op=mpx.SUM, token=token)
        c, token = mpx.scan(x, op=mpx.SUM, token=token)
        token = mpx.barrier(token=token)
        d, token = mpx.allgather(x, token=token)
        return a + b + c + jnp.sum(d)

    out = f(ranks_arange(()))
    total = size * (size - 1) / 2
    ranks = np.arange(size)
    expected = 0 + total + np.cumsum(ranks) + total
    assert np.allclose(np.asarray(out), expected)


def test_full_op_matrix_on_two_axis_comm():
    """Every op family on a MULTI-AXIS comm (ref parity: ops accept any
    communicator handle, ref _src/utils.py:80-96).  Point-to-point, scan,
    alltoall, and scatter linearize the (4, 2) mesh to the row-major rank
    order Get_rank defines; before round 5 they raised on multi-axis
    comms."""
    mesh = mpx.make_world_mesh((4, 2), ("y", "x"))
    comm = mpx.Comm(("y", "x"), mesh=mesh)
    n = 8

    @mpx.spmd(comm=comm)
    def f(x, rows):
        token = mpx.create_token()
        a, token = mpx.allreduce(x, op=mpx.SUM, comm=comm, token=token)
        p, token = mpx.allreduce(x, op=mpx.PROD, comm=comm, token=token)
        b, token = mpx.bcast(x, 3, comm=comm, token=token)
        g, token = mpx.allgather(x, comm=comm, token=token)
        s, token = mpx.scan(x, mpx.SUM, comm=comm, token=token)
        r, token = mpx.sendrecv(x, x, dest=mpx.shift(1), comm=comm,
                                token=token)
        t, token = mpx.alltoall(rows, comm=comm, token=token)
        sc, token = mpx.scatter(rows, 2, comm=comm, token=token)
        gt, token = mpx.gather(x, 1, comm=comm, token=token)
        rd, token = mpx.reduce(x, mpx.MAX, 0, comm=comm, token=token)
        token = mpx.barrier(comm=comm, token=token)
        return a, p, b, g.sum(0), s, r, t, sc, gt.sum(0), rd

    x = (jnp.arange(float(n))[:, None] + 1.0)
    rows = jnp.arange(float(n * n)).reshape(n, n, 1)
    a, p, b, gs, s, r, t, sc, gt, rd = (np.asarray(v) for v in f(x, rows))
    vals = np.arange(1.0, n + 1)
    assert (a[:, 0] == vals.sum()).all()
    np.testing.assert_allclose(p[:, 0], np.prod(vals), rtol=1e-5)
    assert (b[:, 0] == vals[3]).all()
    assert (gs[:, 0] == vals.sum()).all()
    np.testing.assert_allclose(s[:, 0], np.cumsum(vals))
    np.testing.assert_array_equal(r[:, 0], np.roll(vals, 1))
    # alltoall: out[r][i] = rank i's row r (the linearized transpose)
    rows_np = np.asarray(rows)[..., 0]
    np.testing.assert_array_equal(t[..., 0], rows_np.T)
    # scatter from rank 2: rank r gets rank 2's row r
    np.testing.assert_array_equal(sc[:, 0], rows_np[2])
    # gather to rank 1 (summed over the gathered axis): the root's sum
    # covers every rank's value
    assert gt[1, 0] == vals.sum()
    np.testing.assert_array_equal(rd[0, 0], vals.max())
    np.testing.assert_array_equal(rd[1:, 0], vals[1:])


def test_butterfly_emits_ppermute_rounds_aot():
    """AOT/HLO pin for the butterfly lowerings' CollectivePermute rounds.

    The only place these lowerings compile on a real chip today is the
    1-device ambient lane (tests/test_tpu_compiled.py), where ``kmax == 1``
    makes every ppermute round dead code — so this asserts, at the lowered-
    HLO level on the 8-device mesh, that the rounds actually exist for
    ``kmax > 1``: ``ceil(log2 8) = 3`` doubling-broadcast rounds for a
    group bcast, and fold + broadcast rounds for a butterfly (PROD)
    allreduce.
    """
    import math

    _, size = world()
    rounds = math.ceil(math.log2(size))
    comm = mpx.get_default_comm()
    split = comm.Split([0] * size)  # one group of everyone: kmax = size

    @mpx.spmd(comm=split)
    def doubling_bcast(x):
        res, _ = mpx.bcast(x, 0, comm=split)
        return res

    text = jax.jit(doubling_bcast).lower(jnp.ones((size, 2))).as_text()
    got = text.count("collective_permute")
    assert got >= rounds, (
        f"doubling bcast lowered with {got} collective_permute ops; "
        f"expected the {rounds} doubling rounds for kmax={size}"
    )

    @mpx.spmd
    def butterfly_allreduce(x):
        res, _ = mpx.allreduce(x, op=mpx.PROD)
        return res

    text = jax.jit(butterfly_allreduce).lower(jnp.ones((size, 2))).as_text()
    got = text.count("collective_permute")
    # suffix-fold rounds + doubling-broadcast rounds
    assert got >= 2 * rounds, (
        f"butterfly allreduce lowered with {got} collective_permute ops; "
        f"expected {rounds} fold + {rounds} broadcast rounds for "
        f"size={size}"
    )


def test_doubling_bcast_root_out_of_range_raises():
    """``apply_doubling_bcast`` must reject a root that is not a valid group
    position in EVERY group — ``members[(root + p) % kk]`` would silently
    wrap it into a different position and misroute each round."""
    from mpi4jax_tpu.ops._base import apply_doubling_bcast

    _, size = world()
    comm = mpx.get_default_comm()
    # unequal split: group sizes (2, size - 2) — root 2 is valid in the big
    # group but out of range for the small one
    split = comm.Split([0, 0] + [1] * (size - 2))

    @mpx.spmd(comm=split)
    def f(x):
        return apply_doubling_bcast(x, split, 2)

    with pytest.raises(ValueError, match="root 2 out of range"):
        f(jnp.ones((size, 2)))
