"""Regression tests for the driver entry points (``__graft_entry__.py``).

Round 1 shipped a ``dryrun_multichip`` that asserted on device count but
never forced the virtual-CPU platform, so the driver's multi-chip check
failed (VERDICT round 1, item 1).  These tests run the entry points the
way the driver does — in a subprocess with no test conftest in sight —
so the contract cannot silently rot.
"""

import os
import subprocess
import sys

import pytest

from envcheck import jax_meets_package_floor, subprocess_import_skip_reason

# every test here spawns a subprocess that imports mpi4jax_tpu (via
# __graft_entry__); below the package's jax floor that import refuses by
# design, so the only observable outcome is the version error
pytestmark = pytest.mark.skipif(
    not jax_meets_package_floor(), reason=subprocess_import_skip_reason()
)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run(code, timeout=600):
    """Run ``code`` in a clean subprocess from the repo root.

    Scrubs the JAX/XLA env vars that tests/conftest.py sets, so the child
    sees what the driver's process sees (analog of the reference's
    ``run_in_subprocess`` scrubbed env, ref
    tests/collective_ops/test_common.py:13-57).
    """
    env = {
        k: v
        for k, v in os.environ.items()
        if k not in ("XLA_FLAGS", "JAX_PLATFORMS")
    }
    return subprocess.run(
        [sys.executable, "-c", code],
        cwd=REPO_ROOT,
        env=env,
        capture_output=True,
        text=True,
        timeout=timeout,
    )


@pytest.mark.parametrize(
    "n",
    # the full dryrun costs ~25 s per subprocess; the driver runs n=8
    # every round anyway, so only the odd-size config stays in the fast
    # tier (it covers the non-power-of-2 group/ring edge cases)
    [pytest.param(2, marks=pytest.mark.slow), 3,
     pytest.param(8, marks=pytest.mark.slow)],
)
def test_dryrun_multichip_self_forces_platform(n):
    # The child process gets NO platform env vars — dryrun_multichip must
    # force the n-device virtual CPU platform entirely on its own.
    res = _run(
        f"import __graft_entry__ as g; g.dryrun_multichip({n})"
    )
    assert res.returncode == 0, f"stderr:\n{res.stderr}"
    assert "dryrun_multichip OK" in res.stdout


@pytest.mark.slow
def test_dryrun_multichip_survives_preinitialized_jax():
    # Even if jax was already imported and backend-initialized before the
    # driver calls dryrun_multichip, the forcing must still yield n devices.
    res = _run(
        "import jax; jax.devices(); "
        "import __graft_entry__ as g; g.dryrun_multichip(4)"
    )
    assert res.returncode == 0, f"stderr:\n{res.stderr}"
    assert "dryrun_multichip OK" in res.stdout


def test_entry_compiles_and_runs():
    # The driver compile-checks entry() single-chip; mirror that here.
    # block_until_ready is a no-op on the axon-tunneled TPU, so sync by
    # fetching one element to host and assert it is finite.
    res = _run(
        "import __graft_entry__ as g; import jax, numpy as np; "
        "fn, args = g.entry(); out = jax.jit(fn)(*args); "
        "leaf = jax.tree_util.tree_leaves(out)[0]; "
        "val = np.asarray(leaf)[(0,) * leaf.ndim]; "
        "assert np.isfinite(val), val; print('entry OK')"
    )
    assert res.returncode == 0, f"stderr:\n{res.stderr}"
    assert "entry OK" in res.stdout
