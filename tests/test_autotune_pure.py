"""Autotune + tuning layer: the pure-Python half (docs/autotune.md).

Schema parse/accept/reject, the content stamp, knob lookup per
(payload-bucket, topology), the config layer's default < tuning < env
precedence, the cache-token fold, the pure fitters (crossover
interpolation, alpha-beta closed form, candidate argmin, chunk
buckets, commit-interval math), the cost-model unification
(``mpx-tuning/1`` accepted alongside ``mpx-cost-model/1``), the
``tuned@<stamp>`` advisory provenance, and
``mpx.elastic.run(commit_every='auto')`` control flow on a scripted
store — all loaded under a private package name (the isolated-loader
idiom of tests/test_cost_pure.py) so everything runs even where the
installed JAX is below the package's floor.

The traced half — retrace pins, HLO byte-identity with no file, the
live ``mpx.autotune()`` loop on the 8-device mesh — is
tests/test_autotune.py (needs jax >= the package floor).
"""

import importlib
import json
import os
import pathlib
import sys
import time
import types

import pytest

REPO = pathlib.Path(__file__).resolve().parent.parent
PKG = REPO / "mpi4jax_tpu"

_ISO_NAME = "_mpx_autotune_iso"


def _load_isolated():
    if _ISO_NAME in sys.modules:
        return sys.modules[_ISO_NAME]
    root = types.ModuleType(_ISO_NAME)
    root.__path__ = [str(PKG)]
    sys.modules[_ISO_NAME] = root
    for sub in ("utils", "analysis", "ops", "parallel", "resilience"):
        m = types.ModuleType(f"{_ISO_NAME}.{sub}")
        m.__path__ = [str(PKG / sub)]
        sys.modules[f"{_ISO_NAME}.{sub}"] = m
        setattr(root, sub, m)
    for mod in ("utils.config", "autotune", "autotune.schema",
                "autotune.fit", "autotune.runner", "ops._fusion",
                "ops._algos", "ops._hierarchy", "analysis.report",
                "analysis.graph", "analysis.checkers",
                "analysis.schedule", "analysis.matcher",
                "analysis.progress", "analysis.costmodel",
                "analysis.cost", "parallel.topology",
                "resilience.faultinject", "resilience.retry",
                "resilience.watchdog", "resilience.elastic",
                "resilience.runtime"):
        importlib.import_module(f"{_ISO_NAME}.{mod}")
    return root


ISO = _load_isolated()
config = ISO.utils.config
schema = sys.modules[f"{_ISO_NAME}.autotune.schema"]
fit = sys.modules[f"{_ISO_NAME}.autotune.fit"]
runner = sys.modules[f"{_ISO_NAME}.autotune.runner"]
algos = sys.modules[f"{_ISO_NAME}.ops._algos"]
cm = sys.modules[f"{_ISO_NAME}.analysis.costmodel"]
graph = sys.modules[f"{_ISO_NAME}.analysis.graph"]
checkers = sys.modules[f"{_ISO_NAME}.analysis.checkers"]
el = ISO.resilience.elastic

E = graph.CollectiveEvent
G = graph.CollectiveGraph


@pytest.fixture(autouse=True)
def _clean_layer(monkeypatch):
    """Every test starts and ends with no tuning layer and none of the
    tuned flags set (env is process-global; the iso config's override
    cell is module state)."""
    for flag in list(schema.KNOB_FLAGS.values()) + [
            "MPI4JAX_TPU_TUNING", "MPI4JAX_TPU_TOPOLOGY",
            "MPI4JAX_TPU_COST_MODEL"]:
        monkeypatch.delenv(flag, raising=False)
    config.load_tuning(None)
    yield
    config.load_tuning(None)


def _payload(**over):
    base = {
        "schema": schema.SCHEMA,
        "links": {"ici": {"alpha_us": 0.5, "gb_per_s": 50.0},
                  "dcn": {"alpha_us": 20.0, "gb_per_s": 10.0}},
        "tuned": {
            "ring_crossover_bytes": 4096,
            "dcn_crossover_bytes": 1 << 16,
            "fusion_bucket_bytes": 2 << 20,
            "overlap_chunks": [
                {"max_bytes": 1 << 20, "chunks": 1},
                {"max_bytes": None, "chunks": 4},
            ],
            "commit": {"pack_gb_per_s": 3.5, "target_overhead": 0.05},
        },
        "measured": {"ring_crossover_bytes": 4096,
                     "fusion_bucket_bytes": 2 << 20},
        "topologies": {"2x4": {"ring_crossover_bytes": 9999}},
        "provenance": {"jax": "0.0-test", "topology": "1x8"},
    }
    base.update(over)
    return base


# ---------------------------------------------------------------------------
# schema: accept / reject / stamp
# ---------------------------------------------------------------------------


def test_minimal_and_full_payloads_validate():
    tf = schema.TuningFile({"schema": schema.SCHEMA})
    assert len(tf.stamp) == 12 and int(tf.stamp, 16) >= 0
    full = schema.TuningFile(_payload())
    assert full.knobs()["ring_crossover_bytes"] == 4096
    assert full.has_links()
    assert not schema.TuningFile({"schema": schema.SCHEMA}).has_links()


@pytest.mark.parametrize("bad,needle", [
    ([], "JSON object"),
    ({"schema": "mpx-tuning/999"}, "schema"),
    ({"schema": schema.COST_SCHEMA}, "schema"),  # subset is NOT a layer
    ({"schema": schema.SCHEMA, "tuned": {"bogus_knob": 1}}, "unknown"),
    ({"schema": schema.SCHEMA, "tuned": {"ring_crossover_bytes": "x"}},
     "positive integer"),
    ({"schema": schema.SCHEMA, "tuned": {"ring_crossover_bytes": True}},
     "positive integer"),
    ({"schema": schema.SCHEMA, "tuned": {"ring_crossover_bytes": 0}},
     "positive integer"),
    ({"schema": schema.SCHEMA, "tuned": {"ring_crossover_bytes": 1.5}},
     "positive integer"),
    ({"schema": schema.SCHEMA, "tuned": {"commit": {"bogus": 1}}},
     "unknown"),
    ({"schema": schema.SCHEMA, "tuned": {"commit":
                                         {"pack_gb_per_s": 0}}},
     "positive"),
    ({"schema": schema.SCHEMA, "tuned": {"overlap_chunks": []}},
     "bucket"),
    ({"schema": schema.SCHEMA,
      "tuned": {"overlap_chunks": [{"max_bytes": 1}]}}, "exactly"),
    ({"schema": schema.SCHEMA,
      "tuned": {"overlap_chunks": [{"max_bytes": None, "chunks": 2},
                                   {"max_bytes": 4, "chunks": 1}]}},
     "open-ended"),
    ({"schema": schema.SCHEMA,
      "tuned": {"overlap_chunks": [{"max_bytes": 8, "chunks": 2},
                                   {"max_bytes": 4, "chunks": 1}]}},
     "ascending"),
    ({"schema": schema.SCHEMA,
      "topologies": {"2x4": {"commit": {"target_overhead": 0.02}}}},
     "only valid in"),
    ({"schema": schema.SCHEMA, "topologies": []}, "object"),
    ({"schema": schema.SCHEMA, "topologies": {"": {}}}, "non-empty"),
    ({"schema": schema.SCHEMA, "provenance": 3}, "object"),
    ({"schema": schema.SCHEMA,
      "links": {"ici": {"gb_per_s": -1}}}, "gb_per_s"),
])
def test_reject_matrix(bad, needle):
    with pytest.raises(ValueError) as ei:
        schema.validate_tuning_dict(bad)
    assert needle in str(ei.value)


def test_stamp_is_content_addressed():
    a = schema.stamp_of(_payload())
    assert a == schema.stamp_of(_payload())  # deterministic
    assert a != schema.stamp_of(_payload(source="other"))
    # key order does not matter (canonical JSON)
    p = _payload()
    rev = dict(reversed(list(p.items())))
    assert schema.stamp_of(rev) == a


def test_knob_lookup_topology_and_buckets():
    tf = schema.TuningFile(_payload())
    assert tf.knob("ring_crossover_bytes") == 4096
    assert tf.knob("ring_crossover_bytes", topology="2x4") == 9999
    assert tf.knob("ring_crossover_bytes", topology="4x2") == 4096
    # bucketed overlap chunks: boundary inclusive, open tail, no-payload
    assert tf.knob("overlap_chunks", payload_bytes=1) == 1
    assert tf.knob("overlap_chunks", payload_bytes=1 << 20) == 1
    assert tf.knob("overlap_chunks", payload_bytes=(1 << 20) + 1) == 4
    assert tf.knob("overlap_chunks") == 4
    # untuned knob on a sparse file
    sparse = schema.TuningFile({"schema": schema.SCHEMA,
                                "tuned": {"fusion_bucket_bytes": 1024}})
    assert sparse.knob("ring_crossover_bytes") is None
    with pytest.raises(KeyError):
        tf.knob("bogus")


def test_commit_params():
    tf = schema.TuningFile(_payload())
    assert tf.commit_param("pack_gb_per_s") == 3.5
    assert tf.commit_param("target_overhead") == 0.05
    assert schema.TuningFile(
        {"schema": schema.SCHEMA}).commit_param("pack_gb_per_s") is None
    with pytest.raises(KeyError):
        tf.commit_param("bogus")


def test_file_loading_and_memo(tmp_path):
    path = tmp_path / "t.json"
    path.write_text(json.dumps(_payload()))
    tf = schema.load_tuning_file_memo(str(path))
    assert tf.path == str(path)
    # content pinned at first read: same object back, even after an
    # in-place edit (cached programs cannot see the edit, so silently
    # re-reading would mix old and new lowerings in one process)
    assert schema.load_tuning_file_memo(str(path)) is tf
    path.write_text(json.dumps(_payload(source="v2")))
    os.utime(path, (time.time() + 5, time.time() + 5))
    assert schema.load_tuning_file_memo(str(path)) is tf
    # the explicit refresh (the mpx.load_tuning(path) route) re-reads
    tf2 = schema.refresh_tuning_file(str(path))
    assert tf2.stamp != tf.stamp
    assert schema.load_tuning_file_memo(str(path)) is tf2
    with pytest.raises(ValueError, match="could not be read"):
        schema.load_tuning_file(str(tmp_path / "missing.json"))
    bad = tmp_path / "bad.json"
    bad.write_text("{nope")
    with pytest.raises(ValueError, match="not valid JSON"):
        schema.load_tuning_file(str(bad))


def test_as_tuning_coercions():
    tf = schema.TuningFile(_payload())
    assert schema.as_tuning(tf) is tf
    assert schema.as_tuning(_payload()).stamp == tf.stamp
    with pytest.raises(TypeError):
        schema.as_tuning(42)


def test_knob_flags_match_the_registry():
    # every knob's shadowed flag must exist in the config registry (the
    # env-wins precedence reads it) — schema/registry drift fails here
    for flag in schema.KNOB_FLAGS.values():
        assert flag in config.FLAGS, flag


# ---------------------------------------------------------------------------
# the config layer: default < tuning < env
# ---------------------------------------------------------------------------


def test_layer_precedence_ring_crossover(monkeypatch):
    assert config.ring_crossover_bytes() == config.DEFAULT_RING_CROSSOVER_BYTES
    config.load_tuning(_payload())
    assert config.ring_crossover_bytes() == 4096
    # an explicitly set env flag wins over the file
    monkeypatch.setenv("MPI4JAX_TPU_RING_CROSSOVER_BYTES", "777")
    assert config.ring_crossover_bytes() == 777
    # an EMPTY env value counts as unset: tuning applies
    monkeypatch.setenv("MPI4JAX_TPU_RING_CROSSOVER_BYTES", "")
    assert config.ring_crossover_bytes() == 4096
    config.load_tuning(None)
    assert config.ring_crossover_bytes() == config.DEFAULT_RING_CROSSOVER_BYTES


def test_layer_serves_every_knob(monkeypatch):
    config.load_tuning(_payload())
    assert config.dcn_crossover_bytes() == 1 << 16
    assert config.fusion_bucket_bytes() == 2 << 20
    assert config.overlap_chunks() == 4
    assert config.overlap_chunks(100) == 1
    assert config.overlap_chunks(2 << 20) == 4
    monkeypatch.setenv("MPI4JAX_TPU_OVERLAP_CHUNKS", "8")
    assert config.overlap_chunks(100) == 8  # env wins over buckets too


def test_layer_precedence_alltoall_crossover(monkeypatch):
    # the PR-15 knob rides the same default < tuning < env precedence —
    # and its arrival needed NO schema bump (the content stamp retraces
    # new files, old files simply leave it untuned)
    assert config.alltoall_crossover_bytes() == \
        config.DEFAULT_ALLTOALL_CROSSOVER_BYTES
    config.load_tuning(_payload(tuned={"alltoall_crossover_bytes": 2048}))
    assert config.alltoall_crossover_bytes() == 2048
    monkeypatch.setenv("MPI4JAX_TPU_ALLTOALL_CROSSOVER_BYTES", "555")
    assert config.alltoall_crossover_bytes() == 555  # env wins
    monkeypatch.setenv("MPI4JAX_TPU_ALLTOALL_CROSSOVER_BYTES", "")
    assert config.alltoall_crossover_bytes() == 2048  # empty = unset
    config.load_tuning(None)
    assert config.alltoall_crossover_bytes() == \
        config.DEFAULT_ALLTOALL_CROSSOVER_BYTES
    # old files (no alltoall key) validate unchanged and leave the
    # knob at its default
    config.load_tuning(_payload())
    assert config.alltoall_crossover_bytes() == \
        config.DEFAULT_ALLTOALL_CROSSOVER_BYTES


def test_alltoall_crossover_topology_override_and_token(monkeypatch):
    tf = config.load_tuning(_payload(
        tuned={"alltoall_crossover_bytes": 2048},
        topologies={"2x4": {"alltoall_crossover_bytes": 4096}},
    ))
    assert config.alltoall_crossover_bytes() == 2048
    monkeypatch.setenv("MPI4JAX_TPU_TOPOLOGY", "2x4")
    assert config.alltoall_crossover_bytes() == 4096
    # the cache-token fold: the stamp rides algo_cache_token, and the
    # raw knob itself is in the base tuple — either move retraces
    tok = algos.algo_cache_token()
    assert tok[-1] == ("tuning", tf.stamp)
    monkeypatch.setenv("MPI4JAX_TPU_ALLTOALL_CROSSOVER_BYTES", "999")
    assert algos.algo_cache_token() != tok
    monkeypatch.delenv("MPI4JAX_TPU_ALLTOALL_CROSSOVER_BYTES")
    config.load_tuning(None)


def test_layer_topology_scope(monkeypatch):
    config.load_tuning(_payload())
    assert config.ring_crossover_bytes() == 4096
    monkeypatch.setenv("MPI4JAX_TPU_TOPOLOGY", "2x4")
    assert config.ring_crossover_bytes() == 9999
    monkeypatch.setenv("MPI4JAX_TPU_TOPOLOGY", "4x2")
    assert config.ring_crossover_bytes() == 4096


def test_cache_token_folds_the_stamp():
    tok0 = algos.algo_cache_token()
    # no layer: exactly the pre-tuning token (5 knobs since the
    # alltoall crossover joined in PR 15 — algo, ring, dcn, topology,
    # alltoall), no trailing stamp entry
    assert len(tok0) == 5
    tf = config.load_tuning(_payload())
    tok1 = algos.algo_cache_token()
    assert tok1[-1] == ("tuning", tf.stamp)
    assert tok1[:2] != tok0[:2] or tok1 != tok0  # tuned crossover moved
    # CHANGING the file content moves the token even when the knob
    # values stay identical (the stamp is content-addressed)
    tf2 = config.load_tuning(_payload(source="recalibrated"))
    tok2 = algos.algo_cache_token()
    assert tok2 != tok1 and tok2[-1] == ("tuning", tf2.stamp)
    config.load_tuning(None)
    assert algos.algo_cache_token() == tok0


def test_env_route_and_programmatic_override(tmp_path, monkeypatch):
    path = tmp_path / "t.json"
    path.write_text(json.dumps(_payload()))
    monkeypatch.setenv("MPI4JAX_TPU_TUNING", str(path))
    assert config.active_tuning().path == str(path)
    assert config.ring_crossover_bytes() == 4096
    # load_tuning() wins over the env file
    over = config.load_tuning(_payload(
        tuned={"ring_crossover_bytes": 1234}))
    assert config.active_tuning() is over
    assert config.ring_crossover_bytes() == 1234
    config.load_tuning(None)  # back to the env file
    assert config.ring_crossover_bytes() == 4096
    # a malformed env file raises loudly, never silently untuned
    bad = tmp_path / "bad.json"
    bad.write_text("{}")
    monkeypatch.setenv("MPI4JAX_TPU_TUNING", str(bad))
    with pytest.raises(ValueError, match="schema"):
        config.active_tuning()


def test_env_flag_wins_without_touching_a_malformed_file(tmp_path,
                                                         monkeypatch):
    # an explicitly set knob flag must win WITHOUT consulting the layer
    # at all: a malformed MPI4JAX_TPU_TUNING file cannot mask a
    # deliberate override (it still raises loudly for untuned reads)
    bad = tmp_path / "bad.json"
    bad.write_text("{}")
    monkeypatch.setenv("MPI4JAX_TPU_TUNING", str(bad))
    monkeypatch.setenv("MPI4JAX_TPU_FUSION_BUCKET_BYTES", "8388608")
    monkeypatch.setenv("MPI4JAX_TPU_OVERLAP_CHUNKS", "3")
    monkeypatch.setenv("MPI4JAX_TPU_DCN_CROSSOVER_BYTES", "4096")
    monkeypatch.setenv("MPI4JAX_TPU_RING_CROSSOVER_BYTES", "2048")
    assert config.fusion_bucket_bytes() == 8388608
    assert config.overlap_chunks() == 3
    assert config.dcn_crossover_bytes() == 4096
    assert config.ring_crossover_bytes() == 2048
    monkeypatch.delenv("MPI4JAX_TPU_FUSION_BUCKET_BYTES")
    with pytest.raises(ValueError, match="schema"):
        config.fusion_bucket_bytes()  # unset flag: the bad file is loud


def test_config_epoch_bumps_on_load():
    e0 = config.config_epoch()
    config.load_tuning(_payload())
    assert config.config_epoch() > e0
    e1 = config.config_epoch()
    config.load_tuning(None)
    assert config.config_epoch() > e1


def test_tuning_snapshot_shape(monkeypatch):
    assert config.tuning_snapshot() is None
    tf = config.load_tuning(_payload())
    monkeypatch.setenv("MPI4JAX_TPU_FUSION_BUCKET_BYTES", "512")
    snap = config.tuning_snapshot()
    assert snap["stamp"] == tf.stamp and snap["path"] is None
    k = snap["knobs"]
    assert k["ring_crossover_bytes"]["tuned"] == 4096
    assert k["ring_crossover_bytes"]["effective"] == 4096
    assert not k["ring_crossover_bytes"]["env_wins"]
    assert k["fusion_bucket_bytes"]["env_wins"]
    assert k["fusion_bucket_bytes"]["effective"] == 512
    assert snap["commit"]["pack_gb_per_s"] == 3.5


# ---------------------------------------------------------------------------
# fitters
# ---------------------------------------------------------------------------


def test_measured_crossover_interpolates_and_edges():
    rows = [{"mb": 0.1, "a": 10.0, "b": 20.0},
            {"mb": 1.0, "a": 40.0, "b": 30.0}]
    x = fit.measured_crossover(rows, "mb", "a", "b")
    assert 0.5e6 < x < 0.6e6  # delta -10 -> +10: midpoint
    # B wins immediately: first row's payload
    assert fit.measured_crossover(
        [{"mb": 0.5, "a": 5.0, "b": 1.0}], "mb", "a", "b") == 500000
    # B never wins / missing timing / empty sweep -> None
    assert fit.measured_crossover(
        [{"mb": 1.0, "a": 1.0, "b": 2.0}], "mb", "a", "b") is None
    assert fit.measured_crossover(
        [{"mb": 1.0, "a": 1.0}], "mb", "a", "b") is None
    assert fit.measured_crossover([], "mb", "a", "b") is None


def test_analytic_crossover_closed_form():
    x8 = fit.analytic_crossover(1.0, 100.0, 8)
    assert x8 is not None and x8 > 0
    # more per-round latency pushes the crossover up proportionally
    assert fit.analytic_crossover(2.0, 100.0, 8) == pytest.approx(
        2 * x8, rel=0.01)
    # below the ring's minimum group the ring never wins
    assert fit.analytic_crossover(1.0, 100.0, 3) is None
    assert fit.analytic_crossover(-1.0, 100.0, 8) is None
    assert fit.analytic_crossover(1.0, 0.0, 8) is None
    # exact check at k=4: lat_gap=2(3)-2(2)=2, byte_gap=4-1.5=2.5
    assert fit.analytic_crossover(1.0, 1.0, 4) == \
        int(-(-2 * 1.0 * 1e3 // 2.5))


def test_pick_min_and_chunk_buckets():
    rows = [{"c": 1, "t": 5.0}, {"c": 2, "t": 3.0}, {"c": 4, "t": 3.0}]
    assert fit.pick_min(rows, "c", "t") == (2, 3.0)  # tie -> earlier
    assert fit.pick_min([], "c", "t") is None
    assert fit.pick_min([{"c": 1}], "c", "t") is None
    assert fit.chunk_buckets([(1 << 20, 2), (4 << 20, 2)]) == 2
    assert fit.chunk_buckets([(1 << 20, 1), (4 << 20, 4)]) == [
        {"max_bytes": 1 << 20, "chunks": 1},
        {"max_bytes": None, "chunks": 4},
    ]
    # adjacent same-winner buckets merge before the open tail
    assert fit.chunk_buckets([(1, 1), (2, 1), (3, 4)]) == [
        {"max_bytes": 2, "chunks": 1}, {"max_bytes": None, "chunks": 4}]
    assert fit.chunk_buckets([]) is None
    # the bucketed emit loads back through the schema
    schema.validate_tuning_dict({
        "schema": schema.SCHEMA,
        "tuned": {"overlap_chunks":
                  fit.chunk_buckets([(1 << 20, 1), (4 << 20, 4)])},
    })


def test_auto_commit_interval_math():
    # 5% target: a 1 s commit over 0.1 s steps -> every 200 steps
    assert fit.auto_commit_interval(0.1, 1.0) == 200
    assert fit.auto_commit_interval(0.1, 1.0, target_overhead=0.5) == 20
    assert fit.auto_commit_interval(1.0, 0.0) == 1        # free commits
    assert fit.auto_commit_interval(0.0, 1.0) == 1        # unmeasurable
    assert fit.auto_commit_interval(1e-9, 3600.0) == \
        fit.MAX_COMMIT_INTERVAL                            # clamped


# ---------------------------------------------------------------------------
# selector + cost-model integration
# ---------------------------------------------------------------------------


def test_resolve_algo_flips_at_seeded_crossover():
    config.load_tuning(_payload())  # ring crossover tuned to 4096
    assert algos.resolve_algo("auto", 4096, 8, ring_ok=True) == "ring"
    assert algos.resolve_algo("auto", 4095, 8, ring_ok=True) == "butterfly"
    # the hier pick follows the same tuned threshold on multi-host comms
    assert algos.resolve_algo("auto", 4096, 8, ring_ok=True,
                              hier_ok=True) == "hier"
    config.load_tuning(None)
    assert algos.resolve_algo("auto", 4096, 8, ring_ok=True) == "butterfly"


def test_resolve_dcn_algo_follows_tuned_crossover():
    config.load_tuning(_payload())  # dcn crossover tuned to 64 KiB
    assert algos.resolve_dcn_algo(1 << 16, 8) == "ring"
    assert algos.resolve_dcn_algo((1 << 16) - 1, 8) == "butterfly"
    config.load_tuning(None)
    assert algos.resolve_dcn_algo(1 << 16, 8) == "butterfly"


def test_costmodel_accepts_both_schemas():
    cm.validate_model_dict({"schema": cm.SCHEMA,
                            "links": {"ici": {"alpha_us": 1.0}}})
    cm.validate_model_dict(_payload())  # the superset loads whole
    with pytest.raises(ValueError, match="schema"):
        cm.validate_model_dict({"schema": "mpx-cost-model/999"})


def test_costmodel_reads_the_tuning_layer(tmp_path, monkeypatch):
    tf = config.load_tuning(_payload())
    model = cm.load_model(None)
    assert model.tuned_stamp == tf.stamp
    assert model.params["links"]["ici"]["alpha_us"] == 0.5
    assert model.measured["ring_crossover_bytes"] == 4096
    meta = cm.measured_meta()
    assert meta["tuned_stamp"] == tf.stamp
    assert meta["measured_ring_crossover_bytes"] == 4096
    # an explicit MPI4JAX_TPU_COST_MODEL file still wins over the layer
    other = tmp_path / "cm.json"
    other.write_text(json.dumps({
        "schema": cm.SCHEMA,
        "links": {"ici": {"alpha_us": 9.0, "gb_per_s": 9.0}}}))
    monkeypatch.setenv("MPI4JAX_TPU_COST_MODEL", str(other))
    model2 = cm.load_model(None)
    assert model2.tuned_stamp is None
    assert model2.params["links"]["ici"]["alpha_us"] == 9.0


def test_cost_advisory_provenance_suffix():
    cost_mod = sys.modules[f"{_ISO_NAME}.analysis.cost"]
    tuned = cm.CostModel(tuned_stamp="feedbeef0123")
    assert cost_mod._model_provenance(tuned) == " [model tuned@feedbeef0123]"
    assert cost_mod._model_provenance(cm.CostModel()) == ""


def test_costmodel_defaults_without_any_layer():
    model = cm.load_model(None)
    assert model.tuned_stamp is None and model.source is None
    assert model.params["links"]["ici"] == \
        cm.DEFAULT_PARAMS["links"]["ici"]


# ---------------------------------------------------------------------------
# advisory provenance: tuned@<stamp> in MPX109/111/113 texts
# ---------------------------------------------------------------------------

_TUNED_META = {
    "collective_algo": "auto",
    "ring_crossover_bytes": 4096,
    "fusion_bucket_bytes": 2 << 20,
    "fusion": "off",
    "measured_ring_crossover_bytes": 4096,
    "measured_fusion_bucket_bytes": 2 << 20,
    "cost_model": "<tuning layer>",
    "tuned_stamp": "abc123def456",
}


def _findings(events, meta):
    return checkers.run_checkers(G(events=events, meta=dict(meta)))


def test_mpx113_cites_tuned_stamp():
    evs = [E(0, "allreduce", comm_uid=1, comm_size=8,
             payload_bytes=8192, algo="ring", hosts=2)]
    meta = dict(_TUNED_META, collective_algo="ring")
    (f,) = [x for x in _findings(evs, meta) if x.code == "MPX113"]
    assert "tuned@abc123def456" in f.message
    assert "measured crossover" in f.message
    # without the stamp the cite falls back to the cost-model path
    meta2 = dict(meta)
    meta2.pop("tuned_stamp")
    meta2["cost_model"] = "/tmp/cm.json"
    (f2,) = [x for x in _findings(evs, meta2) if x.code == "MPX113"]
    assert "cost model /tmp/cm.json" in f2.message
    assert "tuned@" not in f2.message


def test_mpx111_cites_tuned_stamp():
    evs = [E(i, "allreduce", comm_uid=1, reduction="sum",
             payload_bytes=64) for i in range(2)]
    (f,) = [x for x in _findings(evs, _TUNED_META) if x.code == "MPX111"]
    assert "tuned@abc123def456" in f.message
    assert f"measured {2 << 20} B bucket" in f.message


def test_mpx109_cites_tuned_stamp():
    evs = [E(0, "allreduce", comm_uid=1, comm_size=8,
             payload_bytes=4096, algo="ring")]
    (f,) = [x for x in _findings(evs, _TUNED_META) if x.code == "MPX109"]
    assert "tuned@abc123def456" in f.message
    # untouched text without a layer (the pre-autotune wording)
    meta0 = {"collective_algo": "auto", "ring_crossover_bytes": 4096}
    (f0,) = [x for x in _findings(evs, meta0) if x.code == "MPX109"]
    assert "tuned@" not in f0.message and "ring crossover" in f0.message
    # a layer that does NOT actually supply the effective crossover —
    # other knobs tuned, or an env override shadowing the file — must
    # not claim "measured" provenance for it
    meta1 = dict(_TUNED_META, ring_crossover_bytes=1 << 20)
    evs1 = [E(0, "allreduce", comm_uid=1, comm_size=8,
              payload_bytes=1 << 20, algo="ring")]
    (f1,) = [x for x in _findings(evs1, meta1) if x.code == "MPX109"]
    assert "tuned@" not in f1.message
    meta2 = dict(meta0, tuned_stamp="abc123def456")  # no measured_* key
    (f2,) = [x for x in _findings(evs, meta2) if x.code == "MPX109"]
    assert "tuned@" not in f2.message


# ---------------------------------------------------------------------------
# commit_every='auto'
# ---------------------------------------------------------------------------


class _FakeComm:
    _uids = iter(range(50_000, 60_000))

    def __init__(self, size):
        self._size = size
        self.uid = next(self._uids)

    def world_size(self):
        return self._size


class _FakeStore:
    def __init__(self, world=4):
        self.redundancy = 1
        self.bootstrap = {}
        self.comm = _FakeComm(world)
        self.commits = []
        self._committed = None
        self.drained = False

    @property
    def committed_step(self):
        return self._committed and self._committed[0]

    def commit(self, step, state):
        self._committed = (step, state)
        self.commits.append(step)

    def multiprocess(self):
        return False

    def restore(self, failed=(), force_exchange=False):
        return self._committed


def test_resolve_auto_commit_interval_reads_tuned_target():
    assert el.resolve_auto_commit_interval(0.1, 1.0) == 200  # 5% default
    config.load_tuning(_payload(tuned={"commit":
                                       {"target_overhead": 0.5}}))
    assert el.resolve_auto_commit_interval(0.1, 1.0) == 20
    config.load_tuning(None)
    assert el.resolve_auto_commit_interval(0.1, 1.0) == 200


def test_run_auto_commit_locks_an_interval():
    store = _FakeStore()

    def step_fn(state, step, comm):
        time.sleep(0.002)  # step time >> (scripted) commit cost
        return state + 1

    out = el.run(step_fn, 0, store, steps=4, commit_every="auto")
    assert out == 4
    # initial commit, then: commits every boundary until both
    # measurements exist, then the locked interval (commit cost on the
    # scripted store is microseconds against a 2 ms step -> interval 1)
    assert store.commits[0] == 0 and store.commits[-1] == 4
    assert store.commits == [0, 1, 2, 3, 4]


def test_run_rejects_unknown_commit_strings():
    with pytest.raises(ValueError, match="auto"):
        el.run(lambda s, i, c: s, 0, _FakeStore(), steps=1,
               commit_every="never")


# ---------------------------------------------------------------------------
# the whole measurement->fit->emit pipeline on a scripted microbench
# ---------------------------------------------------------------------------


class _SweepComm:
    mesh = None
    axes = ("x",)

    def Get_size(self):
        return 8

    def world_size(self):
        return 8


def _scripted_micro():
    """A fake ``benchmarks/micro.py`` with deterministic sweep rows —
    drives the ENTIRE autotune pipeline (budget loop, fitters, schema
    emission, layer load) without jax or a mesh."""
    mod = types.ModuleType("micro")

    def bench_sendrecv_ring(comm, sizes_kb, iters):
        # a perfect alpha-beta line: 2 us + bytes at 1 GB/s
        return [{"size_kb": kb, "hop_us": 2.0 + kb * 1e3 / 1e3,
                 "link_gb_s": 1.0} for kb in sizes_kb]

    def bench_allreduce_algos(comm, sizes_mb, iters):
        # ring wins at >= 0.5 MB
        return [{"size_mb": mb,
                 "butterfly_us": 10.0 * mb * 2,
                 "ring_us": 10.0 * mb + 5.0,
                 "ring_speedup": (10.0 * mb * 2) / (10.0 * mb + 5.0)}
                for mb in sizes_mb]

    def bench_hierarchy(comm, sizes_mb, topologies, iters):
        return [{"size_mb": mb, "topology": t,
                 "flat_us": 10.0 * mb, "hier_us": 4.0 + 2.0 * mb,
                 "hier_speedup": None}
                for t in topologies for mb in sizes_mb]

    def bench_alltoall(comm, sizes_mb, topologies, iters):
        # the two-level exchange wins at >= 0.5 MB
        return [{"size_mb": mb, "topology": t or "derived",
                 "flat_us": 20.0 * mb, "hier_us": 6.0 + 8.0 * mb,
                 "async_us": 18.0 * mb, "hier_speedup": None}
                for t in topologies for mb in sizes_mb]

    def bench_fusion(comm, counts, size_kb, iters):
        # 1 MiB bucket is the scripted sweet spot
        cap = int(os.environ["MPI4JAX_TPU_FUSION_BUCKET_BYTES"])
        best = 1 << 20
        cost = 1.0 + abs(cap - best) / best
        return [{"count": counts[0], "size_kb": size_kb,
                 "unfused_us_per_op": 10.0, "fused_us_per_op": cost,
                 "fused_speedup": 10.0 / cost}]

    def bench_overlap(comm, sizes_mb, iters, compute_dim):
        # small payloads want 1 chunk, large want 4
        chunks = int(os.environ["MPI4JAX_TPU_OVERLAP_CHUNKS"])
        mb = sizes_mb[0]
        want = 1 if mb < 1 else 4
        return [{"size_mb": mb, "chunks": chunks,
                 "monolithic_us": 10.0,
                 "overlap_us": 5.0 + abs(chunks - want),
                 "overlap_speedup": 1.0}]

    def bench_compression(comm, sizes_mb, iters):
        # bf16 fits the default 1e-2 error budget, fp8 does not; bf16's
        # modeled leg beats off -> the tuned knob buckets to bf16 above
        # the dcn crossover
        return [
            {"size_mb": mb, "codec": codec, "topology": "2x4",
             "logical_dcn_bytes": int(mb * 5e5),
             "wire_dcn_bytes": int(mb * 5e5) // div,
             "modeled_dcn_us": 100.0 * mb / div,
             "rel_err": err}
            for mb in sizes_mb
            for codec, div, err in (("off", 1, 0.0),
                                    ("bf16", 2, 4e-3),
                                    ("fp8", 4, 7e-2))
        ]

    def fit_alpha_beta(points):
        return 2.0, 1.0

    def measured_ring_crossover(rows):
        prev = None
        for r in rows:
            delta = r["butterfly_us"] - r["ring_us"]
            if delta >= 0:
                return int((prev if prev is not None else r["size_mb"])
                           * 1e6)
            prev = r["size_mb"]
        return None

    for fn in (bench_sendrecv_ring, bench_allreduce_algos,
               bench_hierarchy, bench_alltoall, bench_fusion,
               bench_overlap, bench_compression, fit_alpha_beta,
               measured_ring_crossover):
        setattr(mod, fn.__name__, fn)
    return mod


def test_autotune_pipeline_on_scripted_sweeps(tmp_path, monkeypatch):
    monkeypatch.setitem(sys.modules, "micro", _scripted_micro())
    path = tmp_path / "tuning.json"
    result = runner.autotune(comm=_SweepComm(), budget_s=30.0,
                             save=str(path), load=True,
                             topologies=("2x4",))
    payload = json.loads(path.read_text())
    schema.validate_tuning_dict(payload)
    assert payload["schema"] == schema.SCHEMA
    # the scripted ici fit came through verbatim
    assert payload["links"]["ici"] == {"alpha_us": 2.0, "gb_per_s": 1.0}
    assert payload["links"]["dcn"]["gb_per_s"] > 0
    # ring crossover from the scripted sweep (ring wins at 0.5 MB)
    assert 0 < payload["tuned"]["ring_crossover_bytes"] <= int(5e5)
    # dcn crossover from the closed form over the scaled dcn class
    assert payload["tuned"]["dcn_crossover_bytes"] > 0
    # fusion bucket: the scripted sweet spot
    assert payload["tuned"]["fusion_bucket_bytes"] == 1 << 20
    # overlap chunks bucketed: small payload 1, large 4
    chunks = payload["tuned"]["overlap_chunks"]
    assert chunks == [{"max_bytes": 250000, "chunks": 1},
                      {"max_bytes": None, "chunks": 4}]
    # pack throughput measured on the synthetic state
    assert payload["tuned"]["commit"]["pack_gb_per_s"] > 0
    # per-topology override from the scripted hier sweep
    assert payload["topologies"]["2x4"]["ring_crossover_bytes"] > 0
    # the PR-15 knob: fitted from the scripted flat-vs-hier alltoall
    # sweep (hier wins at >= 0.5 MB), per-topology AND flat-seeded,
    # with the fit source recorded in provenance
    a2a = payload["topologies"]["2x4"]["alltoall_crossover_bytes"]
    assert 0 < a2a <= int(5e5)
    assert payload["tuned"]["alltoall_crossover_bytes"] == a2a
    assert payload["measured"]["alltoall_crossover_bytes"] == a2a
    assert payload["provenance"]["fit_sources"][
        "alltoall_crossover_bytes"] == "sweep @ 2x4"
    assert config.alltoall_crossover_bytes() == a2a  # layer serves it
    # provenance self-description
    prov = payload["provenance"]
    assert prov["n_devices"] == 8 and prov["budget_s"] == 30.0
    assert len(prov["config_stamp"]) == 12
    # load=True installed the layer in the iso config
    assert config.active_tuning() is not None
    assert config.active_tuning().stamp == result.stamp
    assert config.ring_crossover_bytes() == \
        payload["tuned"]["ring_crossover_bytes"]
    # the PR-17 codec knob: bf16 fits the scripted error budget and
    # beats off on the modeled DCN leg; fp8 is over budget and loses.
    # Bucketed: legs below the fitted dcn crossover stay exact ("off")
    comp = payload["tuned"]["compress"]
    assert comp == [
        {"max_bytes": payload["tuned"]["dcn_crossover_bytes"],
         "codec": "off"},
        {"max_bytes": None, "codec": "bf16"},
    ]
    assert payload["measured"]["compress_rel_err_bf16"] == 4e-3
    assert payload["provenance"]["fit_sources"]["compress"] == \
        "sweep vs error budget"
    # the layer serves it through the payload-bucketed getter: a leg
    # below the crossover stays exact, one above compresses
    small = payload["tuned"]["dcn_crossover_bytes"]
    assert config.compress_mode(payload_bytes=small) == "off"
    assert config.compress_mode(payload_bytes=small + 1) == "bf16"
    assert result.unfitted == ()
    assert "links" in result.fitted and "commit" in result.fitted


def test_autotune_rejects_bad_budget():
    with pytest.raises(ValueError, match="budget_s"):
        runner.autotune(comm=_SweepComm(), budget_s=0)


# ---------------------------------------------------------------------------
# runner scaffolding (the jax-free parts) + CLI usage errors
# ---------------------------------------------------------------------------


def test_env_patch_restores(monkeypatch):
    monkeypatch.setenv("MPI4JAX_TPU_FUSION_BUCKET_BYTES", "123")
    with runner._EnvPatch(MPI4JAX_TPU_FUSION_BUCKET_BYTES=456,
                          MPI4JAX_TPU_OVERLAP_CHUNKS=7):
        assert os.environ["MPI4JAX_TPU_FUSION_BUCKET_BYTES"] == "456"
        assert os.environ["MPI4JAX_TPU_OVERLAP_CHUNKS"] == "7"
    assert os.environ["MPI4JAX_TPU_FUSION_BUCKET_BYTES"] == "123"
    assert "MPI4JAX_TPU_OVERLAP_CHUNKS" not in os.environ


def test_budget_polling():
    b = runner._Budget(1000.0)
    assert b.ok() and b.elapsed() < 1000.0
    b2 = runner._Budget(1e-9)
    time.sleep(0.001)
    assert not b2.ok()


def test_cli_rejects_bad_budget():
    main = importlib.import_module(f"{_ISO_NAME}.autotune.__main__").main
    assert main(["--budget-s", "0"]) == 2
    assert main(["--budget-s", "-5"]) == 2


def test_cli_any_crash_is_exit_2(monkeypatch, tmp_path, capsys):
    # a crashed run must NEVER exit 1 ("partial fit, file written"):
    # any exception class maps to the failure code 2
    main = importlib.import_module(f"{_ISO_NAME}.autotune.__main__").main

    def boom(**kw):
        raise KeyError("missing sweep key")

    monkeypatch.setattr(runner, "autotune", boom)
    rc = main(["--budget-s", "5", "--save", str(tmp_path / "t.json")])
    assert rc == 2
    assert "KeyError" in capsys.readouterr().err
