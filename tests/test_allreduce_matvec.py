"""Distributed matvec: the transpose-correctness acceptance suite.

Port of ref tests/collective_ops/test_allreduce_matvec.py (239 LoC): a dense
matrix A is column-sharded across ranks; ``A @ x`` needs one allreduce of the
per-rank partial products, and ``jax.linear_transpose`` of that operator must
give the exact row-sharded ``A.T @ y`` — "the transposed operator for free" —
including through jit and double transposition (SURVEY.md §2.6(3)).
"""

import jax
import jax.numpy as jnp
import numpy as np

import mpi4jax_tpu as mpx
from helpers import world

N = 16  # precondition n % size == 0 (ref test_allreduce_matvec.py:23)


def _setup():
    comm, size = world()
    rng = np.random.RandomState(42)
    A = rng.randn(N, N).astype(np.float32)
    x = rng.randn(N).astype(np.float32)
    cols = N // size
    # global sharded operands: rank r holds A[:, r*cols:(r+1)*cols] and the
    # corresponding slice of x
    A_sharded = jnp.asarray(
        np.stack([A[:, r * cols:(r + 1) * cols] for r in range(size)])
    )
    x_sharded = jnp.asarray(x.reshape(size, cols))
    return A, x, A_sharded, x_sharded, size, cols


def _matvec(A_local, x_local):
    """Per-rank column-sharded matvec: partial = A_local @ x_local, allreduce."""
    partial = A_local @ x_local
    res, _ = mpx.allreduce(partial, op=mpx.SUM)
    return res


def test_matvec_forward():
    A, x, A_sh, x_sh, size, cols = _setup()

    @mpx.spmd
    def f(Al, xl):
        return _matvec(Al, xl)

    out = np.asarray(f(A_sh, x_sh))
    expected = A @ x
    assert np.allclose(out, expected, atol=1e-4), np.abs(out - expected).max()


def test_matvec_transpose():
    # linear_transpose of the column-sharded matvec = row-sharded A.T @ y
    A, x, A_sh, x_sh, size, cols = _setup()
    rng = np.random.RandomState(7)
    y = rng.randn(N).astype(np.float32)

    @mpx.spmd
    def f(Al, xl):
        mv = lambda v: _matvec(Al, v)
        t = jax.linear_transpose(mv, xl)
        y_rep = jax.lax.psum(jnp.zeros((N,), jnp.float32), "mpi4jax") + jnp.asarray(y)
        (ct,) = t(y_rep)
        return ct

    out = np.asarray(f(A_sh, x_sh))  # (size, cols)
    expected = (A.T @ y).reshape(out.shape)
    assert np.allclose(out, expected, atol=1e-4), np.abs(out - expected).max()


def test_matvec_double_transpose():
    A, x, A_sh, x_sh, size, cols = _setup()

    @mpx.spmd
    def f(Al, xl):
        mv = lambda v: _matvec(Al, v)
        t = jax.linear_transpose(mv, xl)
        y_rep = jax.lax.psum(jnp.zeros((N,), jnp.float32), "mpi4jax")
        t2 = jax.linear_transpose(lambda c: t(c)[0], y_rep)
        return t2(xl)[0]

    out = np.asarray(f(A_sh, x_sh))
    expected = A @ x
    assert np.allclose(out, expected, atol=1e-4)


def test_matvec_vjp_matches_numpy():
    A, x, A_sh, x_sh, size, cols = _setup()
    rng = np.random.RandomState(3)
    y = rng.randn(N).astype(np.float32)

    @mpx.spmd
    def f(Al, xl):
        mv = lambda v: _matvec(Al, v)
        out, vjp_fn = jax.vjp(mv, xl)
        y_rep = jax.lax.psum(jnp.zeros((N,), jnp.float32), "mpi4jax") + jnp.asarray(y)
        (ct,) = vjp_fn(y_rep)
        return ct

    out = np.asarray(f(A_sh, x_sh))
    expected = (A.T @ y).reshape(out.shape)
    assert np.allclose(out, expected, atol=1e-4)


def test_matvec_jvp():
    A, x, A_sh, x_sh, size, cols = _setup()

    @mpx.spmd
    def f(Al, xl):
        mv = lambda v: _matvec(Al, v)
        y, dy = jax.jvp(mv, (xl,), (jnp.ones_like(xl),))
        return dy

    out = np.asarray(f(A_sh, x_sh))
    expected = A @ np.ones(N, np.float32)
    assert np.allclose(out, expected, atol=1e-4)
