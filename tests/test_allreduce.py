"""allreduce: the reference's transform-coverage matrix.

Ports ref tests/collective_ops/test_allreduce.py:57-251 — eager, jit, vmap,
grad, jvp, vjp, linear_transpose (×2 and ×3 nested), token chaining — plus
the non-SUM reductions the reference can't differentiate.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import mpi4jax_tpu as mpx
from helpers import ranks_arange, world


def _expected_sum(shape=()):
    _, size = world()
    return np.full(shape, size * (size - 1) / 2.0)


def test_allreduce_region_jit():
    comm, size = world()

    @mpx.spmd
    def f(x):
        res, _ = mpx.allreduce(x, op=mpx.SUM)
        return res

    x = ranks_arange((3, 2))
    out = np.asarray(f(x))
    assert np.allclose(out, _expected_sum((3, 2)))


def test_allreduce_eager():
    x = ranks_arange((3, 3))
    res, token = mpx.allreduce(x, op=mpx.SUM)
    assert np.allclose(np.asarray(res), _expected_sum((3, 3)))
    assert isinstance(token, mpx.Token)


@pytest.mark.parametrize(
    "op,npfn",
    [
        (mpx.SUM, np.add.reduce),
        (mpx.PROD, np.multiply.reduce),
        (mpx.MIN, np.minimum.reduce),
        (mpx.MAX, np.maximum.reduce),
    ],
)
def test_allreduce_ops(op, npfn):
    _, size = world()

    @mpx.spmd
    def f(x):
        res, _ = mpx.allreduce(x, op=op)
        return res

    vals = np.arange(1, size + 1, dtype=np.float32).reshape(size, 1)
    out = np.asarray(f(jnp.asarray(vals)))
    assert np.allclose(out, npfn(vals, axis=0)), (out, npfn(vals, axis=0))


def test_allreduce_logical():
    _, size = world()

    @mpx.spmd
    def f(x):
        res, _ = mpx.allreduce(x, op=mpx.LAND)
        return res

    vals = np.ones((size, 2), dtype=bool)
    vals[2, 0] = False
    out = np.asarray(f(jnp.asarray(vals)))
    assert out.dtype == bool
    assert not out[:, 0].any() and out[:, 1].all()


def test_allreduce_custom_op():
    # User-defined reduction as a callable — beyond-reference capability.
    # MPI's contract (which the reference inherits from libmpi): the op
    # must be ASSOCIATIVE; commutativity is NOT required, and the result
    # must be the fold in ascending rank order.  A 2x2 matrix product pins
    # exactly that: associative, non-commutative, so any mis-ordered or
    # mis-grouped combine changes the answer.
    _, size = world()

    @mpx.spmd
    def f(x):
        res, _ = mpx.allreduce(x, op=jnp.matmul)
        return res

    rng = np.random.default_rng(0)
    mats = rng.normal(size=(size, 2, 2)).astype(np.float32)
    out = np.asarray(f(jnp.asarray(mats)))
    expected = np.eye(2, dtype=np.float32)
    for r in range(size):
        expected = expected @ mats[r]
    # every rank must hold the same rank-ordered product
    for r in range(size):
        np.testing.assert_allclose(out[r], expected, rtol=1e-5, atol=1e-5)


def test_allreduce_custom_op_commutative():
    # an associative+commutative callable: sqrt-of-sum-of-squares
    _, size = world()

    @mpx.spmd
    def f(x):
        res, _ = mpx.allreduce(x, op=lambda a, b: jnp.sqrt(a * a + b * b))
        return res

    out = np.asarray(f(ranks_arange((1,))))
    expected = np.sqrt(sum(float(r) ** 2 for r in range(size)))
    assert np.allclose(out, expected, rtol=1e-5)


def test_allreduce_vmap():
    _, size = world()

    @mpx.spmd
    def f(x):
        res, _ = mpx.allreduce(x, op=mpx.SUM)
        return res

    xb = jnp.arange(size * 2 * 3, dtype=jnp.float32).reshape(size, 2, 3)
    out = jax.vmap(f, in_axes=1, out_axes=1)(xb)
    assert np.allclose(np.asarray(out), np.asarray(xb).sum(0, keepdims=True))


def test_allreduce_grad():
    # ref test_allreduce.py grad coverage; DP-SGD gradient pattern
    x = ranks_arange((4,))

    def loss(w):
        @mpx.spmd
        def per_rank(wl):
            s, _ = mpx.allreduce(jnp.sum(wl ** 2), op=mpx.SUM)
            return s

        return per_rank(w)[0]

    g = jax.grad(loss)(x)
    assert np.allclose(np.asarray(g), 2 * np.asarray(x))


def test_allreduce_jvp():
    # ref allreduce jvp: tangent is allreduced alongside primal
    _, size = world()

    @mpx.spmd
    def f(x):
        def g(a):
            return mpx.allreduce(a, op=mpx.SUM)[0]

        # tangent must be rank-varying like the primal (ones_like inherits
        # the vma type; a fresh jnp.ones would be replicated-typed)
        y, dy = jax.jvp(g, (x,), (jnp.ones_like(x),))
        return y + 0 * dy, dy

    x = ranks_arange((2,))
    y, dy = f(x)
    assert np.allclose(np.asarray(y), _expected_sum((2,)))
    assert np.allclose(np.asarray(dy), size)


def test_allreduce_vjp():
    _, size = world()

    @mpx.spmd
    def f(x):
        def g(a):
            return mpx.allreduce(a, op=mpx.SUM)[0]

        y, vjp_fn = jax.vjp(g, x)
        (ct,) = vjp_fn(jnp.ones(y.shape, y.dtype))
        return y, ct

    x = ranks_arange((2,))
    y, ct = f(x)
    assert np.allclose(np.asarray(y), _expected_sum((2,)))
    # vjp of psum: cotangent replicated back (identity per rank, then the
    # pullback to each rank's contribution is the full cotangent)
    assert np.allclose(np.asarray(ct), 1.0)


def test_allreduce_transpose_identity():
    # ref test_allreduce.py:105-138 — transpose of allreduce-SUM is identity
    @mpx.spmd
    def f(x):
        g = lambda a: mpx.allreduce(a, op=mpx.SUM)[0]
        t = jax.linear_transpose(g, x)
        return t(jnp.ones(x.shape, x.dtype))[0]

    out = np.asarray(f(ranks_arange((3,))))
    assert np.allclose(out, 1.0)


def test_allreduce_double_transpose():
    # double transpose restores a true allreduce
    @mpx.spmd
    def f(x):
        g = lambda a: mpx.allreduce(a, op=mpx.SUM)[0]
        t = jax.linear_transpose(g, x)
        rep = jax.lax.psum(jnp.zeros(x.shape, x.dtype), "mpi4jax")
        t2 = jax.linear_transpose(lambda c: t(c)[0], rep)
        return t2(x)[0]

    out = np.asarray(f(ranks_arange((3,))))
    assert np.allclose(out, _expected_sum((3,)))


def test_allreduce_triple_transpose():
    # ref nests linear_transpose three deep (test_allreduce.py:105-138)
    @mpx.spmd
    def f(x):
        g = lambda a: mpx.allreduce(a, op=mpx.SUM)[0]
        t1 = jax.linear_transpose(g, x)
        rep = jax.lax.psum(jnp.zeros(x.shape, x.dtype), "mpi4jax")
        t2 = jax.linear_transpose(lambda c: t1(c)[0], rep)
        t3 = jax.linear_transpose(lambda c: t2(c)[0], x)
        return t3(rep + 1.0)[0]

    # t3 = transpose of allreduce = identity again
    out = np.asarray(f(ranks_arange((3,))))
    assert np.allclose(out, 1.0)


def test_allreduce_chained_tokens():
    # ref chained-token tests: two allreduces threaded through one token
    _, size = world()

    @mpx.spmd
    def f(x):
        token = mpx.create_token()
        a, token = mpx.allreduce(x, op=mpx.SUM, token=token)
        b, token = mpx.allreduce(a, op=mpx.MAX, token=token)
        return b

    out = np.asarray(f(ranks_arange((2,))))
    assert np.allclose(out, _expected_sum((2,)))


def test_allreduce_scalar():
    @mpx.spmd
    def f(x):
        res, _ = mpx.allreduce(x, op=mpx.SUM)
        return res

    out = np.asarray(f(ranks_arange(())))
    assert np.allclose(out, _expected_sum(()))


def test_allreduce_bf16():
    # bfloat16 is first-class on this framework (TPU native dtype)
    @mpx.spmd
    def f(x):
        res, _ = mpx.allreduce(x, op=mpx.SUM)
        return res

    x = ranks_arange((2,), dtype=jnp.bfloat16)
    out = f(x)
    assert out.dtype == jnp.bfloat16
    assert np.allclose(np.asarray(out, dtype=np.float32), _expected_sum((2,)))


# ---------------------------------------------------------------------------
# payload-aware algorithm layer (ops/_algos.py): butterfly vs ring
# ---------------------------------------------------------------------------

_ALGO_OP_CASES = [
    (mpx.SUM, np.add.reduce, "float"),
    (mpx.PROD, np.multiply.reduce, "float"),
    (mpx.MIN, np.minimum.reduce, "float"),
    (mpx.MAX, np.maximum.reduce, "float"),
    (mpx.LAND, np.logical_and.reduce, "bool"),
    (mpx.LOR, np.logical_or.reduce, "bool"),
    (mpx.LXOR, np.logical_xor.reduce, "bool"),
    (mpx.BAND, np.bitwise_and.reduce, "int"),
    (mpx.BOR, np.bitwise_or.reduce, "int"),
    (mpx.BXOR, np.bitwise_xor.reduce, "int"),
]


@pytest.mark.parametrize("algo", ["auto", "butterfly", "ring"])
@pytest.mark.parametrize("op,npred,kind", _ALGO_OP_CASES,
                         ids=[o.name for o, _, _ in _ALGO_OP_CASES])
def test_allreduce_algo_equivalence(monkeypatch, algo, op, npred, kind):
    """Every Op must produce the same result under the forced butterfly,
    the forced ring, and auto — on a payload NOT divisible by the group
    size, so the ring's chunk padding is exercised too.  The env override
    is folded into the compiled-program cache keys, so each setting
    retraces (no clear_caches needed)."""
    monkeypatch.setenv("MPI4JAX_TPU_COLLECTIVE_ALGO", algo)
    _, size = world()

    @mpx.spmd
    def f(x):
        res, _ = mpx.allreduce(x, op=op)
        return res

    rng = np.random.default_rng(7)
    if kind == "bool":
        vals = rng.integers(0, 2, size=(size, 5)).astype(bool)
    elif kind == "int":
        vals = rng.integers(0, 128, size=(size, 5)).astype(np.int32)
    else:
        vals = rng.uniform(0.5, 1.5, size=(size, 5)).astype(np.float32)
    out = np.asarray(f(jnp.asarray(vals)))
    expected = npred(vals, axis=0)
    for r in range(size):
        np.testing.assert_allclose(
            out[r].astype(np.float64), expected.astype(np.float64),
            rtol=1e-5, err_msg=f"algo={algo} op={op} rank={r}")


def test_allreduce_ring_elementwise_callable_order(monkeypatch):
    """A forced ring accepts ELEMENTWISE callables (the MPI_User_function
    contract; whole-array callables keep the butterfly — see _algos).
    Right-projection is associative, non-commutative, and elementwise:
    the ascending group-rank fold must yield the LAST rank's value, which
    any mis-ordered ring combine would change."""
    monkeypatch.setenv("MPI4JAX_TPU_COLLECTIVE_ALGO", "ring")
    _, size = world()

    @mpx.spmd
    def f(x):
        res, _ = mpx.allreduce(x, op=lambda a, b: b)
        return res

    out = np.asarray(f(ranks_arange((5,))))
    assert np.allclose(out, size - 1), out


def test_allreduce_ring_vs_butterfly_hlo_byte_volume(monkeypatch):
    """The acceptance-criteria HLO pin: a forced-ring allreduce must move
    chunk-sized payloads per CollectivePermute round (O(size) bytes per
    rank over 2·(k-1) rounds), while the butterfly ships the FULL payload
    every round (O(size·log k))."""
    _, size = world()
    nelem = 64 * size  # local payload; ring chunk = 64 elements
    x = jnp.ones((size, nelem), jnp.float32)

    def lowered(algo):
        monkeypatch.setenv("MPI4JAX_TPU_COLLECTIVE_ALGO", algo)

        @mpx.spmd
        def f(xl):
            res, _ = mpx.allreduce(xl, op=mpx.SUM)
            return res

        return jax.jit(f).lower(x).as_text()

    ring_lines = [ln for ln in lowered("ring").splitlines()
                  if "collective_permute" in ln]
    # 2·(k-1) chunk-sized rounds: k-1 reduce-scatter + k-1 allgather
    assert len(ring_lines) >= 2 * (size - 1), len(ring_lines)
    assert any(f"tensor<{nelem // size}xf32>" in ln for ln in ring_lines)
    for ln in ring_lines:  # never the full payload
        assert f"tensor<{nelem}xf32>" not in ln, ln

    fly_lines = [ln for ln in lowered("butterfly").splitlines()
                 if "collective_permute" in ln]
    assert len(fly_lines) >= 1
    # every butterfly round ships the FULL payload
    assert all(f"tensor<{nelem}xf32>" in ln for ln in fly_lines)


def test_eager_cache_algo_key_and_clear_caches(monkeypatch):
    """Toggling MPI4JAX_TPU_COLLECTIVE_ALGO must retrace the eager one-op
    program (the knob is folded into the cache key, mirroring the
    resilience flags), and mpx.clear_caches() must drain the cache."""
    from mpi4jax_tpu.ops import _base

    mpx.clear_caches()
    x = ranks_arange((4,))
    res1, _ = mpx.allreduce(x, op=mpx.PROD)
    n1 = len(_base._eager_cache)
    assert n1 >= 1
    mpx.allreduce(x, op=mpx.PROD)  # same key: cache hit, no growth
    assert len(_base._eager_cache) == n1
    monkeypatch.setenv("MPI4JAX_TPU_COLLECTIVE_ALGO", "ring")
    res2, _ = mpx.allreduce(x, op=mpx.PROD)  # new key: retraced
    assert len(_base._eager_cache) == n1 + 1
    np.testing.assert_allclose(np.asarray(res2), np.asarray(res1),
                               rtol=1e-5)
    mpx.clear_caches()
    assert len(_base._eager_cache) == 0
