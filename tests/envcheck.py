"""Environment predicates for subprocess tests.

Some tests spawn a fresh interpreter that ``import mpi4jax_tpu``s; in a
sandbox whose installed JAX is below the package's hard floor
(utils/jax_compat.MIN_JAX_VERSION) that import refuses by design, so the
subprocess can only ever report the version error.  Those tests carry
``pytest.mark.skipif(not jax_meets_package_floor(), ...)`` — the skip
reason documents that this is a container-environment limitation, not a
product bug (CHANGES.md PR 7 triage).

The floor is read from the source text (not imported): importing
``mpi4jax_tpu.utils.jax_compat`` would execute the package ``__init__``
whose version check is the very thing that refuses.
"""

import pathlib
import re

REPO = pathlib.Path(__file__).resolve().parent.parent


def _versiontuple(v: str):
    parts = []
    for p in v.split("."):
        digits = ""
        for ch in p:
            if ch.isdigit():
                digits += ch
            else:
                break
        parts.append(int(digits) if digits else 0)
    return tuple(parts[:3])


def package_jax_floor() -> str:
    src = (REPO / "mpi4jax_tpu" / "utils" / "jax_compat.py").read_text()
    m = re.search(r'MIN_JAX_VERSION\s*=\s*"([^"]+)"', src)
    assert m, "MIN_JAX_VERSION not found in utils/jax_compat.py"
    return m.group(1)


def jax_meets_package_floor() -> bool:
    import jax

    return _versiontuple(jax.__version__) >= _versiontuple(
        package_jax_floor())


SUBPROCESS_IMPORT_SKIP = (
    "container-environment-only failure: the subprocess imports "
    "mpi4jax_tpu, whose jax floor (>= {floor}) the installed jax does "
    "not meet — the import refuses by design (see utils/jax_compat.py "
    "and CHANGES.md PR 7 triage)"
)


def subprocess_import_skip_reason() -> str:
    return SUBPROCESS_IMPORT_SKIP.format(floor=package_jax_floor())
