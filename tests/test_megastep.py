"""Traced half of the megastep execution suite (docs/aot.md "Megastep
execution"): everything that needs real traces on the 8-device virtual
CPU mesh.

- megastep == N eager steps bit-identity: ``mpx.compile(fn, unroll=N)``
  and ``mpx.spmd(..., unroll=N)`` against N sequential single-step
  executions, through the token, notoken, and eager comparison paths,
  with fusion and start/wait spans inside the loop body;
- HLO byte-identity at ``unroll=1`` (the megastep layer must be
  invisible until asked for);
- MPX130 (span straddles the loop boundary) positive/negative through
  ``mpx.analyze`` and the ambient error mode;
- the elastic 8 -> 7 shrink drill with a megastep step function:
  commit/retry at megastep granularity, resuming from the last commit;
- the C++ fast-path dispatch: graceful fallback when jaxlib support is
  missing (or ``MPI4JAX_TPU_CPP_DISPATCH=false``), no staleness on the
  dispatch-only flag;
- the whole-megastep watchdog bracket (deadline scaled by N) and the
  events-tier megastep bracket + synthesized per-step estimate;
- the cache-warming CLI end to end against a manifest.

The pure half (MPX130 checker matrix, fastpath fakes, manifest parsing,
alignment helpers) runs under any JAX in tests/test_megastep_pure.py
via the isolated loader.
"""

import json
import textwrap

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import mpi4jax_tpu as mpx
from mpi4jax_tpu.resilience import elastic as el
from mpi4jax_tpu.resilience import runtime as resilience_runtime

UNROLL = 4


@pytest.fixture(autouse=True)
def _clean_state():
    el._reset_epoch_for_tests()
    mpx.set_default_mesh(None)
    mpx.clear_caches()
    yield
    mpx.set_telemetry_mode(None)
    mpx.set_analyze_mode(None)
    mpx.set_fusion_mode(None)
    resilience_runtime.reset_overrides()
    el._reset_epoch_for_tests()
    mpx.set_default_mesh(None)
    mpx.clear_caches()
    from mpi4jax_tpu.parallel import region as _region

    _region._default_comm = None


def _world_comm():
    mesh = mpx.make_world_mesh()
    return mpx.Comm(mesh.axis_names[0], mesh=mesh)


def _step_token(v):
    tok = mpx.create_token()
    s, tok = mpx.allreduce(v, op=mpx.SUM, token=tok)
    b, tok = mpx.bcast(mpx.varying(s), 0, token=tok)
    return mpx.varying(b * 0.25 + v * 0.5)


def _step_plain(v):
    s, _ = mpx.allreduce(v, op=mpx.SUM)
    return mpx.varying(s * 0.25 + v * 0.5)


def _n_eager_steps(fn_single, x, n, comm):
    out = x
    prog = mpx.spmd(fn_single, comm=comm)
    for _ in range(n):
        out = prog(out)
    return np.asarray(out)


# ---------------------------------------------------------------------------
# megastep == N eager steps bit-identity
# ---------------------------------------------------------------------------


def test_megastep_pinned_matches_n_steps_token_path():
    comm = _world_comm()
    k = comm.Get_size()
    x = jnp.arange(k * 6, dtype=jnp.float32).reshape(k, 6) * 0.01
    want = _n_eager_steps(_step_token, x, UNROLL, comm)
    pinned = mpx.compile(_step_token, x, comm=comm, unroll=UNROLL)
    assert pinned.unroll == UNROLL
    got = np.asarray(pinned(x))
    np.testing.assert_array_equal(want, got)


def test_megastep_pinned_matches_n_steps_notoken_path(monkeypatch):
    monkeypatch.setenv("MPI4JAX_TPU_PREFER_NOTOKEN", "1")
    comm = _world_comm()
    k = comm.Get_size()
    x = jnp.full((k, 4), 2.0, jnp.float32)
    want = _n_eager_steps(_step_plain, x, UNROLL, comm)
    pinned = mpx.compile(_step_plain, x, comm=comm, unroll=UNROLL)
    np.testing.assert_array_equal(want, np.asarray(pinned(x)))


def test_megastep_spmd_matches_n_steps():
    comm = _world_comm()
    k = comm.Get_size()
    x = jnp.arange(k * 4, dtype=jnp.float32).reshape(k, 4) * 0.1
    want = _n_eager_steps(_step_plain, x, UNROLL, comm)
    mega = mpx.spmd(_step_plain, comm=comm, unroll=UNROLL)
    np.testing.assert_array_equal(want, np.asarray(mega(x)))


def test_megastep_matches_eager_applications():
    comm = _world_comm()
    k = comm.Get_size()
    x = jnp.full((k, 3), 1.5, jnp.float32)
    # eager reference: N global-array applications outside any region
    out = x
    for _ in range(UNROLL):
        s, _ = mpx.allreduce(out, op=mpx.SUM, comm=comm)
        out = np.asarray(s) * 0.25 + np.asarray(out) * 0.5
    pinned = mpx.compile(_step_plain, x, comm=comm, unroll=UNROLL)
    np.testing.assert_allclose(np.asarray(pinned(x)), out, rtol=1e-6)


def test_megastep_with_fusion_inside_body():
    mpx.set_fusion_mode("auto")
    comm = _world_comm()
    k = comm.Get_size()

    def step(pair):
        a, b = pair
        # the fusion idiom inside the loop body: issue both, then
        # consume — buckets must stay per-iteration
        ra = mpx.allreduce(a, op=mpx.SUM)[0]
        rb = mpx.allreduce(b, op=mpx.SUM)[0]
        return (mpx.varying(ra * (1.0 / k)), mpx.varying(rb * (1.0 / k)))

    a = jnp.arange(k * 4, dtype=jnp.float32).reshape(k, 4)
    b = jnp.full((k, 4), 3.0, jnp.float32)
    want = _n_eager_steps(step, (a, b), UNROLL, comm)
    pinned = mpx.compile(step, (a, b), comm=comm, unroll=UNROLL)
    got = np.asarray(pinned((a, b)))
    np.testing.assert_array_equal(want, got)


def test_megastep_with_start_wait_inside_body():
    comm = _world_comm()
    k = comm.Get_size()

    def step(v):
        h, _tok = mpx.allreduce_start(v, op=mpx.SUM)
        w = jnp.tanh(v)  # independent compute in the gap
        s, _tok = mpx.allreduce_wait(h)
        return mpx.varying(s * (1.0 / k) + w * 0.0)

    x = jnp.arange(k * 8, dtype=jnp.float32).reshape(k, 8) * 0.05
    want = _n_eager_steps(step, x, UNROLL, comm)
    pinned = mpx.compile(step, x, comm=comm, unroll=UNROLL)
    np.testing.assert_array_equal(want, np.asarray(pinned(x)))


def test_megastep_multi_arg_carry_and_statics():
    comm = _world_comm()
    k = comm.Get_size()

    @mpx.spmd(comm=comm, static_argnums=(1,), unroll=UNROLL)
    def mega(v, gain, w):
        s, _ = mpx.allreduce(v, op=mpx.SUM)
        return (mpx.varying(s * gain), mpx.varying(w + 1.0))

    @mpx.spmd(comm=comm, static_argnums=(1,))
    def single(v, gain, w):
        s, _ = mpx.allreduce(v, op=mpx.SUM)
        return (mpx.varying(s * gain), mpx.varying(w + 1.0))

    v = jnp.full((k, 4), 0.5, jnp.float32)
    w = jnp.zeros((k, 2), jnp.float32)
    cv, cw = v, w
    for _ in range(UNROLL):
        cv, cw = single(cv, 0.125, cw)
    gv, gw = mega(v, 0.125, w)
    np.testing.assert_array_equal(np.asarray(cv), np.asarray(gv))
    np.testing.assert_array_equal(np.asarray(cw), np.asarray(gw))


# ---------------------------------------------------------------------------
# invisibility at unroll=1
# ---------------------------------------------------------------------------


def test_unroll_one_hlo_byte_identical():
    comm = _world_comm()
    k = comm.Get_size()
    x = jnp.ones((k, 4), jnp.float32)

    from jax.sharding import PartitionSpec as P

    from mpi4jax_tpu.parallel.region import make_region_body

    def lower_text(**kw):
        body = make_region_body(_step_plain, comm, (), (), (), 1,
                                squeeze_in=True, squeeze_out=True, **kw)
        sm = jax.jit(jax.shard_map(
            body, mesh=comm.mesh, in_specs=P(comm.axes[0]),
            out_specs=P(comm.axes[0])))
        return sm.lower(x).as_text()

    assert lower_text(unroll=1) == lower_text()


def test_unroll_validation_and_kwarg_rejection():
    comm = _world_comm()
    k = comm.Get_size()
    x = jnp.ones((k, 4), jnp.float32)
    with pytest.raises(ValueError, match=">= 1"):
        mpx.spmd(_step_plain, comm=comm, unroll=0)(x)
    with pytest.raises(TypeError, match="positional"):
        mpx.spmd(lambda *, v: v, comm=comm, unroll=2)(v=x)
    with pytest.raises(ValueError, match="wrap=False|region"):
        mpx.compile(lambda v: v, x, comm=comm, wrap=False, unroll=2)


def test_megastep_carry_contract_error():
    comm = _world_comm()
    k = comm.Get_size()

    def shape_changer(v):
        s, _ = mpx.allreduce(v, op=mpx.SUM)
        return mpx.varying(s[..., :2])  # narrows the carry

    x = jnp.ones((k, 4), jnp.float32)
    with pytest.raises(ValueError, match="megastep carry contract"):
        mpx.spmd(shape_changer, comm=comm, unroll=2)(x)


def test_unroll_default_env_flag(monkeypatch):
    comm = _world_comm()
    k = comm.Get_size()
    x = jnp.full((k, 4), 1.0, jnp.float32)
    want = _n_eager_steps(_step_plain, x, 2, comm)
    monkeypatch.setenv("MPI4JAX_TPU_UNROLL_DEFAULT", "2")
    got = mpx.spmd(_step_plain, comm=comm)(x)  # default picks N=2
    np.testing.assert_array_equal(want, np.asarray(got))


def test_unroll_default_degrades_for_non_unrollable_shapes(monkeypatch):
    # a fleet-wide default must not break programs that cannot carry a
    # megastep loop — only an EXPLICIT unroll= errors on them
    comm = _world_comm()
    k = comm.Get_size()
    x = jnp.ones((k, 4), jnp.float32)
    monkeypatch.setenv("MPI4JAX_TPU_UNROLL_DEFAULT", "4")
    pinned = mpx.compile(lambda v: v + 1.0, x, comm=comm, wrap=False)
    assert pinned.unroll == 1
    np.testing.assert_array_equal(np.asarray(pinned(x)),
                                  np.asarray(x) + 1.0)


# ---------------------------------------------------------------------------
# MPX130 through analyze and env=error
# ---------------------------------------------------------------------------


def _straddling_step(v):
    # a start whose wait never appears in the iteration: the span
    # straddles the loop boundary by construction
    h, _tok = mpx.allreduce_start(v, op=mpx.SUM)
    return mpx.varying(v * 1.0)


def _paired_step(v):
    h, _tok = mpx.allreduce_start(v, op=mpx.SUM)
    s, _tok = mpx.allreduce_wait(h)
    return mpx.varying(s * 0.125)


def test_mpx130_through_analyze_positive_and_negative():
    comm = _world_comm()
    k = comm.Get_size()
    x = jnp.ones((k, 4), jnp.float32)

    bad = mpx.spmd(_straddling_step, comm=comm, unroll=UNROLL)
    report = mpx.analyze(bad, x)
    assert any(f.code == "MPX130" for f in report.findings), report.render()

    good = mpx.spmd(_paired_step, comm=comm, unroll=UNROLL)
    report = mpx.analyze(good, x)
    assert not any(f.code == "MPX130" for f in report.findings), \
        report.render()
    # the same span outside a megastep is MPX112 territory, never MPX130
    report = mpx.analyze(_straddling_step, x, comm=comm)
    assert not any(f.code == "MPX130" for f in report.findings)


def test_mpx130_env_error_fires_at_trace():
    comm = _world_comm()
    k = comm.Get_size()
    x = jnp.ones((k, 4), jnp.float32)
    mpx.set_analyze_mode("error")
    try:
        with pytest.raises(mpx.AnalysisError, match="MPX130"):
            mpx.spmd(_straddling_step, comm=comm, unroll=UNROLL)(x)
        # negative: the paired span traces clean under the error mode
        out = mpx.spmd(_paired_step, comm=comm, unroll=UNROLL)(x)
        assert np.asarray(out).shape == (k, 4)
    finally:
        mpx.set_analyze_mode(None)


# ---------------------------------------------------------------------------
# the C++ fast-path dispatch
# ---------------------------------------------------------------------------


def test_fast_path_fallback_on_missing_jaxlib_support(monkeypatch):
    from mpi4jax_tpu.aot import fastpath

    # simulate a jaxlib without create_cpp_call: every pin must fall
    # back to the Python Compiled call and still execute correctly
    monkeypatch.setattr(fastpath, "cpp_call_for", lambda c: (c, False))
    comm = _world_comm()
    k = comm.Get_size()
    x = jnp.ones((k, 4), jnp.float32)
    pinned = mpx.compile(_step_plain, x, comm=comm)
    assert pinned.fast_path is False
    out = np.asarray(pinned(x))
    np.testing.assert_allclose(out, np.full((k, 4), k * 0.25 + 0.5),
                               rtol=1e-6)
    assert mpx.cache_stats()["aot"]["fast_path_pins"] == 0


def test_fast_path_flag_off_and_no_staleness(monkeypatch):
    comm = _world_comm()
    k = comm.Get_size()
    x = jnp.ones((k, 4), jnp.float32)
    pinned = mpx.compile(_step_plain, x, comm=comm)
    want = np.asarray(pinned(x))
    # flipping the dispatch-only flag must NOT stale the live pin
    monkeypatch.setenv("MPI4JAX_TPU_CPP_DISPATCH", "false")
    assert not pinned.is_stale()
    np.testing.assert_array_equal(want, np.asarray(pinned(x)))
    # and new pins under the off flag take the Python path
    fresh = mpx.compile(_step_plain, x, comm=comm)
    assert fresh.fast_path is False
    np.testing.assert_array_equal(want, np.asarray(fresh(x)))


def test_fast_path_result_matches_python_path(monkeypatch):
    comm = _world_comm()
    k = comm.Get_size()
    x = jnp.arange(k * 4, dtype=jnp.float32).reshape(k, 4)
    fast = mpx.compile(_step_plain, x, comm=comm)
    monkeypatch.setenv("MPI4JAX_TPU_CPP_DISPATCH", "false")
    slow = mpx.compile(_step_plain, x, comm=comm)
    np.testing.assert_array_equal(np.asarray(fast(x)), np.asarray(slow(x)))


# ---------------------------------------------------------------------------
# watchdog: whole-megastep bracket, deadline scaled by N
# ---------------------------------------------------------------------------


def test_watchdog_brackets_megastep_with_scaled_deadline(monkeypatch):
    from mpi4jax_tpu.resilience import watchdog

    armed = []
    real_arm = watchdog.arm_in_graph

    def spy(mpi_name, call_id, comm, rank, timeout):
        armed.append((mpi_name, timeout))
        return real_arm(mpi_name, call_id, comm, rank, timeout)

    monkeypatch.setattr(watchdog, "arm_in_graph", spy)
    mpx.set_watchdog_timeout(5.0)
    try:
        comm = _world_comm()
        k = comm.Get_size()
        x = jnp.ones((k, 4), jnp.float32)
        pinned = mpx.compile(_step_plain, x, comm=comm, unroll=UNROLL)
        jax.block_until_ready(pinned(x))
    finally:
        resilience_runtime.reset_overrides()
    mega = [(n, t) for n, t in armed if n.startswith("MPI_Megastep")]
    assert len(mega) == 1, armed
    assert mega[0][1] == pytest.approx(5.0 * UNROLL)
    # per-op arms inside the loop keep the per-collective deadline
    assert any(t == pytest.approx(5.0) for n, t in armed
               if not n.startswith("MPI_Megastep")), armed


# ---------------------------------------------------------------------------
# telemetry: one bracket per megastep + the per-step estimate
# ---------------------------------------------------------------------------


def test_events_tier_megastep_bracket_and_estimate():
    mpx.set_telemetry_mode("events")
    try:
        comm = _world_comm()
        k = comm.Get_size()
        x = jnp.ones((k, 4), jnp.float32)
        pinned = mpx.compile(_step_plain, x, comm=comm, unroll=UNROLL)
        jax.block_until_ready(pinned(x))
        mpx.flush()
        snap = mpx.telemetry.snapshot(include_events=True)
        mega = [e for e in snap["events"]
                if e.get("op") == "megastep" and e.get("type") == "op"]
        assert mega, snap["events"][:5]
        assert all(e["unroll"] == UNROLL for e in mega)
        # one bracket per rank per megastep execution — not one per step
        per_rank = {}
        for e in mega:
            per_rank[e["rank"]] = per_rank.get(e["rank"], 0) + 1
        assert set(per_rank.values()) == {1}, per_rank
        from mpi4jax_tpu.telemetry.core import op_key

        step_key = op_key("megastep_step", str(comm.uid), "estimate", "")
        hist = snap["ops"][step_key]["latency"]
        assert hist["count"] >= 1
    finally:
        mpx.set_telemetry_mode(None)


def test_counters_tier_adds_no_bracket_callbacks():
    # counters mode must not change the megastep HLO (no io_callbacks)
    comm = _world_comm()
    k = comm.Get_size()
    x = jnp.ones((k, 4), jnp.float32)

    from jax.sharding import PartitionSpec as P

    from mpi4jax_tpu.parallel.region import make_region_body

    def lower_text():
        body = make_region_body(_step_plain, comm, (), (), (), 1,
                                squeeze_in=True, squeeze_out=True,
                                unroll=UNROLL)
        sm = jax.jit(jax.shard_map(
            body, mesh=comm.mesh, in_specs=P(comm.axes[0]),
            out_specs=P(comm.axes[0])))
        return sm.lower(x).as_text()

    base = lower_text()
    mpx.set_telemetry_mode("counters")
    try:
        assert lower_text() == base
    finally:
        mpx.set_telemetry_mode(None)


# ---------------------------------------------------------------------------
# the elastic megastep drill: 8 -> 7 mid-megastep, resume from commit
# ---------------------------------------------------------------------------


@pytest.mark.faults
def test_elastic_run_megastep_shrink_drill():
    """The acceptance drill at megastep granularity: a pinned megastep
    step function (unroll=2) survives an 8 -> 7 shrink — the loop
    advances by 2 per call, commit_every aligns up to the megastep
    boundary, the failure mid-run resumes from the last commit, and the
    budget completes on 7 ranks with a second pin on record."""
    steps, fail_at, unroll = 8, 4, 2
    comm = _world_comm()
    store = mpx.ShardStore(comm)
    worlds = []

    def base(state, step_scalar, comm):
        g, _ = mpx.allreduce(state["p"] * 0.01, op=mpx.SUM, comm=comm)
        return {"p": mpx.varying(state["p"] - g / comm.uniform_size())}

    class Drill:
        def __init__(self):
            self.inner = mpx.aot.compile_step(base, unroll=unroll)
            self.unroll = self.inner.unroll

        def __call__(self, state, step, comm):
            worlds.append((step, comm.Get_size()))
            if step == fail_at and comm.epoch == 0:
                raise mpx.RankFailure({3}, "simulated")
            return self.inner(state, step, comm)

        def repin(self):
            self.inner.repin()
            return self

    p0 = np.full((3, 2), 1.0, np.float32)
    final = mpx.elastic.run(Drill(), {"p": p0}, store, steps=steps,
                            commit_every=1)  # aligns up to 2 internally

    assert el.current_epoch() == 1
    assert store.comm.Get_size() == 7
    # megastep granularity: only even step boundaries were dispatched,
    # and the post-shrink world finished the budget from the last commit
    assert all(s % unroll == 0 for s, _ in worlds), worlds
    assert sorted({s for s, w in worlds if w == 7}) == list(
        range(fail_at, steps, unroll)), worlds
    stats = mpx.cache_stats()["aot"]
    assert stats["pins"] >= 2, stats
    assert stats["stale_raises"] >= 1, stats
    assert np.asarray(final["p"]).shape == (3, 2)


def test_elastic_megastep_equals_single_steps():
    comm = _world_comm()

    def base(state, step_scalar, comm):
        s, _ = mpx.allreduce(state["v"], op=mpx.SUM, comm=comm)
        return {"v": mpx.varying(s / comm.uniform_size() + 0.25)}

    single = mpx.aot.compile_step(base)
    mega = mpx.aot.compile_step(base, unroll=3)
    assert mega.unroll == 3

    s0 = {"v": np.full((4,), 1.0, np.float32)}
    want = s0
    for i in range(3):
        want = single(want, i, comm)
    got = mega(s0, 0, comm)
    np.testing.assert_allclose(np.asarray(got["v"]), np.asarray(want["v"]),
                               rtol=1e-6)


def test_elastic_run_budget_must_align():
    comm = _world_comm()
    store = mpx.ShardStore(comm)

    def base(state, step_scalar, comm):
        return state

    step = mpx.aot.compile_step(base, unroll=3)
    with pytest.raises(ValueError, match="multiple of"):
        mpx.elastic.run(step, {"v": np.ones((2,), np.float32)}, store,
                        steps=8)


# ---------------------------------------------------------------------------
# the cache-warming CLI, end to end
# ---------------------------------------------------------------------------


def test_warm_cli_populates_cache(monkeypatch, tmp_path):
    from mpi4jax_tpu.aot import serialization
    from mpi4jax_tpu.aot.warm import EXIT_OK, warm_from_manifest

    if not serialization.supported():
        pytest.skip("this jax cannot serialize compiled executables")

    target = tmp_path / "warmtarget.py"
    target.write_text(textwrap.dedent("""
        import mpi4jax_tpu as mpx

        def step(v):
            s, _ = mpx.allreduce(v, op=mpx.SUM)
            return mpx.varying(s * 0.125)
    """))
    monkeypatch.syspath_prepend(str(tmp_path))
    monkeypatch.setenv("MPI4JAX_TPU_COMPILE_CACHE_DIR",
                       str(tmp_path / "cache"))

    comm = _world_comm()
    k = comm.Get_size()
    manifest = tmp_path / "manifest.json"
    manifest.write_text(json.dumps({"programs": [{
        "fn": "warmtarget:step",
        "args": [{"shape": [k, 16], "dtype": "float32"}],
        "unroll": 4,
    }]}))

    code, payload = warm_from_manifest(str(manifest), comm=comm)
    assert code == EXIT_OK, payload
    assert payload["warmed"] == 1 and payload["failed"] == 0
    assert payload["programs"][0]["unroll"] == 4
    stats = mpx.cache_stats()
    assert stats["aot"]["warmed"] == 1
    assert stats["disk_cache"]["writes"] >= 1

    # the warmed artifact serves the real pin: zero re-lowers
    mpx.clear_caches()
    import warmtarget

    x = jnp.ones((k, 16), jnp.float32)
    pinned = mpx.compile(warmtarget.step, x, comm=comm, unroll=4)
    assert pinned.from_disk, "warmed program was not served from disk"
    assert mpx.cache_stats()["disk_cache"]["misses"] == 0
    out = np.asarray(pinned(x))
    assert out.shape == (k, 16)


def test_warm_cli_main_exit_codes(monkeypatch, tmp_path, capsys):
    from mpi4jax_tpu.aot.__main__ import main

    monkeypatch.delenv("MPI4JAX_TPU_COMPILE_CACHE_DIR", raising=False)
    code = main(["warm", str(tmp_path / "nope.json"), "--json"])
    assert code == 2
    payload = json.loads(capsys.readouterr().out.strip())
    assert "COMPILE_CACHE_DIR" in payload["error"]
