"""Traced half of the serving-runtime suite (docs/serving.md): real
programs on the 8-device virtual CPU mesh.

- pinned-per-bucket == un-bucketed reference bit-identity: the engine's
  ``mpx.compile`` prefill/decode programs produce bitwise the outputs of
  plain ``mpx.spmd`` runs of the same step functions, and a decode
  MEGASTEP equals ``unroll`` sequential single steps;
- scheduling invariance: greedy decode tokens depend only on the
  request (lanes are independent), so continuous vs static vs any
  unroll produce identical token streams;
- one program per (bucket, phase): live batches sharing a bucket share
  one pinned program;
- megastep-boundary admission under the deterministic virtual clock;
- MPX136 positive/negative through ``mpx.analyze`` AND the ambient
  error mode (gated on a declared bucket table);
- the serving telemetry surface (per-phase op rows + the report
  section);
- the drain drill (slow): a preemption notice at a megastep boundary
  row-shrinks a (2, 4) world to 4 ranks mid-traffic with zero failed
  requests, in-flight sequences re-admitted from committed history;
- warm-manifest round trip (slow): ``aot warm`` over the emitted
  serving manifest, then a serving run with ``disk_cache.misses == 0``.

The pure half (bucket table, scheduler, allocator, SLO math, manifest
schema, MPX136 checker, cost-model replay) runs under any JAX in
tests/test_serving_pure.py via the isolated loader.
"""

import json
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import mpi4jax_tpu as mpx
from mpi4jax_tpu.resilience import elastic as el
from mpi4jax_tpu.serving import (
    ServingConfig,
    ServingEngine,
    clear_declared_buckets,
    declare_buckets,
    poisson_trace,
    warm_manifest,
)
from mpi4jax_tpu.serving import model as smodel


@pytest.fixture(autouse=True)
def _clean_state():
    el._reset_epoch_for_tests()
    mpx.set_default_mesh(None)
    mpx.clear_caches()
    clear_declared_buckets()
    yield
    mpx.set_telemetry_mode(None)
    mpx.set_analyze_mode(None)
    el._reset_epoch_for_tests()
    mpx.set_default_mesh(None)
    mpx.clear_caches()
    clear_declared_buckets()
    from mpi4jax_tpu.parallel import region as _region

    _region._default_comm = None
    from mpi4jax_tpu.telemetry import core as _tcore

    _tcore.reset()


def _world_comm():
    mesh = mpx.make_world_mesh()
    return mpx.Comm(mesh.axis_names[0], mesh=mesh)


def _tiny_cfg(**overrides):
    base = dict(vocab=32, heads=8, head_dim=2, ffn=32, max_len=32,
                max_prompt=8, max_batch=4, kv_slots=8, unroll=2,
                slo_p99_ms=60_000.0, clock="virtual", seed=11)
    base.update(overrides)
    return ServingConfig(**base)


def _tiny_trace(n=6, rate=300.0, seed=5):
    return poisson_trace(n, rate, seed=seed, prompt_len=(2, 4),
                         max_new=(2, 6), long_frac=0.0, vocab=32)


def _trees_equal(a, b):
    la, lb = jax.tree.leaves(a), jax.tree.leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


# ---------------------------------------------------------------------------
# end-to-end serving
# ---------------------------------------------------------------------------


def test_engine_serves_trace_continuous():
    comm = _world_comm()
    engine = ServingEngine(_tiny_cfg(), comm)
    trace = _tiny_trace()
    out = engine.run(trace, scheduler="continuous")
    assert out["completed"] == len(trace)
    assert out["failed"] == 0
    assert out["tokens"] == sum(r.max_new_tokens for r in trace)
    assert out["p99_ms"] is not None and out["slo_met"]
    assert out["world"] == comm.Get_size()
    assert any(p.startswith("decode.b") for p in out["programs"])
    assert any(p.startswith("prefill.b") for p in out["programs"])


def test_engine_static_baseline_completes():
    comm = _world_comm()
    engine = ServingEngine(_tiny_cfg(), comm)
    trace = _tiny_trace()
    out = engine.run(trace, scheduler="static")
    assert out["completed"] == len(trace) and out["failed"] == 0


def test_tokens_invariant_under_scheduling():
    """Lanes are independent (attention reads only the lane's own KV
    slot), so the greedy token stream of a request is a pure function of
    the request — identical under continuous/static scheduling and any
    megastep unroll."""
    comm = _world_comm()
    trace = _tiny_trace(n=5)

    def tokens_for(cfg, sched):
        engine = ServingEngine(cfg, comm)
        engine.run(trace, scheduler=sched)
        return {s.rid: tuple(s.generated)
                for s in engine._sched.finished}

    base = tokens_for(_tiny_cfg(unroll=1), "continuous")
    assert tokens_for(_tiny_cfg(unroll=2), "continuous") == base
    assert tokens_for(_tiny_cfg(unroll=2), "static") == base


def test_one_program_per_bucket():
    """Live batches 3 and 4 share bucket 4: ONE pinned decode program
    serves both compositions (the padded-bucket one-key rule)."""
    from mpi4jax_tpu.serving import Request

    comm = _world_comm()
    engine = ServingEngine(_tiny_cfg(unroll=1), comm)
    # 4 requests at t=0; one finishes after 2 tokens (live batch drops
    # to 3, still bucket 4), the rest together after 4 — the decode
    # bucket is 4 throughout
    budgets = [2, 4, 4, 4]
    trace = [Request(rid=i, arrival_s=0.0, prompt=(1, 2),
                     max_new_tokens=b) for i, b in enumerate(budgets)]
    from mpi4jax_tpu.aot import pinning

    pinning.reset_stats()
    out = engine.run(trace, scheduler="continuous")
    assert out["failed"] == 0
    decode_programs = [p for p in out["programs"]
                       if p.startswith("decode.")]
    assert decode_programs == ["decode.b4"]
    # exactly one pin per program the engine reports
    assert pinning.stats()["pins"] == len(out["programs"])


# ---------------------------------------------------------------------------
# bit-identity vs the un-bucketed reference
# ---------------------------------------------------------------------------


def _manual_args(engine, cfg, comm, n_live=2):
    """Hand-built lane arrays for ``n_live`` sequences in bucket
    ``bucket_for(n_live)`` with freshly allocated slots."""
    k = comm.Get_size()
    bucket = engine.table.bucket_for(n_live)
    rng = np.random.default_rng(3)
    plens = [3, 2][:n_live]
    prompts = np.zeros((bucket, cfg.max_prompt), np.int32)
    for i, pl in enumerate(plens):
        prompts[i, :pl] = rng.integers(1, cfg.vocab, pl)
    prompts_g = engine._prep(np.tile(prompts[None], (k, 1, 1)))
    plens_g = engine._prep(np.tile(np.asarray(
        plens + [1] * (bucket - n_live), np.int32)[None], (k, 1)))
    slots_g = engine._prep(np.tile(np.asarray(
        list(range(n_live)) + [cfg.slots()] * (bucket - n_live),
        np.int32)[None], (k, 1)))
    return bucket, prompts_g, plens_g, slots_g


def test_pinned_prefill_matches_spmd_reference():
    comm = _world_comm()
    cfg = _tiny_cfg()
    engine = ServingEngine(cfg, comm)
    bucket, prompts_g, plens_g, slots_g = _manual_args(engine, cfg, comm)
    args = engine._state + (prompts_g, plens_g, slots_g)
    pinned = engine._program("prefill", bucket)(*args)
    ref = mpx.spmd(smodel.prefill_step, comm=comm)(*args)
    _trees_equal(pinned, ref)


def test_decode_megastep_matches_stepwise_reference():
    """One pinned decode megastep (unroll=N) == N sequential un-bucketed
    single-step spmd calls, bit for bit."""
    comm = _world_comm()
    cfg = _tiny_cfg(unroll=2)
    engine = ServingEngine(cfg, comm)
    bucket, prompts_g, plens_g, slots_g = _manual_args(engine, cfg, comm)
    kk, vv, tok, first = mpx.spmd(smodel.prefill_step, comm=comm)(
        *(engine._state + (prompts_g, plens_g, slots_g)))
    state = engine._state[:5] + (kk, vv, tok)
    lens_g = plens_g  # after prefill: lens == plen, last token at col plen
    dec_args = state + (first, lens_g, slots_g)

    meg = engine._program("decode", bucket)(*dec_args)

    ref_step = mpx.spmd(smodel.decode_step, comm=comm, unroll=1)
    cur = dec_args
    for _ in range(cfg.unroll):
        cur = ref_step(*cur)
    _trees_equal(meg, tuple(cur))


# ---------------------------------------------------------------------------
# megastep-boundary admission (virtual clock)
# ---------------------------------------------------------------------------


def test_admission_lands_on_megastep_boundaries():
    comm = _world_comm()
    cfg = _tiny_cfg(unroll=2, tick_s=0.01)
    engine = ServingEngine(cfg, comm)
    # one request up front, one arriving strictly BETWEEN boundary
    # instants: it must be admitted at the next boundary tick, never
    # mid-megastep
    trace = _tiny_trace(n=1, rate=1e6)
    late = poisson_trace(1, 1e6, seed=9, prompt_len=(2, 3),
                         max_new=(2, 4), vocab=32)[0]
    late = type(late)(rid=99, arrival_s=0.015, prompt=late.prompt,
                      max_new_tokens=late.max_new_tokens)
    out = engine.run(trace + [late], scheduler="continuous")
    assert out["failed"] == 0 and out["completed"] == 2
    tick = cfg.tick_s
    for s in engine._sched.finished:
        # admission instants are boundary instants
        ratio = s.admitted_s / tick
        assert abs(ratio - round(ratio)) < 1e-9, s.admitted_s
    late_seq = next(s for s in engine._sched.finished if s.rid == 99)
    assert late_seq.admitted_s >= 0.02  # the boundary AFTER arrival


# ---------------------------------------------------------------------------
# MPX136 through analyze and the ambient error mode
# ---------------------------------------------------------------------------


def _unbucketed_fn(comm):
    def fn(x):  # per-rank payload (5, 16): 5 is not a bucket
        s, _ = mpx.allreduce(x, op=mpx.SUM, comm=comm)
        return mpx.varying(s)

    return fn


def test_mpx136_via_analyze():
    comm = _world_comm()
    k = comm.Get_size()
    declare_buckets((1, 2, 4, 8))
    x = jnp.ones((k, 5, 16), jnp.float32)
    report = mpx.analyze(_unbucketed_fn(comm), x, comm=comm)
    assert any(f.code == "MPX136" for f in report.findings), report
    # in-bucket shape: clean
    x4 = jnp.ones((k, 4, 16), jnp.float32)
    report = mpx.analyze(_unbucketed_fn(comm), x4, comm=comm)
    assert not any(f.code == "MPX136" for f in report.findings), report


def test_mpx136_requires_declared_table():
    comm = _world_comm()
    k = comm.Get_size()
    x = jnp.ones((k, 5, 16), jnp.float32)
    report = mpx.analyze(_unbucketed_fn(comm), x, comm=comm)
    assert not any(f.code == "MPX136" for f in report.findings), report


def test_mpx136_ambient_error_mode():
    comm = _world_comm()
    k = comm.Get_size()
    declare_buckets((1, 2, 4, 8))
    mpx.set_analyze_mode("error")
    x = jnp.ones((k, 5, 16), jnp.float32)
    with pytest.raises(mpx.AnalysisError, match="MPX136"):
        mpx.run(_unbucketed_fn(comm), x, comm=comm)
    mpx.set_analyze_mode(None)


# ---------------------------------------------------------------------------
# telemetry: per-phase rows + the serving report section
# ---------------------------------------------------------------------------


def test_serving_phase_telemetry_and_report_section():
    comm = _world_comm()
    mpx.set_telemetry_mode("events")
    engine = ServingEngine(_tiny_cfg(), comm)
    out = engine.run(_tiny_trace(), scheduler="continuous")
    assert out["failed"] == 0
    from mpi4jax_tpu.telemetry import core as tcore
    from mpi4jax_tpu.telemetry import journal
    from mpi4jax_tpu.telemetry import report as treport

    snap = tcore.snapshot(include_events=True)
    phase_ops = {row["op"] for row in snap["ops"].values()}
    assert "serving.prefill" in phase_ops
    assert "serving.decode" in phase_ops
    # journal brackets per dispatch, with bucket + unroll meta
    recs = [r for r in journal.snapshot_events()
            if r.get("op") == "serving.decode"]
    assert recs and all(r["unroll"] == 2 for r in recs)
    assert all("latency" in r for r in recs)
    text = treport.render([snap])
    assert "serving:" in text
    assert "requests completed" in text
    assert "serving.decode" in text
    meters = snap["meters"]
    assert meters["serving.megasteps"] >= 1
    assert meters["serving.requests_completed"] == 6


# ---------------------------------------------------------------------------
# the drain drill (single-controller): preemption at a boundary
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_drain_drill_row_shrink(monkeypatch):
    """A preemption notice lands at megastep boundary 2: the (2, 4)
    world row-shrinks to 4 ranks between megasteps, survivors re-shard
    the committed parameters, re-admit every in-flight sequence from its
    committed history, and the trace finishes with zero failures —
    exactly one drain incident journalled."""
    monkeypatch.setenv("MPI4JAX_TPU_ELASTIC_FAIL_UNIT", "row")
    mpx.set_telemetry_mode("events")
    mesh = mpx.make_world_mesh((2, 4), ("y", "x"))
    comm = mpx.Comm(("y", "x"), mesh=mesh)
    store = mpx.ShardStore(comm)
    cfg = _tiny_cfg()
    engine = ServingEngine(cfg, comm, store=store)
    trace = _tiny_trace(n=10, rate=400.0)

    from mpi4jax_tpu.parallel import megastep

    def notice(step, **info):
        if step == 2 and el.current_epoch() == 0:
            mpx.request_drain(rank=7)

    unregister = megastep.register_boundary_hook("test-preempt", notice)
    try:
        out = engine.run(trace, scheduler="continuous")
    finally:
        unregister()

    assert out["failed"] == 0
    assert out["completed"] == len(trace)
    assert out["world"] == 4
    assert out["preempt_readmissions"] > 0
    assert el.current_epoch() == 1
    from mpi4jax_tpu.telemetry import journal

    drains = [r for r in journal.snapshot_events()
              if r.get("type") == "instant" and r.get("name") == "drain"]
    assert len(drains) == 1, drains
    # replay programs were pinned for the re-admission
    assert any(p.startswith("replay.") for p in out["programs"])


# ---------------------------------------------------------------------------
# warm manifest -> zero-miss serving run (slow)
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_warm_manifest_then_zero_miss_serving(tmp_path, monkeypatch):
    from mpi4jax_tpu.aot import pinning, warm

    cfg = _tiny_cfg()
    manifest = warm_manifest(cfg, jax.device_count())
    path = tmp_path / "serving-manifest.json"
    path.write_text(json.dumps(manifest))
    cache_dir = tmp_path / "cache"
    monkeypatch.setenv("MPI4JAX_TPU_COMPILE_CACHE_DIR",
                       str(cache_dir))

    code, payload = warm.warm_from_manifest(str(path))
    assert code == 0, payload
    assert payload["warmed"] == len(manifest["programs"])
    assert os.path.isdir(cache_dir)

    # a fresh serving run over the warmed cache: every pin deserializes.
    # The engine serves over the same DEFAULT world comm the warm used,
    # so the mesh descriptor — and with it every persistent key — match.
    mpx.clear_caches()
    pinning.reset_stats()
    from mpi4jax_tpu.aot import diskcache

    diskcache.reset_stats()
    engine = ServingEngine(cfg)
    out = engine.run(_tiny_trace(), scheduler="continuous")
    assert out["failed"] == 0
    stats = mpx.cache_stats()
    assert stats["disk_cache"]["misses"] == 0, stats
    assert stats["disk_cache"]["hits"] >= len(out["programs"]), stats
    assert stats["aot"]["compiles"] == 0, stats
