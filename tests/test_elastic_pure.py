"""Pure half of the elastic-recovery suite (docs/resilience.md
"Elastic recovery").

Everything here runs WITHOUT importing mpi4jax_tpu (the isolated loader
below, mirroring tests/test_resilience.py), so the protocol core is
verified under any JAX version:

- epoch arithmetic + the resilience cache token carrying it;
- shard ownership, k-redundant neighbor-replication placement, and the
  reconstruction plan (including the unrecoverable-loss error);
- rank compaction and color-split group shrink;
- failure agreement: the gossip fixpoint on simulated link matrices
  (agreement within a connected component, suspicion of unreachable
  peers, split-brain majority arbitration) and the TCP runtime form on
  localhost;
- ShardStore commit/reassemble simulated with per-rank stores — kill any
  `redundancy` ranks and the state returns bit-identical;
- failure classification (explicit, watchdog-claimed, death-rattle);
- the `hang` fault verb (parser + probe semantics);
- pluggable watchdog `on_timeout` + registry drain;
- `retry_with_backoff(max_attempts=...)` and the bootstrap flags;
- `elastic.run`'s control flow against a scripted fake store.

The traced half (epoch→retrace cache pin, HLO identity with elastic off,
the 8-device shrink) is tests/test_elastic.py, which needs jax >= the
package floor.
"""

import importlib
import os
import pathlib
import sys
import threading
import time
import types

import numpy as np
import pytest

REPO = pathlib.Path(__file__).resolve().parent.parent
PKG = REPO / "mpi4jax_tpu"

_ISO_NAME = "_mpx_elastic_iso"


def _load_isolated():
    """Load the pure-Python elastic stack under a private package name
    (bypasses mpi4jax_tpu/__init__.py and its JAX floor; state isolated
    from any real import in the same process)."""
    if _ISO_NAME in sys.modules:
        return sys.modules[_ISO_NAME]
    root = types.ModuleType(_ISO_NAME)
    root.__path__ = [str(PKG)]
    sys.modules[_ISO_NAME] = root
    for sub in ("utils", "resilience"):
        m = types.ModuleType(f"{_ISO_NAME}.{sub}")
        m.__path__ = [str(PKG / sub)]
        sys.modules[f"{_ISO_NAME}.{sub}"] = m
        setattr(root, sub, m)
    for mod in (
        "utils.config",
        "resilience.faultinject",
        "resilience.retry",
        "resilience.watchdog",
        "resilience.elastic",
        "resilience.runtime",
    ):
        importlib.import_module(f"{_ISO_NAME}.{mod}")
    return root


ISO = _load_isolated()
el = ISO.resilience.elastic
fi = ISO.resilience.faultinject
wd = ISO.resilience.watchdog
rt = ISO.resilience.runtime
retry_mod = ISO.resilience.retry
config = ISO.utils.config


@pytest.fixture(autouse=True)
def _clean_state():
    el._reset_epoch_for_tests()
    el.take_pending_failure()
    wd.set_on_timeout(None)
    wd.drain_registry()
    fi.reset_fault_state()
    saved = {
        k: os.environ.pop(k, None)
        for k in (
            "MPI4JAX_TPU_BOOTSTRAP_DEADLINE",
            "MPI4JAX_TPU_BOOTSTRAP_MAX_ATTEMPTS",
            "MPI4JAX_TPU_ELASTIC_REDUNDANCY",
        )
    }
    yield
    el._reset_epoch_for_tests()
    el.take_pending_failure()
    wd.set_on_timeout(None)
    wd.drain_registry()
    for k, v in saved.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v


# ---------------------------------------------------------------------------
# epoch arithmetic
# ---------------------------------------------------------------------------


def test_epoch_starts_at_zero_and_advances_monotonically():
    assert el.current_epoch() == 0
    assert el.elastic_cache_token() == 0
    assert el.advance_epoch() == 1
    assert el.advance_epoch() == 2
    assert el.current_epoch() == 2
    assert el.elastic_cache_token() == 2


def test_advance_epoch_bumps_config_epoch():
    """Every stamp-memoized configuration consumer must invalidate on a
    revocation — that is how the epoch reaches the program-cache keys."""
    before = config.config_epoch()
    el.advance_epoch()
    assert config.config_epoch() > before


def test_resilience_cache_token_carries_the_epoch():
    base = rt.cache_token()
    assert base[-1] == 0
    el.advance_epoch()
    bumped = rt.cache_token()
    assert bumped != base
    assert bumped[-1] == 1
    # everything else in the token is untouched by a revocation
    assert bumped[:-1] == base[:-1]


# ---------------------------------------------------------------------------
# shard ownership + replication placement
# ---------------------------------------------------------------------------


def test_shard_bounds_equal_chunks_with_padding():
    assert el.shard_bounds(0, 4) == (0, 0)
    assert el.shard_bounds(100, 4) == (25, 100)
    assert el.shard_bounds(101, 4) == (26, 104)   # ceil + pad
    assert el.shard_bounds(3, 8) == (1, 8)
    with pytest.raises(ValueError, match="at least one rank"):
        el.shard_bounds(10, 0)


def test_replica_ranks_neighbor_placement():
    assert el.replica_ranks(0, 8, 1) == (0, 1)
    assert el.replica_ranks(7, 8, 1) == (7, 0)    # wraps
    assert el.replica_ranks(2, 8, 2) == (2, 3, 4)
    assert el.replica_ranks(5, 8, 0) == (5,)      # no redundancy: owner only
    # more copies than ranks degenerates to "everyone"
    assert el.replica_ranks(1, 3, 7) == (1, 2, 0)
    with pytest.raises(ValueError, match="out of range"):
        el.replica_ranks(8, 8, 1)
    with pytest.raises(ValueError, match="redundancy"):
        el.replica_ranks(0, 8, -1)


def test_shards_held_by_is_the_inverse_of_replica_ranks():
    for k in (1, 2, 3, 5, 8):
        for red in (0, 1, 2, k - 1):
            for r in range(k):
                held = el.shards_held_by(r, k, red)
                assert len(held) == min(red, k - 1) + 1
                for s in held:
                    assert r in el.replica_ranks(s, k, red)
            # every shard has exactly redundancy+1 holders
            counts = {s: 0 for s in range(k)}
            for r in range(k):
                for s in el.shards_held_by(r, k, red):
                    counts[s] += 1
            assert set(counts.values()) == {min(red, k - 1) + 1}


def test_recoverable_tolerates_exactly_the_redundancy_budget():
    # any single failure is recoverable at redundancy 1
    for r in range(8):
        assert el.recoverable({r}, 8, 1)
    # two ADJACENT failures kill a whole replica set at redundancy 1
    assert not el.recoverable({3, 4}, 8, 1)      # shard 3's copies: ranks 3,4
    # two non-adjacent failures are fine
    assert el.recoverable({1, 5}, 8, 1)
    # redundancy 2 tolerates any 2 failures
    for a in range(8):
        for b in range(8):
            if a != b:
                assert el.recoverable({a, b}, 8, 2)


def test_reconstruction_plan_names_lowest_surviving_holder():
    plan = el.reconstruction_plan({3}, 8, 1)
    assert set(plan) == set(range(8))
    assert plan[3] == 4          # shard 3's owner died; right neighbor holds it
    assert plan[2] == 2          # untouched shards use their owner
    for s, provider in plan.items():
        assert provider != 3
        assert provider in el.replica_ranks(s, 8, 1)
    with pytest.raises(el.RankFailure, match="unrecoverable"):
        el.reconstruction_plan({3, 4}, 8, 1)


# ---------------------------------------------------------------------------
# rank compaction + group shrink
# ---------------------------------------------------------------------------


def test_compact_rank_map_renumbers_ascending():
    assert el.compact_rank_map(4, {3}) == {0: 0, 1: 1, 2: 2}
    assert el.compact_rank_map(4, {0}) == {1: 0, 2: 1, 3: 2}
    assert el.compact_rank_map(8, {2, 5}) == {
        0: 0, 1: 1, 3: 2, 4: 3, 6: 4, 7: 5,
    }
    with pytest.raises(ValueError, match="out of range"):
        el.compact_rank_map(4, {4})
    with pytest.raises(el.RankFailure, match="no survivors"):
        el.compact_rank_map(2, {0, 1})


def test_shrink_groups_drops_dead_and_preserves_order():
    groups = ((0, 2, 4, 6), (1, 3, 5, 7))
    assert el.shrink_groups(groups, {3}, 8) == ((0, 2, 3, 5), (1, 4, 6))
    # a group losing every member disappears
    assert el.shrink_groups(((0, 1), (2, 3)), {2, 3}, 4) == ((0, 1),)
    # key-ordered (non-ascending) member order survives the renumbering
    assert el.shrink_groups(((2, 0, 1),), {1}, 3) == ((1, 0),)


# ---------------------------------------------------------------------------
# failure agreement
# ---------------------------------------------------------------------------


def _links(world, down=(), cut=()):
    """Full link matrix minus every link touching ``down`` ranks and the
    explicit ``cut`` pairs."""
    m = [[i != j for j in range(world)] for i in range(world)]
    for r in down:
        for j in range(world):
            m[r][j] = m[j][r] = False
    for a, b in cut:
        m[a][b] = m[b][a] = False
    return m


def test_gossip_agreement_converges_on_the_union():
    # ranks 0 and 1 each suspect a different dead rank; everyone agrees
    # on the union, and the dead are suspected by everyone via dead links
    agreed = el.gossip_agreement(
        {0: {6}, 1: {7}}, _links(8, down=(6, 7)))
    for r in range(6):
        assert agreed[r] == frozenset({6, 7}), r


def test_gossip_agreement_suspects_unreachable_peers_without_hints():
    # nobody *observed* anything, but rank 5's links are all down
    agreed = el.gossip_agreement({}, _links(8, down=(5,)))
    for r in range(8):
        if r != 5:
            assert agreed[r] == frozenset({5})


def test_gossip_agreement_partition_disagrees_and_majority_arbitrates():
    # cut the world into {0,1,2,3,4} and {5,6,7}: each side suspects the
    # other wholesale
    cut = [(a, b) for a in range(5) for b in range(5, 8)]
    agreed = el.gossip_agreement({}, _links(8, cut=cut))
    for r in range(5):
        assert agreed[r] == frozenset({5, 6, 7})
    for r in range(5, 8):
        assert agreed[r] == frozenset({0, 1, 2, 3, 4})
    # the majority side continues; the minority must abort
    assert el.majority_survives(agreed[0], 8)
    assert not el.majority_survives(agreed[5], 8)
    # exact half is NOT a majority (4 of 8 survive)
    assert not el.majority_survives({0, 1, 2, 3}, 8)


def test_exchange_suspects_tcp_converges_across_survivors():
    """The runtime agreement on localhost: 3 survivors of a 4-rank world
    (rank 3 dead, its port never listening) with DIFFERENT local
    suspicions all converge on {3}."""
    import socket

    with socket.socket() as s:
        s.bind(("localhost", 0))
        base = s.getsockname()[1]
    # find a base with 4 free consecutive ports (the probe above freed one)
    world = 4
    suspects = {0: {3}, 1: set(), 2: set()}
    results = {}

    def worker(rank):
        results[rank] = el.exchange_suspects(
            rank, world, suspects[rank], "localhost", base,
            timeout=5.0,
        )

    threads = [threading.Thread(target=worker, args=(r,)) for r in range(3)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    assert results == {r: frozenset({3}) for r in range(3)}, results


# ---------------------------------------------------------------------------
# state packing + ShardStore simulation
# ---------------------------------------------------------------------------


def _state():
    return {
        "params": {"w": np.arange(12, dtype=np.float32).reshape(3, 4),
                   "b": np.ones((5,), np.float64)},
        "opt": [np.arange(7, dtype=np.int32), np.float32(2.5)],
        "step_scale": np.bool_(True),
    }


def _assert_state_equal(a, b):
    assert set(a) == set(b)
    np.testing.assert_array_equal(a["params"]["w"], b["params"]["w"])
    np.testing.assert_array_equal(a["params"]["b"], b["params"]["b"])
    np.testing.assert_array_equal(a["opt"][0], b["opt"][0])
    np.testing.assert_array_equal(a["opt"][1], b["opt"][1])
    np.testing.assert_array_equal(a["step_scale"], b["step_scale"])
    assert np.asarray(b["params"]["w"]).dtype == np.float32
    assert np.asarray(b["params"]["b"]).dtype == np.float64
    assert np.asarray(b["opt"][0]).dtype == np.int32


def test_pack_unpack_leaves_round_trip_mixed_dtypes():
    leaves = [np.arange(5, dtype=np.float32),
              np.arange(6, dtype=np.int64).reshape(2, 3),
              np.asarray(True)]
    buf, meta = el.pack_leaves(leaves)
    assert buf.dtype == np.uint8
    assert buf.nbytes == sum(m[2] for m in meta)
    out = el.unpack_leaves(buf, meta)
    for a, b in zip(leaves, out):
        np.testing.assert_array_equal(a, b)
        assert a.dtype == b.dtype and a.shape == b.shape
    # empty state packs to an empty buffer
    buf0, meta0 = el.pack_leaves([])
    assert buf0.nbytes == 0 and meta0 == []


def _per_rank_stores(k, redundancy, step, state):
    stores = {}
    for r in range(k):

        class _FixedComm:
            def world_size(self, _k=k):
                return _k

        store = el.ShardStore(_FixedComm(), redundancy=redundancy, rank=r)
        store.commit(step, state)
        stores[r] = store
    return stores


def test_shardstore_per_rank_holdings_match_the_placement():
    stores = _per_rank_stores(8, 1, 3, _state())
    for r, store in stores.items():
        rec = store._committed
        assert rec["step"] == 3 and rec["k"] == 8
        assert tuple(sorted(rec["shards"])) == el.shards_held_by(r, 8, 1)
        for s, payload in rec["shards"].items():
            assert len(payload) == rec["shard"]


@pytest.mark.parametrize("k,redundancy,failed", [
    (8, 1, {3}),
    (8, 1, {0}),
    (8, 1, {7}),
    (8, 2, {3, 4}),      # adjacent double loss needs redundancy 2
    (8, 2, {0, 7}),      # wrap-adjacent double loss
    (4, 1, {2}),
    (3, 2, {0, 1}),
    (5, 1, set()),       # no failure: trivial reassembly
])
def test_shardstore_reassembles_bit_identical_after_losses(
        k, redundancy, failed):
    state = _state()
    stores = _per_rank_stores(k, redundancy, 11, state)
    step, restored = el.reassemble_from_stores(
        {r: s for r, s in stores.items() if r not in failed}
        | {r: stores[r] for r in failed},  # full dict; failed arg filters
        failed,
    )
    assert step == 11
    _assert_state_equal(state, restored)


def test_shardstore_reassembly_fails_loudly_past_the_budget():
    stores = _per_rank_stores(8, 1, 5, _state())
    with pytest.raises(el.RankFailure, match="unrecoverable"):
        el.reassemble_from_stores(stores, {3, 4})


def test_shardstore_redundancy_default_comes_from_the_flag():
    class _C:
        def world_size(self):
            return 4

    assert el.ShardStore(_C()).redundancy == 1
    os.environ["MPI4JAX_TPU_ELASTIC_REDUNDANCY"] = "2"
    assert el.ShardStore(_C()).redundancy == 2
    with pytest.raises(ValueError):
        el.ShardStore(_C(), redundancy=-1)


def test_shardstore_restore_requires_a_commit():
    class _C:
        def world_size(self):
            return 4

    store = el.ShardStore(_C(), redundancy=1, rank=0)
    with pytest.raises(RuntimeError, match="nothing committed"):
        store.restore()


# ---------------------------------------------------------------------------
# failure classification
# ---------------------------------------------------------------------------


def test_classify_failure_passthrough_and_markers():
    rf = el.RankFailure({3}, "peer death")
    assert el.classify_failure(rf) is rf
    assert el.classify_failure(RuntimeError("heartbeat deadline exceeded"))
    assert el.classify_failure(OSError("connection reset by peer"))
    assert el.classify_failure(ValueError("heartbeat")) is None  # wrong type
    assert el.classify_failure(RuntimeError("shape mismatch")) is None


def test_classify_failure_adopts_the_watchdog_claim():
    el._post_failure(el.RankFailure((), "watchdog expiry: MPI_Allreduce"))
    rf = el.classify_failure(KeyboardInterrupt())
    assert rf is not None and "watchdog expiry" in rf.detail
    # the pending slot drained: an ordinary error afterwards propagates
    assert el.classify_failure(RuntimeError("shape mismatch")) is None


def test_rank_failure_message_names_the_suspects():
    assert "unknown" in str(el.RankFailure())
    assert "[2, 5]" in str(el.RankFailure({5, 2}))


# ---------------------------------------------------------------------------
# hang fault verb
# ---------------------------------------------------------------------------


def test_hang_spec_parses_and_round_trips():
    (c,) = fi.parse_fault_spec("hang:rank=3:op=allreduce:after=5")
    assert (c.verb, c.rank, c.op, c.after) == ("hang", 3, "allreduce", 5)
    canon = fi.canonical_spec((c,))
    assert canon == "hang:rank=3:op=allreduce:after=5"
    assert fi.parse_fault_spec(canon) == (c,)
    # bare hang: every rank, every op, immediately
    (c,) = fi.parse_fault_spec("hang")
    assert (c.rank, c.op, c.after) == (None, None, 0)
    assert c.matches_op("barrier")


def test_hang_spec_rejects_delay_only_args():
    with pytest.raises(ValueError, match="secs"):
        fi.parse_fault_spec("hang:secs=2")
    with pytest.raises(ValueError, match="bare field"):
        fi.parse_fault_spec("hang:nan")


def test_hang_probe_blocks_until_interrupted(monkeypatch):
    """The hang probe sleeps in bounded naps (so drills stay
    interruptible); after the ``after`` window it never returns on the
    firing rank, and other ranks run clean."""
    naps = []

    def fake_hang():
        naps.append(True)
        raise _Escaped

    class _Escaped(Exception):
        pass

    monkeypatch.setattr(fi, "_hang_forever", fake_hang)
    (c,) = fi.parse_fault_spec("hang:rank=1:after=1")
    indexed = ((0, c),)
    assert fi.probe_host(indexed, "MPI_Allreduce", 0) == 0   # wrong rank
    assert fi.probe_host(indexed, "MPI_Allreduce", 1) == 0   # clean window
    with pytest.raises(_Escaped):
        fi.probe_host(indexed, "MPI_Allreduce", 1)           # hangs
    assert naps == [True]


def test_hang_nap_is_bounded():
    """The real hang loop sleeps in ``_HANG_NAP_SECS`` slices, not one
    giant sleep — the property that keeps interrupt_main effective."""
    assert 0 < fi._HANG_NAP_SECS <= 5.0


# ---------------------------------------------------------------------------
# pluggable watchdog handler + drain
# ---------------------------------------------------------------------------


def test_set_on_timeout_swaps_and_restores_the_live_handler():
    assert wd._registry.on_timeout is wd._default_on_timeout
    marker = lambda entries, expired: None  # noqa: E731
    wd.set_on_timeout(marker)
    assert wd._registry.on_timeout is marker
    wd.set_on_timeout(None)
    assert wd._registry.on_timeout is wd._default_on_timeout


def test_monitor_survives_a_nonfatal_handler_and_keeps_watching():
    """A claiming handler (elastic recovery) returns instead of killing;
    the monitor must drain the claimed entries and catch a LATER expiry
    with the same thread."""
    fired = []
    reg = wd._Registry(on_timeout=lambda entries, expired: fired.append(
        expired["call_id"]))
    reg.arm("MPI_Allreduce", "aaaa0001", 0, "('i',)", timeout=0.1)
    deadline = time.monotonic() + 5.0
    while not fired and time.monotonic() < deadline:
        time.sleep(0.02)
    assert fired == ["aaaa0001"]
    deadline = time.monotonic() + 5.0
    while not reg.empty() and time.monotonic() < deadline:
        time.sleep(0.02)
    assert reg.empty()           # claimed entries drained
    # the SAME monitor catches the next epoch's expiry
    reg.arm("MPI_Bcast", "aaaa0002", 0, "('i',)", timeout=0.1)
    deadline = time.monotonic() + 5.0
    while len(fired) < 2 and time.monotonic() < deadline:
        time.sleep(0.02)
    assert fired == ["aaaa0001", "aaaa0002"]


def test_monitor_claim_drains_only_expired_entries():
    """A claimed expiry must not wipe the un-expired arms of unrelated
    concurrent collectives — they keep their watchdog coverage."""
    now = [100.0]
    reg = wd._Registry(on_timeout=lambda e, x: None, clock=lambda: now[0])
    reg.arm("MPI_Allreduce", "dddd0001", 0, "('i',)", timeout=1.0)
    reg.arm("MPI_Bcast", "dddd0002", 0, "('i',)", timeout=900.0)
    now[0] += 2.0                               # only the allreduce expired
    assert reg.check_expired()["opname"] == "MPI_Allreduce"
    assert reg.drain_expired() == 1
    snap = reg.snapshot()
    assert [e["opname"] for e in snap] == ["MPI_Bcast"]
    assert reg.check_expired() is None          # survivor not expired


def test_exchange_suspects_returns_the_self_verdict():
    """A rank whose peers declared it failed must SEE itself in its own
    agreement result (so _recover can abort it) — the verdict is not
    stripped on the way out."""
    import json
    import socket

    with socket.socket() as s:
        s.bind(("localhost", 0))
        base = s.getsockname()[1]

    # fake rank 1: accept rank 0's sends, and tell rank 0 that rank 0 is
    # the failed one
    def fake_peer():
        srv = socket.socket()
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        srv.bind(("localhost", base + 1))
        srv.listen(2)
        srv.settimeout(10.0)
        msg = json.dumps({"from": 1, "suspects": [0]}).encode()
        try:
            with socket.create_connection(("localhost", base + 0),
                                          timeout=10.0) as c:
                c.sendall(len(msg).to_bytes(8, "big") + msg)
            for _ in range(2):
                try:
                    conn, _ = srv.accept()
                    conn.close()
                except socket.timeout:
                    break
        finally:
            srv.close()

    t = threading.Thread(target=fake_peer, daemon=True)
    result = {}

    def me():
        result["agreed"] = el.exchange_suspects(
            0, 2, (), "localhost", base, timeout=5.0)

    m = threading.Thread(target=me, daemon=True)
    m.start()
    time.sleep(0.3)           # my server is up before the peer connects
    t.start()
    m.join(timeout=30)
    t.join(timeout=30)
    assert 0 in result["agreed"], result


def test_drain_registry_counts_and_clears():
    wd._registry.arm("MPI_Allreduce", "bbbb0001", 0, "('i',)", timeout=900)
    wd._registry.arm("MPI_Allreduce", "bbbb0001", 1, "('i',)", timeout=900)
    assert not wd.registry_empty()
    assert wd.drain_registry() == 2
    assert wd.registry_empty()
    assert wd.drain_registry() == 0


# ---------------------------------------------------------------------------
# retry max_attempts + bootstrap flags
# ---------------------------------------------------------------------------


class _Flaky:
    def __init__(self, refusals):
        self.left = refusals
        self.calls = 0

    def __call__(self):
        self.calls += 1
        if self.left > 0:
            self.left -= 1
            raise ConnectionError(f"refused ({self.calls})")
        return "ok"


def test_retry_max_attempts_caps_before_the_deadline():
    fn = _Flaky(10)
    with pytest.raises(RuntimeError, match="max_attempts 3") as ei:
        retry_mod.retry_with_backoff(
            fn, what="rendezvous", deadline=1e9, max_attempts=3,
            jitter=False, sleep=lambda s: None, clock=lambda: 0.0,
        )
    assert fn.calls == 3
    assert isinstance(ei.value.__cause__, ConnectionError)


def test_retry_max_attempts_none_or_zero_is_unlimited():
    now = [0.0]

    def sleep(s):
        now[0] += s

    for cap in (None, 0):
        fn = _Flaky(4)
        out = retry_mod.retry_with_backoff(
            fn, deadline=300.0, max_attempts=cap, jitter=False,
            sleep=sleep, clock=lambda: now[0],
        )
        assert out == "ok" and fn.calls == 5
    with pytest.raises(ValueError, match="max_attempts"):
        retry_mod.retry_with_backoff(lambda: None, max_attempts=-1)


def test_bootstrap_flags_parse_and_validate():
    assert config.bootstrap_deadline() == 300.0
    assert config.bootstrap_max_attempts() == 0
    os.environ["MPI4JAX_TPU_BOOTSTRAP_DEADLINE"] = "12.5"
    os.environ["MPI4JAX_TPU_BOOTSTRAP_MAX_ATTEMPTS"] = "7"
    assert config.bootstrap_deadline() == 12.5
    assert config.bootstrap_max_attempts() == 7
    os.environ["MPI4JAX_TPU_BOOTSTRAP_DEADLINE"] = "0"
    with pytest.raises(ValueError, match="BOOTSTRAP_DEADLINE"):
        config.bootstrap_deadline()
    os.environ["MPI4JAX_TPU_BOOTSTRAP_MAX_ATTEMPTS"] = "-1"
    with pytest.raises(ValueError, match="BOOTSTRAP_MAX_ATTEMPTS"):
        config.bootstrap_max_attempts()
    os.environ["MPI4JAX_TPU_ELASTIC_REDUNDANCY"] = "nope"
    with pytest.raises(ValueError, match="ELASTIC_REDUNDANCY"):
        config.elastic_redundancy()


# ---------------------------------------------------------------------------
# elastic.run control flow (scripted fake store: no jax, no mesh)
# ---------------------------------------------------------------------------


class _FakeComm:
    def __init__(self, size):
        self._size = size

    def world_size(self):
        return self._size


class _FakeStore:
    """Scripted ShardStore double: world of 4, shrink drops the failed
    ranks, restore replays the committed (step, state)."""

    def __init__(self, world=4):
        self.redundancy = 1
        self.bootstrap = {}
        self.comm = _FakeComm(world)
        self.commits = []
        self._committed = None
        self.shrunk_with = None

    @property
    def committed_step(self):
        return self._committed and self._committed[0]

    def commit(self, step, state):
        self._committed = (step, state)
        self.commits.append(step)

    def multiprocess(self):
        return False

    def apply_shrink(self, failed):
        self.shrunk_with = frozenset(failed)
        self.comm = _FakeComm(self.comm.world_size() - len(self.shrunk_with))

    def restore(self, failed=()):
        return self._committed


def test_run_happy_path_commits_on_schedule():
    store = _FakeStore()
    steps_seen = []

    def step_fn(state, step, comm):
        steps_seen.append((step, comm.world_size()))
        return state + 1

    out = el.run(step_fn, 0, store, steps=6, commit_every=2)
    assert out == 6
    assert steps_seen == [(s, 4) for s in range(6)]
    # initial commit at 0, then every 2 steps
    assert store.commits == [0, 2, 4, 6]


def test_run_recovers_from_an_explicit_rank_failure():
    store = _FakeStore()
    calls = {"fails": 0}

    def step_fn(state, step, comm):
        if step == 3 and calls["fails"] == 0:
            calls["fails"] += 1
            raise el.RankFailure({3}, "simulated death")
        return state + 1

    out = el.run(step_fn, 0, store, steps=5, commit_every=1)
    # failure at step 3 replays from committed step 3: total = 5 steps of
    # +1 from the restored value 3
    assert out == 5
    assert store.shrunk_with == frozenset({3})
    assert store.comm.world_size() == 3
    assert el.current_epoch() == 1           # exactly one revocation


def test_run_refuses_empty_agreed_failure():
    store = _FakeStore()

    def step_fn(state, step, comm):
        raise el.RankFailure((), "suspects unknown, no agreement channel")

    with pytest.raises(el.RankFailure, match="empty failed set"):
        el.run(step_fn, 0, store, steps=2)


def test_run_refuses_minority_partition():
    store = _FakeStore()

    def step_fn(state, step, comm):
        raise el.RankFailure({0, 1, 2}, "three of four died")

    with pytest.raises(el.RankFailure, match="majority"):
        el.run(step_fn, 0, store, steps=2)
    assert el.current_epoch() == 0           # no revocation on refusal


def test_run_propagates_ordinary_errors():
    store = _FakeStore()

    def step_fn(state, step, comm):
        raise ValueError("a plain bug")

    with pytest.raises(ValueError, match="plain bug"):
        el.run(step_fn, 0, store, steps=2)


def test_run_claims_and_restores_the_watchdog_handler():
    store = _FakeStore()
    seen = []

    def step_fn(state, step, comm):
        seen.append(wd._registry.on_timeout)
        return state

    el.run(step_fn, 0, store, steps=1)
    assert seen == [el._claimed_on_timeout]
    assert wd._registry.on_timeout is wd._default_on_timeout
    el.run(step_fn, 0, store, steps=1, claim_watchdog=False)
    assert seen[-1] is wd._default_on_timeout


def test_run_recovery_from_watchdog_claim_pending():
    """A pending failure posted by the claimed handler converts the
    interrupting exception into a recovery."""
    store = _FakeStore()
    fired = {"n": 0}

    def step_fn(state, step, comm):
        if step == 1 and fired["n"] == 0:
            fired["n"] += 1
            el._post_failure(el.RankFailure({2}, "watchdog expiry"))
            raise KeyboardInterrupt
        return state + 1

    out = el.run(step_fn, 0, store, steps=3)
    assert out == 3
    assert store.shrunk_with == frozenset({2})


def test_revoke_epoch_drains_watchdog_and_advances():
    wd._registry.arm("MPI_Allreduce", "cccc0001", 0, "('i',)", timeout=900)
    new = el.revoke_epoch({3}, rank=0, world=4)
    assert new == 1 and el.current_epoch() == 1
    assert wd.registry_empty()


def test_run_validates_arguments():
    store = _FakeStore()
    with pytest.raises(ValueError, match="steps"):
        el.run(lambda s, i, c: s, 0, store, steps=-1)
    with pytest.raises(ValueError, match="commit_every"):
        el.run(lambda s, i, c: s, 0, store, steps=1, commit_every=0)
