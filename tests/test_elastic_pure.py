"""Pure half of the elastic-recovery suite (docs/resilience.md
"Elastic recovery").

Everything here runs WITHOUT importing mpi4jax_tpu (the isolated loader
below, mirroring tests/test_resilience.py), so the protocol core is
verified under any JAX version:

- epoch arithmetic + the resilience cache token carrying it;
- shard ownership, k-redundant neighbor-replication placement, and the
  reconstruction plan (including the unrecoverable-loss error);
- rank compaction and color-split group shrink;
- failure agreement: the gossip fixpoint on simulated link matrices
  (agreement within a connected component, suspicion of unreachable
  peers, split-brain majority arbitration) and the TCP runtime form on
  localhost;
- ShardStore commit/reassemble simulated with per-rank stores — kill any
  `redundancy` ranks and the state returns bit-identical;
- failure classification (explicit, watchdog-claimed, death-rattle);
- the `hang` fault verb (parser + probe semantics);
- pluggable watchdog `on_timeout` + registry drain;
- `retry_with_backoff(max_attempts=...)` and the bootstrap flags;
- `elastic.run`'s control flow against a scripted fake store.

The traced half (epoch→retrace cache pin, HLO identity with elastic off,
the 8-device shrink) is tests/test_elastic.py, which needs jax >= the
package floor.
"""

import importlib
import os
import pathlib
import sys
import threading
import time
import types

import numpy as np
import pytest

REPO = pathlib.Path(__file__).resolve().parent.parent
PKG = REPO / "mpi4jax_tpu"

_ISO_NAME = "_mpx_elastic_iso"


def _load_isolated():
    """Load the pure-Python elastic stack under a private package name
    (bypasses mpi4jax_tpu/__init__.py and its JAX floor; state isolated
    from any real import in the same process)."""
    if _ISO_NAME in sys.modules:
        return sys.modules[_ISO_NAME]
    root = types.ModuleType(_ISO_NAME)
    root.__path__ = [str(PKG)]
    sys.modules[_ISO_NAME] = root
    for sub in ("utils", "resilience"):
        m = types.ModuleType(f"{_ISO_NAME}.{sub}")
        m.__path__ = [str(PKG / sub)]
        sys.modules[f"{_ISO_NAME}.{sub}"] = m
        setattr(root, sub, m)
    for mod in (
        "utils.config",
        "resilience.faultinject",
        "resilience.retry",
        "resilience.watchdog",
        "resilience.elastic",
        "resilience.drill",
        "resilience.runtime",
    ):
        importlib.import_module(f"{_ISO_NAME}.{mod}")
    return root


ISO = _load_isolated()
el = ISO.resilience.elastic
fi = ISO.resilience.faultinject
wd = ISO.resilience.watchdog
rt = ISO.resilience.runtime
retry_mod = ISO.resilience.retry
config = ISO.utils.config


@pytest.fixture(autouse=True)
def _clean_state():
    el._reset_epoch_for_tests()
    el.take_pending_failure()
    wd.set_on_timeout(None)
    wd.drain_registry()
    fi.reset_fault_state()
    saved = {
        k: os.environ.pop(k, None)
        for k in (
            "MPI4JAX_TPU_BOOTSTRAP_DEADLINE",
            "MPI4JAX_TPU_BOOTSTRAP_MAX_ATTEMPTS",
            "MPI4JAX_TPU_ELASTIC_REDUNDANCY",
            "MPI4JAX_TPU_ELASTIC_GROW",
            "MPI4JAX_TPU_DRAIN_GRACE_S",
            "MPI4JAX_TPU_ELASTIC_FAIL_UNIT",
            "MPI4JAX_TPU_ELASTIC_PORT_SPAN",
            "MPI4JAX_TPU_ELASTIC_PLACEMENT",
            "MPI4JAX_TPU_ELASTIC_AGREEMENT",
            "MPI4JAX_TPU_TOPOLOGY",
        )
    }
    yield
    el._reset_epoch_for_tests()
    el.take_pending_failure()
    wd.set_on_timeout(None)
    wd.drain_registry()
    for k, v in saved.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v


# ---------------------------------------------------------------------------
# epoch arithmetic
# ---------------------------------------------------------------------------


def test_epoch_starts_at_zero_and_advances_monotonically():
    assert el.current_epoch() == 0
    assert el.elastic_cache_token() == 0
    assert el.advance_epoch() == 1
    assert el.advance_epoch() == 2
    assert el.current_epoch() == 2
    assert el.elastic_cache_token() == 2


def test_advance_epoch_bumps_config_epoch():
    """Every stamp-memoized configuration consumer must invalidate on a
    revocation — that is how the epoch reaches the program-cache keys."""
    before = config.config_epoch()
    el.advance_epoch()
    assert config.config_epoch() > before


def test_resilience_cache_token_carries_the_epoch():
    base = rt.cache_token()
    assert base[-1] == 0
    el.advance_epoch()
    bumped = rt.cache_token()
    assert bumped != base
    assert bumped[-1] == 1
    # everything else in the token is untouched by a revocation
    assert bumped[:-1] == base[:-1]


# ---------------------------------------------------------------------------
# shard ownership + replication placement
# ---------------------------------------------------------------------------


def test_shard_bounds_equal_chunks_with_padding():
    assert el.shard_bounds(0, 4) == (0, 0)
    assert el.shard_bounds(100, 4) == (25, 100)
    assert el.shard_bounds(101, 4) == (26, 104)   # ceil + pad
    assert el.shard_bounds(3, 8) == (1, 8)
    with pytest.raises(ValueError, match="at least one rank"):
        el.shard_bounds(10, 0)


def test_replica_ranks_neighbor_placement():
    assert el.replica_ranks(0, 8, 1) == (0, 1)
    assert el.replica_ranks(7, 8, 1) == (7, 0)    # wraps
    assert el.replica_ranks(2, 8, 2) == (2, 3, 4)
    assert el.replica_ranks(5, 8, 0) == (5,)      # no redundancy: owner only
    # more copies than ranks degenerates to "everyone"
    assert el.replica_ranks(1, 3, 7) == (1, 2, 0)
    with pytest.raises(ValueError, match="out of range"):
        el.replica_ranks(8, 8, 1)
    with pytest.raises(ValueError, match="redundancy"):
        el.replica_ranks(0, 8, -1)


def test_shards_held_by_is_the_inverse_of_replica_ranks():
    for k in (1, 2, 3, 5, 8):
        for red in (0, 1, 2, k - 1):
            for r in range(k):
                held = el.shards_held_by(r, k, red)
                assert len(held) == min(red, k - 1) + 1
                for s in held:
                    assert r in el.replica_ranks(s, k, red)
            # every shard has exactly redundancy+1 holders
            counts = {s: 0 for s in range(k)}
            for r in range(k):
                for s in el.shards_held_by(r, k, red):
                    counts[s] += 1
            assert set(counts.values()) == {min(red, k - 1) + 1}


def test_recoverable_tolerates_exactly_the_redundancy_budget():
    # any single failure is recoverable at redundancy 1
    for r in range(8):
        assert el.recoverable({r}, 8, 1)
    # two ADJACENT failures kill a whole replica set at redundancy 1
    assert not el.recoverable({3, 4}, 8, 1)      # shard 3's copies: ranks 3,4
    # two non-adjacent failures are fine
    assert el.recoverable({1, 5}, 8, 1)
    # redundancy 2 tolerates any 2 failures
    for a in range(8):
        for b in range(8):
            if a != b:
                assert el.recoverable({a, b}, 8, 2)


def test_reconstruction_plan_names_lowest_surviving_holder():
    plan = el.reconstruction_plan({3}, 8, 1)
    assert set(plan) == set(range(8))
    assert plan[3] == 4          # shard 3's owner died; right neighbor holds it
    assert plan[2] == 2          # untouched shards use their owner
    for s, provider in plan.items():
        assert provider != 3
        assert provider in el.replica_ranks(s, 8, 1)
    with pytest.raises(el.RankFailure, match="unrecoverable"):
        el.reconstruction_plan({3, 4}, 8, 1)


# ---------------------------------------------------------------------------
# rank compaction + group shrink
# ---------------------------------------------------------------------------


def test_compact_rank_map_renumbers_ascending():
    assert el.compact_rank_map(4, {3}) == {0: 0, 1: 1, 2: 2}
    assert el.compact_rank_map(4, {0}) == {1: 0, 2: 1, 3: 2}
    assert el.compact_rank_map(8, {2, 5}) == {
        0: 0, 1: 1, 3: 2, 4: 3, 6: 4, 7: 5,
    }
    with pytest.raises(ValueError, match="out of range"):
        el.compact_rank_map(4, {4})
    with pytest.raises(el.RankFailure, match="no survivors"):
        el.compact_rank_map(2, {0, 1})


def test_shrink_groups_drops_dead_and_preserves_order():
    groups = ((0, 2, 4, 6), (1, 3, 5, 7))
    assert el.shrink_groups(groups, {3}, 8) == ((0, 2, 3, 5), (1, 4, 6))
    # a group losing every member disappears
    assert el.shrink_groups(((0, 1), (2, 3)), {2, 3}, 4) == ((0, 1),)
    # key-ordered (non-ascending) member order survives the renumbering
    assert el.shrink_groups(((2, 0, 1),), {1}, 3) == ((1, 0),)


# ---------------------------------------------------------------------------
# failure agreement
# ---------------------------------------------------------------------------


def _links(world, down=(), cut=()):
    """Full link matrix minus every link touching ``down`` ranks and the
    explicit ``cut`` pairs."""
    m = [[i != j for j in range(world)] for i in range(world)]
    for r in down:
        for j in range(world):
            m[r][j] = m[j][r] = False
    for a, b in cut:
        m[a][b] = m[b][a] = False
    return m


def test_gossip_agreement_converges_on_the_union():
    # ranks 0 and 1 each suspect a different dead rank; everyone agrees
    # on the union, and the dead are suspected by everyone via dead links
    agreed = el.gossip_agreement(
        {0: {6}, 1: {7}}, _links(8, down=(6, 7)))
    for r in range(6):
        assert agreed[r] == frozenset({6, 7}), r


def test_gossip_agreement_suspects_unreachable_peers_without_hints():
    # nobody *observed* anything, but rank 5's links are all down
    agreed = el.gossip_agreement({}, _links(8, down=(5,)))
    for r in range(8):
        if r != 5:
            assert agreed[r] == frozenset({5})


def test_gossip_agreement_partition_disagrees_and_majority_arbitrates():
    # cut the world into {0,1,2,3,4} and {5,6,7}: each side suspects the
    # other wholesale
    cut = [(a, b) for a in range(5) for b in range(5, 8)]
    agreed = el.gossip_agreement({}, _links(8, cut=cut))
    for r in range(5):
        assert agreed[r] == frozenset({5, 6, 7})
    for r in range(5, 8):
        assert agreed[r] == frozenset({0, 1, 2, 3, 4})
    # the majority side continues; the minority must abort
    assert el.majority_survives(agreed[0], 8)
    assert not el.majority_survives(agreed[5], 8)
    # exact half is NOT a majority (4 of 8 survive)
    assert not el.majority_survives({0, 1, 2, 3}, 8)


def test_exchange_suspects_tcp_converges_across_survivors():
    """The runtime agreement on localhost: 3 survivors of a 4-rank world
    (rank 3 dead, its port never listening) with DIFFERENT local
    suspicions all converge on {3}."""
    import socket

    with socket.socket() as s:
        s.bind(("localhost", 0))
        base = s.getsockname()[1]
    # find a base with 4 free consecutive ports (the probe above freed one)
    world = 4
    suspects = {0: {3}, 1: set(), 2: set()}
    results = {}

    def worker(rank):
        results[rank] = el.exchange_suspects(
            rank, world, suspects[rank], "localhost", base,
            timeout=5.0,
        )

    threads = [threading.Thread(target=worker, args=(r,)) for r in range(3)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    assert results == {r: frozenset({3}) for r in range(3)}, results


# ---------------------------------------------------------------------------
# state packing + ShardStore simulation
# ---------------------------------------------------------------------------


def _state():
    return {
        "params": {"w": np.arange(12, dtype=np.float32).reshape(3, 4),
                   "b": np.ones((5,), np.float64)},
        "opt": [np.arange(7, dtype=np.int32), np.float32(2.5)],
        "step_scale": np.bool_(True),
    }


def _assert_state_equal(a, b):
    assert set(a) == set(b)
    np.testing.assert_array_equal(a["params"]["w"], b["params"]["w"])
    np.testing.assert_array_equal(a["params"]["b"], b["params"]["b"])
    np.testing.assert_array_equal(a["opt"][0], b["opt"][0])
    np.testing.assert_array_equal(a["opt"][1], b["opt"][1])
    np.testing.assert_array_equal(a["step_scale"], b["step_scale"])
    assert np.asarray(b["params"]["w"]).dtype == np.float32
    assert np.asarray(b["params"]["b"]).dtype == np.float64
    assert np.asarray(b["opt"][0]).dtype == np.int32


def test_pack_unpack_leaves_round_trip_mixed_dtypes():
    leaves = [np.arange(5, dtype=np.float32),
              np.arange(6, dtype=np.int64).reshape(2, 3),
              np.asarray(True)]
    buf, meta = el.pack_leaves(leaves)
    assert buf.dtype == np.uint8
    assert buf.nbytes == sum(m[2] for m in meta)
    out = el.unpack_leaves(buf, meta)
    for a, b in zip(leaves, out):
        np.testing.assert_array_equal(a, b)
        assert a.dtype == b.dtype and a.shape == b.shape
    # empty state packs to an empty buffer
    buf0, meta0 = el.pack_leaves([])
    assert buf0.nbytes == 0 and meta0 == []


def _per_rank_stores(k, redundancy, step, state):
    stores = {}
    for r in range(k):

        class _FixedComm:
            def world_size(self, _k=k):
                return _k

        store = el.ShardStore(_FixedComm(), redundancy=redundancy, rank=r)
        store.commit(step, state)
        stores[r] = store
    return stores


def test_shardstore_per_rank_holdings_match_the_placement():
    stores = _per_rank_stores(8, 1, 3, _state())
    for r, store in stores.items():
        rec = store._committed
        assert rec["step"] == 3 and rec["k"] == 8
        assert tuple(sorted(rec["shards"])) == el.shards_held_by(r, 8, 1)
        for s, payload in rec["shards"].items():
            assert len(payload) == rec["shard"]


@pytest.mark.parametrize("k,redundancy,failed", [
    (8, 1, {3}),
    (8, 1, {0}),
    (8, 1, {7}),
    (8, 2, {3, 4}),      # adjacent double loss needs redundancy 2
    (8, 2, {0, 7}),      # wrap-adjacent double loss
    (4, 1, {2}),
    (3, 2, {0, 1}),
    (5, 1, set()),       # no failure: trivial reassembly
])
def test_shardstore_reassembles_bit_identical_after_losses(
        k, redundancy, failed):
    state = _state()
    stores = _per_rank_stores(k, redundancy, 11, state)
    step, restored = el.reassemble_from_stores(
        {r: s for r, s in stores.items() if r not in failed}
        | {r: stores[r] for r in failed},  # full dict; failed arg filters
        failed,
    )
    assert step == 11
    _assert_state_equal(state, restored)


def test_shardstore_reassembly_fails_loudly_past_the_budget():
    stores = _per_rank_stores(8, 1, 5, _state())
    with pytest.raises(el.RankFailure, match="unrecoverable"):
        el.reassemble_from_stores(stores, {3, 4})


def test_shardstore_redundancy_default_comes_from_the_flag():
    class _C:
        def world_size(self):
            return 4

    assert el.ShardStore(_C()).redundancy == 1
    os.environ["MPI4JAX_TPU_ELASTIC_REDUNDANCY"] = "2"
    assert el.ShardStore(_C()).redundancy == 2
    with pytest.raises(ValueError):
        el.ShardStore(_C(), redundancy=-1)


def test_shardstore_restore_requires_a_commit():
    class _C:
        def world_size(self):
            return 4

    store = el.ShardStore(_C(), redundancy=1, rank=0)
    with pytest.raises(RuntimeError, match="nothing committed"):
        store.restore()


# ---------------------------------------------------------------------------
# failure classification
# ---------------------------------------------------------------------------


def test_classify_failure_passthrough_and_markers():
    rf = el.RankFailure({3}, "peer death")
    assert el.classify_failure(rf) is rf
    assert el.classify_failure(RuntimeError("heartbeat deadline exceeded"))
    assert el.classify_failure(OSError("connection reset by peer"))
    assert el.classify_failure(ValueError("heartbeat")) is None  # wrong type
    assert el.classify_failure(RuntimeError("shape mismatch")) is None


def test_classify_failure_adopts_the_watchdog_claim():
    el._post_failure(el.RankFailure((), "watchdog expiry: MPI_Allreduce"))
    rf = el.classify_failure(KeyboardInterrupt())
    assert rf is not None and "watchdog expiry" in rf.detail
    # the pending slot drained: an ordinary error afterwards propagates
    assert el.classify_failure(RuntimeError("shape mismatch")) is None


def test_rank_failure_message_names_the_suspects():
    assert "unknown" in str(el.RankFailure())
    assert "[2, 5]" in str(el.RankFailure({5, 2}))


# ---------------------------------------------------------------------------
# hang fault verb
# ---------------------------------------------------------------------------


def test_hang_spec_parses_and_round_trips():
    (c,) = fi.parse_fault_spec("hang:rank=3:op=allreduce:after=5")
    assert (c.verb, c.rank, c.op, c.after) == ("hang", 3, "allreduce", 5)
    canon = fi.canonical_spec((c,))
    assert canon == "hang:rank=3:op=allreduce:after=5"
    assert fi.parse_fault_spec(canon) == (c,)
    # bare hang: every rank, every op, immediately
    (c,) = fi.parse_fault_spec("hang")
    assert (c.rank, c.op, c.after) == (None, None, 0)
    assert c.matches_op("barrier")


def test_hang_spec_rejects_delay_only_args():
    with pytest.raises(ValueError, match="secs"):
        fi.parse_fault_spec("hang:secs=2")
    with pytest.raises(ValueError, match="bare field"):
        fi.parse_fault_spec("hang:nan")


def test_hang_probe_blocks_until_interrupted(monkeypatch):
    """The hang probe sleeps in bounded naps (so drills stay
    interruptible); after the ``after`` window it never returns on the
    firing rank, and other ranks run clean."""
    naps = []

    def fake_hang():
        naps.append(True)
        raise _Escaped

    class _Escaped(Exception):
        pass

    monkeypatch.setattr(fi, "_hang_forever", fake_hang)
    (c,) = fi.parse_fault_spec("hang:rank=1:after=1")
    indexed = ((0, c),)
    assert fi.probe_host(indexed, "MPI_Allreduce", 0) == 0   # wrong rank
    assert fi.probe_host(indexed, "MPI_Allreduce", 1) == 0   # clean window
    with pytest.raises(_Escaped):
        fi.probe_host(indexed, "MPI_Allreduce", 1)           # hangs
    assert naps == [True]


def test_hang_nap_is_bounded():
    """The real hang loop sleeps in ``_HANG_NAP_SECS`` slices, not one
    giant sleep — the property that keeps interrupt_main effective."""
    assert 0 < fi._HANG_NAP_SECS <= 5.0


# ---------------------------------------------------------------------------
# pluggable watchdog handler + drain
# ---------------------------------------------------------------------------


def test_set_on_timeout_swaps_and_restores_the_live_handler():
    assert wd._registry.on_timeout is wd._default_on_timeout
    marker = lambda entries, expired: None  # noqa: E731
    wd.set_on_timeout(marker)
    assert wd._registry.on_timeout is marker
    wd.set_on_timeout(None)
    assert wd._registry.on_timeout is wd._default_on_timeout


def test_monitor_survives_a_nonfatal_handler_and_keeps_watching():
    """A claiming handler (elastic recovery) returns instead of killing;
    the monitor must drain the claimed entries and catch a LATER expiry
    with the same thread."""
    fired = []
    reg = wd._Registry(on_timeout=lambda entries, expired: fired.append(
        expired["call_id"]))
    reg.arm("MPI_Allreduce", "aaaa0001", 0, "('i',)", timeout=0.1)
    deadline = time.monotonic() + 5.0
    while not fired and time.monotonic() < deadline:
        time.sleep(0.02)
    assert fired == ["aaaa0001"]
    deadline = time.monotonic() + 5.0
    while not reg.empty() and time.monotonic() < deadline:
        time.sleep(0.02)
    assert reg.empty()           # claimed entries drained
    # the SAME monitor catches the next epoch's expiry
    reg.arm("MPI_Bcast", "aaaa0002", 0, "('i',)", timeout=0.1)
    deadline = time.monotonic() + 5.0
    while len(fired) < 2 and time.monotonic() < deadline:
        time.sleep(0.02)
    assert fired == ["aaaa0001", "aaaa0002"]


def test_monitor_claim_drains_only_expired_entries():
    """A claimed expiry must not wipe the un-expired arms of unrelated
    concurrent collectives — they keep their watchdog coverage."""
    now = [100.0]
    reg = wd._Registry(on_timeout=lambda e, x: None, clock=lambda: now[0])
    reg.arm("MPI_Allreduce", "dddd0001", 0, "('i',)", timeout=1.0)
    reg.arm("MPI_Bcast", "dddd0002", 0, "('i',)", timeout=900.0)
    now[0] += 2.0                               # only the allreduce expired
    assert reg.check_expired()["opname"] == "MPI_Allreduce"
    assert reg.drain_expired() == 1
    snap = reg.snapshot()
    assert [e["opname"] for e in snap] == ["MPI_Bcast"]
    assert reg.check_expired() is None          # survivor not expired


def test_exchange_suspects_returns_the_self_verdict():
    """A rank whose peers declared it failed must SEE itself in its own
    agreement result (so _recover can abort it) — the verdict is not
    stripped on the way out."""
    import json
    import socket

    with socket.socket() as s:
        s.bind(("localhost", 0))
        base = s.getsockname()[1]

    # fake rank 1: accept rank 0's sends, and tell rank 0 that rank 0 is
    # the failed one
    def fake_peer():
        srv = socket.socket()
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        srv.bind(("localhost", base + 1))
        srv.listen(2)
        srv.settimeout(10.0)
        msg = json.dumps({"from": 1, "suspects": [0]}).encode()
        try:
            with socket.create_connection(("localhost", base + 0),
                                          timeout=10.0) as c:
                c.sendall(len(msg).to_bytes(8, "big") + msg)
            for _ in range(2):
                try:
                    conn, _ = srv.accept()
                    conn.close()
                except socket.timeout:
                    break
        finally:
            srv.close()

    t = threading.Thread(target=fake_peer, daemon=True)
    result = {}

    def me():
        result["agreed"] = el.exchange_suspects(
            0, 2, (), "localhost", base, timeout=5.0)

    m = threading.Thread(target=me, daemon=True)
    m.start()
    time.sleep(0.3)           # my server is up before the peer connects
    t.start()
    m.join(timeout=30)
    t.join(timeout=30)
    assert 0 in result["agreed"], result


def test_drain_registry_counts_and_clears():
    wd._registry.arm("MPI_Allreduce", "bbbb0001", 0, "('i',)", timeout=900)
    wd._registry.arm("MPI_Allreduce", "bbbb0001", 1, "('i',)", timeout=900)
    assert not wd.registry_empty()
    assert wd.drain_registry() == 2
    assert wd.registry_empty()
    assert wd.drain_registry() == 0


# ---------------------------------------------------------------------------
# retry max_attempts + bootstrap flags
# ---------------------------------------------------------------------------


class _Flaky:
    def __init__(self, refusals):
        self.left = refusals
        self.calls = 0

    def __call__(self):
        self.calls += 1
        if self.left > 0:
            self.left -= 1
            raise ConnectionError(f"refused ({self.calls})")
        return "ok"


def test_retry_max_attempts_caps_before_the_deadline():
    fn = _Flaky(10)
    with pytest.raises(RuntimeError, match="max_attempts 3") as ei:
        retry_mod.retry_with_backoff(
            fn, what="rendezvous", deadline=1e9, max_attempts=3,
            jitter=False, sleep=lambda s: None, clock=lambda: 0.0,
        )
    assert fn.calls == 3
    assert isinstance(ei.value.__cause__, ConnectionError)


def test_retry_max_attempts_none_or_zero_is_unlimited():
    now = [0.0]

    def sleep(s):
        now[0] += s

    for cap in (None, 0):
        fn = _Flaky(4)
        out = retry_mod.retry_with_backoff(
            fn, deadline=300.0, max_attempts=cap, jitter=False,
            sleep=sleep, clock=lambda: now[0],
        )
        assert out == "ok" and fn.calls == 5
    with pytest.raises(ValueError, match="max_attempts"):
        retry_mod.retry_with_backoff(lambda: None, max_attempts=-1)


def test_bootstrap_flags_parse_and_validate():
    assert config.bootstrap_deadline() == 300.0
    assert config.bootstrap_max_attempts() == 0
    os.environ["MPI4JAX_TPU_BOOTSTRAP_DEADLINE"] = "12.5"
    os.environ["MPI4JAX_TPU_BOOTSTRAP_MAX_ATTEMPTS"] = "7"
    assert config.bootstrap_deadline() == 12.5
    assert config.bootstrap_max_attempts() == 7
    os.environ["MPI4JAX_TPU_BOOTSTRAP_DEADLINE"] = "0"
    with pytest.raises(ValueError, match="BOOTSTRAP_DEADLINE"):
        config.bootstrap_deadline()
    os.environ["MPI4JAX_TPU_BOOTSTRAP_MAX_ATTEMPTS"] = "-1"
    with pytest.raises(ValueError, match="BOOTSTRAP_MAX_ATTEMPTS"):
        config.bootstrap_max_attempts()
    os.environ["MPI4JAX_TPU_ELASTIC_REDUNDANCY"] = "nope"
    with pytest.raises(ValueError, match="ELASTIC_REDUNDANCY"):
        config.elastic_redundancy()


# ---------------------------------------------------------------------------
# elastic.run control flow (scripted fake store: no jax, no mesh)
# ---------------------------------------------------------------------------


class _FakeComm:
    _uids = iter(range(10_000, 20_000))

    def __init__(self, size):
        self._size = size
        self.uid = next(self._uids)

    def world_size(self):
        return self._size


class _FakeStore:
    """Scripted ShardStore double: world of 4, shrink drops the failed
    ranks, grow appends, restore replays the committed (step, state)."""

    def __init__(self, world=4):
        self.redundancy = 1
        self.bootstrap = {}
        self.comm = _FakeComm(world)
        self.commits = []
        self._committed = None
        self.shrunk_with = None
        self.shrunk_unit = None
        self.grown_by = 0
        self.restores = 0
        self.drained = False

    @property
    def committed_step(self):
        return self._committed and self._committed[0]

    def commit(self, step, state):
        self._committed = (step, state)
        self.commits.append(step)

    def multiprocess(self):
        return False

    def apply_shrink(self, failed, fail_unit="rank"):
        self.shrunk_with = frozenset(failed)
        self.shrunk_unit = fail_unit
        self.comm = _FakeComm(self.comm.world_size() - len(self.shrunk_with))

    def apply_grow(self, added):
        self.grown_by += added
        self.comm = _FakeComm(self.comm.world_size() + added)

    def restore(self, failed=(), force_exchange=False):
        self.restores += 1
        return self._committed


def test_run_happy_path_commits_on_schedule():
    store = _FakeStore()
    steps_seen = []

    def step_fn(state, step, comm):
        steps_seen.append((step, comm.world_size()))
        return state + 1

    out = el.run(step_fn, 0, store, steps=6, commit_every=2)
    assert out == 6
    assert steps_seen == [(s, 4) for s in range(6)]
    # initial commit at 0, then every 2 steps
    assert store.commits == [0, 2, 4, 6]


def test_run_recovers_from_an_explicit_rank_failure():
    store = _FakeStore()
    calls = {"fails": 0}

    def step_fn(state, step, comm):
        if step == 3 and calls["fails"] == 0:
            calls["fails"] += 1
            raise el.RankFailure({3}, "simulated death")
        return state + 1

    out = el.run(step_fn, 0, store, steps=5, commit_every=1)
    # failure at step 3 replays from committed step 3: total = 5 steps of
    # +1 from the restored value 3
    assert out == 5
    assert store.shrunk_with == frozenset({3})
    assert store.comm.world_size() == 3
    assert el.current_epoch() == 1           # exactly one revocation


def test_run_refuses_empty_agreed_failure():
    store = _FakeStore()

    def step_fn(state, step, comm):
        raise el.RankFailure((), "suspects unknown, no agreement channel")

    with pytest.raises(el.RankFailure, match="empty failed set"):
        el.run(step_fn, 0, store, steps=2)


def test_run_refuses_minority_partition():
    store = _FakeStore()

    def step_fn(state, step, comm):
        raise el.RankFailure({0, 1, 2}, "three of four died")

    with pytest.raises(el.RankFailure, match="majority"):
        el.run(step_fn, 0, store, steps=2)
    assert el.current_epoch() == 0           # no revocation on refusal


def test_run_propagates_ordinary_errors():
    store = _FakeStore()

    def step_fn(state, step, comm):
        raise ValueError("a plain bug")

    with pytest.raises(ValueError, match="plain bug"):
        el.run(step_fn, 0, store, steps=2)


def test_run_claims_and_restores_the_watchdog_handler():
    store = _FakeStore()
    seen = []

    def step_fn(state, step, comm):
        seen.append(wd._registry.on_timeout)
        return state

    el.run(step_fn, 0, store, steps=1)
    assert seen == [el._claimed_on_timeout]
    assert wd._registry.on_timeout is wd._default_on_timeout
    el.run(step_fn, 0, store, steps=1, claim_watchdog=False)
    assert seen[-1] is wd._default_on_timeout


def test_run_recovery_from_watchdog_claim_pending():
    """A pending failure posted by the claimed handler converts the
    interrupting exception into a recovery."""
    store = _FakeStore()
    fired = {"n": 0}

    def step_fn(state, step, comm):
        if step == 1 and fired["n"] == 0:
            fired["n"] += 1
            el._post_failure(el.RankFailure({2}, "watchdog expiry"))
            raise KeyboardInterrupt
        return state + 1

    out = el.run(step_fn, 0, store, steps=3)
    assert out == 3
    assert store.shrunk_with == frozenset({2})


def test_revoke_epoch_drains_watchdog_and_advances():
    wd._registry.arm("MPI_Allreduce", "cccc0001", 0, "('i',)", timeout=900)
    new = el.revoke_epoch({3}, rank=0, world=4)
    assert new == 1 and el.current_epoch() == 1
    assert wd.registry_empty()


def test_run_validates_arguments():
    store = _FakeStore()
    with pytest.raises(ValueError, match="steps"):
        el.run(lambda s, i, c: s, 0, store, steps=-1)
    with pytest.raises(ValueError, match="commit_every"):
        el.run(lambda s, i, c: s, 0, store, steps=1, commit_every=0)


# ---------------------------------------------------------------------------
# port-wrap math (the declared rendezvous window)
# ---------------------------------------------------------------------------


def test_coordinator_port_wraps_within_the_span():
    # identical to the unwrapped pre-span scheme for the first span epochs
    for e in (0, 1, 63):
        assert el.coordinator_port(5000, e, 64) == 5000 + e
    # ...and bounded forever after
    assert el.coordinator_port(5000, 64, 64) == 5000
    assert el.coordinator_port(5000, 1000, 64) == 5000 + (1000 % 64)
    ports = {el.coordinator_port(5000, e, 64) for e in range(1000)}
    assert ports == set(range(5000, 5064))


def test_port_banks_never_overlap():
    """Coordinator, join, and the two control banks are disjoint for
    every epoch — a joiner scanning the join window can never poke a
    jax.distributed socket, and consecutive epochs' control listeners
    never contend."""
    span, base, world = 8, 5000, 8
    coord = {el.coordinator_port(base, e, span) for e in range(100)}
    join = {el.join_port(base, e, span) for e in range(100)}
    ctrl = {el.control_port(base, r, e, span)
            for e in range(100) for r in range(world)}
    assert not coord & join and not coord & ctrl and not join & ctrl
    # consecutive epochs use disjoint control banks
    for r in range(world):
        assert (el.control_port(base, r, 4, span)
                != el.control_port(base, r, 5, span))
        assert (el.control_port(base, r, 4, span)
                == el.control_port(base, r, 6, span))


def test_control_port_rejects_rank_outside_the_span():
    with pytest.raises(ValueError, match="span"):
        el.control_port(5000, 8, 0, 8)
    with pytest.raises(ValueError, match="span"):
        el.wrapped_epoch(3, 0)


def test_port_span_flag_parses_and_validates():
    assert config.elastic_port_span() == 64
    os.environ["MPI4JAX_TPU_ELASTIC_PORT_SPAN"] = "16"
    assert config.elastic_port_span() == 16
    assert el.coordinator_port(5000, 20) == 5000 + 4   # flag-driven wrap
    os.environ["MPI4JAX_TPU_ELASTIC_PORT_SPAN"] = "0"
    with pytest.raises(ValueError, match="ELASTIC_PORT_SPAN"):
        config.elastic_port_span()


# ---------------------------------------------------------------------------
# fail-unit expansion + the 2-D renumbering
# ---------------------------------------------------------------------------


def test_expand_fail_unit_rows_and_cols():
    # 2x4 grid, row-major: rank 5 = (row 1, col 1)
    assert el.expand_fail_unit({5}, (2, 4), "row") == frozenset({4, 5, 6, 7})
    assert el.expand_fail_unit({5}, (2, 4), "col") == frozenset({1, 5})
    # two failures in one row expand to that one row
    assert el.expand_fail_unit({4, 6}, (2, 4), "row") == frozenset(
        {4, 5, 6, 7})
    # failures in different rows take both rows
    assert el.expand_fail_unit({0, 5}, (2, 4), "row") == frozenset(range(8))
    # rank unit is the identity; 1-D degrades every unit to rank
    assert el.expand_fail_unit({5}, (2, 4), "rank") == frozenset({5})
    assert el.expand_fail_unit({5}, (8,), "row") == frozenset({5})
    assert el.expand_fail_unit((), (2, 4), "row") == frozenset()
    with pytest.raises(ValueError, match="out of range"):
        el.expand_fail_unit({8}, (2, 4), "row")
    with pytest.raises(ValueError, match="fail_unit"):
        el.expand_fail_unit({1}, (2, 4), "diagonal")
    with pytest.raises(ValueError, match="2-D"):
        el.expand_fail_unit({1}, (2, 2, 2), "row")


def test_shrunken_shape_drops_whole_lines():
    row_dead = el.expand_fail_unit({5}, (2, 4), "row")
    assert el.shrunken_shape((2, 4), row_dead, "row") == (1, 4)
    col_dead = el.expand_fail_unit({5}, (2, 4), "col")
    assert el.shrunken_shape((2, 4), col_dead, "col") == (2, 3)
    assert el.shrunken_shape((8,), {3}, "rank") == (7,)
    two_cols = el.expand_fail_unit({0, 7}, (2, 4), "col")  # cols 0 and 3
    assert el.shrunken_shape((2, 4), two_cols, "col") == (2, 2)


@pytest.mark.parametrize("shape,failed,unit", [
    ((2, 4), {5}, "row"),
    ((2, 4), {5}, "col"),
    ((4, 2), {0}, "row"),
    ((3, 3), {4}, "col"),
    ((2, 4), {1, 6}, "col"),
])
def test_compact_rank_map_is_the_2d_row_major_renumbering(
        shape, failed, unit):
    """Dropping whole grid lines keeps the survivors' row-major order =
    the shrunken grid's row-major numbering: compact_rank_map over the
    expanded set IS the 2-D renumbering, with no special casing."""
    rows, cols = shape
    world = rows * cols
    dead = el.expand_fail_unit(failed, shape, unit)
    rmap = el.compact_rank_map(world, dead)
    new_shape = el.shrunken_shape(shape, dead, unit)
    # enumerate the shrunken grid row-major and check each survivor maps
    # to its position in it
    dead_rows = {r // cols for r in dead} if unit == "row" else set()
    dead_cols = {r % cols for r in dead} if unit == "col" else set()
    expect = {}
    new = 0
    for i in range(rows):
        if i in dead_rows:
            continue
        for j in range(cols):
            if j in dead_cols:
                continue
            expect[i * cols + j] = new
            new += 1
    assert rmap == expect
    assert len(rmap) == new_shape[0] * new_shape[1]


def test_shrink_groups_on_an_expanded_row():
    # column sub-comms of a 2x4 grid: group g = {g, g+4}
    groups = tuple((j, j + 4) for j in range(4))
    dead = el.expand_fail_unit({5}, (2, 4), "row")       # row 1 gone
    # every column group loses its row-1 member; survivors renumber 0..3
    assert el.shrink_groups(groups, dead, 8) == ((0,), (1,), (2,), (3,))
    # row sub-comms: group 1 disappears wholesale
    rows = ((0, 1, 2, 3), (4, 5, 6, 7))
    assert el.shrink_groups(rows, dead, 8) == ((0, 1, 2, 3),)


# ---------------------------------------------------------------------------
# epoch history + cache-token pins
# ---------------------------------------------------------------------------


def test_epoch_history_records_world_deltas():
    assert el.epoch_history() == []
    el.advance_epoch(world=7, cause="failure", detail="rank 3 died")
    el.advance_epoch(world=8, cause="join", detail="1 replacement")
    hist = el.epoch_history()
    assert [h["epoch"] for h in hist] == [1, 2]
    assert [h["cause"] for h in hist] == ["failure", "join"]
    assert [h["world"] for h in hist] == [7, 8]
    el._reset_epoch_for_tests()
    assert el.epoch_history() == []


def test_set_epoch_adopts_forward_only():
    el._set_epoch(3)
    assert el.current_epoch() == 3
    assert el.epoch_history()[-1]["cause"] == "adopt"
    el._set_epoch(3)                          # idempotent
    assert el.current_epoch() == 3
    with pytest.raises(ValueError, match="backwards"):
        el._set_epoch(1)


def test_cache_token_is_the_pre_change_literal_with_flags_off():
    """The PR 1-8 contract, pinned byte-for-byte: with every elastic
    knob at its default the elastic token is the plain epoch int and the
    resilience cache token is EXACTLY the tuple previous releases
    produced — both program-cache keys are unchanged."""
    assert el.elastic_cache_token() == 0
    assert rt.cache_token() == (None, "", False, False, 0)
    el.advance_epoch()
    assert el.elastic_cache_token() == 1
    assert rt.cache_token() == (None, "", False, False, 1)


def test_elastic_cache_token_folds_every_new_knob():
    base = el.elastic_cache_token()
    for name, value in (
        ("MPI4JAX_TPU_ELASTIC_GROW", "1"),
        ("MPI4JAX_TPU_DRAIN_GRACE_S", "9.5"),
        ("MPI4JAX_TPU_ELASTIC_FAIL_UNIT", "row"),
        ("MPI4JAX_TPU_ELASTIC_PORT_SPAN", "16"),
    ):
        os.environ[name] = value
        tok = el.elastic_cache_token()
        assert tok != base, name
        assert isinstance(tok, tuple) and tok[0] == el.current_epoch()
        del os.environ[name]
    assert el.elastic_cache_token() == base


def test_new_elastic_flags_parse_and_validate():
    assert config.elastic_grow() is False
    assert config.drain_grace_s() == 5.0
    assert config.elastic_fail_unit() == "rank"
    os.environ["MPI4JAX_TPU_ELASTIC_GROW"] = "yes"
    os.environ["MPI4JAX_TPU_DRAIN_GRACE_S"] = "2.5"
    os.environ["MPI4JAX_TPU_ELASTIC_FAIL_UNIT"] = "col"
    assert config.elastic_grow() is True
    assert config.drain_grace_s() == 2.5
    assert config.elastic_fail_unit() == "col"
    os.environ["MPI4JAX_TPU_DRAIN_GRACE_S"] = "0"
    with pytest.raises(ValueError, match="DRAIN_GRACE_S"):
        config.drain_grace_s()
    os.environ["MPI4JAX_TPU_ELASTIC_FAIL_UNIT"] = "diagonal"
    with pytest.raises(ValueError, match="FAIL_UNIT"):
        config.elastic_fail_unit()


# ---------------------------------------------------------------------------
# the preempt fault verb
# ---------------------------------------------------------------------------


def test_preempt_spec_parses_and_round_trips():
    (c,) = fi.parse_fault_spec("preempt:rank=3:after=4:grace=2")
    assert (c.verb, c.rank, c.after, c.grace) == ("preempt", 3, 4, 2.0)
    canon = fi.canonical_spec((c,))
    assert canon == "preempt:rank=3:after=4:grace=2"
    assert fi.parse_fault_spec(canon) == (c,)
    # bare preempt: every rank, immediately, flag-default grace
    (c,) = fi.parse_fault_spec("preempt")
    assert (c.rank, c.op, c.after, c.grace) == (None, None, 0, None)
    assert c.canonical() == "preempt"


def test_preempt_spec_rejects_misplaced_args():
    with pytest.raises(ValueError, match="grace"):
        fi.parse_fault_spec("die:grace=2")
    with pytest.raises(ValueError, match="secs"):
        fi.parse_fault_spec("preempt:secs=2")
    with pytest.raises(ValueError, match="bare field"):
        fi.parse_fault_spec("preempt:nan")
    with pytest.raises(ValueError, match="grace must be > 0"):
        fi.parse_fault_spec("preempt:grace=0")


def test_preempt_probe_posts_a_drain_notice():
    (c,) = fi.parse_fault_spec("preempt:rank=1:after=1:grace=3")
    indexed = ((0, c),)
    assert fi.probe_host(indexed, "MPI_Allreduce", 0) == 0   # wrong rank
    assert el.take_pending_drain() is None
    assert fi.probe_host(indexed, "MPI_Allreduce", 1) == 0   # clean window
    assert el.take_pending_drain() is None
    assert fi.probe_host(indexed, "MPI_Allreduce", 1) == 0   # fires
    drain = el.take_pending_drain()
    assert drain == {"rank": 1, "grace": 3.0}
    # the collective itself proceeds (no mask bit, process alive) and a
    # second notice while one is pending does not duplicate
    fi.probe_host(indexed, "MPI_Allreduce", 1)
    el.request_drain(rank=2)
    fi.probe_host(indexed, "MPI_Allreduce", 1)
    assert el.take_pending_drain()["rank"] == 1
    assert el.take_pending_drain() is None


# ---------------------------------------------------------------------------
# drain scheduling (commit-before-leave) + join admission ordering
# ---------------------------------------------------------------------------


def test_drain_forces_commit_at_the_next_boundary_then_shrinks():
    """The commit-before-leave invariant: a drain requested mid-interval
    forces an EARLY commit at the next step boundary (off the
    commit_every cadence), then executes the planned shrink — one epoch,
    no restore (survivor state is live), loop continues to the budget."""
    store = _FakeStore()
    seen = []

    def step_fn(state, step, comm):
        seen.append((step, comm.world_size()))
        if step == 2 and store.shrunk_with is None:
            el.request_drain(rank=3)
        return state + 1

    out = el.run(step_fn, 0, store, steps=6, commit_every=2)
    assert out == 6
    # commit at 0 and 2 (cadence), then the FORCED commit at 3 (the
    # drain boundary), then the cadence again on the shrunken world
    assert store.commits == [0, 2, 3, 4, 6]
    assert store.shrunk_with == frozenset({3})
    assert store.shrunk_unit == "rank"
    assert store.restores == 0                  # drains never restore
    assert el.current_epoch() == 1
    assert el.epoch_history()[-1]["cause"] == "drain"
    # no step was replayed: the drain is planned, not a failure — the
    # world shrinks at the boundary right after the notice landed
    assert seen == [(0, 4), (1, 4), (2, 4),
                    (3, 3), (4, 3), (5, 3)]
    assert el.take_peer_drain() is None


def test_drain_without_a_rank_needs_a_multiprocess_world():
    store = _FakeStore()

    def step_fn(state, step, comm):
        el.request_drain()
        return state

    with pytest.raises(RuntimeError, match="multi-process"):
        el.run(step_fn, 0, store, steps=2)


def test_join_admitted_at_the_next_commit_boundary_only():
    """Admission ordering: joiners posted mid-interval wait for the next
    COMMIT boundary (the state streamed to them is the committed one),
    then the world grows by the full pending count at once."""
    store = _FakeStore()
    seen = []

    def step_fn(state, step, comm):
        seen.append((step, comm.world_size()))
        if step == 0:
            el.post_simulated_join(2)
        return state + 1

    out = el.run(step_fn, 0, store, steps=6, commit_every=3)
    assert out == 6
    # posted during step 0, but steps 1 and 2 still run at world 4 —
    # admission waits for the step-3 commit boundary
    assert seen == [(0, 4), (1, 4), (2, 4),
                    (3, 6), (4, 6), (5, 6)]
    assert store.grown_by == 2
    assert store.restores == 1                 # the cold-join restore
    assert el.current_epoch() == 1
    assert el.epoch_history()[-1]["cause"] == "join"
    assert el.pending_join_count() == 0


def test_join_and_drain_at_one_boundary_drain_wins():
    """A drain scheduled for a boundary takes priority; the join is
    admitted at the following commit boundary."""
    store = _FakeStore()

    def step_fn(state, step, comm):
        if step == 0:
            el.post_simulated_join(1)
            el.request_drain(rank=3)
        return state + 1

    out = el.run(step_fn, 0, store, steps=4, commit_every=1)
    assert out == 4
    assert store.shrunk_with == frozenset({3})
    assert store.grown_by == 1
    assert el.current_epoch() == 2             # drain epoch, then join epoch
    assert [h["cause"] for h in el.epoch_history()] == ["drain", "join"]


# ---------------------------------------------------------------------------
# cold-join: describe/adopt + the zero-contribution exchange
# ---------------------------------------------------------------------------


class _SizedComm:
    uid = 4242

    def __init__(self, k):
        self._k = k

    def world_size(self):
        return self._k


def test_describe_adopt_commit_round_trips_through_json():
    import json as _json

    os.environ["MPI4JAX_TPU_ELASTIC_GROW"] = "1"   # spec computed on commit
    state = _state()
    store = el.ShardStore(_SizedComm(4), redundancy=1, rank=0)
    store.commit(7, state)
    assert store.can_describe_commit()
    desc = _json.loads(_json.dumps(store.describe_commit()))
    assert desc["step"] == 7 and desc["k"] == 4
    assert "shards" not in desc                # geometry only, no payload
    cold = el.ShardStore(_SizedComm(4), redundancy=1, rank=4)
    cold.adopt_commit(desc)
    assert cold.committed_step == 7
    rec = cold._committed
    assert rec["shards"] == {} and rec["cold"] is True
    assert rec["meta"] == store._committed["meta"]


def test_cold_join_exchange_reassembles_bit_identical():
    """The cold-join branch of the restore exchange, simulated purely:
    every old rank contributes the shards the plan makes it provider of,
    the cold joiner contributes ZEROS, and the SUM reassembles the
    committed state bit-identically on every rank (one contributor per
    shard, so sum is placement)."""
    os.environ["MPI4JAX_TPU_ELASTIC_GROW"] = "1"
    state = _state()
    k = 4
    stores = {r: el.ShardStore(_SizedComm(k), redundancy=1, rank=r)
              for r in range(k)}
    for s in stores.values():
        s.commit(7, state)
    desc = stores[0].describe_commit()
    cold = el.ShardStore(_SizedComm(k + 1), redundancy=1, rank=k)
    cold.adopt_commit(desc)

    plan = el.reconstruction_plan((), k, 1)
    contribs = [s.exchange_contribution(s._committed, plan)
                for s in stores.values()]
    cold_contrib = cold.exchange_contribution(cold._committed, plan)
    assert not cold_contrib.any()              # the joiner supplies zeros
    total = sum(c.astype(np.int64) for c in contribs) + cold_contrib
    assert total.max() <= 255                  # one contributor per shard
    buf = total.astype(np.uint8)
    rec = cold._committed
    nbytes = sum(m[2] for m in rec["meta"])
    restored = el._unflatten_state(
        rec["treedef"], el.unpack_leaves(buf[:nbytes], rec["meta"]))
    _assert_state_equal(state, restored)


def test_describe_commit_refuses_undescribable_structures():
    # with grow off the spec is never computed (hot-path cost gating);
    # with a custom pytree node it validates to None — either way the
    # description refuses loudly and can_describe_commit gates admission
    store = el.ShardStore(_SizedComm(2), redundancy=1, rank=0)
    store.commit(1, _state())
    assert store._committed["pure_spec"] is None   # grow off: not computed
    assert not store.can_describe_commit()
    with pytest.raises(RuntimeError, match="not.*JSON-able"):
        store.describe_commit()


def test_restore_skips_feasibility_check_when_all_shards_are_local():
    """A single-controller store (holding every shard) restores locally
    even when a whole contiguous replica block died — the row-shrink
    case that would falsely trip the neighbor-replication budget."""
    state = _state()

    class _All:
        uid = 9
        mesh = None

        def world_size(self):
            return 8

    store = el.ShardStore(_All(), redundancy=1)   # no rank pin: holds all
    store.commit(3, state)
    assert store.held_shards() == tuple(range(8))
    step, restored = store.restore({4, 5, 6, 7})  # adjacent block dead
    assert step == 3
    _assert_state_equal(state, restored)


# ---------------------------------------------------------------------------
# drained-comm registry (MPX127's ground truth)
# ---------------------------------------------------------------------------


def test_drained_comm_registry_transitions():
    comm = _FakeComm(4)
    assert el.comm_draining(comm) is None
    assert not el.comm_drained(comm)
    el.mark_comm_draining(comm, 7)
    assert el.comm_draining(comm) == 7
    assert not el.comm_drained(comm)           # legal through the boundary
    el.seal_drained_comm(comm)
    assert el.comm_drained(comm)               # MPX127 territory
    assert el.comm_draining(comm) is None
    el._reset_epoch_for_tests()
    assert not el.comm_drained(comm)


# ---------------------------------------------------------------------------
# the control/join TCP protocol on localhost
# ---------------------------------------------------------------------------


def _free_port_base():
    import socket

    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def test_control_server_acks_drain_notices():
    """The planned-drain announcement: notify_drain reaches every peer's
    control listener, each acks immediately (the leaver's proof nobody
    can race past the boundary), and the notice lands in the peer-drain
    slot the run loop consumes."""
    os.environ["MPI4JAX_TPU_ELASTIC_PORT_SPAN"] = "8"
    base = _free_port_base()
    servers = [el._ControlServer("localhost", el.control_port(base, r, 0))
               for r in (1, 2)]
    try:
        unacked = el.notify_drain("localhost", base, 0, 3, boundary=7,
                                  epoch=0, grace=10.0)
        assert unacked == []
        assert el.peek_peer_drain() == {"rank": 0, "boundary": 7}
    finally:
        for srv in servers:
            srv.stop()
    # a dead peer never acks: it is reported, the drain proceeds anyway
    # (epoch 1's control bank was never bound — nobody listens there)
    el.take_peer_drain()
    assert el.notify_drain("localhost", base, 0, 2, boundary=3,
                           epoch=1, grace=0.5) == [1]


def test_join_server_parks_request_and_admit_round_trips():
    """The join handshake end to end on localhost: request_join scans
    the declared port window for the live epoch's listener (it does not
    know the epoch), parks on the connection, and receives the admit
    message the coordinator sends at the boundary."""
    os.environ["MPI4JAX_TPU_ELASTIC_PORT_SPAN"] = "8"
    base = _free_port_base()
    srv = el._JoinServer("localhost", el.join_port(base, 3))  # epoch 3
    result = {}

    def joiner():
        result["admit"] = el.request_join("localhost", base, timeout=20.0)

    t = threading.Thread(target=joiner, daemon=True)
    try:
        t.start()
        deadline = time.monotonic() + 15.0
        while el.pending_join_count() == 0 and time.monotonic() < deadline:
            time.sleep(0.05)
        (parked,) = el._take_pending_joins()
        assert parked["info"]["kind"] == "join"
        admit = {"kind": "admit", "epoch": 4, "process_id": 3,
                 "num_processes": 4, "step": 6,
                 "commit": {"k": 3}, "axes": ["i"]}
        el._send_json(parked["conn"], admit)
        parked["conn"].close()
        t.join(timeout=20.0)
        assert result["admit"]["process_id"] == 3
        assert result["admit"]["num_processes"] == 4
        assert result["admit"]["commit"] == {"k": 3}
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# watchdog expiry suspension (planned-reconfiguration windows)
# ---------------------------------------------------------------------------


def test_suspend_expiries_masks_detection_and_nests():
    now = [100.0]
    reg = wd._Registry(on_timeout=lambda e, x: None, clock=lambda: now[0])
    reg.arm("MPI_Allreduce", "ffff0001", 0, "('i',)", timeout=1.0)
    now[0] += 5.0
    assert reg.check_expired() is not None
    with wd.suspend_expiries():
        assert reg.check_expired() is None
        with wd.suspend_expiries():            # windows nest
            assert reg.check_expired() is None
        assert reg.check_expired() is None     # still inside the outer
    assert reg.check_expired() is not None     # coverage resumes
    assert reg.drain() == 1


# ---------------------------------------------------------------------------
# striped replica placement (PR 16 tentpole a)
# ---------------------------------------------------------------------------


def test_stripe_placement_goldens_2x4_4x2_8x1():
    # 2 hosts x 4 ranks, redundancy 1: the replica always lands exactly
    # one host over, same local index — (s, s+4 mod 8)
    assert el.stripe_placement(8, 1, (4, 4)) == tuple(
        (s, (s + 4) % 8) for s in range(8))
    # 4 hosts x 2 ranks, redundancy 1: one host over, same local slot
    assert el.stripe_placement(8, 1, (2, 2, 2, 2)) == tuple(
        (s, (s + 2) % 8) for s in range(8))
    # 8 hosts x 1 rank: every rank is its own host — the stripe IS the
    # neighbor ring
    assert el.stripe_placement(8, 1, (1,) * 8) == el.neighbor_placement(8, 1)
    # redundancy 2 on 4x2: replicas on the next TWO hosts over
    assert el.stripe_placement(8, 2, (2, 2, 2, 2))[0] == (0, 2, 4)


def test_stripe_placement_degrades_to_neighbor_without_topology():
    for k, r in ((8, 1), (5, 2), (3, 0)):
        assert el.stripe_placement(k, r, None) == el.neighbor_placement(k, r)
        # single host: nothing to stripe over
        assert el.stripe_placement(k, r, (k,)) == el.neighbor_placement(k, r)


def test_stripe_placement_accepts_spec_strings_and_topology_objects():
    assert el.stripe_placement(8, 1, "2x4") == el.stripe_placement(
        8, 1, (4, 4))

    class _Topo:
        host_of_rank = (0, 0, 0, 0, 1, 1, 1, 1)

    assert el.stripe_placement(8, 1, _Topo()) == el.stripe_placement(
        8, 1, (4, 4))
    # a topology that does not cover k ranks is ignored (neighbor)
    assert el.stripe_placement(8, 1, (4, 3)) == el.neighbor_placement(8, 1)


@pytest.mark.parametrize("counts", [
    (4, 4), (2, 2, 2, 2), (3, 5), (1, 7), (2, 3, 3), (4, 4, 4, 4),
    (8, 8, 8, 8, 8, 8, 8, 8),
])
@pytest.mark.parametrize("redundancy", [1, 2])
def test_stripe_placement_survives_any_single_host_loss(counts, redundancy):
    """The proof-style property: redundancy >= 1 and hosts >= 2 =>
    every single-host loss leaves every shard a live copy."""
    k = sum(counts)
    table = el.stripe_placement(k, redundancy, counts)
    host_of = [h for h, c in enumerate(counts) for _ in range(c)]
    # structure: owner first, all holders distinct, owner's replicas
    # off-host while hosts allow
    hosts = len(counts)
    for s, holders in enumerate(table):
        assert holders[0] == s
        assert len(set(holders)) == len(holders)
        if redundancy < hosts:
            assert len({host_of[r] for r in holders}) == len(holders), (
                s, holders)
    for h in range(hosts):
        dead = {r for r in range(k) if host_of[r] == h}
        assert el.placement_recoverable(dead, table), (h, dead)
        plan = el.plan_from_placement(dead, table)
        assert set(plan) == set(range(k))
        assert all(p not in dead for p in plan.values())


def test_stripe_placement_warns_and_degrades_when_redundancy_ge_hosts():
    with pytest.warns(RuntimeWarning, match="redundancy 2 >= hosts 2"):
        table = el.stripe_placement(8, 2, (4, 4))
    # still recoverable after a single-host loss, and copies stay on
    # distinct ranks
    for holders in table:
        assert len(set(holders)) == 3
    assert el.placement_recoverable(set(range(4)), table)
    assert el.placement_recoverable(set(range(4, 8)), table)


def test_neighbor_placement_dies_on_host_row_where_stripe_survives():
    """The PR's headline contrast, at both acceptance topologies."""
    for counts in ((4, 4), (2, 2, 2, 2)):
        k = sum(counts)
        host_of = [h for h, c in enumerate(counts) for _ in range(c)]
        row = {r for r in range(k) if host_of[r] == 1}
        stripe = el.stripe_placement(k, 1, counts)
        assert el.placement_recoverable(row, stripe)
        neighbor = el.neighbor_placement(k, 1)
        assert not el.placement_recoverable(row, neighbor)
        with pytest.raises(el.RankFailure, match="unrecoverable"):
            el.plan_from_placement(row, neighbor)


def test_reconstruction_plan_validates_placement_length():
    with pytest.raises(ValueError, match="covers 4 shards, expected 8"):
        el.reconstruction_plan({1}, 8, 1, el.neighbor_placement(4, 1))


def test_shardstore_commit_records_stripe_and_restore_follows_it():
    """Kill a whole host row; per-rank stores committed under the stripe
    reassemble bit-identically — the end-to-end form of the golden."""
    state = _state()
    for counts in ((4, 4), (2, 2, 2, 2)):
        k = sum(counts)

        class _C:
            def world_size(self, _k=k):
                return _k

        stores = {}
        for r in range(k):
            stores[r] = el.ShardStore(_C(), redundancy=1, rank=r,
                                      topology=counts, placement="stripe")
            stores[r].commit(4, state)
        table = el.stripe_placement(k, 1, counts)
        assert stores[0]._committed["placement"] == table
        host_of = [h for h, c in enumerate(counts) for _ in range(c)]
        row = {r for r in range(k) if host_of[r] == 1}
        step, restored = el.reassemble_from_stores(stores, row)
        assert step == 4
        _assert_state_equal(state, restored)


def test_shardstore_placement_mode_flag_and_override():
    class _C:
        def world_size(self):
            return 8

    # flag default is stripe; without topology the table degrades
    store = el.ShardStore(_C(), redundancy=1, rank=0)
    assert store.placement_mode() == "stripe"
    assert store.placement_table(8) == el.neighbor_placement(8, 1)
    os.environ["MPI4JAX_TPU_ELASTIC_PLACEMENT"] = "neighbor"
    assert store.placement_mode() == "neighbor"
    # constructor override beats the flag
    store2 = el.ShardStore(_C(), redundancy=1, rank=0, placement="stripe",
                           topology=(4, 4))
    assert store2.placement_mode() == "stripe"
    assert store2.placement_table(8) == el.stripe_placement(8, 1, (4, 4))
    with pytest.raises(ValueError, match="placement"):
        el.ShardStore(_C(), placement="diagonal")


def test_shardstore_topology_flag_feeds_the_stripe():
    class _C:
        def world_size(self):
            return 8

    os.environ["MPI4JAX_TPU_TOPOLOGY"] = "2x4"
    store = el.ShardStore(_C(), redundancy=1, rank=0)
    assert store.placement_table(8) == el.stripe_placement(8, 1, (4, 4))
    # spec not covering k: ignored, neighbor fallback (never an error)
    assert store.placement_table(6) == el.neighbor_placement(6, 1)


def test_shardstore_non_divisible_sizes_restore_bit_identical():
    """Satellite: shard sizes that do not divide the payload (padding
    path) restore exactly, striped and neighbor alike."""
    state = {"odd": np.arange(131, dtype=np.float64),   # 1048 bytes
             "tiny": np.float32(7.0)}                   # + 4 -> 1052
    for placement, counts in (("stripe", (3, 5)), ("neighbor", None)):
        k = 8

        class _C:
            def world_size(self):
                return 8

        stores = {}
        for r in range(k):
            stores[r] = el.ShardStore(_C(), redundancy=2, rank=r,
                                      topology=counts, placement=placement)
            stores[r].commit(9, state)
        rec = stores[0]._committed
        assert rec["shard"] * k > rec["nbytes"]  # genuinely padded
        step, restored = el.reassemble_from_stores(stores, {0, 5})
        assert step == 9
        np.testing.assert_array_equal(state["odd"], restored["odd"])
        np.testing.assert_array_equal(state["tiny"], restored["tiny"])


def test_describe_adopt_commit_carries_the_placement_table():
    class _C:
        def world_size(self):
            return 8

    os.environ["MPI4JAX_TPU_ELASTIC_GROW"] = "1"
    store = el.ShardStore(_C(), redundancy=1, rank=0, topology=(4, 4))
    store.commit(2, {"w": np.arange(8, dtype=np.float32)})
    desc = store.describe_commit()
    assert desc["placement"] == [list(h)
                                 for h in el.stripe_placement(8, 1, (4, 4))]
    joiner = el.ShardStore(_C(), redundancy=1, rank=7)
    joiner.adopt_commit(desc)
    assert joiner._committed["placement"] == el.stripe_placement(8, 1, (4, 4))
    # restore_plan follows the RECORDED table, not current flags
    os.environ["MPI4JAX_TPU_ELASTIC_PLACEMENT"] = "neighbor"
    assert joiner.restore_plan({4}) == el.plan_from_placement(
        {4}, el.stripe_placement(8, 1, (4, 4)))
    # a description without a placement (older peer) falls back to the
    # neighbor table
    del desc["placement"]
    joiner.adopt_commit(desc)
    assert joiner._committed["placement"] == el.neighbor_placement(8, 1)


# ---------------------------------------------------------------------------
# gossip edge cases + coordinator agreement (PR 16 tentpole b)
# ---------------------------------------------------------------------------


def test_gossip_agreement_rejects_out_of_range_suspects():
    with pytest.raises(ValueError, match="outside the world"):
        el.gossip_agreement({0: {9}}, _links(4))


def test_gossip_agreement_unnamed_death_under_partition_converges():
    """Satellite fix: rank 0 knows 3 died; rank 0 is ALSO partitioned
    from 1 (so 1 hearsay-suspects 0 before reading its gossip).  The old
    'skip suspected peers' rule lost {3} at rank 1 depending on
    evaluation order; the inbox-union semantics must propagate it
    through rank 2."""
    links = _links(4, down=(3,), cut=[(0, 1)])
    agreed = el.gossip_agreement({0: {3}, 1: set(), 2: set()}, links)
    # every survivor converges on the SAME set (agreement), which names
    # the true casualty 3 — the old rule could leave 1 missing {3}
    # entirely — plus, conservatively, BOTH endpoints of the cut link
    # (hearsay-transitive suspicion; 0 and 1 see themselves in the
    # verdict and abort, the runtime's declared-failed-by-peers path)
    assert agreed[0] == agreed[1] == agreed[2] == frozenset({0, 1, 3})


def test_gossip_agreement_late_arriving_suspect_is_idempotent():
    """A suspect learned only via hearsay must survive re-running the
    fixpoint on the converged output (idempotence = convergence)."""
    links = _links(6, down=(5,))
    first = el.gossip_agreement({2: {4}}, links)
    for r in range(5):
        assert first[r] == frozenset({4, 5})
    again = el.gossip_agreement(
        {r: first[r] for r in range(5)}, links)
    for r in range(5):
        assert again[r] == first[r]


def test_gossip_agreement_empty_suspects_everywhere_names_the_dead():
    # nobody can NAME the casualty ("something died but unnamed"), and
    # the survivor component is additionally partitioned pairwise — the
    # link evidence alone must still converge the majority side
    links = _links(5, down=(4,), cut=[(0, 1)])
    agreed = el.gossip_agreement({r: set() for r in range(4)}, links)
    # one identical verdict across the component, naming the true
    # casualty plus both endpoints of the cut (conservative); the
    # majority guard still passes for the untainted survivors
    for r in range(4):
        assert agreed[r] == frozenset({0, 1, 4}), (r, agreed[r])
    assert el.majority_survives(agreed[2], 5) is False  # 2 of 5 left
    # with a larger component the same cut keeps a working majority
    big = el.gossip_agreement({r: set() for r in range(7)},
                              _links(8, down=(7,), cut=[(0, 1)]))
    assert big[2] == frozenset({0, 1, 7})
    assert el.majority_survives(big[2], 8)


@pytest.mark.parametrize("world,down,suspects", [
    (8, (6, 7), {0: {6}, 1: {7}}),
    (8, (5,), {}),
    (4, (3,), {0: {3}, 1: set(), 2: set()}),
    (16, (2, 9, 10), {4: {2}}),
    (8, (0,), {3: {0}}),              # the coordinator itself dies
    (8, (0, 4), {}),                  # coordinator + mid-world, unnamed
])
def test_coordinator_agreement_matches_gossip_fixpoint(
        world, down, suspects):
    """The arbiter pin: the O(k) star equals the gossip fixpoint for
    every survivor, on every drill-shaped matrix — including when the
    coordinator is among the dead (full degradation)."""
    links = _links(world, down=down)
    gossip = el.gossip_agreement(suspects, links)
    coord = el.coordinator_agreement(suspects, links)
    for r in range(world):
        if r in down:
            continue
        assert coord[r] == gossip[r], (r, coord[r], gossip[r])
        assert coord[r] == frozenset(down)


def test_coordinator_agreement_locally_suspected_coordinator_degrades():
    # rank 2 names the (live) coordinator a suspect: it must not park at
    # rank 0; it degrades to gossip and conservatively suspects the star
    links = _links(4)
    out = el.coordinator_agreement({2: {0}}, links)
    # star members converge on a verdict containing the degraded rank
    assert out[0] == out[1] == out[3]
    assert 2 in out[0]
    # the degraded rank, gossiping alone against a masked star, suspects
    # everyone else — conservative, resolved by the majority guard
    assert out[2] == frozenset({0, 1, 3})
    assert not el.majority_survives(out[2], 4)


def test_coordinator_exchange_suspects_tcp_star_converges():
    """The TCP star on localhost: 3 survivors of 4 (rank 3 dead), rank 2
    with the empty 'unnamed' set, all converge on {3} — and the
    coordinator answers every reporter with the same verdict."""
    base = _free_port_base()
    world = 4
    suspects = {0: set(), 1: {3}, 2: set()}
    results = {}

    def worker(rank):
        results[rank] = el.coordinator_exchange_suspects(
            rank, world, suspects[rank], "localhost", base, timeout=5.0)

    threads = [threading.Thread(target=worker, args=(r,))
               for r in range(3)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    assert results == {r: frozenset({3}) for r in range(3)}, results


def test_coordinator_exchange_suspects_reporter_fails_without_listener():
    base = _free_port_base()
    with pytest.raises(RuntimeError, match="suspect report"):
        el.coordinator_exchange_suspects(
            1, 4, {3}, "localhost", base, timeout=0.6)


def test_negotiate_failed_falls_back_to_gossip_when_coordinator_dead():
    """Ranks 1 and 2 survive a 3-rank world whose coordinator (0) died:
    the star phase times out and BOTH degrade to the gossip round,
    agreeing on {0}."""
    agree_base = _free_port_base()
    gossip_base = _free_port_base()
    results = {}

    def worker(rank, suspects):
        results[rank] = el.negotiate_failed(
            rank, 3, suspects, "localhost",
            agree_port_no=agree_base,
            gossip_port_base=gossip_base,
            timeout=4.0, mode="coordinator")

    threads = [threading.Thread(target=worker, args=(1, {0})),
               threading.Thread(target=worker, args=(2, set()))]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    assert results == {1: frozenset({0}), 2: frozenset({0})}, results


def test_negotiate_failed_gossip_mode_skips_the_star():
    gossip_base = _free_port_base()
    results = {}

    def worker(rank, suspects):
        results[rank] = el.negotiate_failed(
            rank, 3, suspects, "localhost",
            agree_port_no=1,  # invalid on purpose: must never be dialed
            gossip_port_base=gossip_base,
            timeout=5.0, mode="gossip")

    threads = [threading.Thread(target=worker, args=(0, {2})),
               threading.Thread(target=worker, args=(1, set()))]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    assert results == {0: frozenset({2}), 1: frozenset({2})}, results


def test_agreement_mode_flag_defaults_and_parses():
    assert config.elastic_agreement() == "coordinator"
    os.environ["MPI4JAX_TPU_ELASTIC_AGREEMENT"] = "gossip"
    assert config.elastic_agreement() == "gossip"
    os.environ["MPI4JAX_TPU_ELASTIC_AGREEMENT"] = "star"
    with pytest.raises(ValueError):
        config.elastic_agreement()
    assert config.elastic_placement() == "stripe"


def test_agree_port_gets_its_own_bank():
    span = 64
    a = el.agree_port(9000, 3, span)
    assert a == 9000 + 4 * span + 3
    # wraps within the span window like every other bank
    assert el.agree_port(9000, span + 3, span) == a
    # disjoint from coordinator/join/control banks for every epoch
    assert a >= 9000 + 4 * span
    assert el.control_port(9000, span - 1, 1, span) < 9000 + 4 * span
