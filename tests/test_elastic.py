"""Traced half of the elastic-recovery suite (docs/resilience.md
"Elastic recovery"): everything that needs real traces on the 8-device
virtual CPU mesh.

- the epoch→retrace pin: advancing the communication epoch must MISS
  both program caches (spmd and eager) so no old-world executable can
  replay — while the re-traced HLO at an unchanged world stays
  byte-identical (the epoch lives in cache keys, not in programs);
- HLO byte-identity with the elastic layer idle (epoch 0);
- the 8-device shrink test: ``elastic.run`` survives a simulated rank
  loss, finishes the step budget on 7 devices, and the post-restore
  losses match a clean 7-device run from the restored state onward —
  the ISSUE's acceptance equality;
- ShardStore commit/restore bit-identity through jax state;
- ``Comm.shrink`` / ``GroupComm.shrink`` semantics + collectives over a
  shrunk comm;
- MPX126 (collective on a revoked epoch) positive and negative, through
  ``mpx.analyze`` and the ambient env=error mode.

The pure protocol half (ownership maps, agreement, packing) runs under
any JAX in tests/test_elastic_pure.py via the isolated loader.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import mpi4jax_tpu as mpx
from mpi4jax_tpu.parallel.mesh import shrink_world_mesh
from mpi4jax_tpu.resilience import elastic as el

pytestmark = pytest.mark.faults


@pytest.fixture(autouse=True)
def _clean_elastic_state():
    """Every test starts and ends at epoch 0 with the stock default mesh,
    an empty pending-failure slot, and cold program caches — an elastic
    shrink mutates all of those."""
    el._reset_epoch_for_tests()
    el.take_pending_failure()
    mpx.set_default_mesh(None)
    mpx.clear_caches()
    yield
    el._reset_epoch_for_tests()
    el.take_pending_failure()
    mpx.set_default_mesh(None)
    mpx.clear_caches()
    from mpi4jax_tpu.parallel import region as _region

    _region._default_comm = None


def _world_comm():
    mesh = mpx.make_world_mesh()
    return mpx.Comm(mesh.axis_names[0], mesh=mesh)


# ---------------------------------------------------------------------------
# epoch -> cache keys (the revocation pin)
# ---------------------------------------------------------------------------


def test_epoch_advance_retraces_spmd_and_hlo_is_unchanged():
    comm = _world_comm()
    traces = []

    @mpx.spmd(comm=comm)
    def f(x):
        traces.append(1)
        res, _ = mpx.allreduce(x, op=mpx.SUM, comm=comm)
        return res

    x = jnp.ones((8, 4))
    np.testing.assert_allclose(np.asarray(f(x)), 8.0)
    np.testing.assert_allclose(np.asarray(f(x)), 8.0)
    assert len(traces) == 1                      # cached

    el.advance_epoch()                           # revoke
    np.testing.assert_allclose(np.asarray(f(x)), 8.0)
    assert len(traces) == 2                      # old program unreachable
    np.testing.assert_allclose(np.asarray(f(x)), 8.0)
    assert len(traces) == 2                      # new epoch caches again


def test_epoch_advance_misses_the_eager_cache():
    comm = _world_comm()
    x = jnp.ones((8, 4))
    mpx.allreduce(x, op=mpx.SUM, comm=comm)
    before = mpx.cache_stats()
    mpx.allreduce(x, op=mpx.SUM, comm=comm)
    mid = mpx.cache_stats()
    assert mid["hits"] == before["hits"] + 1
    el.advance_epoch()
    mpx.allreduce(x, op=mpx.SUM, comm=comm)
    after = mpx.cache_stats()
    assert after["misses"] == mid["misses"] + 1


def test_hlo_identical_at_epoch_zero_and_across_epochs():
    """The epoch is a cache-key-only knob: the lowered HLO with the
    elastic layer idle (epoch 0) is byte-identical to the HLO re-traced
    after a revocation at an unchanged world — programs never embed the
    epoch."""
    comm = _world_comm()

    @mpx.spmd(comm=comm)
    def f(x):
        res, _ = mpx.allreduce(x, op=mpx.SUM, comm=comm)
        return res

    x = jnp.ones((8, 4))
    epoch0 = jax.jit(f).lower(x).as_text()
    el.advance_epoch()
    epoch1 = jax.jit(f).lower(x).as_text()
    assert epoch0 == epoch1


def test_comm_epoch_stamping_and_inheritance():
    comm = _world_comm()
    assert comm.epoch == 0
    assert comm.Clone().epoch == 0
    el.advance_epoch()
    assert comm.epoch == 0                       # stamped at construction
    fresh = _world_comm()
    assert fresh.epoch == 1
    # derived comms inherit the parent's (stale) stamp, not the current
    assert comm.Clone().epoch == 0
    split = fresh.Split([0, 0, 0, 0, 1, 1, 1, 1])
    assert split.epoch == 1
    assert split.Clone().epoch == 1


# ---------------------------------------------------------------------------
# mesh + comm shrink
# ---------------------------------------------------------------------------


def test_shrink_world_mesh_drops_the_failed_devices():
    mesh = mpx.make_world_mesh()
    small = shrink_world_mesh(mesh, {3})
    assert tuple(small.shape.values()) == (7,)
    assert small.axis_names == mesh.axis_names
    devices = list(mesh.devices.flat)
    assert list(small.devices.flat) == devices[:3] + devices[4:]
    with pytest.raises(ValueError, match="out of range"):
        shrink_world_mesh(mesh, {8})
    grid = mpx.make_world_mesh((2, 4), ("y", "x"))
    with pytest.raises(ValueError, match="1-D"):
        shrink_world_mesh(grid, {3})


def test_comm_shrink_renumbers_and_collectives_work():
    comm = _world_comm()
    el.advance_epoch()
    small_mesh = shrink_world_mesh(comm.mesh, {3})
    small = comm.shrink({3}, mesh=small_mesh)
    assert small.Get_size() == 7
    assert small.epoch == 1
    assert small.uid != comm.uid                 # fresh matching namespace
    out, _ = mpx.allreduce(jnp.ones((7, 2)), op=mpx.SUM, comm=small)
    np.testing.assert_allclose(np.asarray(out), 7.0)
    with pytest.raises(ValueError, match="spans"):
        comm.shrink({3}, mesh=comm.mesh)         # wrong (unshrunk) mesh


def test_group_comm_shrink_preserves_partition_structure():
    comm = _world_comm()
    split = comm.Split([0, 0, 0, 0, 1, 1, 1, 1])
    assert split.groups == ((0, 1, 2, 3), (4, 5, 6, 7))
    small_mesh = shrink_world_mesh(comm.mesh, {2, 5})
    small = split.shrink({2, 5}, mesh=small_mesh)
    # survivors renumber compactly: 0,1,3 -> 0,1,2 ; 4,6,7 -> 3,4,5
    assert small.groups == ((0, 1, 2), (3, 4, 5))
    assert small.Get_size() == 3                 # uniform group size
    # per-group allreduce over the shrunk split: each group sums itself
    vals = jnp.arange(6, dtype=jnp.float32)[:, None]
    out, _ = mpx.allreduce(vals, op=mpx.SUM, comm=small)
    np.testing.assert_allclose(
        np.asarray(out)[:, 0], [3, 3, 3, 12, 12, 12])


# ---------------------------------------------------------------------------
# ShardStore through jax state
# ---------------------------------------------------------------------------


def _jax_state():
    return {
        "w": jnp.arange(24, dtype=jnp.float32).reshape(4, 6) / 7.0,
        "opt": [jnp.ones((3,), jnp.float64), jnp.int32(17)],
    }


def test_shardstore_commit_restore_round_trip_on_device_state():
    comm = _world_comm()
    store = mpx.ShardStore(comm)
    state = _jax_state()
    store.commit(5, state)
    assert store.committed_step == 5
    step, restored = store.restore()
    assert step == 5
    np.testing.assert_array_equal(np.asarray(state["w"]),
                                  np.asarray(restored["w"]))
    np.testing.assert_array_equal(np.asarray(state["opt"][0]),
                                  np.asarray(restored["opt"][0]))
    assert int(restored["opt"][1]) == 17
    # a single-controller process holds every shard
    assert store.held_shards() == tuple(range(8))


def test_shardstore_restore_after_simulated_loss():
    comm = _world_comm()
    store = mpx.ShardStore(comm)
    state = _jax_state()
    store.commit(9, state)
    el.advance_epoch()
    store.apply_shrink({3})
    assert store.comm.Get_size() == 7
    step, restored = store.restore({3})
    assert step == 9
    np.testing.assert_array_equal(np.asarray(state["w"]),
                                  np.asarray(restored["w"]))


# ---------------------------------------------------------------------------
# the 8-device shrink drill + loss-equality acceptance
# ---------------------------------------------------------------------------


def _make_step(comm_free_losses):
    """A DP-style step over the CURRENT comm: grad allreduce + update,
    logging (step, world, loss)."""
    programs = {}

    def step_fn(state, step, comm):
        key = (comm.uid, comm.epoch)
        if key not in programs:
            size = comm.Get_size()

            @mpx.spmd(comm=comm)
            def train(params, x):
                def loss_fn(p, x):
                    return jnp.mean((x @ p) ** 2)

                loss, grad = jax.value_and_grad(loss_fn)(params, x)
                grad, _ = mpx.allreduce(grad, op=mpx.SUM, comm=comm)
                loss, _ = mpx.allreduce(loss, op=mpx.SUM, comm=comm)
                return mpx.varying((params - 0.05 * grad / size,
                                    loss / size))

            programs[key] = train

        k = comm.Get_size()
        rng = np.random.default_rng(100 + step)
        x = jnp.asarray(rng.normal(size=(k, 4, 3)).astype(np.float32))
        params_g = jnp.tile(jnp.asarray(state["p"])[None], (k, 1, 1))
        params_g, loss = programs[key](params_g, x)
        comm_free_losses.append(
            {"step": step, "world": k, "loss": float(np.asarray(loss)[0])})
        return {"p": np.asarray(params_g[0])}

    return step_fn


def test_elastic_run_survives_shrink_and_matches_clean_small_run():
    """The acceptance equality: a run that loses rank 3 at step 4 must
    (a) finish the full budget on 7 ranks at epoch 1, and (b) produce,
    from the restored step onward, exactly the losses of a CLEAN 7-rank
    run started from the committed state."""
    steps, fail_at = 8, 4
    comm = _world_comm()
    store = mpx.ShardStore(comm)
    losses = []
    base = _make_step(losses)

    committed = {}

    def failing_step(state, step, comm):
        if step == fail_at and comm.epoch == 0:
            # the failure strikes BEFORE step fail_at's work: the state
            # entering this step is exactly the store's last commit
            committed["state"] = {"p": np.array(state["p"])}
            raise mpx.RankFailure({3}, "simulated")
        return base(state, step, comm)

    p0 = np.full((3, 1), 0.5, np.float32)
    final = mpx.elastic.run(failing_step, {"p": p0}, store, steps=steps)

    assert el.current_epoch() == 1
    assert store.comm.Get_size() == 7
    last = [r for r in losses if r["step"] == steps - 1]
    assert len(last) == 1 and last[0]["world"] == 7
    # (a) the budget completed: steps fail_at..steps-1 replayed on 7 ranks
    post = [r for r in losses if r["world"] == 7]
    assert sorted({r["step"] for r in post}) == list(range(fail_at, steps))

    # (b) replay clean on 7 devices from the committed state
    el._reset_epoch_for_tests()
    mpx.set_default_mesh(None)
    mpx.clear_caches()
    small_mesh = shrink_world_mesh(mpx.make_world_mesh(), {3})
    small_comm = mpx.Comm(small_mesh.axis_names[0], mesh=small_mesh)
    clean_losses = []
    clean_step = _make_step(clean_losses)
    state = {"p": committed["state"]["p"]}
    for s in range(fail_at, steps):
        state = clean_step(state, s, small_comm)

    post_by_step = {r["step"]: r["loss"] for r in post}
    clean_by_step = {r["step"]: r["loss"] for r in clean_losses}
    assert post_by_step.keys() == clean_by_step.keys()
    for s in post_by_step:
        np.testing.assert_allclose(post_by_step[s], clean_by_step[s],
                                   rtol=1e-6)
    np.testing.assert_allclose(np.asarray(final["p"]), np.asarray(state["p"]),
                               rtol=1e-6)


def test_elastic_run_commits_and_replays_from_commit_boundary():
    comm = _world_comm()
    store = mpx.ShardStore(comm)
    seen = []

    def step_fn(state, step, comm):
        seen.append((step, comm.Get_size()))
        if step == 3 and comm.epoch == 0:
            raise mpx.RankFailure({7}, "simulated")
        return {"n": state["n"] + 1}

    out = mpx.elastic.run(step_fn, {"n": 0}, store, steps=5, commit_every=2)
    # commit at 0 and 2; failure at step 3 replays steps 2..4 on 7 ranks
    assert seen == [(0, 8), (1, 8), (2, 8), (3, 8),
                    (2, 7), (3, 7), (4, 7)]
    assert out["n"] == 5


# ---------------------------------------------------------------------------
# MPX126: collectives across a revoked epoch
# ---------------------------------------------------------------------------


def test_mpx126_flags_stale_comm_and_passes_fresh_comm():
    stale = _world_comm()

    def f(x):
        res, _ = mpx.allreduce(x, op=mpx.SUM, comm=stale)
        return res

    # negative: same epoch, clean
    report = mpx.analyze(f, jnp.ones((8, 2)), comm=stale)
    assert not [fd for fd in report.findings if fd.code == "MPX126"], (
        report.render())

    el.advance_epoch()
    report = mpx.analyze(f, jnp.ones((8, 2)), comm=stale)
    codes = [fd.code for fd in report.findings]
    assert "MPX126" in codes, report.render()
    (finding,) = [fd for fd in report.findings if fd.code == "MPX126"]
    assert "epoch" in finding.message
    assert finding.severity == "error"

    # negative after recovery: a freshly-built comm is current-epoch
    fresh = _world_comm()

    def g(x):
        res, _ = mpx.allreduce(x, op=mpx.SUM, comm=fresh)
        return res

    report = mpx.analyze(g, jnp.ones((8, 2)), comm=fresh)
    assert not [fd for fd in report.findings if fd.code == "MPX126"], (
        report.render())


def test_mpx126_fires_through_ambient_error_mode():
    stale = _world_comm()
    x = jnp.ones((8, 2))
    mpx.set_analyze_mode("error")
    try:
        out, _ = mpx.allreduce(x, op=mpx.SUM, comm=stale)  # clean at epoch 0
        np.testing.assert_allclose(np.asarray(out), 8.0)
        el.advance_epoch()
        with pytest.raises(mpx.AnalysisError, match="MPX126"):
            mpx.allreduce(x, op=mpx.SUM, comm=stale)
    finally:
        mpx.set_analyze_mode(None)


def test_elastic_run_produces_mpx126_clean_recovery():
    """The whole point of re-entering through elastic.run: the recovered
    loop's collectives run on CURRENT-epoch comms, so the verifier stays
    clean across the shrink."""
    comm = _world_comm()
    store = mpx.ShardStore(comm)

    def step_fn(state, step, comm):
        out, _ = mpx.allreduce(jnp.ones((comm.Get_size(), 2)), op=mpx.SUM,
                               comm=comm)
        assert float(np.asarray(out)[0, 0]) == comm.Get_size()
        if step == 1 and comm.epoch == 0:
            raise mpx.RankFailure({3}, "simulated")
        return state

    mpx.set_analyze_mode("error")
    try:
        mpx.elastic.run(step_fn, {"x": 1}, store, steps=3)
    finally:
        mpx.set_analyze_mode(None)
    assert el.current_epoch() == 1


# ---------------------------------------------------------------------------
# watchdog claim wiring (traced)
# ---------------------------------------------------------------------------


def _grid_comm(shape=(2, 4)):
    mesh = mpx.make_world_mesh(shape, ("y", "x"))
    return mpx.Comm(tuple(mesh.axis_names), mesh=mesh)


# ---------------------------------------------------------------------------
# cache-key + HLO pins for the new elastic knobs
# ---------------------------------------------------------------------------


def test_cache_keys_byte_identical_with_new_flags_off(monkeypatch):
    """The PR 1-8 contract for the grow/drain/fail-unit knobs: with
    every new flag at its default the elastic token is the plain epoch
    int, the resilience token is the exact pre-change tuple, and both
    program-cache keys are untouched; toggling ANY new knob changes
    them (retrace), while the lowered HLO stays byte-identical either
    way (the knobs are host-side only)."""
    from mpi4jax_tpu.ops._base import dynamic_cache_token
    from mpi4jax_tpu.resilience import runtime as rt

    assert el.elastic_cache_token() == 0
    assert rt.cache_token() == (None, "", False, False, 0)

    comm = _world_comm()

    @mpx.spmd(comm=comm)
    def f(x):
        res, _ = mpx.allreduce(x, op=mpx.SUM, comm=comm)
        return res

    x = jnp.ones((8, 4))
    base_key = dynamic_cache_token()
    base_hlo = jax.jit(f).lower(x).as_text()
    for name, value in (
        ("MPI4JAX_TPU_ELASTIC_GROW", "1"),
        ("MPI4JAX_TPU_DRAIN_GRACE_S", "9"),
        ("MPI4JAX_TPU_ELASTIC_FAIL_UNIT", "row"),
        ("MPI4JAX_TPU_ELASTIC_PORT_SPAN", "16"),
    ):
        monkeypatch.setenv(name, value)
        assert dynamic_cache_token() != base_key, name
        assert jax.jit(f).lower(x).as_text() == base_hlo, name
        monkeypatch.delenv(name)
    assert dynamic_cache_token() == base_key


# ---------------------------------------------------------------------------
# Cartesian row/column shrink
# ---------------------------------------------------------------------------


def test_shrink_world_mesh_row_and_col_units():
    grid = mpx.make_world_mesh((2, 4), ("y", "x"))
    devices = list(grid.devices.flat)
    # rank 5 = (row 1, col 1): row shrink drops ranks 4..7
    small = shrink_world_mesh(grid, {5}, "row")
    assert tuple(small.shape.values()) == (1, 4)
    assert small.axis_names == grid.axis_names
    assert list(small.devices.flat) == devices[:4]
    # col shrink drops ranks 1 and 5
    small = shrink_world_mesh(grid, {5}, "col")
    assert tuple(small.shape.values()) == (2, 3)
    assert list(small.devices.flat) == [devices[i] for i in
                                        (0, 2, 3, 4, 6, 7)]
    # rank unit still refuses ragged grids, pointing at the units
    with pytest.raises(ValueError, match="row"):
        shrink_world_mesh(grid, {5}, "rank")
    # 1-D meshes accept every unit (a row IS a rank)
    line = mpx.make_world_mesh()
    assert tuple(shrink_world_mesh(line, {3}, "row").shape.values()) == (7,)


def test_comm_shrink_across_a_row_keeps_the_grid():
    comm = _grid_comm()
    el.advance_epoch()
    removed = el.expand_fail_unit({5}, (2, 4), "row")
    small_mesh = shrink_world_mesh(comm.mesh, removed, "row")
    small = comm.shrink(removed, mesh=small_mesh)
    assert small.Get_size() == 4
    assert small.epoch == 1
    out, _ = mpx.allreduce(jnp.ones((4, 2)), op=mpx.SUM, comm=small)
    np.testing.assert_allclose(np.asarray(out), 4.0)


def test_elastic_run_row_failure_retraces_on_the_shrunken_grid(monkeypatch):
    """The Cartesian acceptance: a (2, 4) tensor x data run that loses
    rank 5 under fail_unit=row shrinks to (1, 4) — whole row removed,
    grid rectangular, budget completed at the new size, one epoch."""
    monkeypatch.setenv("MPI4JAX_TPU_ELASTIC_FAIL_UNIT", "row")
    steps, fail_at = 6, 2
    comm = _grid_comm()
    store = mpx.ShardStore(comm)
    losses = []
    base = _make_step(losses)

    def failing_step(state, step, comm):
        if step == fail_at and comm.epoch == 0:
            raise mpx.RankFailure({5}, "simulated row casualty")
        return base(state, step, comm)

    p0 = np.full((3, 1), 0.5, np.float32)
    mpx.elastic.run(failing_step, {"p": p0}, store, steps=steps)

    assert el.current_epoch() == 1
    assert tuple(store.comm.mesh.shape.values()) == (1, 4)
    assert store.comm.Get_size() == 4
    post = [r for r in losses if r["world"] == 4]
    assert sorted({r["step"] for r in post}) == list(range(fail_at, steps))
    hist = el.epoch_history()
    assert hist[-1]["cause"] == "failure"


# ---------------------------------------------------------------------------
# grow: simulated join + cold restore
# ---------------------------------------------------------------------------


def test_elastic_run_grow_after_shrink_matches_clean_run():
    """The closed loop, single-controller form of the CI grow drill:
    8 -> (rank 3 dies) -> 7 -> (replacement admitted at a commit
    boundary) -> 8, and from the admission step onward the losses match
    a CLEAN 8-rank run started from the committed state — the joiner
    received exactly the committed bytes."""
    steps, fail_at, join_at = 10, 3, 5
    comm = _world_comm()
    store = mpx.ShardStore(comm)
    losses = []
    base = _make_step(losses)
    entered = {}

    def step_fn(state, step, comm):
        if step == fail_at and comm.epoch == 0:
            raise mpx.RankFailure({3}, "simulated")
        if step == join_at and comm.Get_size() == 7:
            el.post_simulated_join(1)
        if comm.Get_size() == 8 and comm.epoch == 2 and not entered:
            entered["state"] = {"p": np.array(state["p"])}
            entered["step"] = step
        return base(state, step, comm)

    p0 = np.full((3, 1), 0.5, np.float32)
    final = mpx.elastic.run(step_fn, {"p": p0}, store, steps=steps)

    assert el.current_epoch() == 2
    assert store.comm.Get_size() == 8
    assert [h["cause"] for h in el.epoch_history()] == ["failure", "join"]
    assert el.epoch_history()[-1]["world"] == 8
    # the budget completed back at the full world size
    last = [r for r in losses if r["step"] == steps - 1]
    assert len(last) == 1 and last[0]["world"] == 8

    # replay clean on a fresh epoch-0 8-device world from the state the
    # loop re-entered with after the grow
    el._reset_epoch_for_tests()
    mpx.set_default_mesh(None)
    mpx.clear_caches()
    clean_comm = _world_comm()
    clean_losses = []
    clean_step = _make_step(clean_losses)
    state = {"p": entered["state"]["p"]}
    for s in range(entered["step"], steps):
        state = clean_step(state, s, clean_comm)

    post = {r["step"]: r["loss"] for r in losses
            if r["world"] == 8 and r["step"] >= entered["step"]}
    clean = {r["step"]: r["loss"] for r in clean_losses}
    assert post.keys() == clean.keys()
    for s in post:
        np.testing.assert_allclose(post[s], clean[s], rtol=1e-6)
    np.testing.assert_allclose(np.asarray(final["p"]), np.asarray(state["p"]),
                               rtol=1e-6)


def test_cold_join_adopted_commit_restores_bit_identical(monkeypatch):
    """The cold-join metadata path on real jax state: describe_commit's
    JSON round trip + adopt_commit reproduce a record through which the
    exchanged bytes unpack to the EXACT committed state (shapes, dtypes,
    structure) — the bit-identity the joiner depends on."""
    import json

    monkeypatch.setenv("MPI4JAX_TPU_ELASTIC_GROW", "1")
    comm = _world_comm()
    store = mpx.ShardStore(comm)
    state = _jax_state()
    store.commit(5, state)

    desc = json.loads(json.dumps(store.describe_commit()))
    cold = mpx.ShardStore(comm, rank=0)
    cold.adopt_commit(desc)
    assert cold.committed_step == 5

    # the bytes the exchange would deliver: the full committed buffer
    rec = store._committed
    buf = np.concatenate(
        [np.frombuffer(rec["shards"][s], np.uint8)
         for s in range(rec["k"])])
    crec = cold._committed
    total = sum(m[2] for m in crec["meta"])
    restored = el._unflatten_state(
        crec["treedef"], el.unpack_leaves(buf[:total], crec["meta"]))
    np.testing.assert_array_equal(np.asarray(state["w"]),
                                  np.asarray(restored["w"]))
    assert np.asarray(restored["w"]).dtype == np.float32
    np.testing.assert_array_equal(np.asarray(state["opt"][0]),
                                  np.asarray(restored["opt"][0]))
    assert np.asarray(restored["opt"][0]).dtype == np.float64
    assert int(restored["opt"][1]) == 17


def test_apply_grow_rebuilds_the_mesh_and_restore_replays():
    comm = _world_comm()
    store = mpx.ShardStore(comm)
    state = _jax_state()
    store.commit(4, state)
    el.advance_epoch()
    store.apply_shrink({6})
    assert store.comm.Get_size() == 7
    el.advance_epoch(world=8, cause="join")
    store.apply_grow(1)
    assert store.comm.Get_size() == 8
    assert store.comm.epoch == 2
    step, restored = store.restore()
    assert step == 4
    np.testing.assert_array_equal(np.asarray(state["w"]),
                                  np.asarray(restored["w"]))


# ---------------------------------------------------------------------------
# graceful drain (single-controller) + MPX127
# ---------------------------------------------------------------------------


def test_drain_executes_planned_shrink_with_forced_commit():
    from mpi4jax_tpu.resilience import watchdog as wd

    comm = _world_comm()
    store = mpx.ShardStore(comm)
    seen = []

    def step_fn(state, step, comm):
        seen.append((step, comm.Get_size()))
        if step == 1 and comm.epoch == 0:
            mpx.request_drain(rank=7)
        return {"n": state["n"] + 1}

    out = mpx.elastic.run(step_fn, {"n": 0}, store, steps=4,
                          commit_every=4)
    assert out["n"] == 4
    # the drain boundary forced a commit OFF the commit_every cadence
    assert seen == [(0, 8), (1, 8), (2, 7), (3, 7)]
    assert el.current_epoch() == 1
    assert el.epoch_history()[-1]["cause"] == "drain"
    assert store.comm.Get_size() == 7
    assert not store.drained                  # the controller never leaves
    # the OLD comm is sealed: past its leave boundary now
    assert comm.drained
    assert not store.comm.drained
    assert wd._registry.empty()


def test_mpx127_flags_drained_comm_and_passes_draining():
    comm = _world_comm()

    def f(x):
        res, _ = mpx.allreduce(x, op=mpx.SUM, comm=comm)
        return res

    x = jnp.ones((8, 2))
    report = mpx.analyze(f, x, comm=comm)
    assert not [fd for fd in report.findings if fd.code == "MPX127"], (
        report.render())

    # scheduled but not past the boundary: still legal (that is what
    # makes the drain graceful)
    el.mark_comm_draining(comm, 5)
    report = mpx.analyze(f, x, comm=comm)
    assert not [fd for fd in report.findings if fd.code == "MPX127"], (
        report.render())

    el.seal_drained_comm(comm)
    report = mpx.analyze(f, x, comm=comm)
    (finding,) = [fd for fd in report.findings if fd.code == "MPX127"]
    assert finding.severity == "error"
    assert "leave boundary" in finding.message
    # the epoch never advanced: MPX127 is not a duplicate of MPX126
    assert not [fd for fd in report.findings if fd.code == "MPX126"], (
        report.render())


def test_mpx127_fires_through_ambient_error_mode():
    stale = _world_comm()
    x = jnp.ones((8, 2))
    mpx.set_analyze_mode("error")
    try:
        out, _ = mpx.allreduce(x, op=mpx.SUM, comm=stale)  # clean
        np.testing.assert_allclose(np.asarray(out), 8.0)
        el.seal_drained_comm(stale)
        with pytest.raises(mpx.AnalysisError, match="MPX127"):
            mpx.allreduce(x, op=mpx.SUM, comm=stale)
    finally:
        mpx.set_analyze_mode(None)


def test_telemetry_snapshot_carries_the_epoch_history():
    mpx.set_telemetry_mode("counters")
    try:
        comm = _world_comm()
        store = mpx.ShardStore(comm)

        def step_fn(state, step, comm):
            if step == 1 and comm.epoch == 0:
                raise mpx.RankFailure({3}, "simulated")
            return state

        mpx.elastic.run(step_fn, {"x": 1}, store, steps=3)
        snap = mpx.telemetry.snapshot()
        (rec,) = snap["epochs"]
        assert rec["epoch"] == 1 and rec["cause"] == "failure"
        assert rec["world"] == 7
    finally:
        mpx.set_telemetry_mode(None)


def test_claimed_watchdog_expiry_recovers_instead_of_killing():
    """End to end on one host: a watchdog expiry posted by the claimed
    handler converts into a shrink instead of a process kill (the
    single-process analog of the hang drill — the collective itself
    cannot hang here, so the expiry is driven through the registry)."""
    from mpi4jax_tpu.resilience import watchdog as wd

    comm = _world_comm()
    store = mpx.ShardStore(comm)

    def step_fn(state, step, comm):
        if step == 1 and comm.epoch == 0:
            # simulate what the monitor thread does on expiry with the
            # elastic handler claimed: journal, post, interrupt
            el._claimed_on_timeout(
                [], {"opname": "MPI_Allreduce", "call_id": "deadbeef",
                     "rank": 3, "timeout": 1.0, "elapsed": 2.0})
            raise mpx.RankFailure({3}, "expiry attribution")
        return state

    out = mpx.elastic.run(step_fn, {"x": 0}, store, steps=3)
    assert out == {"x": 0}
    assert el.current_epoch() == 1
    assert store.comm.Get_size() == 7
    # the loop restored the default handler + native routing on exit
    assert wd._registry.on_timeout is wd._default_on_timeout
    assert not wd._force_fallback
