"""True multi-process execution tests.

The reference's whole suite runs under ``mpirun -np 2 pytest`` with an
explicit warning that single-process runs "cannot test a large part" of the
library (ref docs/developers.rst:15-27).  The TPU-native analog launches
N OS processes that rendezvous through ``mpi4jax_tpu.init_distributed``
(``jax.distributed.initialize`` under the hood — the ``mpirun`` replacement,
SURVEY.md §2.7) on localhost, each owning a slice of a virtual-CPU device
"pod", and runs collectives + the shallow-water model over the
process-spanning mesh.

This is the only place ``init_distributed`` executes for real: the rest of
the suite is single-process/8-virtual-devices.
"""

import os
import socket
import subprocess
import sys
import textwrap

import pytest

# true multi-controller runs take ~15+ min: slow tier (pyproject addopts)
pytestmark = pytest.mark.slow

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# Everything the workers run.  Process-spanning assertions check only this
# process's addressable shards (a host fetch of the full global array is not
# legal in multi-controller JAX).
WORKER = textwrap.dedent(
    """
    import os, sys
    proc_id = int(sys.argv[1])
    nprocs = int(sys.argv[2])
    port = sys.argv[3]
    local_devices = int(sys.argv[4])
    os.environ["XLA_FLAGS"] = (
        f"--xla_force_host_platform_device_count={local_devices}"
    )
    os.environ["JAX_PLATFORMS"] = "cpu"
    repo = sys.argv[5]
    sys.path.insert(0, repo)
    sys.path.insert(0, os.path.join(repo, "examples"))
    import jax
    jax.config.update("jax_platforms", "cpu")
    import mpi4jax_tpu as mpx

    # the mpirun replacement: explicit coordinator on CPU clusters
    mpx.init_distributed(
        coordinator_address=f"localhost:{port}",
        num_processes=nprocs,
        process_id=proc_id,
    )
    # idempotent (second call is a no-op, not an error)
    mpx.init_distributed()
    assert jax.process_count() == nprocs, jax.process_count()
    size = nprocs * local_devices
    assert jax.device_count() == size, jax.device_count()

    import numpy as np
    import jax.numpy as jnp

    # --- 1. allreduce over the process-spanning world mesh ---------------
    @mpx.spmd
    def f(x):
        res, _ = mpx.allreduce(x, op=mpx.SUM)
        return res

    x = jnp.zeros((size, 3)) + jnp.arange(float(size))[:, None]
    out = f(x)
    want = size * (size - 1) / 2
    for s in out.addressable_shards:
        assert np.all(np.asarray(s.data) == want), (proc_id, s.index)

    # --- 2. sendrecv ring across the process boundary --------------------
    @mpx.spmd
    def ring(x):
        res, _ = mpx.sendrecv(x, x, dest=mpx.shift(1))
        return res

    r = ring(jnp.arange(float(size)))
    for s in r.addressable_shards:
        rank = s.index[0].start
        got = np.asarray(s.data)[0]
        assert got == (rank - 1) % size, (rank, got)

    # --- 3. shallow-water multistep over a process-spanning 2-D mesh ------
    from shallow_water import (
        Config, initial_state, make_mesh_and_comm, make_stepper,
    )

    nproc_y = 2 if size % 2 == 0 else 1
    cfg = Config(
        nproc_y=nproc_y, nproc_x=size // nproc_y,
        nx=4 * (size // nproc_y), ny=8 * nproc_y,
    )
    mesh, comm = make_mesh_and_comm(cfg)
    # fast="auto" selects the shipped multi-rank mode
    # (model_step_pallas_halo; on this CPU worker its interpret fallback —
    # same math, no Pallas machinery) with the sendrecv halo exchanges
    # crossing real process boundaries
    first, multi = make_stepper(cfg, comm, fast="auto")
    state = multi(first(initial_state(cfg)), 3)
    for s in state.h.addressable_shards:
        block = np.asarray(s.data)
        assert np.isfinite(block).all(), (proc_id, s.index)
        assert 50 < block.mean() < 150  # height near resting depth

    # --- 3b. unequal color split across real process boundaries -----------
    if size >= 3:
        uneq = mpx.get_default_comm().Split([0, 0] + [1] * (size - 2))
        xs = jnp.arange(float(size))[:, None]
        sc, _ = mpx.scan(xs, mpx.SUM, comm=uneq)
        rg, _ = mpx.sendrecv(xs, xs, dest=mpx.shift(1), comm=uneq)
        for arr, expect in ((sc, "scan"), (rg, "ring")):
            for s in arr.addressable_shards:
                r = s.index[0].start
                got = float(np.asarray(s.data)[0, 0])
                g = next(grp for grp in uneq.groups if r in grp)
                i = g.index(r)
                want = (float(sum(g[: i + 1])) if expect == "scan"
                        else float(g[(i - 1) % len(g)]))
                assert got == want, (proc_id, expect, r, got, want)

    # --- 4. wide-halo carried frame across real process boundaries --------
    # 16-cell local interiors: "auto" ships the communication-avoiding
    # wide path, whose margin-band sendrecvs here cross processes
    cfg_w = Config(
        nproc_y=nproc_y, nproc_x=size // nproc_y,
        nx=16 * (size // nproc_y), ny=16 * nproc_y,
    )
    _, comm_w = make_mesh_and_comm(cfg_w)
    from shallow_water import model_step_wide, select_step
    assert select_step("auto", cfg_w) is model_step_wide
    first_w, multi_w = make_stepper(cfg_w, comm_w, fast="auto")
    state_w = multi_w(first_w(initial_state(cfg_w)), 3)
    for s in state_w.h.addressable_shards:
        block = np.asarray(s.data)
        assert np.isfinite(block).all(), (proc_id, s.index)
        assert 50 < block.mean() < 150

    print(f"MULTIPROC_OK {proc_id}", flush=True)
    """
)


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def _launch(nprocs: int, local_devices: int, timeout: int = 420):
    """Launch ``nprocs`` worker processes and wait for all of them."""
    env = {
        k: v
        for k, v in os.environ.items()
        if k not in ("XLA_FLAGS", "JAX_PLATFORMS")
    }
    port = str(_free_port())
    procs = [
        subprocess.Popen(
            [
                sys.executable, "-c", WORKER,
                str(i), str(nprocs), port, str(local_devices), REPO_ROOT,
            ],
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
        )
        for i in range(nprocs)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=timeout)
            outs.append(out)
    except subprocess.TimeoutExpired:
        # one worker crashed → the others hang in the collective until the
        # timeout.  Kill everyone and collect whatever each wrote, so the
        # crashed worker's traceback reaches the assertion message.
        for p in procs:
            if p.poll() is None:
                p.kill()
        while len(outs) < len(procs):
            out, _ = procs[len(outs)].communicate()
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    return procs, outs


@pytest.mark.parametrize(
    "nprocs,local_devices", [(2, 4), (4, 2)],
    ids=["2procs-x4dev", "4procs-x2dev"],
)
def test_multiprocess_collectives_and_shallow_water(nprocs, local_devices):
    procs, outs = _launch(nprocs, local_devices)
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {i} failed:\n{out[-4000:]}"
        assert f"MULTIPROC_OK {i}" in out
