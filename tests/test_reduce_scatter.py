"""reduce_scatter: the 13th op's contract matrix.

The reference has NO reduce_scatter, so there is no ported suite to
mirror; instead this file holds the op to the same contracts the other 12
satisfy (tests/test_allreduce.py is the closest template): region + eager
execution, the global-array convention, every Op, non-commutative
associative callables (block-wise — valid on every algorithm here, see
ops/reduce_scatter.py), token chaining, jvp/vjp/linear_transpose, vmap,
color splits, bf16, and the payload-aware algorithm selector
(``MPI4JAX_TPU_COLLECTIVE_ALGO``) with its native ``psum_scatter`` HLO pin.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import mpi4jax_tpu as mpx
from helpers import per_rank, world


def _blocks(seed=0, block_shape=(3,), kind="float"):
    """Global input (size, size, *block_shape): rank r's block addressed
    to rank c is ``[r, c]``."""
    _, size = world()
    rng = np.random.default_rng(seed)
    shape = (size, size) + block_shape
    if kind == "bool":
        return rng.integers(0, 2, size=shape).astype(bool)
    if kind == "int":
        return rng.integers(0, 128, size=shape).astype(np.int32)
    return rng.uniform(0.5, 1.5, size=shape).astype(np.float32)


def test_reduce_scatter_region_jit():
    _, size = world()

    @mpx.spmd
    def f(x):
        res, _ = mpx.reduce_scatter(x, op=mpx.SUM)
        return res

    vals = _blocks()
    out = np.asarray(f(jnp.asarray(vals)))  # (size, *block_shape)
    # rank i receives the sum of every rank's block i — allreduce(x)[rank]
    # at a fraction of the byte volume
    for i in range(size):
        np.testing.assert_allclose(out[i], vals[:, i].sum(0), rtol=1e-5)


def test_reduce_scatter_eager():
    _, size = world()
    vals = _blocks(seed=1)
    res, token = mpx.reduce_scatter(jnp.asarray(vals), op=mpx.SUM)
    assert isinstance(token, mpx.Token)
    assert res.shape == (size,) + vals.shape[2:]
    for i in range(size):
        np.testing.assert_allclose(np.asarray(res)[i], vals[:, i].sum(0),
                                   rtol=1e-5)


_ALGO_OP_CASES = [
    (mpx.SUM, np.add.reduce, "float"),
    (mpx.PROD, np.multiply.reduce, "float"),
    (mpx.MIN, np.minimum.reduce, "float"),
    (mpx.MAX, np.maximum.reduce, "float"),
    (mpx.LAND, np.logical_and.reduce, "bool"),
    (mpx.LOR, np.logical_or.reduce, "bool"),
    (mpx.LXOR, np.logical_xor.reduce, "bool"),
    (mpx.BAND, np.bitwise_and.reduce, "int"),
    (mpx.BOR, np.bitwise_or.reduce, "int"),
    (mpx.BXOR, np.bitwise_xor.reduce, "int"),
]


@pytest.mark.parametrize("algo", ["auto", "butterfly", "ring"])
@pytest.mark.parametrize("op,npred,kind", _ALGO_OP_CASES,
                         ids=[o.name for o, _, _ in _ALGO_OP_CASES])
def test_reduce_scatter_ops_all_algos(monkeypatch, algo, op, npred, kind):
    monkeypatch.setenv("MPI4JAX_TPU_COLLECTIVE_ALGO", algo)
    _, size = world()

    @mpx.spmd
    def f(x):
        res, _ = mpx.reduce_scatter(x, op=op)
        return res

    vals = _blocks(seed=2, kind=kind)
    out = np.asarray(f(jnp.asarray(vals)))
    for i in range(size):
        np.testing.assert_allclose(
            out[i].astype(np.float64),
            npred(vals[:, i], axis=0).astype(np.float64),
            rtol=1e-5, err_msg=f"algo={algo} op={op} block={i}")


@pytest.mark.parametrize("algo", ["auto", "butterfly", "ring"])
def test_reduce_scatter_matmul_callable_order(monkeypatch, algo):
    """Block-wise callables are valid on EVERY algorithm here (the chunks
    are the user's own blocks, unlike the chunked-allreduce path), and
    non-commutative associative ops must fold in ascending group-rank
    order: the 2x2 matrix product pins both."""
    monkeypatch.setenv("MPI4JAX_TPU_COLLECTIVE_ALGO", algo)
    _, size = world()

    @mpx.spmd
    def f(x):
        res, _ = mpx.reduce_scatter(x, op=jnp.matmul)
        return res

    rng = np.random.default_rng(3)
    mats = rng.normal(size=(size, size, 2, 2)).astype(np.float32)
    out = np.asarray(f(jnp.asarray(mats)))
    for i in range(size):
        expected = np.eye(2, dtype=np.float32)
        for r in range(size):
            expected = expected @ mats[r, i]
        np.testing.assert_allclose(out[i], expected, rtol=1e-4, atol=1e-4,
                                   err_msg=f"algo={algo} block={i}")


def test_reduce_scatter_shape_check():
    _, size = world()
    with pytest.raises(ValueError, match="leading axis"):
        @mpx.spmd
        def f(x):
            res, _ = mpx.reduce_scatter(x)
            return res

        f(per_rank(lambda r: np.zeros((size + 1, 2))))


def test_reduce_scatter_chained_tokens():
    _, size = world()

    @mpx.spmd
    def f(x):
        token = mpx.create_token()
        a, token = mpx.reduce_scatter(x, op=mpx.SUM, token=token)
        b, token = mpx.allreduce(a, op=mpx.SUM, token=token)
        return b

    vals = _blocks(seed=4)
    out = np.asarray(f(jnp.asarray(vals)))
    # allreduce of the scattered blocks = the grand total of all blocks
    np.testing.assert_allclose(out, vals.sum((0, 1)), rtol=1e-5)


def test_reduce_scatter_jvp():
    # tangents are reduce-scattered alongside primals
    _, size = world()

    @mpx.spmd
    def f(x):
        def g(a):
            return mpx.reduce_scatter(a, op=mpx.SUM)[0]

        y, dy = jax.jvp(g, (x,), (jnp.ones_like(x),))
        return y + 0 * dy, dy

    vals = _blocks(seed=5)
    y, dy = f(jnp.asarray(vals))
    for i in range(size):
        np.testing.assert_allclose(np.asarray(y)[i], vals[:, i].sum(0),
                                   rtol=1e-5)
    # each output element sums `size` tangent ones
    np.testing.assert_allclose(np.asarray(dy), float(size), rtol=1e-6)


@pytest.mark.parametrize("algo", ["auto", "butterfly", "ring"])
def test_reduce_scatter_transpose_is_allgather(monkeypatch, algo):
    """The transpose of SUM-reduce_scatter distributes the per-rank
    cotangent back to every contributing block: block j of the transposed
    cotangent is rank j's cotangent (the psum_scatter / all_gather adjoint
    pair) — and the ppermute-based ring and butterfly lowerings must
    transpose identically."""
    monkeypatch.setenv("MPI4JAX_TPU_COLLECTIVE_ALGO", algo)
    _, size = world()

    @mpx.spmd
    def f(x, ct):
        def g(a):
            return mpx.reduce_scatter(a, op=mpx.SUM)[0]

        t = jax.linear_transpose(g, x)
        return t(ct)[0]

    x = jnp.asarray(_blocks(seed=6))
    ct = per_rank(lambda r: np.full((3,), float(r)))  # ct[r] = r
    out = np.asarray(f(x, ct))  # (size, size, 3)
    for r in range(size):
        for j in range(size):
            np.testing.assert_allclose(out[r, j], float(j), rtol=1e-6,
                                       err_msg=f"algo={algo}")


def test_reduce_scatter_grad():
    _, size = world()

    def loss(x):
        @mpx.spmd
        def per_rank_f(xl):
            y, _ = mpx.reduce_scatter(xl, op=mpx.SUM)
            return jnp.sum(y ** 2)

        return jnp.sum(per_rank_f(x))

    vals = _blocks(seed=7)
    g = np.asarray(jax.grad(loss)(jnp.asarray(vals)))
    totals = vals.sum(0)  # totals[i] = the block-i reduction
    # d/dx[r, i] sum_i (total_i)^2 = 2 * total_i, for every contributing r
    for r in range(size):
        np.testing.assert_allclose(g[r], 2 * totals, rtol=1e-4)


def test_reduce_scatter_vmap():
    _, size = world()

    @mpx.spmd
    def f(x):
        res, _ = mpx.reduce_scatter(x, op=mpx.SUM)
        return res

    xb = jnp.arange(size * size * 4, dtype=jnp.float32).reshape(
        size, size, 4)
    out = jax.vmap(f, in_axes=2, out_axes=1)(xb)  # (size, 4)
    expected = np.asarray(xb).sum(0)  # block i total, per vmapped lane
    np.testing.assert_allclose(np.asarray(out), expected, rtol=1e-6)


def test_reduce_scatter_split_uniform_groups(monkeypatch):
    """On a color split, blocks index GROUP-LOCAL positions: group member
    at position i receives the fold of its group's blocks i."""
    comm, size = world()
    split = comm.Split([r % 2 for r in range(size)])
    gs = size // 2
    groups = ((0, 2, 4, 6), (1, 3, 5, 7))
    rng = np.random.default_rng(8)
    vals = rng.uniform(0.5, 1.5, size=(size, gs, 2)).astype(np.float32)

    for algo in ("auto", "butterfly", "ring"):
        monkeypatch.setenv("MPI4JAX_TPU_COLLECTIVE_ALGO", algo)

        @mpx.spmd
        def f(x):
            res, _ = mpx.reduce_scatter(x, op=mpx.SUM, comm=split)
            return res

        out = np.asarray(f(jnp.asarray(vals)))
        for grp in groups:
            for i, rank in enumerate(grp):
                expected = sum(vals[m, i] for m in grp)
                np.testing.assert_allclose(out[rank], expected, rtol=1e-5,
                                           err_msg=f"algo={algo}")


def test_reduce_scatter_unequal_split_raises():
    comm, size = world()
    split = comm.Split([0, 0] + [1] * (size - 2))
    with pytest.raises(RuntimeError, match="unequal group sizes"):
        mpx.reduce_scatter(jnp.ones((size, 2, 3)), comm=split)


def test_reduce_scatter_notoken():
    from mpi4jax_tpu.experimental import notoken

    _, size = world()

    @mpx.spmd
    def f(x):
        return notoken.reduce_scatter(x, op=mpx.SUM)

    vals = _blocks(seed=9)
    out = np.asarray(f(jnp.asarray(vals)))
    for i in range(size):
        np.testing.assert_allclose(out[i], vals[:, i].sum(0), rtol=1e-5)


def test_reduce_scatter_bf16():
    _, size = world()

    @mpx.spmd
    def f(x):
        res, _ = mpx.reduce_scatter(x, op=mpx.SUM)
        return res

    x = per_rank(lambda r: np.full((size, 2), r), dtype=jnp.bfloat16)
    out = f(x)
    assert out.dtype == jnp.bfloat16
    total = size * (size - 1) / 2.0
    np.testing.assert_allclose(np.asarray(out, dtype=np.float32), total)


def test_reduce_scatter_hlo_native_vs_ring(monkeypatch):
    """HLO pins: SUM on a whole single-axis comm under ``auto`` lowers to
    ONE native reduce-scatter HLO (no ppermute rounds); the forced ring is
    k-1 block-sized CollectivePermute rounds; the forced butterfly ships
    the full (k, *s) stack every round."""
    _, size = world()
    x = jnp.ones((size, size, 16), jnp.float32)

    def lowered(algo):
        monkeypatch.setenv("MPI4JAX_TPU_COLLECTIVE_ALGO", algo)

        @mpx.spmd
        def f(xl):
            res, _ = mpx.reduce_scatter(xl, op=mpx.SUM)
            return res

        return jax.jit(f).lower(x).as_text()

    auto = lowered("auto")
    assert "reduce_scatter" in auto or "reduce-scatter" in auto, auto[:2000]
    assert "collective_permute" not in auto

    ring_lines = [ln for ln in lowered("ring").splitlines()
                  if "collective_permute" in ln]
    assert len(ring_lines) >= size - 1
    # block-sized messages, never the full block stack
    assert any("tensor<16xf32>" in ln for ln in ring_lines)
    for ln in ring_lines:
        assert f"tensor<{size}x16xf32>" not in ln, ln

    fly_lines = [ln for ln in lowered("butterfly").splitlines()
                 if "collective_permute" in ln]
    assert len(fly_lines) >= 1
    assert all(f"tensor<{size}x16xf32>" in ln for ln in fly_lines)
