"""In-repo lint: unused imports.

CI runs flake8 (see .github/workflows/test.yml), but the dev sandbox may not
have it installed — this AST-based check keeps the one lint class that has
actually bitten this repo (unused imports surviving across rounds, VERDICT
r1/r2) enforceable everywhere the test suite runs.
"""

import ast
import pathlib
import re

import pytest

REPO = pathlib.Path(__file__).resolve().parent.parent

SOURCES = sorted(
    p
    for d in ("mpi4jax_tpu", "tests", "examples", "benchmarks")
    for p in (REPO / d).rglob("*.py")
    if "__pycache__" not in p.parts
) + [REPO / "bench.py", REPO / "__graft_entry__.py"]


def _imported_names(tree, src_lines):
    """(name, lineno) for every binding introduced by an import statement,
    skipping lines marked ``# noqa`` (re-export convention)."""
    out = []
    for node in ast.walk(tree):
        if not isinstance(node, (ast.Import, ast.ImportFrom)):
            continue
        if isinstance(node, ast.ImportFrom) and node.module == "__future__":
            continue
        # multi-line imports: noqa can sit on any line of the statement;
        # only a bare noqa or an explicit F401 waives THIS check (an
        # unrelated code like "# noqa: E501" must not)
        stmt_lines = range(node.lineno, (node.end_lineno or node.lineno) + 1)
        # waive on a bare "# noqa" or any code list containing F401
        # (flake8 accepts "# noqa:F401", "# noqa: F401, E501", trailing
        # comment text, ...); an unrelated code like "# noqa: E501" must not
        waiver = re.compile(r"#\s*noqa(\s*$|:[^#]*\bF401\b)")
        if any(waiver.search(src_lines[i - 1]) for i in stmt_lines):
            continue
        for alias in node.names:
            if alias.name == "*":
                continue
            bound = alias.asname or alias.name.split(".")[0]
            out.append((bound, node.lineno))
    return out


def _used_names(tree):
    return {node.id for node in ast.walk(tree) if isinstance(node, ast.Name)}


@pytest.mark.parametrize("path", SOURCES, ids=lambda p: str(p.relative_to(REPO)))
def test_no_unused_imports(path):
    if path.name == "__init__.py":
        pytest.skip("re-export modules")
    src = path.read_text()
    tree = ast.parse(src)
    used = _used_names(tree)
    # names referenced only in __all__ strings count as used (but not
    # arbitrary string literals — that would hide real unused imports)
    for node in tree.body:
        if (
            isinstance(node, ast.Assign)
            and any(
                isinstance(t, ast.Name) and t.id == "__all__"
                for t in node.targets
            )
        ):
            for el in ast.walk(node.value):
                if isinstance(el, ast.Constant) and isinstance(el.value, str):
                    used.add(el.value)
    unused = [
        f"{path.relative_to(REPO)}:{line}: {name}"
        for name, line in _imported_names(tree, src.splitlines())
        if name not in used
    ]
    assert not unused, "unused imports:\n" + "\n".join(unused)
