"""In-repo lint pack: unused imports, undeclared env flags, docs sync.

CI runs flake8 (see .github/workflows/test.yml), but the dev sandbox may not
have it installed — these AST-based checks keep the lint classes that have
actually bitten this repo enforceable everywhere the test suite runs:

- unused imports (surviving across rounds, VERDICT r1/r2);
- ``MPI4JAX_TPU_*`` environment flags read anywhere under ``mpi4jax_tpu/``
  without being declared in the ``utils/config.py`` registry (name, type,
  default, docstring — the single source of truth the docs and the
  runtime ``_getenv`` guard share);
- declared flags missing from the docs flag tables
  (docs/usage.md / docs/resilience.md).
"""

import ast
import importlib
import pathlib
import re
import sys
import types

import pytest

REPO = pathlib.Path(__file__).resolve().parent.parent

SOURCES = sorted(
    p
    for d in ("mpi4jax_tpu", "tests", "examples", "benchmarks")
    for p in (REPO / d).rglob("*.py")
    if "__pycache__" not in p.parts
) + [REPO / "bench.py", REPO / "__graft_entry__.py"]


def _imported_names(tree, src_lines):
    """(name, lineno) for every binding introduced by an import statement,
    skipping lines marked ``# noqa`` (re-export convention)."""
    out = []
    for node in ast.walk(tree):
        if not isinstance(node, (ast.Import, ast.ImportFrom)):
            continue
        if isinstance(node, ast.ImportFrom) and node.module == "__future__":
            continue
        # multi-line imports: noqa can sit on any line of the statement;
        # only a bare noqa or an explicit F401 waives THIS check (an
        # unrelated code like "# noqa: E501" must not)
        stmt_lines = range(node.lineno, (node.end_lineno or node.lineno) + 1)
        # waive on a bare "# noqa" or any code list containing F401
        # (flake8 accepts "# noqa:F401", "# noqa: F401, E501", trailing
        # comment text, ...); an unrelated code like "# noqa: E501" must not
        waiver = re.compile(r"#\s*noqa(\s*$|:[^#]*\bF401\b)")
        if any(waiver.search(src_lines[i - 1]) for i in stmt_lines):
            continue
        for alias in node.names:
            if alias.name == "*":
                continue
            bound = alias.asname or alias.name.split(".")[0]
            out.append((bound, node.lineno))
    return out


def _used_names(tree):
    return {node.id for node in ast.walk(tree) if isinstance(node, ast.Name)}


@pytest.mark.parametrize("path", SOURCES, ids=lambda p: str(p.relative_to(REPO)))
def test_no_unused_imports(path):
    if path.name == "__init__.py":
        pytest.skip("re-export modules")
    src = path.read_text()
    tree = ast.parse(src)
    used = _used_names(tree)
    # names referenced only in __all__ strings count as used (but not
    # arbitrary string literals — that would hide real unused imports)
    for node in tree.body:
        if (
            isinstance(node, ast.Assign)
            and any(
                isinstance(t, ast.Name) and t.id == "__all__"
                for t in node.targets
            )
        ):
            for el in ast.walk(node.value):
                if isinstance(el, ast.Constant) and isinstance(el.value, str):
                    used.add(el.value)
    unused = [
        f"{path.relative_to(REPO)}:{line}: {name}"
        for name, line in _imported_names(tree, src.splitlines())
        if name not in used
    ]
    assert not unused, "unused imports:\n" + "\n".join(unused)


# ---------------------------------------------------------------------------
# env-flag registry checks (loaded without importing mpi4jax_tpu, so the
# lint runs even where the installed JAX is below the package's hard floor)
# ---------------------------------------------------------------------------

_ISO_NAME = "_mpx_lint_iso"


def _load_config():
    if _ISO_NAME not in sys.modules:
        root = types.ModuleType(_ISO_NAME)
        root.__path__ = [str(REPO / "mpi4jax_tpu")]
        sys.modules[_ISO_NAME] = root
        sub = types.ModuleType(f"{_ISO_NAME}.utils")
        sub.__path__ = [str(REPO / "mpi4jax_tpu" / "utils")]
        sys.modules[f"{_ISO_NAME}.utils"] = sub
        root.utils = sub
        importlib.import_module(f"{_ISO_NAME}.utils.config")
    return sys.modules[f"{_ISO_NAME}.utils.config"]


PKG_SOURCES = [p for p in SOURCES
               if p.is_relative_to(REPO / "mpi4jax_tpu")]

# call names whose first string argument is an env-flag read: the raw
# os.environ surface plus the config-module parse helpers (which go through
# the registry's _getenv at runtime — the lint catches it statically)
_ENV_READ_FUNCS = {
    "getenv",          # os.getenv("...")
    "get", "pop", "setdefault",  # os.environ.get / .pop / .setdefault
    "parse_env_bool", "parse_env_float", "_getenv", "_parse_env_choice",
}

_FLAG_RE = re.compile(r"^MPI4JAX_TPU_\w+$")


def _env_flag_reads(tree):
    """(flag_name, lineno) for every MPI4JAX_TPU_* environment read."""
    out = []
    for node in ast.walk(tree):
        key = None
        if isinstance(node, ast.Call):
            fn = node.func
            name = fn.attr if isinstance(fn, ast.Attribute) else getattr(
                fn, "id", None)
            if name in _ENV_READ_FUNCS and node.args:
                arg = node.args[0]
                if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
                    key = arg.value
        elif isinstance(node, ast.Subscript):
            # os.environ["..."] — any literal-keyed subscript is cheap to
            # inspect; non-flag strings are filtered below
            sl = node.slice
            if isinstance(sl, ast.Constant) and isinstance(sl.value, str):
                key = sl.value
        if key is not None and _FLAG_RE.match(key):
            out.append((key, node.lineno))
    return out


@pytest.mark.parametrize(
    "path", PKG_SOURCES, ids=lambda p: str(p.relative_to(REPO)))
def test_no_undeclared_env_flags(path):
    """Every MPI4JAX_TPU_* flag read under mpi4jax_tpu/ must be declared in
    the utils/config.py registry (name, type, default, docstring)."""
    config = _load_config()
    tree = ast.parse(path.read_text())
    undeclared = [
        f"{path.relative_to(REPO)}:{line}: {name}"
        for name, line in _env_flag_reads(tree)
        if name not in config.FLAGS
    ]
    assert not undeclared, (
        "undeclared environment flags (declare them in "
        "mpi4jax_tpu/utils/config.py FLAGS):\n" + "\n".join(undeclared)
    )


def test_registry_flags_are_wellformed():
    config = _load_config()
    for name, flag in config.FLAGS.items():
        assert _FLAG_RE.match(name), name
        assert flag.name == name
        assert flag.type in ("bool", "float", "int", "str", "choice")
        assert flag.doc.strip(), f"{name} needs a docstring"
        if flag.type == "choice":
            assert flag.choices and flag.default in flag.choices, name


def _load_analysis_report():
    """analysis/report.py under the lint's isolated package (it is
    dependency-free by contract, so this works on any JAX)."""
    name = f"{_ISO_NAME}.analysis"
    if name not in sys.modules:
        _load_config()  # ensure the root package exists
        sub = types.ModuleType(name)
        sub.__path__ = [str(REPO / "mpi4jax_tpu" / "analysis")]
        sys.modules[name] = sub
        importlib.import_module(f"{name}.report")
    return sys.modules[f"{name}.report"]


_MPX_RE = re.compile(r"MPX\d{3}")


def test_mpx_codes_sync():
    """MPX-code sync: every ``MPX\\d+`` code raised or annotated anywhere
    under ``mpi4jax_tpu/`` must be declared in the ``report.CODES``
    catalog AND appear in the docs/analysis.md checker catalog — and
    vice versa (a stale catalog after new codes land, or a doc
    mentioning a code the checkers no longer own, fails here)."""
    rep = _load_analysis_report()
    registry = set(rep.CODES)
    src_codes = set()
    src_where = {}
    for path in PKG_SOURCES:
        if path.name == "report.py" and path.parent.name == "analysis":
            continue  # the declaration site itself proves nothing
        for m in _MPX_RE.finditer(path.read_text()):
            src_codes.add(m.group(0))
            src_where.setdefault(m.group(0), str(path.relative_to(REPO)))
    doc_codes = set(_MPX_RE.findall((REPO / "docs" / "analysis.md")
                                    .read_text()))

    undeclared = sorted(src_codes - registry)
    assert not undeclared, (
        "MPX codes referenced in mpi4jax_tpu/ but not declared in "
        "analysis/report.py CODES: "
        + ", ".join(f"{c} ({src_where[c]})" for c in undeclared)
    )
    unreferenced = sorted(registry - src_codes)
    assert not unreferenced, (
        "MPX codes declared in analysis/report.py CODES but never "
        "raised/annotated anywhere under mpi4jax_tpu/: "
        + ", ".join(unreferenced)
    )
    undocumented = sorted(registry - doc_codes)
    assert not undocumented, (
        "MPX codes missing from the docs/analysis.md checker catalog: "
        + ", ".join(undocumented)
    )
    stale = sorted(doc_codes - registry)
    assert not stale, (
        "docs/analysis.md mentions MPX codes absent from the "
        "analysis/report.py catalog (stale docs): " + ", ".join(stale)
    )


def test_every_error_code_has_a_seeded_positive():
    """Coverage lint: every ERROR-severity code in the catalog must be
    demonstrably fireable — a seeded fixture under ``examples/broken/``
    (the CI analyze lane asserts analyzing it FAILS with that code) or a
    positive in the test suites (a hand-built graph/schedule or a traced
    program asserting the code fires).  A code that nothing can
    demonstrate is either dead or untested — both fail here."""
    rep = _load_analysis_report()
    error_codes = {c for c, info in rep.CODES.items()
                   if info.severity == rep.ERROR}
    fixtures = "\n".join(
        p.read_text()
        for p in sorted((REPO / "examples" / "broken").glob("*.py")))
    suites = "\n".join(
        p.read_text() for p in sorted((REPO / "tests").glob("test_*.py"))
        if p.name != "test_lint.py")  # this file proves nothing
    uncovered = sorted(c for c in error_codes
                       if c not in fixtures and c not in suites)
    assert not uncovered, (
        "ERROR-severity MPX codes with neither a seeded examples/broken/ "
        "fixture nor an in-suite positive: " + ", ".join(uncovered)
    )


def test_docs_list_every_registered_flag():
    """Docs-sync: each declared flag must appear in the docs flag tables
    (docs/usage.md, docs/resilience.md, docs/observability.md,
    docs/overlap.md, docs/topology.md, docs/aot.md, docs/autotune.md,
    docs/serving.md, docs/moe.md, docs/compression.md, or
    docs/pipeline.md) — a flag without documentation is
    indistinguishable from an undocumented sharp bit."""
    config = _load_config()
    docs = "\n".join(
        (REPO / "docs" / f).read_text()
        for f in ("usage.md", "resilience.md", "observability.md",
                  "overlap.md", "topology.md", "aot.md", "autotune.md",
                  "serving.md", "moe.md", "compression.md",
                  "pipeline.md")
    )
    missing = [name for name in config.FLAGS if name not in docs]
    assert not missing, (
        "flags declared in utils/config.py but absent from the docs flag "
        "tables (docs/usage.md / docs/resilience.md / "
        "docs/observability.md / docs/overlap.md / docs/topology.md / "
        "docs/aot.md / docs/autotune.md / docs/serving.md / "
        "docs/moe.md / docs/compression.md / docs/pipeline.md): "
        + ", ".join(missing)
    )
