"""Static cost model: the traced integration half (docs/analysis.md
"Cost model").

``cost=True`` through ``mpx.analyze`` and through the ambient env path
(``MPI4JAX_TPU_ANALYZE_COST=on``) on the real 8-device mesh, the
tuning-file route end to end, the HLO/report byte-identity pins with
cost on vs off, and the seeded pipeline example
(examples/pipeline_parallel.py): the naive ladder must report MPX135,
its microbatched twin must not — and both must match the sequential
reference numerically.  The pure formula/simulation matrix lives in
tests/test_cost_pure.py.
"""

import json
import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import mpi4jax_tpu as mpx
from mpi4jax_tpu.analysis import costmodel
from helpers import ranks_arange, world

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", "examples"))


@pytest.fixture(autouse=True)
def _reset_analysis(monkeypatch):
    for var in ("MPI4JAX_TPU_ANALYZE", "MPI4JAX_TPU_ANALYZE_RANKS",
                "MPI4JAX_TPU_ANALYZE_COST", "MPI4JAX_TPU_COST_MODEL"):
        monkeypatch.delenv(var, raising=False)
    yield
    mpx.set_analyze_mode(None)
    mpx.clear_caches()


def codes(report):
    return [f.code for f in report.findings]


def _step(comm):
    def step(x):
        out, tok = mpx.allreduce(x, comm=comm)
        out2, _ = mpx.allreduce(mpx.varying(out * 0.5), comm=comm,
                                token=tok)
        return mpx.varying(out2)

    return step


# ---------------------------------------------------------------------------
# cost=True through mpx.analyze
# ---------------------------------------------------------------------------


def test_cost_through_analyze():
    comm, size = world()
    report = mpx.analyze(_step(comm), ranks_arange((64,)), comm=comm,
                         ranks="all", cost=True)
    assert not report.errors
    cost = report.cost
    assert cost is not None
    assert cost.total_us > 0
    assert cost.path_us > 0 and cost.dispatch_us > 0
    assert cost.ranks == tuple(range(size))
    assert cost.per_op["allreduce"]["count"] == 2
    assert cost.per_link["ici"]["bytes"] > 0  # single host: all ICI
    assert cost.per_link["dcn"]["bytes"] == 0
    assert cost.critical_path  # rendered rank by rank
    payload = report.to_json()
    assert payload["cost"]["total_us"] == pytest.approx(cost.total_us,
                                                        rel=1e-6)
    json.dumps(payload)  # CI-consumable end to end
    assert "predicted step time" in report.render()
    # compute estimate came from the per-rank jaxprs
    assert max(cost.compute_us.values()) > 0


def test_cost_implies_ranks_all():
    comm, size = world()
    report = mpx.analyze(_step(comm), ranks_arange((8,)), comm=comm,
                         cost=True)
    assert report.cost is not None
    assert list(report.meta["ranks"]) == list(range(size))


def test_cost_off_keeps_report_shape():
    comm, _ = world()
    report = mpx.analyze(_step(comm), ranks_arange((8,)), comm=comm,
                         ranks="all")
    assert report.cost is None
    assert "cost" not in report.to_json()
    assert "predicted step time" not in report.render()


def test_cost_memo_distinct_from_plain():
    # the cost=True report is memoized separately (the key grows a cost
    # stamp ONLY when the pass runs), so the two can never cross-serve
    comm, _ = world()
    step = _step(comm)
    x = ranks_arange((8,))
    plain = mpx.analyze(step, x, comm=comm, ranks="all")
    costed = mpx.analyze(step, x, comm=comm, ranks="all", cost=True)
    assert plain.cost is None and costed.cost is not None
    assert mpx.analyze(step, x, comm=comm, ranks="all") is plain
    assert mpx.analyze(step, x, comm=comm, ranks="all", cost=True) is costed


def test_tuning_file_through_analyze(tmp_path):
    comm, _ = world()
    payload = {
        "schema": costmodel.SCHEMA,
        "links": {"ici": {"alpha_us": 5.0, "gb_per_s": 10.0}},
    }
    path = tmp_path / "m.json"
    path.write_text(json.dumps(payload))
    slow = mpx.analyze(_step(comm), ranks_arange((8,)), comm=comm,
                       ranks="all", cost=True, cost_model=str(path))
    fast = mpx.analyze(_step(comm), ranks_arange((8,)), comm=comm,
                       ranks="all", cost=True)
    assert slow.cost.source == str(path)
    assert slow.cost.total_us > fast.cost.total_us  # 5 us alpha rounds
    # a malformed file is a loud error, not a silent default
    bad = tmp_path / "bad.json"
    bad.write_text("[]")
    with pytest.raises(ValueError, match="JSON object"):
        mpx.analyze(_step(comm), ranks_arange((8,)), comm=comm,
                    ranks="all", cost=True, cost_model=str(bad))


# ---------------------------------------------------------------------------
# the ambient env path
# ---------------------------------------------------------------------------


def test_env_mode_attaches_cost(monkeypatch):
    from mpi4jax_tpu.analysis.hook import set_report_sink

    comm, _ = world()
    monkeypatch.setenv("MPI4JAX_TPU_ANALYZE", "warn")
    monkeypatch.setenv("MPI4JAX_TPU_ANALYZE_COST", "on")
    mpx.clear_caches()
    sink = []
    set_report_sink(sink)
    try:
        @mpx.spmd(comm=comm)
        def step(x):
            out, _ = mpx.allreduce(x, comm=comm)
            return mpx.varying(out)

        step(ranks_arange((8,)))
    finally:
        set_report_sink(None)
    # a CLEAN report is sunk too when the cost pass ran: the CLI's
    # --cost breakdown artifacts cover clean programs
    assert sink, "cost-armed ambient pass sank no report"
    where, report = sink[-1]
    assert report.ok and report.cost is not None
    assert report.cost.total_us > 0


def test_hlo_byte_identical_with_cost_pass_armed(monkeypatch):
    # the cost pass is pure host-side arithmetic over the re-traced
    # schedules: the lowered HLO must stay byte-identical with it off,
    # on, and on-with-tuning-file (the acceptance pin)
    from mpi4jax_tpu.parallel.region import spmd

    comm, _ = world()
    x = ranks_arange((8,))

    def lower():
        mpx.clear_caches()
        twin = spmd(lambda v: mpx.varying(mpx.allreduce(v, comm=comm)[0]),
                    comm=comm, jit=False)
        return jax.jit(twin).lower(x).as_text()

    mpx.set_analyze_mode("warn")
    off = lower()
    monkeypatch.setenv("MPI4JAX_TPU_ANALYZE_COST", "on")
    on = lower()
    assert off == on


def test_cache_keys_identical_when_cost_off(monkeypatch):
    # cost=off must not change the analysis token folded into the
    # compiled-program cache keys; cost=on must (a flip retraces)
    from mpi4jax_tpu.analysis.hook import analysis_cache_token

    base = analysis_cache_token()
    monkeypatch.setenv("MPI4JAX_TPU_ANALYZE_COST", "off")
    assert analysis_cache_token() == base
    monkeypatch.setenv("MPI4JAX_TPU_ANALYZE_COST", "on")
    assert analysis_cache_token() != base


# ---------------------------------------------------------------------------
# the seeded pipeline example (MPX135 positive + its fix)
# ---------------------------------------------------------------------------


def _pipeline():
    import pipeline_parallel as pp

    comm, size = world()
    return pp, comm, size


def test_pipeline_ladder_matches_reference():
    pp, comm, size = _pipeline()
    batch, dim = 8, 16
    rng = np.random.default_rng(0)
    x = jnp.zeros((size, batch, dim), jnp.float32).at[0].set(
        jnp.asarray(rng.normal(size=(batch, dim)), jnp.float32))
    ws = jnp.asarray(rng.normal(size=(size, dim, dim)) * 0.5, jnp.float32)
    fwd, fwd_mb = pp.make_pipeline(comm)
    ref = pp.reference(x[0], ws)
    np.testing.assert_allclose(fwd(x, ws)[-1], ref, rtol=1e-5, atol=1e-5)
    m = pp.MICROBATCHES
    mbs = jnp.zeros((size, m, batch // m, dim), jnp.float32).at[0].set(
        x[0].reshape(m, batch // m, dim))
    out = fwd_mb(mbs, ws)[-1].reshape(batch, dim)
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-5)


def test_pipeline_ladder_reports_mpx135_microbatched_does_not():
    pp, comm, size = _pipeline()
    batch, dim = 8, 16
    x = jnp.zeros((size, batch, dim), jnp.float32)
    ws = jnp.zeros((size, dim, dim), jnp.float32)
    fwd, fwd_mb = pp.make_pipeline(comm)
    report = mpx.analyze(fwd, x, ws, ranks="all", cost=True)
    assert not report.errors, report.render()
    assert "MPX135" in codes(report)
    assert report.cost is not None and report.cost.total_us > 0
    # without the cost pass the ladder verifies clean: correct, not fast
    plain = mpx.analyze(fwd, x, ws, ranks="all")
    assert plain.ok, plain.render()
    # the GPipe fix: same math, no serialized chain on the critical path
    m = pp.MICROBATCHES
    mbs = jnp.zeros((size, m, batch // m, dim), jnp.float32)
    report_mb = mpx.analyze(fwd_mb, mbs, ws, ranks="all", cost=True)
    assert not report_mb.errors, report_mb.render()
    assert "MPX135" not in codes(report_mb)
