"""Hierarchical topology-aware collectives (ops/_hierarchy.py): the
synthetic-topology lockstep suite.

Extends the PR-2 lockstep simulator (tests/test_algos.py) to two-level
topologies: the hierarchical lowerings keep ALL of their static
structure — the host-partition geometry (``host_blocks``/``hier_split``),
the per-phase chunk/pair formulas (shared with ``_algos``), and the
per-link-class byte models — in plain functions polymorphic over Python
values, so this file drives the SAME functions through pure-Python
lockstep simulations:

- symbolic string folds pin that the two-level fold (intra-host
  ascending, then hosts ascending) is EXACTLY the flat ascending
  group-rank fold — associativity alone, never commutativity;
- exact-arithmetic numpy folds pin hierarchical == flat **bit-for-bit**
  for all 10 ``Op``s across the 2x4 / 4x2 / 8x1 (and 2x2) topologies;
- the non-uniform ``3,5`` split and the 1x8 single-host case pin the
  flat fallback (plan is ``None``, never an error);
- explicit per-round message counting pins the per-rank, per-link-class
  byte volumes (intra ≈ ``2·(r-1)/r·size`` over ICI, inter ≈
  ``2·(h-1)/h·size/r`` over DCN) — the bandwidth claim is a test.

Loaded under a private package name (``_load_isolated``, mirroring
tests/test_algos.py) so everything here runs even where the installed
JAX is below the package's hard floor; the traced integration half lives
in tests/test_hier_traced.py.
"""

import importlib
import os
import pathlib
import sys
import types

import numpy as np
import pytest

import test_algos as ta  # the PR-2 lockstep simulator (same directory)

REPO = pathlib.Path(__file__).resolve().parent.parent
PKG = REPO / "mpi4jax_tpu"

_ISO_NAME = "_mpx_hier_iso"


def _load_isolated():
    """Load utils/config, ops/_algos, ops/_hierarchy, parallel/topology,
    and parallel/comm under a private package name, bypassing
    ``mpi4jax_tpu/__init__.py`` (whose JAX-floor check refuses to import
    on old JAX) while preserving package context for relative imports."""
    if _ISO_NAME in sys.modules:
        return sys.modules[_ISO_NAME]
    root = types.ModuleType(_ISO_NAME)
    root.__path__ = [str(PKG)]
    sys.modules[_ISO_NAME] = root
    for sub in ("utils", "ops", "parallel"):
        m = types.ModuleType(f"{_ISO_NAME}.{sub}")
        m.__path__ = [str(PKG / sub)]
        sys.modules[f"{_ISO_NAME}.{sub}"] = m
        setattr(root, sub, m)
    for mod in ("utils.config", "ops._algos", "ops._hierarchy",
                "parallel.topology", "parallel.comm"):
        importlib.import_module(f"{_ISO_NAME}.{mod}")
    return root


ISO = _load_isolated()
al = ISO.ops._algos
hi = ISO.ops._hierarchy
config = ISO.utils.config
topo_mod = ISO.parallel.topology
comm_mod = ISO.parallel.comm

# the synthetic topology matrix of ISSUE 6: (hosts, ranks_per_host) over
# 8 ranks, plus a small 2x2; 1x8 is the single-host fallback case
TOPOLOGIES = [(2, 4), (4, 2), (8, 1), (2, 2)]


@pytest.fixture(autouse=True)
def _clean_env():
    saved = {
        k: os.environ.pop(k, None)
        for k in ("MPI4JAX_TPU_COLLECTIVE_ALGO",
                  "MPI4JAX_TPU_RING_CROSSOVER_BYTES",
                  "MPI4JAX_TPU_DCN_CROSSOVER_BYTES",
                  "MPI4JAX_TPU_ALLTOALL_CROSSOVER_BYTES",
                  "MPI4JAX_TPU_TOPOLOGY")
    }
    yield
    for k, v in saved.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v


def hosts_of(h, r):
    return tuple(b for b in range(h) for _ in range(r))


# ---------------------------------------------------------------------------
# spec parsing + the topology model
# ---------------------------------------------------------------------------


def test_parse_topology_spec():
    assert config.parse_topology_spec("") is None
    assert config.parse_topology_spec(None) is None
    assert config.parse_topology_spec("2x4") == (4, 4)
    assert config.parse_topology_spec("8x1") == (1,) * 8
    assert config.parse_topology_spec(" 4X2 ") == (2, 2, 2, 2)
    assert config.parse_topology_spec("3,5") == (3, 5)
    assert config.parse_topology_spec("1,2,5") == (1, 2, 5)
    for bad in ("2x", "x4", "0x4", "2x-1", "3,0", "a,b", "2x4x2", "nope"):
        with pytest.raises(ValueError, match="MPI4JAX_TPU_TOPOLOGY"):
            config.parse_topology_spec(bad)


def test_canonical_labels_and_topology():
    assert topo_mod.canonical_labels((7, 7, 3, 7)) == (0, 0, 1, 0)
    t = topo_mod.from_counts((3, 5))
    assert t.num_hosts == 2
    assert t.ranks_per_host == (3, 5)
    assert t.host_of_rank == (0, 0, 0, 1, 1, 1, 1, 1)
    # canonical: physical ids never matter
    assert topo_mod.Topology((9, 9, 2, 2)) == topo_mod.Topology((0, 0, 1, 1))
    assert hash(topo_mod.Topology((9, 9))) == hash(topo_mod.Topology((4, 4)))
    assert t.fingerprint() == t.host_of_rank


class SizedComm(comm_mod.Comm):
    """An unbound comm with a known world size — enough for the spec-
    driven topology derivation and plan construction."""

    def __init__(self, axes, world):
        super().__init__(axes)
        self._world = world

    def world_size(self):
        return self._world


def test_derive_world_topology_from_spec():
    os.environ["MPI4JAX_TPU_TOPOLOGY"] = "2x4"
    t = topo_mod.derive_world_topology(SizedComm("i", 8))
    assert t is not None and t.num_hosts == 2
    assert t.ranks_per_host == (4, 4)
    # a spec that does not cover this comm's world: flat fallback
    assert topo_mod.derive_world_topology(SizedComm("i", 4)) is None
    # no spec, no mesh: underivable
    del os.environ["MPI4JAX_TPU_TOPOLOGY"]
    assert topo_mod.derive_world_topology(SizedComm("i", 8)) is None


def test_derive_world_topology_nonuniform_spec():
    os.environ["MPI4JAX_TPU_TOPOLOGY"] = "3,5"
    t = topo_mod.derive_world_topology(SizedComm("i", 8))
    assert t is not None and t.ranks_per_host == (3, 5)


# ---------------------------------------------------------------------------
# plan geometry
# ---------------------------------------------------------------------------


def test_host_blocks_contiguous():
    assert hi.host_blocks((0, 1, 2, 3), (0, 0, 1, 1)) == [[0, 1], [2, 3]]
    assert hi.host_blocks((4, 5, 6, 7), hosts_of(2, 4)) == [[4, 5, 6, 7]]
    # round-robin placement: host 0 reappears -> no hierarchy
    assert hi.host_blocks((0, 1, 2, 3), (0, 1, 0, 1)) is None


@pytest.mark.parametrize("h,r", TOPOLOGIES)
def test_hier_split_uniform(h, r):
    k = h * r
    split = hi.hier_split((tuple(range(k)),), hosts_of(h, r))
    assert split is not None
    intra, inter, hh, rr = split
    assert (hh, rr) == (h, r)
    assert intra == tuple(tuple(range(b * r, (b + 1) * r)) for b in range(h))
    assert inter == tuple(tuple(b * r + j for b in range(h))
                          for j in range(r))
    # both levels partition the whole world
    assert sorted(m for g in intra for m in g) == list(range(k))
    assert sorted(m for g in inter for m in g) == list(range(k))


def test_hier_split_fallbacks():
    # single host (1x8): nothing to hierarchize
    assert hi.hier_split((tuple(range(8)),), hosts_of(1, 8)) is None
    # non-uniform 3/5 split: per-host sizes differ
    assert hi.hier_split((tuple(range(8)),), (0, 0, 0, 1, 1, 1, 1, 1)) is None
    # non-contiguous (round-robin) placement
    assert hi.hier_split((tuple(range(4)),), (0, 1, 0, 1)) is None
    # per-group hierarchies differ: inexpressible in one SPMD program
    assert hi.hier_split(((0, 1, 2, 3), (4, 5, 6, 7)),
                         (0, 0, 1, 1, 2, 2, 2, 2)) is None


def test_hier_split_color_groups():
    # a color split whose groups each span both hosts
    hosts = hosts_of(2, 4)
    split = hi.hier_split(((0, 1, 4, 5), (2, 3, 6, 7)), hosts)
    assert split is not None
    intra, inter, h, r = split
    assert (h, r) == (2, 2)
    assert intra == ((0, 1), (4, 5), (2, 3), (6, 7))
    assert inter == ((0, 4), (1, 5), (2, 6), (3, 7))
    # groups that sit entirely within one host: no hierarchy
    assert hi.hier_split(((0, 1, 2, 3), (4, 5, 6, 7)), hosts) is None


def test_hier_plan_from_spec_and_memo():
    os.environ["MPI4JAX_TPU_TOPOLOGY"] = "2x4"
    comm = SizedComm("i", 8)
    plan = hi.hier_plan(comm)
    assert plan is not None and (plan.h, plan.r) == (2, 4)
    assert plan.intra.groups == ((0, 1, 2, 3), (4, 5, 6, 7))
    assert plan.inter.groups == ((0, 4), (1, 5), (2, 6), (3, 7))
    assert hi.hier_plan(comm) is plan  # memoized
    # non-uniform topology: no plan, never an error
    os.environ["MPI4JAX_TPU_TOPOLOGY"] = "3,5"
    assert hi.hier_plan(SizedComm("i", 8)) is None
    # single host
    os.environ["MPI4JAX_TPU_TOPOLOGY"] = "1x8"
    assert hi.hier_plan(SizedComm("i", 8)) is None


def test_hier_plan_on_color_split():
    os.environ["MPI4JAX_TPU_TOPOLOGY"] = "2x4"
    parent = SizedComm("i", 8)
    gc = comm_mod.GroupComm(parent, ((0, 1, 4, 5), (2, 3, 6, 7)))
    gc.world_size = lambda: 8
    plan = hi.hier_plan(gc)
    assert plan is not None and (plan.h, plan.r) == (2, 2)
    assert plan.intra.groups == ((0, 1), (4, 5), (2, 3), (6, 7))
    # groups within one host each: flat fallback
    gc2 = comm_mod.GroupComm(parent, ((0, 1, 2, 3), (4, 5, 6, 7)))
    gc2.world_size = lambda: 8
    assert hi.hier_plan(gc2) is None


def test_comm_hosts():
    os.environ["MPI4JAX_TPU_TOPOLOGY"] = "2x4"
    assert hi.comm_hosts(SizedComm("i", 8)) == 2
    os.environ["MPI4JAX_TPU_TOPOLOGY"] = "3,5"
    assert hi.comm_hosts(SizedComm("i", 8)) == 2  # non-uniform still spans 2
    del os.environ["MPI4JAX_TPU_TOPOLOGY"]
    assert hi.comm_hosts(SizedComm("i", 8)) is None


def test_uniform_size_accessor():
    """Satellite: the explicit ``uniform_size`` accessor — ``None`` for
    unequal splits, the size otherwise, and ``static_group_size``
    delegates to it (behavior identical to the old RuntimeError dance)."""
    parent = SizedComm("i", 8)
    equal = comm_mod.GroupComm(parent, ((0, 1, 2), (3, 4, 5)))
    unequal = comm_mod.GroupComm(parent, ((0, 1, 2), (3, 4)))
    assert equal.uniform_size() == 3
    assert unequal.uniform_size() is None
    assert al.static_group_size(equal) == 3
    assert al.static_group_size(unequal) is None
    # Get_size keeps its loud error for the gather family
    with pytest.raises(RuntimeError, match="unequal group sizes"):
        unequal.Get_size()
    assert equal.Get_size() == 3
    # a whole-axes comm outside any trace still maps to None
    assert al.static_group_size(comm_mod.Comm("i")) is None


# ---------------------------------------------------------------------------
# lockstep simulation: hierarchical == flat, bit for bit
# ---------------------------------------------------------------------------


def sim_hier_allreduce(xs, fn, h, r, preserve):
    """Pure-Python lockstep of ``apply_hier_allreduce``: ``xs[g][c]`` is
    rank ``g``'s chunk ``c`` (``r`` chunks per rank, hosts contiguous);
    returns ``out[g][c]``.  Phase 1/3 drive the SAME ring machinery as
    the flat simulator (tests/test_algos.py); phase 2 folds the per-host
    partials in ascending host order (the order both inter algorithms
    deliver — the butterfly by construction, the ring via the
    order-preserving pair, pinned in test_algos)."""
    k = h * r
    partial = [None] * k
    for b in range(h):
        members = list(range(b * r, (b + 1) * r))
        if r == 1:
            partial[members[0]] = xs[members[0]][0]
        else:
            blocks = [[xs[m][c] for c in range(r)] for m in members]
            out = ta.sim_ring_reduce_scatter(blocks, fn, r, preserve)
            for j, m in enumerate(members):
                partial[m] = out[j]
    reduced = []
    for j in range(r):
        acc = partial[j]
        for b in range(1, h):
            acc = fn(acc, partial[b * r + j])
        reduced.append(acc)
    # intra allgather: every rank of every host reassembles all r chunks
    return [list(reduced) for _ in range(k)]


def flat_fold(xs, fn, k, r):
    """The flat reference: chunk ``c``'s ascending group-rank fold."""
    out = []
    for c in range(r):
        acc = xs[0][c]
        for g in range(1, k):
            acc = fn(acc, xs[g][c])
        out.append(acc)
    return out


@pytest.mark.parametrize("h,r", TOPOLOGIES)
def test_hier_allreduce_preserves_ascending_fold_order(h, r):
    # string concatenation: associative, non-commutative, fully
    # observable — the two-level fold must produce the IDENTICAL operand
    # sequence as the flat ascending fold, or the string differs
    k = h * r
    xs = [[f"({g}:{c})" for c in range(r)] for g in range(k)]
    fn = lambda a, b: a + b  # noqa: E731
    out = sim_hier_allreduce(xs, fn, h, r, preserve=True)
    expected = flat_fold(xs, fn, k, r)
    for g in range(k):
        assert out[g] == expected, (h, r, g)
        for c in range(r):
            assert out[g][c] == "".join(f"({j}:{c})" for j in range(k))


@pytest.mark.parametrize("opname,npfn", [
    ("SUM", np.add), ("PROD", np.multiply), ("MIN", np.minimum),
    ("MAX", np.maximum), ("LAND", np.logical_and), ("LOR", np.logical_or),
    ("LXOR", np.logical_xor), ("BAND", np.bitwise_and),
    ("BOR", np.bitwise_or), ("BXOR", np.bitwise_xor),
])
@pytest.mark.parametrize("h,r", TOPOLOGIES)
def test_hier_allreduce_all_ops_bit_for_bit(opname, npfn, h, r):
    # exact-arithmetic data (small integers, bools, bitmasks): every fold
    # association is exact, so hierarchical == flat must hold BIT FOR BIT
    import zlib

    k = h * r
    rng = np.random.default_rng(zlib.crc32(f"hier/{opname}/{h}x{r}".encode()))
    if opname in ("LAND", "LOR", "LXOR"):
        data = rng.integers(0, 2, size=(k, r, 3)).astype(bool)
    elif opname in ("BAND", "BOR", "BXOR"):
        data = rng.integers(0, 255, size=(k, r, 3)).astype(np.int32)
    elif opname == "PROD":
        # k <= 8 factors of 1..3 stay exact in float64
        data = rng.integers(1, 4, size=(k, r, 3)).astype(np.float64)
    else:
        data = rng.integers(-100, 100, size=(k, r, 3)).astype(np.float64)
    xs = [[data[g, c] for c in range(r)] for g in range(k)]
    out = sim_hier_allreduce(xs, npfn, h, r, preserve=False)
    expected = flat_fold(xs, npfn, k, r)
    for g in range(k):
        for c in range(r):
            assert np.array_equal(np.asarray(out[g][c]),
                                  np.asarray(expected[c])), (h, r, g, c)


def sim_hier_reduce_scatter(blocks, fn, h, r, preserve):
    """Lockstep of ``apply_hier_reduce_scatter``: ``blocks[g][i]`` is
    rank ``g``'s block addressed to rank ``i``; returns ``final[g]`` —
    the fold rank ``g`` ends up owning.  The intra phase reduce-scatters
    position SUPER-blocks (one list entry per host), the inter phase
    reduce-scatters the per-host partials."""
    k = h * r

    def fnl(A, B):
        return [fn(a, b) for a, b in zip(A, B)]

    partial = [None] * k  # partial[m] = per-host list of intra folds
    for b in range(h):
        members = list(range(b * r, (b + 1) * r))
        sb = [
            [[blocks[m][bp * r + j] for bp in range(h)] for j in range(r)]
            for m in members
        ]
        if r == 1:
            partial[members[0]] = sb[0][0]
        else:
            out = ta.sim_ring_reduce_scatter(sb, fnl, r, preserve)
            for j, m in enumerate(members):
                partial[m] = out[j]
    final = [None] * k
    for j in range(r):
        mem = [b * r + j for b in range(h)]
        if h == 1:
            final[mem[0]] = partial[mem[0]][0]
        else:
            blocks2 = [[partial[m][c] for c in range(h)] for m in mem]
            out2 = ta.sim_ring_reduce_scatter(blocks2, fn, h, preserve)
            for b, m in enumerate(mem):
                final[m] = out2[b]
    return final


@pytest.mark.parametrize("h,r", TOPOLOGIES)
def test_hier_reduce_scatter_preserves_fold_order(h, r):
    k = h * r
    blocks = [[f"({g}:{i})" for i in range(k)] for g in range(k)]
    fn = lambda a, b: a + b  # noqa: E731
    final = sim_hier_reduce_scatter(blocks, fn, h, r, preserve=True)
    for g in range(k):
        assert final[g] == "".join(f"({j}:{g})" for j in range(k)), (h, r, g)


@pytest.mark.parametrize("opname,npfn", [
    ("SUM", np.add), ("PROD", np.multiply), ("MIN", np.minimum),
    ("MAX", np.maximum), ("LAND", np.logical_and), ("LOR", np.logical_or),
    ("LXOR", np.logical_xor), ("BAND", np.bitwise_and),
    ("BOR", np.bitwise_or), ("BXOR", np.bitwise_xor),
])
@pytest.mark.parametrize("h,r", [(2, 4), (4, 2), (8, 1)])
def test_hier_reduce_scatter_all_ops_bit_for_bit(opname, npfn, h, r):
    import zlib

    k = h * r
    rng = np.random.default_rng(
        zlib.crc32(f"hier-rs/{opname}/{h}x{r}".encode()))
    if opname in ("LAND", "LOR", "LXOR"):
        data = rng.integers(0, 2, size=(k, k, 3)).astype(bool)
    elif opname in ("BAND", "BOR", "BXOR"):
        data = rng.integers(0, 255, size=(k, k, 3)).astype(np.int32)
    elif opname == "PROD":
        data = rng.integers(1, 4, size=(k, k, 3)).astype(np.float64)
    else:
        data = rng.integers(-100, 100, size=(k, k, 3)).astype(np.float64)
    blocks = [[data[g, i] for i in range(k)] for g in range(k)]
    final = sim_hier_reduce_scatter(blocks, npfn, h, r, preserve=False)
    for g in range(k):
        expected = data[0, g]
        for j in range(1, k):
            expected = npfn(expected, data[j, g])
        assert np.array_equal(np.asarray(final[g]), np.asarray(expected)), \
            (h, r, g)


def _sim_intra_scatter(payloads, j0, r):
    """Chunk-level lockstep of the intra-host binomial scatter phase of
    ``apply_hier_bcast`` over one host block of ``r`` positions, rooted
    at position ``j0`` (drives the REAL ``vdg_scatter_pairs`` — the same
    clamped-slice semantics as the traced applier).  ``payloads[p]`` is
    position ``p``'s R-padded chunk list; returns the chunk each
    position holds afterwards plus the rel index table."""
    R = al.next_pow2(r)
    rel = [(p - j0) % r for p in range(r)]
    buf = [list(payloads[p]) for p in range(r)]
    groups = [tuple(range(r))]
    for w in al.vdg_widths(R):
        pairs = al.vdg_scatter_pairs(groups, j0, w, R)

        def slab(p):
            start = min(max(rel[p] + w, 0), R - w)
            return buf[p][start:start + w]

        recvd = {d: slab(s) for s, d in pairs}
        for p in range(r):
            if rel[p] % (2 * w) == w:
                assert p in recvd, (r, j0, w, p)
                start = min(max(rel[p], 0), R - w)
                for i, v in enumerate(recvd[p]):
                    buf[p][start + i] = v
    return [buf[p][rel[p]] for p in range(r)], rel


@pytest.mark.parametrize("h,r", TOPOLOGIES)
def test_hier_bcast_delivers_root_payload(h, r):
    # every (root, rank): the scatter -> inter-bcast -> allgather chain
    # must reassemble exactly the root's chunks on every rank
    k = h * r
    R = al.next_pow2(r)
    for root in range(k):
        b0, j0 = divmod(root, r)
        held = {}
        rel = None
        for b in range(h):
            members = [b * r + p for p in range(r)]
            payloads = [[("P", m, c) for c in range(R)] for m in members]
            vals, rel = _sim_intra_scatter(payloads, j0, r)
            for p, m in enumerate(members):
                held[m] = vals[p]
        # after the intra scatter, position p of the ROOT's host holds
        # chunk rel(p) of the root's payload (other hosts hold their own
        # position-j0 member's chunks — replaced by the inter bcast)
        for p in range(r):
            assert held[b0 * r + p] == ("P", root, rel[p])
        # inter bcast per position group from host b0 (group-bcast
        # semantics pinned by test_algos' vdg/doubling suites)
        for p in range(r):
            src = held[b0 * r + p]
            for b in range(h):
                held[b * r + p] = src
        # intra ring allgather by rel chunk index (trivial at r == 1)
        for b in range(h):
            members = [b * r + p for p in range(r)]
            if r == 1:
                out = [[held[members[0]]]]
            else:
                out = ta.sim_ring_allgather([held[m] for m in members],
                                            rel, r)
            for p, m in enumerate(members):
                assert out[p] == [("P", root, c) for c in range(r)], \
                    (h, r, root, m)


# ---------------------------------------------------------------------------
# per-link-class byte volumes: the bandwidth claim as a test
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("h,r", TOPOLOGIES)
def test_hier_allreduce_byte_volumes(h, r):
    n = 64 * 1024  # payload bytes, divisible by every r in the matrix
    chunk = -(-n // r)
    intra, inter = hi.hier_link_bytes("allreduce", n, h, r)
    # intra: (r-1) reduce-scatter rounds + (r-1) allgather rounds, one
    # chunk each — the simulated round count, not a free-floating formula
    assert intra == (r - 1) * chunk * 2
    if r > 1:
        assert intra == int(2 * (r - 1) / r * n)  # == 2·(r-1)/r·size
    # inter: the DCN algorithm on ONE chunk over h hosts (butterfly at
    # the 4 MiB default crossover and these sizes)
    dcn = al.resolve_dcn_algo(chunk, h, ring_ok=True)
    assert dcn == "butterfly"
    assert inter == al.algorithm_bytes_per_rank("butterfly", chunk, h)
    # the whole point of the two-level split: DCN traffic scales with
    # size/r, never with the full payload times log k
    if h > 1:
        assert inter <= 2 * ((h - 1).bit_length()) * chunk


@pytest.mark.parametrize("h,r", [(4, 2), (8, 1)])
def test_hier_allreduce_dcn_ring_byte_volumes(h, r):
    # drop the DCN crossover so the inter phase rings: per-rank DCN bytes
    # must hit the bandwidth-optimal 2·(h-1)/h·(size/r) bound
    os.environ["MPI4JAX_TPU_DCN_CROSSOVER_BYTES"] = "1"
    n = 64 * 1024
    chunk = -(-n // r)
    assert al.resolve_dcn_algo(chunk, h, ring_ok=True) == "ring"
    intra, inter = hi.hier_link_bytes("allreduce", n, h, r)
    assert inter == al.algorithm_bytes_per_rank("ring", chunk, h)
    assert inter == (h - 1) * (-(-chunk // h)) * 2
    assert inter <= 2 * chunk  # bandwidth-optimal bound on the shard
    # order-preserving callables ship the lo/hi pair intra-host but the
    # DCN phase keeps the butterfly (never re-chunks a callable)
    intra_p, inter_p = hi.hier_link_bytes("allreduce", n, h, r,
                                          preserve=True)
    assert intra_p == (r - 1) * chunk * 3
    assert inter_p == al.algorithm_bytes_per_rank("butterfly", chunk, h,
                                                  True)


def test_hier_reduce_scatter_and_bcast_byte_models():
    n = 64 * 1024
    h, r = 2, 4
    chunk = -(-n // r)
    intra, inter = hi.hier_link_bytes("reduce_scatter", n, h, r)
    assert intra == (r - 1) * chunk  # no allgather phase
    assert inter == 2 * (h - 1).bit_length() * chunk  # butterfly + select
    intra_b, inter_b = hi.hier_link_bytes("bcast", n, h, r)
    assert intra_b == n + (r - 1) * chunk  # halving scatter + allgather
    assert inter_b == (h - 1).bit_length() * chunk  # doubling rounds
    with pytest.raises(ValueError, match="unknown hierarchical"):
        hi.hier_link_bytes("scan", n, h, r)


def test_flat_link_bytes_classification():
    n = 1 << 20
    # single host (or unknown): everything is intra
    assert hi.flat_link_bytes("allreduce", "ring", n, 8, None) == \
        (al.algorithm_bytes_per_rank("ring", n, 8), 0)
    assert hi.flat_link_bytes("allreduce", "butterfly", n, 8, 1) == \
        (al.algorithm_bytes_per_rank("butterfly", n, 8), 0)
    # multi-host: a flat algorithm's every round gates on DCN
    assert hi.flat_link_bytes("allreduce", "ring", n, 8, 2) == \
        (0, al.algorithm_bytes_per_rank("ring", n, 8))
    # native HLO: payload proxy on intra (XLA schedules it, we don't)
    assert hi.flat_link_bytes("allreduce", "native", n, 8, 2) == (n, 0)


def test_flat_link_bytes_per_kind_models():
    # the flat models mirror each lowering round for round, so the
    # flat-vs-hier link comparison in the telemetry report is fair
    n, k = 1 << 20, 8
    chunk = n // k
    # doubling broadcast ships the payload once per round, not twice
    assert hi.flat_link_bytes("bcast", "butterfly", n, k, 2) == \
        (0, 3 * n)
    # van de Geijn: halving scatter (~size) + ring allgather
    assert hi.flat_link_bytes("bcast", "ring", n, k, 2) == \
        (0, n + (k - 1) * chunk)
    # reduce_scatter's ring has no allgather phase
    assert hi.flat_link_bytes("reduce_scatter", "ring", n, k, 2) == \
        (0, (k - 1) * chunk)
    assert hi.flat_link_bytes("reduce_scatter", "ring", n, k, 2,
                              preserve=True) == (0, (k - 1) * chunk * 2)
    # butterfly reduce_scatter = allreduce + own-block select
    assert hi.flat_link_bytes("reduce_scatter", "butterfly", n, k, 1) == \
        (2 * 3 * n, 0)


# ---------------------------------------------------------------------------
# selection
# ---------------------------------------------------------------------------


def test_resolve_algo_hier_rules():
    cross = config.ring_crossover_bytes()
    # auto: hier only when expressible AND the payload clears the ring
    # crossover on a big-enough group
    assert al.resolve_algo("auto", cross, 8, True, hier_ok=True) == "hier"
    assert al.resolve_algo("auto", cross - 1, 8, True,
                           hier_ok=True) == "butterfly"
    assert al.resolve_algo("auto", cross, 8, True, hier_ok=False) == "ring"
    assert al.resolve_algo("auto", cross, 2, True, hier_ok=True) == \
        "butterfly"  # below RING_MIN_GROUP
    # forced hier wins whenever expressible, any payload
    assert al.resolve_algo("hier", 1, 8, True, hier_ok=True) == "hier"
    assert al.resolve_algo("hier", 1, 8, False, hier_ok=True) == "hier"
    # forced hier falls back to the auto rules where inexpressible —
    # never an error
    assert al.resolve_algo("hier", cross, 8, True, hier_ok=False) == "ring"
    assert al.resolve_algo("hier", cross - 1, 8, True,
                           hier_ok=False) == "butterfly"
    assert al.resolve_algo("hier", cross, 8, False,
                           hier_ok=False) == "butterfly"
    # forced flat algorithms still win over an expressible hierarchy
    # (the MPX113 advisory's trigger)
    assert al.resolve_algo("ring", cross, 8, True, hier_ok=True) == "ring"
    assert al.resolve_algo("butterfly", cross, 8, True,
                           hier_ok=True) == "butterfly"


def test_resolve_dcn_algo():
    cross = config.dcn_crossover_bytes()
    assert cross == config.DEFAULT_DCN_CROSSOVER_BYTES
    assert al.resolve_dcn_algo(cross, 8) == "ring"
    assert al.resolve_dcn_algo(cross - 1, 8) == "butterfly"
    assert al.resolve_dcn_algo(cross, 2) == "butterfly"  # tiny host count
    assert al.resolve_dcn_algo(cross, 8, ring_ok=False) == "butterfly"
    os.environ["MPI4JAX_TPU_DCN_CROSSOVER_BYTES"] = "256"
    assert al.resolve_dcn_algo(256, 8) == "ring"
    assert al.resolve_dcn_algo(255, 8) == "butterfly"


def test_dcn_crossover_parsing():
    os.environ["MPI4JAX_TPU_DCN_CROSSOVER_BYTES"] = "-3"
    with pytest.raises(ValueError, match="must be >= 0"):
        config.dcn_crossover_bytes()
    os.environ["MPI4JAX_TPU_DCN_CROSSOVER_BYTES"] = "4MB"
    with pytest.raises(ValueError, match="could not be parsed"):
        config.dcn_crossover_bytes()


def test_algo_cache_token_reflects_topology_knobs():
    # mirrors test_algos.py::test_algo_cache_token_reflects_every_knob:
    # the topology fingerprint and DCN crossover must move the compiled-
    # program cache keys, or toggling them would serve stale programs
    base = al.algo_cache_token()
    tokens = {base}
    os.environ["MPI4JAX_TPU_TOPOLOGY"] = "2x4"
    tokens.add(al.algo_cache_token())
    os.environ["MPI4JAX_TPU_TOPOLOGY"] = "4x2"
    tokens.add(al.algo_cache_token())
    os.environ["MPI4JAX_TPU_DCN_CROSSOVER_BYTES"] = "123"
    tokens.add(al.algo_cache_token())
    assert len(tokens) == 4
    del os.environ["MPI4JAX_TPU_TOPOLOGY"]
    del os.environ["MPI4JAX_TPU_DCN_CROSSOVER_BYTES"]
    assert al.algo_cache_token() == base


# ---------------------------------------------------------------------------
# alltoall: pairwise exchange + the two-level hierarchical split
# ---------------------------------------------------------------------------


def sim_pairwise_alltoall(blocks, k):
    """Pure-Python lockstep of ``apply_pairwise_alltoall`` driving the
    REAL index formulas (``rotation_pairs``/``a2a_send_block``/
    ``a2a_recv_slot``): ``blocks[p][q]`` is position ``p``'s block
    addressed to ``q``; returns ``out`` with ``out[q][p]`` = the block
    ``p`` addressed to ``q`` (the alltoall contract)."""
    out = [[None] * k for _ in range(k)]
    for p in range(k):
        out[p][p] = blocks[p][p]
    groups = (tuple(range(k)),)
    for t in range(1, k):
        pairs = al.rotation_pairs(groups, t)
        sent = {src: blocks[src][al.a2a_send_block(src, t, k)]
                for src, _ in pairs}
        for src, dst in pairs:
            slot = al.a2a_recv_slot(dst, t, k)
            assert slot == src, (k, t, src, dst)  # the rotation inverse
            out[dst][slot] = sent[src]
    return out


@pytest.mark.parametrize("k", [1, 2, 3, 4, 8])
def test_pairwise_alltoall_routing(k):
    blocks = [[("B", p, q) for q in range(k)] for p in range(k)]
    out = sim_pairwise_alltoall(blocks, k)
    for q in range(k):
        for p in range(k):
            assert out[q][p] == ("B", p, q), (k, p, q)


def sim_hier_alltoall(blocks, h, r):
    """Lockstep of ``apply_hier_alltoall`` phase for phase: intra-host
    transpose (pairwise over each host block) → inter-host exchange of
    host-aggregated blocks (pairwise over each position group) → local
    de-interleave.  ``blocks[g][g']`` = rank ``g``'s block addressed to
    group position ``g'``; returns ``final[g][g']`` = the block ``g'``
    addressed to ``g``."""
    k = h * r
    A = {}
    for b in range(h):
        payload = [
            [[blocks[b * r + i][bp * r + j] for bp in range(h)]
             for j in range(r)]
            for i in range(r)
        ]
        out1 = sim_pairwise_alltoall(payload, r)
        for j in range(r):
            A[(b, j)] = out1[j]  # A[(b,j)][i][b'] = x_{(b,i)}[b'·r+j]
    final = [[None] * k for _ in range(k)]
    for j in range(r):
        payload2 = [
            [[A[(b, j)][i][bp] for i in range(r)] for bp in range(h)]
            for b in range(h)
        ]
        out2 = sim_pairwise_alltoall(payload2, h)
        for b in range(h):
            for bpp in range(h):
                for i in range(r):
                    final[b * r + j][bpp * r + i] = out2[b][bpp][i]
    return final


@pytest.mark.parametrize("h,r", TOPOLOGIES)
def test_hier_alltoall_bit_identical_to_flat(h, r):
    # pure routing: the two-level composition must deliver EXACTLY the
    # flat permutation — symbolic blocks make any misrouting visible
    k = h * r
    blocks = [[("B", g, d) for d in range(k)] for g in range(k)]
    final = sim_hier_alltoall(blocks, h, r)
    for g in range(k):
        for src in range(k):
            assert final[g][src] == ("B", src, g), (h, r, g, src)


@pytest.mark.parametrize("h,r", TOPOLOGIES)
def test_hier_alltoall_numpy_bit_for_bit(h, r):
    import zlib

    k = h * r
    rng = np.random.default_rng(zlib.crc32(f"a2a/{h}x{r}".encode()))
    data = rng.standard_normal((k, k, 3)).astype(np.float32)
    blocks = [[data[g, d] for d in range(k)] for g in range(k)]
    final = sim_hier_alltoall(blocks, h, r)
    for g in range(k):
        for src in range(k):
            assert np.array_equal(final[g][src], data[src, g]), (h, r, g)


@pytest.mark.parametrize("h,r", TOPOLOGIES)
def test_alltoall_byte_and_message_models(h, r):
    n = 64 * 1024
    k = h * r
    intra, inter = hi.hier_link_bytes("alltoall", n, h, r)
    # phase 1 ships (r-1) destination blocks of size/r over ICI; phase 2
    # ships (h-1) host-aggregated blocks of size/h over DCN
    assert intra == (r - 1) * (-(-n // r))
    assert inter == (h - 1) * (-(-n // h))
    flat_intra, flat_inter = hi.flat_link_bytes("alltoall", "native", n,
                                                k, h)
    assert (flat_intra, flat_inter) == (0, (k - 1) * (n // k))
    # single host / unknown topology: the flat volume lands on ICI
    assert hi.flat_link_bytes("alltoall", "native", n, k, 1) == \
        ((k - 1) * (n // k), 0)
    assert hi.flat_link_bytes("alltoall", "pairwise", n, k, None) == \
        ((k - 1) * (n // k), 0)
    # hier never ships MORE DCN bytes than the flat attribution...
    assert inter <= flat_inter
    # ...and the DCN message model is exactly 1/r of flat — the
    # acceptance claim of BENCH_alltoall.json
    msgs_flat, msgs_hier = hi.alltoall_dcn_messages(h, r)
    assert msgs_flat == r * r * h * (h - 1)
    assert msgs_hier * r == msgs_flat


def test_resolve_alltoall_algo_rules():
    cross = config.alltoall_crossover_bytes()
    assert cross == config.DEFAULT_ALLTOALL_CROSSOVER_BYTES
    # auto: hier only when expressible AND at/above the crossover
    assert al.resolve_alltoall_algo("auto", cross, True) == "hier"
    assert al.resolve_alltoall_algo("auto", cross - 1, True) == "native"
    assert al.resolve_alltoall_algo("auto", cross, False) == "native"
    # forced hier wins where expressible, falls back flat otherwise
    assert al.resolve_alltoall_algo("hier", 1, True) == "hier"
    assert al.resolve_alltoall_algo("hier", 1, False) == "native"
    # forced flat algorithms keep the flat exchange (MPX137's trigger)
    assert al.resolve_alltoall_algo("butterfly", cross, True) == "native"
    assert al.resolve_alltoall_algo("ring", cross, True) == "native"
    # the async split's flat form is the pairwise exchange
    assert al.resolve_alltoall_algo("auto", 1, True,
                                    flat="pairwise") == "pairwise"
    os.environ["MPI4JAX_TPU_ALLTOALL_CROSSOVER_BYTES"] = "256"
    assert al.resolve_alltoall_algo("auto", 256, True) == "hier"
    assert al.resolve_alltoall_algo("auto", 255, True) == "native"


def test_algo_cache_token_reflects_alltoall_crossover():
    base = al.algo_cache_token()
    os.environ["MPI4JAX_TPU_ALLTOALL_CROSSOVER_BYTES"] = "123"
    tok = al.algo_cache_token()
    assert tok != base
    del os.environ["MPI4JAX_TPU_ALLTOALL_CROSSOVER_BYTES"]
    assert al.algo_cache_token() == base


# ---------------------------------------------------------------------------
# elastic row/column shrink: hierarchical == flat on the shrunken grid
# ---------------------------------------------------------------------------


def test_hier_flat_equality_on_the_shrunken_grid():
    """The elastic Cartesian shrink (resilience/elastic.py fail_unit)
    removes whole grid rows/columns; the renumbered world must keep the
    hierarchical == flat fold equality — the lockstep pin for the comms
    a row-shrunken training run retraces with."""
    el = importlib.import_module(f"{_ISO_NAME}.resilience.elastic")
    cases = [
        ((2, 4), {5}, "row", (1, 4)),   # (2,4) -> (1,4): 4 ranks
        ((2, 4), {5}, "col", (2, 3)),   # (2,4) -> (2,3): 6 ranks
        ((4, 2), {3}, "row", (3, 2)),   # (4,2) -> (3,2): 6 ranks
    ]
    for shape, failed, unit, expect_shape in cases:
        dead = el.expand_fail_unit(failed, shape, unit)
        new_shape = el.shrunken_shape(shape, dead, unit)
        assert new_shape == expect_shape, (shape, failed, unit)
        h, r = new_shape
        k = h * r
        rmap = el.compact_rank_map(shape[0] * shape[1], dead)
        assert sorted(rmap.values()) == list(range(k))
        # string fold: the two-level fold over the shrunken world's
        # host blocks is EXACTLY the flat ascending fold (only
        # associativity, observable operand order)
        xs = [[f"({g}:{c})" for c in range(r)] for g in range(k)]
        fn = lambda a, b: a + b  # noqa: E731
        out = sim_hier_allreduce(xs, fn, h, r, preserve=True)
        expected = flat_fold(xs, fn, k, r)
        for g in range(k):
            assert out[g] == expected, (shape, unit, g)
        # exact-arithmetic numpy fold: bit-for-bit equality
        rng = np.random.default_rng(100 + k)
        data = rng.integers(-100, 100, size=(k, r, 3)).astype(np.float64)
        xs = [[data[g, c] for c in range(r)] for g in range(k)]
        out = sim_hier_allreduce(xs, np.add, h, r, preserve=False)
        expected = flat_fold(xs, np.add, k, r)
        for g in range(k):
            for c in range(r):
                assert np.array_equal(np.asarray(out[g][c]),
                                      np.asarray(expected[c])), (shape, g, c)
