"""Runtime type validation: enforce_types and its application to public ops.

Ports ref tests/test_validation.py (decorator unit tests incl. the
tracer-error path, ref _src/validation.py:77-88) and adds live-decorator
coverage: every public op rejects wrong-typed structural arguments at call
time, like the reference which decorates every public function.
"""

import jax
import jax.numpy as jnp
import pytest

import mpi4jax_tpu as mpx
from mpi4jax_tpu.utils.validation import enforce_types
from helpers import ranks_arange, world


def test_enforce_types_basic():
    @enforce_types(y=(int, str))
    def foo(x, y):
        return y

    assert foo(1, 2) == 2
    assert foo("a", "b") == "b"

    with pytest.raises(TypeError, match="wrong type float"):
        foo(1, 2.5)


def test_enforce_types_none_shorthand():
    @enforce_types(y=(int, None))
    def foo(x, y=None):
        return y

    assert foo(1) is None
    assert foo(1, 2) == 2
    with pytest.raises(TypeError, match="wrong type str"):
        foo(1, "nope")


def test_enforce_types_invalid_argname():
    # ref test_validation.py: decorating a nonexistent argument is an error
    def foo(x):
        pass

    with pytest.raises(ValueError, match="no argument 'a'"):
        enforce_types(a=int)(foo)


def test_enforce_types_tracer_message():
    # ref _src/validation.py:77-88 — a tracer where a static value is
    # expected must point the user at static_argnums
    @enforce_types(x=int)
    def foo(x):
        return x

    assert jax.jit(foo, static_argnums=(0,))(3) == 3

    with pytest.raises(TypeError, match="static_argnums"):
        jax.jit(foo)(3)


# --- the decorator is live on every public op -----------------------------

ROOT_OPS = ["bcast", "gather", "reduce", "scatter"]


@pytest.mark.parametrize("opname", ROOT_OPS)
def test_root_ops_reject_nonint_root(opname):
    world()
    op = getattr(mpx, opname)
    x = ranks_arange((1,))
    args = (x, mpx.SUM, 0.5) if opname == "reduce" else (x, 0.5)
    with pytest.raises(TypeError, match="'root'"):
        op(*args)


@pytest.mark.parametrize("opname", ROOT_OPS)
def test_root_ops_reject_traced_root(opname):
    world()
    op = getattr(mpx, opname)

    def f(x, root):
        if opname == "reduce":
            return op(x, mpx.SUM, root)[0]
        return op(x, root)[0]

    with pytest.raises(TypeError, match="static_argnums"):
        jax.jit(f)(ranks_arange((1,)), 0)


def test_send_recv_reject_nonint_tag():
    world()
    x = ranks_arange((1,))
    with pytest.raises(TypeError, match="'tag'"):
        mpx.send(x, dest=mpx.shift(1), tag="a")
    with pytest.raises(TypeError, match="'tag'"):
        mpx.recv(x, tag=1.5)


def test_numpy_integer_scalars_accepted():
    # int-typed specs must accept numpy integer scalars — the reference's
    # enforce_types checks via np.issubdtype (ref _src/validation.py:66), so
    # ported MPI code passing np.int64 roots/tags keeps working
    import numpy as np

    _, size = world()

    @mpx.spmd
    def f(x):
        y, t = mpx.bcast(x, np.int64(0))
        z, _ = mpx.sendrecv(x, x, dest=mpx.shift(1),
                            sendtag=np.int32(7), recvtag=np.int32(7),
                            token=t)
        return y, z

    y, _ = f(ranks_arange((1,)))
    assert jnp.allclose(jnp.asarray(y), 0.0)


def test_sendrecv_rejects_nonint_tags():
    world()
    x = ranks_arange((1,))
    with pytest.raises(TypeError, match="'sendtag'"):
        mpx.sendrecv(x, x, dest=mpx.shift(1), sendtag=jnp.int32(1))
    with pytest.raises(TypeError, match="'recvtag'"):
        mpx.sendrecv(x, x, dest=mpx.shift(1), recvtag=None)


def test_ops_reject_wrong_comm_type():
    world()
    x = ranks_arange((1,))
    for opname in ["allreduce", "allgather", "alltoall", "scan"]:
        op = getattr(mpx, opname)
        with pytest.raises(TypeError, match="'comm'"):
            op(x, comm="world")
    with pytest.raises(TypeError, match="'comm'"):
        mpx.barrier(comm=42)


def test_ops_reject_wrong_token_type():
    world()
    x = ranks_arange((1,))
    with pytest.raises(TypeError, match="'token'"):
        mpx.allreduce(x, token=jnp.zeros(()))  # raw array, not a Token


def test_sendrecv_rejects_wrong_status_type():
    world()
    x = ranks_arange((1,))
    with pytest.raises(TypeError, match="'status'"):
        mpx.sendrecv(x, x, dest=mpx.shift(1), status=object())
