"""Opt-in real-accelerator lane: the Mosaic-COMPILED Pallas kernels.

The normal suite runs on the forced 8-device virtual CPU mesh, where
every Pallas path takes its interpret/jnp form — identical arithmetic,
but the compiled kernels themselves (Mosaic lowering, VMEM blocking,
SMEM scalar operands, in-kernel rolls) are never built.  This file is
the chip-side complement, the analog of the reference suite's second
execution mode (``mpirun -np N pytest``, ref docs/developers.rst:15-27 —
same tests, realer substrate):

    MPI4JAX_TPU_TEST_PLATFORM=ambient python -m pytest \
        tests/test_tpu_compiled.py -q

With the env var set, conftest.py keeps the process's own backend (the
attached TPU) instead of forcing CPU; without it — i.e. in the normal
suite — every test here skips.  Run it against this file only: the rest
of the suite assumes 8 devices.

Each test compares a compiled kernel path against the fast jnp step on
the SAME chip, so the assertion bounds are the fusion-order rounding
bands established by the interpret-mode equality tests, not looser
device tolerances.  Grids are kept small (a few kernel blocks) so the
whole lane is a handful of compiles (~30 s each, first run).
"""

import os
import sys

import numpy as np
import pytest

import jax

_AMBIENT = os.environ.get("MPI4JAX_TPU_TEST_PLATFORM") == "ambient"
if _AMBIENT and jax.default_backend() != "tpu":
    # the operator explicitly asked for the chip lane: a silent all-skip
    # green run would mask a broken TPU attach — fail loudly instead
    raise RuntimeError(
        "MPI4JAX_TPU_TEST_PLATFORM=ambient is set but the backend is "
        f"'{jax.default_backend()}', not 'tpu' — the accelerator plugin "
        "did not claim the process; fix the attach before trusting this "
        "lane"
    )

pytestmark = pytest.mark.skipif(
    not _AMBIENT,
    reason="real-TPU lane (MPI4JAX_TPU_TEST_PLATFORM=ambient)",
)

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "examples"))

_RUNS = {}  # (cfg, fast, steps) -> State; Config is frozen/hashable


def _run(cfg, fast, steps):
    """Stepper runs, cached: the fast-step baseline for the periodic
    config is shared by two tests, and each make_stepper costs a fresh
    ~30 s XLA compile on chip."""
    key = (cfg, fast, steps)
    if key not in _RUNS:
        from shallow_water import (
            initial_state, make_mesh_and_comm, make_stepper,
        )

        _, comm = make_mesh_and_comm(cfg, devices=jax.devices()[:1])
        first, multi = make_stepper(cfg, comm, fast=fast)
        _RUNS[key] = multi(first(initial_state(cfg)), steps)
    return _RUNS[key]


def _assert_fields_close(a, b, what):
    for name, x, y in zip(a._fields, a, b):
        x, y = np.asarray(x), np.asarray(y)
        bound = 5e-6 + 1e-6 * np.abs(x).max()
        assert np.abs(x - y).max() <= bound, (
            f"{what}: field {name} diverged on chip: "
            f"{np.abs(x - y).max():.3e} > {bound:.3e}"
        )


def test_whole_step_pair_kernel_compiled():
    """The benchmark path: the fused whole-step pair kernel, Mosaic-
    compiled (multi-block grid: ny_local = 2 x _PBLK)."""
    from shallow_water import Config, model_step_pallas, select_step

    cfg = Config(nproc_y=1, nproc_x=1, nx=512, ny=254)
    assert select_step("auto", cfg) is model_step_pallas
    _assert_fields_close(
        _run(cfg, "pallas2", 7), _run(cfg, True, 7), "pallas2"
    )


def test_wide_halo_kernel_compiled():
    """The multi-rank path's kernels (wide masks, SMEM offsets, carried
    frame with margin refresh), compiled on the single chip — walls
    config, which 'auto' routes to the wide path."""
    from shallow_water import Config, model_step_wide, select_step

    cfg = Config(nproc_y=1, nproc_x=1, nx=512, ny=254, periodic_x=False)
    assert select_step("auto", cfg) is model_step_wide
    _assert_fields_close(_run(cfg, "auto", 7), _run(cfg, True, 7), "wide")


def test_wide_halo_kernel_compiled_periodic():
    """Wide path on a periodic config: the wrap self-exchanges are elided
    to identity routings; the compiled kernel must agree with the
    specialist whole-step kernel's physics."""
    from shallow_water import Config

    cfg = Config(nproc_y=1, nproc_x=1, nx=512, ny=254)
    _assert_fields_close(
        _run(cfg, "wide2", 7), _run(cfg, True, 7), "wide-periodic"
    )


def test_flash_attention_kernel_compiled():
    """The flash block kernel (masked, unmasked, and causal tile-skipping
    forms) vs the jnp reference path, on chip."""
    import jax.numpy as jnp

    from mpi4jax_tpu.kernels.flash_attention import flash_block_partials

    b, t, h, d = 2, 1024, 4, 128
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q, k, v = (jax.random.normal(kk, (b, t, h, d), jnp.float32) for kk in ks)
    scale = 1.0 / np.sqrt(d)

    tril = jnp.tril(jnp.ones((t, t), bool))
    for kernel_kwargs, jnp_kwargs in (
        (dict(mask=None), dict(mask=None)),
        (dict(mask=tril), dict(mask=tril)),
        (dict(mask=None, causal=True), dict(mask=tril)),
    ):
        o1, m1, l1 = flash_block_partials(
            q, k, v, scale=scale, **kernel_kwargs
        )
        o2, m2, l2 = flash_block_partials(
            q, k, v, scale=scale, force_jnp=True, **jnp_kwargs
        )
        # f32 dots ride the MXU's bf16-multiply default on chip, and the
        # kernel and einsum accumulate in different orders, so scores —
        # and everything downstream — agree to matmul (bf16-epsilon)
        # precision, not CPU 1-ulp: observed ~1e-3 relative on m
        np.testing.assert_allclose(
            np.asarray(m1), np.asarray(m2), rtol=2e-2, atol=2e-2
        )
        np.testing.assert_allclose(
            np.asarray(l1), np.asarray(l2), rtol=5e-2, atol=5e-2
        )
        # compare NORMALIZED attention (o / l): unnormalized partials have
        # per-row magnitudes spanning orders of magnitude, so elementwise
        # relative error is meaningless there (observed ~3e3 relative on
        # near-zero partials that are ~0.3% of their row's scale)
        def norm(o, l):
            return np.asarray(o) / np.moveaxis(
                np.maximum(np.asarray(l), 1e-6), 1, 2
            )[..., None]

        np.testing.assert_allclose(
            norm(o1, l1), norm(o2, l2), atol=2e-2
        )


def test_all_twelve_ops_on_chip():
    """The full op surface, compiled and EXECUTED on the real chip, on a
    1-device mesh — in-region (one jitted shard_map program) and eagerly
    (every op through the auto-wrapped dispatch path).  Single-device
    collectives degenerate to self-communication (the reference's
    1-process mode, ref docs/developers.rst:15-27) but still exercise the
    real TPU lowering + runtime of every op, which the CPU-mesh suite
    never compiles."""
    import jax.numpy as jnp

    import mpi4jax_tpu as mpx

    mesh = mpx.make_world_mesh(devices=jax.devices()[:1])
    comm = mpx.Comm(mesh.axis_names[0], mesh=mesh)

    @mpx.spmd(comm=comm)
    def f(x, rows):
        token = mpx.create_token()
        a, token = mpx.allreduce(x, op=mpx.SUM, comm=comm, token=token)
        p, token = mpx.allreduce(x, op=mpx.PROD, comm=comm, token=token)
        b, token = mpx.bcast(x, 0, comm=comm, token=token)
        g, token = mpx.allgather(x, comm=comm, token=token)
        s, token = mpx.scan(x, mpx.SUM, comm=comm, token=token)
        r, token = mpx.sendrecv(x, x, dest=mpx.shift(1), comm=comm,
                                token=token)
        token = mpx.send(x, dest=[(0, 0)], comm=comm, token=token)
        rcv, token = mpx.recv(x, comm=comm, token=token)
        t, token = mpx.alltoall(rows, comm=comm, token=token)
        sc, token = mpx.scatter(rows, 0, comm=comm, token=token)
        gt, token = mpx.gather(x, 0, comm=comm, token=token)
        rd, token = mpx.reduce(x, mpx.MAX, 0, comm=comm, token=token)
        token = mpx.barrier(comm=comm, token=token)
        return a, p, b, g.sum(0), s, r, rcv, t, sc, gt.sum(0), rd

    x = jnp.full((1, 4), 3.0)
    rows = jnp.arange(4.0).reshape(1, 1, 4)
    outs = f(x, rows)
    for name, v in zip("a p b g s r rcv t sc gt rd".split(), outs):
        v = np.asarray(v)
        assert np.isfinite(v).all(), name
        ref = np.asarray(rows) if name in ("t",) else (
            np.asarray(rows)[0] if name == "sc" else np.asarray(x))
        np.testing.assert_allclose(v.ravel(), ref.ravel(), err_msg=name)

    # eager path — ALL ops: global arrays with leading rank axis, each op
    # compiling its own auto-wrapped shard_map program on the chip
    xg, rg = x[None], rows[None]
    e_ar, tok = mpx.allreduce(xg, op=mpx.SUM, comm=comm)
    e_bc, tok = mpx.bcast(xg, 0, comm=comm, token=tok)
    e_ag, tok = mpx.allgather(xg, comm=comm, token=tok)
    e_sc, tok = mpx.scan(xg, mpx.SUM, comm=comm, token=tok)
    e_sr, tok = mpx.sendrecv(xg, xg, dest=mpx.shift(1), comm=comm,
                             token=tok)
    tok = mpx.send(xg, dest=[(0, 0)], comm=comm, token=tok)
    e_rc, tok = mpx.recv(xg, comm=comm, token=tok)
    e_t, tok = mpx.alltoall(rg, comm=comm, token=tok)
    e_st, tok = mpx.scatter(rg, 0, comm=comm, token=tok)
    e_gt, tok = mpx.gather(xg, 0, comm=comm, token=tok)
    e_rd, tok = mpx.reduce(xg, mpx.MAX, 0, comm=comm, token=tok)
    tok = mpx.barrier(comm=comm, token=tok)
    for name, v, ref in (
        ("allreduce", e_ar, xg), ("bcast", e_bc, xg),
        ("allgather", e_ag, xg), ("scan", e_sc, xg),
        ("sendrecv", e_sr, xg), ("recv", e_rc, xg),
        ("alltoall", e_t, rg), ("scatter", e_st, rows),
        ("gather", e_gt, xg), ("reduce", e_rd, xg),
    ):
        np.testing.assert_allclose(
            np.asarray(v).ravel(), np.asarray(ref).ravel(),
            err_msg=f"eager {name}",
        )


# COVERAGE GAP (by construction): on the 1-device mesh above, every group
# lowering's CollectivePermute machinery is dead code — kmax == 1 returns
# the input before any butterfly/doubling round is traced, so the chip lane
# compiles none of the ppermute rounds.  The rounds themselves are pinned
# at the lowered-HLO level on the 8-device CPU mesh
# (tests/test_collectives.py::test_butterfly_emits_ppermute_rounds_aot);
# the test below closes the on-chip half whenever the attached TPU has
# more than one device (e.g. a v4-8 slice).


def test_butterfly_rounds_on_multi_device_chip():
    """The butterfly/doubling ppermute rounds compiled and EXECUTED on a
    real multi-device TPU mesh — the coverage the 1-device lane cannot
    provide.  PROD allreduce takes the fold+broadcast butterfly; the split
    bcast takes the doubling broadcast."""
    import jax.numpy as jnp

    import mpi4jax_tpu as mpx

    n = jax.device_count()
    if n < 2:
        pytest.skip("needs a multi-device TPU slice (ppermute rounds are "
                    "dead code on 1 device)")

    mesh = mpx.make_world_mesh()
    comm = mpx.Comm(mesh.axis_names[0], mesh=mesh)
    split = comm.Split([0] * n)  # one group of everyone: kmax = n

    @mpx.spmd(comm=comm)
    def butterfly(x):
        res, _ = mpx.allreduce(x, op=mpx.PROD, comm=comm)
        return res

    @mpx.spmd(comm=split)
    def doubling(x):
        res, _ = mpx.bcast(x, 1, comm=split)
        return res

    vals = jnp.arange(1.0, n + 1)[:, None] * jnp.ones((n, 4))
    p = np.asarray(butterfly(vals))
    np.testing.assert_allclose(
        p, np.prod(np.arange(1.0, n + 1)) * np.ones((n, 4)), rtol=1e-5
    )
    b = np.asarray(doubling(vals))
    np.testing.assert_allclose(b, 2.0 * np.ones((n, 4)))


def test_profile_ops_on_chip(tmp_path):
    """The per-op latency story on the REAL backend: profile_ops must
    capture a device trace of a collective-bearing program on the chip
    (the CPU suite pins the same protocol; this is the platform the
    MPI4JAX_TPU_TRACE host brackets cannot cover)."""
    import glob

    import jax.numpy as jnp

    import mpi4jax_tpu as mpx

    mesh = mpx.make_world_mesh(devices=jax.devices()[:1])
    comm = mpx.Comm(mesh.axis_names[0], mesh=mesh)

    @mpx.spmd(comm=comm)
    def step(x):
        y, _ = mpx.allreduce(x, op=mpx.SUM, comm=comm)
        return y

    x = jnp.ones((1, 512, 512))
    step(x)  # compile first
    logdir = str(tmp_path / "trace")
    with mpx.profile_ops(logdir):
        step(x)
    assert glob.glob(f"{logdir}/**/*.xplane.pb", recursive=True), logdir


def test_bench_smoke_on_chip():
    """bench.py (the driver's benchmark entry) must produce its one-line
    JSON on the chip with the on-chip amortized metric present and sane;
    the parsed result is captured as an artifact for the round record."""
    import json
    import subprocess

    repo = os.path.join(os.path.dirname(__file__), "..")
    out = subprocess.run(
        [sys.executable, "bench.py"], capture_output=True, text=True,
        timeout=900, cwd=repo,
    )
    assert out.returncode == 0, out.stderr[-3000:]
    line = [ln for ln in out.stdout.splitlines() if ln.startswith("{")][-1]
    res = json.loads(line)
    assert res["unit"] == "steps/s/chip"
    assert res["value"] > 0
    onchip = res.get("onchip_steps_per_s_per_chip")
    assert onchip is not None, (
        "bench.py dropped onchip_steps_per_s_per_chip (amortized slope "
        f"was non-positive on this run): {res}"
    )
    assert onchip > res["value"] * 0.5, res
    # artifact capture is best-effort: a read-only checkout must not turn
    # a passing bench into a failing test
    try:
        os.makedirs(os.path.join(repo, "benchmarks", "results"),
                    exist_ok=True)
        with open(os.path.join(repo, "benchmarks", "results",
                               "bench_lane_latest.json"), "w") as fh:
            json.dump(res, fh, indent=1)
    except OSError:
        pass


def test_flash_attention_backward_compiled():
    """jax.grad through the Pallas flash kernels — forward AND the
    blockwise backward kernels — Mosaic-compiled.  This was the round-4
    gap: grad through ``flash_block_partials`` raised ``Linearization
    failed`` on the chip, so the "differentiable" claim held only on the
    CPU/jnp fallback.  Gradient equality is against the jnp path's grads
    computed on the SAME chip (shared MXU bf16-multiply default)."""
    import jax.numpy as jnp

    from mpi4jax_tpu.kernels.flash_attention import flash_block_partials

    b, t, h, d = 2, 1024, 4, 128
    ks = jax.random.split(jax.random.PRNGKey(1), 3)
    q, k, v = (jax.random.normal(kk, (b, t, h, d), jnp.float32) for kk in ks)
    scale = 1.0 / np.sqrt(d)

    def loss(q, k, v, causal, **kwargs):
        o, _, l = flash_block_partials(
            q, k, v, None, scale=scale, causal=causal, **kwargs
        )
        l_safe = jnp.where(l == 0.0, 1.0, l)
        out = o / jnp.moveaxis(l_safe, 1, 2)[..., None]
        return (out**2).sum()

    for causal in (False, True):
        g_k = jax.jit(jax.grad(
            lambda *a: loss(*a, causal), (0, 1, 2)
        ))(q, k, v)
        g_j = jax.jit(jax.grad(
            lambda *a: loss(*a, causal, force_jnp=True), (0, 1, 2)
        ))(q, k, v)
        for a, e, nm in zip(g_k, g_j, "qkv"):
            a, e = np.asarray(a), np.asarray(e)
            assert np.isfinite(a).all(), f"d{nm} (causal={causal}) not finite"
            # grads of a squared loss amplify the matmul (bf16-epsilon)
            # band; bound element error against the cotangent's scale
            # (observed ~6e-3 of max-grad on the causal dq at T=1024 —
            # interpret mode pins the same comparison at 1e-3 RELATIVE,
            # so this band is chip matmul precision, not kernel logic)
            bound = 1e-2 * np.abs(e).max() + 1e-3
            assert np.abs(a - e).max() <= bound, (
                f"d{nm} (causal={causal}) diverged on chip: "
                f"{np.abs(a - e).max():.3e} > {bound:.3e}"
            )


def test_flash_backward_small_shapes_all_inputs_compiled():
    """Grads wrt q AND k/v at small T and small head dims, Mosaic-compiled.

    Regression: the dk/dv kernel used to dynamic-slice m/g_l on the LANE
    dim at qj*bq offsets, which Mosaic can only prove 128-aligned when bq
    (= min(Tq, 512)) is a multiple of 128 — so any transformer-block
    training step with a T_local that wasn't failed to compile on TPU,
    and nothing caught it because every earlier chip test took grads wrt
    q only (the dk/dv kernel was dead code there).  m/g_l now enter that
    kernel transposed (query positions on the sublane dim, 8-aligned)."""
    import jax.numpy as jnp

    from mpi4jax_tpu.kernels.flash_attention import flash_block_partials

    for (t, d) in ((32, 8), (200, 32), (1024, 128)):
        ks = jax.random.split(jax.random.PRNGKey(3), 3)
        q, k, v = (
            jax.random.normal(kk, (1, t, 2, d), jnp.float32) for kk in ks
        )
        for causal in (False, True):
            g = jax.jit(jax.grad(
                lambda q, k, v: flash_block_partials(
                    q, k, v, None, scale=0.2, causal=causal
                )[0].sum(),
                (0, 1, 2),
            ))(q, k, v)
            for a, nm in zip(g, "qkv"):
                assert np.isfinite(np.asarray(a)).all(), (t, d, causal, nm)


def test_ring_and_ulysses_grad_compiled():
    """ring/ulysses grads compile and run on a 1-device mesh on chip.

    Scope (the attach hosts ONE chip): size=1 means the ring has no
    sendrecv rotation and the Ulysses all-to-alls are no-ops — what this
    exercises is the custom-VJP kernel path (Pallas fwd + causal bwd
    kernels) *inside shard_map under grad* on real hardware, value-checked
    against reference attention grads on the same chip.  The multi-rank
    collective-transpose half of the grad path is pinned by the CPU-mesh
    suite (tests/test_long_context.py) and the driver's dryrun."""
    import jax.numpy as jnp

    import mpi4jax_tpu as mpx
    from long_context_attention import (
        reference_attention, ring_attention, ulysses_attention,
    )

    mesh = mpx.make_world_mesh(devices=jax.devices()[:1])
    comm = mpx.Comm(mesh.axis_names[0], mesh=mesh)
    b, t, h, d = 1, 512, 4, 64
    ks = jax.random.split(jax.random.PRNGKey(2), 3)
    q, k, v = (
        jax.random.normal(kk, (1, b, t, h, d), jnp.float32) for kk in ks
    )
    g_ref = jax.jit(jax.grad(
        lambda q: (reference_attention(q, k[0], v[0], causal=True) ** 2).sum()
    ))(q[0])

    for scheme in (ring_attention, ulysses_attention):

        @mpx.spmd(comm=comm)
        def f(q, k, v, scheme=scheme):
            out = scheme(q, k, v, comm=comm, causal=True)
            return mpx.varying(jnp.sum(out**2))

        g = np.asarray(jax.grad(lambda q: jnp.sum(f(q, k, v)))(q))[0]
        assert np.isfinite(g).all(), scheme.__name__
        e = np.asarray(g_ref)
        bound = 1e-2 * np.abs(e).max() + 1e-3
        assert np.abs(g - e).max() <= bound, (
            f"{scheme.__name__} dq diverged on chip: "
            f"{np.abs(g - e).max():.3e} > {bound:.3e}"
        )
