"""Opt-in real-accelerator lane: the Mosaic-COMPILED Pallas kernels.

The normal suite runs on the forced 8-device virtual CPU mesh, where
every Pallas path takes its interpret/jnp form — identical arithmetic,
but the compiled kernels themselves (Mosaic lowering, VMEM blocking,
SMEM scalar operands, in-kernel rolls) are never built.  This file is
the chip-side complement, the analog of the reference suite's second
execution mode (``mpirun -np N pytest``, ref docs/developers.rst:15-27 —
same tests, realer substrate):

    MPI4JAX_TPU_TEST_PLATFORM=ambient python -m pytest \
        tests/test_tpu_compiled.py -q

With the env var set, conftest.py keeps the process's own backend (the
attached TPU) instead of forcing CPU; without it — i.e. in the normal
suite — every test here skips.  Run it against this file only: the rest
of the suite assumes 8 devices.

Each test compares a compiled kernel path against the fast jnp step on
the SAME chip, so the assertion bounds are the fusion-order rounding
bands established by the interpret-mode equality tests, not looser
device tolerances.  Grids are kept small (a few kernel blocks) so the
whole lane is a handful of compiles (~30 s each, first run).
"""

import os
import sys

import numpy as np
import pytest

import jax

_AMBIENT = os.environ.get("MPI4JAX_TPU_TEST_PLATFORM") == "ambient"
if _AMBIENT and jax.default_backend() != "tpu":
    # the operator explicitly asked for the chip lane: a silent all-skip
    # green run would mask a broken TPU attach — fail loudly instead
    raise RuntimeError(
        "MPI4JAX_TPU_TEST_PLATFORM=ambient is set but the backend is "
        f"'{jax.default_backend()}', not 'tpu' — the accelerator plugin "
        "did not claim the process; fix the attach before trusting this "
        "lane"
    )

pytestmark = pytest.mark.skipif(
    not _AMBIENT,
    reason="real-TPU lane (MPI4JAX_TPU_TEST_PLATFORM=ambient)",
)

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "examples"))

_RUNS = {}  # (cfg, fast, steps) -> State; Config is frozen/hashable


def _run(cfg, fast, steps):
    """Stepper runs, cached: the fast-step baseline for the periodic
    config is shared by two tests, and each make_stepper costs a fresh
    ~30 s XLA compile on chip."""
    key = (cfg, fast, steps)
    if key not in _RUNS:
        from shallow_water import (
            initial_state, make_mesh_and_comm, make_stepper,
        )

        _, comm = make_mesh_and_comm(cfg, devices=jax.devices()[:1])
        first, multi = make_stepper(cfg, comm, fast=fast)
        _RUNS[key] = multi(first(initial_state(cfg)), steps)
    return _RUNS[key]


def _assert_fields_close(a, b, what):
    for name, x, y in zip(a._fields, a, b):
        x, y = np.asarray(x), np.asarray(y)
        bound = 5e-6 + 1e-6 * np.abs(x).max()
        assert np.abs(x - y).max() <= bound, (
            f"{what}: field {name} diverged on chip: "
            f"{np.abs(x - y).max():.3e} > {bound:.3e}"
        )


def test_whole_step_pair_kernel_compiled():
    """The benchmark path: the fused whole-step pair kernel, Mosaic-
    compiled (multi-block grid: ny_local = 2 x _PBLK)."""
    from shallow_water import Config, model_step_pallas, select_step

    cfg = Config(nproc_y=1, nproc_x=1, nx=512, ny=254)
    assert select_step("auto", cfg) is model_step_pallas
    _assert_fields_close(
        _run(cfg, "pallas2", 7), _run(cfg, True, 7), "pallas2"
    )


def test_wide_halo_kernel_compiled():
    """The multi-rank path's kernels (wide masks, SMEM offsets, carried
    frame with margin refresh), compiled on the single chip — walls
    config, which 'auto' routes to the wide path."""
    from shallow_water import Config, model_step_wide, select_step

    cfg = Config(nproc_y=1, nproc_x=1, nx=512, ny=254, periodic_x=False)
    assert select_step("auto", cfg) is model_step_wide
    _assert_fields_close(_run(cfg, "auto", 7), _run(cfg, True, 7), "wide")


def test_wide_halo_kernel_compiled_periodic():
    """Wide path on a periodic config: the wrap self-exchanges are elided
    to identity routings; the compiled kernel must agree with the
    specialist whole-step kernel's physics."""
    from shallow_water import Config

    cfg = Config(nproc_y=1, nproc_x=1, nx=512, ny=254)
    _assert_fields_close(
        _run(cfg, "wide2", 7), _run(cfg, True, 7), "wide-periodic"
    )


def test_flash_attention_kernel_compiled():
    """The flash block kernel (masked, unmasked, and causal tile-skipping
    forms) vs the jnp reference path, on chip."""
    import jax.numpy as jnp

    from mpi4jax_tpu.kernels.flash_attention import flash_block_partials

    b, t, h, d = 2, 1024, 4, 128
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q, k, v = (jax.random.normal(kk, (b, t, h, d), jnp.float32) for kk in ks)
    scale = 1.0 / np.sqrt(d)

    tril = jnp.tril(jnp.ones((t, t), bool))
    for kernel_kwargs, jnp_kwargs in (
        (dict(mask=None), dict(mask=None)),
        (dict(mask=tril), dict(mask=tril)),
        (dict(mask=None, causal=True), dict(mask=tril)),
    ):
        o1, m1, l1 = flash_block_partials(
            q, k, v, scale=scale, **kernel_kwargs
        )
        o2, m2, l2 = flash_block_partials(
            q, k, v, scale=scale, force_jnp=True, **jnp_kwargs
        )
        # f32 dots ride the MXU's bf16-multiply default on chip, and the
        # kernel and einsum accumulate in different orders, so scores —
        # and everything downstream — agree to matmul (bf16-epsilon)
        # precision, not CPU 1-ulp: observed ~1e-3 relative on m
        np.testing.assert_allclose(
            np.asarray(m1), np.asarray(m2), rtol=2e-2, atol=2e-2
        )
        np.testing.assert_allclose(
            np.asarray(l1), np.asarray(l2), rtol=5e-2, atol=5e-2
        )
        # compare NORMALIZED attention (o / l): unnormalized partials have
        # per-row magnitudes spanning orders of magnitude, so elementwise
        # relative error is meaningless there (observed ~3e3 relative on
        # near-zero partials that are ~0.3% of their row's scale)
        def norm(o, l):
            return np.asarray(o) / np.moveaxis(
                np.maximum(np.asarray(l), 1e-6), 1, 2
            )[..., None]

        np.testing.assert_allclose(
            norm(o1, l1), norm(o2, l2), atol=2e-2
        )
