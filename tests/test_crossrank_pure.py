"""Cross-rank schedule verifier: the pure-Python half (docs/analysis.md
"Cross-rank verification").

Positive/negative matrix for MPX120–MPX125 (plus the cross-rank reuses
of MPX101/102/106) driven by hand-built per-rank schedules through the
matcher (analysis/matcher.py) and the progress checker
(analysis/progress.py), plus the rank-concretization scope and the
schedule builder (analysis/schedule.py) — all loaded under a private
package name (the tests/test_analysis_pure.py isolated loader) so these
run even where the installed JAX is below the package's floor.  The
traced integration half — real 8-device programs through
``mpx.analyze(ranks='all')`` and the ambient env path — lives in
tests/test_crossrank.py.
"""

import importlib
import pathlib
import sys
import types

import pytest

REPO = pathlib.Path(__file__).resolve().parent.parent
PKG = REPO / "mpi4jax_tpu"

_ISO_NAME = "_mpx_crossrank_iso"


def _load_isolated():
    if _ISO_NAME in sys.modules:
        return sys.modules[_ISO_NAME]
    root = types.ModuleType(_ISO_NAME)
    root.__path__ = [str(PKG)]
    sys.modules[_ISO_NAME] = root
    for sub in ("utils", "analysis", "ops", "parallel"):
        m = types.ModuleType(f"{_ISO_NAME}.{sub}")
        m.__path__ = [str(PKG / sub)]
        sys.modules[f"{_ISO_NAME}.{sub}"] = m
        setattr(root, sub, m)
    for mod in ("utils.config", "ops._fusion", "analysis.report",
                "analysis.graph", "analysis.checkers", "analysis.schedule",
                "analysis.matcher", "analysis.progress",
                "parallel.rankspec"):
        importlib.import_module(f"{_ISO_NAME}.{mod}")
    return root


ISO = _load_isolated()
report = sys.modules[f"{_ISO_NAME}.analysis.report"]
graph = sys.modules[f"{_ISO_NAME}.analysis.graph"]
schedule = sys.modules[f"{_ISO_NAME}.analysis.schedule"]
matcher = sys.modules[f"{_ISO_NAME}.analysis.matcher"]
progress = sys.modules[f"{_ISO_NAME}.analysis.progress"]

S = schedule.SchedOp
E = graph.CollectiveEvent


def verify(schedules):
    """matcher + progress, returning the finding codes in order."""
    m = matcher.match_schedules(schedules)
    return [f.code for f in m.findings + progress.check_progress(m)]


def coll(rank, pos, seq, ck=0, op="allreduce", parts=(0, 1), **kw):
    return S(rank=rank, pos=pos, kind="coll", op=op, comm_key=ck, seq=seq,
             participants=parts, **kw)


def send(rank, pos, dst, tag=0, ck=0, **kw):
    return S(rank=rank, pos=pos, kind="send", op="send", comm_key=ck,
             src=rank, dst=dst, tag=tag, **kw)


def recv(rank, pos, src, tag=0, ck=0, **kw):
    return S(rank=rank, pos=pos, kind="recv", op="recv", comm_key=ck,
             src=src, dst=rank, tag=tag, **kw)


# ---------------------------------------------------------------------------
# MPX120 — cross-rank collective order mismatch
# ---------------------------------------------------------------------------


def test_mpx120_kind_mismatch_fires():
    codes = verify({
        0: [coll(0, 0, 0, op="allreduce")],
        1: [coll(1, 0, 0, op="bcast")],
    })
    assert "MPX120" in codes
    m = matcher.match_schedules({
        0: [coll(0, 0, 0, op="allreduce")],
        1: [coll(1, 0, 0, op="bcast")],
    })
    (f,) = [x for x in m.findings if x.code == "MPX120"]
    assert "allreduce" in f.message and "bcast" in f.message
    assert f.severity == "error"
    assert f.seq == 0


def test_mpx120_root_and_reduction_mismatch_fire():
    assert "MPX120" in verify({
        0: [coll(0, 0, 0, op="bcast", root=0)],
        1: [coll(1, 0, 0, op="bcast", root=1)],
    })
    assert "MPX120" in verify({
        0: [coll(0, 0, 0, reduction="sum")],
        1: [coll(1, 0, 0, reduction="max")],
    })


def test_mpx120_interleave_cycle_fires():
    # comm A then B on rank 0; B then A on rank 1 — per-comm sequences
    # agree, the INTERLEAVE deadlocks: the progress checker reports it
    codes = verify({
        0: [coll(0, 0, 0, ck=0), coll(0, 1, 0, ck=1)],
        1: [coll(1, 0, 0, ck=1), coll(1, 1, 0, ck=0)],
    })
    assert codes == ["MPX120"]


def test_mpx120_clean():
    assert verify({
        0: [coll(0, 0, 0), coll(0, 1, 1, op="bcast", root=0)],
        1: [coll(1, 0, 0), coll(1, 1, 1, op="bcast", root=0)],
    }) == []


# ---------------------------------------------------------------------------
# MPX121 — send/recv deadlock cycle
# ---------------------------------------------------------------------------


def test_mpx121_recv_cycle_fires_rank_by_rank():
    m = matcher.match_schedules({
        0: [recv(0, 0, src=1, tag=1), send(0, 1, dst=1, tag=0)],
        1: [recv(1, 0, src=0, tag=0), send(1, 1, dst=0, tag=1)],
    })
    assert m.findings == []  # counts match; the ORDER deadlocks
    (f,) = progress.check_progress(m)
    assert f.code == "MPX121" and f.severity == "error"
    # the full cycle, rendered rank-by-rank
    assert "rank 0: blocked at recv(src=1, tag=1)" in f.message
    assert "rank 1: blocked at recv(src=0, tag=0)" in f.message
    assert "waits for rank" in f.message


def test_mpx121_four_rank_ring_cycle():
    # every rank recvs from its left neighbor before sending right
    k = 4
    scheds = {
        r: [recv(r, 0, src=(r - 1) % k), send(r, 1, dst=(r + 1) % k)]
        for r in range(k)
    }
    codes = verify(scheds)
    assert codes == ["MPX121"]


def test_mpx121_negative_buffered_exchange():
    # send-then-recv head-to-head: safe under this library's buffered
    # (deferred-pairing) sends — must NOT fire
    assert verify({
        0: [send(0, 0, dst=1), recv(0, 1, src=1)],
        1: [send(1, 0, dst=0), recv(1, 1, src=0)],
    }) == []


def test_mpx121_negative_safe_ring():
    # sendrecv-style: everyone sends first, then receives — clean
    k = 4
    assert verify({
        r: [send(r, 0, dst=(r + 1) % k), recv(r, 1, src=(r - 1) % k)]
        for r in range(k)
    }) == []


# ---------------------------------------------------------------------------
# MPX122 — collective/p2p interleave deadlock
# ---------------------------------------------------------------------------


def test_mpx122_mixed_cycle_fires():
    codes = verify({
        0: [recv(0, 0, src=1), coll(0, 1, 0, ck=1)],
        1: [coll(1, 0, 0, ck=1), send(1, 1, dst=0)],
    })
    assert codes == ["MPX122"]


def test_mpx122_negative_ordered():
    assert verify({
        0: [coll(0, 0, 0, ck=1), recv(0, 1, src=1)],
        1: [coll(1, 0, 0, ck=1), send(1, 1, dst=0)],
    }) == []


# ---------------------------------------------------------------------------
# MPX123 — orphaned rank
# ---------------------------------------------------------------------------


def test_mpx123_orphan_fires():
    m = matcher.match_schedules({
        0: [coll(0, 0, 0, op="barrier")],
        1: [],
    })
    (f,) = m.findings
    assert f.code == "MPX123" and f.rank == 1 and f.seq == 0
    assert "never issues" in f.message


def test_mpx123_reported_once_per_rank_and_comm():
    m = matcher.match_schedules({
        0: [coll(0, 0, 0), coll(0, 1, 1), coll(0, 2, 2)],
        1: [],
    })
    assert [f.code for f in m.findings] == ["MPX123"]


def test_mpx123_negative_partial_analysis():
    # analyzing a subset of the comm must not orphan the absent ranks
    assert verify({
        0: [coll(0, 0, 0, parts=(0, 1, 2, 3))],
        1: [coll(1, 0, 0, parts=(0, 1, 2, 3))],
    }) == []


# ---------------------------------------------------------------------------
# MPX124 / MPX125 — fusion bucketing and hierarchy plan divergence
# ---------------------------------------------------------------------------


def test_mpx124_divergent_bucketing_fires():
    lay2 = (("float32", 16), ("float32", 16))
    lay3 = lay2 + (("float32", 16),)
    m = matcher.match_schedules({
        0: [coll(0, 0, 0, fused=(2, 128, lay2))],
        1: [coll(1, 0, 0, fused=(3, 192, lay3))],
    })
    (f,) = m.findings
    assert f.code == "MPX124"
    assert "2 member(s)" in f.message and "3 member(s)" in f.message


def test_mpx124_negative_same_buckets():
    lay = (("float32", 16),)
    assert verify({
        0: [coll(0, 0, 0, fused=(1, 64, lay))],
        1: [coll(1, 0, 0, fused=(1, 64, lay))],
    }) == []


def test_mpx125_divergent_hier_plan_fires():
    m = matcher.match_schedules({
        0: [coll(0, 0, 0, hier=(2, 4))],
        1: [coll(1, 0, 0, hier=(4, 2))],
    })
    (f,) = m.findings
    assert f.code == "MPX125"
    assert "2x4" in f.message and "4x2" in f.message
    # hier vs flat is also a divergence
    m = matcher.match_schedules({
        0: [coll(0, 0, 0, hier=(2, 4))],
        1: [coll(1, 0, 0, hier=None)],
    })
    assert [f.code for f in m.findings] == ["MPX125"]
    assert "flat" in m.findings[0].message


def test_mpx125_negative_agreeing_plans():
    assert verify({
        0: [coll(0, 0, 0, hier=(2, 4))],
        1: [coll(1, 0, 0, hier=(2, 4))],
    }) == []


# ---------------------------------------------------------------------------
# cross-rank reuses of MPX101 / MPX102 / MPX106
# ---------------------------------------------------------------------------


def test_crossrank_mpx101_unreceived_send():
    m = matcher.match_schedules({
        0: [send(0, 0, dst=1)],
        1: [],
    })
    (f,) = m.findings
    assert f.code == "MPX101" and f.rank == 0
    assert "never received" in f.message


def test_crossrank_mpx102_unsent_recv():
    m = matcher.match_schedules({
        0: [],
        1: [recv(1, 0, src=0)],
    })
    (f,) = m.findings
    assert f.code == "MPX102" and f.rank == 1


def test_crossrank_mpx106_signature_mismatch():
    m = matcher.match_schedules({
        0: [send(0, 0, dst=1, dtype="float32", nelems=4)],
        1: [recv(1, 0, src=0, dtype="int32", nelems=4)],
    })
    (f,) = m.findings
    assert f.code == "MPX106"
    assert "type-signature" in f.message
    # equal element count, equal dtype: clean
    assert verify({
        0: [send(0, 0, dst=1, dtype="float32", nelems=4)],
        1: [recv(1, 0, src=0, dtype="float32", nelems=4)],
    }) == []


def test_wildcard_recv_matches_any_sender():
    assert verify({
        0: [send(0, 0, dst=1)],
        1: [recv(1, 0, src=None)],
    }) == []
    # but an unsatisfiable wildcard still fires MPX102
    m = matcher.match_schedules({0: [], 1: [recv(1, 0, src=None)]})
    assert [f.code for f in m.findings] == ["MPX102"]


def test_fifo_channel_pairing_is_positional():
    # two sends, two recvs on one channel: k-th pairs with k-th; a dtype
    # flip on the SECOND pair only is exactly one MPX106
    m = matcher.match_schedules({
        0: [send(0, 0, dst=1, dtype="f32", nelems=4),
            send(0, 1, dst=1, dtype="i32", nelems=4)],
        1: [recv(1, 0, src=0, dtype="f32", nelems=4),
            recv(1, 1, src=0, dtype="f32", nelems=4)],
    })
    assert [f.code for f in m.findings] == ["MPX106"]


# ---------------------------------------------------------------------------
# async start/wait progress semantics
# ---------------------------------------------------------------------------


def astart(rank, pos, seq, ck=0, parts=(0, 1)):
    return S(rank=rank, pos=pos, kind="start", op="allreduce_start",
             comm_key=ck, seq=seq, participants=parts, span=rank)


def await_(rank, pos, seq, ck=0, parts=(0, 1)):
    return S(rank=rank, pos=pos, kind="wait", op="allreduce_wait",
             comm_key=ck, seq=seq, participants=parts, span=rank)


def test_start_wait_clean_and_overlapping_compute():
    assert verify({
        r: [astart(r, 0, 0), await_(r, 1, 0)] for r in (0, 1)
    }) == []
    # start is nonblocking: issue, exchange p2p, then wait — clean
    assert verify({
        0: [astart(0, 0, 0), send(0, 1, dst=1), await_(0, 2, 0)],
        1: [astart(1, 0, 0), recv(1, 1, src=0), await_(1, 2, 0)],
    }) == []


def test_wait_blocks_on_unissued_peer_start():
    # rank 1 never starts: rank 0's wait can never complete (the orphan
    # is the matcher's finding; no cycle is invented)
    m = matcher.match_schedules({
        0: [astart(0, 0, 0), await_(0, 1, 0)],
        1: [],
    })
    assert [f.code for f in m.findings] == ["MPX123"]
    assert progress.check_progress(m) == []


# ---------------------------------------------------------------------------
# the schedule builder (event stream -> per-rank SchedOps)
# ---------------------------------------------------------------------------


def test_build_schedule_projects_roles():
    events = [
        E(0, "allreduce", comm_uid=7, comm_size=2, reduction="sum"),
        E(1, "send", comm_uid=7, tag=3, pairs=((0, 1),), shape=(4,),
          dtype="float32"),
        E(2, "recv", comm_uid=7, tag=3, pairs=((0, 1),), shape=(4,),
          dtype="float32"),
    ]
    s0 = schedule.build_schedule(events, rank=0, world=2)
    s1 = schedule.build_schedule(events, rank=1, world=2)
    assert [o.kind for o in s0] == ["coll", "send"]
    assert [o.kind for o in s1] == ["coll", "recv"]
    assert s0[1].dst == 1 and s1[1].src == 0 and s1[1].tag == 3
    assert s0[0].participants == (0, 1)
    assert verify({0: s0, 1: s1}) == []


def test_build_schedule_sendrecv_is_buffered_safe():
    # one sendrecv event covering the whole ring: every rank gets a send
    # entry before its recv entry — clean by construction
    k = 4
    ring = tuple((i, (i + 1) % k) for i in range(k))
    events = [E(0, "sendrecv", comm_uid=1, comm_size=k, pairs=ring,
                shape=(2,), dtype="f32")]
    scheds = {r: schedule.build_schedule(events, rank=r, world=k)
              for r in range(k)}
    assert [o.kind for o in scheds[0]] == ["send", "recv"]
    assert verify(scheds) == []


def test_build_schedule_seq_per_comm_and_span_links():
    events = [
        E(0, "allreduce", comm_uid=5, comm_size=2),
        E(1, "allreduce", comm_uid=9, comm_size=2),
        E(2, "allreduce_start", comm_uid=5, comm_size=2, span=77),
        E(3, "allreduce_wait", comm_uid=5, comm_size=2, span=77),
    ]
    (c0, c1, st, wt) = schedule.build_schedule(events, rank=0, world=2)
    assert (c0.comm_key, c0.seq) == (("u", 5), 0)  # stable uid identity
    assert (c1.comm_key, c1.seq) == (("u", 9), 0)  # own comm, own sequence
    assert (st.kind, st.seq) == ("start", 1)  # comm 5's second instance
    assert (wt.kind, wt.seq) == ("wait", 1)   # linked through the span
    assert st.comm_key == wt.comm_key == ("u", 5)


def test_build_schedule_split_groups_scope_membership():
    groups = ((0, 1), (2, 3))
    events = [E(0, "allreduce", comm_uid=2, comm_size=2, split=True,
                groups=groups)]
    s0 = schedule.build_schedule(events, rank=0, world=4)
    s3 = schedule.build_schedule(events, rank=3, world=4)
    assert s0[0].participants == (0, 1)
    assert s3[0].participants == (2, 3)
    # group-divergent schedules still verify independently
    scheds = {r: schedule.build_schedule(events, rank=r, world=4)
              for r in range(4)}
    assert verify(scheds) == []


def test_build_schedule_wildcard_recv():
    events = [E(0, "recv", comm_uid=1, tag=2, pairs=None, shape=(4,),
                dtype="f32")]
    (op,) = schedule.build_schedule(events, rank=1, world=2)
    assert op.kind == "recv" and op.src is None and op.tag == 2


def test_recv_source_none_adopts_preceding_send_routing():
    # the reference-compatible pattern: send(partial routing) then
    # recv() adopting the queued send's pairs — the per-rank stream must
    # reproduce the region queue's FIFO adoption, NOT record a blocking
    # wildcard on every rank (which would false-fire MPX101/MPX102)
    fan_in = ((1, 0), (2, 0), (3, 0))
    events = [
        E(0, "send", comm_uid=1, tag=0, pairs=fan_in, shape=(4,),
          dtype="f32"),
        E(1, "recv", comm_uid=1, tag=0, pairs=None, shape=(4,),
          dtype="f32"),
    ]
    scheds = {r: schedule.build_schedule(events, rank=r, world=4)
              for r in range(4)}
    # rank 0: three recvs (one per adopted pair); ranks 1-3: one send
    assert [o.kind for o in scheds[0]] == ["recv"] * 3
    assert {o.src for o in scheds[0]} == {1, 2, 3}
    for r in (1, 2, 3):
        assert [o.kind for o in scheds[r]] == ["send"]
    assert verify(scheds) == []
    # adoption is FIFO per (comm, tag): a second recv() adopts the
    # SECOND send, and an explicit-source recv consumes its queue slot
    ring = ((0, 1), (1, 0))
    events = [
        E(0, "send", comm_uid=1, tag=0, pairs=ring, shape=(2,),
          dtype="f32"),
        E(1, "send", comm_uid=1, tag=0, pairs=ring, shape=(2,),
          dtype="f32"),
        E(2, "recv", comm_uid=1, tag=0, pairs=None, shape=(2,),
          dtype="f32"),
        E(3, "recv", comm_uid=1, tag=0, pairs=None, shape=(2,),
          dtype="f32"),
    ]
    scheds = {r: schedule.build_schedule(events, rank=r, world=2)
              for r in range(2)}
    assert [o.kind for o in scheds[0]] == ["send", "send", "recv", "recv"]
    # both sends are already queued when the first recv matches: the
    # FIFO-ambiguity advisory replays cross-rank (one per rank), exactly
    # like the single-trace MPX110 — and nothing error-severity fires
    assert verify(scheds) == ["MPX110", "MPX110"]


def test_mpx110_replay_fires_and_clean():
    # ambiguous: two sends pending on one channel when the recv matches
    scheds = {
        0: [send(0, 0, dst=1), send(0, 1, dst=1)],
        1: [recv(1, 0, src=0), recv(1, 1, src=0)],
    }
    m = matcher.match_schedules(scheds)
    assert m.findings == []
    fs = progress.check_progress(m)
    assert [f.code for f in fs] == ["MPX110"]
    assert fs[0].rank == 1 and "2 sends were pending" in fs[0].message
    assert fs[0].severity == "advisory"
    # sequential send/recv/send/recv: never two pending — clean
    assert verify({
        0: [send(0, 0, dst=1),
            S(rank=0, pos=1, kind="coll", op="barrier", comm_key=0, seq=0,
              participants=(0, 1))],
        1: [recv(1, 0, src=0),
            S(rank=1, pos=1, kind="coll", op="barrier", comm_key=0, seq=0,
              participants=(0, 1))],
    }) == []


def test_comm_key_watermark_alignment():
    # comms created BEFORE the analysis keep their uid identity: a
    # rank-divergent program where rank 0 uses only comm B and rank 1
    # only comm A must NOT match the two collectives as one instance
    a = [E(0, "allreduce", comm_uid=5, comm_size=2,
           groups=((0, 1),))]
    b = [E(0, "allreduce", comm_uid=7, comm_size=2,
           groups=((0, 1),))]
    s0 = schedule.build_schedule(b, rank=0, world=2, uid_watermark=100)
    s1 = schedule.build_schedule(a, rank=1, world=2, uid_watermark=100)
    assert s0[0].comm_key != s1[0].comm_key
    codes = verify({0: s0, 1: s1})
    # each peer orphaned on the comm it never joins, and the mutual
    # block in collectives on DIFFERENT comms is the interleave MPX120
    assert codes == ["MPX123", "MPX123", "MPX120"], codes
    # comms created DURING the trace (uid >= watermark, fresh per
    # re-trace) align by creation order instead
    t0 = schedule.build_schedule(
        [E(0, "allreduce", comm_uid=101, comm_size=2, groups=((0, 1),))],
        rank=0, world=2, uid_watermark=100)
    t1 = schedule.build_schedule(
        [E(0, "allreduce", comm_uid=102, comm_size=2, groups=((0, 1),))],
        rank=1, world=2, uid_watermark=100)
    assert t0[0].comm_key == t1[0].comm_key
    assert verify({0: t0, 1: t1}) == []


def test_build_schedule_unpaired_wait_skipped():
    # an unpaired wait is MPX112's domain; the schedule must not invent
    # an instance for it
    events = [E(0, "allreduce_wait", comm_uid=1, comm_size=2, span=5)]
    assert schedule.build_schedule(events, rank=0, world=2) == []


# ---------------------------------------------------------------------------
# the rank-concretization scope
# ---------------------------------------------------------------------------


def test_concrete_scope_coords_and_ranks():
    with schedule.scope(("y", "x"), (2, 4), 6):
        assert schedule.concretizing()
        assert schedule.concrete_comm_rank(("y", "x")) == 6
        assert schedule.concrete_comm_rank(("x",)) == 2
        assert schedule.concrete_comm_rank(("y",)) == 1
        assert schedule.concrete_comm_rank(("z",)) is None  # unknown axis
    assert not schedule.concretizing()
    assert schedule.concrete_comm_rank(("x",)) is None


def test_groups_for_axes_partitions():
    with schedule.scope(("y", "x"), (2, 4), 0):
        assert schedule.groups_for_axes(("x",)) == ((0, 1, 2, 3),
                                                    (4, 5, 6, 7))
        assert schedule.groups_for_axes(("y",)) == ((0, 4), (1, 5),
                                                    (2, 6), (3, 7))
        assert schedule.groups_for_axes(("y", "x")) == (tuple(range(8)),)
    assert schedule.groups_for_axes(("x",)) is None


def test_scope_validates():
    with pytest.raises(ValueError, match="out of range"):
        schedule.ConcreteScope(("x",), (4,), 4)


def test_rank_concrete_is_data_not_structure():
    with schedule.scope(("i",), (8,), 5):
        r = schedule.concrete_comm_rank(("i",))
    # an int for every data use...
    assert isinstance(r, int) and r == 5
    assert (r % 2 == 0) is False
    # ...but tagged, so structural validation still refuses it
    assert schedule.is_rank_concrete(r)
    # any derivation strips the tag: rank-derived values are statics
    assert not schedule.is_rank_concrete(r % 2)
    assert not schedule.is_rank_concrete(r ^ 1)
    assert not schedule.is_rank_concrete(int(r))
    assert not schedule.is_rank_concrete(5)


def test_rankspec_refuses_concrete_rank_as_routing():
    # the per-rank re-trace must refuse exactly what the traced-rank
    # form refuses: rank-as-routing is MPX104 either way (a bare static
    # int stays MPX103)
    rankspec = sys.modules[f"{_ISO_NAME}.parallel.rankspec"]
    r = schedule.RankConcrete(1)
    with pytest.raises(TypeError, match=r"\[MPX104\]") as ei:
        rankspec.normalize_dest(r, 4, what="send")
    assert ei.value.mpx_code == "MPX104"
    with pytest.raises(TypeError, match=r"\[MPX103\]"):
        rankspec.normalize_dest(1, 4, what="send")


# ---------------------------------------------------------------------------
# rank-list normalization + report plumbing
# ---------------------------------------------------------------------------


def test_resolve_rank_list():
    # crossrank imports jax lazily, but its module imports hook (fine
    # under the isolated loader too)
    crossrank = importlib.import_module(f"{_ISO_NAME}.analysis.crossrank")
    assert crossrank.resolve_rank_list("all", 4) == (0, 1, 2, 3)
    assert crossrank.resolve_rank_list(2, 4) == (0, 1)
    assert crossrank.resolve_rank_list([3, 1], 4) == (1, 3)
    with pytest.raises(ValueError):
        crossrank.resolve_rank_list(5, 4)
    with pytest.raises(ValueError):
        crossrank.resolve_rank_list([4], 4)
    with pytest.raises(ValueError):
        crossrank.resolve_rank_list([], 4)


def test_analyze_ranks_flag_parsing(monkeypatch):
    config = sys.modules[f"{_ISO_NAME}.utils.config"]
    monkeypatch.delenv("MPI4JAX_TPU_ANALYZE_RANKS", raising=False)
    assert config.analyze_ranks() == "auto"
    monkeypatch.setenv("MPI4JAX_TPU_ANALYZE_RANKS", "off")
    assert config.analyze_ranks() == "off"
    monkeypatch.setenv("MPI4JAX_TPU_ANALYZE_RANKS", "8")
    assert config.analyze_ranks() == 8
    monkeypatch.setenv("MPI4JAX_TPU_ANALYZE_RANKS", "zero")
    with pytest.raises(ValueError, match="MPI4JAX_TPU_ANALYZE_RANKS"):
        config.analyze_ranks()
    monkeypatch.setenv("MPI4JAX_TPU_ANALYZE_RANKS", "0")
    with pytest.raises(ValueError, match="MPI4JAX_TPU_ANALYZE_RANKS"):
        config.analyze_ranks()


def test_finding_and_report_to_json():
    f = report.Finding(code="MPX121", message="cycle", suggestion="break",
                       op="recv", index=3, rank=1, seq=0)
    j = f.to_json()
    assert j["code"] == "MPX121" and j["severity"] == "error"
    assert j["rank"] == 1 and j["seq"] == 0
    assert "deadlock" in j["title"]
    rep = report.Report(findings=(f,), events=(1, 2), meta={"ranks": [0, 1]})
    payload = rep.to_json()
    assert payload["ok"] is False and payload["errors"] == 1
    assert payload["codes"] == {"MPX121": 1}
    assert payload["events"] == 2
    assert payload["meta"]["ranks"] == [0, 1]
    # json-serializable end to end
    import json

    json.dumps(payload)


def test_report_sink_plumbing():
    hook = importlib.import_module(f"{_ISO_NAME}.analysis.hook")
    sink = []
    hook.set_report_sink(sink)
    try:
        rep = report.Report(findings=(report.Finding("MPX121", "x"),))
        hook.sink_report("here", rep)
        assert sink == [("here", rep)]
    finally:
        hook.set_report_sink(None)
    hook.sink_report("ignored", rep)  # no sink: no-op
    assert len(sink) == 1


def test_run_checkers_skip():
    checkers = sys.modules[f"{_ISO_NAME}.analysis.checkers"]
    g = graph.CollectiveGraph(events=[
        E(0, "send", comm_uid=1, tag=0, dtype="f", shape=(1,)),
    ])
    assert [f.code for f in checkers.run_checkers(g)] == ["MPX101"]
    assert checkers.run_checkers(g, skip=("MPX101",)) == []
