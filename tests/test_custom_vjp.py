"""custom_vjp integration through allreduce (ref tests/collective_ops/
test_allreduce.py:227-324: test_custom_vjp + the NetKet-derived
test_advanced_jvp, which computes a jax.vjp *inside* a custom_vjp bwd rule —
the hardest autodiff/effects interaction the reference supports)."""

from functools import partial

import numpy as np

import jax
import jax.numpy as jnp

import mpi4jax_tpu as mpx

SIZE = 8


def test_custom_vjp_through_allreduce():
    # ref test_allreduce.py:227-251: allreduce in both the primal and the
    # backward rule of a custom_vjp function
    @mpx.spmd
    def run(x, y):
        @jax.custom_vjp
        def f(x, y):
            r = (jnp.sin(x) * y).sum()
            return mpx.allreduce(r, op=mpx.SUM)[0]

        def f_fwd(x, y):
            return f(x, y), (jnp.cos(x), jnp.sin(x), y)

        def f_bwd(res, g):
            g = mpx.allreduce(g, op=mpx.SUM)[0]
            cos_x, sin_x, y = res
            return (cos_x * g * y, sin_x * g)

        f.defvjp(f_fwd, f_bwd)
        val = f(x, y)
        grads = jax.grad(f)(x, y)
        return mpx.varying((val, grads))

    x = jnp.ones((SIZE, 3))
    y = jnp.ones((SIZE, 3)) * 2
    val, grads = run(x, y)
    np.testing.assert_allclose(
        np.asarray(val)[0], SIZE * 3 * np.sin(1.0) * 2, rtol=1e-6
    )
    # d/dx sum_r sum_i sin(x_i) y_i, with the bwd-rule's extra allreduce(g):
    # g is already replicated so the sum multiplies it by SIZE
    np.testing.assert_allclose(
        np.asarray(grads[0]), SIZE * np.cos(1.0) * 2, rtol=1e-6
    )


def test_netket_style_expect_vjp():
    # ref test_allreduce.py:254-324 (netket.jax.expect): custom_vjp whose
    # backward rule computes a fresh jax.vjp through another allreduce
    n_chains = 4

    def make(comm_size):
        @partial(jax.custom_vjp, nondiff_argnums=(0, 1))
        def _expect(log_pdf, expected_fun, pars, x):
            L_x = expected_fun(pars, x).reshape((n_chains, -1))
            return mpx.allreduce(L_x.mean(), op=mpx.SUM)[0] / comm_size

        def _expect_fwd(log_pdf, expected_fun, pars, x):
            L_x = expected_fun(pars, x)
            L_mean = mpx.allreduce(
                L_x.reshape((n_chains, -1)).mean(), op=mpx.SUM
            )[0] / comm_size
            return L_mean, (pars, x, L_x - L_mean)

        def _expect_bwd(log_pdf, expected_fun, residuals, dout):
            pars, x, dL_x = residuals

            def f(pars, x):
                log_p = log_pdf(pars, x)
                term1 = jax.vmap(jnp.multiply)(dL_x, log_p)
                term2 = expected_fun(pars, x)
                out = mpx.allreduce(
                    jnp.mean(term1 + term2, axis=0), op=mpx.SUM
                )[0] / comm_size
                return out.sum()

            _, pb = jax.vjp(f, pars, x)
            return pb(dout)

        _expect.defvjp(_expect_fwd, _expect_bwd)
        return _expect

    def log_pdf(w, x):
        return jnp.sum(x @ w, axis=-1)

    def expected_fun(w, x):
        return jnp.exp(jnp.sum(x @ w, axis=-1)) - 2

    w = jax.random.normal(jax.random.PRNGKey(3), (4, 6))
    xs = jax.random.normal(jax.random.PRNGKey(4), (SIZE, n_chains, 4))

    @mpx.spmd
    def run(w_stack, x):
        expect = make(SIZE)
        O, vjpfun = jax.vjp(lambda w: expect(log_pdf, expected_fun, w, x), w_stack)
        (gw,) = vjpfun(jnp.ones_like(O))
        return mpx.varying((O, gw))

    w_stack = jnp.tile(w[None], (SIZE, 1, 1))
    O, gw = run(w_stack, xs)
    O, gw = np.asarray(O), np.asarray(gw)
    assert np.all(np.isfinite(O)) and np.all(np.isfinite(gw))
    # the expectation is a mean over ALL ranks' chains: compare against the
    # same computation done locally on the full batch
    x_all = xs.reshape(-1, 4)
    full = np.asarray(expected_fun(w, x_all)).mean()
    np.testing.assert_allclose(O[0], full, rtol=1e-5)
    # each rank's vjp covers its local samples (the reference's MPI model:
    # per-process gradient pieces, summed by the caller); the rank-sum must
    # equal the full-batch score-function gradient computed single-device
    L = expected_fun(w, x_all)
    dL = L - L.mean()

    def full_batch_surrogate(w_):
        return jnp.mean(dL * log_pdf(w_, x_all) + expected_fun(w_, x_all))

    expected_grad = np.asarray(jax.grad(full_batch_surrogate)(w))
    np.testing.assert_allclose(gw.sum(axis=0), expected_grad, rtol=1e-4)
