"""Tokenless API tests (ref tests/experimental/test_notoken.py).

The reference's notoken suite proves that *implicit* ordering (JAX ordered
effects) preserves program order for point-to-point messages — the "hot
potato" test provably fails without it (ref test_notoken.py:80-131) — and
that ops work inside ``fori_loop``/``while_loop``/``cond`` (:134-190) and
rank-divergent cond branches (:316-357).  Here ordering is structural (one
SPMD program; ppermute pairs are data-ordered), so the same behaviors are
asserted through the tokenless wrappers.
"""

import numpy as np

import jax
import jax.numpy as jnp

import mpi4jax_tpu as mpx
from mpi4jax_tpu.experimental import notoken

SIZE = 8


def test_allreduce_and_variants():
    @mpx.spmd
    def f(x):
        return notoken.allreduce(x, op=mpx.SUM)

    out = np.asarray(f(jnp.arange(SIZE, dtype=jnp.float32)[:, None]))
    assert (out == np.arange(SIZE).sum()).all()


def test_all_ops_smoke():
    """Every tokenless wrapper returns data only (no token tuple)."""

    @mpx.spmd
    def f(x):
        size = SIZE
        a = notoken.allreduce(x, op=mpx.SUM)
        b = notoken.allgather(x)
        c = notoken.bcast(x, 0)
        d = notoken.gather(x, 0)
        e = notoken.reduce(x, mpx.SUM, 0)
        g = notoken.scan(x)
        h = notoken.sendrecv(x, x, dest=mpx.shift(1))
        notoken.barrier()
        i = notoken.alltoall(jnp.tile(x, (size, 1)))
        j = notoken.scatter(jnp.tile(x, (size, 1)), 0)
        return a, b.sum(0), c, d.sum(0), e, g, h, i.sum(0), j

    outs = f(jnp.arange(SIZE, dtype=jnp.float32)[:, None])
    for o in outs:
        assert np.all(np.isfinite(np.asarray(o)))


def test_send_recv_return_none_and_data():
    @mpx.spmd
    def f(x):
        notoken.send(x, dest=[(0, 1)])
        got = notoken.recv(x, tag=0)
        return got

    out = np.asarray(f(jnp.arange(SIZE, dtype=jnp.float32)[:, None])).ravel()
    # rank 1 received rank 0's value; everyone else kept the template
    assert out[1] == 0.0
    assert (out[2:] == np.arange(2, SIZE)).all()


def test_hot_potato():
    """Ref test_notoken.py:80-131: pass a value around the ring one hop per
    step; strict program order makes the final value land back at rank 0."""

    @mpx.spmd
    def f(x):
        val = x
        for _ in range(SIZE):
            val = notoken.sendrecv(val, val, dest=mpx.shift(1))
        return val

    start = jnp.arange(SIZE, dtype=jnp.float32)[:, None]
    out = np.asarray(f(start)).ravel()
    # SIZE hops around a SIZE-ring is the identity
    assert (out == np.arange(SIZE)).all()


def test_inside_fori_loop():
    @mpx.spmd
    def f(x):
        def body(_, v):
            return mpx.varying(notoken.allreduce(v, op=mpx.SUM))

        return jax.lax.fori_loop(0, 3, body, x)

    out = np.asarray(f(jnp.ones((SIZE, 1), jnp.float32)))
    assert (out == SIZE**3).all()


def test_inside_cond():
    """Ops must work under lax.cond with identical branches on all ranks
    (rank-divergent *communication schedules* are impossible under SPMD —
    the reference needs tokens to survive them; see docs/sharp_bits.md)."""

    @mpx.spmd
    def f(x, flag):
        def yes(v):
            # collective outputs are replicated-typed; re-type as varying so
            # both branches agree (docs/sharp_bits.md)
            return mpx.varying(notoken.allreduce(v, op=mpx.SUM))

        def no(v):
            return v

        return jax.lax.cond(flag[0] > 0, yes, no, x)

    ones = jnp.ones((SIZE, 1), jnp.float32)
    on = np.asarray(f(ones, jnp.ones((SIZE, 1))))
    off = np.asarray(f(ones, jnp.zeros((SIZE, 1))))
    assert (on == SIZE).all() and (off == 1).all()


def _count_all_reduce(stablehlo: str) -> int:
    return stablehlo.count("all_reduce")


def test_notoken_barrier_survives_dce():
    """The tokenless barrier's AllReduce must appear in the lowered program
    even though no value is returned from it (the pending_sync mechanism;
    a plain discarded psum would be dead-code-eliminated)."""
    import mpi4jax_tpu.parallel.region as region

    comm = mpx.get_default_comm()

    def with_barrier(x):
        from mpi4jax_tpu.parallel.region import RegionContext, _region_stack

        ctx = RegionContext(comm)
        _region_stack.append(ctx)
        try:
            notoken.barrier()
            out = x * 2
            if ctx.pending_sync is not None:
                from mpi4jax_tpu.ops.token import tie

                out = tie(ctx.pending_sync, out)
            return out
        finally:
            _region_stack.pop()

    lowered = jax.jit(
        jax.shard_map(
            with_barrier,
            mesh=comm.mesh,
            in_specs=jax.sharding.PartitionSpec(comm.axis),
            out_specs=jax.sharding.PartitionSpec(comm.axis),
        )
    ).lower(jnp.ones((SIZE,)))
    assert _count_all_reduce(lowered.as_text()) >= 1


def test_notoken_barrier_orders_next_op():
    """barrier followed by an op: both collectives appear, barrier first."""

    @mpx.spmd
    def f(x):
        notoken.barrier()
        return notoken.allreduce(x, op=mpx.SUM)

    out = np.asarray(f(jnp.ones((SIZE, 1), jnp.float32)))
    assert (out == SIZE).all()


def test_trailing_notoken_barrier_in_region():
    """A barrier as the LAST statement of a region is tied into the region
    outputs (not elided)."""

    @mpx.spmd
    def f(x):
        y = notoken.allreduce(x, op=mpx.SUM)
        notoken.barrier()
        return mpx.varying(y)

    out = np.asarray(f(jnp.ones((SIZE, 1), jnp.float32)))
    assert (out == SIZE).all()


def test_token_barrier_survives_prefer_notoken(monkeypatch):
    """With PREFER_NOTOKEN=1, consume() is a no-op, so the token-API barrier
    must anchor itself through the pending-sync mechanism: two all_reduce
    ops must appear in the lowering (one allreduce + one barrier)."""
    comm = mpx.get_default_comm()

    def prog(x):
        tok = mpx.create_token()
        y, tok = mpx.allreduce(x, op=mpx.SUM, token=tok)
        mpx.barrier(token=tok)
        return mpx.varying(y)

    def lower_count():
        lowered = jax.jit(
            jax.shard_map(
                lambda x: _in_region(comm, prog, x),
                mesh=comm.mesh,
                in_specs=jax.sharding.PartitionSpec(comm.axis),
                out_specs=jax.sharding.PartitionSpec(comm.axis),
            )
        ).lower(jnp.ones((SIZE,)))
        return _count_all_reduce(lowered.as_text())

    monkeypatch.setenv("MPI4JAX_TPU_PREFER_NOTOKEN", "0")
    baseline = lower_count()
    monkeypatch.setenv("MPI4JAX_TPU_PREFER_NOTOKEN", "1")
    assert lower_count() == baseline == 2


def _in_region(comm, fn, *args):
    from mpi4jax_tpu.ops.token import tie
    from mpi4jax_tpu.parallel.region import RegionContext, _region_stack

    ctx = RegionContext(comm)
    _region_stack.append(ctx)
    try:
        out = fn(*args)
        if ctx.pending_sync is not None:
            sync = ctx.pending_sync
            ctx.pending_sync = None
            out = jax.tree.map(lambda v: tie(sync, v), out)
        return out
    finally:
        _region_stack.pop()


def test_notoken_barrier_in_raw_shard_map_survives():
    """notoken.barrier inside a user's own shard_map (no spmd wrapper) must
    still execute (anchored via an effectful callback, not a leakable
    pending tracer) and must not leak state into the global context."""
    import mpi4jax_tpu.parallel.region as region

    comm = mpx.get_default_comm()

    def body(x):
        notoken.barrier()
        return notoken.allreduce(x, op=mpx.SUM)

    lowered = jax.jit(
        jax.shard_map(
            body,
            mesh=comm.mesh,
            in_specs=jax.sharding.PartitionSpec(comm.axis),
            out_specs=jax.sharding.PartitionSpec(comm.axis),
        )
    ).lower(jnp.ones((SIZE,)))
    assert _count_all_reduce(lowered.as_text()) >= 2
    assert region._global_ctx.pending_sync is None


def test_prefer_notoken_skips_token_chains(monkeypatch):
    """MPI4JAX_TPU_PREFER_NOTOKEN=1 drops optimization_barrier threading
    from the token API (ref _src/utils.py:175-177 delegation) while keeping
    results and the barrier collective intact."""
    monkeypatch.setenv("MPI4JAX_TPU_PREFER_NOTOKEN", "1")

    @mpx.spmd
    def f(x):
        tok = mpx.create_token()
        y, tok = mpx.allreduce(x, op=mpx.SUM, token=tok)
        tok = mpx.barrier(token=tok)
        return mpx.varying(y)

    out = np.asarray(f(jnp.ones((SIZE, 1), jnp.float32)))
    assert (out == SIZE).all()


def test_notoken_eager_send_recv_deferred_pairing():
    # the tokenless API inherits standalone eager send/recv (deferred
    # pairing, ops/send.py): send queues, recv emits the fused permute
    from helpers import ranks_arange, world

    _, size = world()
    x = ranks_arange((2,))
    notoken.send(x, dest=mpx.shift(1), tag=31)
    res = notoken.recv(x, tag=31)
    assert np.allclose(np.asarray(res)[:, 0], np.roll(np.arange(size), 1))
    mpx.flush()
