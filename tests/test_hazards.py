"""Dataflow hazard verifier: the traced integration half.

Real 8-device programs through both front-ends (docs/analysis.md
"Dataflow hazards"):

- ``mpx.analyze(...)`` — findings land in ``Report.hazards``, the taint
  frontier rides ``to_json()``;
- the ambient ``MPI4JAX_TPU_ANALYZE=error`` path — the same pass at
  trace time, before anything compiles.

Covers the donation race (MPX139, the traced twin of
examples/broken/overlap_donation_race.py), use-after-donate (MPX140),
the rank-local schedule gate (MPX141, the traced twin of
examples/broken/ef_divergent_gate.py without the compression layer —
the hazard is structural), the approximate-lineage advisory (MPX142,
codec-armed), the HLO byte-identity pin across analyze modes with a
donating program, and the cache-token pin (flipping the mode stales
pinned programs).  The pure fake-jaxpr matrix lives in
tests/test_hazards_pure.py.
"""

import jax
import jax.numpy as jnp
from jax import lax
import pytest

import mpi4jax_tpu as mpx
from mpi4jax_tpu.analysis import hook
from helpers import ranks_arange, world


@pytest.fixture(autouse=True)
def _reset_analysis(monkeypatch):
    monkeypatch.delenv("MPI4JAX_TPU_ANALYZE", raising=False)
    monkeypatch.delenv("MPI4JAX_TPU_ANALYZE_RANKS", raising=False)
    yield
    mpx.set_analyze_mode(None)
    mpx.clear_caches()


def codes(report):
    return [f.code for f in report.findings]


# ---------------------------------------------------------------------------
# MPX139: buffer donated while an open async span holds it
# ---------------------------------------------------------------------------


def _pinned_scale(donate=True):
    local = jax.ShapeDtypeStruct((16,), jnp.float32)
    kw = {"donate_argnums": (0,)} if donate else {}
    return mpx.compile(lambda v: v * 2.0, local, wrap=False, **kw)


def _donation_race_step(comm):
    """The overlap_donation_race.py program: donate mid-span."""
    scale = _pinned_scale()

    def step(x):
        handle, t = mpx.allreduce_start(x, mpx.SUM, comm=comm)
        y = scale(x)  # BUG: x is still held by the open span
        total, t = mpx.allreduce_wait(handle, token=t)
        return total + y

    return step


def _wait_then_donate_step(comm):
    """The fixed twin: the span closes before the donation."""
    scale = _pinned_scale()

    def step(x):
        handle, t = mpx.allreduce_start(x, mpx.SUM, comm=comm)
        total, t = mpx.allreduce_wait(handle, token=t)
        y = scale(x)  # span closed: donating x is legal now
        return total + y

    return step


def test_mpx139_donation_race_via_analyze():
    comm, _ = world()
    step = _donation_race_step(comm)
    x = ranks_arange((16,))
    report = mpx.analyze(step, x, comm=comm)
    assert "MPX139" in codes(report)
    f = next(f for f in report.findings if f.code == "MPX139")
    assert f.severity == "error"
    # buffer ids are equality handles, never rendered
    assert "0x" not in f.message
    # and the finding is surfaced through the hazards partition
    assert "MPX139" in [g.code for g in report.hazards]


def test_mpx139_donation_race_via_env_error():
    comm, _ = world()
    x = ranks_arange((16,))
    mpx.set_analyze_mode("error")
    # pin under the new mode epoch: flipping the analyze mode stales
    # programs pinned before it
    step = _donation_race_step(comm)
    with pytest.raises(mpx.AnalysisError) as ei:
        mpx.run(step, x, comm=comm)
    assert any(f.code == "MPX139" for f in ei.value.findings)


def test_mpx139_silent_when_donation_follows_wait():
    comm, _ = world()
    step = _wait_then_donate_step(comm)
    x = ranks_arange((16,))
    report = mpx.analyze(step, x, comm=comm)
    assert not {"MPX139", "MPX140"} & set(codes(report))


def test_mpx139_silent_without_donation():
    comm, _ = world()
    scale = _pinned_scale(donate=False)

    def step(x):
        handle, t = mpx.allreduce_start(x, mpx.SUM, comm=comm)
        y = scale(x)  # no donate_argnums: reading mid-span is fine
        total, t = mpx.allreduce_wait(handle, token=t)
        return total + y

    x = ranks_arange((16,))
    report = mpx.analyze(step, x, comm=comm)
    assert not {"MPX139", "MPX140"} & set(codes(report))


# ---------------------------------------------------------------------------
# MPX140: value consumed after the donating pinned call
# ---------------------------------------------------------------------------


def _use_after_donate_step(comm):
    scale = _pinned_scale()

    def step(x):
        y = scale(x)  # donates x's storage
        # BUG: the collective reads a buffer the executable may have
        # already overwritten in place
        total, _ = mpx.allreduce(x, mpx.SUM, comm=comm)
        return total + y

    return step


def test_mpx140_use_after_donate_via_analyze():
    comm, _ = world()
    step = _use_after_donate_step(comm)
    x = ranks_arange((16,))
    report = mpx.analyze(step, x, comm=comm)
    assert "MPX140" in codes(report)
    f = next(f for f in report.findings if f.code == "MPX140")
    assert f.severity == "error"
    assert "MPX140" in [g.code for g in report.hazards]


def test_mpx140_use_after_donate_via_env_error():
    comm, _ = world()
    x = ranks_arange((16,))
    mpx.set_analyze_mode("error")
    step = _use_after_donate_step(comm)
    with pytest.raises(mpx.AnalysisError) as ei:
        mpx.run(step, x, comm=comm)
    assert any(f.code == "MPX140" for f in ei.value.findings)


def test_mpx140_silent_when_collective_precedes_donation():
    comm, _ = world()
    scale = _pinned_scale()

    def step(x):
        total, _ = mpx.allreduce(x, mpx.SUM, comm=comm)
        y = scale(x)  # donation last: nothing reads x afterwards
        return total + y

    x = ranks_arange((16,))
    report = mpx.analyze(step, x, comm=comm)
    assert not {"MPX139", "MPX140"} & set(codes(report))


# ---------------------------------------------------------------------------
# MPX141: rank-local lineage gates divergent collective schedules
# ---------------------------------------------------------------------------


def _divergent_gate_step(comm, diverge=True):
    """The ef_divergent_gate.py shape without the compression layer: the
    raw per-rank input is rank-varying by type, so gating a cond on it
    is structurally the same hazard as gating on the EF residual."""

    def step(x):
        total, _ = mpx.allreduce(x, mpx.SUM, comm=comm)
        drift = jnp.max(jnp.abs(x))  # rank-LOCAL: raw input, not total

        def resync(v):
            s, _ = mpx.allreduce(v, mpx.SUM, comm=comm)
            m, _ = mpx.allreduce(jnp.mean(s) * jnp.ones_like(s),
                                 mpx.SUM, comm=comm)
            return s - m

        def keep(v):
            s, _ = mpx.allreduce(v, mpx.SUM, comm=comm)
            return s

        left = resync if diverge else keep
        return lax.cond(drift > jnp.float32(0.5), left, keep, total)

    return step


def test_mpx141_divergent_gate_via_analyze():
    comm, _ = world()
    step = _divergent_gate_step(comm)
    x = ranks_arange((16,))
    report = mpx.analyze(step, x, comm=comm)
    assert "MPX141" in codes(report)
    f = next(f for f in report.findings if f.code == "MPX141")
    assert f.severity == "error"
    # the op-by-op taint frontier is rendered and serialized
    assert f.frontier and "cond predicate" in f.frontier[-1]
    assert "taint:" in report.render()
    payload = next(d for d in report.to_json()["findings"]
                   if d["code"] == "MPX141")
    assert payload["frontier"]
    # both branches communicate: the structural checker stays silent
    assert "MPX108" not in codes(report)


def test_mpx141_divergent_gate_via_env_error():
    comm, _ = world()
    step = _divergent_gate_step(comm)
    x = ranks_arange((16,))
    mpx.set_analyze_mode("error")
    with pytest.raises(mpx.AnalysisError) as ei:
        mpx.run(step, x, comm=comm)
    assert any(f.code == "MPX141" for f in ei.value.findings)


def test_mpx141_silent_when_schedules_agree():
    comm, _ = world()
    step = _divergent_gate_step(comm, diverge=False)
    x = ranks_arange((16,))
    report = mpx.analyze(step, x, comm=comm)
    # still rank-gated, but both branches issue the same schedule: no
    # rank can hang another
    assert "MPX141" not in codes(report)


# ---------------------------------------------------------------------------
# MPX142: approximate lineage reaches an exactness-required sink
# ---------------------------------------------------------------------------


def _codec_gate_step(comm):
    def step(x):
        total, _ = mpx.allreduce(x, mpx.SUM, comm=comm)
        # a codec-style lossy roundtrip on the gating value
        q = total.astype(jnp.bfloat16).astype(jnp.float32)

        def a(v):
            s, _ = mpx.allreduce(v, mpx.SUM, comm=comm)
            return s

        def b(v):
            s, _ = mpx.allreduce(v, mpx.SUM, comm=comm)
            return s

        # same schedule on both sides: MPX141 has nothing to say, but
        # quantization error can still flip the pick differently per rank
        return lax.cond(jnp.max(q) > jnp.float32(0.5), a, b, total)

    return step


def test_mpx142_advisory_when_codec_armed(monkeypatch):
    comm, _ = world()
    monkeypatch.setenv("MPI4JAX_TPU_COMPRESS", "bf16")
    mpx.clear_caches()
    step = _codec_gate_step(comm)
    x = ranks_arange((16,))
    report = mpx.analyze(step, x, comm=comm)
    assert "MPX142" in codes(report)
    f = next(f for f in report.findings if f.code == "MPX142")
    assert f.severity == "advisory"
    assert f.frontier  # the downcast seed is named op by op
    assert "MPX141" not in codes(report)


def test_mpx142_unarmed_without_codec_activity(monkeypatch):
    comm, _ = world()
    # same program, no codec anywhere in the config or the recorded
    # graph: plain mixed precision must never taint
    monkeypatch.delenv("MPI4JAX_TPU_COMPRESS", raising=False)
    mpx.clear_caches()
    step = _codec_gate_step(comm)
    x = ranks_arange((16,))
    report = mpx.analyze(step, x, comm=comm)
    assert "MPX142" not in codes(report)


# ---------------------------------------------------------------------------
# mode pins: byte-identical HLO + the analysis cache token
# ---------------------------------------------------------------------------


def test_hlo_byte_identical_across_modes_with_donation():
    # the hazard pass is pure host-side bookkeeping: a CLEAN donating
    # program must lower byte-identically in off/warn/error (the
    # schedule-checker version of this pin lives in test_analysis.py /
    # test_crossrank.py)
    from mpi4jax_tpu.parallel.region import spmd

    comm, _ = world()
    x = ranks_arange((16,))
    texts = {}
    for mode in (None, "warn", "error"):
        mpx.set_analyze_mode(mode)
        mpx.clear_caches()
        step = _wait_then_donate_step(comm)
        twin = spmd(lambda v: mpx.varying(step(v)), comm=comm, jit=False)
        texts[mode] = jax.jit(twin).lower(x).as_text()
    assert texts[None] == texts["warn"] == texts["error"]


def test_analysis_cache_token_tracks_mode(monkeypatch):
    # the token is folded into every compiled-program cache key: a mode
    # flip (or a cross-rank setting change) must stale pinned programs
    base = hook.analysis_cache_token()
    mpx.set_analyze_mode("error")
    armed = hook.analysis_cache_token()
    assert armed != base
    mpx.set_analyze_mode(None)
    assert hook.analysis_cache_token() == base
    monkeypatch.setenv("MPI4JAX_TPU_ANALYZE_RANKS", "4")
    assert hook.analysis_cache_token() != base


def test_clean_program_clean_at_all_ranks():
    # the acceptance shape of the CI analyze lane: a non-broken program
    # carries zero hazard findings through the cross-rank path too
    from mpi4jax_tpu.analysis.report import HAZARD_CODES

    comm, _ = world()
    step = _wait_then_donate_step(comm)
    x = ranks_arange((16,))
    report = mpx.analyze(step, x, comm=comm, ranks="all")
    assert not set(HAZARD_CODES) & set(codes(report))
    assert not report.hazards
