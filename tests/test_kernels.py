"""Pallas kernel tests: flash-attention block partials.

The kernel (``mpi4jax_tpu/kernels/flash_attention.py``) is the ring-attention
hot op — ``mpi4jax_tpu.attention.ring_attention`` calls it once
per ring step.  Interpret mode runs the actual kernel body on CPU; the
acceptance criterion is equality with the identical-math jnp path
(``force_jnp=True``), including rows with no attendable key, which must come
back as ``m = -inf``, ``l = 0``, ``o = 0`` rather than NaN.
"""

import math

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from mpi4jax_tpu.kernels.flash_attention import (
    flash_block_partials,
    merge_partials,
)


def _qkv(seed, b, tq, tk, h, d, dtype=jnp.float32):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    q = jax.random.normal(ks[0], (b, tq, h, d), dtype)
    k = jax.random.normal(ks[1], (b, tk, h, d), dtype)
    v = jax.random.normal(ks[2], (b, tk, h, d), dtype)
    return q, k, v


@pytest.mark.parametrize(
    "b,tq,tk,h,d",
    [
        (1, 16, 16, 1, 32),
        (2, 16, 24, 4, 32),  # rectangular block (ring step of unequal shards)
        (2, 8, 8, 3, 64),
        (1, 257, 1100, 1, 32),  # ragged q AND k tiles (streaming loop)
    ],
)
def test_kernel_matches_jnp_path(b, tq, tk, h, d):
    q, k, v = _qkv(0, b, tq, tk, h, d)
    mask = jax.random.bernoulli(jax.random.PRNGKey(7), 0.8, (tq, tk))
    scale = 1.0 / math.sqrt(d)
    o_k, m_k, l_k = flash_block_partials(q, k, v, mask, scale=scale,
                                         interpret=True)
    o_j, m_j, l_j = flash_block_partials(q, k, v, mask, scale=scale,
                                         force_jnp=True)
    np.testing.assert_allclose(np.asarray(m_k), np.asarray(m_j),
                               rtol=1e-6, atol=1e-6)
    np.testing.assert_allclose(np.asarray(l_k), np.asarray(l_j),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(o_k), np.asarray(o_j),
                               rtol=1e-5, atol=1e-5)


def test_kernel_fully_masked_rows():
    """A ring step attending a strictly-future K/V block has fully-masked
    query rows: the partials must be (m=-inf, l=0, o=0) — not NaN — so the
    merge rule can ignore them."""
    b, t, h, d = 2, 16, 2, 32
    q, k, v = _qkv(1, b, t, t, h, d)
    # causal mask of a future block: every row fully masked
    mask = jnp.zeros((t, t), bool)
    for kwargs in ({"interpret": True}, {"force_jnp": True}):
        o, m, l = flash_block_partials(q, k, v, mask, scale=0.1, **kwargs)
        o, m, l = np.asarray(o), np.asarray(m), np.asarray(l)
        assert np.all(np.isinf(m)) and np.all(m < 0), kwargs
        assert np.all(l == 0.0), kwargs
        assert np.all(o == 0.0), kwargs
        assert not np.any(np.isnan(o)), kwargs


def test_kernel_partially_masked_rows():
    """The causal diagonal block: rows have 1..t attendable keys."""
    b, t, h, d = 1, 16, 2, 32
    q, k, v = _qkv(2, b, t, t, h, d)
    mask = jnp.tril(jnp.ones((t, t), bool))
    o_k, m_k, l_k = flash_block_partials(q, k, v, mask, scale=0.2,
                                         interpret=True)
    o_j, m_j, l_j = flash_block_partials(q, k, v, mask, scale=0.2,
                                         force_jnp=True)
    np.testing.assert_allclose(np.asarray(o_k), np.asarray(o_j),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(l_k), np.asarray(l_j),
                               rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("impl", ["interpret", "force_jnp"])
def test_blockwise_merge_equals_full_softmax(impl):
    """Splitting K/V into blocks, computing partials per block, and folding
    with merge_partials must equal plain full attention — the invariant
    ring_attention rests on."""
    b, t, h, d = 2, 32, 2, 32
    q, k, v = _qkv(3, b, t, t, h, d)
    scale = 1.0 / math.sqrt(d)
    kwargs = {impl: True} if impl == "force_jnp" else {"interpret": True}

    # ground truth: full softmax attention
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    expected = jnp.einsum(
        "bhqk,bkhd->bqhd", jax.nn.softmax(s, axis=-1), v
    )

    m = jnp.full((b, h, t), -jnp.inf, jnp.float32)
    l = jnp.zeros((b, h, t), jnp.float32)
    acc = jnp.zeros_like(q)
    n_blocks = 4
    blk = t // n_blocks
    full_mask = jnp.ones((t, blk), bool)
    for i in range(n_blocks):
        kb = k[:, i * blk : (i + 1) * blk]
        vb = v[:, i * blk : (i + 1) * blk]
        o_new, m_new, l_new = flash_block_partials(
            q, kb, vb, full_mask, scale=scale, **kwargs
        )
        acc, m, l = merge_partials(acc, m, l, o_new, m_new, l_new)
    out = acc / jnp.moveaxis(l, 1, 2)[..., None]
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(expected), rtol=1e-5, atol=1e-5
    )


@pytest.mark.parametrize("impl", ["interpret", "force_jnp"])
def test_mask_none_equals_all_true_mask(impl):
    b, t, h, d = 2, 16, 2, 32
    q, k, v = _qkv(5, b, t, t, h, d)
    kwargs = {impl: True} if impl == "force_jnp" else {"interpret": True}
    o_n, m_n, l_n = flash_block_partials(q, k, v, None, scale=0.2, **kwargs)
    o_t, m_t, l_t = flash_block_partials(
        q, k, v, jnp.ones((t, t), bool), scale=0.2, **kwargs
    )
    np.testing.assert_allclose(np.asarray(o_n), np.asarray(o_t),
                               rtol=1e-6, atol=1e-6)
    np.testing.assert_allclose(np.asarray(m_n), np.asarray(m_t), rtol=1e-7)
    np.testing.assert_allclose(np.asarray(l_n), np.asarray(l_t), rtol=1e-6)


@pytest.mark.parametrize("impl", ["interpret", "force_jnp"])
def test_bf16_dtype_contract(impl):
    """o_part keeps the input dtype; m/l are f32 on both paths."""
    b, t, h, d = 1, 16, 2, 32
    q, k, v = _qkv(6, b, t, t, h, d, dtype=jnp.bfloat16)
    kwargs = {impl: True} if impl == "force_jnp" else {"interpret": True}
    o, m, l = flash_block_partials(q, k, v, None, scale=0.2, **kwargs)
    assert o.dtype == jnp.bfloat16
    assert m.dtype == jnp.float32 and l.dtype == jnp.float32


def test_ring_attention_preserves_bf16_dtype():
    import os
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                    "examples"))
    from long_context_attention import ring_attention

    import mpi4jax_tpu as mpx

    comm = mpx.get_default_comm()
    size = comm.Get_size()
    shape = (size, 1, 8, 2, 32)
    q = jnp.ones(shape, jnp.bfloat16)

    @mpx.spmd
    def f(q):
        return ring_attention(q, q, q, comm=comm, causal=True)

    out = f(q)
    assert out.dtype == jnp.bfloat16


def test_merge_with_fully_masked_block_is_identity():
    b, t, h, d = 1, 8, 1, 32
    q, k, v = _qkv(4, b, t, t, h, d)
    o1, m1, l1 = flash_block_partials(q, k, v, jnp.ones((t, t), bool),
                                      scale=0.3, force_jnp=True)
    o0, m0, l0 = flash_block_partials(q, k, v, jnp.zeros((t, t), bool),
                                      scale=0.3, force_jnp=True)
    acc, m, l = merge_partials(o1, m1, l1, o0, m0, l0)
    np.testing.assert_allclose(np.asarray(acc), np.asarray(o1), rtol=1e-7)
    np.testing.assert_array_equal(np.asarray(m), np.asarray(m1))
    np.testing.assert_allclose(np.asarray(l), np.asarray(l1), rtol=1e-7)


@pytest.mark.parametrize(
    "b,t,h,d",
    [
        (2, 16, 4, 32),     # single q tile
        (1, 1024, 1, 32),   # two full tiles (exercises the fori_loop)
        (1, 1100, 1, 32),   # ragged final tile (K/V padding + kpos guard)
    ],
)
def test_causal_kernel_matches_tril_mask(b, t, h, d):
    """causal=True (the key-tile-skipping kernel) must equal the general
    kernel/jnp path given the equivalent triangular mask, including across
    tile boundaries and ragged tails."""
    q, k, v = _qkv(3, b, t, t, h, d)
    scale = 1.0 / math.sqrt(d)
    o_c, m_c, l_c = flash_block_partials(q, k, v, None, scale=scale,
                                         causal=True, interpret=True)
    mask = jnp.tril(jnp.ones((t, t), bool))
    o_j, m_j, l_j = flash_block_partials(q, k, v, mask, scale=scale,
                                         force_jnp=True)
    np.testing.assert_allclose(np.asarray(m_c), np.asarray(m_j),
                               rtol=1e-6, atol=1e-6)
    np.testing.assert_allclose(np.asarray(l_c), np.asarray(l_j),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(o_c), np.asarray(o_j),
                               rtol=1e-4, atol=1e-4)


def _normalized(o, l):
    l_safe = jnp.where(l == 0.0, 1.0, l)
    return o / jnp.moveaxis(l_safe, 1, 2)[..., None]


@pytest.mark.parametrize(
    "b,tq,tk,h,d,causal,masked",
    [
        (1, 16, 16, 2, 32, False, False),
        (2, 16, 24, 2, 32, False, False),   # rectangular ring block
        (2, 16, 24, 2, 32, False, True),    # user mask (float0 cotangent)
        (1, 64, 64, 2, 32, True, False),    # causal kernel
        (1, 550, 550, 1, 32, True, False),  # ragged tiles (padding guards)
        (1, 257, 1100, 1, 32, False, False),  # streaming non-causal tiles
        (1, 257, 1100, 1, 32, False, True),   # ... with a mask
    ],
)
def test_grad_kernel_matches_jnp_path(b, tq, tk, h, d, causal, masked):
    """The blockwise backward (Pallas kernels, run under interpret) must
    agree with the dense jnp backward — same custom-VJP formula, different
    execution/tiling — through a normalized-attention loss."""
    q, k, v = _qkv(11, b, tq, tk, h, d)
    mask = (
        jax.random.bernoulli(jax.random.PRNGKey(7), 0.8, (tq, tk))
        if masked else None
    )
    scale = 1.0 / math.sqrt(d)

    def loss(q, k, v, **kwargs):
        o, _, l = flash_block_partials(
            q, k, v, mask, scale=scale, causal=causal, **kwargs
        )
        return (_normalized(o, l) ** 2).sum()

    g_k = jax.grad(lambda *a: loss(*a, interpret=True), (0, 1, 2))(q, k, v)
    g_j = jax.grad(lambda *a: loss(*a, force_jnp=True), (0, 1, 2))(q, k, v)
    for a, e, nm in zip(g_k, g_j, "qkv"):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(e), rtol=1e-3, atol=1e-4,
            err_msg=f"d{nm}",
        )


@pytest.mark.parametrize("impl", ["interpret", "force_jnp"])
def test_grad_through_blockwise_merge(impl):
    """Gradients through a merge_partials chain: this is the path where a
    NONZERO stabilizer cotangent (g_m) reaches the custom VJP and is
    dropped — exact because the merge rule is stabilizer-invariant.  The
    composed gradient must match full-softmax attention's."""
    b, t, h, d = 1, 32, 2, 32
    q, k, v = _qkv(12, b, t, t, h, d)
    scale = 1.0 / math.sqrt(d)
    kwargs = {impl: True} if impl == "force_jnp" else {"interpret": True}
    n_blocks, blk = 4, t // 4

    def loss_blockwise(q, k, v):
        m = jnp.full((b, h, t), -jnp.inf, jnp.float32)
        l = jnp.zeros((b, h, t), jnp.float32)
        acc = jnp.zeros_like(q)
        for i in range(n_blocks):
            kb = k[:, i * blk: (i + 1) * blk]
            vb = v[:, i * blk: (i + 1) * blk]
            o_new, m_new, l_new = flash_block_partials(
                q, kb, vb, None, scale=scale, **kwargs
            )
            acc, m, l = merge_partials(acc, m, l, o_new, m_new, l_new)
        return ((acc / jnp.moveaxis(l, 1, 2)[..., None]) ** 2).sum()

    def loss_full(q, k, v):
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
        out = jnp.einsum("bhqk,bkhd->bqhd", jax.nn.softmax(s, axis=-1), v)
        return (out ** 2).sum()

    g_b = jax.grad(loss_blockwise, (0, 1, 2))(q, k, v)
    g_f = jax.grad(loss_full, (0, 1, 2))(q, k, v)
    for a, e, nm in zip(g_b, g_f, "qkv"):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(e), rtol=5e-3, atol=5e-4,
            err_msg=f"d{nm}",
        )


@pytest.mark.parametrize("impl", ["interpret", "force_jnp"])
def test_grad_fully_masked_rows_no_nan(impl):
    """Rows with no attendable key (m = -inf, l = 0) must produce ZERO
    gradients, not NaN, so the ring's skipped-block merges stay clean."""
    b, t, h, d = 1, 16, 2, 32
    q, k, v = _qkv(13, b, t, t, h, d)
    mask = jnp.zeros((t, t), bool)
    kwargs = {impl: True} if impl == "force_jnp" else {"interpret": True}

    def loss(q, k, v):
        o, _, l = flash_block_partials(q, k, v, mask, scale=0.2, **kwargs)
        return (_normalized(o, l) ** 2).sum()

    g = jax.grad(loss, (0, 1, 2))(q, k, v)
    for a, nm in zip(g, "qkv"):
        a = np.asarray(a)
        assert not np.any(np.isnan(a)), f"d{nm} has NaN"
        np.testing.assert_array_equal(a, np.zeros_like(a), err_msg=f"d{nm}")


def test_grad_bf16_dtype_contract():
    """Cotangents keep the primal dtypes on both backward paths."""
    b, t, h, d = 1, 16, 2, 32
    q, k, v = _qkv(14, b, t, t, h, d, dtype=jnp.bfloat16)
    for kwargs in ({"interpret": True}, {"force_jnp": True}):
        def loss(q, k, v):
            o, _, l = flash_block_partials(
                q, k, v, None, scale=0.2, causal=True, **kwargs
            )
            return (_normalized(o, l).astype(jnp.float32) ** 2).sum()

        g = jax.grad(loss, (0, 1, 2))(q, k, v)
        assert all(a.dtype == jnp.bfloat16 for a in g), kwargs


def test_forward_mode_stays_supported_on_jnp_path():
    """The custom VJP wraps only the kernel path: the jnp fallback must
    keep JAX's native forward-mode (jax.jvp) — regression for wrapping
    the whole dispatch in custom_vjp, which would raise TypeError here."""
    q, k, v = _qkv(15, 1, 8, 8, 1, 32)

    def f(q):
        o, _, l = flash_block_partials(q, k, v, None, scale=0.2,
                                       force_jnp=True)
        return (_normalized(o, l) ** 2).sum()

    _, tang = jax.jvp(f, (q,), (jnp.ones_like(q),))
    assert np.isfinite(float(tang))


def test_causal_kernel_validation():
    q, k, v = _qkv(4, 1, 16, 24, 1, 32)
    with pytest.raises(ValueError, match="Tq == Tk"):
        flash_block_partials(q, k, v, None, scale=1.0, causal=True,
                             interpret=True)
    q2, k2, v2 = _qkv(4, 1, 16, 16, 1, 32)
    with pytest.raises(ValueError, match="replaces mask"):
        flash_block_partials(q2, k2, v2, jnp.ones((16, 16), bool),
                             scale=1.0, causal=True, interpret=True)
