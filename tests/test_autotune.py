"""Autotune + tuning layer: the traced integration half
(docs/autotune.md).

On the real 8-device mesh: loading a tuning file retraces BOTH program
caches (the stamp is in every key) for eager and spmd alike; with no
file the dynamic cache token and the lowered HLO are byte-identical to
a build without the layer; ``resolve_algo`` flips lowerings at a seeded
measured crossover; the MPX113 advisory carries ``tuned@<stamp>``
provenance; ``mpx.elastic.run(commit_every='auto')`` completes a real
single-process run; telemetry meters/snapshot/report carry the tuning
section; and (slow) a live ``mpx.autotune()`` with a small budget emits
a file that validates, loads, and round-trips the offline CLI.  The
pure half (schema, fitters, precedence, commit math) is
tests/test_autotune_pure.py.
"""

import json
import subprocess
import sys

import pytest

import mpi4jax_tpu as mpx
from mpi4jax_tpu.autotune import SCHEMA, validate_tuning_dict
from mpi4jax_tpu.ops._base import dynamic_cache_token
from helpers import ranks_arange, world


def _tuning_payload(**over):
    base = {
        "schema": SCHEMA,
        "links": {"ici": {"alpha_us": 0.5, "gb_per_s": 50.0}},
        "tuned": {"ring_crossover_bytes": 64,
                  "fusion_bucket_bytes": 2 << 20},
        "measured": {"ring_crossover_bytes": 64},
    }
    base.update(over)
    return base


@pytest.fixture(autouse=True)
def _clean(monkeypatch):
    for var in ("MPI4JAX_TPU_TUNING", "MPI4JAX_TPU_COST_MODEL",
                "MPI4JAX_TPU_RING_CROSSOVER_BYTES",
                "MPI4JAX_TPU_COLLECTIVE_ALGO"):
        monkeypatch.delenv(var, raising=False)
    mpx.load_tuning(None)
    yield
    mpx.load_tuning(None)
    mpx.set_analyze_mode(None)
    mpx.clear_caches()


# ---------------------------------------------------------------------------
# cache-token + HLO identity with no file (pinned)
# ---------------------------------------------------------------------------


def test_no_file_token_and_hlo_identity():
    """With no tuning layer the dynamic cache token and the lowered HLO
    must be byte-identical to a build without autotune: load+clear must
    round-trip to the exact same token VALUE and program text."""
    import jax

    comm, _ = world()
    x = ranks_arange((4,))

    def lower_text():
        from jax.sharding import PartitionSpec as P

        from mpi4jax_tpu.parallel.region import make_region_body

        def step(v):
            return mpx.varying(mpx.allreduce(v, op=mpx.PROD)[0])

        body = make_region_body(step, comm, (), (), (), 1,
                                squeeze_in=True, squeeze_out=True)
        sm = jax.jit(jax.shard_map(
            body, mesh=comm.mesh, in_specs=P(comm.axes[0]),
            out_specs=P(comm.axes[0])))
        return sm.lower(x).as_text()

    tok0 = dynamic_cache_token()
    base = lower_text()
    tf = mpx.load_tuning(_tuning_payload())
    assert mpx.active_tuning() is tf
    tok1 = dynamic_cache_token()
    assert tok1 != tok0  # the stamp (and tuned crossover) moved the key
    mpx.load_tuning(None)
    assert dynamic_cache_token() == tok0  # exact VALUE round trip
    assert lower_text() == base


def test_stamp_retraces_eager_program():
    comm, _ = world()
    mpx.clear_caches()
    x = ranks_arange((4,))
    mpx.allreduce(x, op=mpx.PROD)
    # values identical to the defaults — ONLY the stamp moves the key
    mpx.load_tuning({"schema": SCHEMA})
    mpx.allreduce(x, op=mpx.PROD)                 # miss: retrace
    mpx.load_tuning({"schema": SCHEMA, "source": "v2"})
    mpx.allreduce(x, op=mpx.PROD)                 # changed file: retrace
    mpx.load_tuning(None)
    mpx.allreduce(x, op=mpx.PROD)                 # back: hit
    s = mpx.cache_stats()
    assert s["misses"] == 3 and s["hits"] == 1
    mpx.clear_caches()


def test_stamp_retraces_spmd_program():
    mpx.telemetry.reset()
    mpx.set_telemetry_mode("counters")
    try:

        @mpx.spmd
        def f(xl):
            res, _ = mpx.allreduce(xl, op=mpx.PROD)
            return res

        x = ranks_arange((4,))
        f(x)
        f(x)                                      # hit
        mpx.load_tuning({"schema": SCHEMA})
        f(x)                                      # miss: retrace
        meters = mpx.telemetry.snapshot()["meters"]
        assert meters.get("spmd_cache.misses") == 2
        assert meters.get("spmd_cache.hits") == 1
    finally:
        mpx.set_telemetry_mode(None)
        mpx.telemetry.reset()


# ---------------------------------------------------------------------------
# the selector follows the measured values
# ---------------------------------------------------------------------------


def _algo_of(fn, *args, comm):
    report = mpx.analyze(fn, *args, comm=comm)
    (evt,) = [e for e in report.events if e.op == "allreduce"]
    return evt.algo


def test_resolve_algo_flips_at_seeded_crossover(monkeypatch):
    comm, size = world()
    if size < 4:
        pytest.skip("ring needs >= 4 ranks")

    def step(v):
        return mpx.varying(mpx.allreduce(v, op=mpx.PROD)[0])

    x = ranks_arange((64,))  # 256 B/rank payload, PROD: no native HLO
    assert _algo_of(step, x, comm=comm) == "butterfly"  # default 1 MiB
    mpx.load_tuning(_tuning_payload())  # measured crossover: 64 B
    assert _algo_of(step, x, comm=comm) == "ring"
    # the env flag still wins over the file
    monkeypatch.setenv("MPI4JAX_TPU_RING_CROSSOVER_BYTES", str(1 << 20))
    assert _algo_of(step, x, comm=comm) == "butterfly"


def test_mpx113_advisory_carries_tuned_provenance(monkeypatch):
    comm, size = world()
    if size < 4 or size % 2:
        pytest.skip("needs an even multi-host-fakeable mesh")
    monkeypatch.setenv("MPI4JAX_TPU_TOPOLOGY", f"2x{size // 2}")
    monkeypatch.setenv("MPI4JAX_TPU_COLLECTIVE_ALGO", "ring")
    tf = mpx.load_tuning(_tuning_payload())

    def step(v):
        return mpx.varying(mpx.allreduce(v, op=mpx.PROD)[0])

    report = mpx.analyze(step, ranks_arange((64,)), comm=comm)
    (f,) = [x for x in report.findings if x.code == "MPX113"]
    assert f"tuned@{tf.stamp}" in f.message
    assert "measured crossover" in f.message


# ---------------------------------------------------------------------------
# elastic commit_every='auto' end to end (single process, real store)
# ---------------------------------------------------------------------------


def test_elastic_run_auto_commit():
    import numpy as np

    comm, _ = world()
    store = mpx.ShardStore(comm)

    def step(state, i, c):
        return {"p": state["p"] * 0.5 + 1.0}

    state0 = {"p": np.arange(32, dtype=np.float32)}
    out = mpx.elastic.run(step, state0, store, steps=3,
                          commit_every="auto")
    np.testing.assert_allclose(out["p"],
                               ((state0["p"] * 0.5 + 1) * 0.5 + 1)
                               * 0.5 + 1)
    assert store.committed_step == 3  # the final commit always lands


# ---------------------------------------------------------------------------
# telemetry: meters + snapshot + report section
# ---------------------------------------------------------------------------


def test_telemetry_tuning_section():
    mpx.telemetry.reset()
    mpx.set_telemetry_mode("counters")
    try:
        snap0 = mpx.telemetry.snapshot()
        assert "tuning" not in snap0  # inactive layer: no payload at all
        tf = mpx.load_tuning(_tuning_payload())
        meters = mpx.telemetry.snapshot()["meters"]
        assert meters.get("autotune.loads") == 1
        snap = mpx.telemetry.snapshot()
        assert snap["tuning"]["stamp"] == tf.stamp
        knob = snap["tuning"]["knobs"]["ring_crossover_bytes"]
        assert knob["tuned"] == 64 and knob["effective"] == 64
        text = mpx.telemetry.report(comm=None)
        assert f"tuned@{tf.stamp}" in text
        assert "ring_crossover_bytes" in text
    finally:
        mpx.set_telemetry_mode(None)
        mpx.telemetry.reset()


# ---------------------------------------------------------------------------
# the live loop + offline CLI (slow: runs real sweeps)
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_live_autotune_roundtrip(tmp_path):
    path = tmp_path / "tuning.json"
    result = mpx.autotune(budget_s=5.0, save=str(path), load=True)
    payload = json.loads(path.read_text())
    validate_tuning_dict(payload)  # the emitted file validates
    assert payload["schema"] == SCHEMA
    assert payload["links"]["ici"]["gb_per_s"] > 0
    assert payload["provenance"]["n_devices"] >= 1
    assert "fusion_bucket_bytes" in payload["tuned"]
    assert "commit" in payload["tuned"]
    # load=True installed the layer: the stamp is live
    assert mpx.active_tuning() is not None
    assert mpx.active_tuning().stamp == result.stamp
    meters = None
    mpx.set_telemetry_mode("counters")
    try:
        mpx.autotune(budget_s=2.0, load=False)
        meters = mpx.telemetry.snapshot()["meters"]
    finally:
        mpx.set_telemetry_mode(None)
        mpx.telemetry.reset()
    assert meters.get("autotune.runs") == 1
    assert meters.get("autotune.fits", 0) >= 3


@pytest.mark.slow
def test_offline_cli_contract(tmp_path):
    out = tmp_path / "t.json"
    proc = subprocess.run(
        [sys.executable, "-m", "mpi4jax_tpu.autotune",
         "--budget-s", "5", "--save", str(out), "--json"],
        capture_output=True, text=True, timeout=600,
    )
    assert proc.returncode in (0, 1), proc.stderr  # partial is legal
    payload = json.loads(proc.stdout)
    validate_tuning_dict(payload)
    assert out.exists()
    assert "tuned@" in proc.stderr
