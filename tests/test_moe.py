"""Expert-parallel MoE layer (parallel/moe.py): the traced half.

Needs a real ``mpi4jax_tpu`` import (jax>=0.6) and the 8-device mesh:

- the 8-device dryrun pin: the distributed layer against the pure
  single-device ``reference_moe`` fold;
- overlap == synchronous bit-identity (the async combine split is pure
  routing) and gradient parity through the differentiable layer;
- MPX137 positive/negative through ``mpx.analyze`` AND the ambient
  ``MPI4JAX_TPU_ANALYZE=error`` path;
- the rank-divergent capacity shape flagged MPX120 by the cross-rank
  pass (the examples/broken/ fixture's in-suite twin).

The pure gate/capacity math half lives in tests/test_moe_pure.py.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax import lax

import mpi4jax_tpu as mpx
from mpi4jax_tpu.parallel import moe
from helpers import world

TOKENS = 16
D = 8
D_FF = 12
SEED = 3


@pytest.fixture(autouse=True)
def _clean_env(monkeypatch):
    for flag in ("MPI4JAX_TPU_TOPOLOGY", "MPI4JAX_TPU_COLLECTIVE_ALGO",
                 "MPI4JAX_TPU_ALLTOALL_CROSSOVER_BYTES",
                 "MPI4JAX_TPU_MOE_CAPACITY_CHUNKS",
                 "MPI4JAX_TPU_OVERLAP_CHUNKS"):
        monkeypatch.delenv(flag, raising=False)
    yield
    mpx.set_analyze_mode(None)
    mpx.clear_caches()


def _inputs(size):
    rng = np.random.default_rng(SEED)
    x = rng.standard_normal((size, TOKENS, D)).astype(np.float32)
    params = [moe.init_moe_params(D, D_FF, size, rank=r, seed=SEED)
              for r in range(size)]
    w_gate = jnp.asarray(np.stack([p.w_gate for p in params]))
    w_in = jnp.asarray(np.stack([p.w_in for p in params]))
    w_out = jnp.asarray(np.stack([p.w_out for p in params]))
    return jnp.asarray(x), w_gate, w_in, w_out


def _fwd(comm, chunks):
    @mpx.spmd(comm=comm)
    def prog(xv, wg, wi, wo):
        y, _ = moe.moe_layer(xv, moe.MoEParams(wg, wi, wo), comm=comm,
                             chunks=chunks)
        return mpx.varying(y)

    return prog


def test_moe_layer_pinned_against_single_device_reference():
    comm, size = world()
    x, wg, wi, wo = _inputs(size)
    got = np.asarray(_fwd(comm, 1)(x, wg, wi, wo))
    want = moe.reference_moe(np.asarray(x), D_FF, size, seed=SEED)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("chunks", [2, 3])
def test_overlapped_combine_bit_identical_to_sync(chunks):
    comm, size = world()
    x, wg, wi, wo = _inputs(size)
    sync = np.asarray(_fwd(comm, 1)(x, wg, wi, wo))
    ovl = np.asarray(_fwd(comm, chunks)(x, wg, wi, wo))
    np.testing.assert_array_equal(sync, ovl)


def test_moe_capacity_chunks_env_default(monkeypatch):
    comm, size = world()
    x, wg, wi, wo = _inputs(size)
    sync = np.asarray(_fwd(comm, 1)(x, wg, wi, wo))
    monkeypatch.setenv("MPI4JAX_TPU_MOE_CAPACITY_CHUNKS", "2")
    got = np.asarray(_fwd(comm, None)(x, wg, wi, wo))
    np.testing.assert_array_equal(sync, got)


def test_gradients_match_between_sync_and_overlap():
    comm, size = world()
    x, wg, wi, wo = _inputs(size)

    def grads(chunks):
        @mpx.spmd(comm=comm)
        def prog(xv, wg_, wi_, wo_):
            def loss(wi__):
                y, _ = moe.moe_layer(
                    xv, moe.MoEParams(wg_, wi__, wo_), comm=comm,
                    chunks=chunks)
                return jnp.sum(y * y)

            return mpx.varying(jax.grad(loss)(wi_))

        return np.asarray(prog(x, wg, wi, wo))

    np.testing.assert_allclose(grads(1), grads(2), rtol=1e-5, atol=1e-6)


def test_moe_layer_under_faked_two_host_topology(monkeypatch):
    comm, size = world()
    if size % 2:
        pytest.skip("needs an even mesh for the 2-host fake")
    monkeypatch.setenv("MPI4JAX_TPU_TOPOLOGY", f"2x{size // 2}")
    monkeypatch.setenv("MPI4JAX_TPU_ALLTOALL_CROSSOVER_BYTES", "1")
    x, wg, wi, wo = _inputs(size)
    got = np.asarray(_fwd(comm, 2)(x, wg, wi, wo))  # hier + overlap
    want = moe.reference_moe(np.asarray(x), D_FF, size, seed=SEED)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


# ---------------------------------------------------------------------------
# MPX137 — traced positive/negative through analyze and env=error
# ---------------------------------------------------------------------------


def _a2a(x):
    res, _ = mpx.alltoall(x)
    return res


def test_mpx137_traced_positive_and_negative(monkeypatch):
    comm, size = world()
    if size % 2:
        pytest.skip("needs an even mesh for the 2-host fake")
    monkeypatch.setenv("MPI4JAX_TPU_TOPOLOGY", f"2x{size // 2}")
    monkeypatch.setenv("MPI4JAX_TPU_ALLTOALL_CROSSOVER_BYTES", "1024")
    x = jnp.ones((size, size, 256), jnp.float32)  # 8 KiB: above
    # positive: a forced flat algorithm keeps the single-level exchange
    monkeypatch.setenv("MPI4JAX_TPU_COLLECTIVE_ALGO", "butterfly")
    report = mpx.analyze(_a2a, x, comm=comm)
    found = [f for f in report.findings if f.code == "MPX137"]
    assert len(found) == 1
    assert found[0].severity == "advisory"
    assert "DCN message count" in found[0].message
    # negative: auto picks the hierarchy — nothing to advise
    monkeypatch.setenv("MPI4JAX_TPU_COLLECTIVE_ALGO", "auto")
    report = mpx.analyze(_a2a, x, comm=comm)
    assert not [f for f in report.findings if f.code == "MPX137"]
    # negative: below the crossover the flat exchange is the right call
    monkeypatch.setenv("MPI4JAX_TPU_COLLECTIVE_ALGO", "butterfly")
    report = mpx.analyze(_a2a, jnp.ones((size, size, 2), jnp.float32),
                         comm=comm)
    assert not [f for f in report.findings if f.code == "MPX137"]


def test_mpx137_fires_through_env_error_mode(monkeypatch):
    comm, size = world()
    if size % 2:
        pytest.skip("needs an even mesh for the 2-host fake")
    monkeypatch.setenv("MPI4JAX_TPU_TOPOLOGY", f"2x{size // 2}")
    monkeypatch.setenv("MPI4JAX_TPU_ALLTOALL_CROSSOVER_BYTES", "1024")
    monkeypatch.setenv("MPI4JAX_TPU_COLLECTIVE_ALGO", "butterfly")
    x = jnp.ones((size, size, 256), jnp.float32)
    mpx.set_analyze_mode("error")
    try:
        with pytest.raises(mpx.AnalysisError) as exc:
            mpx.run(_a2a, x, comm=comm)
        assert any(f.code == "MPX137" for f in exc.value.findings)
    finally:
        mpx.set_analyze_mode(None)
        mpx.clear_caches()


# ---------------------------------------------------------------------------
# MPX120 — the rank-divergent capacity shape (the broken fixture's twin)
# ---------------------------------------------------------------------------


def test_rank_divergent_capacity_flags_mpx120():
    comm, size = world()
    if size < 2:
        pytest.skip("needs >= 2 ranks to diverge")
    cap = 4

    def combine(buckets):
        r = comm.Get_rank()

        def even_path(b):
            lo, _ = mpx.alltoall(b[:, :cap // 2], comm=comm)
            hi, _ = mpx.alltoall(b[:, cap // 2:], comm=comm)
            return jnp.concatenate([lo, hi], axis=1)

        def odd_path(b):
            out, _ = mpx.alltoall(b, comm=comm)
            return out

        combined = lax.cond(r % 2 == 0, even_path, odd_path, buckets)
        load, _ = mpx.allreduce(jnp.sum(combined), op=mpx.SUM, comm=comm)
        return combined, load

    x = jnp.stack([jnp.full((size, cap, 3), float(r))
                   for r in range(size)])
    report = mpx.analyze(combine, x, comm=comm, ranks="all")
    codes = {f.code for f in report.findings}
    assert "MPX120" in codes, sorted(codes)
