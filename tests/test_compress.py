"""Wire compression: the traced integration half (docs/compression.md).

The byte math, config resolution, schema grammar, and checker matrix
run under any JAX in tests/test_compress_pure.py via the isolated
loader; here the codec layer runs for real on the 8-device CPU mesh
under a faked multi-host topology:

- per-codec parity for the hierarchical reduction family and alltoall
  (off is bit-identical to flat; bf16/fp8 land within the documented
  tolerances — compression is opt-in and NOT bit-exact);
- the zero-cost contract: with the knob off (or on a single-host comm
  where no DCN leg exists) the lowered HLO is byte-identical, and
  explicit ``off`` replays the unset program from cache (the token
  pin);
- toggle-retrace: flipping the knob misses the program caches exactly
  once per mode;
- error-feedback: ``ef_allreduce`` degenerates to the plain allreduce
  with the layer off, enforces tree compatibility, and under fp8 the
  telescoping invariant holds (the sum of quantized updates tracks the
  sum of true gradients minus the final residual);
- EF residuals across elastic reconfigs: bit-identical through
  ``ShardStore`` commit/restore, re-sharded through the committed
  ``last_rank_map`` on shrink AND grow, and a cold joiner's row is
  zeroed, never stale;
- telemetry's logical-vs-wire DCN split on a live compressed program;
- MPX138 positive/negative through ``mpx.analyze`` and the ambient
  ``MPI4JAX_TPU_ANALYZE=error`` mode.
"""

import numpy as np
import pytest

mpx = pytest.importorskip("mpi4jax_tpu",
                          exc_type=(ImportError, RuntimeError))

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from helpers import per_rank, world  # noqa: E402


@pytest.fixture(autouse=True)
def _clean_compress_env(monkeypatch):
    for flag in ("MPI4JAX_TPU_COMPRESS",
                 "MPI4JAX_TPU_COMPRESS_ERROR_BUDGET",
                 "MPI4JAX_TPU_TOPOLOGY",
                 "MPI4JAX_TPU_COLLECTIVE_ALGO",
                 "MPI4JAX_TPU_DCN_CROSSOVER_BYTES",
                 "MPI4JAX_TPU_RING_CROSSOVER_BYTES",
                 "MPI4JAX_TPU_ALLTOALL_CROSSOVER_BYTES"):
        monkeypatch.delenv(flag, raising=False)
    mpx.clear_caches()
    yield
    mpx.clear_caches()


def _two_hosts(monkeypatch):
    _, size = world()
    monkeypatch.setenv("MPI4JAX_TPU_TOPOLOGY", f"2x{size // 2}")
    return 2, size // 2


def _forced_hier(monkeypatch):
    monkeypatch.setenv("MPI4JAX_TPU_COLLECTIVE_ALGO", "hier")


def _rand_global(size, nelem, seed=0, scale=1.0):
    rng = np.random.default_rng(seed)
    return rng.standard_normal((size, nelem)).astype(np.float32) * scale


# ---------------------------------------------------------------------------
# per-codec parity on the hierarchical lowerings
# ---------------------------------------------------------------------------


# documented parity envelopes (docs/compression.md): bf16 keeps ~8
# mantissa bits; fp8's per-chunk scale bounds the step at maxabs/8
_TOL = {"bf16": 5e-3, "fp8": 5e-2}


def test_hier_allreduce_off_is_bit_identical_to_flat(monkeypatch):
    _, size = world()
    vals = _rand_global(size, 512)
    x = jnp.asarray(vals)
    monkeypatch.setenv("MPI4JAX_TPU_COLLECTIVE_ALGO", "butterfly")
    flat, _ = mpx.allreduce(x, op=mpx.SUM)
    _two_hosts(monkeypatch)
    _forced_hier(monkeypatch)
    monkeypatch.setenv("MPI4JAX_TPU_COMPRESS", "off")
    hier, _ = mpx.allreduce(x, op=mpx.SUM)
    np.testing.assert_array_equal(np.asarray(flat), np.asarray(hier))


@pytest.mark.parametrize("mode", ["bf16", "fp8"])
def test_hier_allreduce_parity_per_codec(mode, monkeypatch):
    _, size = world()
    _two_hosts(monkeypatch)
    _forced_hier(monkeypatch)
    vals = _rand_global(size, 512)
    want = np.add.reduce(vals.astype(np.float64))
    monkeypatch.setenv("MPI4JAX_TPU_COMPRESS", mode)
    got, _ = mpx.allreduce(jnp.asarray(vals), op=mpx.SUM)
    got = np.asarray(got)
    assert got.shape == vals.shape
    scale = np.maximum(np.abs(want), 1.0)
    rel = np.max(np.abs(got[0] - want) / scale)
    assert rel <= _TOL[mode], (mode, rel)
    # every rank sees the same reduced values
    np.testing.assert_array_equal(got, np.broadcast_to(got[0], got.shape))


@pytest.mark.parametrize("mode", ["bf16", "fp8"])
def test_hier_reduce_scatter_parity_per_codec(mode, monkeypatch):
    _, size = world()
    _two_hosts(monkeypatch)
    _forced_hier(monkeypatch)
    vals = _rand_global(size, size * 64, seed=1)
    want = np.add.reduce(vals.astype(np.float64)).reshape(size, 64)
    monkeypatch.setenv("MPI4JAX_TPU_COMPRESS", mode)
    got, _ = mpx.reduce_scatter(jnp.asarray(vals), op=mpx.SUM)
    got = np.asarray(got)
    scale = np.maximum(np.abs(want), 1.0)
    rel = np.max(np.abs(got - want) / scale)
    assert rel <= _TOL[mode], (mode, rel)


def test_hier_alltoall_parity_bf16(monkeypatch):
    _, size = world()
    _two_hosts(monkeypatch)
    _forced_hier(monkeypatch)
    monkeypatch.setenv("MPI4JAX_TPU_ALLTOALL_CROSSOVER_BYTES", "1")
    vals = _rand_global(size, size * 32, seed=2)
    want = (vals.reshape(size, size, 32)
            .transpose(1, 0, 2).reshape(size, size * 32))
    monkeypatch.setenv("MPI4JAX_TPU_COMPRESS", "bf16")
    got, _ = mpx.alltoall(jnp.asarray(vals))
    got = np.asarray(got)
    # a pure cast-through: elementwise bf16 rounding, no accumulation
    assert np.max(np.abs(got - want)) <= 2.0 ** -8 * np.max(
        np.abs(want)) + 1e-6


def test_fp8_degrades_to_bf16_for_non_sum(monkeypatch):
    # fp8's per-chunk scales only commute with SUM; a MAX reduction
    # under fp8 ships the bf16 wire instead (pure pin:
    # _hierarchy.selected_codec) — so parity lands in the bf16 envelope
    _, size = world()
    _two_hosts(monkeypatch)
    _forced_hier(monkeypatch)
    vals = _rand_global(size, 512, seed=3)
    want = np.maximum.reduce(vals.astype(np.float64))
    monkeypatch.setenv("MPI4JAX_TPU_COMPRESS", "fp8")
    got, _ = mpx.allreduce(jnp.asarray(vals), op=mpx.MAX)
    rel = np.max(np.abs(np.asarray(got)[0] - want)
                 / np.maximum(np.abs(want), 1.0))
    assert rel <= _TOL["bf16"], rel


# ---------------------------------------------------------------------------
# the zero-cost contract: HLO byte-identity + cache-token pin
# ---------------------------------------------------------------------------


def _lowered_sum(x):
    @mpx.spmd
    def f(xl):
        res, _ = mpx.allreduce(xl, op=mpx.SUM)
        return res

    return jax.jit(f).lower(x).as_text()


def test_hlo_byte_identical_with_knob_off(monkeypatch):
    _, size = world()
    _two_hosts(monkeypatch)
    _forced_hier(monkeypatch)
    x = jnp.ones((size, 1024), jnp.float32)
    base = _lowered_sum(x)
    # explicit off IS the default: byte-identical program
    monkeypatch.setenv("MPI4JAX_TPU_COMPRESS", "off")
    assert _lowered_sum(x) == base
    # a live codec rewrites the DCN leg: the program must differ
    monkeypatch.setenv("MPI4JAX_TPU_COMPRESS", "bf16")
    assert _lowered_sum(x) != base


def test_hlo_unchanged_by_codec_without_a_dcn_leg(monkeypatch):
    # single-host comm: no DCN leg exists, so even a live codec changes
    # nothing about the lowered program — compression is a property of
    # the inter-host phase, not of the collective
    _, size = world()
    x = jnp.ones((size, 1024), jnp.float32)
    base = _lowered_sum(x)
    monkeypatch.setenv("MPI4JAX_TPU_COMPRESS", "bf16")
    assert _lowered_sum(x) == base


def test_compress_toggle_retraces_eager_program(monkeypatch):
    _, size = world()
    _two_hosts(monkeypatch)
    _forced_hier(monkeypatch)
    mpx.clear_caches()
    x = per_rank(lambda r: np.full((64,), float(r)))
    mpx.allreduce(x, op=mpx.SUM)
    monkeypatch.setenv("MPI4JAX_TPU_COMPRESS", "bf16")
    mpx.allreduce(x, op=mpx.SUM)           # new codec: must retrace
    monkeypatch.setenv("MPI4JAX_TPU_COMPRESS", "fp8")
    mpx.allreduce(x, op=mpx.SUM)           # and again per codec
    monkeypatch.setenv("MPI4JAX_TPU_COMPRESS", "off")
    mpx.allreduce(x, op=mpx.SUM)           # off == unset: the FIRST program
    s = mpx.cache_stats()
    assert s["misses"] == 3 and s["hits"] == 1


# ---------------------------------------------------------------------------
# error feedback
# ---------------------------------------------------------------------------


def test_ef_allreduce_off_is_plain_and_residual_stays_zero():
    _, size = world()
    grads = {"w": per_rank(lambda r: np.full((16,), float(r + 1))),
             "b": per_rank(lambda r: np.full((4,), -float(r)))}
    residual = mpx.compress.ef_zeros_like(grads)
    red, new_res, token = mpx.compress.ef_allreduce(
        grads, residual, op=mpx.SUM)
    want_w = sum(range(1, size + 1))
    np.testing.assert_array_equal(
        np.asarray(red["w"]),
        np.full((size, 16), float(want_w), np.float32))
    for leaf in (new_res["w"], new_res["b"]):
        assert float(np.max(np.abs(np.asarray(leaf)))) == 0.0
    assert token is not None


def test_ef_allreduce_rejects_mismatched_trees():
    grads = {"w": per_rank(lambda r: np.zeros((4,)))}
    bad = {"w": per_rank(lambda r: np.zeros((4,))),
           "extra": per_rank(lambda r: np.zeros((4,)))}
    with pytest.raises(ValueError):
        mpx.compress.ef_allreduce(grads, bad, op=mpx.SUM)


def test_ef_telescoping_under_fp8(monkeypatch):
    """The EF guarantee: after T steps, the sum of what was actually
    applied (the quantized, reduced updates) equals the sum of the true
    reduced gradients minus what the final residual still carries."""
    _, size = world()
    monkeypatch.setenv("MPI4JAX_TPU_COMPRESS", "fp8")
    rng = np.random.default_rng(7)
    residual = mpx.compress.ef_zeros_like(
        {"w": per_rank(lambda r: np.zeros((512,)))})
    applied = np.zeros((512,), np.float64)
    true_sum = np.zeros((512,), np.float64)
    for _ in range(5):
        vals = rng.standard_normal((size, 512)).astype(np.float32)
        grads = {"w": jnp.asarray(vals)}
        red, residual, _ = mpx.compress.ef_allreduce(
            grads, residual, op=mpx.SUM)
        applied += np.asarray(red["w"])[0].astype(np.float64)
        true_sum += np.add.reduce(vals.astype(np.float64))
    res_sum = np.add.reduce(
        np.asarray(residual["w"]).astype(np.float64))
    np.testing.assert_allclose(applied + res_sum, true_sum,
                               rtol=0, atol=1e-2)
    # and the residual is genuinely nonzero — fp8 quantized something
    assert float(np.max(np.abs(res_sum))) > 0.0


# ---------------------------------------------------------------------------
# EF residuals across elastic reconfigs (docs/compression.md,
# docs/resilience.md)
# ---------------------------------------------------------------------------


def _elastic_fixture():
    from mpi4jax_tpu.resilience import elastic as el

    el._reset_epoch_for_tests()
    el.take_pending_failure()
    mpx.set_default_mesh(None)
    mpx.clear_caches()
    return el


def _world_store():
    mesh = mpx.make_world_mesh()
    comm = mpx.Comm(mesh.axis_names[0], mesh=mesh)
    return mpx.ShardStore(comm)


@pytest.mark.faults
def test_ef_residual_commit_restore_bit_identity():
    el = _elastic_fixture()
    try:
        store = _world_store()
        _, size = world()
        res = {"w": per_rank(lambda r: np.full((8,), r / 7.0))}
        state = {"params": per_rank(lambda r: np.ones((4,))),
                 "ef_residual": res}
        store.commit(3, state)
        assert store.last_rank_map is None  # no reconfig yet
        step, restored = store.restore()
        assert step == 3
        np.testing.assert_array_equal(np.asarray(res["w"]),
                                      np.asarray(restored["ef_residual"]["w"]))
    finally:
        el._reset_epoch_for_tests()
        mpx.set_default_mesh(None)
        mpx.clear_caches()


@pytest.mark.faults
def test_ef_residual_reshards_through_shrink_rank_map():
    el = _elastic_fixture()
    try:
        store = _world_store()
        _, size = world()
        res = {"w": per_rank(lambda r: np.full((8,), float(r)))}
        store.commit(5, {"ef_residual": res})
        el.advance_epoch()
        rank_map = store.apply_shrink({3})
        assert store.last_rank_map == rank_map
        assert 3 not in rank_map
        new_k = size - 1
        moved = mpx.compress.ef_reshard(res, store.last_rank_map, new_k)
        got = np.asarray(moved["w"])
        assert got.shape == (new_k, 8)
        # each surviving rank carries ITS old row — rank 3's is gone
        keep = [r for r in range(size) if r != 3]
        np.testing.assert_array_equal(got, np.asarray(res["w"])[keep])
    finally:
        el._reset_epoch_for_tests()
        mpx.set_default_mesh(None)
        mpx.clear_caches()


@pytest.mark.faults
def test_ef_residual_zeroed_for_cold_joiner_on_grow():
    el = _elastic_fixture()
    try:
        store = _world_store()
        _, size = world()
        store.commit(2, {"x": per_rank(lambda r: np.ones((2,)))})
        el.advance_epoch()
        store.apply_shrink({size - 1})
        # re-commit at the shrunken world: k = size-1 rows
        small = {"w": jnp.stack(
            [jnp.full((8,), float(r)) for r in range(size - 1)])}
        store.commit(6, {"ef_residual": small})
        el.advance_epoch(world=size, cause="join")
        store.apply_grow(1)
        rmap = store.last_rank_map
        # grow stamps identity over the committed world: survivors keep
        # their rows, the joiner maps to nothing
        assert rmap == {r: r for r in range(size - 1)}
        grown = mpx.compress.ef_reshard(small, rmap, size)
        got = np.asarray(grown["w"])
        assert got.shape == (size, 8)
        np.testing.assert_array_equal(got[:-1], np.asarray(small["w"]))
        # the cold joiner starts from ZERO error — never a stale row
        np.testing.assert_array_equal(got[-1], np.zeros(8, np.float32))
    finally:
        el._reset_epoch_for_tests()
        mpx.set_default_mesh(None)
        mpx.clear_caches()


# ---------------------------------------------------------------------------
# telemetry: the logical-vs-wire DCN split on a live program
# ---------------------------------------------------------------------------


def test_telemetry_wire_split_on_compressed_program(monkeypatch):
    from mpi4jax_tpu.ops._hierarchy import hier_link_bytes

    _, size = world()
    h, r = _two_hosts(monkeypatch)
    _forced_hier(monkeypatch)
    nelem = 256
    nbytes = nelem * 4
    x = jnp.ones((size, nelem), jnp.float32)

    def run():
        mpx.telemetry.reset()

        @mpx.spmd
        def f(xl):
            res, _ = mpx.allreduce(xl, op=mpx.SUM)
            return res

        f(x)
        (row,) = [row for row in mpx.telemetry.snapshot()["ops"].values()
                  if row["algo"] == "hier"]
        return row

    mpx.set_telemetry_mode("counters")
    try:
        intra, inter = hier_link_bytes("allreduce", nbytes, h, r)
        monkeypatch.setenv("MPI4JAX_TPU_COMPRESS", "bf16")
        row = run()
        assert row["inter_bytes"] == inter          # logical: unchanged
        assert row["wire_inter_bytes"] == mpx.compress.wire_bytes(
            inter, "bf16")                          # wire: halved
        assert row["intra_bytes"] == intra          # ICI stays exact
        monkeypatch.setenv("MPI4JAX_TPU_COMPRESS", "off")
        row = run()
        assert row["wire_inter_bytes"] == row["inter_bytes"] == inter
    finally:
        mpx.set_telemetry_mode(None)
        mpx.telemetry.reset()


# ---------------------------------------------------------------------------
# MPX138 — traced positive/negative through analyze and env=error
# ---------------------------------------------------------------------------


def _sum(x):
    res, _ = mpx.allreduce(x, op=mpx.SUM)
    return res


def _mpx138_env(monkeypatch, size):
    monkeypatch.setenv("MPI4JAX_TPU_TOPOLOGY", f"2x{size // 2}")
    monkeypatch.setenv("MPI4JAX_TPU_COLLECTIVE_ALGO", "hier")
    monkeypatch.setenv("MPI4JAX_TPU_DCN_CROSSOVER_BYTES", "1024")


def test_mpx138_traced_positive_and_negative(monkeypatch):
    comm, size = world()
    _mpx138_env(monkeypatch, size)
    x = jnp.ones((size, 4096), jnp.float32)  # 16 KiB: leg 4 KiB > 1 KiB
    # positive: hier above the crossover with the codec layer off
    report = mpx.analyze(_sum, x, comm=comm)
    found = [f for f in report.findings if f.code == "MPX138"]
    assert len(found) == 1
    assert found[0].severity == "advisory"
    assert "MPI4JAX_TPU_COMPRESS=bf16" in found[0].message
    # negative: the layer is on — the user already made the trade
    monkeypatch.setenv("MPI4JAX_TPU_COMPRESS", "bf16")
    report = mpx.analyze(_sum, x, comm=comm)
    assert not [f for f in report.findings if f.code == "MPX138"]
    monkeypatch.delenv("MPI4JAX_TPU_COMPRESS")
    # negative: below the crossover compression cannot pay
    report = mpx.analyze(_sum, jnp.ones((size, 64), jnp.float32),
                         comm=comm)
    assert not [f for f in report.findings if f.code == "MPX138"]
    # negative: non-float32 payloads ship exact in every mode
    report = mpx.analyze(
        lambda v: mpx.allreduce(v, op=mpx.SUM)[0],
        jnp.ones((size, 4096), jnp.int32), comm=comm)
    assert not [f for f in report.findings if f.code == "MPX138"]


def test_mpx138_fires_through_env_error_mode(monkeypatch):
    comm, size = world()
    _mpx138_env(monkeypatch, size)
    x = jnp.ones((size, 4096), jnp.float32)
    mpx.set_analyze_mode("error")
    try:
        with pytest.raises(mpx.AnalysisError) as exc:
            mpx.run(_sum, x, comm=comm)
        assert any(f.code == "MPX138" for f in exc.value.findings)
    finally:
        mpx.set_analyze_mode(None)
        mpx.clear_caches()
