"""Test configuration: 8 virtual CPU devices.

The reference suite runs under ``pytest`` and ``mpirun -np N pytest``
(ref docs/developers.rst:15-27) — real MPI, no fakes.  The TPU-native analog
runs the real collective lowerings on a virtual multi-device CPU mesh
(``--xla_force_host_platform_device_count``), exercising the identical XLA
collective code paths that run on ICI, without TPU hardware
(SURVEY.md §4 "Implication for the TPU build").
"""

import os

# MPI4JAX_TPU_TEST_PLATFORM=ambient keeps the process's own backend (e.g.
# a real TPU) instead of forcing the virtual CPU mesh — the opt-in lane
# for tests/test_tpu_compiled.py, which exercises the Mosaic-COMPILED
# Pallas kernels that interpret mode cannot (docs/developers.md).  Run it
# against that file only: the rest of the suite assumes 8 devices.
_AMBIENT = os.environ.get("MPI4JAX_TPU_TEST_PLATFORM") == "ambient"

if not _AMBIENT:
    # Must be set before jax initializes. JAX_PLATFORMS=cpu also overrides
    # the axon TPU plugin, whose sitecustomize would otherwise claim the
    # backend.
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8"
    )
    os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

if not _AMBIENT:
    jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


def pytest_collection_modifyitems(config, items):
    # Under the ambient lane the suite-wide 8-device CPU-mesh assumption
    # does not hold; everything except the chip lane would fail confusingly.
    # Force-skip those files loudly rather than run them on the wrong mesh.
    if not _AMBIENT:
        return
    skip = pytest.mark.skip(
        reason="MPI4JAX_TPU_TEST_PLATFORM=ambient runs only "
        "tests/test_tpu_compiled.py; the rest of the suite needs the "
        "forced 8-device CPU mesh"
    )
    for item in items:
        if item.fspath.basename != "test_tpu_compiled.py":
            item.add_marker(skip)


def pytest_report_header(config):
    # Analog of ref tests/conftest.py:1-9 (reports MPI vendor/rank/size).
    return (
        f"mpi4jax_tpu: backend={jax.default_backend()} "
        f"n_devices={jax.device_count()}"
    )


@pytest.fixture
def mesh8():
    import mpi4jax_tpu as mpx

    mesh = mpx.make_world_mesh()
    yield mesh
