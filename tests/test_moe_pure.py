"""Expert-parallel MoE helper (parallel/moe.py): the pure half.

The gate/capacity/dispatch math is numpy-polymorphic and seeded, so
this file drives the SAME functions the traced layer uses through plain
numpy under any installed JAX (isolated loader, mirroring
tests/test_algos.py) — including an independent per-token loop oracle
that re-derives the whole layer without a single einsum, so the one-hot
bucketing can never be wrong in a way its own machinery hides.  The
traced half (8-device pins against ``reference_moe``, overlap == sync
bit-identity, the broken-capacity MPX120 fixture) lives in
tests/test_moe.py.
"""

import importlib
import os
import pathlib
import sys
import types

import numpy as np
import pytest

REPO = pathlib.Path(__file__).resolve().parent.parent
PKG = REPO / "mpi4jax_tpu"

_ISO_NAME = "_mpx_moe_iso"


def _load_isolated():
    if _ISO_NAME in sys.modules:
        return sys.modules[_ISO_NAME]
    root = types.ModuleType(_ISO_NAME)
    root.__path__ = [str(PKG)]
    sys.modules[_ISO_NAME] = root
    for sub in ("utils", "parallel"):
        m = types.ModuleType(f"{_ISO_NAME}.{sub}")
        m.__path__ = [str(PKG / sub)]
        sys.modules[f"{_ISO_NAME}.{sub}"] = m
        setattr(root, sub, m)
    for mod in ("utils.config", "parallel.moe"):
        importlib.import_module(f"{_ISO_NAME}.{mod}")
    return root


ISO = _load_isolated()
moe = sys.modules[f"{_ISO_NAME}.parallel.moe"]
config = sys.modules[f"{_ISO_NAME}.utils.config"]


@pytest.fixture(autouse=True)
def _clean_env():
    saved = os.environ.pop("MPI4JAX_TPU_MOE_CAPACITY_CHUNKS", None)
    yield
    if saved is None:
        os.environ.pop("MPI4JAX_TPU_MOE_CAPACITY_CHUNKS", None)
    else:
        os.environ["MPI4JAX_TPU_MOE_CAPACITY_CHUNKS"] = saved


# ---------------------------------------------------------------------------
# capacity + flags
# ---------------------------------------------------------------------------


def test_capacity_for_values():
    assert moe.capacity_for(32, 8, 1.25) == 5
    assert moe.capacity_for(32, 8, 1.0) == 4
    assert moe.capacity_for(7, 8, 1.0) == 1   # floor of 1
    assert moe.capacity_for(1, 1, 1.0) == 1
    assert moe.capacity_for(100, 4, 2.0) == 50


def test_capacity_for_rejects_bad_inputs():
    with pytest.raises(ValueError, match="tokens >= 1"):
        moe.capacity_for(0, 8)
    with pytest.raises(ValueError, match="tokens >= 1"):
        moe.capacity_for(8, 0)
    with pytest.raises(ValueError, match="factor"):
        moe.capacity_for(8, 2, 0.0)


def test_moe_capacity_chunks_flag():
    assert config.moe_capacity_chunks() == \
        config.DEFAULT_MOE_CAPACITY_CHUNKS
    os.environ["MPI4JAX_TPU_MOE_CAPACITY_CHUNKS"] = "4"
    assert config.moe_capacity_chunks() == 4
    os.environ["MPI4JAX_TPU_MOE_CAPACITY_CHUNKS"] = "0"
    with pytest.raises(ValueError, match="must be >= 1"):
        config.moe_capacity_chunks()


# ---------------------------------------------------------------------------
# seeded params + gating
# ---------------------------------------------------------------------------


def test_init_params_seeded_and_expert_distinct():
    a = moe.init_moe_params(8, 16, 4, rank=0, seed=3)
    b = moe.init_moe_params(8, 16, 4, rank=0, seed=3)
    c = moe.init_moe_params(8, 16, 4, rank=1, seed=3)
    # same seed: identical router AND expert weights (bit-for-bit)
    assert np.array_equal(a.w_gate, b.w_gate)
    assert np.array_equal(a.w_in, b.w_in)
    # another rank: SAME router (replicated), different expert
    assert np.array_equal(a.w_gate, c.w_gate)
    assert not np.array_equal(a.w_in, c.w_in)
    assert a.w_gate.dtype == np.float32 and a.w_in.dtype == np.float32


def test_gate_tokens_routing_and_probs():
    # crafted logits: token t routes to expert t % 3 with certainty
    w_gate = np.eye(3, dtype=np.float32) * 10.0
    x = np.eye(3, dtype=np.float32)[[0, 1, 2, 0]]
    a, gate = moe.gate_tokens(np, x, w_gate)
    assert list(a) == [0, 1, 2, 0]
    assert np.all(gate > 0.99)
    # probabilities: softmax rows sum to one by construction
    logits = x @ w_gate
    z = np.exp(logits - logits.max(axis=-1, keepdims=True))
    np.testing.assert_allclose(
        gate, (z / z.sum(axis=-1, keepdims=True)).max(axis=-1), rtol=1e-6)


# ---------------------------------------------------------------------------
# the dispatch tensor: capacity discipline
# ---------------------------------------------------------------------------


def test_dispatch_tensor_positions_and_drops():
    # 5 tokens, 2 experts, capacity 2: expert 0 gets tokens 0,1,4 —
    # token 4 is the third arrival and must be DROPPED
    assignment = np.array([0, 0, 1, 1, 0])
    D = moe.dispatch_tensor(np, assignment, experts=2, capacity=2)
    assert D.shape == (5, 2, 2)
    assert D[0, 0, 0] == 1 and D[1, 0, 1] == 1      # in-order slots
    assert D[2, 1, 0] == 1 and D[3, 1, 1] == 1
    assert D[4].sum() == 0                          # dropped
    # each slot holds at most one token; each kept token one slot
    assert np.all(D.sum(axis=0) <= 1)
    assert np.all(D.sum(axis=(1, 2)) <= 1)


def test_dispatch_roundtrip_identity():
    # bucket then un-bucket: every kept token comes back exactly once
    rng = np.random.default_rng(0)
    x = rng.standard_normal((6, 4)).astype(np.float32)
    assignment = np.array([0, 1, 0, 1, 0, 1])
    D = moe.dispatch_tensor(np, assignment, experts=2, capacity=3)
    buckets = np.einsum("tec,td->ecd", D, x)
    back = np.einsum("tec,ecd->td", D, buckets)
    np.testing.assert_array_equal(back, x)


# ---------------------------------------------------------------------------
# the reference layer vs an independent per-token oracle
# ---------------------------------------------------------------------------


def _oracle_moe(x_global, d_ff, experts, seed, capacity_factor):
    """Naive per-token re-derivation: route each token, walk the
    buckets in arrival order, drop beyond capacity, apply the owning
    expert's MLP, weigh by the gate — no einsum, no one-hot."""
    k, tokens, d = x_global.shape
    cap = moe.capacity_for(tokens, experts, capacity_factor)
    params = [moe.init_moe_params(d, d_ff, experts, rank=r, seed=seed)
              for r in range(k)]
    out = np.zeros_like(x_global)
    for r in range(k):
        a, gate = moe.gate_tokens(np, x_global[r], params[r].w_gate)
        counts = {}
        for t in range(tokens):
            e = int(a[t])
            c = counts.get(e, 0)
            counts[e] = c + 1
            if c >= cap:
                continue  # dropped: zero output row
            y = moe.expert_mlp(np, x_global[r][t][None, :],
                               params[e].w_in, params[e].w_out)[0]
            out[r][t] = gate[t] * y
    return out


def test_reference_moe_matches_oracle():
    rng = np.random.default_rng(11)
    k, tokens, d, d_ff = 4, 8, 6, 12
    x = rng.standard_normal((k, tokens, d)).astype(np.float32)
    ref = moe.reference_moe(x, d_ff, k, seed=5, capacity_factor=1.0)
    oracle = _oracle_moe(x, d_ff, k, seed=5, capacity_factor=1.0)
    np.testing.assert_allclose(ref, oracle, rtol=1e-5, atol=1e-6)
    # determinism: same inputs, same bits
    ref2 = moe.reference_moe(x, d_ff, k, seed=5, capacity_factor=1.0)
    np.testing.assert_array_equal(ref, ref2)


def test_reference_moe_drops_beyond_capacity():
    # route EVERY token to expert 0 (w_gate column 0 dominant): with
    # capacity 1, exactly one token per rank survives
    k, tokens, d, d_ff = 2, 4, 3, 5
    x = np.abs(np.random.default_rng(2).standard_normal(
        (k, tokens, d))).astype(np.float32)
    # seed chosen arbitrarily; force routing via a huge first gate col
    params = moe.init_moe_params(d, d_ff, k, rank=0, seed=9)
    w_gate = params.w_gate.copy()
    w_gate[:, 0] = 50.0

    a, _ = moe.gate_tokens(np, x[0], w_gate)
    assert set(a) == {0}
    D = moe.dispatch_tensor(np, a, experts=k,
                            capacity=moe.capacity_for(tokens, k, 0.5))
    # capacity_for(4, 2, 0.5) == 1: one slot, three drops
    assert D.sum() == 1
